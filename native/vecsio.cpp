// Host data layer: reader for the TexMex *.fvecs / *.bvecs / *.ivecs vector
// formats — the on-disk format of the SIFT1M/GIST1M ANN benchmark corpora
// (BASELINE.md "SIFT1M (1M×128) multi-host" config). The reference project
// ships only MAT-file I/O (/root/reference/knn-serial.c:38-52); this native
// component extends the rebuild's data layer to the benchmark datasets the
// perf targets are defined on, in the same C++ style as matio.cpp.
//
// Format (little-endian, per vector): int32 dimension d, then d components —
// float32 (fvecs), uint8 (bvecs), or int32 (ivecs, used for ground-truth
// neighbor-id files). All rows must share d.
//
// C ABI for the ctypes binding in mpi_knn_tpu/data/vecs.py. Output is always
// float32 for f/b kinds (bvecs widened) and int32 for i. The INPUT is
// streamed row by row (no whole-file buffer) and reading stops at `limit`,
// so memory is bounded by the requested output (rows x dim x 4 bytes here,
// plus the caller's numpy copy) — pass a limit when sampling huge files.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct VecsResult {
  std::vector<uint8_t> data;  // packed rows, out dtype
  int64_t rows = 0;
  int64_t dim = 0;
  std::string error;
};

size_t comp_size(char kind) {
  switch (kind) {
    case 'f':
    case 'i':
      return 4;
    case 'b':
      return 1;
    default:
      return 0;
  }
}

VecsResult* read_vecs(const char* path, char kind, int64_t limit) {
  auto* r = new VecsResult();
  size_t csize = comp_size(kind);
  if (csize == 0) {
    r->error = std::string("unknown vecs kind '") + kind + "'";
    return r;
  }
  FILE* f = fopen(path, "rb");
  if (!f) {
    r->error = "cannot open file";
    return r;
  }

  std::vector<uint8_t> rowbuf;
  while (limit < 0 || r->rows < limit) {
    int32_t d;
    size_t got = fread(&d, 1, 4, f);
    if (got == 0) break;  // clean EOF at a row boundary
    if (got != 4) {
      r->error = "truncated dimension field at row " + std::to_string(r->rows);
      break;
    }
    if (d <= 0 || d > (1 << 24)) {
      r->error = "implausible dimension " + std::to_string(d) + " at row " +
                 std::to_string(r->rows);
      break;
    }
    if (r->rows == 0) {
      r->dim = d;
    } else if (d != r->dim) {
      r->error = "inconsistent dimension (" + std::to_string(d) + " vs " +
                 std::to_string(r->dim) + ") at row " + std::to_string(r->rows);
      break;
    }
    rowbuf.resize(csize * d);
    if (fread(rowbuf.data(), 1, rowbuf.size(), f) != rowbuf.size()) {
      r->error = "truncated row " + std::to_string(r->rows);
      break;
    }
    if (kind == 'b') {
      // widen uint8 -> float32
      size_t base = r->data.size();
      r->data.resize(base + 4 * d);
      float* out = reinterpret_cast<float*>(r->data.data() + base);
      for (int32_t j = 0; j < d; ++j) out[j] = rowbuf[j];
    } else {
      r->data.insert(r->data.end(), rowbuf.begin(), rowbuf.end());
    }
    r->rows += 1;
  }
  fclose(f);
  if (!r->error.empty()) {
    r->data.clear();
    r->rows = 0;
    r->dim = 0;
  }
  return r;
}

}  // namespace

extern "C" {

void* tknn_vecs_read(const char* path, char kind, int64_t limit) {
  return read_vecs(path, kind, limit);
}

const char* tknn_vecs_error(void* h) {
  auto* r = static_cast<VecsResult*>(h);
  return r->error.empty() ? nullptr : r->error.c_str();
}

int64_t tknn_vecs_rows(void* h) { return static_cast<VecsResult*>(h)->rows; }
int64_t tknn_vecs_dim(void* h) { return static_cast<VecsResult*>(h)->dim; }

// copies rows*dim components into `out` (float32 for f/b, int32 for i)
void tknn_vecs_copy(void* h, void* out) {
  auto* r = static_cast<VecsResult*>(h);
  memcpy(out, r->data.data(), r->data.size());
}

void tknn_vecs_close(void* h) { delete static_cast<VecsResult*>(h); }

}  // extern "C"
