/* Clean-room subset of the MPI C API ("mpi.h"), backed by named FIFOs
 * between single-host processes instead of a real MPI runtime.
 *
 * Purpose: compile and run the UNMODIFIED reference MPI programs
 * (/root/reference/mpi-knn-parallel_{blocking,non_blocking}.c) on this
 * host, so BASELINE.md can carry *measured* numbers for the reference's
 * two distributed headline benchmarks (it published none), and so the
 * SURVEY Q1/Q2 bug analysis can be confirmed empirically against the
 * reference's own compiled code (e.g. under AddressSanitizer).
 *
 * Only the surface those two programs use is provided: COMM_WORLD,
 * doubles, blocking Send/Recv, Isend/Irecv/Wait, Barrier. The process
 * model is one OS process per rank, launched by scripts/ref_mpi_baseline.py
 * with TKNN_MPI_RANK / TKNN_MPI_SIZE / TKNN_MPI_DIR in the environment.
 * This is measurement tooling, not part of the framework API (the
 * framework's distributed backend is XLA collectives — backends/ring.py).
 */
#ifndef TKNN_MPISHIM_H_
#define TKNN_MPISHIM_H_

#ifdef __cplusplus
extern "C" {
#endif

typedef struct {
  int MPI_SOURCE;
  int MPI_TAG;
  int MPI_ERROR;
  int TKNN_BYTES;  /* shim-only: bytes delivered by the matching receive
                      (debug channel; real MPI has opaque extra fields) */
} MPI_Status;

typedef struct TknnMpiReq *MPI_Request;  /* opaque; filled by Isend/Irecv */

typedef int MPI_Comm;
typedef int MPI_Datatype;

#define MPI_COMM_WORLD 0
#define MPI_DOUBLE 8 /* encodes the element size in bytes */
#define MPI_ANY_TAG (-1)
#define MPI_SUCCESS 0

int MPI_Init(int *argc, char ***argv);
int MPI_Finalize(void);
int MPI_Comm_size(MPI_Comm comm, int *size);
int MPI_Comm_rank(MPI_Comm comm, int *rank);
int MPI_Barrier(MPI_Comm comm);
int MPI_Send(const void *buf, int count, MPI_Datatype dt, int dest, int tag,
             MPI_Comm comm);
int MPI_Recv(void *buf, int count, MPI_Datatype dt, int source, int tag,
             MPI_Comm comm, MPI_Status *status);
int MPI_Isend(const void *buf, int count, MPI_Datatype dt, int dest, int tag,
              MPI_Comm comm, MPI_Request *request);
int MPI_Irecv(void *buf, int count, MPI_Datatype dt, int source, int tag,
              MPI_Comm comm, MPI_Request *request);
int MPI_Wait(MPI_Request *request, MPI_Status *status);

#ifdef __cplusplus
}
#endif

#endif /* TKNN_MPISHIM_H_ */
