// Implementation of the mpi.h shim (see mpishim.h): single-host message
// passing over named FIFOs, one OS process per rank.
//
// Transport layout (created by the launcher under $TKNN_MPI_DIR):
//   ch_<src>_<dst>   data channel, framed [uint64 nbytes][payload]
//   bar_up_<i>       rank i -> rank 0 barrier token (1 byte)
//   bar_dn_<i>       rank 0 -> rank i barrier release (1 byte)
//
// Semantics notes, matched to what the reference programs rely on:
// - Recv's count is a MAXIMUM: the actual delivered size is the sender's
//   message size (the blocking reference's first exchange receives an
//   (n+2)-count into a buffer fed by an n-count send — real MPI permits
//   that, so the shim must too).
// - FIFO opens block until the peer opens the other end; the reference's
//   role-ordered ladder (rank 0 Recv->Send, last rank Send->Recv) forms a
//   sequential chain, so lazy opens cannot deadlock. Writes of messages
//   larger than the pipe buffer block until the receiver drains —
//   rendezvous-like, which that ladder also tolerates.
// - Isend/Irecv run the blocking op on a detached pthread; Wait joins it.
//   The reference keeps at most one outstanding request of each kind.

#include "mpishim.h"

#include <cerrno>
#include <fcntl.h>
#include <pthread.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

#include <string>
#include <vector>

namespace {

int g_rank = -1;
int g_size = 0;
std::string g_dir;
// fd caches, indexed by peer rank
std::vector<int> g_send_fds, g_recv_fds;

[[noreturn]] void die(const char *what) {
  fprintf(stderr, "[mpishim rank %d] fatal: %s\n", g_rank, what);
  exit(70);
}

int open_checked(const std::string &path, int flags) {
  int fd;
  do {
    fd = open(path.c_str(), flags);
  } while (fd < 0 && errno == EINTR);
  if (fd < 0) die(path.c_str());
  return fd;
}

void write_all(int fd, const void *buf, size_t n) {
  const char *p = static_cast<const char *>(buf);
  while (n) {
    ssize_t w = write(fd, p, n);
    if (w < 0) {
      if (errno == EINTR) continue;
      die("write");
    }
    p += w;
    n -= static_cast<size_t>(w);
  }
}

void read_all(int fd, void *buf, size_t n) {
  char *p = static_cast<char *>(buf);
  while (n) {
    ssize_t r = read(fd, p, n);
    if (r < 0) {
      if (errno == EINTR) continue;
      die("read");
    }
    if (r == 0) die("peer closed channel mid-message");
    p += r;
    n -= static_cast<size_t>(r);
  }
}

int send_fd(int dest) {
  if (dest < 0 || dest >= g_size) die("send: bad dest rank");
  if (g_send_fds[dest] < 0)
    g_send_fds[dest] = open_checked(
        g_dir + "/ch_" + std::to_string(g_rank) + "_" + std::to_string(dest),
        O_WRONLY);
  return g_send_fds[dest];
}

int recv_fd(int source) {
  if (source < 0 || source >= g_size) die("recv: bad source rank");
  if (g_recv_fds[source] < 0)
    g_recv_fds[source] = open_checked(
        g_dir + "/ch_" + std::to_string(source) + "_" + std::to_string(g_rank),
        O_RDONLY);
  return g_recv_fds[source];
}

void do_send(const void *buf, size_t nbytes, int dest) {
  int fd = send_fd(dest);
  uint64_t hdr = nbytes;
  write_all(fd, &hdr, sizeof(hdr));
  write_all(fd, buf, nbytes);
}

// Returns the delivered byte count (<= cap).
size_t do_recv(void *buf, size_t cap, int source) {
  int fd = recv_fd(source);
  uint64_t hdr = 0;
  read_all(fd, &hdr, sizeof(hdr));
  if (hdr > cap) die("recv: message larger than receive buffer");
  read_all(fd, buf, hdr);
  return hdr;
}

struct ReqArgs {
  const void *sbuf;
  void *rbuf;
  size_t nbytes;
  int peer;
  bool is_send;
};

void *req_main(void *arg) {
  ReqArgs *a = static_cast<ReqArgs *>(arg);
  if (a->is_send)
    do_send(a->sbuf, a->nbytes, a->peer);
  else
    do_recv(a->rbuf, a->nbytes, a->peer);
  return nullptr;
}

}  // namespace

struct TknnMpiReq {
  pthread_t thread;
  ReqArgs args;
  int peer;
};

extern "C" {

int MPI_Init(int *, char ***) {
  const char *r = getenv("TKNN_MPI_RANK");
  const char *s = getenv("TKNN_MPI_SIZE");
  const char *d = getenv("TKNN_MPI_DIR");
  if (!r || !s || !d)
    die("TKNN_MPI_RANK/SIZE/DIR unset (launch via ref_mpi_baseline.py)");
  g_rank = atoi(r);
  g_size = atoi(s);
  g_dir = d;
  if (g_size < 1 || g_rank < 0 || g_rank >= g_size) die("bad rank/size");
  g_send_fds.assign(g_size, -1);
  g_recv_fds.assign(g_size, -1);
  return MPI_SUCCESS;
}

int MPI_Finalize(void) {
  for (int fd : g_send_fds)
    if (fd >= 0) close(fd);
  for (int fd : g_recv_fds)
    if (fd >= 0) close(fd);
  return MPI_SUCCESS;
}

int MPI_Comm_size(MPI_Comm, int *size) {
  *size = g_size;
  return MPI_SUCCESS;
}

int MPI_Comm_rank(MPI_Comm, int *rank) {
  *rank = g_rank;
  return MPI_SUCCESS;
}

int MPI_Barrier(MPI_Comm) {
  unsigned char tok = 1;
  if (g_size == 1) return MPI_SUCCESS;
  if (g_rank == 0) {
    for (int i = 1; i < g_size; i++) {
      int fd = open_checked(g_dir + "/bar_up_" + std::to_string(i), O_RDONLY);
      read_all(fd, &tok, 1);
      close(fd);
    }
    for (int i = 1; i < g_size; i++) {
      int fd = open_checked(g_dir + "/bar_dn_" + std::to_string(i), O_WRONLY);
      write_all(fd, &tok, 1);
      close(fd);
    }
  } else {
    int fd = open_checked(g_dir + "/bar_up_" + std::to_string(g_rank),
                          O_WRONLY);
    write_all(fd, &tok, 1);
    close(fd);
    fd = open_checked(g_dir + "/bar_dn_" + std::to_string(g_rank), O_RDONLY);
    read_all(fd, &tok, 1);
    close(fd);
  }
  return MPI_SUCCESS;
}

int MPI_Send(const void *buf, int count, MPI_Datatype dt, int dest, int,
             MPI_Comm) {
  do_send(buf, static_cast<size_t>(count) * dt, dest);
  return MPI_SUCCESS;
}

int MPI_Recv(void *buf, int count, MPI_Datatype dt, int source, int,
             MPI_Comm, MPI_Status *status) {
  size_t got = do_recv(buf, static_cast<size_t>(count) * dt, source);
  if (status) {
    status->MPI_SOURCE = source;
    // conforming values: the matched send's tag (every reference send uses
    // tag 0) and MPI_SUCCESS — a caller following MPI semantics must not
    // see the old byte-count-in-MPI_ERROR debug hack (ADVICE r3). The byte
    // count survives in the shim-only TKNN_BYTES field instead.
    status->MPI_TAG = 0;
    status->MPI_ERROR = 0;
    status->TKNN_BYTES = static_cast<int>(got);
  }
  return MPI_SUCCESS;
}

int MPI_Isend(const void *buf, int count, MPI_Datatype dt, int dest, int,
              MPI_Comm, MPI_Request *request) {
  TknnMpiReq *req = new TknnMpiReq();
  req->args = {buf, nullptr, static_cast<size_t>(count) * dt, dest, true};
  req->peer = dest;
  if (pthread_create(&req->thread, nullptr, req_main, &req->args))
    die("pthread_create");
  *request = req;
  return MPI_SUCCESS;
}

int MPI_Irecv(void *buf, int count, MPI_Datatype dt, int source, int,
              MPI_Comm, MPI_Request *request) {
  TknnMpiReq *req = new TknnMpiReq();
  req->args = {nullptr, buf, static_cast<size_t>(count) * dt, source, false};
  req->peer = source;
  if (pthread_create(&req->thread, nullptr, req_main, &req->args))
    die("pthread_create");
  *request = req;
  return MPI_SUCCESS;
}

int MPI_Wait(MPI_Request *request, MPI_Status *status) {
  if (!request || !*request) return MPI_SUCCESS;
  TknnMpiReq *req = *request;
  if (pthread_join(req->thread, nullptr)) die("pthread_join");
  if (status) {
    status->MPI_SOURCE = req->peer;
    status->MPI_TAG = 0;
    status->MPI_ERROR = 0;
  }
  delete req;
  *request = nullptr;
  return MPI_SUCCESS;
}

}  // extern "C"
