/* Clean-room subset of MATLAB's MAT-file C API ("mat.h"), backed by the
 * framework's own MAT v5 reader (native/matio.cpp) instead of libmat.
 *
 * Purpose: compile and run the UNMODIFIED reference program
 * (/root/reference/knn-serial.c includes "mat.h" and calls matOpen /
 * matGetVariable / mxGetM / mxGetN / mxGetPr / mxDestroyArray / matClose)
 * on this host, so BASELINE.md can carry a *measured* number for the
 * reference's own headline benchmark rather than "not published".
 *
 * Only the surface the reference uses is provided; everything returns
 * double-precision column-major data, which is what mxGetPr yields for
 * MATLAB double arrays and what the reference's `p[k + j*m]` indexing
 * assumes. This is measurement tooling, not part of the framework API.
 */
#ifndef TKNN_MATSHIM_H_
#define TKNN_MATSHIM_H_

#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct MATFile MATFile;
typedef struct mxArray_tag mxArray;

MATFile *matOpen(const char *filename, const char *mode);
int matClose(MATFile *pmat);
mxArray *matGetVariable(MATFile *pmat, const char *name);

size_t mxGetM(const mxArray *pa);
size_t mxGetN(const mxArray *pa);
double *mxGetPr(const mxArray *pa);
void mxDestroyArray(mxArray *pa);

#ifdef __cplusplus
}
#endif

#endif /* TKNN_MATSHIM_H_ */
