// Implementation of the mat.h shim (see matshim.h) on top of the
// framework's MAT v5 reader C API (native/matio.cpp: tknn_mat_*).
//
// The reference program opens the file read-only, fetches whole variables,
// and reads them through mxGetPr as column-major doubles — exactly the
// contract tknn_mat_read_f64 provides, so the shim is a thin ownership
// adapter: MATFile wraps the reader handle, mxArray owns a materialized
// f64 buffer plus its (rows, cols) shape.

#include "matshim.h"

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <new>

extern "C" {
// native/matio.cpp public API
void *tknn_mat_open(const char *path);
const char *tknn_mat_error(void *h);
int tknn_mat_var_shape(void *h, const char *name, int64_t *dims, int max_dims);
int64_t tknn_mat_read_f64(void *h, const char *name, double *out);
void tknn_mat_close(void *h);
}

struct MATFile {
  void *handle;
};

struct mxArray_tag {
  size_t m;  // rows
  size_t n;  // cols
  double *data;  // column-major, owned
};

extern "C" {

MATFile *matOpen(const char *filename, const char *mode) {
  (void)mode;  // the shim is read-only; the reference only opens "r"
  void *h = tknn_mat_open(filename);
  if (!h) return nullptr;  // defensive: the current reader never returns
  // null — it signals missing/corrupt files via its error channel, which
  // must be consulted here or the reference would happily run over zero
  // variables and record "Clock time = 0" as a real measurement
  const char *err = tknn_mat_error(h);
  if (err && err[0]) {
    tknn_mat_close(h);
    return nullptr;
  }
  MATFile *f = new (std::nothrow) MATFile{h};
  if (!f) tknn_mat_close(h);
  return f;
}

int matClose(MATFile *pmat) {
  if (!pmat) return -1;
  tknn_mat_close(pmat->handle);
  delete pmat;
  return 0;
}

mxArray *matGetVariable(MATFile *pmat, const char *name) {
  if (!pmat) return nullptr;
  int64_t dims[8] = {0};
  int nd = tknn_mat_var_shape(pmat->handle, name, dims, 8);
  if (nd < 1) return nullptr;
  size_t rows = static_cast<size_t>(dims[0]);
  size_t cols = nd >= 2 ? static_cast<size_t>(dims[1]) : 1;
  for (int i = 2; i < nd; i++) cols *= static_cast<size_t>(dims[i]);
  size_t count = rows * cols;
  double *buf = static_cast<double *>(std::malloc(count * sizeof(double)));
  if (!buf) return nullptr;
  if (tknn_mat_read_f64(pmat->handle, name, buf) !=
      static_cast<int64_t>(count)) {
    std::free(buf);
    return nullptr;
  }
  mxArray *a = new (std::nothrow) mxArray_tag{rows, cols, buf};
  if (!a) std::free(buf);
  return a;
}

size_t mxGetM(const mxArray *pa) { return pa ? pa->m : 0; }
size_t mxGetN(const mxArray *pa) { return pa ? pa->n : 0; }
double *mxGetPr(const mxArray *pa) { return pa ? pa->data : nullptr; }

void mxDestroyArray(mxArray *pa) {
  if (!pa) return;
  std::free(pa->data);
  delete pa;
}

}  // extern "C"
