// TPU-native framework's host data layer: a clean-room MAT v5 reader.
//
// Replaces the reference's dependency on MATLAB's proprietary libmat/libmx
// (matOpen/matGetVariable/mxGetPr, /root/reference/knn-serial.c:38-52) with a
// small self-contained C++ library reading the public MAT-File Level 5 format:
// 128-byte header, then a sequence of tagged data elements; variables are
// miMATRIX elements (optionally zlib-wrapped in miCOMPRESSED) holding
// [array-flags, dimensions, name, real data] sub-elements, column-major.
//
// Exposed as a C ABI for the ctypes binding in mpi_knn_tpu/data/matfile.py.
// All numeric classes are converted to float64 on read (the reference's
// convention: mxGetPr always yields double).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>
#include <zlib.h>

namespace {

// MAT v5 data type tags
enum MiType : uint32_t {
  miINT8 = 1,
  miUINT8 = 2,
  miINT16 = 3,
  miUINT16 = 4,
  miINT32 = 5,
  miUINT32 = 6,
  miSINGLE = 7,
  miDOUBLE = 9,
  miINT64 = 12,
  miUINT64 = 13,
  miMATRIX = 14,
  miCOMPRESSED = 15,
  miUTF8 = 16,
};

size_t mi_type_size(uint32_t t) {
  switch (t) {
    case miINT8:
    case miUINT8:
    case miUTF8:
      return 1;
    case miINT16:
    case miUINT16:
      return 2;
    case miINT32:
    case miUINT32:
    case miSINGLE:
      return 4;
    case miDOUBLE:
    case miINT64:
    case miUINT64:
      return 8;
    default:
      return 0;
  }
}

struct Variable {
  std::string name;
  std::vector<int64_t> dims;  // column-major
  std::vector<double> data;   // converted to f64, column-major order
};

struct MatFile {
  std::vector<Variable> vars;
  std::string error;
};

struct Cursor {
  const uint8_t* p;
  size_t n;
  size_t off = 0;

  bool need(size_t k) const { return off + k <= n; }
  const uint8_t* at() const { return p + off; }
};

bool convert_numeric(uint32_t type, const uint8_t* src, size_t nbytes,
                     std::vector<double>* out) {
  size_t esz = mi_type_size(type);
  if (esz == 0) return false;
  size_t count = nbytes / esz;
  out->resize(count);
  switch (type) {
    case miINT8: {
      auto* s = reinterpret_cast<const int8_t*>(src);
      for (size_t i = 0; i < count; i++) (*out)[i] = s[i];
      break;
    }
    case miUINT8:
    case miUTF8: {
      for (size_t i = 0; i < count; i++) (*out)[i] = src[i];
      break;
    }
    case miINT16: {
      auto* s = reinterpret_cast<const int16_t*>(src);
      for (size_t i = 0; i < count; i++) (*out)[i] = s[i];
      break;
    }
    case miUINT16: {
      auto* s = reinterpret_cast<const uint16_t*>(src);
      for (size_t i = 0; i < count; i++) (*out)[i] = s[i];
      break;
    }
    case miINT32: {
      auto* s = reinterpret_cast<const int32_t*>(src);
      for (size_t i = 0; i < count; i++) (*out)[i] = s[i];
      break;
    }
    case miUINT32: {
      auto* s = reinterpret_cast<const uint32_t*>(src);
      for (size_t i = 0; i < count; i++) (*out)[i] = s[i];
      break;
    }
    case miSINGLE: {
      auto* s = reinterpret_cast<const float*>(src);
      for (size_t i = 0; i < count; i++) (*out)[i] = s[i];
      break;
    }
    case miDOUBLE: {
      auto* s = reinterpret_cast<const double*>(src);
      for (size_t i = 0; i < count; i++) (*out)[i] = s[i];
      break;
    }
    case miINT64: {
      auto* s = reinterpret_cast<const int64_t*>(src);
      for (size_t i = 0; i < count; i++) (*out)[i] = static_cast<double>(s[i]);
      break;
    }
    case miUINT64: {
      auto* s = reinterpret_cast<const uint64_t*>(src);
      for (size_t i = 0; i < count; i++) (*out)[i] = static_cast<double>(s[i]);
      break;
    }
    default:
      return false;
  }
  return true;
}

// Reads one sub-element tag (handling the packed "small data element" form
// where payloads <= 4 bytes live inside the 8-byte tag itself). Returns false
// on truncation. After return: *type/*nbytes describe the payload at *data;
// cursor advanced past the element incl. 8-byte padding.
bool read_element(Cursor* c, uint32_t* type, uint32_t* nbytes,
                  const uint8_t** data) {
  if (!c->need(8)) return false;
  uint32_t w0, w1;
  memcpy(&w0, c->at(), 4);
  memcpy(&w1, c->at() + 4, 4);
  if (w0 >> 16) {
    // small element: high 16 bits = byte count, low 16 = type, data in w1
    *type = w0 & 0xFFFF;
    *nbytes = w0 >> 16;
    if (*nbytes > 4) return false;
    *data = c->at() + 4;
    c->off += 8;
    return true;
  }
  *type = w0;
  *nbytes = w1;
  c->off += 8;
  if (!c->need(*nbytes)) return false;
  *data = c->at();
  size_t adv;
  if (*type == miCOMPRESSED) {
    adv = *nbytes;  // compressed elements are never padded
  } else {
    adv = (*nbytes + 7) & ~size_t(7);  // others pad to 8-byte boundary
    size_t remaining = c->n - c->off;
    if (adv > remaining) adv = *nbytes;  // last element may omit pad
  }
  c->off += adv;
  return true;
}

bool parse_matrix(const uint8_t* p, size_t n, Variable* var,
                  std::string* error) {
  Cursor c{p, n};
  uint32_t type, nbytes;
  const uint8_t* data;

  // 1. array flags (miUINT32 x2): class in the low byte of the first word
  if (!read_element(&c, &type, &nbytes, &data) || type != miUINT32 ||
      nbytes < 8) {
    *error = "bad array flags";
    return false;
  }
  uint32_t flags;
  memcpy(&flags, data, 4);
  uint32_t cls = flags & 0xFF;
  bool is_complex = (flags >> 11) & 1;
  // numeric classes mxDOUBLE(6) mxSINGLE(7) mxINT8(8)..mxUINT64(15); skip
  // cell/struct/object/char/sparse (1..5) — not needed for point matrices
  if (cls < 6 || cls > 15) {
    *error = "unsupported array class " + std::to_string(cls);
    return false;
  }

  // 2. dimensions (miINT32)
  if (!read_element(&c, &type, &nbytes, &data) || type != miINT32) {
    *error = "bad dimensions";
    return false;
  }
  size_t ndim = nbytes / 4;
  var->dims.resize(ndim);
  int64_t total = ndim ? 1 : 0;
  for (size_t i = 0; i < ndim; i++) {
    int32_t d;
    memcpy(&d, data + 4 * i, 4);
    var->dims[i] = d;
    total *= d;
  }

  // 3. name (miINT8)
  if (!read_element(&c, &type, &nbytes, &data) || type != miINT8) {
    *error = "bad name";
    return false;
  }
  var->name.assign(reinterpret_cast<const char*>(data), nbytes);

  // 4. real part
  if (!read_element(&c, &type, &nbytes, &data)) {
    *error = "bad data element";
    return false;
  }
  if (!convert_numeric(type, data, nbytes, &var->data)) {
    *error = "unsupported data type " + std::to_string(type);
    return false;
  }
  if (static_cast<int64_t>(var->data.size()) != total) {
    *error = "element count mismatch";
    return false;
  }
  if (is_complex) {
    // imaginary part ignored (real point matrices only), but not an error
  }
  return true;
}

bool inflate_buf(const uint8_t* src, size_t n, std::vector<uint8_t>* out) {
  z_stream zs;
  memset(&zs, 0, sizeof(zs));
  if (inflateInit(&zs) != Z_OK) return false;
  out->clear();
  out->resize(n * 4 + 1024);
  zs.next_in = const_cast<Bytef*>(src);
  zs.avail_in = static_cast<uInt>(n);
  int ret = Z_OK;
  size_t written = 0;
  while (ret != Z_STREAM_END) {
    if (written == out->size()) out->resize(out->size() * 2);
    zs.next_out = out->data() + written;
    zs.avail_out = static_cast<uInt>(out->size() - written);
    ret = inflate(&zs, Z_NO_FLUSH);
    if (ret != Z_OK && ret != Z_STREAM_END) {
      inflateEnd(&zs);
      return false;
    }
    written = out->size() - zs.avail_out;
    if (ret == Z_OK && zs.avail_in == 0 && zs.avail_out > 0) break;  // truncated
  }
  out->resize(written);
  inflateEnd(&zs);
  return ret == Z_STREAM_END;
}

}  // namespace

extern "C" {

void* tknn_mat_open(const char* path) {
  auto* mf = new MatFile();
  FILE* f = fopen(path, "rb");
  if (!f) {
    mf->error = "cannot open file";
    return mf;
  }
  fseek(f, 0, SEEK_END);
  long sz = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::vector<uint8_t> buf(sz);
  if (fread(buf.data(), 1, sz, f) != static_cast<size_t>(sz)) {
    fclose(f);
    mf->error = "short read";
    return mf;
  }
  fclose(f);

  if (sz < 128) {
    mf->error = "not a MAT v5 file (too short)";
    return mf;
  }
  uint16_t version, endian;
  memcpy(&version, buf.data() + 124, 2);
  memcpy(&endian, buf.data() + 126, 2);
  if (endian != 0x4D49) {  // 'IM' read little-endian
    mf->error = "big-endian MAT files unsupported";
    return mf;
  }

  Cursor c{buf.data(), static_cast<size_t>(sz)};
  c.off = 128;
  while (c.off + 8 <= c.n) {
    uint32_t type, nbytes;
    const uint8_t* data;
    size_t elem_start = c.off;
    if (!read_element(&c, &type, &nbytes, &data)) break;

    Variable var;
    std::string err;
    if (type == miCOMPRESSED) {
      std::vector<uint8_t> raw;
      if (!inflate_buf(data, nbytes, &raw)) {
        mf->error = "zlib inflate failed at offset " +
                    std::to_string(elem_start);
        break;
      }
      // the decompressed payload is one full tagged element (miMATRIX)
      Cursor ic{raw.data(), raw.size()};
      uint32_t itype, inb;
      const uint8_t* idata;
      if (!read_element(&ic, &itype, &inb, &idata) || itype != miMATRIX) {
        continue;  // skip non-matrix elements
      }
      if (parse_matrix(idata, inb, &var, &err)) {
        mf->vars.push_back(std::move(var));
      }
    } else if (type == miMATRIX) {
      if (parse_matrix(data, nbytes, &var, &err)) {
        mf->vars.push_back(std::move(var));
      }
    }
    // other top-level element types (e.g. subsystem data) are skipped
  }
  return mf;
}

const char* tknn_mat_error(void* h) {
  auto* mf = static_cast<MatFile*>(h);
  return mf->error.c_str();
}

int tknn_mat_num_vars(void* h) {
  return static_cast<int>(static_cast<MatFile*>(h)->vars.size());
}

const char* tknn_mat_var_name(void* h, int i) {
  auto* mf = static_cast<MatFile*>(h);
  if (i < 0 || i >= static_cast<int>(mf->vars.size())) return "";
  return mf->vars[i].name.c_str();
}

// Writes up to max_dims dimension sizes; returns ndim, or -1 if not found.
int tknn_mat_var_shape(void* h, const char* name, int64_t* dims,
                       int max_dims) {
  auto* mf = static_cast<MatFile*>(h);
  for (auto& v : mf->vars) {
    if (v.name == name) {
      int nd = static_cast<int>(v.dims.size());
      for (int i = 0; i < nd && i < max_dims; i++) dims[i] = v.dims[i];
      return nd;
    }
  }
  return -1;
}

// Copies the variable's data (f64, column-major) into out; returns element
// count, or -1 if not found.
int64_t tknn_mat_read_f64(void* h, const char* name, double* out) {
  auto* mf = static_cast<MatFile*>(h);
  for (auto& v : mf->vars) {
    if (v.name == name) {
      memcpy(out, v.data.data(), v.data.size() * sizeof(double));
      return static_cast<int64_t>(v.data.size());
    }
  }
  return -1;
}

void tknn_mat_close(void* h) { delete static_cast<MatFile*>(h); }

}  // extern "C"
