"""Headline benchmark: MNIST-60k×784 all-kNN, k=10 (BASELINE.md north star:
< 1 s on a v5e-8 at recall@10 parity with the serial reference semantics).

Prints ONE JSON line per series:
    {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}

Architecture (ISSUE 6): ``python bench.py`` is a SUPERVISOR. Every series
of a round runs in its own child subprocess under the resilience worker
runner (``mpi_knn_tpu.resilience``): the child writes monotonic heartbeat
progress, and the supervisor kills on *beat starvation* (a wedged
transport stops beating immediately) with wall-clock as the outer bound
only. One wedged series can therefore never take down its siblings —
the failure modes that erased 4 of 5 r5 rounds (whole-process watchdog,
``rc: 2``, zero banked signal) are structurally gone:

- a completed series banks its real measurement line, always;
- a wedged/crashed series banks a structured ``"failed": true`` line
  under its own series name — with ``"value": null`` and the kill time
  in an explicit ``time_until_kill_s`` field, never a ``vs_baseline``
  number (BENCH_r05 banked a watchdog timeout as ``value: 480.0,
  vs_baseline: 0.0`` — a timeout stamped as a zero-regression
  measurement; ISSUE 7), plus the child's banked span flight record
  (``mpi_knn_tpu.obs.spans``) so the round keeps the story of where the
  time went;
- the process exits 0 whenever at least one series banked;
- only when NO series banked anything does the round fall to the last
  rung of the ladder: a serial/CPU re-run in a fresh subprocess at
  ``BENCH_FALLBACK_M``, banked with the ``"degraded": "cpu-fallback"``
  marker (PR 4's convention) — a degraded number beats an empty round.

Series come from ``BENCH_SERIES``: a JSON list of env-overlay objects,
each overlaid on this process's environment for one child (optional
``"name"`` key labels supervisor notes). Unset = one series from the
ambient knobs, which is the PR-driver contract (exactly one stdout line).
``BENCH_DOCTOR=1`` runs the ``mpi-knn doctor`` preflight probe first and
skips straight to the failure ladder if the device is already wedged.

Methodology (mirrors the reference, which times ONLY the distance/top-k
phase — ``/root/reference/knn-serial.c:70,94-98`` — not I/O or voting):

- the corpus is placed on device once, outside the timed region;
- each timed rep runs the full ``all_knn`` API path on the device-resident
  corpus and synchronizes with ``device_sync`` (a 1-element fetch —
  ``block_until_ready`` alone can return at dispatch time on tunneled
  device transports and would under-report);
- value = MEDIAN rep wall-clock of the all-kNN phase (all reps plus the
  min are reported on stderr — min alone flatters a noisy transport);
- recall@10 is checked against a float64 host oracle on a 256-query sample
  (computed in matmul form, chunk-free at this sample size); a recall miss
  (<0.999) zeroes vs_baseline rather than reporting a fast-but-wrong number.

vs_baseline: north_star_seconds / value, scaled by the fraction of the
8-chip target this host provides (1 chip => target is 8 s), so >1.0 beats
the north star at equal silicon.

Environment knobs: BENCH_M (default 60000), BENCH_BACKEND (serial|pallas),
BENCH_REPS, BENCH_QT/BENCH_CT (tiles), BENCH_TOPK (exact|approx),
BENCH_PRECISION (default|high|highest), BENCH_PRECISION_POLICY
(exact|mixed — mixed is the compress-and-rerank pipeline and owns both dot
precisions, so it overrides BENCH_PRECISION),
BENCH_PALLAS_VARIANT (tiles|sweep), BENCH_IVF_PARTITIONS /
BENCH_IVF_NPROBE (clustered-index path: k-means partitions trained
outside the timed region, per-query probed scan timed; the series name
carries the knobs and the gate is the configured recall_target — the
clustered rung's own acceptance bar), BENCH_IVF_SHARDS (the SHARDED
clustered path, mpi_knn_tpu.ivf.sharded: the bucket store distributed
over that many ring-mesh devices with the routed all-to-all candidate
exchange; requires BENCH_IVF_PARTITIONS, series name carries the shard
count), BENCH_RING_FUSION (xla|fused — 'fused' runs the ring round as
the fused collective-matmul Pallas kernel, ops/pallas_ring.py: distance
sweep + carry merge in one kernel with the next corpus block streamed
over ICI during compute; ring-overlap backend only, and only on a
platform whose Pallas path exists — TPU hardware or CPU interpret mode —
any other combination is a loud exit-2 refusal because the knob would be
silently ignored or the kernel cannot lower; the series name carries the
knob), BENCH_WATCHDOG_S (per-series wall
bound, 0 disables), BENCH_BEAT_TIMEOUT_S (per-series beat-starvation
bound, 0 disables), BENCH_SERIES / BENCH_DOCTOR (supervisor, above),
BENCH_PLATFORM (forces jax_platforms via the config API — JAX_PLATFORMS
alone is ignored by the axon TPU plugin), TKNN_MNIST (real data path;
synthetic surrogate otherwise), TKNN_FAULTS (fault injection — see
mpi_knn_tpu/resilience/faults.py; the bench series fault site is
``bench-series``).

The recall gate is FIXED at 0.999 regardless of knobs — it is the north
star's acceptance bar, not a tunable. Setting BENCH_RT below it tunes
approx_min_k to a recall the gate will reject, zeroing vs_baseline by
design (speed bought with recall does not count).
"""

import json
import os
import sys
import time

import numpy as np


NORTH_STAR_SECONDS = 1.0  # on 8 chips (v5e-8)
NORTH_STAR_CHIPS = 8
RECALL_GATE = 0.999


def metric_name(env=None) -> str:
    """One construction of the series name, shared by the success and
    failure paths so a failure always lands in the real series — and
    computable by the supervisor from a child's env when the child died
    before printing anything. The IVF knobs are part of the name: a
    clustered run measures a different computation (sublinear probed scan
    at a measured recall target) and must never masquerade as the exact
    full-scan series."""
    env = os.environ if env is None else env
    m = int(env.get("BENCH_M", "60000"))
    k = int(env.get("BENCH_K", "10"))
    ivf = ""
    if env.get("BENCH_IVF_PARTITIONS"):
        p = env["BENCH_IVF_PARTITIONS"]
        n = env.get("BENCH_IVF_NPROBE", "auto")
        ivf = f"_ivf{p}p{n}"
        if env.get("BENCH_IVF_SHARDS"):
            # a sharded run measures a different program (routed exchange
            # over the mesh) and must never masquerade as the
            # single-device clustered series
            ivf += f"s{env['BENCH_IVF_SHARDS']}"
    fusion = ""
    if env.get("BENCH_RING_FUSION", "xla") != "xla":
        # the fused rotation is a different PROGRAM (in-kernel streaming
        # collective-matmul) proven bit-identical to the xla form — the
        # whole point of the series is the A/B, so the name must carry
        # the axis or the two would bank under one metric
        fusion = f"_{env['BENCH_RING_FUSION']}"
    return f"mnist{m // 1000}k_allknn_k{k}{ivf}{fusion}_seconds"


def oracle_topk(X: np.ndarray, sample: np.ndarray, k: int) -> np.ndarray:
    """f64 ground-truth neighbor ids for the sampled queries, matmul form
    (no (q, m, d) broadcast — that would be ~100 GB at MNIST scale)."""
    Xs = X.astype(np.float64)
    Q = Xs[sample]
    d = (
        (Q**2).sum(1)[:, None]
        + (Xs**2).sum(1)[None, :]
        - 2.0 * (Q @ Xs.T)
    )
    # reference zero-exclusion (SURVEY.md Q3) + exact self-exclusion
    d[d <= 1e-9] = np.inf
    d[np.arange(len(sample)), sample] = np.inf
    return np.argsort(d, axis=1, kind="stable")[:, :k]


def main() -> int:
    """ONE series measurement — always a supervised child process
    (``TKNN_BENCH_CHILD=1``). Heartbeats bracket every step that can
    hang, so the supervisor's beat-starvation kill names the wedged
    step; the injectable ``bench-series`` fault site stands in for a
    wedged transport in tier-1."""
    from mpi_knn_tpu.obs.spans import span as flight_span
    from mpi_knn_tpu.resilience.faults import fault_point
    from mpi_knn_tpu.resilience.heartbeat import maybe_beat

    maybe_beat("start")
    fault_point("bench-series")
    if os.environ.get("BENCH_PLATFORM"):
        # the axon TPU plugin ignores JAX_PLATFORMS; the shared helper is
        # the only reliable way to keep a CPU smoke run off the tunnel
        from mpi_knn_tpu.utils.platform import force_platform

        # a sharded clustered series needs a real multi-device mesh: on
        # the forced-CPU platform that means virtual host devices, sized
        # to the shard count BEFORE the backend comes up
        _shards = os.environ.get("BENCH_IVF_SHARDS")
        force_platform(
            os.environ["BENCH_PLATFORM"],
            n_devices=(int(_shards)
                       if _shards and _shards.isdigit()
                       and os.environ["BENCH_PLATFORM"] == "cpu"
                       else None),
        )
    maybe_beat("platform")

    import jax
    import jax.numpy as jnp

    maybe_beat("jax-import")

    m = int(os.environ.get("BENCH_M", "60000"))
    k = int(os.environ.get("BENCH_K", "10"))
    reps = int(os.environ.get("BENCH_REPS", "3"))
    if reps < 1:
        # median([]) would silently emit NaN as the headline value
        print(json.dumps({"error": "BENCH_REPS must be >= 1"}),
              file=sys.stderr)
        return 2
    backend = os.environ.get("BENCH_BACKEND", "serial")
    # BENCH_PRECISION_POLICY=mixed: the compress-and-rerank pipeline — the
    # O(q·c·d) dot runs single-pass bf16 MXU, only the 4k-overfetched
    # survivors are reranked at HIGHEST. The policy owns both dot
    # precisions, so combining it with an explicit BENCH_PRECISION is a
    # usage error — refuse loudly rather than silently ignore one knob
    # (an A/B sweep over BENCH_PRECISION would otherwise record identical
    # mixed runs mislabeled as precision variants).
    precision_policy = os.environ.get("BENCH_PRECISION_POLICY", "exact")
    if precision_policy == "mixed" and os.environ.get("BENCH_PRECISION"):
        print(
            json.dumps({
                "error": "BENCH_PRECISION conflicts with "
                "BENCH_PRECISION_POLICY=mixed (the policy owns both dot "
                "precisions: DEFAULT compress, HIGHEST rerank)"
            }),
            file=sys.stderr,
        )
        return 2
    # BENCH_RING_SCHEDULE=bidir: full-duplex ring rotation (both torus
    # directions at once, floor(P/2)+1 rounds). The knob only means anything
    # on a ring backend — setting it with a single-device backend would
    # silently measure an identical program under a different label, so the
    # conflicting combination is refused loudly (same treatment as
    # BENCH_PRECISION × BENCH_PRECISION_POLICY above).
    ring_schedule = os.environ.get("BENCH_RING_SCHEDULE", "uni")
    if ring_schedule != "uni" and backend not in ("ring", "ring-overlap"):
        print(
            json.dumps({
                "error": f"BENCH_RING_SCHEDULE={ring_schedule} conflicts "
                f"with BENCH_BACKEND={backend}: the ring schedule only "
                "exists on ring/ring-overlap backends — an A/B sweep here "
                "would record identical single-device runs mislabeled as "
                "schedule variants"
            }),
            file=sys.stderr,
        )
        return 2
    # BENCH_RING_FUSION=fused: the ring round runs as the fused
    # collective-matmul Pallas kernel (distance sweep + carry merge in one
    # kernel, next block streamed over ICI during compute). Two loud
    # refusals, same doctrine as the schedule knob above: on a non-ring
    # backend the knob names a rotation that never runs (a fused-labeled
    # serial run would poison the A/B), and on a platform with no Pallas
    # path (neither TPU hardware nor CPU interpret mode) the kernel cannot
    # lower — the run would crash deep in tracing instead of explaining
    # itself.
    ring_fusion = os.environ.get("BENCH_RING_FUSION", "xla")
    if ring_fusion not in ("xla", "fused"):
        print(
            json.dumps({
                "error": f"BENCH_RING_FUSION={ring_fusion!r} is not one "
                "of xla|fused"
            }),
            file=sys.stderr,
        )
        return 2
    if ring_fusion == "fused" and backend != "ring-overlap":
        print(
            json.dumps({
                "error": f"BENCH_RING_FUSION=fused conflicts with "
                f"BENCH_BACKEND={backend}: the fused collective-matmul "
                "rotation exists only on the ring-overlap backend (on "
                "'ring' the blocking schedule contradicts in-kernel "
                "streaming by construction; on single-device backends "
                "there is no rotation at all) — the series would be a "
                "mislabeled measurement"
            }),
            file=sys.stderr,
        )
        return 2
    if ring_fusion == "fused" and jax.default_backend() not in (
        "tpu", "cpu"
    ):
        print(
            json.dumps({
                "error": "BENCH_RING_FUSION=fused needs a platform whose "
                "Pallas path exists — TPU hardware (in-kernel async "
                "remote DMAs) or CPU (interpret-mode parity form) — got "
                f"{jax.default_backend()!r}; the fused kernel cannot "
                "lower here and the run would die in tracing instead of "
                "refusing"
            }),
            file=sys.stderr,
        )
        return 2
    # BENCH_IVF_PARTITIONS=P: the clustered (IVF) path — the corpus is
    # k-means-partitioned once OUTSIDE the timed region (index build is
    # the amortized half, like the data upload), and each timed rep is
    # the full all-pairs query sweep probing only BENCH_IVF_NPROBE
    # partitions per query (unset = auto-tuned to cfg.recall_target). The
    # series name carries the knobs, and the recall gate for IVF rows is
    # the configured recall_target, not the exact path's 0.999 — the
    # clustered rung's acceptance bar IS its measured recall target
    # (DESIGN.md ladder rung 4); vs_baseline still zeroes on a miss.
    ivf_partitions = os.environ.get("BENCH_IVF_PARTITIONS")
    ivf_nprobe = os.environ.get("BENCH_IVF_NPROBE")
    ivf_shards = os.environ.get("BENCH_IVF_SHARDS")
    if ivf_shards and not ivf_partitions:
        print(
            json.dumps({
                "error": "BENCH_IVF_SHARDS without BENCH_IVF_PARTITIONS: "
                "sharding distributes a clustered index's partition "
                "buckets over the mesh — a shard count without "
                "partitions would be silently ignored"
            }),
            file=sys.stderr,
        )
        return 2
    if ivf_shards and not ivf_shards.isdigit():
        # a typo'd knob must be a usage refusal (never banked, never
        # fallback-triggering), not an uncaught crash the supervisor
        # books as a failed series
        print(
            json.dumps({
                "error": f"BENCH_IVF_SHARDS={ivf_shards!r} is not a "
                "positive integer"
            }),
            file=sys.stderr,
        )
        return 2
    if ivf_shards and int(ivf_shards) > len(jax.devices()):
        print(
            json.dumps({
                "error": f"BENCH_IVF_SHARDS={ivf_shards} exceeds the "
                f"{len(jax.devices())} visible device(s): the sharded "
                "clustered index places one bucket slice per device — "
                "set BENCH_PLATFORM=cpu for virtual host devices, or "
                "lower the shard count"
            }),
            file=sys.stderr,
        )
        return 2
    if ivf_shards and os.environ.get("BENCH_RING_XFER"):
        print(
            json.dumps({
                "error": "BENCH_RING_XFER conflicts with "
                "BENCH_IVF_SHARDS: the candidate exchange moves bucket "
                "rows at the at-rest dtype (BENCH_DTYPE=bfloat16 halves "
                "exchange bytes) — there is no ring rotation to re-dtype, "
                "so the knob would be silently ignored"
            }),
            file=sys.stderr,
        )
        return 2
    if (
        os.environ.get("BENCH_RING_XFER") == "int8"
        and precision_policy != "mixed"
    ):
        # same refusal the config itself raises, surfaced as the bench's
        # structured exit-2 so a sweep script reads WHY instead of a
        # traceback: int8 transfer has no rerank to absorb the
        # quantization under the exact policy — the run would silently
        # degrade every banked distance, not just the preselect keys
        print(
            json.dumps({
                "error": "BENCH_RING_XFER=int8 requires "
                "BENCH_PRECISION_POLICY=mixed: the block-scaled int8 "
                "transfer is dequantized into the compress dot and the "
                "exact HIGHEST rerank absorbs the quantization noise — "
                f"policy {precision_policy!r} has no rerank, so the "
                "banked recall would silently carry full quantization "
                "error"
            }),
            file=sys.stderr,
        )
        return 2
    if ivf_nprobe and not ivf_partitions:
        print(
            json.dumps({
                "error": "BENCH_IVF_NPROBE without BENCH_IVF_PARTITIONS: "
                "nprobe selects how many of a clustered index's "
                "partitions to scan — a probe count without partitions "
                "would be silently ignored"
            }),
            file=sys.stderr,
        )
        return 2
    if ivf_partitions and backend != "serial":
        print(
            json.dumps({
                "error": f"BENCH_IVF_PARTITIONS conflicts with "
                f"BENCH_BACKEND={backend}: the clustered search is a "
                "single-device serial-math path — an A/B sweep here would "
                "record identical serial runs mislabeled as backend "
                "variants"
            }),
            file=sys.stderr,
        )
        return 2
    if ivf_partitions and os.environ.get("BENCH_PRECISION"):
        print(
            json.dumps({
                "error": "BENCH_PRECISION conflicts with "
                "BENCH_IVF_PARTITIONS: the clustered search owns its dot "
                "precisions (HIGHEST centroid score + rerank; DEFAULT "
                "compress under BENCH_PRECISION_POLICY=mixed)"
            }),
            file=sys.stderr,
        )
        return 2
    if ivf_partitions and (
        os.environ.get("BENCH_TOPK") or os.environ.get("BENCH_SCHEDULE")
    ):
        # the probed path always finishes with the exact rerank top-k and
        # has no tile-merge schedule — a banked line whose metadata names
        # a selection method / schedule that never ran would be a
        # mislabeled measurement (the library refuses the same knobs)
        print(
            json.dumps({
                "error": "BENCH_TOPK/BENCH_SCHEDULE conflict with "
                "BENCH_IVF_PARTITIONS: the clustered search always "
                "finishes with the exact rerank top-k and has no "
                "tile-merge schedule — the knobs would be silently "
                "ignored and the measurement mislabeled"
            }),
            file=sys.stderr,
        )
        return 2
    # BENCH_CENTER=0: skip mean-centering — read ONCE; the zero_eps pairing
    # below derives from the same bool so the two can never desync
    center = os.environ.get("BENCH_CENTER", "1") != "0"

    from mpi_knn_tpu import KNNConfig, all_knn
    from mpi_knn_tpu.data.mnist import load_mnist
    from mpi_knn_tpu.utils.report import recall_at_k
    from mpi_knn_tpu.utils.timing import device_sync

    X, _, source = load_mnist(m=m)
    maybe_beat("data")
    cfg = KNNConfig(
        k=k,
        backend=backend,
        query_tile=int(os.environ.get("BENCH_QT", "4096")),
        # corpus tile capped at 8192: exact lax.top_k over very wide
        # (~60k-col) concats is the known device-wedge mode on the tunneled
        # transport (round-1 watchdog fired on the whole-corpus default).
        # A surviving 8k-tile run beats a wedged "faster" config every time;
        # the aggressive whole-corpus tiling stays reachable via BENCH_CT.
        corpus_tile=int(os.environ.get("BENCH_CT", "8192")),
        topk_method=os.environ.get("BENCH_TOPK", "exact"),
        merge_schedule=os.environ.get("BENCH_SCHEDULE", "twolevel"),
        topk_block=int(os.environ.get("BENCH_BLOCK", "128")),
        pallas_variant=os.environ.get("BENCH_PALLAS_VARIANT", "tiles"),
        recall_target=float(os.environ.get("BENCH_RT", "0.999")),
        dtype=os.environ.get("BENCH_DTYPE", "float32"),
        precision_policy=precision_policy,
        # BENCH_RING_XFER=bfloat16 halves ICI bytes per ring hop (the knob
        # only matters for BENCH_BACKEND=ring/ring-overlap)
        ring_transfer_dtype=os.environ.get("BENCH_RING_XFER") or None,
        ring_schedule=ring_schedule,
        ring_fusion=ring_fusion,
        # uncentered mode exists because raw MNIST pixels are small integers
        # — exactly representable even in bf16 — where *centered* values lose
        # mantissa bits. The relative zero-exclusion threshold is calibrated
        # for centered data (ops/topk.py), so uncentered runs switch to an
        # absolute epsilon: above the fp noise of a true duplicate at these
        # magnitudes (≲16 in squared space), orders below genuine MNIST
        # neighbor distances (~1e5).
        center=center,
        zero_eps=0.0 if center else 64.0,
        partitions=int(ivf_partitions) if ivf_partitions else None,
        nprobe=int(ivf_nprobe) if ivf_nprobe else None,
        ivf_shards=int(ivf_shards) if ivf_shards else None,
        # bench default HIGH (3-pass bf16): measured recall 1.0 on the
        # integer-pixel corpus with ~4% median win over HIGHEST (r3 A/B,
        # BASELINE.md). The LIBRARY default stays HIGHEST — the bench knows
        # its data; the library does not. BENCH_PRECISION overrides;
        # BENCH_PRECISION_POLICY=mixed takes the knob over entirely and the
        # ivf search path fixes its own dot precisions (both conflicting
        # combinations were rejected above).
        matmul_precision=None if (ivf_partitions or
                                  precision_policy == "mixed")
        else os.environ.get("BENCH_PRECISION") or "high",
    )

    if ivf_partitions:
        from mpi_knn_tpu.ivf import build_ivf_index
        from mpi_knn_tpu.ivf.search import (
            prepare_query_tiles,
            run_query_tiles,
        )

        # index build (k-means train + nprobe tune) is the amortized
        # half — outside the timed region, like the corpus upload below;
        # the queries are likewise centered/padded/tiled and put on
        # device ONCE, so the timed region is probe compute + sync only
        # (the dense series' timer placement — a per-rep host centering
        # pass would make the two series incomparable)
        # build_ivf_index dispatches on cfg.ivf_shards: the sharded form
        # trains the same single-device k-means then distributes the
        # bucket store over the ring mesh (ivf/sharded.py) — either way
        # the build is the amortized half, outside the timed region
        index = build_ivf_index(X, cfg)
        maybe_beat("index-build")
        rcfg = index.compatible_cfg(index.cfg)
        qids = np.arange(m, dtype=np.int32)
        if ivf_shards:
            from mpi_knn_tpu.ivf.sharded import (
                prepare_sharded_tiles,
                run_sharded_tiles,
            )

            q_tiles, qid_tiles, q_pad, _, route_cap = prepare_sharded_tiles(
                index, X, qids, rcfg
            )

            def run_ivf():
                d, i, _ = run_sharded_tiles(
                    index, q_tiles, qid_tiles, rcfg, route_cap
                )
                return d, i
        else:
            q_tiles, qid_tiles, q_pad, _ = prepare_query_tiles(
                index, X, qids, rcfg
            )

            def run_ivf():
                return run_query_tiles(index, q_tiles, qid_tiles, rcfg)
        device_sync(q_tiles)
        with flight_span("warm", cat="bench", backend=index.backend):
            d, i = run_ivf()  # warm
            device_sync(d, i)
        maybe_beat("warm")
        times = []
        for r in range(reps):
            with flight_span("rep", cat="bench", rep=r):
                t0 = time.perf_counter()
                d, i = run_ivf()
                device_sync(d, i)
                times.append(time.perf_counter() - t0)
            maybe_beat(f"rep{r}")
        got_ids = np.asarray(
            jax.device_get(i)
        ).reshape(q_pad, rcfg.k)[:m]
    else:
        # data to device ONCE — the timed region is the all-kNN phase,
        # matching the reference's timer placement
        Xd = jax.device_put(jnp.asarray(X, dtype=jnp.dtype(cfg.dtype)))
        device_sync(Xd)

        # compile + warm up
        with flight_span("warm", cat="bench", backend=backend):
            result = all_knn(Xd, config=cfg)
            device_sync(result.dists)
        maybe_beat("warm")

        times = []
        for r in range(reps):
            with flight_span("rep", cat="bench", rep=r):
                t0 = time.perf_counter()
                result = all_knn(Xd, config=cfg)
                device_sync(result.dists, result.ids)
                times.append(time.perf_counter() - t0)
            maybe_beat(f"rep{r}")
    # median is the headline (VERDICT r1 #9): honest under transport noise;
    # min stays visible on stderr for best-case comparisons
    value = float(np.median(times))

    sample = np.linspace(0, m - 1, num=min(256, m), dtype=np.int64)
    want = oracle_topk(X, sample, k)
    if ivf_partitions:
        got = got_ids[sample]
    else:
        got = np.asarray(jax.device_get(result.ids[jnp.asarray(sample)]))
    recall = recall_at_k(got, want)
    maybe_beat("oracle")

    n_chips = jax.local_device_count() if jax.default_backend() == "tpu" else 1
    target_here = NORTH_STAR_SECONDS * (NORTH_STAR_CHIPS / n_chips)
    gate = cfg.recall_target if ivf_partitions else RECALL_GATE
    vs = (target_here / value) if recall >= gate else 0.0

    line = {
        "metric": metric_name(),
        "value": round(value, 4),
        "unit": "s",
        "vs_baseline": round(vs, 3),
    }
    print(json.dumps(line), flush=True)
    # context for humans / the judge, on stderr so stdout stays one line
    print(
        json.dumps(
            {
                "backend": backend,
                "data": source,
                "shape": list(X.shape),
                "recall_at_k_vs_oracle": round(float(recall), 5),
                "times": [round(t, 4) for t in times],
                "min_seconds": round(min(times), 4),
                "chips": n_chips,
                "platform": jax.default_backend(),
                "target_seconds_at_this_chip_count": target_here,
                "topk_method": cfg.topk_method,
                "precision_policy": cfg.precision_policy,
                "partitions": cfg.partitions,
                "nprobe": (index.nprobe if ivf_partitions else None),
                "ivf_shards": cfg.ivf_shards,
                "recall_gate": gate,
                "ring_fusion": cfg.ring_fusion,
                "merge_schedule": cfg.merge_schedule,
                "tiles": [cfg.query_tile, cfg.corpus_tile],
            }
        ),
        file=sys.stderr,
    )
    return 0


# ---------------------------------------------------------------------------
# Supervisor: one child subprocess per series, heartbeat-watchdogged


def _note(msg: str) -> None:
    # never JSON-shaped: harness tooling reads the LAST '{'-prefixed
    # stderr line as the measurement context object
    print(f"bench-supervisor: {msg}", file=sys.stderr, flush=True)


def _parse_series():
    """BENCH_SERIES (JSON list of env-overlay objects) → list of dicts;
    unset = one series from the ambient knobs. Malformed input is a loud
    usage error (None return → supervisor exits 2): a typo'd round spec
    silently measuring the default series would be a mislabeled round."""
    raw = os.environ.get("BENCH_SERIES")
    if not raw:
        return [{}]
    try:
        doc = json.loads(raw)
        if not isinstance(doc, list) or not doc or not all(
            isinstance(s, dict) for s in doc
        ):
            raise ValueError("want a non-empty JSON list of objects")
    except (json.JSONDecodeError, ValueError) as e:
        print(
            json.dumps({
                "error": f"bad BENCH_SERIES: {e} — want a JSON list of "
                'env-overlay objects, e.g. [{"name": "exact"}, '
                '{"name": "mixed", "BENCH_PRECISION_POLICY": "mixed"}]'
            }),
            file=sys.stderr,
        )
        return None
    return doc


def _series_label(i: int, overlay: dict) -> str:
    return str(overlay.get("name") or f"series{i}")


def _child_env(overlay: dict) -> dict:
    env = dict(os.environ)
    # children never recurse into supervision, and never re-run preflight
    for k in ("BENCH_SERIES", "BENCH_DOCTOR"):
        env.pop(k, None)
    for k, v in overlay.items():
        if k == "name":
            continue
        env[k] = str(v)
    env["TKNN_BENCH_CHILD"] = "1"
    return env


def _measurement_line(stdout: str):
    """The LAST metric/value JSON line of a child's stdout, or None."""
    found = None
    for line in stdout.splitlines():
        try:
            doc = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(doc, dict) and "metric" in doc and "value" in doc:
            found = doc
    return found


def _failed_line(metric: str, series: str, status: str,
                 time_until_kill_s: float | None = None,
                 flight: dict | None = None) -> dict:
    """The structured line a failed series banks (ISSUE 7 shape):
    ``value`` is null — a watchdog kill is NOT a measurement, and
    BENCH_r05 proved a numeric value here gets read as one (the timeout
    banked as ``value: 480.0, vs_baseline: 0.0``, a kill stamped as a
    zero-regression data point). The kill time lives in the explicit
    ``time_until_kill_s`` field instead, the line NEVER carries
    ``vs_baseline``, and the child's span flight-record summary (open
    spans name the step the kill interrupted) rides along when the
    worker recorded one."""
    doc = {
        "metric": metric,
        "value": None,
        "unit": "s",
        "failed": True,
        "series": series,
        "status": status,
        "time_until_kill_s": time_until_kill_s,
    }
    if flight is not None:
        doc["flight"] = flight
    return doc


def _is_usage_error(res) -> bool:
    """A child that refused its knobs (loud exit-2 convention): a
    configuration bug, not a device failure — it must NOT be banked as a
    failed measurement (the series name would be lying) and must NOT
    trigger the CPU fallback (the bad knobs would just recur)."""
    return (
        res.status == "crashed"
        and res.returncode == 2
        and '"error"' in (res.stderr_tail + res.stdout)
    )


def _cpu_fallback_line(primary_metric: str):
    """The round ladder's LAST rung: re-run the bench on the CPU platform
    in a fresh supervised subprocess (the wedged transport lives in the
    dead children; the fallback must share nothing with them) at a
    CPU-feasible corpus size. Returns the parsed measurement line, or
    None if the fallback failed too.

    4 of 5 r5 rounds banked only ``rc: 2`` watchdog JSON ("no measurement
    completed") — a dead chip erased the whole round's signal. The CPU
    number says nothing absolute about the TPU, but it pins the RELATIVE
    per-round trajectory on the platform that always works (the
    bench_ops.py rationale), which beats banking nothing.
    """
    if os.environ.get("BENCH_NO_FALLBACK") == "1":
        return None  # recursion/choice guard: the last rung is opt-out-able
    from mpi_knn_tpu.resilience.worker import run_supervised

    m = min(int(os.environ.get("BENCH_M", "60000")),
            int(os.environ.get("BENCH_FALLBACK_M", "8000")))
    env = dict(os.environ)
    # serial CPU is the one configuration with no device transport, no
    # mesh and no knob conflicts; strip ring/pallas/ivf knobs the forced
    # backend would loudly refuse (their loud-exit-2 conflict checks are
    # correct for user runs — the fallback must not trip them), plus the
    # fault-injection arming (the last rung must run clean: an injected
    # hang propagating into the fallback would erase the round after all)
    # and the supervisor's own knobs
    for k in ("BENCH_RING_SCHEDULE", "BENCH_RING_XFER",
              "BENCH_RING_FUSION",
              "BENCH_PALLAS_VARIANT", "BENCH_IVF_PARTITIONS",
              "BENCH_IVF_NPROBE", "BENCH_IVF_SHARDS", "BENCH_SERIES",
              "BENCH_DOCTOR", "TKNN_FAULTS"):
        env.pop(k, None)
    env.update(
        BENCH_PLATFORM="cpu",
        BENCH_BACKEND="serial",
        BENCH_M=str(m),
        BENCH_NO_FALLBACK="1",
        TKNN_BENCH_CHILD="1",
    )
    res = run_supervised(
        [sys.executable, os.path.abspath(__file__)],
        env=env,
        beat_timeout_s=None,  # the wall bound below is the contract
        wall_timeout_s=float(
            os.environ.get("BENCH_FALLBACK_TIMEOUT_S", "420")
        ),
    )
    doc = _measurement_line(res.stdout) if res.ok else None
    if doc is None:
        return None
    # the fallback reports under an explicitly-marked series name:
    # a reduced m alone is NOT collision-proof (a genuine BENCH_M=8000
    # TPU series would share "mnist8k_..."), so the marker is part of the
    # name and the degraded number can never poison any primary series;
    # vs_baseline stays 0 — a CPU number does not beat a TPU north star
    # by definition
    doc["metric"] = doc["metric"] + "_cpu_fallback"
    doc["vs_baseline"] = 0.0
    doc["degraded"] = "cpu-fallback"
    doc["fallback_of"] = primary_metric
    return doc


def _series_timeouts(env: dict):
    """Per-series watchdog bounds, read from the child's (overlaid) env:
    a series overlay may tighten or loosen the ambient knobs — a
    wedge-prone configuration gets a short leash while its healthy
    siblings keep the full first-compile allowance. 0 disables."""
    beat = float(env.get("BENCH_BEAT_TIMEOUT_S", "240"))
    wall = float(env.get("BENCH_WATCHDOG_S", "480"))
    return (beat if beat > 0 else None, wall if wall > 0 else None)


def supervise() -> int:
    from mpi_knn_tpu.resilience.worker import run_supervised

    series = _parse_series()
    if series is None:
        return 2

    preflight_ok = True
    if os.environ.get("BENCH_DOCTOR") == "1":
        from mpi_knn_tpu.resilience.doctor import run_probe

        verdict = run_probe(
            platform=os.environ.get("BENCH_PLATFORM", "auto"),
            env={
                k: v for k, v in os.environ.items()
                if k != "TKNN_FAULTS" or "doctor" in v
            },
        )
        _note(f"doctor preflight: {json.dumps(verdict)}")
        preflight_ok = verdict["ok"]
        if not preflight_ok:
            _note("device failed preflight; skipping device series and "
                  "walking the failure ladder")

    banked_real = 0
    failed = []  # failure docs, in series order
    for i, overlay in enumerate(series):
        label = _series_label(i, overlay)
        env = _child_env(overlay)
        if not preflight_ok:
            # the series never started: 0 s until the (preflight) kill
            failed.append(_failed_line(
                metric_name(env), label, "preflight",
                time_until_kill_s=0.0,
            ))
            continue
        beat_timeout, wall_timeout = _series_timeouts(env)
        res = run_supervised(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            beat_timeout_s=beat_timeout,
            wall_timeout_s=wall_timeout,
        )
        if res.stderr_tail:
            # child context (its last '{'-line is the series' context
            # object) — forwarded verbatim, supervisor notes stay non-JSON
            sys.stderr.write(res.stderr_tail)
            if not res.stderr_tail.endswith("\n"):
                sys.stderr.write("\n")
        doc = _measurement_line(res.stdout) if res.ok else None
        if doc is not None:
            # print the moment it is earned: a supervisor-level kill
            # while a later series runs must not erase this one's signal
            # (eager is safe — the fallback only ever REPLACES failure
            # docs, and once one real line banked it never runs)
            banked_real += 1
            print(json.dumps(doc), flush=True)
            _note(f"series {label!r}: banked {doc['metric']} = "
                  f"{doc['value']}{doc['unit']}")
            continue
        if _is_usage_error(res):
            _note(f"series {label!r}: usage error (exit 2) — not banked; "
                  "fix the knobs")
            continue
        # wedged (beat starvation / wall kill) or crashed or silent-ok:
        # a structured failed line under the series' real name, with the
        # banked flight record telling where the time went. Buffered,
        # not printed: an all-failed round replaces these with the
        # fallback's one real line.
        status = res.status if res.status != "ok" else "crashed"
        failed.append(_failed_line(
            metric_name(env), label, status,
            time_until_kill_s=round(res.duration_s, 1),
            flight=res.flight,
        ))
        _note(
            f"series {label!r}: {status}"
            + (f" ({res.reason})" if res.reason else "")
            + f" after {res.duration_s:.1f}s at beat {res.beats} "
            f"{res.last_beat_label!r}; banked a failed line"
        )

    if banked_real == 0 and failed:
        fb = _cpu_fallback_line(failed[0]["metric"])
        if fb is not None:
            # the degraded line REPLACES the failed lines: the round
            # banks one real (self-labeled) measurement instead of a
            # pile of sentinels (PR 4's single-series behavior, kept)
            print(json.dumps(fb), flush=True)
            _note("no series banked; banked a degraded cpu-fallback "
                  f"measurement instead ({fb['metric']})")
            return 0
    for doc in failed:
        print(json.dumps(doc), flush=True)
    if banked_real > 0:
        return 0
    if failed:
        _note("no series banked a measurement (failed lines above)")
    return 2


if __name__ == "__main__":
    if os.environ.get("TKNN_BENCH_CHILD") == "1":
        sys.exit(main())
    sys.exit(supervise())
