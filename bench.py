"""Headline benchmark: MNIST-60k×784 all-kNN, k=10 (BASELINE.md north star:
< 1 s on a v5e-8 at recall@10 parity with the serial reference semantics).

Prints ONE JSON line:
    {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}

Methodology (mirrors the reference, which times ONLY the distance/top-k
phase — ``/root/reference/knn-serial.c:70,94-98`` — not I/O or voting):

- the corpus is placed on device once, outside the timed region;
- each timed rep runs the full ``all_knn`` API path on the device-resident
  corpus and synchronizes with ``device_sync`` (a 1-element fetch —
  ``block_until_ready`` alone can return at dispatch time on tunneled
  device transports and would under-report);
- value = MEDIAN rep wall-clock of the all-kNN phase (all reps plus the
  min are reported on stderr — min alone flatters a noisy transport);
- recall@10 is checked against a float64 host oracle on a 256-query sample
  (computed in matmul form, chunk-free at this sample size); a recall miss
  (<0.999) zeroes vs_baseline rather than reporting a fast-but-wrong number.

vs_baseline: north_star_seconds / value, scaled by the fraction of the
8-chip target this host provides (1 chip => target is 8 s), so >1.0 beats
the north star at equal silicon.

Environment knobs: BENCH_M (default 60000), BENCH_BACKEND (serial|pallas),
BENCH_REPS, BENCH_QT/BENCH_CT (tiles), BENCH_TOPK (exact|approx),
BENCH_PRECISION (default|high|highest), BENCH_PRECISION_POLICY
(exact|mixed — mixed is the compress-and-rerank pipeline and owns both dot
precisions, so it overrides BENCH_PRECISION),
BENCH_PALLAS_VARIANT (tiles|sweep), BENCH_IVF_PARTITIONS /
BENCH_IVF_NPROBE (clustered-index path: k-means partitions trained
outside the timed region, per-query probed scan timed; the series name
carries the knobs and the gate is the configured recall_target — the
clustered rung's own acceptance bar), BENCH_WATCHDOG_S (0 disables),
BENCH_PLATFORM (forces jax_platforms via the config API — JAX_PLATFORMS
alone is ignored by the axon TPU plugin), TKNN_MNIST (real data path;
synthetic surrogate otherwise).

The recall gate is FIXED at 0.999 regardless of knobs — it is the north
star's acceptance bar, not a tunable. Setting BENCH_RT below it tunes
approx_min_k to a recall the gate will reject, zeroing vs_baseline by
design (speed bought with recall does not count).
"""

import json
import os
import sys
import threading
import time

import numpy as np


NORTH_STAR_SECONDS = 1.0  # on 8 chips (v5e-8)
NORTH_STAR_CHIPS = 8
RECALL_GATE = 0.999


def metric_name() -> str:
    """One construction of the series name, shared by the success and
    watchdog paths so a failure always lands in the real series. The IVF
    knobs are part of the name: a clustered run measures a different
    computation (sublinear probed scan at a measured recall target) and
    must never masquerade as the exact full-scan series."""
    m = int(os.environ.get("BENCH_M", "60000"))
    k = int(os.environ.get("BENCH_K", "10"))
    ivf = ""
    if os.environ.get("BENCH_IVF_PARTITIONS"):
        p = os.environ["BENCH_IVF_PARTITIONS"]
        n = os.environ.get("BENCH_IVF_NPROBE", "auto")
        ivf = f"_ivf{p}p{n}"
    return f"mnist{m // 1000}k_allknn_k{k}{ivf}_seconds"


def oracle_topk(X: np.ndarray, sample: np.ndarray, k: int) -> np.ndarray:
    """f64 ground-truth neighbor ids for the sampled queries, matmul form
    (no (q, m, d) broadcast — that would be ~100 GB at MNIST scale)."""
    Xs = X.astype(np.float64)
    Q = Xs[sample]
    d = (
        (Q**2).sum(1)[:, None]
        + (Xs**2).sum(1)[None, :]
        - 2.0 * (Q @ Xs.T)
    )
    # reference zero-exclusion (SURVEY.md Q3) + exact self-exclusion
    d[d <= 1e-9] = np.inf
    d[np.arange(len(sample)), sample] = np.inf
    return np.argsort(d, axis=1, kind="stable")[:, :k]


def main() -> int:
    if os.environ.get("BENCH_PLATFORM"):
        # the axon TPU plugin ignores JAX_PLATFORMS; the shared helper is
        # the only reliable way to keep a CPU smoke run off the tunnel
        from mpi_knn_tpu.utils.platform import force_platform

        force_platform(os.environ["BENCH_PLATFORM"])

    import jax
    import jax.numpy as jnp

    m = int(os.environ.get("BENCH_M", "60000"))
    k = int(os.environ.get("BENCH_K", "10"))
    reps = int(os.environ.get("BENCH_REPS", "3"))
    if reps < 1:
        # median([]) would silently emit NaN as the headline value
        print(json.dumps({"error": "BENCH_REPS must be >= 1"}),
              file=sys.stderr)
        return 2
    backend = os.environ.get("BENCH_BACKEND", "serial")
    # BENCH_PRECISION_POLICY=mixed: the compress-and-rerank pipeline — the
    # O(q·c·d) dot runs single-pass bf16 MXU, only the 4k-overfetched
    # survivors are reranked at HIGHEST. The policy owns both dot
    # precisions, so combining it with an explicit BENCH_PRECISION is a
    # usage error — refuse loudly rather than silently ignore one knob
    # (an A/B sweep over BENCH_PRECISION would otherwise record identical
    # mixed runs mislabeled as precision variants).
    precision_policy = os.environ.get("BENCH_PRECISION_POLICY", "exact")
    if precision_policy == "mixed" and os.environ.get("BENCH_PRECISION"):
        print(
            json.dumps({
                "error": "BENCH_PRECISION conflicts with "
                "BENCH_PRECISION_POLICY=mixed (the policy owns both dot "
                "precisions: DEFAULT compress, HIGHEST rerank)"
            }),
            file=sys.stderr,
        )
        return 2
    # BENCH_RING_SCHEDULE=bidir: full-duplex ring rotation (both torus
    # directions at once, floor(P/2)+1 rounds). The knob only means anything
    # on a ring backend — setting it with a single-device backend would
    # silently measure an identical program under a different label, so the
    # conflicting combination is refused loudly (same treatment as
    # BENCH_PRECISION × BENCH_PRECISION_POLICY above).
    ring_schedule = os.environ.get("BENCH_RING_SCHEDULE", "uni")
    if ring_schedule != "uni" and backend not in ("ring", "ring-overlap"):
        print(
            json.dumps({
                "error": f"BENCH_RING_SCHEDULE={ring_schedule} conflicts "
                f"with BENCH_BACKEND={backend}: the ring schedule only "
                "exists on ring/ring-overlap backends — an A/B sweep here "
                "would record identical single-device runs mislabeled as "
                "schedule variants"
            }),
            file=sys.stderr,
        )
        return 2
    # BENCH_IVF_PARTITIONS=P: the clustered (IVF) path — the corpus is
    # k-means-partitioned once OUTSIDE the timed region (index build is
    # the amortized half, like the data upload), and each timed rep is
    # the full all-pairs query sweep probing only BENCH_IVF_NPROBE
    # partitions per query (unset = auto-tuned to cfg.recall_target). The
    # series name carries the knobs, and the recall gate for IVF rows is
    # the configured recall_target, not the exact path's 0.999 — the
    # clustered rung's acceptance bar IS its measured recall target
    # (DESIGN.md ladder rung 4); vs_baseline still zeroes on a miss.
    ivf_partitions = os.environ.get("BENCH_IVF_PARTITIONS")
    ivf_nprobe = os.environ.get("BENCH_IVF_NPROBE")
    if ivf_nprobe and not ivf_partitions:
        print(
            json.dumps({
                "error": "BENCH_IVF_NPROBE without BENCH_IVF_PARTITIONS: "
                "nprobe selects how many of a clustered index's "
                "partitions to scan — a probe count without partitions "
                "would be silently ignored"
            }),
            file=sys.stderr,
        )
        return 2
    if ivf_partitions and backend != "serial":
        print(
            json.dumps({
                "error": f"BENCH_IVF_PARTITIONS conflicts with "
                f"BENCH_BACKEND={backend}: the clustered search is a "
                "single-device serial-math path — an A/B sweep here would "
                "record identical serial runs mislabeled as backend "
                "variants"
            }),
            file=sys.stderr,
        )
        return 2
    if ivf_partitions and os.environ.get("BENCH_PRECISION"):
        print(
            json.dumps({
                "error": "BENCH_PRECISION conflicts with "
                "BENCH_IVF_PARTITIONS: the clustered search owns its dot "
                "precisions (HIGHEST centroid score + rerank; DEFAULT "
                "compress under BENCH_PRECISION_POLICY=mixed)"
            }),
            file=sys.stderr,
        )
        return 2
    if ivf_partitions and (
        os.environ.get("BENCH_TOPK") or os.environ.get("BENCH_SCHEDULE")
    ):
        # the probed path always finishes with the exact rerank top-k and
        # has no tile-merge schedule — a banked line whose metadata names
        # a selection method / schedule that never ran would be a
        # mislabeled measurement (the library refuses the same knobs)
        print(
            json.dumps({
                "error": "BENCH_TOPK/BENCH_SCHEDULE conflict with "
                "BENCH_IVF_PARTITIONS: the clustered search always "
                "finishes with the exact rerank top-k and has no "
                "tile-merge schedule — the knobs would be silently "
                "ignored and the measurement mislabeled"
            }),
            file=sys.stderr,
        )
        return 2
    # BENCH_CENTER=0: skip mean-centering — read ONCE; the zero_eps pairing
    # below derives from the same bool so the two can never desync
    center = os.environ.get("BENCH_CENTER", "1") != "0"

    from mpi_knn_tpu import KNNConfig, all_knn
    from mpi_knn_tpu.data.mnist import load_mnist
    from mpi_knn_tpu.utils.report import recall_at_k
    from mpi_knn_tpu.utils.timing import device_sync

    X, _, source = load_mnist(m=m)
    cfg = KNNConfig(
        k=k,
        backend=backend,
        query_tile=int(os.environ.get("BENCH_QT", "4096")),
        # corpus tile capped at 8192: exact lax.top_k over very wide
        # (~60k-col) concats is the known device-wedge mode on the tunneled
        # transport (round-1 watchdog fired on the whole-corpus default).
        # A surviving 8k-tile run beats a wedged "faster" config every time;
        # the aggressive whole-corpus tiling stays reachable via BENCH_CT.
        corpus_tile=int(os.environ.get("BENCH_CT", "8192")),
        topk_method=os.environ.get("BENCH_TOPK", "exact"),
        merge_schedule=os.environ.get("BENCH_SCHEDULE", "twolevel"),
        topk_block=int(os.environ.get("BENCH_BLOCK", "128")),
        pallas_variant=os.environ.get("BENCH_PALLAS_VARIANT", "tiles"),
        recall_target=float(os.environ.get("BENCH_RT", "0.999")),
        dtype=os.environ.get("BENCH_DTYPE", "float32"),
        precision_policy=precision_policy,
        # BENCH_RING_XFER=bfloat16 halves ICI bytes per ring hop (the knob
        # only matters for BENCH_BACKEND=ring/ring-overlap)
        ring_transfer_dtype=os.environ.get("BENCH_RING_XFER") or None,
        ring_schedule=ring_schedule,
        # uncentered mode exists because raw MNIST pixels are small integers
        # — exactly representable even in bf16 — where *centered* values lose
        # mantissa bits. The relative zero-exclusion threshold is calibrated
        # for centered data (ops/topk.py), so uncentered runs switch to an
        # absolute epsilon: above the fp noise of a true duplicate at these
        # magnitudes (≲16 in squared space), orders below genuine MNIST
        # neighbor distances (~1e5).
        center=center,
        zero_eps=0.0 if center else 64.0,
        partitions=int(ivf_partitions) if ivf_partitions else None,
        nprobe=int(ivf_nprobe) if ivf_nprobe else None,
        # bench default HIGH (3-pass bf16): measured recall 1.0 on the
        # integer-pixel corpus with ~4% median win over HIGHEST (r3 A/B,
        # BASELINE.md). The LIBRARY default stays HIGHEST — the bench knows
        # its data; the library does not. BENCH_PRECISION overrides;
        # BENCH_PRECISION_POLICY=mixed takes the knob over entirely and the
        # ivf search path fixes its own dot precisions (both conflicting
        # combinations were rejected above).
        matmul_precision=None if (ivf_partitions or
                                  precision_policy == "mixed")
        else os.environ.get("BENCH_PRECISION") or "high",
    )

    if ivf_partitions:
        from mpi_knn_tpu.ivf import build_ivf_index
        from mpi_knn_tpu.ivf.search import (
            prepare_query_tiles,
            run_query_tiles,
        )

        # index build (k-means train + nprobe tune) is the amortized
        # half — outside the timed region, like the corpus upload below;
        # the queries are likewise centered/padded/tiled and put on
        # device ONCE, so the timed region is probe compute + sync only
        # (the dense series' timer placement — a per-rep host centering
        # pass would make the two series incomparable)
        index = build_ivf_index(X, cfg)
        rcfg = index.compatible_cfg(index.cfg)
        qids = np.arange(m, dtype=np.int32)
        q_tiles, qid_tiles, q_pad, _ = prepare_query_tiles(
            index, X, qids, rcfg
        )
        device_sync(q_tiles)
        d, i = run_query_tiles(index, q_tiles, qid_tiles, rcfg)  # warm
        device_sync(d, i)
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            d, i = run_query_tiles(index, q_tiles, qid_tiles, rcfg)
            device_sync(d, i)
            times.append(time.perf_counter() - t0)
        got_ids = np.asarray(
            jax.device_get(i)
        ).reshape(q_pad, rcfg.k)[:m]
    else:
        # data to device ONCE — the timed region is the all-kNN phase,
        # matching the reference's timer placement
        Xd = jax.device_put(jnp.asarray(X, dtype=jnp.dtype(cfg.dtype)))
        device_sync(Xd)

        # compile + warm up
        result = all_knn(Xd, config=cfg)
        device_sync(result.dists)

        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            result = all_knn(Xd, config=cfg)
            device_sync(result.dists, result.ids)
            times.append(time.perf_counter() - t0)
    # median is the headline (VERDICT r1 #9): honest under transport noise;
    # min stays visible on stderr for best-case comparisons
    value = float(np.median(times))

    sample = np.linspace(0, m - 1, num=min(256, m), dtype=np.int64)
    want = oracle_topk(X, sample, k)
    if ivf_partitions:
        got = got_ids[sample]
    else:
        got = np.asarray(jax.device_get(result.ids[jnp.asarray(sample)]))
    recall = recall_at_k(got, want)

    n_chips = jax.local_device_count() if jax.default_backend() == "tpu" else 1
    target_here = NORTH_STAR_SECONDS * (NORTH_STAR_CHIPS / n_chips)
    gate = cfg.recall_target if ivf_partitions else RECALL_GATE
    vs = (target_here / value) if recall >= gate else 0.0

    line = {
        "metric": metric_name(),
        "value": round(value, 4),
        "unit": "s",
        "vs_baseline": round(vs, 3),
    }
    # set-and-print must be atomic against the watchdog's check-and-print
    # (a fired watchdog spends minutes in the fallback subprocess; the
    # primary finishing in that window must not produce a SECOND stdout
    # measurement line). The watchdog os._exits while holding this lock,
    # so losing the race here means never reaching the duplicate print.
    with _EMIT_LOCK:
        _COMPLETED.set()  # suppress the watchdog from here on
        print(json.dumps(line), flush=True)
    # context for humans / the judge, on stderr so stdout stays one line
    print(
        json.dumps(
            {
                "backend": backend,
                "data": source,
                "shape": list(X.shape),
                "recall_at_k_vs_oracle": round(float(recall), 5),
                "times": [round(t, 4) for t in times],
                "min_seconds": round(min(times), 4),
                "chips": n_chips,
                "platform": jax.default_backend(),
                "target_seconds_at_this_chip_count": target_here,
                "topk_method": cfg.topk_method,
                "precision_policy": cfg.precision_policy,
                "partitions": cfg.partitions,
                "nprobe": (index.nprobe if ivf_partitions else None),
                "recall_gate": gate,
                "merge_schedule": cfg.merge_schedule,
                "tiles": [cfg.query_tile, cfg.corpus_tile],
            }
        ),
        file=sys.stderr,
    )
    return 0


_COMPLETED = threading.Event()
# serializes "check _COMPLETED, then print a measurement line" between the
# main thread and the watchdog thread: stdout carries EXACTLY one
# measurement line per run, whoever takes the lock first wins
_EMIT_LOCK = threading.Lock()


def _cpu_fallback_line():
    """Re-run the bench on the CPU platform in a FRESH subprocess (the
    wedged transport lives in this process; the fallback must not share
    it) at a CPU-feasible corpus size. Returns the fallback's parsed JSON
    measurement line, or None if it too failed.

    4 of 5 r5 rounds banked only ``rc: 2`` watchdog JSON ("no measurement
    completed") — a dead chip erased the whole round's signal. The CPU
    number says nothing absolute about the TPU, but it pins the RELATIVE
    per-round trajectory on the platform that always works (the
    bench_ops.py rationale), which beats banking nothing.
    """
    if os.environ.get("BENCH_NO_FALLBACK") == "1":
        return None  # recursion guard: the fallback itself never falls back
    import subprocess

    m = min(int(os.environ.get("BENCH_M", "60000")),
            int(os.environ.get("BENCH_FALLBACK_M", "8000")))
    env = dict(os.environ)
    # serial CPU is the one configuration with no device transport, no
    # mesh and no knob conflicts; strip ring/pallas knobs the forced
    # backend would loudly refuse (their loud-exit-2 conflict checks are
    # correct for user runs — the fallback must not trip them)
    for k in ("BENCH_RING_SCHEDULE", "BENCH_RING_XFER",
              "BENCH_PALLAS_VARIANT", "BENCH_IVF_PARTITIONS",
              "BENCH_IVF_NPROBE"):
        env.pop(k, None)
    env.update(
        BENCH_PLATFORM="cpu",
        BENCH_BACKEND="serial",
        BENCH_M=str(m),
        BENCH_WATCHDOG_S="0",  # the subprocess timeout below is the bound
        BENCH_NO_FALLBACK="1",
    )
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True,
            timeout=float(os.environ.get("BENCH_FALLBACK_TIMEOUT_S", "420")),
        )
    except Exception:
        return None
    for line in proc.stdout.splitlines():
        try:
            doc = json.loads(line)
        except json.JSONDecodeError:
            continue
        if "metric" in doc and "value" in doc:
            # the fallback reports under an explicitly-marked series name:
            # a reduced m alone is NOT collision-proof (a genuine
            # BENCH_M=8000 TPU series would share "mnist8k_..."), so the
            # marker is part of the name and the degraded number can never
            # poison any primary series; vs_baseline stays 0 — a CPU
            # number does not beat a TPU north star by definition
            doc["metric"] = doc["metric"] + "_cpu_fallback"
            doc["vs_baseline"] = 0.0
            doc["degraded"] = "cpu-fallback"
            doc["fallback_of"] = metric_name()
            return doc
    return None


def _watchdog_fire():
    # a wedged device transport hangs inside a native runtime call that
    # never returns — a signal handler would never run (the interpreter
    # can't regain control), so a daemon THREAD takes over: it banks a
    # degraded CPU-mesh measurement from a fresh process when it can, and
    # only then falls back to the honest failure line (vs_baseline 0)
    # before hard-exiting instead of hanging the harness
    if _COMPLETED.is_set():
        return  # raced with a just-finished run: its success line stands
    print(
        json.dumps({"warning": "watchdog fired (wedged transport?); "
                               "attempting CPU fallback measurement"}),
        file=sys.stderr,
        flush=True,
    )
    fallback = _cpu_fallback_line()
    # check-and-print under the emit lock: the primary finishing during
    # the minutes the fallback subprocess ran must not race us into a
    # second stdout measurement line. os._exit below runs while the lock
    # is held — a primary blocked on it dies with the process, before its
    # duplicate print.
    with _EMIT_LOCK:
        if _COMPLETED.is_set():
            return  # the primary finished while the fallback ran: it stands
        if fallback is not None:
            print(json.dumps(fallback), flush=True)
            print(
                json.dumps({
                    "error": "watchdog: device unresponsive; banked a "
                    "degraded cpu-fallback measurement instead",
                    "fallback_metric": fallback["metric"],
                }),
                file=sys.stderr,
                flush=True,
            )
            # the round banked a real (degraded, self-labeled) measurement
            # — exit 0 so the harness records it instead of discarding it
            os._exit(0)
        watchdog_s = float(os.environ.get("BENCH_WATCHDOG_S", "480"))
        print(
            json.dumps(
                {
                    # same series name a successful run reports; value is
                    # the timeout itself ("took at least this long") so
                    # lower-is-better aggregations are not poisoned by a
                    # negative sentinel
                    "metric": metric_name(),
                    "value": watchdog_s,
                    "unit": "s",
                    "vs_baseline": 0.0,
                    "failed": True,
                }
            ),
            flush=True,
        )
        print(
            json.dumps({"error": "watchdog: device unresponsive (wedged "
                                 "transport?); no measurement completed"}),
            file=sys.stderr,
            flush=True,
        )
        os._exit(2)


if __name__ == "__main__":
    # generous enough for first-compile (~40 s) + the run, tight enough
    # that a wedged tunnel doesn't hang the harness forever
    watchdog_s = int(os.environ.get("BENCH_WATCHDOG_S", "480"))
    t = None
    if watchdog_s > 0:
        t = threading.Timer(watchdog_s, _watchdog_fire)
        t.daemon = True
        t.start()
    try:
        rc = main()
    finally:
        # main sets _COMPLETED before printing its result line, so a timer
        # that fires during the final prints is a no-op; cancel handles the
        # not-yet-fired case (exception paths included)
        if t is not None:
            t.cancel()
    sys.exit(rc)
