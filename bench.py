"""Headline benchmark: MNIST-60k×784 all-kNN, k=10 (BASELINE.md north star:
< 1 s on a v5e-8 at recall@10 parity with the serial reference semantics).

Prints ONE JSON line:
    {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}

- value: best wall-clock seconds of the all-kNN phase (post-compile,
  device-synchronized) on the available hardware.
- vs_baseline: north_star_seconds / value, scaled by the fraction of the
  8-chip target this host provides (1 chip => target is 8 s), so >1.0 beats
  the north star at equal silicon. Recall@10 against the f64 oracle on a
  subsample is checked and reported in the JSON; a recall miss zeroes
  vs_baseline rather than reporting a fast-but-wrong number.

Environment knobs: BENCH_M (default 60000), BENCH_BACKEND (serial|pallas),
BENCH_REPS, TKNN_MNIST (real data path; synthetic surrogate otherwise).
"""

import json
import os
import sys
import time

import numpy as np


NORTH_STAR_SECONDS = 1.0  # on 8 chips (v5e-8)
NORTH_STAR_CHIPS = 8


def main() -> int:
    import jax

    m = int(os.environ.get("BENCH_M", "60000"))
    k = int(os.environ.get("BENCH_K", "10"))
    reps = int(os.environ.get("BENCH_REPS", "3"))
    backend = os.environ.get("BENCH_BACKEND", "serial")

    from mpi_knn_tpu import KNNConfig, all_knn
    from mpi_knn_tpu.data.mnist import load_mnist
    from mpi_knn_tpu.utils.report import recall_at_k

    X, _, source = load_mnist(m=m)
    cfg = KNNConfig(
        k=k,
        backend=backend,
        query_tile=int(os.environ.get("BENCH_QT", "2048")),
        corpus_tile=int(os.environ.get("BENCH_CT", "4096")),
        dtype=os.environ.get("BENCH_DTYPE", "float32"),
        matmul_precision=os.environ.get("BENCH_PRECISION") or None,
    )

    # compile + warm up
    result = all_knn(X, config=cfg)
    result.dists.block_until_ready()

    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        result = all_knn(X, config=cfg)
        result.dists.block_until_ready()
        times.append(time.perf_counter() - t0)
    value = min(times)

    # recall vs the f64 oracle on a query subsample (full oracle is O(m^2) on
    # host; 256 rows give a tight estimate)
    sample = np.linspace(0, m - 1, num=min(256, m), dtype=np.int64)
    Xs = X.astype(np.float64)
    d = ((Xs[sample][:, None, :] - Xs[None, :, :]) ** 2).sum(-1)
    d[d <= 0.0] = np.inf
    d[np.arange(len(sample)), sample] = np.inf
    want = np.argsort(d, axis=1, kind="stable")[:, :k]
    recall = recall_at_k(np.asarray(result.ids)[sample], want)

    n_chips = jax.local_device_count() if jax.default_backend() == "tpu" else 1
    target_here = NORTH_STAR_SECONDS * (NORTH_STAR_CHIPS / n_chips)
    vs = (target_here / value) if recall >= 0.999 else 0.0

    line = {
        "metric": f"mnist{m // 1000}k_allknn_k{k}_seconds",
        "value": round(value, 4),
        "unit": "s",
        "vs_baseline": round(vs, 3),
    }
    print(json.dumps(line))
    # context for humans / the judge, on stderr so stdout stays one line
    print(
        json.dumps(
            {
                "backend": backend,
                "data": source,
                "shape": list(X.shape),
                "recall_at_k_vs_oracle": round(float(recall), 5),
                "times": [round(t, 4) for t in times],
                "chips": n_chips,
                "platform": jax.default_backend(),
                "target_seconds_at_this_chip_count": target_here,
            }
        ),
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
