"""Sharded clustered (IVF) index: the bucket store distributed over the
ring mesh with a routed candidate exchange — TPU-KNN's actual deployment
shape (PAPERS.md), and the first configuration in this repo that scales
corpus CAPACITY with devices while keeping per-query work SUBLINEAR.

Layout (derived, never stored — one saved index serves on any shard
count):

- the trained ``(P, d)`` centroid table and its norms are REPLICATED on
  every shard: routing is a small dot, and replicating it means every
  shard can score its own resident queries without a collective;
- the padded bucket store ``(P, cap, d)`` + ids + norms shard over the
  ring axis in CONTIGUOUS, capacity-balanced slices: every bucket has the
  same static ``bucket_cap``, so ``ceil(P / S)`` clusters per shard
  balances resident bytes exactly; cluster ``c`` lives on shard
  ``c // per_shard`` at local slot ``c % per_shard`` (padding clusters on
  the last shard carry id −1 rows and are unreachable — the routing table
  only has P real rows);
- query batches shard over the same axis: each device is the HOME shard
  of its resident query tiles.

Routed two-stage search, per query tile (all shapes static — the serving
bucket cache stays zero-recompile):

1. **score at home** — every shard scores the replicated centroid table
   for its resident tile (the shared ``ivf/search.score_centroids``:
   exact HIGHEST dot + static top-nprobe) → the routing table of
   ``(q_tile, nprobe)`` global partition ids;
2. **request exchange** — each (query, probe) pair is a ROUTE to the
   owning shard. Routes to the same owner are ranked PROBE-RANK-major
   (every query's rank-0 probe outranks any query's rank-1 probe, so a
   tight cap is spent on the highest-value probes tile-wide) and padded
   to the static per-(home, owner) ``route_cap`` (−1 = empty slot; ranks
   beyond the cap are DROPPED and counted — see
   ``KNNConfig.ivf_route_cap``); ONE static ``all_to_all`` delivers every
   shard its incoming request table;
3. **candidate exchange** — each owner gathers the requested buckets from
   its resident slice and three ``all_to_all``s return the
   ``(rows, ids, norms)`` tiles to the requesting home shards (rows
   travel at the at-rest dtype — a bf16 store halves exchange bytes,
   the EQuARX-cheap-collective direction);
4. **rerank at home** — the returned candidates are scattered back to
   ``(q_tile, nprobe·cap, d)`` in EXACTLY the probe order the
   single-device gather produces, then the shared
   ``ivf/search.finish_candidates`` runs: the mixed compress pass and the
   exact HIGHEST rerank are the same code as the single-device path, so
   ``precision_policy="mixed"`` composes and S=1 is bit-identical to the
   unsharded index.

Cost model: per query the exchange moves ≤ nprobe·cap·(d·itemsize + 8)
bytes and the rerank touches nprobe·cap·d elements — both independent of
P and m, while each shard's resident slice is m/S. Lint rule R2 runs in
STRICT mode per shard (the exchange + rerank working set is the declared
budget; the resident slice is exempt plumbing) and R4 accounts the
all-to-alls (count, full-ring replica groups, payload bytes ≤ the
declared exchange budget).

Per-shard exchange stats ride out of the program as a third output
``(3·S,)`` — [routed, dropped, served] per shard — aliased to a donated
scratch like the top-k carry, so R5's every-output-aliased contract
holds and the serving engine can stamp routed-candidate counters,
exchange bytes, and probe-cap overflow drops into the metrics registry
without an extra device program.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mpi_knn_tpu.config import KNNConfig
from mpi_knn_tpu.ivf.index import IVFIndex, _refuse_inert_knobs
from mpi_knn_tpu.ivf.search import finish_candidates, score_centroids
from mpi_knn_tpu.ops.quant import (
    QUANT_DTYPES,
    dequantize_rows,
    row_wire_bytes,
)
from mpi_knn_tpu.ops.topk import init_topk_tiles, merge_topk
from mpi_knn_tpu.parallel.mesh import make_ring_mesh
from mpi_knn_tpu.parallel.partition import pad_to_multiple
from mpi_knn_tpu.utils.compat import shard_map

# per-shard exchange stats vector: [routed (non-dropped probe routes this
# shard's resident queries issued), dropped (probe-cap overflow), served
# (real incoming requests this shard answered as owner)]
STATS_FIELDS = ("routed", "dropped", "served")
N_STATS = len(STATS_FIELDS)


def resolve_route_cap(cfg: KNNConfig, q_tile: int, nprobe: int) -> int:
    """The static per-(home, owner) route capacity for one query tile:
    ``cfg.ivf_route_cap`` clamped to the safe cap ``q_tile·nprobe`` (a
    bigger table could never fill), or the safe cap itself when unset
    (no probe can ever drop)."""
    safe = max(1, q_tile * nprobe)
    if cfg.ivf_route_cap is None:
        return safe
    return min(cfg.ivf_route_cap, safe)


def exchange_elems(shards: int, route_cap: int, cap: int, dim: int) -> int:
    """Largest single exchange buffer of one tile's candidate exchange, in
    elements — the (S, route_cap, cap, d) candidate-rows all-to-all (the
    ids/norms tables are d× smaller). This is what R2's strict per-shard
    budget must cover beyond the rerank working set."""
    return shards * route_cap * cap * dim


def exchange_bytes_per_tile(
    shards: int, route_cap: int, cap: int, dim: int, itemsize: int,
    scale_bytes: int = 0,
) -> int:
    """Total bytes the exchange all-to-alls of ONE query tile move per
    shard: the s32 request table plus rows (at-rest width — a quantized
    store's rows are its int8 code lanes, so callers pass the PACKED dim
    and itemsize 1) + ids (s32) + norms (f32) + ``scale_bytes`` (4 for a
    quantized store's per-row f32 scale, which rides its own all-to-all)
    per route. Static per executable — the serving engine stamps it into
    the exchange-bytes counter without reading the device, and R4 holds
    the compiled payload to it at the WIRE dtype."""
    per_route = 4 + cap * (dim * itemsize + 4 + 4 + scale_bytes)
    return shards * route_cap * per_route


def exchange_wire_args(index) -> tuple[int, int, int]:
    """(dim_lanes, itemsize, scale_bytes) of one candidate row on the
    exchange wire for an index — the adapter every
    :func:`exchange_bytes_per_tile` caller shares so the declared budget
    always prices the store that actually ships."""
    if getattr(index, "store_dtype", None) in QUANT_DTYPES:
        return index.buckets.shape[-1], 1, 4
    return index.dim, index.buckets.dtype.itemsize, 0


def expected_exchange_alltoalls(index) -> int:
    """Collectives of one routed tile: the request table + the
    rows/ids/norms returns (4), plus the scale-table return of a
    quantized store (5) — the count R4 pins in the lowered program."""
    return 5 if getattr(index, "store_dtype", None) in QUANT_DTYPES else 4


def sharded_query_shapes(
    cfg: KNNConfig, nprobe: int, bucket_cap: int, dim: int, nq: int,
    shards: int,
) -> tuple[int, int, int]:
    """(q_tile, q_pad, route_cap) for a sharded batch: q_tile shrinks
    until BOTH the per-tile rerank working set (q_tile·nprobe·cap·d) and
    the exchange buffer (shards·route_cap·cap·d) fit
    ``cfg.max_tile_elems`` — the same hard per-step bound the dense and
    single-device IVF paths enforce, applied to this path's dominant
    intermediates. q_pad is a multiple of shards·q_tile so every shard
    holds the same number of whole tiles (the SPMD program needs equal
    trip counts)."""
    per_row = max(1, nprobe * bucket_cap * dim)
    q_tile = min(cfg.query_tile, pad_to_multiple(max(1, -(-nq // shards)), 8))

    def biggest(qt: int) -> int:
        rc = resolve_route_cap(cfg, qt, nprobe)
        return max(qt * per_row, exchange_elems(shards, rc, bucket_cap, dim))

    while q_tile > 1 and biggest(q_tile) > cfg.max_tile_elems:
        q_tile = max(1, q_tile // 2)
    if biggest(q_tile) > cfg.max_tile_elems:
        raise ValueError(
            f"one sharded query tile's working set ({biggest(q_tile)} "
            f"elems: nprobe={nprobe} × bucket_cap={bucket_cap} × d={dim} "
            f"per row, exchanged over {shards} shards) exceeds "
            f"max_tile_elems={cfg.max_tile_elems}; lower nprobe/"
            "partitions, set a smaller ivf_route_cap, raise "
            "max_tile_elems, or serve unsharded"
        )
    q_pad = pad_to_multiple(nq, shards * q_tile)
    return q_tile, q_pad, resolve_route_cap(cfg, q_tile, nprobe)


def routed_query_tile(
    q_x: jax.Array,  # (q_tile, d) resident query tile (home shard)
    q_ids: jax.Array,  # (q_tile,)
    centroids: jax.Array,  # (P, d) replicated routing table
    centroid_sqs: jax.Array,  # (P,)
    buckets: jax.Array,  # (per_shard, cap, d) THIS shard's slice —
    # (per_shard, cap, pd) int8 code lanes for a quantized store
    bucket_ids: jax.Array,  # (per_shard, cap)
    bucket_sqs: jax.Array,  # (per_shard, cap)
    bucket_scales: jax.Array | None,  # (per_shard, cap) f32, quantized only
    cfg: KNNConfig,
    nprobe: int,
    axis: str,
    shards: int,
    route_cap: int,
):
    """One resident query tile through the routed two-stage search →
    ((q_tile, k) dists, ids, (N_STATS,) int32 stats). Runs inside
    shard_map: every shard executes this body over its own tile while
    serving its peers' bucket requests through the same four static
    all-to-alls."""
    acc = jnp.float32
    q_x = q_x.astype(acc)
    q_sq, probe = score_centroids(q_x, centroids, centroid_sqs, nprobe)

    per_shard, cap = buckets.shape[0], buckets.shape[1]
    qt = q_x.shape[0]
    n = qt * nprobe
    # routes are prioritized PROBE-RANK-major (every query's rank-0 probe
    # outranks any query's rank-1 probe at the same owner): under cap
    # pressure the cap is spent on the highest-value probes across the
    # whole tile, and a query can lose ALL its probes only when an
    # owner's rank-0 demand alone exceeds the cap — not merely because
    # an earlier query spent the budget on its low-value probes
    flat_t = probe.T.reshape(n)  # route t = j·qt + q (probe-rank major)
    dest_t = flat_t // per_shard  # owning shard of each route
    slot_t = (flat_t % per_shard).astype(jnp.int32)
    # rank of each route within its destination group, in priority order
    # (cumsum over one-hot — deterministic, stable, n·S ops); ranks
    # beyond route_cap are dropped (and counted), never mis-sent
    onehot = (
        dest_t[:, None] == jnp.arange(shards, dtype=dest_t.dtype)
    ).astype(jnp.int32)
    rank_t = (jnp.cumsum(onehot, axis=0) - 1)[jnp.arange(n), dest_t]
    dropped_t = rank_t >= route_cap

    # request exchange: row s of the (S, route_cap) table is this home
    # shard's request list for owner s; after the all-to-all, row s is
    # the request list FROM home shard s against our resident slice
    req = jnp.full((shards, route_cap), -1, jnp.int32)
    req = req.at[dest_t, jnp.where(dropped_t, route_cap, rank_t)].set(
        slot_t, mode="drop"
    )
    req_in = jax.lax.all_to_all(req, axis, 0, 0, tiled=True)

    # owner side: gather the requested buckets from the resident slice
    # (empty slots gather slot 0 but their ids are masked to −1, which
    # the shared mask_tile semantics turn into +inf candidates)
    take = jnp.clip(req_in, 0, per_shard - 1)
    rows_out = buckets[take]  # (S, route_cap, cap, d|pd) at-rest dtype
    ids_out = jnp.where((req_in < 0)[..., None], -1, bucket_ids[take])
    sqs_out = bucket_sqs[take]

    # candidate exchange: after these, row s holds owner s's answers to
    # OUR requests — rows travel at the at-rest dtype (bf16 store = half
    # the exchange bytes; a quantized store ships its int8 code lanes at
    # a 4–8× cut, with the per-row scale table riding a fifth, d×-smaller
    # all-to-all — ids and norms are unchanged)
    rows_home = jax.lax.all_to_all(rows_out, axis, 0, 0, tiled=True)
    ids_home = jax.lax.all_to_all(ids_out, axis, 0, 0, tiled=True)
    sqs_home = jax.lax.all_to_all(sqs_out, axis, 0, 0, tiled=True)
    scl_home = None
    if bucket_scales is not None:
        scl_home = jax.lax.all_to_all(
            bucket_scales[take], axis, 0, 0, tiled=True
        )

    # scatter back to per-query candidate tiles in QUERY-major flat probe
    # order — the exact (q_tile, nprobe·cap) layout the single-device
    # gather produces, so the shared finish is bit-compatible; dropped
    # routes point at a clamped slot with ids forced to −1. t_of maps the
    # query-major flat index f = q·nprobe + j back to its priority-order
    # position t = j·qt + q.
    t_of = (jnp.arange(n) % nprobe) * qt + jnp.arange(n) // nprobe
    dest, rank, dropped = dest_t[t_of], rank_t[t_of], dropped_t[t_of]
    src = dest * route_cap + jnp.minimum(rank, route_cap - 1)
    rows = rows_home.reshape(shards * route_cap, cap, -1)[src]
    ids = jnp.where(
        dropped[:, None], -1, ids_home.reshape(shards * route_cap, cap)[src]
    )
    sqs = sqs_home.reshape(shards * route_cap, cap)[src]
    v = nprobe * cap
    rows = rows.reshape(qt, v, rows.shape[-1])
    if scl_home is not None:
        # dequantize AT HOME, after the scatter: the exchange moved only
        # code lanes; the f32 candidate rows exist for exactly one tile's
        # finish (the same asymmetric-distance shape as the single-device
        # quantized gather, so the shared finish stays bit-compatible)
        scl = scl_home.reshape(shards * route_cap, cap)[src].reshape(qt, v)
        rows = dequantize_rows(rows, scl, cfg.dtype, q_x.shape[1])
    rows = rows.astype(acc)
    d_out, i_out = finish_candidates(
        q_x, q_ids, q_sq, rows, ids.reshape(qt, v), sqs.reshape(qt, v), cfg
    )
    stats = jnp.stack([
        jnp.sum(~dropped).astype(jnp.int32),
        jnp.sum(dropped).astype(jnp.int32),
        jnp.sum(req_in >= 0).astype(jnp.int32),
    ])
    return d_out, i_out, stats


def ivf_sharded_serve_chunk(
    q_tiles: jax.Array,  # (QT, q_tile, d) one padded batch, q-sharded
    qid_tiles: jax.Array,  # (QT, q_tile)
    carry_d: jax.Array,  # (QT, q_tile, k) donated scratch
    carry_i: jax.Array,
    stats_scratch: jax.Array,  # (N_STATS·S,) donated zeros
    centroids: jax.Array,  # (P, d) replicated
    centroid_sqs: jax.Array,
    buckets: jax.Array,  # (S·per_shard, cap, d|pd) sharded over axis
    bucket_ids: jax.Array,
    bucket_sqs: jax.Array,
    bucket_scales: jax.Array | None,  # sharded like buckets, quantized only
    cfg: KNNConfig,
    nprobe: int,
    mesh: Mesh,
    axis: str,
    shards: int,
    route_cap: int,
):
    """One serving batch against a resident :class:`ShardedIVFIndex` —
    the engine's uniform (queries, query_ids, carry_d, carry_i, <scratch>,
    <resident…>) convention with the stats vector as a THIRD donated
    scratch (``donate_argnums=(2, 3, 4)``): every output aliases a
    donated input, so R5's contract holds with the stats riding along."""
    qspec = P(axis)

    def per_shard_search(qt, qidt, cd, ci, st, cent, cent_sq, bks, bids,
                         bsqs, bscls):
        def per_tile(args):
            q_x, q_ids, cd0, ci0 = args
            d, i, ts = routed_query_tile(
                q_x, q_ids, cent, cent_sq, bks, bids, bsqs, bscls,
                cfg, nprobe, axis, shards, route_cap,
            )
            d2, i2 = merge_topk(
                cd0, ci0, d.astype(cd0.dtype), i, method="exact"
            )
            return d2, i2, ts

        d, i, ts = jax.lax.map(per_tile, (qt, qidt, cd, ci))
        # dtype pinned: under x64 an un-annotated integer sum promotes to
        # int64, and a widened stats output could not alias its donated
        # int32 scratch (R5 would rightly flag the dropped donation)
        return d, i, st + jnp.sum(ts, axis=0, dtype=jnp.int32)

    if bucket_scales is None:

        def shard_body(qt, qidt, cd, ci, st, cent, cent_sq, bks, bids,
                       bsqs):
            return per_shard_search(
                qt, qidt, cd, ci, st, cent, cent_sq, bks, bids, bsqs, None
            )

        fn = shard_map(
            shard_body,
            mesh=mesh,
            in_specs=(qspec, qspec, qspec, qspec, qspec, P(), P(),
                      qspec, qspec, qspec),
            out_specs=(qspec, qspec, qspec),
        )
        return fn(
            q_tiles, qid_tiles, carry_d, carry_i, stats_scratch,
            centroids, centroid_sqs, buckets, bucket_ids, bucket_sqs,
        )

    fn = shard_map(
        per_shard_search,
        mesh=mesh,
        in_specs=(qspec, qspec, qspec, qspec, qspec, P(), P(),
                  qspec, qspec, qspec, qspec),
        out_specs=(qspec, qspec, qspec),
    )
    return fn(
        q_tiles, qid_tiles, carry_d, carry_i, stats_scratch,
        centroids, centroid_sqs, buckets, bucket_ids, bucket_sqs,
        bucket_scales,
    )


_ivf_sharded_jit = jax.jit(
    ivf_sharded_serve_chunk,
    static_argnames=("cfg", "nprobe", "mesh", "axis", "shards", "route_cap"),
)


# ---------------------------------------------------------------------------
# The resident sharded index


@dataclasses.dataclass
class ShardedIVFIndex:
    """Mesh-resident sharded clustered index. Duck-types the engine corner
    of :class:`~mpi_knn_tpu.ivf.index.IVFIndex` (``backend``/``cfg``/
    ``mu``/``m``/``dim``/``_cache``/``compatible_cfg``/
    ``nbytes_resident``) so the bucketed AOT executable cache,
    ``ServeSession`` and ``api.query_knn`` serve it unchanged."""

    cfg: KNNConfig  # resolved: backend="serial", concrete nprobe + shards
    m: int
    dim: int
    partitions: int
    bucket_cap: int
    nprobe: int
    mu: object | None
    shards: int
    per_shard: int  # clusters per shard (incl. derived padding clusters)
    mesh: Mesh
    axis: str
    centroids: jax.Array  # (P, d) replicated on every shard
    centroid_sqs: jax.Array  # (P,) replicated
    buckets: jax.Array  # (S·per_shard, cap, d|pd) sharded over the ring axis
    bucket_ids: jax.Array  # (S·per_shard, cap) sharded
    bucket_sqs: jax.Array  # (S·per_shard, cap) sharded
    bucket_scales: jax.Array | None = None  # sharded; quantized stores only
    tuned_recall: float | None = None
    backend: str = "ivf-sharded"
    _cache: dict = dataclasses.field(default_factory=dict)

    @property
    def store_dtype(self) -> str:
        """The at-rest level of the bucket store (cfg.dtype by the build
        contract)."""
        return self.cfg.dtype

    @property
    def nbytes_resident(self) -> int:
        """Bytes of resident corpus payload across ALL shards (the global
        bucket store incl. derived padding clusters, plus a quantized
        store's scale table)."""
        n = self.buckets.size * self.buckets.dtype.itemsize
        if self.bucket_scales is not None:
            n += self.bucket_scales.size * self.bucket_scales.dtype.itemsize
        return n

    @property
    def shard_nbytes_resident(self) -> int:
        """Bytes of ONE shard's resident bucket slice — the denominator of
        the per-shard probed-bytes claim."""
        return self.nbytes_resident // self.shards

    @property
    def probe_bytes(self) -> int:
        """Bytes one query row's routed probe touches at the index-default
        nprobe — identical to the single-device bound (the routing moves
        the same nprobe buckets, just across the mesh), priced at the
        at-rest wire width."""
        return self.nprobe * self.bucket_cap * row_wire_bytes(
            self.dim,
            self.store_dtype if self.store_dtype in QUANT_DTYPES else None,
            self.buckets.dtype.itemsize,
        )

    def compatible_cfg(self, cfg: KNNConfig) -> KNNConfig:
        """Validate a per-query config against the sharded layout: the
        single-device corpus-side freeze plus ``ivf_shards`` (the layout
        is derived from it — serving a 4-shard index with a 2-shard
        config would route to devices that do not hold the clusters).
        ``ivf_route_cap`` is query-side: it shapes the exchange program
        only, and the executable cache keys on the full config."""
        frozen = (
            "backend", "metric", "dtype", "partitions", "kmeans_iters",
            "kmeans_init", "ivf_seed", "center", "exclude_zero", "zero_eps",
            "ivf_shards",
        )
        want = cfg if cfg.backend != "auto" else cfg.replace(backend="serial")
        bad = [
            f for f in frozen
            if getattr(want, f) != getattr(self.cfg, f)
        ]
        if bad:
            raise ValueError(
                "query config changes corpus-side knobs baked into this "
                f"sharded clustered index: {bad}; build (or re-shard) a "
                "new index, or override only query-side knobs: k/nprobe/"
                "precision_policy/ivf_route_cap/query_tile/query_bucket/"
                "dispatch_depth/donate"
            )
        _refuse_inert_knobs(want)
        if want.nprobe is None:
            want = want.replace(nprobe=self.nprobe)
        return want


def shard_ivf_index(
    index: IVFIndex,
    shards: int | None = None,
    mesh: Mesh | None = None,
    route_cap: int | None = None,
) -> ShardedIVFIndex:
    """Distribute a trained single-device :class:`IVFIndex` over the ring
    mesh. The shard layout is DERIVED here from (partitions, shards) —
    nothing about it is stored in the index, so one ``save_ivf_index``
    artifact serves on any shard count (bit-compatibly: the per-query
    candidate tiles and every dot shape are shard-count-independent).

    Args:
      index: a trained (or loaded) single-device clustered index.
      shards: ring size; default ``index.cfg.ivf_shards`` or the mesh
        size or all visible devices.
      mesh: optional 1-D mesh to place on (defaults to the first
        ``shards`` visible devices).
      route_cap: optional ``KNNConfig.ivf_route_cap`` override recorded
        on the index's default config.
    """
    if shards is None:
        shards = (
            index.cfg.ivf_shards
            if index.cfg.ivf_shards is not None
            else (mesh.devices.size if mesh is not None
                  else len(jax.devices()))
        )
    if shards < 1:
        raise ValueError(f"ivf_shards must be >= 1, got {shards}")
    if mesh is None:
        mesh = make_ring_mesh(shards, axis_name=index.cfg.mesh_axis)
    if len(mesh.axis_names) != 1:
        raise ValueError(
            f"the sharded clustered index wants a 1-D ring mesh, got axes "
            f"{mesh.axis_names} (the candidate exchange is one all-to-all "
            "group over the ring axis)"
        )
    if mesh.devices.size != shards:
        raise ValueError(
            f"mesh has {mesh.devices.size} device(s) but ivf_shards="
            f"{shards}; build the mesh over exactly the shard count"
        )
    axis = mesh.axis_names[0]
    P_real = index.partitions
    per_shard = -(-P_real // shards)
    P_pad = per_shard * shards

    # host-staged slice + pad of the cluster axis, then ONE device_put
    # per array onto its layout — the plain index's device arrays are not
    # kept alive (callers may drop the unsharded copy)
    quantized = index.cfg.dtype in QUANT_DTYPES
    buckets = np.asarray(index.buckets)
    bids = np.asarray(index.bucket_ids)
    bsqs = np.asarray(index.bucket_sqs)
    bscl = (
        np.asarray(index.bucket_scales)
        if index.bucket_scales is not None else None
    )
    if P_pad > P_real:
        padc = P_pad - P_real
        buckets = np.concatenate(
            [buckets, np.zeros((padc,) + buckets.shape[1:], buckets.dtype)]
        )
        bids = np.concatenate(
            [bids, np.full((padc,) + bids.shape[1:], -1, bids.dtype)]
        )
        bsqs = np.concatenate(
            [bsqs, np.zeros((padc,) + bsqs.shape[1:], bsqs.dtype)]
        )
        if bscl is not None:
            bscl = np.concatenate(
                [bscl, np.zeros((padc,) + bscl.shape[1:], bscl.dtype)]
            )
    csh = NamedSharding(mesh, P(axis))
    rsh = NamedSharding(mesh, P())  # replicated
    cfg = index.cfg.replace(
        ivf_shards=shards,
        ivf_route_cap=(route_cap if route_cap is not None
                       else index.cfg.ivf_route_cap),
    )
    if quantized:
        # the codes are ALREADY the at-rest representation — a cast here
        # would corrupt them; they shard verbatim alongside their scales
        buckets_dev = jax.device_put(jnp.asarray(buckets), csh)
    else:
        buckets_dev = jax.device_put(
            jnp.asarray(buckets).astype(jnp.dtype(index.cfg.dtype)), csh
        )
    return ShardedIVFIndex(
        cfg=cfg,
        m=index.m,
        dim=index.dim,
        partitions=P_real,
        bucket_cap=index.bucket_cap,
        nprobe=index.nprobe,
        mu=index.mu,
        shards=shards,
        per_shard=per_shard,
        mesh=mesh,
        axis=axis,
        centroids=jax.device_put(np.asarray(index.centroids), rsh),
        centroid_sqs=jax.device_put(np.asarray(index.centroid_sqs), rsh),
        buckets=buckets_dev,
        bucket_ids=jax.device_put(bids, csh),
        bucket_sqs=jax.device_put(bsqs, csh),
        bucket_scales=(
            jax.device_put(bscl, csh) if bscl is not None else None
        ),
        tuned_recall=index.tuned_recall,
    )


def unshard_ivf_index(index: ShardedIVFIndex) -> IVFIndex:
    """The plain single-device view of a sharded index (host gather, strip
    the derived padding clusters) — what ``save_ivf_index`` persists, so
    a sharded build round-trips through the SAME .npz as an unsharded one
    and reloads on any shard count."""
    Pn = index.partitions
    return IVFIndex(
        cfg=index.cfg.replace(ivf_shards=None, ivf_route_cap=None),
        m=index.m,
        dim=index.dim,
        partitions=Pn,
        bucket_cap=index.bucket_cap,
        nprobe=index.nprobe,
        mu=index.mu,
        centroids=jnp.asarray(np.asarray(index.centroids)),
        centroid_sqs=jnp.asarray(np.asarray(index.centroid_sqs)),
        buckets=jnp.asarray(np.asarray(index.buckets)[:Pn]),
        bucket_ids=jnp.asarray(np.asarray(index.bucket_ids)[:Pn]),
        bucket_sqs=jnp.asarray(np.asarray(index.bucket_sqs)[:Pn]),
        bucket_scales=(
            jnp.asarray(np.asarray(index.bucket_scales)[:Pn])
            if index.bucket_scales is not None else None
        ),
        tuned_recall=index.tuned_recall,
    )


def build_sharded_ivf_index(
    corpus,
    config: KNNConfig | None = None,
    mesh: Mesh | None = None,
    **overrides,
) -> ShardedIVFIndex:
    """Train the k-means partitioner (single-device math — clustering is
    layout-independent) and distribute the result over the ring mesh.
    ``cfg.ivf_shards`` must be set; ``nprobe=None`` auto-tunes on the
    single-device index before sharding (recall is layout-independent at
    the safe route cap, so the tuned number transfers)."""
    from mpi_knn_tpu.ivf.index import build_ivf_index

    cfg = (config or KNNConfig()).replace(**overrides)
    if cfg.ivf_shards is None:
        raise ValueError(
            "building a sharded clustered index requires ivf_shards "
            "(KNNConfig.ivf_shards); for a single-device index use "
            "build_ivf_index"
        )
    plain = build_ivf_index(
        corpus, cfg.replace(ivf_shards=None, ivf_route_cap=None)
    )
    return shard_ivf_index(
        plain, shards=cfg.ivf_shards, mesh=mesh,
        route_cap=cfg.ivf_route_cap,
    )


# ---------------------------------------------------------------------------
# One-shot search (prepare/run split for the bench's timer placement)


def prepare_sharded_tiles(index: ShardedIVFIndex, queries, query_ids,
                          cfg: KNNConfig, assume_centered: bool = False):
    """Host-side half of :func:`search_ivf_sharded`: center with the
    index's stored mean, pad to shards·q_tile and tile, place the tiles
    on the query sharding. Returns (q_tiles, qid_tiles, q_pad, q_tile,
    route_cap)."""
    queries = np.asarray(queries)
    nq = queries.shape[0]
    if query_ids is None:
        q_ids = np.full(nq, -1, dtype=np.int32)
    else:
        q_ids = np.asarray(query_ids, dtype=np.int32)
    if cfg.center and index.mu is not None and not assume_centered:
        queries = queries - index.mu
    q_tile, q_pad, route_cap = sharded_query_shapes(
        cfg, cfg.nprobe, index.bucket_cap, index.dim, nq, index.shards
    )
    qt = q_pad // q_tile
    qsh = NamedSharding(index.mesh, P(index.axis))
    q_tiles = jax.device_put(
        np.pad(queries.astype(np.float32), ((0, q_pad - nq), (0, 0)))
        .reshape(qt, q_tile, index.dim),
        qsh,
    )
    qid_tiles = jax.device_put(
        np.pad(q_ids, (0, q_pad - nq), constant_values=-1)
        .reshape(qt, q_tile),
        qsh,
    )
    return q_tiles, qid_tiles, q_pad, q_tile, route_cap


@functools.lru_cache(maxsize=None)
def scratch_maker(qt: int, q_tile: int, k: int, shards: int, mesh: Mesh,
                  axis: str):
    """A once-compiled maker of the (carry_d, carry_i, stats) donated
    scratch, born directly under the query sharding (the ring-serve
    trick: building on the default device and resharding would pay an
    allocate-then-copy on every batch) — cached so repeated one-shot
    calls and the serving engine share one executable per shape."""
    qsh = NamedSharding(mesh, P(axis))
    return jax.jit(
        functools.partial(_sharded_scratch, qt, q_tile, k, shards),
        out_shardings=(qsh, qsh, qsh),
    )


def run_sharded_tiles(index: ShardedIVFIndex, q_tiles, qid_tiles,
                      cfg: KNNConfig, route_cap: int):
    """Device half: fresh sharded carries + the jitted routed search.
    Returns padded ((QT, q_tile, k) dists, ids, (N_STATS·S,) stats)
    device arrays (not synchronized)."""
    qt, q_tile = q_tiles.shape[0], q_tiles.shape[1]
    carry_d, carry_i, stats = scratch_maker(
        qt, q_tile, cfg.k, index.shards, index.mesh, index.axis
    )()
    return _ivf_sharded_jit(
        q_tiles, qid_tiles, carry_d, carry_i, stats,
        index.centroids, index.centroid_sqs, index.buckets,
        index.bucket_ids, index.bucket_sqs, index.bucket_scales,
        cfg, cfg.nprobe, index.mesh, index.axis, index.shards, route_cap,
    )


def _sharded_scratch(qt: int, q_tile: int, k: int, shards: int):
    carry_d, carry_i = init_topk_tiles(qt, q_tile, k, dtype=jnp.float32)
    return carry_d, carry_i, jnp.zeros(N_STATS * shards, jnp.int32)


def search_ivf_sharded(index: ShardedIVFIndex, queries, query_ids=None,
                       config=None, assume_centered=False, **overrides):
    """One-shot query batch against a :class:`ShardedIVFIndex` (no
    executable cache — the serving engine owns that). Returns
    ((q, k) dists ascending, (q, k) ids, per-shard stats (S, N_STATS))
    as numpy arrays."""
    cfg = index.compatible_cfg((config or index.cfg).replace(**overrides))
    nq = np.shape(queries)[0]
    q_tiles, qid_tiles, q_pad, _, route_cap = prepare_sharded_tiles(
        index, queries, query_ids, cfg, assume_centered=assume_centered
    )
    d, i, stats = run_sharded_tiles(index, q_tiles, qid_tiles, cfg, route_cap)
    return (
        np.asarray(d.reshape(q_pad, cfg.k)[:nq]),
        np.asarray(i.reshape(q_pad, cfg.k)[:nq]),
        np.asarray(stats).reshape(index.shards, N_STATS),
    )
