"""Clustered (IVF) index: TPU-native k-means partitioner + recall-targeted
two-stage search — the repo's first SUBLINEAR per-query path (TPU-KNN,
arXiv 2206.14286: score centroids, scan only the nprobe nearest
partitions, exact rerank; probed bytes per query are nprobe/partitions of
the corpus).

Public surface::

    from mpi_knn_tpu.ivf import build_ivf_index, search_ivf
    from mpi_knn_tpu import KNNConfig, query_knn

    idx = build_ivf_index(X, KNNConfig(k=10, partitions=64))  # nprobe auto-tuned
    d, i = search_ivf(idx, Q)                  # one-shot
    res = query_knn(Q, idx)                    # serving engine (bucket cache)

    save_ivf_index(idx, "corpus.ivf.npz")
    idx = load_ivf_index("corpus.ivf.npz")

    # sharded over the ring mesh (TPU-KNN's deployment shape): capacity
    # scales with devices, per-query work stays sublinear, the candidate
    # exchange is a static all-to-all (DESIGN.md ladder rung 5)
    sidx = build_ivf_index(X, KNNConfig(k=10, partitions=64, ivf_shards=4))
    sidx = shard_ivf_index(load_ivf_index("corpus.ivf.npz"), shards=2)
    d, i, stats = search_ivf_sharded(sidx, Q)

Design rationale: DESIGN.md "The ladder" rungs 4–5; the machine-checked
probed-bytes (per shard, in the sharded case), probe-gather and
exchange-accounting contracts are lint rules R2/R4/R6
(``mpi_knn_tpu/analysis/README.md``).
"""

from mpi_knn_tpu.ivf.index import (
    IVFIndex,
    build_ivf_index,
    load_ivf_index,
    save_ivf_index,
    tune_nprobe,
)
from mpi_knn_tpu.ivf.kmeans import KMeansResult, kmeans
from mpi_knn_tpu.ivf.search import ivf_query_tile, search_ivf
from mpi_knn_tpu.ivf.sharded import (
    ShardedIVFIndex,
    build_sharded_ivf_index,
    search_ivf_sharded,
    shard_ivf_index,
    unshard_ivf_index,
)

__all__ = [
    "IVFIndex",
    "KMeansResult",
    "ShardedIVFIndex",
    "build_ivf_index",
    "build_sharded_ivf_index",
    "ivf_query_tile",
    "kmeans",
    "load_ivf_index",
    "save_ivf_index",
    "search_ivf",
    "search_ivf_sharded",
    "shard_ivf_index",
    "tune_nprobe",
    "unshard_ivf_index",
]
