"""Clustered (IVF) corpus index: k-means partitions, padded per-cluster
buckets, recall-targeted probe count.

Layout (all device-resident after build):

- ``centroids (P, d)`` f32 + their squared norms — the routing table;
- ``buckets (P, bucket_cap, d)`` — every partition's rows, padded to one
  static ``bucket_cap`` (max cluster size, lane-aligned) so the probe
  gather is shape-static; padding slots carry id −1 and the standard
  ``mask_tile`` semantics make them +inf candidates, never answers;
- ``bucket_ids (P, bucket_cap)`` int32 global ids;
- ``bucket_sqs (P, bucket_cap)`` squared norms, computed UNDER JIT from
  the at-rest buckets (the serve-index precedent: eager reductions
  produce different bits than traced ones, and the degenerate
  nprobe == partitions scan is parity-tested against the serial backend).

``dtype="bfloat16"`` stores buckets compressed at rest (half the HBM and
half the probe-gather bytes; candidates upcast to f32 after the gather) —
the same measured-recall contract as the compressed serve index.
``dtype="int8"``/``"int4"`` go further down the ladder (ops/quant.py):
buckets reside as block-scaled codes (int4 nibble-packed into int8
lanes) plus a per-row f32 scale table — 4–8× less HBM and probe-gather
traffic than f32 — and the search dequantizes candidates right after the
probe gather into an asymmetric distance (exact f32 queries vs
dequantized candidates; ``bucket_sqs`` holds the DEQUANTIZED store's
norms, so distances are exact w.r.t. the stored values). The recall each
level pays is measured, never assumed: the bench compression axis and
DESIGN.md's ladder table carry the numbers, and the int4 gate's bar is
the honestly measured one.

``nprobe`` auto-tuning: when the build config leaves ``nprobe=None``, a
held-out corpus sample is searched at doubling nprobe values and compared
against the brute-force oracle (``nprobe == partitions`` — the exact full
scan through the same program, so the measured number is pure partition-
pruning loss, no cross-program fp noise); the smallest nprobe reaching
``cfg.recall_target`` becomes the index default.

``save``/``load`` round-trip the whole index through one ``.npz``
bit-identically (bf16 buckets travel as uint16 views — numpy has no
native bfloat16).
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from mpi_knn_tpu.config import KNNConfig
from mpi_knn_tpu.ivf.kmeans import kmeans
from mpi_knn_tpu.ivf.search import search_ivf
from mpi_knn_tpu.ops.distance import sq_norms
from mpi_knn_tpu.ops.quant import (
    QUANT_DTYPES,
    dequantize_rows,
    quantize_rows,
    row_wire_bytes,
)
from mpi_knn_tpu.parallel.partition import pad_to_multiple

# held-out sample size for recall-targeted nprobe tuning (the CLI/bench
# recall-gate convention: enough rows for a stable estimate, cheap enough
# to run at build time)
TUNE_SAMPLE = 256
# at-rest bucket-store dtypes: the float pair stores rows verbatim (bf16
# halves bytes); int8/int4 are the block-scaled quantized levels — codes
# (int4 nibble-packed into int8 lanes) + a per-row f32 scale table, 4–8×
# less resident HBM, dequantized after the probe gather into an
# asymmetric distance (exact f32 queries vs dequantized candidates).
# int8 costs ~1 recall@10 point on the SIFT-shaped gate; int4 is the
# capacity rung with an explicitly measured (larger) cost — the bench's
# compression axis and DESIGN.md's ladder table carry the numbers.
IVF_DTYPES = ("float32", "bfloat16") + QUANT_DTYPES


@dataclasses.dataclass
class IVFIndex:
    """Resident clustered-index state for one (corpus, config) pair.

    Duck-types the corner of ``serve.CorpusIndex`` the serving engine
    touches (``backend``/``cfg``/``mu``/``m``/``dim``/``_cache``/
    ``compatible_cfg``/``nbytes_resident``), so the bucketed AOT
    executable cache, ``ServeSession`` and ``api.query_knn`` serve it
    unchanged.
    """

    cfg: KNNConfig  # resolved: backend="serial", concrete nprobe
    m: int
    dim: int
    partitions: int
    bucket_cap: int
    nprobe: int  # index default (tuned or configured)
    mu: object | None  # centering mean (host f64), or None
    centroids: jax.Array  # (P, d) f32
    centroid_sqs: jax.Array  # (P,)
    buckets: jax.Array  # (P, cap, d) at-rest dtype — (P, cap, pd) int8
    # code lanes when the store is quantized (pd = packed_dim)
    bucket_ids: jax.Array  # (P, cap) int32
    bucket_sqs: jax.Array  # (P, cap) f32 (norms of the DEQUANTIZED store
    # when quantized — distances are exact w.r.t. the stored values)
    bucket_scales: jax.Array | None = None  # (P, cap) f32, quantized only
    tuned_recall: float | None = None  # measured recall@k at `nprobe`
    backend: str = "ivf"
    # per-index executable cache: {(bucket, cfg) -> engine._BucketExec}
    _cache: dict = dataclasses.field(default_factory=dict)

    @property
    def store_dtype(self) -> str:
        """The at-rest level of the bucket store (cfg.dtype by the build
        contract)."""
        return self.cfg.dtype

    @property
    def live_rows(self) -> int:
        """Rows currently live (non-tombstoned). ``m`` stays the
        BUILD-time row count — it is executable-fingerprint material;
        the dynamic truth lives on the mutation freelist."""
        from mpi_knn_tpu.ivf.mutate import freelist_of

        return freelist_of(self).live

    @property
    def nbytes_resident(self) -> int:
        """Bytes of resident corpus payload (the bucket store: code/row
        array plus the scale table of a quantized store)."""
        n = self.buckets.size * self.buckets.dtype.itemsize
        if self.bucket_scales is not None:
            n += self.bucket_scales.size * self.bucket_scales.dtype.itemsize
        return n

    @property
    def probe_bytes(self) -> int:
        """Bytes one query row's probe gather touches at the index-default
        nprobe — the sublinear bound (≤ nprobe·bucket_bytes, never the
        corpus) that lint rule R2 budgets on the lowered program. Priced
        at the AT-REST width: a quantized store's gather moves code lanes
        plus per-row scales, which is exactly the 4–8× cut."""
        return self.nprobe * self.bucket_cap * row_wire_bytes(
            self.dim,
            self.store_dtype if self.store_dtype in QUANT_DTYPES else None,
            self.buckets.dtype.itemsize,
        )

    def compatible_cfg(self, cfg: KNNConfig) -> KNNConfig:
        """Validate a per-query config against the build-time clustering.

        Query-side knobs (k, nprobe, precision policy, tiling, serving
        pacing, donation) may vary per call — the executable cache keys
        on the full config. Corpus-side knobs (metric, dtype, partitions,
        the k-means training knobs, centering, zero-exclusion) are baked
        into the trained partitions and may NOT vary. A ``nprobe=None``
        query config resolves to the index's tuned default.
        """
        frozen = (
            "backend", "metric", "dtype", "partitions", "kmeans_iters",
            "kmeans_init", "ivf_seed", "center", "exclude_zero", "zero_eps",
            "bucket_headroom",
        )
        want = cfg if cfg.backend != "auto" else cfg.replace(backend="serial")
        bad = [
            f for f in frozen
            if getattr(want, f) != getattr(self.cfg, f)
        ]
        if bad:
            raise ValueError(
                "query config changes corpus-side knobs baked into this "
                f"clustered index: {bad}; build a new index (or override "
                "only query-side knobs: k/nprobe/precision_policy/"
                "query_tile/query_bucket/dispatch_depth/donate)"
            )
        _refuse_inert_knobs(want)
        if want.nprobe is None:
            want = want.replace(nprobe=self.nprobe)
        return want


def _refuse_inert_knobs(cfg: KNNConfig) -> None:
    """Knobs the clustered search cannot honor are refused LOUDLY, never
    silently ignored (the serve-CLI/bench convention): the probed
    candidates always finish with the exact rerank top-k, and the
    centroid-score/rerank dots fix their own precisions — a config (or a
    banked measurement's metadata) claiming otherwise would be lying
    about the program that ran."""
    if cfg.topk_method != "exact":
        raise ValueError(
            f"topk_method={cfg.topk_method!r} cannot be honored by the "
            "clustered (IVF) search: the probed-candidate finish is "
            "always the exact rerank top-k (ops/rerank.rerank_exact_topk)"
            " — unset it, or use a dense backend for approximate "
            "selection"
        )
    if cfg.matmul_precision is not None:
        raise ValueError(
            f"matmul_precision={cfg.matmul_precision!r} cannot be "
            "honored by the clustered (IVF) search: it fixes its own dot "
            "precisions (HIGHEST centroid score + rerank; DEFAULT "
            "compress under precision_policy='mixed')"
        )
    if cfg.merge_schedule != "twolevel":
        raise ValueError(
            f"merge_schedule={cfg.merge_schedule!r} cannot be honored by "
            "the clustered (IVF) search: there is no tile-merge schedule "
            "on the probed path (one gather, one exact finish) — leave "
            "it at the default"
        )


def _corpus_from_serve_index(serve_index):
    """Centered corpus rows + mean back out of a serial-layout
    ``serve.CorpusIndex`` (the tile stack is the corpus, padded — strip
    the sentinel rows)."""
    if serve_index.tiles is None:
        raise ValueError(
            "an IVF index can only be built from a serial-layout "
            "CorpusIndex (tiles resident on one device); the "
            f"{serve_index.backend!r} layout shards or fuses the corpus"
        )
    rows = np.asarray(serve_index.tiles, dtype=np.float32).reshape(
        -1, serve_index.dim
    )[: serve_index.m]
    return rows, serve_index.mu, serve_index.cfg


def build_ivf_index(
    corpus,
    config: Optional[KNNConfig] = None,
    **overrides,
) -> IVFIndex:
    """Train the k-means partitioner and build a device-resident
    :class:`IVFIndex`.

    Args:
      corpus: (m, d) host/device array, or an existing serial-layout
        ``serve.CorpusIndex`` (its centered resident tiles are reused;
        no second centering pass).
      config: build-time :class:`KNNConfig` with ``partitions`` set;
        kwargs override fields. ``nprobe=None`` triggers the
        recall-targeted auto-tune.
    """
    from mpi_knn_tpu.serve.index import CorpusIndex

    cfg = (config or KNNConfig()).replace(**overrides)
    if cfg.ivf_shards is not None:
        # the sharded-clustered axis: train here (single-device math —
        # clustering is layout-independent), then distribute over the
        # ring mesh (ivf/sharded.py derives the layout)
        from mpi_knn_tpu.ivf.sharded import build_sharded_ivf_index

        return build_sharded_ivf_index(corpus, cfg)
    if cfg.partitions is None:
        raise ValueError(
            "building a clustered index requires partitions "
            "(KNNConfig.partitions / --partitions)"
        )
    if cfg.backend not in ("auto", "serial"):
        raise ValueError(
            f"the clustered index is a single-device serial-math path; "
            f"backend={cfg.backend!r} cannot honor it (the pallas kernels "
            "and the ring rotation scan the full corpus by construction) "
            "— use backend='serial' or 'auto'"
        )
    if cfg.dtype not in IVF_DTYPES:
        raise ValueError(
            f"clustered index dtype must be one of {IVF_DTYPES} (float64 "
            f"is the dense backends' debug mode), got {cfg.dtype!r}"
        )
    _refuse_inert_knobs(cfg)
    cfg = cfg.replace(backend="serial")

    mu = None
    if isinstance(corpus, CorpusIndex):
        rows, mu, built_cfg = _corpus_from_serve_index(corpus)
        for f in ("metric", "dtype", "center"):
            if getattr(built_cfg, f) != getattr(cfg, f):
                raise ValueError(
                    f"IVF config {f}={getattr(cfg, f)!r} disagrees with "
                    f"the source CorpusIndex ({getattr(built_cfg, f)!r})"
                )
        X = rows  # already centered at serve-index build time
    else:
        X = np.asarray(
            corpus if not isinstance(corpus, jax.Array)
            else jax.device_get(corpus),
            dtype=np.float32,
        )
        if cfg.center:
            mu = X.astype(np.float64).mean(axis=0)
            X = X - mu
    m, dim = X.shape
    if cfg.partitions > m:
        raise ValueError(
            f"partitions={cfg.partitions} exceeds the corpus rows ({m})"
        )

    res = kmeans(
        X, cfg.partitions, iters=cfg.kmeans_iters, seed=cfg.ivf_seed,
        init=cfg.kmeans_init,
    )
    assign = np.asarray(res.assignments)
    counts = np.asarray(res.counts)
    P = cfg.partitions
    # capacity headroom (ISSUE 14): spare slots per bucket are what buy
    # STATIC-SHAPE upserts — the freelist hands them out and a donated
    # scatter fills them in place, no recompile. The padding slots carry
    # id −1 (mask_tile: +inf candidates, never answers), so headroom
    # costs padded FLOPs/gather bytes, not correctness — set
    # bucket_headroom=0.0 for a frozen corpus.
    need = max(int(counts.max()), 1)
    cap = pad_to_multiple(
        max(1, int(np.ceil(need * (1.0 + cfg.bucket_headroom)))), 8
    )

    buckets_np = np.zeros((P, cap, dim), dtype=np.float32)
    ids_np = np.full((P, cap), -1, dtype=np.int32)
    # vectorized scatter: rows sorted by cluster, each row's slot is its
    # rank within its cluster (searchsorted finds the cluster's start) —
    # a per-row Python loop here would make SIFT-scale builds
    # interpreter-bound
    order = np.argsort(assign, kind="stable")
    sa = assign[order]
    within = np.arange(m) - np.searchsorted(sa, sa)
    buckets_np[sa, within] = X[order]
    ids_np[sa, within] = order

    bucket_scales = None
    if cfg.dtype in QUANT_DTYPES:
        # block-scaled quantized store: per-row codes + scales (padding
        # rows are zero → scale 0, codes 0 — dequantization is exactly
        # zero and the id −1 mask keeps them non-answers anyway); norms
        # computed UNDER JIT from the DEQUANTIZED store so the asymmetric
        # distance is exact w.r.t. the values actually stored
        buckets, bucket_scales = jax.jit(
            functools.partial(quantize_rows, dtype=cfg.dtype)
        )(jnp.asarray(buckets_np))
        bucket_sqs = jax.jit(
            lambda c, s: jax.vmap(sq_norms)(
                dequantize_rows(c, s, cfg.dtype, dim)
            )
        )(buckets, bucket_scales)
    else:
        buckets = jnp.asarray(buckets_np).astype(jnp.dtype(cfg.dtype))
        # norms from the AT-REST buckets, under jit (bit-parity with the
        # serial serve index's norm construction)
        bucket_sqs = jax.jit(jax.vmap(sq_norms))(buckets)
    bucket_ids = jnp.asarray(ids_np)
    centroids = res.centroids
    centroid_sqs = jax.jit(sq_norms)(centroids)

    index = IVFIndex(
        cfg=cfg, m=m, dim=dim, partitions=P, bucket_cap=cap,
        nprobe=cfg.nprobe or P, mu=mu,
        centroids=centroids, centroid_sqs=centroid_sqs,
        buckets=buckets, bucket_ids=bucket_ids, bucket_sqs=bucket_sqs,
        bucket_scales=bucket_scales,
    )
    if cfg.nprobe is None:
        tuned, rec = tune_nprobe(index, cfg.recall_target, k=cfg.k)
        index.nprobe = tuned
        index.tuned_recall = rec
        index.cfg = cfg.replace(nprobe=tuned)
    else:
        index.cfg = cfg
    return index


def tune_nprobe(
    index: IVFIndex, recall_target: float, k: int = 10,
    sample: int = TUNE_SAMPLE,
) -> tuple[int, float]:
    """Smallest nprobe whose measured recall@k on a held-out corpus
    sample reaches ``recall_target`` against the brute-force oracle —
    which is the SAME search program at ``nprobe == partitions`` (an
    exact full scan), so the measurement isolates partition-pruning loss
    from every other fp effect. Returns (nprobe, measured_recall)."""
    from mpi_knn_tpu.utils.report import recall_at_k

    P = index.partitions
    ns = min(sample, index.m)
    rows = np.linspace(0, index.m - 1, num=ns, dtype=np.int64)
    # held-out queries are corpus rows WITH their identities, so
    # self-exclusion matches the all-pairs workload the gate mirrors;
    # they come back out of the bucket store (already centered). Only the
    # sampled rows are gathered ON DEVICE — fetching/decompressing the
    # whole store to host for ≤ TUNE_SAMPLE rows would move hundreds of
    # MB at the corpus scales the index targets.
    flat_ids = np.asarray(index.bucket_ids).reshape(-1)
    pos_of = np.full(index.m, -1, dtype=np.int64)
    valid = flat_ids >= 0
    pos_of[flat_ids[valid]] = np.flatnonzero(valid)
    sel = index.buckets.reshape(-1, index.buckets.shape[-1])[
        jnp.asarray(pos_of[rows])
    ]
    if index.bucket_scales is not None:
        # quantized store: the tuner's held-out queries are the
        # DEQUANTIZED rows — still "corpus rows in the centered frame",
        # and still isolating partition-pruning loss (both the probed
        # search and its nprobe=partitions oracle see the same store)
        sel = dequantize_rows(
            sel,
            index.bucket_scales.reshape(-1)[jnp.asarray(pos_of[rows])],
            index.store_dtype,
            index.dim,
        )
    Q = np.asarray(sel.astype(jnp.float32))
    qids = rows.astype(np.int32)

    base_cfg = index.cfg.replace(nprobe=P, k=k)
    _, want = search_ivf(
        index, Q, query_ids=qids, config=base_cfg, assume_centered=True
    )

    def recall_at(n: int) -> float:
        _, got = search_ivf(
            index, Q, query_ids=qids,
            config=index.cfg.replace(nprobe=n, k=k), assume_centered=True,
        )
        return float(recall_at_k(got, want))

    # doubling walk to bracket the target, then a binary refinement so
    # the result is the SMALLEST passing nprobe (the documented
    # contract), not the smallest passing power of two — a power-of-two
    # answer can probe up to ~2x the bytes the contract promises
    lo, hi, hi_rec = 0, P, 1.0
    n = 1
    while n < P:
        rec = recall_at(n)
        if rec >= recall_target:
            hi, hi_rec = n, rec
            break
        lo = n
        n = min(2 * n, P)
    while hi - lo > 1:
        mid = (lo + hi) // 2
        rec = recall_at(mid)
        if rec >= recall_target:
            hi, hi_rec = mid, rec
        else:
            lo = mid
    return hi, hi_rec


def save_ivf_index(index, path: str) -> str:
    """Write the full index to one ``.npz`` (bit-identical round trip;
    bf16 buckets travel as uint16 views). A :class:`~mpi_knn_tpu.ivf.
    sharded.ShardedIVFIndex` saves through its single-device view — the
    shard layout is DERIVED, never stored, so one artifact reloads and
    serves on any shard count. Returns the path written."""
    if getattr(index, "backend", None) == "ivf-sharded":
        from mpi_knn_tpu.ivf.sharded import unshard_ivf_index

        index = unshard_ivf_index(index)
    if not path.endswith(".npz"):
        path += ".npz"
    buckets = np.asarray(index.buckets)
    bf16 = index.buckets.dtype == jnp.bfloat16
    if bf16:
        buckets = buckets.view(np.uint16)
    meta = {
        "cfg": {
            k: v for k, v in dataclasses.asdict(index.cfg).items()
        },
        "m": index.m,
        "dim": index.dim,
        "partitions": index.partitions,
        "bucket_cap": index.bucket_cap,
        "nprobe": index.nprobe,
        "tuned_recall": index.tuned_recall,
        "buckets_bf16": bf16,
        # the at-rest level by name (int8/int4 stores travel as their
        # int8 code lanes — bit-identical by construction); absent in
        # pre-quantization artifacts, defaulted on load
        "store_dtype": index.cfg.dtype,
        "has_mu": index.mu is not None,
        # live-mutation provenance (informational — the freelist itself
        # is DERIVED from bucket_ids on load, so tombstones and headroom
        # round-trip through the id plane; pre-mutation artifacts simply
        # lack this key and derive full headroom from their padding)
        "live_rows": int((np.asarray(index.bucket_ids) >= 0).sum()),
    }
    # write-to-temp + atomic rename: a re-save over a path another
    # process is serving from (or has mmapped mid-load) must never
    # expose a torn archive — the reader keeps the old inode, the new
    # file replaces it whole (the aotcache entry-write convention)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        np.savez(
            f,
            meta=np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8),
            centroids=np.asarray(index.centroids),
            centroid_sqs=np.asarray(index.centroid_sqs),
            buckets=buckets,
            bucket_ids=np.asarray(index.bucket_ids),
            bucket_sqs=np.asarray(index.bucket_sqs),
            bucket_scales=(np.asarray(index.bucket_scales)
                           if index.bucket_scales is not None
                           else np.zeros(0, np.float32)),
            mu=(np.asarray(index.mu)
                if index.mu is not None else np.zeros(0)),
        )
    os.replace(tmp, path)
    return path


def load_ivf_index(path: str, mmap: bool = True) -> IVFIndex:
    """Reload a :func:`save_ivf_index` ``.npz`` — arrays land back on
    device bit-identically; the executable cache starts empty.

    ``mmap=True`` (the default) maps the archive's uncompressed members
    read-only instead of decompress-copying them into host memory
    (``utils/npz_mmap``): nothing reads until ``jax.device_put`` touches
    the pages, so disk read and H2D transfer fuse into one pass and the
    host never holds a second corpus copy — the cold-start zero-copy
    path (DESIGN.md "Cold start"), pipelining index load under the AOT
    warm pool's compiles. An archive the mapper cannot handle (a
    compressed ``savez_compressed`` file, foreign members) falls back to
    the copying ``np.load`` reader LOUDLY (``RuntimeWarning``), with
    bit-identical results either way."""
    z: dict | None = None
    if mmap:
        from mpi_knn_tpu.utils.npz_mmap import mmap_npz

        try:
            z = mmap_npz(path)
        except ValueError as e:
            import warnings

            warnings.warn(
                f"cannot mmap index {path!r} ({e}); falling back to the "
                "copying np.load reader",
                RuntimeWarning,
                stacklevel=2,
            )
    if z is None:
        with np.load(path) as zf:
            z = {k: zf[k] for k in zf.files}
    meta = json.loads(bytes(np.asarray(z["meta"])).decode())
    cfg = KNNConfig(**meta["cfg"])
    buckets = z["buckets"]
    if meta["buckets_bf16"]:
        import ml_dtypes  # jax dependency; numpy has no native bf16

        buckets = jnp.asarray(buckets.view(ml_dtypes.bfloat16))
    else:
        buckets = jnp.asarray(buckets)
    store = meta.get("store_dtype", cfg.dtype)
    scales = None
    if store in QUANT_DTYPES:
        scales = jnp.asarray(z["bucket_scales"]).reshape(
            meta["partitions"], meta["bucket_cap"]
        )
    return IVFIndex(
        cfg=cfg,
        m=meta["m"],
        dim=meta["dim"],
        partitions=meta["partitions"],
        bucket_cap=meta["bucket_cap"],
        nprobe=meta["nprobe"],
        tuned_recall=meta["tuned_recall"],
        # np.array (a COPY), never np.asarray: on the mmap path asarray
        # would return a view pinning the file mapping for the index's
        # whole lifetime — every other field is copied to device by
        # jnp.asarray, and the zero-copy contract is "the mapping is
        # dropped once load returns"
        mu=np.array(z["mu"]) if meta["has_mu"] else None,
        centroids=jnp.asarray(z["centroids"]),
        centroid_sqs=jnp.asarray(z["centroid_sqs"]),
        buckets=buckets,
        bucket_ids=jnp.asarray(z["bucket_ids"]),
        bucket_sqs=jnp.asarray(z["bucket_sqs"]),
        bucket_scales=scales,
    )
