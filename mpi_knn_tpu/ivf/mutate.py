"""Static-shape live mutation of clustered indices: freelist slot math,
donated in-place upsert/delete scatters, and the re-cluster/compact
rebuild (the ISSUE 14 tentpole's clustered half).

Why headroom buys static shapes: every TPU-KNN program in this repo is
fast *because* its shapes are frozen (one AOT executable per cell, zero
steady-state compiles). A growing corpus would normally force new shapes
— so instead each bucket is built with spare capacity
(``KNNConfig.bucket_headroom``: ``bucket_cap = pad(max_cluster · (1 +
headroom))``), and mutation happens INSIDE the fixed shapes:

- **upsert** — the new row's partition comes from the same exact-HIGHEST
  centroid score the build assignment and the stage-1 routing use; a
  free slot comes from the host-side per-bucket freelist; the device
  program is ONE donated in-place scatter over the resident store
  (rows + ids + norms + scales), so a million-row index absorbs an
  upsert at the cost of the touched bucket rows, never a corpus-sized
  copy (machine-checked: lint R5 reads ``input_output_alias`` and a
  copy census off the compiled program, R2-strict budgets the
  touched-chunk working set);
- **delete** — a tombstone: the slot's id goes to −1, which the standard
  ``mask_tile`` semantics already treat as "never an answer" (the stale
  row data keeps riding the fixed-shape FLOPs, masked). The freelist
  gets the slot back, so a later upsert reclaims it in place;
- **compact** — when headroom runs low or tombstones accumulate
  (``compact_fill_threshold`` / ``compact_tombstone_fraction``), the
  background pass re-clusters: k-means retrained on a deterministic
  sample of the LIVE rows, every slot re-assigned on device
  (``compact_assign``), and the store rebuilt by ONE donated scatter
  from the old resident arrays into fresh ones (``compact_scatter``) —
  row payload never round-trips the host. ``bucket_cap`` is kept
  whenever the live set still fits (so every serve/mutation executable
  stays valid — compaction is invisible to the cache) and grows only
  when it must (the documented recompile path).

Chunk programs pad to ``mutation_bucket · 2^j`` rows (the serve bucket
discipline applied to mutation), with padding rows carrying an
out-of-range partition index: the scatters run in ``mode='drop'`` so
padding is a true no-op, bit-identically.

The freelist is HOST state (a mirror of ``bucket_ids``), deterministic
(lowest free slot first) and derivable from any saved artifact — a
legacy pre-mutation ``.npz`` loads with its full padding reclaimed as
headroom, because "free slot" and "id −1 slot" are the same thing.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from mpi_knn_tpu.config import KNNConfig
from mpi_knn_tpu.ops.distance import pairwise_sq_l2, sq_norms
from mpi_knn_tpu.ops.quant import QUANT_DTYPES, dequantize_rows, quantize_rows


class BucketOverflowError(RuntimeError):
    """An upsert chunk needs more slots than some bucket has free — the
    headroom is exhausted for those partitions. Carries the partitions so
    the caller (``ServeSession.upsert`` / the compactor) can compact and
    retry instead of guessing."""

    def __init__(self, msg: str, partitions=()):
        super().__init__(msg)
        self.partitions = tuple(partitions)


# ---------------------------------------------------------------------------
# Freelist — the host mirror of slot occupancy


class Freelist:
    """Per-bucket free-slot stacks + the id → (partition, slot) map.

    Derived from ``bucket_ids`` (id −1 = free), never stored: any saved
    artifact — including pre-mutation ones — reconstructs it exactly.
    Slot allocation is deterministic (lowest free slot first), so a
    mutation replayed against a reloaded index lands every row in the
    same slot.

    ``tombstones`` counts deleted-not-yet-reused slots (an upsert that
    reclaims a tombstoned slot decrements it); the compaction triggers
    read ``max_fill`` and ``tombstone_fraction`` from here.
    """

    def __init__(self, bucket_ids: np.ndarray, partitions: int):
        ids = np.asarray(bucket_ids)
        self.partitions = int(partitions)  # REAL partitions (a sharded
        # store's derived padding clusters hold no centroids and can
        # never be assigned to — they contribute no capacity)
        # the scatter drop sentinel: one past the STORE's bucket count
        # (a sharded store is padded past `partitions` — an index at the
        # real partition count would land in a padding cluster, so drop
        # must be out of range of the padded store)
        self.total = int(ids.shape[0])
        self.cap = int(ids.shape[1])
        # free stacks in REVERSE slot order so .pop() yields the lowest
        # free slot (deterministic, replayable allocation)
        self.free: list[list[int]] = [
            sorted(np.flatnonzero(ids[p] < 0).tolist(), reverse=True)
            for p in range(self.partitions)
        ]
        self.pos: dict[int, tuple[int, int]] = {}
        for p in range(self.partitions):
            for s in np.flatnonzero(ids[p] >= 0):
                self.pos[int(ids[p, s])] = (p, int(s))
        self.tombstones = 0
        self._tomb_free = [0] * self.partitions

    @property
    def live(self) -> int:
        return len(self.pos)

    @property
    def max_fill(self) -> float:
        """Largest bucket fill fraction (used slots / cap)."""
        if not self.partitions:
            return 0.0
        return max(
            (self.cap - len(f)) / self.cap for f in self.free
        )

    @property
    def tombstone_fraction(self) -> float:
        return self.tombstones / max(1, self.live)

    def stats(self) -> dict:
        used = [self.cap - len(f) for f in self.free]
        return {
            "live": self.live,
            "tombstones": self.tombstones,
            "cap": self.cap,
            "partitions": self.partitions,
            "max_fill": round(self.max_fill, 6),
            "tombstone_fraction": round(self.tombstone_fraction, 6),
            "free_slots": int(sum(len(f) for f in self.free)),
            "max_used": max(used) if used else 0,
        }


def freelist_of(index) -> Freelist:
    """The index's cached freelist, derived on first use from the
    resident id plane (one small host fetch). Cached on the instance
    like ``_cache`` — mutation plans commit into it. Works for both
    mutable layouts: the clustered bucket store (per-partition buckets)
    and the serial tile stack (every tile is a "bucket" of c_tile
    slots)."""
    fl = index.__dict__.get("_freelist")
    if fl is None:
        if getattr(index, "tiles", None) is not None:
            ids = np.asarray(jax.device_get(index.tile_ids))
            fl = Freelist(ids, ids.shape[0])
        else:
            fl = Freelist(
                np.asarray(jax.device_get(index.bucket_ids)),
                index.partitions,
            )
        index.__dict__["_freelist"] = fl
    return fl


def plan_upsert(fl: Freelist, ids: np.ndarray, parts: np.ndarray):
    """Allocate slots for one upsert chunk WITHOUT committing: returns
    ``(part, slot, clear_part, clear_slot, commit)`` where the first four
    are the scatter index vectors and ``commit()`` applies the
    allocation to the freelist once the device scatter has been
    dispatched (plan → dispatch → commit, so a failed dispatch leaves
    the host mirror untouched). An id that is already live is an UPDATE:
    same partition → its own slot is overwritten in place; moved
    partition → the old slot is tombstoned via the clear pair and a
    fresh slot allocated. ``ids`` must be unique within one chunk (the
    orchestration dedupes — duplicate scatter indices would race).
    Raises :class:`BucketOverflowError` (freelist untouched) when any
    target bucket is out of free slots."""
    n = len(ids)
    part = np.empty(n, np.int32)
    slot = np.empty(n, np.int32)
    clear_part = np.full(n, fl.total, np.int32)  # default: drop
    clear_slot = np.zeros(n, np.int32)
    taken: dict[int, int] = {}  # partition -> slots consumed this plan
    moves: list[tuple] = []  # (rid, old_pos|None, new_p, new_s)
    overflow = set()
    for i, (rid, p) in enumerate(zip(ids, parts)):
        rid, p = int(rid), int(p)
        old = fl.pos.get(rid)
        if old is not None and old[0] == p:
            # in-place update: reuse the id's own occupied slot (the
            # row/norm/scale scatter replaces the payload, the id
            # scatter rewrites the same id)
            part[i], slot[i] = p, old[1]
            continue
        if old is not None:
            clear_part[i], clear_slot[i] = old
        depth = taken.get(p, 0)
        stack = fl.free[p]
        if depth >= len(stack):
            overflow.add(p)
            continue
        s = int(stack[-1 - depth])
        taken[p] = depth + 1
        part[i], slot[i] = p, s
        moves.append((rid, old, p, s))
    if overflow:
        raise BucketOverflowError(
            f"bucket headroom exhausted for partition(s) "
            f"{sorted(overflow)} (cap={fl.cap}); compact the index "
            "(re-cluster rebalances and re-derives headroom) and retry",
            partitions=sorted(overflow),
        )

    def commit():
        for rid, old, p, s in moves:
            if old is not None:
                op, os_ = old
                fl.free[op].append(int(os_))
                fl.free[op].sort(reverse=True)
                fl._tomb_free[op] += 1
                fl.tombstones += 1
            fl.free[p].remove(s)
            if fl._tomb_free[p] > 0:
                fl._tomb_free[p] -= 1
                fl.tombstones -= 1
            fl.pos[rid] = (p, s)

    return part, slot, clear_part, clear_slot, commit


def plan_delete(fl: Freelist, ids: np.ndarray):
    """(part, slot, commit, missing): scatter index vectors tombstoning
    every LIVE id in ``ids`` (unknown ids are counted in ``missing`` and
    dropped — deleting an absent id is idempotent, not an error)."""
    n = len(ids)
    part = np.full(n, fl.total, np.int32)  # default: drop
    slot = np.zeros(n, np.int32)
    found = []
    missing = 0
    for i, rid in enumerate(ids):
        old = fl.pos.get(int(rid))
        if old is None:
            missing += 1
            continue
        part[i], slot[i] = old
        found.append(int(rid))

    def commit():
        for rid in found:
            p, s = fl.pos.pop(rid)
            fl.free[p].append(s)
            fl.free[p].sort(reverse=True)
            fl._tomb_free[p] += 1
            fl.tombstones += 1

    return part, slot, commit, missing


# ---------------------------------------------------------------------------
# Device programs (jitted once at module level, store args donated — the
# serving engine's convention, extended to mutation)


def store_rows_and_sqs(rows: jax.Array, cfg: KNNConfig, dim: int):
    """(at-rest rows, scales-or-None, norms) of a chunk of centered f32
    rows — the SAME per-row math the build uses (cast for float stores,
    block-scaled quantize + norms-of-the-dequantized for int8/int4), so
    a mutated slot is indistinguishable from a built one."""
    if cfg.dtype in QUANT_DTYPES:
        codes, scales = quantize_rows(rows, dtype=cfg.dtype)
        sqs = sq_norms(dequantize_rows(codes, scales, cfg.dtype, dim))
        return codes, scales, sqs
    at_rest = rows.astype(jnp.dtype(cfg.dtype))
    if cfg.metric != "l2":
        # cosine tile stacks carry zero norms (the metric kernel
        # normalizes internally) — mirror the build exactly
        return at_rest, None, jnp.zeros(
            rows.shape[:1],
            dtype=jnp.float64 if cfg.dtype == "float64" else jnp.float32,
        )
    return at_rest, None, sq_norms(at_rest)


def ivf_assign_chunk(rows, centroids, centroid_sqs):
    """Nearest partition per centered row — the exact-HIGHEST centroid
    score (the build assignment / stage-1 routing geometry). (B, d) →
    (B,) int32."""
    cd = pairwise_sq_l2(
        rows, centroids, x_sq=sq_norms(rows), y_sq=centroid_sqs,
        precision=jax.lax.Precision.HIGHEST,
    )
    return jnp.argmin(cd, axis=1).astype(jnp.int32)


def ivf_upsert_chunk(
    rows,        # (B, d) f32 centered
    new_ids,     # (B,) int32
    part, slot,  # (B,) int32 target slots (part == P_total -> drop)
    clear_part, clear_slot,  # (B,) int32 old slots of updated ids
    buckets, bucket_ids, bucket_sqs, bucket_scales,  # DONATED store
    cfg: KNNConfig,
):
    """One donated in-place upsert chunk: tombstone any old slots of
    updated ids, then scatter the chunk's at-rest rows + ids + norms
    (+ scales) into their allocated slots. Every output aliases its
    donated input (R5's contract over the mutation programs) and the
    only new payload materialized is the (B, ·) chunk itself (R2-strict's
    touched-bucket budget)."""
    at_rest, scales, sqs = store_rows_and_sqs(rows, cfg, rows.shape[-1])
    bucket_ids = bucket_ids.at[clear_part, clear_slot].set(-1, mode="drop")
    bucket_ids = bucket_ids.at[part, slot].set(new_ids, mode="drop")
    buckets = buckets.at[part, slot].set(at_rest, mode="drop")
    bucket_sqs = bucket_sqs.at[part, slot].set(
        sqs.astype(bucket_sqs.dtype), mode="drop"
    )
    if bucket_scales is not None:
        bucket_scales = bucket_scales.at[part, slot].set(
            scales, mode="drop"
        )
    return buckets, bucket_ids, bucket_sqs, bucket_scales


def ivf_delete_chunk(part, slot, bucket_ids):
    """One donated tombstone chunk: ids at the given slots go to −1
    (``mask_tile`` makes them +inf candidates — never answers). Row data
    stays resident and masked; the freelist reclaims the slots."""
    return bucket_ids.at[part, slot].set(-1, mode="drop")


def ivf_compact_assign(buckets, bucket_scales, centroids, centroid_sqs,
                       cfg: KNNConfig):
    """Partition assignment of EVERY slot in the resident store against
    (possibly retrained) centroids — tiled per bucket so the distance
    intermediate stays (cap, P), never (P·cap, P). Returns (P_total·cap,)
    int32; the host plan masks dead/padding slots via ``bucket_ids``."""
    dim = centroids.shape[1]

    def per_bucket(args):
        b, s = args
        rows = b
        if s is not None:
            rows = dequantize_rows(b, s, cfg.dtype, dim)
        rows = rows.astype(jnp.float32)
        return ivf_assign_chunk(rows, centroids, centroid_sqs)

    if bucket_scales is not None:
        parts = jax.lax.map(per_bucket, (buckets, bucket_scales))
    else:
        parts = jax.lax.map(lambda b: per_bucket((b, None)), buckets)
    return parts.reshape(-1)


def ivf_compact_scatter(
    dst_part, dst_slot,  # (N,) int32 per OLD flat slot; drop for dead rows
    src_buckets, src_ids, src_sqs, src_scales,  # the old resident store
    dst_buckets, dst_ids, dst_sqs, dst_scales,  # DONATED fresh store
):
    """The compact rebuild as ONE donated scatter: every live row moves
    from its old flat slot into its re-clustered (part, slot) without the
    payload ever leaving the device. Outputs alias the donated
    destination arrays; the source store is a read-only input (reshape,
    not copy). Dead and padding slots carry an out-of-range ``dst_part``
    and drop."""
    flat_rows = src_buckets.reshape(-1, src_buckets.shape[-1])
    flat_ids = src_ids.reshape(-1)
    flat_sqs = src_sqs.reshape(-1)
    dst_buckets = dst_buckets.at[dst_part, dst_slot].set(
        flat_rows, mode="drop"
    )
    dst_ids = dst_ids.at[dst_part, dst_slot].set(flat_ids, mode="drop")
    dst_sqs = dst_sqs.at[dst_part, dst_slot].set(flat_sqs, mode="drop")
    if dst_scales is not None:
        dst_scales = dst_scales.at[dst_part, dst_slot].set(
            src_scales.reshape(-1), mode="drop"
        )
    return dst_buckets, dst_ids, dst_sqs, dst_scales


# module-level jits, donation fixed (mutation programs are always
# donated — an un-donated store update would copy the corpus per chunk,
# exactly what the lint counterexamples prove the rules catch)
assign_jit = jax.jit(ivf_assign_chunk)
upsert_jit = jax.jit(
    ivf_upsert_chunk, static_argnames=("cfg",), donate_argnums=(6, 7, 8, 9)
)
delete_jit = jax.jit(ivf_delete_chunk, donate_argnums=(2,))
compact_assign_jit = jax.jit(ivf_compact_assign, static_argnames=("cfg",))
compact_scatter_jit = jax.jit(
    ivf_compact_scatter, donate_argnums=(6, 7, 8, 9)
)

# donated parameter positions of each mutation program, by kind — what
# the lint meta (and DESIGN.md's table) reference
UPSERT_DONATED = (6, 7, 8, 9)
DELETE_DONATED = (2,)
COMPACT_DONATED = (6, 7, 8, 9)


# ---------------------------------------------------------------------------
# Compaction planning (host) — sample-retrained k-means + one device
# scatter; bucket_cap kept whenever the live set still fits


COMPACT_SAMPLE = 16384  # deterministic live-row sample for the retrain


def gather_live_sample(index, limit: int = COMPACT_SAMPLE) -> np.ndarray:
    """Up to ``limit`` live rows (dequantized, centered frame) fetched
    via a SMALL device gather — the tune_nprobe precedent: the retrain
    must not round-trip the whole store through the host."""
    fl = freelist_of(index)
    ids = sorted(fl.pos)
    if not ids:
        raise ValueError("cannot compact an empty index (no live rows)")
    take = np.linspace(0, len(ids) - 1, num=min(limit, len(ids)),
                       dtype=np.int64)
    flat = np.array(
        [fl.pos[ids[i]][0] * fl.cap + fl.pos[ids[i]][1] for i in take],
        dtype=np.int64,
    )
    sel = index.buckets.reshape(-1, index.buckets.shape[-1])[
        jnp.asarray(flat)
    ]
    if index.bucket_scales is not None:
        sel = dequantize_rows(
            sel,
            index.bucket_scales.reshape(-1)[jnp.asarray(flat)],
            index.store_dtype,
            index.dim,
        )
    return np.asarray(jax.device_get(sel.astype(jnp.float32)))


def retrain_centroids(index, cfg: KNNConfig, sample: np.ndarray):
    """K-means over a host-copied live-row sample (deterministic per
    ``ivf_seed``) → (centroids, centroid_sqs). Pure compute over the
    SNAPSHOT — it touches no resident array, so the caller runs it OFF
    the mutation lock (training must block nothing)."""
    from mpi_knn_tpu.ivf.kmeans import kmeans

    res = kmeans(
        sample, index.partitions, iters=cfg.kmeans_iters,
        seed=cfg.ivf_seed, init=cfg.kmeans_init,
    )
    return res.centroids, jax.jit(sq_norms)(res.centroids)


def plan_compact(index, cfg: KNNConfig, centroids, centroid_sqs,
                 min_cap: int | None = None):
    """The LOCK-HELD half of a compaction: assign every slot on device
    against the (possibly retrained) centroids and lay out the new
    store. Returns ``(dst_part, dst_slot, new_cap, stats)`` — the device
    scatter itself is the caller's job (it owns the executable cache and
    the donation). ``new_cap`` equals the current cap whenever the
    re-clustered live set fits (compaction then stays invisible to every
    compiled cell); ``min_cap`` forces growth — the overflow backstop
    for a burst that must fit after this pass."""
    from mpi_knn_tpu.parallel.partition import pad_to_multiple

    fl = freelist_of(index)
    parts = np.asarray(jax.device_get(compact_assign_jit(
        index.buckets, index.bucket_scales, centroids, centroid_sqs,
        cfg=index.cfg,
    )))
    ids_flat = np.asarray(
        jax.device_get(index.bucket_ids)
    ).reshape(-1)
    live = ids_flat >= 0
    counts = np.bincount(parts[live], minlength=index.partitions)
    need = int(counts.max()) if counts.size else 1
    headroom_cap = pad_to_multiple(
        max(1, int(np.ceil(need * (1.0 + cfg.bucket_headroom)))), 8
    )
    new_cap = index.bucket_cap if need <= index.bucket_cap else headroom_cap
    if min_cap is not None:
        new_cap = max(new_cap, pad_to_multiple(int(min_cap), 8))
    # destination layout: live rows in flat-slot order get consecutive
    # slots within their new partition (deterministic). The drop
    # sentinel is the STORE's total bucket count (a sharded store pads
    # past the real partitions — see Freelist.total)
    n = ids_flat.shape[0]
    dst_part = np.full(n, index.buckets.shape[0], np.int32)
    dst_slot = np.zeros(n, np.int32)
    next_slot = np.zeros(index.partitions, np.int64)
    for i in np.flatnonzero(live):
        p = int(parts[i])
        dst_part[i] = p
        dst_slot[i] = next_slot[p]
        next_slot[p] += 1
    stats = {
        "live": int(live.sum()),
        "tombstones_reclaimed": fl.tombstones,
        "cap_before": index.bucket_cap,
        "cap_after": int(new_cap),
        "max_bucket": need,
    }
    return dst_part, dst_slot, int(new_cap), stats


def should_compact(index, cfg: KNNConfig) -> str | None:
    """The trigger: the reason string ("fill" / "tombstones") when a
    compaction threshold is crossed, else None."""
    fl = freelist_of(index)
    if fl.max_fill >= cfg.compact_fill_threshold:
        return "fill"
    if (
        fl.tombstones > 0
        and fl.tombstone_fraction >= cfg.compact_tombstone_fraction
    ):
        return "tombstones"
    return None


@functools.lru_cache(maxsize=None)
def _zeros_maker(shape, dtype_str, sharding=None):
    """A jitted zero-store maker (compiled once per shape, shared by
    every compaction at that shape): the donated destination scratch
    must be born on device without an eager host corpus-sized buffer or
    an uncounted eager fill. ``sharding`` places the scratch on a
    sharded index's bucket layout directly."""
    fn = lambda: jnp.zeros(shape, jnp.dtype(dtype_str))  # noqa: E731
    if sharding is not None:
        return jax.jit(fn, out_shardings=sharding)
    return jax.jit(fn)


def make_dst_store(index, new_cap: int, sharding=None):
    """Fresh (donatable) destination arrays for a compact scatter — ids
    start at −1 (everything free), rows/norms/scales at zero. A sharded
    index's scratch is born on its bucket sharding."""
    P = index.buckets.shape[0]
    pd = index.buckets.shape[-1]
    buckets = _zeros_maker(
        (P, new_cap, pd), str(index.buckets.dtype), sharding
    )()
    # the id plane starts all-free (−1): a small host buffer, device_put
    # (a transfer, never a compiled fill — the engine's qids precedent)
    ids_np = np.full((P, new_cap), -1, np.int32)
    ids = (jax.device_put(ids_np, sharding) if sharding is not None
           else jax.device_put(ids_np))
    sqs = _zeros_maker((P, new_cap), str(index.bucket_sqs.dtype), sharding)()
    scales = (
        _zeros_maker((P, new_cap), "float32", sharding)()
        if index.bucket_scales is not None else None
    )
    return buckets, ids, sqs, scales
