"""``mpi-knn build-index`` — train the k-means partitioner and save a
clustered (IVF) index to ``.npz``.

The offline half of the sublinear serving story: cluster once here, then
``mpi-knn query --index-load corpus.ivf.npz`` serves the saved partitions
through the bucketed AOT executable cache (zero steady-state compiles,
probed bytes per query = nprobe/partitions of the corpus).

``--backend ring`` is accepted and means the SHARDED deployment shape
(`mpi_knn_tpu.ivf.sharded`): training is still single-device math
(clustering is layout-independent), the saved ``.npz`` is identical, and
the shard layout is DERIVED at serve time from ``--devices`` — one
artifact serves on any shard count (``mpi-knn query --index-load …
--backend ring --devices 4``).

Flag combinations the clustered path cannot honor are refused with a loud
exit 2 (the serve-CLI convention — never silently build a different index
than the one requested): a pallas backend (the fused kernels scan the
full corpus by construction), a non-L2 metric (the k-means partitioner is
L2 geometry), float64 (the dense backends' debug mode),
nprobe > partitions.

Examples::

    mpi-knn build-index --data sift:100000 --partitions 256 --out sift.ivf.npz
    mpi-knn build-index --data corpus.mat --partitions 64 --nprobe 8 \
        --out corpus.ivf.npz
    mpi-knn query --data sift:100000 --index-load sift.ivf.npz --synthetic 4096
    mpi-knn query --data sift:100000 --index-load sift.ivf.npz \
        --backend ring --devices 4 --synthetic 4096   # sharded serving
"""

from __future__ import annotations

import argparse
import sys
import time

from mpi_knn_tpu.config import KMEANS_INITS, KNNConfig


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mpi-knn build-index",
        description="train a k-means-clustered (IVF) index and save it "
        "(.npz); query it with `mpi-knn query --index-load`",
    )
    d = p.add_argument_group("data")
    d.add_argument("--data", default="mnist",
                   help="corpus spec (same forms as the run driver: "
                   "'mnist', 'digits', 'synthetic:MxDcC', 'sift:M', "
                   "*.fvecs/bvecs, or a .mat file)")
    d.add_argument("--limit", type=int, default=None,
                   help="use first N corpus rows only")

    k = p.add_argument_group("index")
    k.add_argument("--partitions", type=int, required=True,
                   help="k-means partitions (the sublinear axis: probed "
                   "bytes per query are nprobe/partitions of the corpus)")
    k.add_argument("--nprobe", type=int, default=None,
                   help="partitions probed per query; default: auto-tune "
                   "the smallest nprobe reaching --recall-target on a "
                   "held-out corpus sample vs the brute-force oracle")
    k.add_argument("--recall-target", type=float, default=0.95,
                   help="recall@k target for the nprobe auto-tune")
    k.add_argument("--k", type=int, default=10,
                   help="neighbors the auto-tune measures recall@k at")
    k.add_argument("--metric", default="l2", choices=["l2", "cosine"],
                   help="l2 only — cosine is refused loudly (the k-means "
                   "partitioner and centroid score are L2 geometry)")
    k.add_argument("--backend", default="auto",
                   help="serial/auto (single-device) or ring (the sharded "
                   "deployment shape — training is identical; the shard "
                   "layout is derived at serve time, so the saved index "
                   "is the same artifact); pallas is refused")
    k.add_argument("--dtype", default="float32",
                   choices=["float32", "bfloat16", "int8", "int4"],
                   help="bucket-store at-rest dtype; bfloat16 halves "
                   "resident HBM and probe-gather bytes; int8/int4 are "
                   "the block-scaled quantized levels (~4x/8x cuts, "
                   "codes + per-row scales, asymmetric distance with "
                   "exact f32 queries — ops/quant.py)")
    k.add_argument("--kmeans-iters", type=int, default=25,
                   help="fixed Lloyd iteration budget (single compiled "
                   "executable)")
    k.add_argument("--kmeans-init", choices=list(KMEANS_INITS),
                   default="kmeans++")
    k.add_argument("--seed", type=int, default=0,
                   help="PRNG seed threading init + re-seeding "
                   "(bit-deterministic training per seed)")

    o = p.add_argument_group("output")
    o.add_argument("--out", required=True, metavar="PATH.npz",
                   help="where to save the index")
    o.add_argument("--platform", choices=["auto", "cpu", "tpu"],
                   default="auto")
    o.add_argument("-q", "--quiet", action="store_true")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    if args.platform != "auto":
        from mpi_knn_tpu.utils.platform import force_platform

        force_platform(args.platform)

    from mpi_knn_tpu.cli import load_corpus
    from mpi_knn_tpu.ivf import build_ivf_index, save_ivf_index

    X, _, source = load_corpus(args.data, limit=args.limit)

    # --backend ring = the sharded deployment shape: the k-means training
    # and the saved artifact are IDENTICAL (the shard layout is derived at
    # serve time), so the build itself runs the single-device path — the
    # old exit-2 refusal is lifted into real support, not silently mapped
    backend = args.backend
    sharded = backend in ("ring", "ring-overlap")
    if sharded:
        backend = "auto"

    try:
        cfg = KNNConfig(
            k=args.k,
            metric=args.metric,
            backend=backend,
            dtype=args.dtype,
            recall_target=args.recall_target,
            partitions=args.partitions,
            nprobe=args.nprobe,
            kmeans_iters=args.kmeans_iters,
            kmeans_init=args.kmeans_init,
            ivf_seed=args.seed,
        )
    except ValueError as e:
        # invalid knob combination (cosine metric, nprobe > partitions…):
        # loud usage error, never a silently-adjusted index
        print(f"error: {e}", file=sys.stderr)
        return 2

    t0 = time.perf_counter()
    try:
        index = build_ivf_index(X, cfg)
    except ValueError as e:
        # the clustered path cannot honor this combination (non-serial
        # backend, partitions > corpus rows, float64 …)
        print(f"error: {e}", file=sys.stderr)
        return 2
    build_s = time.perf_counter() - t0
    path = save_ivf_index(index, args.out)

    if not args.quiet:
        tuned = (
            f"auto-tuned nprobe={index.nprobe} "
            f"(recall@{args.k}={index.tuned_recall:.4f} vs brute force)"
            if index.tuned_recall is not None
            else f"nprobe={index.nprobe}"
        )
        frac = index.probe_bytes / max(index.nbytes_resident, 1)
        print(
            f"[mpi-knn build-index] {source} shape={X.shape} -> "
            f"{index.partitions} partitions (bucket_cap="
            f"{index.bucket_cap}), {tuned}; probes "
            f"{100 * frac:.1f}% of corpus bytes/query; "
            f"train+tune {build_s:.2f}s; saved {path}"
        )
        if sharded:
            print(
                "[mpi-knn build-index] --backend ring noted: the shard "
                "layout is derived at serve time — serve this artifact "
                "with `mpi-knn query --index-load ... --backend ring "
                "--devices N` on any shard count"
            )
    return 0


if __name__ == "__main__":
    sys.exit(main())
