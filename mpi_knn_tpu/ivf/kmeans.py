"""TPU-native Lloyd's k-means — the partitioner behind the clustered (IVF)
index (``mpi_knn_tpu.ivf``).

The whole trainer is ONE jitted program: init (k-means++ D²-sampling or a
seeded random row draw), a fixed-``iters`` ``lax.scan`` of Lloyd rounds,
and a final assignment pass — so training lowers to a single executable
(no per-iteration dispatch, no host round trips for convergence checks;
a fixed iteration budget is the shape-static analogue of "until
converged", and the bench row measures what the budget buys).

Per round:

- **assignment** reuses ``ops.distance.pairwise_sq_l2`` in row blocks (a
  ``lax.map`` over (block × k) distance tiles, same memory discipline as
  the serial backend's query tiling — the full (m × k) distance matrix is
  never materialized when m is large);
- **update** is a segment-sum: per-cluster coordinate sums and counts via
  ``jax.ops.segment_sum`` on the assignment vector, then a masked divide;
- **empty-cluster re-seeding** is deterministic: the j-th empty cluster
  is re-seeded to the j-th farthest point from its current centroid
  (``lax.top_k`` over the assignment distances). A cluster can only stay
  empty if the data has fewer distinct rows than k — real corpora
  re-populate on the next assignment, and the property is tested
  (tests/test_ivf.py).

Everything is keyed by one PRNG seed (``KNNConfig.ivf_seed``) threaded
through init; same (data, k, seed, init, iters) → bit-identical
centroids.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from mpi_knn_tpu.ops.distance import pairwise_sq_l2, sq_norms

# Row-block width of the assignment pass: bounds the per-step distance
# tile at (block × k) like the serial backend's query tiling bounds its
# (q_tile × c_tile) tile. 2048 × k ≤ 2048 · m elements — far inside every
# configured tile budget at realistic partition counts.
ASSIGN_BLOCK = 2048


@dataclasses.dataclass
class KMeansResult:
    """Trained partitioner state: (k, d) centroids, per-point assignments,
    per-cluster counts, and the mean squared assignment distance
    (inertia/m — the number a training-quality trajectory tracks)."""

    centroids: jax.Array  # (k, d) f32
    assignments: jax.Array  # (m,) int32
    counts: jax.Array  # (k,) int32
    inertia: jax.Array  # () f32, mean of per-point min squared distances


def _assign_blocks(data, data_sq, centroids, block: int):
    """(m,) argmin cluster + (m,) min squared distance, computed in row
    blocks so only a (block × k) distance tile is live at once."""
    m, d = data.shape
    cent_sq = sq_norms(centroids)
    nb = -(-m // block)
    pad = nb * block - m
    if pad:
        data = jnp.pad(data, ((0, pad), (0, 0)))
        data_sq = jnp.pad(data_sq, (0, pad))
    data_b = data.reshape(nb, block, d)
    sq_b = data_sq.reshape(nb, block)

    def one(args):
        rows, rows_sq = args
        dist = pairwise_sq_l2(
            rows, centroids, x_sq=rows_sq, y_sq=cent_sq,
            precision=jax.lax.Precision.HIGHEST,
        )
        return jnp.argmin(dist, axis=-1).astype(jnp.int32), jnp.min(
            dist, axis=-1
        )

    assign, min_d2 = jax.lax.map(one, (data_b, sq_b))
    assign = assign.reshape(nb * block)[:m]
    min_d2 = min_d2.reshape(nb * block)[:m]
    return assign, min_d2


def _init_random(key, data, k: int):
    """k distinct data rows by a seeded permutation draw."""
    m = data.shape[0]
    perm = jax.random.permutation(key, m)[:k]
    return data[perm]


def _init_kmeanspp(key, data, data_sq, k: int):
    """k-means++ D² sampling: first centroid uniform, each next sampled
    with probability proportional to the squared distance to the nearest
    chosen centroid. O(k·m·d) — one pairwise row per step, under a
    ``fori_loop`` with a (k, d) centroid buffer (shape-static)."""
    m, d = data.shape
    k0, key = jax.random.split(key)
    first = jax.random.randint(k0, (), 0, m)
    cents = jnp.zeros((k, d), data.dtype).at[0].set(data[first])
    min_d2 = pairwise_sq_l2(
        data, data[first][None, :], x_sq=data_sq,
        precision=jax.lax.Precision.HIGHEST,
    )[:, 0]

    def step(i, carry):
        cents, min_d2, key = carry
        key, kc = jax.random.split(key)
        # D² sampling; a floor keeps the categorical defined when every
        # remaining point coincides with a chosen centroid (all-zero mass)
        logits = jnp.log(jnp.maximum(min_d2, 1e-30))
        idx = jax.random.categorical(kc, logits)
        cents = cents.at[i].set(data[idx])
        d2 = pairwise_sq_l2(
            data, data[idx][None, :], x_sq=data_sq,
            precision=jax.lax.Precision.HIGHEST,
        )[:, 0]
        return cents, jnp.minimum(min_d2, d2), key

    cents, _, _ = jax.lax.fori_loop(1, k, step, (cents, min_d2, key))
    return cents


@functools.partial(
    jax.jit, static_argnames=("k", "iters", "init", "block")
)
def _kmeans_jit(data, seed, k: int, iters: int, init: str, block: int):
    data = data.astype(jnp.float32)
    data_sq = sq_norms(data)
    key = jax.random.PRNGKey(seed)
    if init == "kmeans++":
        centroids = _init_kmeanspp(key, data, data_sq, k)
    else:
        centroids = _init_random(key, data, k)

    def lloyd(centroids, _):
        assign, min_d2 = _assign_blocks(data, data_sq, centroids, block)
        counts = jax.ops.segment_sum(
            jnp.ones_like(assign, dtype=jnp.int32), assign, num_segments=k
        )
        sums = jax.ops.segment_sum(data, assign, num_segments=k)
        new = sums / jnp.maximum(counts, 1)[:, None].astype(data.dtype)
        # deterministic empty-cluster re-seed: the j-th empty cluster gets
        # the j-th farthest point from its current centroid — the standard
        # split-the-worst-fit move, with no data-dependent shapes
        empty = counts == 0
        _, far_idx = jax.lax.top_k(min_d2, k)
        erank = jnp.clip(jnp.cumsum(empty) - 1, 0, k - 1)
        new = jnp.where(empty[:, None], data[far_idx[erank]], new)
        return new, None

    centroids, _ = jax.lax.scan(lloyd, centroids, None, length=iters)
    assign, min_d2 = _assign_blocks(data, data_sq, centroids, block)
    counts = jax.ops.segment_sum(
        jnp.ones_like(assign, dtype=jnp.int32), assign, num_segments=k
    )
    return centroids, assign, counts, jnp.mean(min_d2)


def kmeans(
    data,
    k: int,
    *,
    iters: int = 25,
    seed: int = 0,
    init: str = "kmeans++",
    block: int = ASSIGN_BLOCK,
) -> KMeansResult:
    """Train a k-partition Lloyd's k-means on (m, d) data (host numpy or
    device array), single compiled executable, bit-deterministic per
    ``seed``. Returns :class:`KMeansResult`."""
    if init not in ("kmeans++", "random"):
        raise ValueError(f"unknown kmeans init {init!r}")
    m = int(np.shape(data)[0])
    if not 1 <= k <= m:
        raise ValueError(f"k must be in [1, m={m}], got {k}")
    if iters < 1:
        raise ValueError(f"iters must be >= 1, got {iters}")
    if not isinstance(data, jax.Array):
        data = jnp.asarray(np.asarray(data, dtype=np.float32))
    centroids, assign, counts, inertia = _kmeans_jit(
        data, jnp.int32(seed), k, iters, init, min(block, m)
    )
    return KMeansResult(
        centroids=centroids, assignments=assign, counts=counts,
        inertia=inertia,
    )
