"""Two-stage clustered search: centroid score → static-shape probe gather
→ exact masked rerank (the sublinear rung of the DESIGN.md ladder).

Per query tile:

1. **centroid score** — one small exact dot against the (P, d) centroid
   table (``ops.distance.pairwise_sq_l2``, HIGHEST: the routing decision
   must not be noisier than the data), followed by a static-shape
   ``lax.top_k`` of the ``nprobe`` nearest partitions;
2. **probe gather** — whole padded buckets for each probed partition:
   ``(q_tile, nprobe, bucket_cap, d)`` rows + ids + precomputed norms.
   The gather is the ONLY place corpus payload enters the program, and
   its size is nprobe·bucket_bytes per query row — NOT the corpus (the
   bound lint rule R2 budgets and R6 ties to the exact dot);
3. **exact finish** — ``ops.rerank.rerank_exact_topk``: HIGHEST batched
   distance dot over the gathered candidates with the full ``mask_tile``
   padding/self/zero semantics re-applied on exact values, exact top-k.
   Under ``precision_policy="mixed"`` a bf16 DEFAULT compress dot first
   overfetches 4k of the gathered candidates (same recipe and masking
   split as ``ops/rerank.py``) and only the survivors hit the exact dot —
   the policies compose because stage 3 IS the shared rerank pipeline.

Bucket padding slots carry id −1 → ``mask_tile`` forces them to +inf, so
ragged partitions cost padded FLOPs but never wrong answers. Every point
lives in exactly one partition, so probed candidates are duplicate-free
and ``nprobe == partitions`` is a full exact scan (the degenerate case
the parity tests pin against the serial backend).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from mpi_knn_tpu.config import KNNConfig
from mpi_knn_tpu.ops.distance import pairwise_sq_l2, sq_norms
from mpi_knn_tpu.ops.quant import dequantize_rows
from mpi_knn_tpu.ops.rerank import (
    mixed_applies,
    overfetch_width,
    rerank_exact_topk,
)
from mpi_knn_tpu.ops.topk import (
    init_topk_tiles,
    mask_tile,
    merge_topk,
    preselect_smallest,
)
from mpi_knn_tpu.parallel.partition import pad_rows_any, pad_to_multiple


def _compress_keys_batched(q_x, q_sq, rows, row_sqs):
    """Per-query compressed distance keys over gathered candidate rows —
    the batched form of ``ops.rerank.compress_tile``: bf16-rounded
    operands, single-pass DEFAULT dot, f32 accumulation. Keys only, never
    output values."""
    xy = jax.lax.dot_general(
        q_x.astype(jnp.bfloat16),
        rows.astype(jnp.bfloat16),
        dimension_numbers=(((1,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT,
    )
    return q_sq[:, None] - 2.0 * xy + row_sqs


def finish_candidates(q_x, q_ids, q_sq, rows, ids, sqs, cfg: KNNConfig):
    """Stage-3 finish over gathered candidates — shared by the
    single-device tile body and the sharded routed tile
    (``ivf/sharded.py``), so the two paths can never drift: under
    ``precision_policy="mixed"`` a bf16 DEFAULT compress dot overfetches
    4k of the (q_tile, v, d) candidates (id-based masks on compressed
    keys, zero-by-value deferred — the ops/rerank.py masking split), then
    the survivors hit the shared exact HIGHEST rerank top-k."""
    v = ids.shape[1]
    if cfg.precision_policy == "mixed" and mixed_applies(cfg.k, v):
        keys = _compress_keys_batched(q_x, q_sq, rows, sqs)
        keys = mask_tile(
            keys,
            ids,
            query_ids=q_ids if cfg.exclude_self else None,
            exclude_self=cfg.exclude_self,
            exclude_zero=False,
        )
        pos = preselect_smallest(keys, overfetch_width(cfg.k, v))
        rows = jnp.take_along_axis(rows, pos[:, :, None], axis=1)
        ids = jnp.take_along_axis(ids, pos, axis=1)
        sqs = jnp.take_along_axis(sqs, pos, axis=1)
    return rerank_exact_topk(
        q_x,
        q_ids,
        q_sq,
        rows,
        ids,
        sqs,
        cfg.k,
        metric="l2",
        exclude_self=cfg.exclude_self,
        exclude_zero=cfg.exclude_zero,
        zero_eps=cfg.zero_eps,
    )


def score_centroids(q_x, centroids, centroid_sqs, nprobe: int):
    """Stage-1 routing decision, shared with the sharded path: exact
    HIGHEST centroid score + static-shape top-nprobe. Returns
    (q_sq, (q_tile, nprobe) partition ids)."""
    q_sq = sq_norms(q_x)
    cd = pairwise_sq_l2(
        q_x, centroids, x_sq=q_sq, y_sq=centroid_sqs,
        precision=jax.lax.Precision.HIGHEST,
    )
    _, probe = jax.lax.top_k(-cd, nprobe)
    return q_sq, probe


def ivf_query_tile(
    q_x: jax.Array,  # (q_tile, d)
    q_ids: jax.Array,  # (q_tile,)
    centroids: jax.Array,  # (P, d) f32
    centroid_sqs: jax.Array,  # (P,)
    buckets: jax.Array,  # (P, cap, d) at-rest dtype — int8 code lanes
    # (packed for int4) when the store is quantized
    bucket_ids: jax.Array,  # (P, cap) int32, -1 padding
    bucket_sqs: jax.Array,  # (P, cap) f32 norms of the dequantized store
    bucket_scales: jax.Array | None,  # (P, cap) f32 per-row scales
    cfg: KNNConfig,
    nprobe: int,
):
    """One query tile through the two-stage search → ((q_tile, k) dists
    ascending, ids). The single tile body behind the one-shot wrapper,
    the serving engine's bucket-cache cells, and the lint lowering.

    A quantized store (``cfg.dtype`` int8/int4) changes exactly one
    thing: the probe gather moves CODE lanes (1/4–1/8 the f32 bytes —
    what R2's quantized gather budget prices) plus the tiny scale table,
    and the candidates are dequantized right after the gather — the
    asymmetric distance (exact f32 queries vs dequantized candidates)
    then runs through the same compress/rerank finish as every other
    store."""
    acc = jnp.float32
    q_x = q_x.astype(acc)
    dim = centroids.shape[1]  # logical d (buckets may hold packed lanes)
    q_sq, probe = score_centroids(q_x, centroids, centroid_sqs, nprobe)
    cap = buckets.shape[1]
    v = nprobe * cap
    rows = jnp.take(buckets, probe, axis=0).reshape(-1, v, buckets.shape[2])
    ids = jnp.take(bucket_ids, probe, axis=0).reshape(-1, v)
    sqs = jnp.take(bucket_sqs, probe, axis=0).reshape(-1, v)
    if bucket_scales is not None:
        scl = jnp.take(bucket_scales, probe, axis=0).reshape(-1, v)
        rows = dequantize_rows(rows, scl, cfg.dtype, dim)
    rows = rows.astype(acc)
    return finish_candidates(q_x, q_ids, q_sq, rows, ids, sqs, cfg)


def ivf_serve_chunk(
    q_tiles: jax.Array,  # (QT, q_tile, d) one padded query batch
    qid_tiles: jax.Array,  # (QT, q_tile)
    carry_d: jax.Array,  # (QT, q_tile, k) per-batch scratch (donatable)
    carry_i: jax.Array,
    centroids: jax.Array,
    centroid_sqs: jax.Array,
    buckets: jax.Array,
    bucket_ids: jax.Array,
    bucket_sqs: jax.Array,
    bucket_scales: jax.Array | None,
    cfg: KNNConfig,
    nprobe: int,
):
    """One serving batch against a resident :class:`~mpi_knn_tpu.ivf.index.
    IVFIndex` — the engine's uniform (queries, query_ids, carry_d,
    carry_i, <resident arrays…>) convention so the scratch donation stays
    ``donate_argnums=(2, 3)``. The tile results merge into the (all-inf)
    donated scratch — a bit-exact no-op merge whose sole purpose is giving
    the scratch buffers an output to alias (the pallas serve path's
    trick)."""

    def per_tile(args):
        q_x, q_ids, cd_, ci_ = args
        d, i = ivf_query_tile(
            q_x, q_ids, centroids, centroid_sqs, buckets, bucket_ids,
            bucket_sqs, bucket_scales, cfg, nprobe,
        )
        return merge_topk(cd_, ci_, d.astype(cd_.dtype), i, method="exact")

    return jax.lax.map(per_tile, (q_tiles, qid_tiles, carry_d, carry_i))


_ivf_serve_jit = jax.jit(
    ivf_serve_chunk, static_argnames=("cfg", "nprobe")
)


def ivf_query_shapes(cfg: KNNConfig, nprobe: int, bucket_cap: int,
                     dim: int, nq: int) -> tuple[int, int]:
    """(q_tile, q_pad) for an IVF batch: the probe gather materializes
    q_tile·nprobe·bucket_cap·dim elements, so q_tile shrinks until that
    stays inside ``cfg.max_tile_elems`` — the same hard per-step bound
    ``cap_corpus_tile`` enforces for the dense backends, applied to the
    gather (the IVF path's dominant intermediate). Unlike the dense cap,
    the per-ROW gather (nprobe·bucket_cap·dim) is fixed by the index
    layout, so when even a single-query tile exceeds the budget there is
    nothing left to shrink — that is refused loudly (the convention),
    never silently materialized."""
    q_tile = min(cfg.query_tile, pad_to_multiple(nq, 8))
    per_row = max(1, nprobe * bucket_cap * dim)
    while q_tile > 1 and q_tile * per_row > cfg.max_tile_elems:
        q_tile = max(1, q_tile // 2)
    if q_tile * per_row > cfg.max_tile_elems:
        raise ValueError(
            f"one query row's probe gather (nprobe={nprobe} × bucket_cap="
            f"{bucket_cap} × d={dim} = {per_row} elems) exceeds "
            f"max_tile_elems={cfg.max_tile_elems}; lower nprobe/partitions "
            "(bigger partitions mean bigger buckets), raise "
            "max_tile_elems, or use a dense backend for full scans"
        )
    return q_tile, pad_to_multiple(nq, q_tile)


def prepare_query_tiles(index, queries, query_ids, cfg: KNNConfig,
                        assume_centered: bool = False):
    """Host-side half of :func:`search_ivf`: center with the index's
    stored mean, pad and tile one query batch for the jitted search.
    Exposed so callers that time the COMPUTE (bench.py's IVF rows) can
    prepare once, keep the tiles device-resident, and run reps against
    them — the dense bench's timer placement. Returns
    (q_tiles, qid_tiles, q_pad, q_tile)."""
    queries = np.asarray(queries)
    nq = queries.shape[0]
    if query_ids is None:
        q_ids = np.full(nq, -1, dtype=np.int32)
    else:
        q_ids = np.asarray(query_ids, dtype=np.int32)
    if cfg.center and index.mu is not None and not assume_centered:
        queries = queries - index.mu
    q_tile, q_pad = ivf_query_shapes(
        cfg, cfg.nprobe, index.bucket_cap, index.dim, nq
    )
    qt = q_pad // q_tile
    q_tiles = pad_rows_any(queries, q_pad, dtype=jnp.float32).reshape(
        qt, q_tile, index.dim
    )
    qid_tiles = pad_rows_any(
        q_ids, q_pad, fill=-1, dtype=jnp.int32
    ).reshape(qt, q_tile)
    return q_tiles, qid_tiles, q_pad, q_tile


def run_query_tiles(index, q_tiles, qid_tiles, cfg: KNNConfig):
    """Device half of :func:`search_ivf`: fresh all-inf carries + the
    jitted two-stage search over prepared tiles. Returns padded
    (QT, q_tile, k) device arrays (not synchronized)."""
    qt, q_tile = q_tiles.shape[0], q_tiles.shape[1]
    carry_d, carry_i = init_topk_tiles(qt, q_tile, cfg.k, dtype=jnp.float32)
    return _ivf_serve_jit(
        q_tiles, qid_tiles, carry_d, carry_i,
        index.centroids, index.centroid_sqs, index.buckets,
        index.bucket_ids, index.bucket_sqs, index.bucket_scales,
        cfg, cfg.nprobe,
    )


def search_ivf(index, queries, query_ids=None, config=None,
               assume_centered=False, **overrides):
    """One-shot query batch against an :class:`IVFIndex` (no executable
    cache — the serving engine owns that): center with the index's stored
    mean, tile, run the jitted two-stage search, strip padding. Returns
    ((q, k) dists ascending, (q, k) ids) as numpy arrays.
    ``assume_centered`` skips the centering step for queries already in
    the index's centered frame (the nprobe auto-tuner's held-out corpus
    rows, which come back out of the bucket store)."""
    cfg = index.compatible_cfg((config or index.cfg).replace(**overrides))
    nq = np.shape(queries)[0]
    q_tiles, qid_tiles, q_pad, _ = prepare_query_tiles(
        index, queries, query_ids, cfg, assume_centered=assume_centered
    )
    d, i = run_query_tiles(index, q_tiles, qid_tiles, cfg)
    return (
        np.asarray(d.reshape(q_pad, cfg.k)[:nq]),
        np.asarray(i.reshape(q_pad, cfg.k)[:nq]),
    )
