"""Logging setup with per-host prefixes (SURVEY.md §6 "Metrics / logging":
the reference's only tracing is anonymous printf debug lines like
"DONE"/"done"/"DOne" per rank per round,
``/root/reference/mpi-knn-parallel_non_blocking.c:208,217,226`` — no way to
tell which rank said what. Every record here carries ``[hostI/N]``.)
"""

from __future__ import annotations

import logging

log = logging.getLogger("mpi_knn_tpu")


class _HostPrefix(logging.Filter):
    """Resolves the [hostI/N] prefix lazily at EMIT time, not setup time.

    Setup-time resolution would (a) initialize the JAX backend before
    ``jax.distributed.initialize`` — which must run first in multi-host jobs
    — and (b) freeze the prefix at host0/1 captured pre-init. The CLI's
    first log record is emitted after multi-host init, so emit-time lookup
    sees the real process index."""

    def filter(self, record: logging.LogRecord) -> bool:
        try:
            import jax

            record.host = f"host{jax.process_index()}/{jax.process_count()}"
        except Exception:
            record.host = "host0/1"
        return True


def setup_logging(verbosity: int = 0, quiet: bool = False) -> logging.Logger:
    """Configure the framework logger: WARNING by default, INFO at -v,
    DEBUG at -vv; records carry this host's process index so multi-host
    output interleaves legibly. Safe to call before
    ``jax.distributed.initialize`` — no JAX call happens here."""
    level = logging.WARNING
    if quiet:
        level = logging.ERROR
    elif verbosity >= 2:
        level = logging.DEBUG
    elif verbosity == 1:
        level = logging.INFO

    handler = logging.StreamHandler()
    handler.setFormatter(
        logging.Formatter(
            "%(asctime)s [%(host)s] %(name)s %(levelname)s: %(message)s",
            datefmt="%H:%M:%S",
        )
    )
    handler.addFilter(_HostPrefix())
    log.handlers.clear()
    log.addHandler(handler)
    log.setLevel(level)
    log.propagate = False
    return log
