"""Round-granular checkpoint/resume (SURVEY.md §6 "Checkpoint / resume").

The reference loses everything on a kill — results only ever reach stdout
(``/root/reference/knn-serial.c:130``). Here the all-kNN carry (per-query
top-k dists/ids) plus the corpus-tile cursor is saved every R rounds; a
restarted run validates the fingerprint (shapes, config, cheap corpus
checksum) and continues from the saved round instead of recomputing.

Files are NPZ, written atomically (tmp + rename) so a crash mid-save leaves
the previous checkpoint intact.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from pathlib import Path
from typing import Optional

import numpy as np

from mpi_knn_tpu.config import KNNConfig

_STATE_FILE = "knn_state.npz"


def _array_signature(arr) -> bytes:
    """Full shape + dtype + a strided ~4096-element content sample covering
    the whole array. The SAME flat-stride scheme runs for host and device
    arrays (device-side reshape+slice, so only the small sample crosses to
    host), so the fingerprint is residency-independent: a run checkpointed
    with a numpy corpus resumes when re-invoked with the identical corpus
    already on device, and vice versa. Exception: centered ring runs fold
    the residency back in (ring_resumable appends a :ctr-dev/:ctr-host
    suffix) because center_for_l2 accumulates the mean at residency-
    dependent precision — do not 'simplify' that suffix away."""
    shape, dtype = tuple(arr.shape), str(arr.dtype)
    n = 1
    for dim in shape:
        n *= dim
    step = max(1, n // 4096)
    if isinstance(arr, np.ndarray):
        sample = np.ascontiguousarray(
            np.ascontiguousarray(arr).reshape(-1)[::step]
        )
    else:
        sample = np.asarray(arr.reshape(-1)[::step])
    return str(shape).encode() + str(dtype).encode() + sample.tobytes()


def fingerprint(corpus, queries, cfg: KNNConfig) -> str:
    """Cheap, stable identity of (data, config): full shapes + strided
    content samples + config fields. Not cryptographic — guards against
    resuming with the wrong data/config, not against adversaries."""
    h = hashlib.sha256()
    h.update(json.dumps(dataclasses.asdict(cfg), sort_keys=True).encode())
    for arr in (corpus, queries):
        h.update(_array_signature(arr))
    return h.hexdigest()


@dataclasses.dataclass
class KNNCheckpoint:
    carry_d: np.ndarray  # (QT, q_tile, k)
    carry_i: np.ndarray  # (QT, q_tile, k)
    tiles_done: int  # corpus tiles already merged into the carry
    fingerprint: str


def save_checkpoint(ckpt_dir, state: KNNCheckpoint):
    d = Path(ckpt_dir)
    d.mkdir(parents=True, exist_ok=True)
    tmp = d / (_STATE_FILE + ".tmp")
    np.savez(
        tmp,
        carry_d=state.carry_d,
        carry_i=state.carry_i,
        tiles_done=np.int64(state.tiles_done),
        fingerprint=np.frombuffer(state.fingerprint.encode(), dtype=np.uint8),
    )
    # np.savez appends .npz to the filename it is given
    os.replace(str(tmp) + ".npz", d / _STATE_FILE)


def load_checkpoint(ckpt_dir, expect_fingerprint: str) -> Optional[KNNCheckpoint]:
    """Returns the saved state, or None if absent/mismatched/corrupt.

    Corruption (torn write outside the atomic rename path, disk fault,
    truncation) degrades to a clean restart — the alternative is a resumable
    run that crashes on the very artifact meant to save it."""
    path = Path(ckpt_dir) / _STATE_FILE
    if not path.exists():
        return None
    try:
        with np.load(path) as z:
            fp = z["fingerprint"].tobytes().decode()
            if fp != expect_fingerprint:
                return None
            return KNNCheckpoint(
                carry_d=z["carry_d"],
                carry_i=z["carry_i"],
                tiles_done=int(z["tiles_done"]),
                fingerprint=fp,
            )
    except Exception as e:  # any unreadable state -> clean restart
        import logging

        logging.getLogger("mpi_knn_tpu").warning(
            "ignoring unreadable checkpoint %s (%s); restarting from zero",
            path,
            e,
        )
        return None


def clear_checkpoint(ckpt_dir):
    path = Path(ckpt_dir) / _STATE_FILE
    if path.exists():
        path.unlink()
