"""Force the JAX platform in axon-proof fashion.

The tunneled axon TPU plugin ignores the ``JAX_PLATFORMS`` environment
variable: a process that merely exports it still initializes the TPU
tunnel (and hangs if the device is wedged — the round-1 MULTICHIP gate
failure). The only reliable sequence is to set the env vars for any
child processes AND apply ``jax.config.update("jax_platforms", ...)``
before first device access. This is the one shared implementation for
the four places that need it (tests, the driver entry point, bench,
the CLI).
"""

from __future__ import annotations

import os
import re
import sys


def force_platform(name: str, n_devices: int | None = None) -> None:
    """Pin ``jax_platforms`` to ``name``; optionally force ``n_devices``
    virtual host devices (CPU platform only).

    Must run before the first JAX device access in this process. If jax's
    backend is already initialized the config update cannot take effect —
    that is reported loudly rather than silently proceeding on the wrong
    platform.
    """
    os.environ["JAX_PLATFORMS"] = name
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        # replace any pre-existing count: a stale smaller value would
        # starve the mesh this process is about to build
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\S+", "", flags
        )
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()

    import jax

    jax.config.update("jax_platforms", name)

    if _backend_already_initialized():
        devs = jax.devices()
        plats = {d.platform for d in devs}
        if plats != {name} or (
            n_devices is not None and len(devs) < n_devices
        ):
            raise RuntimeError(
                f"force_platform({name!r}, n_devices={n_devices}) called "
                f"after JAX initialized {len(devs)} {sorted(plats)} "
                "device(s); it must run before first device access"
            )


def _backend_already_initialized() -> bool:
    """True iff some jax backend has been brought up in this process
    (device queries would no longer honor a config change)."""
    xb = sys.modules.get("jax._src.xla_bridge")
    if xb is None:
        return False
    probe = getattr(xb, "backends_are_initialized", None)
    if probe is not None:
        return bool(probe())
    return bool(getattr(xb, "_backends", None))
