"""Timing and profiling (SURVEY.md §6 "Tracing / profiling").

The reference times only the distance phase with a ``gettimeofday`` pair
(``/root/reference/knn-serial.c:70,94-98``). With an async dispatch runtime
that approach lies: the host returns before the device finishes. PhaseTimer
therefore blocks on the phase's result arrays before reading the clock, and
optional ``jax.profiler`` traces expose MXU utilization / ICI overlap for the
ring backends.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Optional

import jax


class PhaseTimer:
    """Named wall-clock phases with device synchronization.

    Usage::

        timer = PhaseTimer()
        with timer.phase("knn"):
            result = all_knn(...)
            timer.block_on(result.dists)   # device sync inside the phase
        timer.seconds["knn"]
    """

    def __init__(self):
        self.seconds: Dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield self
        finally:
            self.seconds[name] = self.seconds.get(name, 0.0) + (
                time.perf_counter() - t0
            )

    @staticmethod
    def block_on(*arrays):
        """Wait for device work producing `arrays` — call before a phase ends
        so the measurement covers compute, not dispatch."""
        for a in arrays:
            if hasattr(a, "block_until_ready"):
                a.block_until_ready()


@contextlib.contextmanager
def profile_trace(trace_dir: Optional[str]):
    """jax.profiler trace (TensorBoard/XProf-compatible) when a dir is given."""
    if not trace_dir:
        yield
        return
    with jax.profiler.trace(trace_dir):
        yield
