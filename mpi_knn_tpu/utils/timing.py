"""Timing and profiling (SURVEY.md §6 "Tracing / profiling").

The reference times only the distance phase with a ``gettimeofday`` pair
(``/root/reference/knn-serial.c:70,94-98``). With an async dispatch runtime
that approach lies: the host returns before the device finishes. PhaseTimer
therefore blocks on the phase's result arrays before reading the clock, and
optional ``jax.profiler`` traces expose MXU utilization / ICI overlap for the
ring backends.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Optional

import jax
import numpy as np


def device_sync(*trees):
    """Genuinely wait for the device work producing these arrays.

    ``block_until_ready`` alone is not trustworthy on every transport: on
    tunneled/remote device platforms it can return once dispatch (not
    execution) completes, which makes naive timing report near-zero. Fetching
    a single element forces the runtime to materialize the result — a few
    bytes of device-to-host traffic buys an honest clock reading. This is the
    rebuild's answer to the reference's ``gettimeofday`` pair
    (``/root/reference/knn-serial.c:70,94-98``), which had the same
    measure-the-real-work intent in a synchronous world.
    """
    leaves = [
        leaf
        for tree in trees
        for leaf in jax.tree_util.tree_leaves(tree)
        if isinstance(leaf, jax.Array)
    ]
    for leaf in leaves:
        leaf.block_until_ready()
    for leaf in leaves:
        # one element from EVERY addressable shard — fetching only element
        # (0,...,0) would materialize just the shard that holds it, leaving
        # the other devices' work possibly in flight
        shards = getattr(leaf, "addressable_shards", None) or []
        datas = [s.data for s in shards] or [leaf]
        for data in datas:
            if any(dim == 0 for dim in data.shape):
                continue
            np.asarray(jax.device_get(data[(0,) * data.ndim]))


class PhaseTimer:
    """Named wall-clock phases with device synchronization.

    Usage::

        timer = PhaseTimer()
        with timer.phase("knn"):
            result = all_knn(...)
            timer.block_on(result.dists)   # device sync inside the phase
        timer.seconds["knn"]
    """

    def __init__(self):
        self.seconds: Dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield self
        finally:
            self.seconds[name] = self.seconds.get(name, 0.0) + (
                time.perf_counter() - t0
            )

    @staticmethod
    def block_on(*arrays):
        """Wait for device work producing `arrays` — call before a phase ends
        so the measurement covers compute, not dispatch."""
        device_sync(*arrays)


@contextlib.contextmanager
def profile_trace(trace_dir: Optional[str]):
    """jax.profiler trace (TensorBoard/XProf-compatible) when a dir is given."""
    if not trace_dir:
        yield
        return
    with jax.profiler.trace(trace_dir):
        yield
