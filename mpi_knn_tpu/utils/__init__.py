"""Shared utilities. Lazy (PEP 562) exports, like the package root: the
jax-free leaves (``atomicio``, ``logs``) are imported by the resilience
supervisors and the heartbeat writer — processes that must stay light
and must never touch a (possibly wedged) device transport — and an eager
``from .timing import PhaseTimer`` here would drag jax into every one of
them (and add seconds of import wall to a supervised child's first
heartbeat)."""

import importlib
import typing

_EXPORTS = {
    "PhaseTimer": "mpi_knn_tpu.utils.timing",
    "RunReport": "mpi_knn_tpu.utils.report",
}

__all__ = list(_EXPORTS)


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    return getattr(importlib.import_module(mod), name)


if typing.TYPE_CHECKING:  # pragma: no cover — static analysis only
    from mpi_knn_tpu.utils.report import RunReport  # noqa: F401
    from mpi_knn_tpu.utils.timing import PhaseTimer  # noqa: F401
