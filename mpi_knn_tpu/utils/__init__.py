from mpi_knn_tpu.utils.timing import PhaseTimer
from mpi_knn_tpu.utils.report import RunReport

__all__ = ["PhaseTimer", "RunReport"]
