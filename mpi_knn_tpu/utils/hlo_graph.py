"""Structural dependence analysis of XLA HLO text dumps.

This is the checker behind the ring-overlap artifact (VERDICT r4 #2): the
flagship claim — "in the overlap schedule the ``collective-permute`` has no
data dependence on the distance compute; in the blocking schedule it is
sequenced after it via the ``opt-barrier``" — is asserted against the HLO
XLA actually receives/produces (``scripts/dump_ring_hlo.py`` writes the
dumps, ``tests/test_hlo_overlap.py`` asserts the property), instead of
living as prose. The reference's non-blocking variant is the cautionary
tale: it *intended* overlap but MPI_Wait-ed before computing
(``/root/reference/mpi-knn-parallel_non_blocking.c:229-233``), and nothing
in its repo could have caught that — this module is the "catch it" layer.

Scope: parses the HLO text format (one instruction per line,
``%name = type opcode(operands), attrs``) into a def-use graph with call
edges (``to_apply``/``body``/``condition``/``calls``/
``called_computations``/``branch_computations``) and answers backward-
reachability queries. Both surface syntaxes are handled: the ``%``-prefixed
classic form that ``--xla_dump_hlo_as_text`` and compiled executables
print, and the bare-name form ``Lowered.compiler_ir("hlo").as_hlo_text()``
emits (``dot.8 = f32[2,2]{1,0} dot(Arg_0.5, transpose.7)``, computation
headers without parameter lists) — the lint engine
(``mpi_knn_tpu.analysis``) lowers in-process and gets the latter.

The graph is *instruction-flat*: an instruction depends on all of its
operands and on everything its called computations compute. That is exactly XLA's scheduling granularity (an op runs when its
operand instructions have produced values), so "no path" here is sound
evidence that the scheduler is free to run the two ops concurrently.

Parameter mapping is conservative: ``parameter(i)`` continues at operand
``i`` of the call site when it exists, else at *all* call-site operands.
Over-approximation only ever ADDS paths, so a negative answer ("permute
does not depend on any dot") remains sound.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

_CALLED_RE = re.compile(
    r"(?:to_apply|body|condition|calls|true_computation|false_computation)"
    r"=%?([\w.\-]+)"
)
_CONTROL_RE = re.compile(r"control-predecessors=\{([^}]*)\}")
_CALLED_SET_RE = re.compile(
    r"(?:called_computations|branch_computations)=\{([^}]*)\}"
)
_NAME_RE = re.compile(r"%([\w.\-]+)")
# bare-name form: identifiers start with a letter/underscore, so literal
# operands (`constant(1)`, `parameter(0)`, `constant(false)` — "false" is
# filtered by the unknown-name skip in backward_slice) never alias a real
# instruction, and shape tokens never appear inside operand parens there
_BARE_NAME_RE = re.compile(r"[A-Za-z_][\w.\-]*")
_COMP_HDR_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s*[({]")
_INSTR_RE = re.compile(r"^\s+(ROOT\s+)?%?([\w.\-]+)\s*=\s*(.*)$")


def _names(text: str) -> list[str]:
    """Instruction names referenced in an operand list or attribute group,
    in either surface syntax: ``%``-prefixed names when any are present,
    bare identifiers otherwise."""
    if "%" in text:
        return _NAME_RE.findall(text)
    return _BARE_NAME_RE.findall(text)


@dataclass
class Instruction:
    name: str
    opcode: str
    operands: list[str]  # names used inside the operand parens (data)
    called: list[str]  # computations referenced from attributes
    attrs: str  # raw attribute text (custom_call_target etc.)
    operand_text: str = ""  # raw operand parens content — the only place a
    # literal payload survives (e.g. ``constant(5)``: no operand NAMES, but
    # the scan-trip-count reader needs the 5)
    controls: list[str] = field(default_factory=list)  # control-predecessors
    type_str: str = ""  # raw result type text, e.g. "f32[4,8]{1,0}"
    param_index: int | None = None
    is_root: bool = False


@dataclass
class Computation:
    name: str
    is_entry: bool
    instructions: dict[str, Instruction] = field(default_factory=dict)
    root: str | None = None
    params: dict[int, str] = field(default_factory=dict)  # index -> name


@dataclass
class HloModule:
    computations: dict[str, Computation]
    # the `HloModule name, attr=..., ...` line verbatim: module-scoped
    # attributes (input_output_alias, buffer_donor, entry layout) live
    # here, not on any instruction — the donation lint (analysis R5)
    # reads them from this field
    header: str = ""

    def find(self, opcode_prefix: str) -> list[tuple[str, str]]:
        """All (computation, instruction) whose opcode starts with prefix."""
        return [
            (c.name, i.name)
            for c in self.computations.values()
            for i in c.instructions.values()
            if i.opcode.startswith(opcode_prefix)
        ]

    def instr(self, comp: str, name: str) -> Instruction:
        return self.computations[comp].instructions[name]


def _skip_balanced(s: str, i: int) -> int:
    """Index just past the group that opens at s[i] ('(' or '{')."""
    close = {"(": ")", "{": "}"}[s[i]]
    depth = 0
    for j in range(i, len(s)):
        if s[j] == s[i]:
            depth += 1
        elif s[j] == close:
            depth -= 1
            if depth == 0:
                return j + 1
    return len(s)


def _parse_rhs(rhs: str) -> tuple[str, str, str, str]:
    """Split an instruction's right-hand side into (type_text, opcode,
    operand_text, attr_text). The type prefix is either a parenthesised
    tuple type or a space-free token; the opcode is the identifier right
    before the operand parens."""
    i = 0
    rhs = rhs.strip()
    if rhs.startswith("("):  # tuple type
        i = _skip_balanced(rhs, 0)
    else:  # e.g. f32[8,16]{1,0} — no spaces
        while i < len(rhs) and not rhs[i].isspace():
            i += 1
    type_text = rhs[:i]
    rest = rhs[i:].lstrip()
    m = re.match(r"([\w\-]+)\(", rest)
    if not m:
        return type_text, rest.split("(")[0].strip(), "", ""
    opcode = m.group(1)
    start = m.end() - 1
    end = _skip_balanced(rest, start)
    return type_text, opcode, rest[start + 1 : end - 1], rest[end:]


def parse_hlo(text: str) -> HloModule:
    comps: dict[str, Computation] = {}
    cur: Computation | None = None
    header = ""
    for line in text.splitlines():
        if cur is None:
            if not header and line.startswith("HloModule"):
                header = line.rstrip()
                continue
            m = _COMP_HDR_RE.match(line)
            if m and line.rstrip().endswith("{"):
                cur = Computation(name=m.group(2), is_entry=bool(m.group(1)))
            continue
        # computations close with "}" or "} // name" (some printers echo
        # the computation name as a trailing comment)
        if line.strip().startswith("}"):
            comps[cur.name] = cur
            cur = None
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        is_root, name, rhs = bool(m.group(1)), m.group(2), m.group(3)
        type_text, opcode, operand_text, attrs = _parse_rhs(rhs)
        # control-predecessors are scheduling edges, not dataflow — but for
        # "is the scheduler free to run these concurrently" they count
        # exactly like operands (scheduled/post-opt TPU dumps emit them).
        # Kept SEPARATE from data operands so the tuple-element-precise
        # traversal cannot accidentally drop them when it follows only one
        # data element (they are pushed flat on every visit).
        control = [
            n
            for grp in _CONTROL_RE.findall(attrs)
            for n in _names(grp)
        ]
        instr = Instruction(
            name=name,
            opcode=opcode,
            operands=_names(operand_text),
            operand_text=operand_text,
            controls=control,
            called=_CALLED_RE.findall(attrs)
            + [
                n
                for grp in _CALLED_SET_RE.findall(attrs)
                for n in _names(grp)
            ],
            attrs=attrs,
            type_str=type_text,
            is_root=is_root,
        )
        if opcode == "parameter":
            pm = re.match(r"\s*(\d+)", operand_text)
            instr.param_index = int(pm.group(1)) if pm else None
            if instr.param_index is not None:
                cur.params[instr.param_index] = name
        cur.instructions[name] = instr
        if is_root:
            cur.root = name
    return HloModule(computations=comps, header=header)


def _call_sites(module: HloModule) -> dict[str, list[tuple[str, str]]]:
    sites: dict[str, list[tuple[str, str]]] = {}
    for c in module.computations.values():
        for i in c.instructions.values():
            for callee in i.called:
                sites.setdefault(callee, []).append((c.name, i.name))
    return sites


_GTE_IDX_RE = re.compile(r"index=(\d+)")


def backward_slice(
    module: HloModule, comp: str, name: str
) -> set[tuple[str, str]]:
    """Every (computation, instruction) the given instruction transitively
    depends on, crossing call boundaries in both directions (into called
    computations via their roots; out of parameters via call sites).

    Tuple-element precision: a ``get-tuple-element(t), index=i`` depends
    on element i only, tracked as a pending index stack through ``tuple``
    instructions and across ``parameter`` -> call-site hops. Without this,
    a permute inside a scan's while body would drag the ENTIRE loop-init
    tuple into its slice (every operand of the init, not just the block
    element it actually reads) and report spurious compute witnesses.
    The precision is still the correct scheduling model — an op starts
    when its operand VALUES are ready, and a gte's value is its element —
    with two deliberate flat exceptions where the op really does wait on
    everything: ``opt-barrier`` (waiting on all operands is its entire
    purpose) and instructions with called computations (a while's output
    exists only after the whole body ran). Any shape the tracker does not
    understand falls back to flat, so unknown patterns over-approximate
    (adds paths) rather than hide dependence.

    Two soundness details (both found by review, pinned in tests):

    - Loop carries ARE modeled: a while-body parameter continues at the
      body ROOT (same element index) as well as at the call-site init,
      because at iteration j>0 the parameter's value is the previous
      iteration's root element — a permute reading a compute-derived
      carry element must not be certified dependence-free. The cycle this
      creates terminates via the (comp, instr, index) visited set.
    - ``control-predecessors`` edges are pushed flat on EVERY visit,
      including the element-precise gte/tuple fast paths — they are
      scheduling edges and must never be dropped by value tracking."""
    sites = _call_sites(module)
    seen: set[tuple[str, str]] = set()
    visited: set[tuple[str, str, tuple[int, ...]]] = set()
    work: list[tuple[str, str, tuple[int, ...]]] = [(comp, name, ())]
    while work:
        c, n, idx = work.pop()
        if (c, n, idx) in visited:
            continue
        if n not in module.computations[c].instructions:
            continue
        visited.add((c, n, idx))
        seen.add((c, n))
        instr = module.instr(c, n)
        for ctrl in instr.controls:  # scheduling edges: always, flat
            work.append((c, ctrl, ()))

        if instr.opcode == "get-tuple-element" and instr.operands:
            m = _GTE_IDX_RE.search(instr.attrs)
            if m:
                work.append((c, instr.operands[0], (int(m.group(1)),) + idx))
                continue
        if instr.opcode == "tuple" and idx:
            if idx[0] < len(instr.operands):
                work.append((c, instr.operands[idx[0]], idx[1:]))
                continue
            # malformed index: fall through to flat

        if instr.opcode == "parameter":
            # keep the pending element index across the call boundary so a
            # body parameter resolves to the matching init element
            for sc, sn in sites.get(c, ()):
                caller = module.instr(sc, sn)
                pi = instr.param_index
                if caller.opcode == "conditional":
                    # A conditional's operand 0 is the PREDICATE/branch
                    # index; branch b's computation receives call-site
                    # operand b+1 (same layout for the indexed
                    # branch_computations form and true/false_computation).
                    # Mapping parameter(0) to operand pi==0 pointed the
                    # branch argument at the predicate — a missed
                    # dependence (ADVICE r5), i.e. an UNDER-approximation,
                    # the one direction the module contract forbids: a
                    # permute inside a branch could be falsely certified
                    # compute-independent. Branch computations take exactly
                    # one parameter, so parameter(0) is the only shape with
                    # a precise target; anything else (and a branch whose
                    # operand is missing) goes conservative-flat like the
                    # comparator path.
                    branch_args = [
                        caller.operands[bi + 1]
                        for bi, callee in enumerate(caller.called)
                        if callee == c and bi + 1 < len(caller.operands)
                    ]
                    if pi == 0 and branch_args:
                        for o in branch_args:
                            work.append((sc, o, idx))
                        # the branch body cannot issue before the
                        # predicate/branch index is computed — a scheduling
                        # edge every instruction in the branch inherits;
                        # dropping it would be the same under-approximation
                        # in a different operand (a permute in a branch
                        # whose PREDICATE derives from the compute)
                        if caller.operands:
                            work.append((sc, caller.operands[0], ()))
                    else:
                        for o in caller.operands:
                            work.append((sc, o, ()))
                    continue
                if pi is not None and pi < len(caller.operands):
                    work.append((sc, caller.operands[pi], idx))
                else:  # comparator/arity mismatch: conservative, flat
                    for o in caller.operands:
                        work.append((sc, o, ()))
                if caller.opcode == "while":
                    # loop carry: at iteration j>0 this parameter is the
                    # previous iteration's body-root element
                    body = module.computations.get(c)
                    if body and body.root:
                        work.append((c, body.root, idx))
            continue

        if idx and instr.opcode == "call" and instr.called:
            # pre-opt `call` boundaries vanish under inlining, so the
            # call's output element IS the callee root's element — keep
            # the pending index (the callee's dependence on the call
            # operands still flows through its parameters). Without this,
            # gte(call_result, k) falls to the flat branch and drags the
            # WHOLE callee body (dot included) into every slice that
            # crosses a call — e.g. the scan body's rotated-block element.
            # `fusion` and `while` deliberately stay flat below: in the
            # post-opt module they are real scheduling units whose outputs
            # wait on the entire body.
            for callee in instr.called:
                callee_comp = module.computations.get(callee)
                if callee_comp and callee_comp.root:
                    work.append((callee, callee_comp.root, idx))
            continue

        # ordinary instruction (or opt-barrier / caller of computations):
        # flat — all operands, whole called bodies
        for o in instr.operands:
            work.append((c, o, ()))
        for callee in instr.called:
            callee_comp = module.computations.get(callee)
            if callee_comp and callee_comp.root:
                work.append((callee, callee_comp.root, ()))
    return seen


def slice_opcodes(module: HloModule, sl: set[tuple[str, str]]) -> set[str]:
    """Opcodes present in a slice; custom-calls are tagged with their
    target (``custom-call:TopK``) so compute kernels stay identifiable."""
    out = set()
    for c, n in sl:
        i = module.instr(c, n)
        if i.opcode == "custom-call":
            tm = re.search(r'custom_call_target="([^"]+)"', i.attrs)
            out.add(f"custom-call:{tm.group(1)}" if tm else i.opcode)
        else:
            out.add(i.opcode)
    return out


def __getattr__(name):  # pragma: no cover - transitional import shim
    # The overlap RULE (COMPUTE_WITNESS / permute_dependence_report /
    # property_holds) moved to mpi_knn_tpu.analysis.rules when the
    # single-purpose checker grew into the lint engine; this module is the
    # parsing core only. Lazy so the analysis package (which imports this
    # module) creates no cycle.
    if name in (
        "COMPUTE_WITNESS",
        "permute_dependence_report",
        "property_holds",
    ):
        from mpi_knn_tpu.analysis import rules as _rules

        return getattr(_rules, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
