"""Zero-copy reads of ``.npz`` index artifacts.

``np.load`` on an ``.npz`` decompress-copies every member into fresh host
memory before the first byte reaches the device — for a multi-GB bucket
store that is a full extra corpus copy and a fully serialized read.
``np.savez`` (the index writer, ``ivf/index.py``) stores members
UNCOMPRESSED, so each member's ``.npy`` payload sits contiguous inside
the zip: this module locates it (zip local-file header + npy header
parsing) and hands back an ``np.memmap`` view straight into the file.
Nothing is read until someone touches the pages — which is exactly
``jax.device_put`` consuming them during index load, so the disk read,
the host "copy", and the H2D transfer collapse into one pass, and the
kernel's readahead overlaps it with whatever else cold start is doing
(the AOT-cache warm pool, ``serve/aotcache.py``). After ``device_put``
the device owns its own buffer and the mapping is dropped; the file can
be replaced at any time (the index save path's atomic-rename convention
keeps even that safe).

Strictness: a member this module cannot map — compressed (someone used
``savez_compressed``), object dtype, malformed headers — raises
``ValueError`` rather than quietly falling back to a hidden full read;
the CALLER (``load_ivf_index``) owns the loud fallback to ``np.load``.
"""

from __future__ import annotations

import os
import zipfile

import numpy as np

# zip local-file-header layout (PKZIP appnote 4.3.7): fixed 30 bytes,
# then filename and extra field — the extra field here may differ from
# the central directory's, so the data offset MUST come from this header
_LOCAL_HEADER_LEN = 30
_LOCAL_MAGIC = b"PK\x03\x04"


def mmap_npz(path: str | os.PathLike) -> dict[str, np.ndarray]:
    """Read-only memmapped views of every ``*.npy`` member of an
    UNCOMPRESSED ``.npz`` archive, keyed like ``np.load``'s NpzFile.
    Zero-size members come back as ordinary empty arrays (an empty
    mapping is meaningless to mmap(2))."""
    path = os.fspath(path)
    arrays: dict[str, np.ndarray] = {}
    with open(path, "rb") as f:
        with zipfile.ZipFile(f) as zf:
            members = zf.infolist()
        for info in members:
            if not info.filename.endswith(".npy"):
                continue
            key = info.filename[:-4]
            if info.compress_type != zipfile.ZIP_STORED:
                raise ValueError(
                    f"member {info.filename!r} of {path!r} is compressed "
                    "(savez_compressed?): a compressed member has no "
                    "byte-addressable payload to map"
                )
            f.seek(info.header_offset)
            hdr = f.read(_LOCAL_HEADER_LEN)
            if len(hdr) != _LOCAL_HEADER_LEN or hdr[:4] != _LOCAL_MAGIC:
                raise ValueError(
                    f"malformed zip local header for {info.filename!r} "
                    f"in {path!r}"
                )
            name_len = int.from_bytes(hdr[26:28], "little")
            extra_len = int.from_bytes(hdr[28:30], "little")
            f.seek(info.header_offset + _LOCAL_HEADER_LEN + name_len
                   + extra_len)
            shape, fortran, dtype = _read_npy_header(f, info.filename)
            if dtype.hasobject:
                raise ValueError(
                    f"member {info.filename!r} has object dtype — not a "
                    "mappable flat buffer"
                )
            if int(np.prod(shape)) == 0:
                arrays[key] = np.empty(shape, dtype=dtype)
                continue
            arrays[key] = np.memmap(
                path, mode="r", dtype=dtype, shape=shape,
                offset=f.tell(), order="F" if fortran else "C",
            )
    return arrays


def _read_npy_header(f, member: str):
    """(shape, fortran_order, dtype) of the npy payload starting at the
    file's current position; leaves the position at the first data byte."""
    try:
        version = np.lib.format.read_magic(f)
        if version == (1, 0):
            return np.lib.format.read_array_header_1_0(f)
        if version == (2, 0):
            return np.lib.format.read_array_header_2_0(f)
        raise ValueError(f"unsupported npy format version {version}")
    except ValueError:
        raise
    except Exception as e:  # noqa: BLE001 — normalize parser errors
        raise ValueError(
            f"malformed npy header in member {member!r}: {e}"
        ) from e
