"""Shims over jax API drift, so the backends run on every jax this repo
meets (the image pins one version; dev boxes and CI images lag or lead).

Two surfaces moved between jax releases:

- ``shard_map``: graduated from ``jax.experimental.shard_map`` to
  ``jax.shard_map``. The experimental version also *checks replication
  types* by default (``check_rep=True``) but has no way to mark a
  replicated value as device-varying, so the graduated API's ``pcast``
  idiom has no equivalent — we disable the check there instead (the
  sharded out_specs still force the right physical layout).
- ``jax.lax.pcast(x, axes, to="varying")``: exists only where
  ``jax.shard_map`` does. On older jax it is a no-op (see above — the
  replication check that would need it is off).

Keep every version probe in this module: scattering ``hasattr(jax, ...)``
probes through the backends is how version skew becomes untestable.
"""

from __future__ import annotations

import jax

_NEW_SHARD_MAP = hasattr(jax, "shard_map")

if not _NEW_SHARD_MAP:
    from jax.experimental.shard_map import shard_map as _exp_shard_map


def shard_map(f, *, mesh, in_specs, out_specs):
    """``jax.shard_map`` where available, else the experimental one with
    the replication check off (no ``pcast`` exists to satisfy it)."""
    if _NEW_SHARD_MAP:
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs
        )
    return _exp_shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
    )


def axis_size(axis) -> int:
    """``jax.lax.axis_size`` where it exists; else the classic static
    ``psum(1, axis)`` idiom (jax folds a psum of a Python literal to the
    axis size at trace time, so the result is usable as a scan length)."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis)
    return jax.lax.psum(1, axis)


def pcast_varying(x, axes):
    """Mark ``x`` device-varying over ``axes`` for shard_map's replication
    checker; identity on jax versions whose checker is disabled (see
    :func:`shard_map`)."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is None:
        return x
    return pcast(x, tuple(axes), to="varying")
