"""Atomic file publication — the ONE temp + ``os.replace`` helper every
threaded (or supervised) writer of a shared artifact flows through.

Why a single helper: the repo's cross-process rendezvous files are all
read while they are written — the supervisor polls the heartbeat file
mid-overwrite, the CI gate polls ``--ready-file`` while the server
writes it, concurrent warm pools and bench children share one AOT cache
directory. A bare ``open(path, "w")`` publishes a zero-length (then
partially-written) file to every concurrent reader; ``os.replace`` of a
fully-written temp file in the SAME directory publishes either the old
content or the new, never a torn state. The host concurrency lint
(``mpi_knn_tpu.analysis.host``, rule H4) enforces exactly this: a
truncating write in a threaded module that does not flow through this
helper (or carry its own ``os.replace`` in the same function) is a
finding.

The temp file lives next to the target (``os.replace`` must not cross
filesystems) and carries pid + thread id in its name, so concurrent
writers to one path race benignly: last full write wins.

No jax import anywhere in this module (supervisors use it).
"""

from __future__ import annotations

import os
import threading


def _tmp_path(path: str) -> str:
    d, base = os.path.split(os.path.abspath(path))
    return os.path.join(
        d or ".",
        f".{base}.{os.getpid()}.{threading.get_ident()}.tmp",
    )


def atomic_write_bytes(path: str | os.PathLike[str], data: bytes) -> None:
    """Write ``data`` to ``path`` atomically: temp file in the target's
    directory, then ``os.replace``. Readers see the old file or the new
    one, never a truncated or half-written state."""
    path = os.fspath(path)
    tmp = _tmp_path(path)
    try:
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_text(
    path: str | os.PathLike[str], text: str, encoding: str = "utf-8"
) -> None:
    """:func:`atomic_write_bytes` for text content."""
    atomic_write_bytes(path, text.encode(encoding))
