"""Structured run reports (SURVEY.md §6 "Metrics / logging").

The reference's only outputs are three printfs — matches, a timing line, and
per-round debug DONEs (``/root/reference/knn-serial.c:98,130``). The rebuild
emits one JSON document per run: configuration, data provenance, per-phase
seconds, accuracy/matches, and recall against a baseline when one is given.
"""

from __future__ import annotations

import dataclasses
import json
import platform
from typing import Any, Dict, Optional

import jax
import numpy as np


def recall_at_k(got_ids: np.ndarray, want_ids: np.ndarray) -> float:
    """Fraction of baseline neighbors recovered (ignores order; ignores
    invalid (-1) baseline slots). Vectorized — a (q, k, k) broadcast per
    4096-row chunk, no per-row Python loop (the r2 set-based version cost
    minutes at SIFT scale, VERDICT r2 weak #4)."""
    got_ids = np.asarray(got_ids)
    want_ids = np.asarray(want_ids)
    hits, total = 0, 0
    for s in range(0, len(want_ids), 4096):
        g = got_ids[s : s + 4096]
        w = want_ids[s : s + 4096]
        valid = w >= 0
        found = (w[:, :, None] == g[:, None, :]).any(axis=-1) & valid
        hits += int(found.sum())
        total += int(valid.sum())
    return hits / total if total else 1.0


@dataclasses.dataclass
class RunReport:
    """One all-kNN run, serializable to a single JSON object."""

    config: Dict[str, Any]
    data_source: str
    shape: tuple
    phase_seconds: Dict[str, float] = dataclasses.field(default_factory=dict)
    matches: Optional[int] = None
    total: Optional[int] = None
    accuracy: Optional[float] = None
    recall_vs_baseline: Optional[float] = None
    backend: Optional[str] = None
    num_devices: int = 1
    notes: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def finalize(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["environment"] = {
            "jax_version": jax.__version__,
            "platform": jax.default_backend(),
            "devices": [str(x) for x in jax.devices()],
            "host": platform.node(),
        }
        return d

    def to_json(self, indent: Optional[int] = 2) -> str:
        return json.dumps(self.finalize(), indent=indent, default=str)

    def save(self, path: str):
        with open(path, "w") as f:
            f.write(self.to_json())
