"""Block-scaled integer quantization — the compression layer below bf16
(the EQuARX direction, PAPERS.md arxiv 2506.17615).

One scheme, two widths, used in two places:

- **transfer** (``KNNConfig.ring_transfer_dtype="int8"``): the corpus block
  that circulates the ring travels as (int8 codes, f32 per-row scales) —
  4× fewer ICI bytes per ppermute than f32, 2× fewer than bf16 — and is
  dequantized into each round's compress dot (backends/ring.py);
- **at rest** (``IVFIndex`` with ``dtype="int8"``/``"int4"``): the clustered
  bucket store resides as codes + scales — 4–8× less HBM than f32 — and the
  probe gather moves codes; dequantization happens after the gather, feeding
  the asymmetric distance (exact f32 queries vs dequantized candidates) in
  the compress/rerank stages (ivf/search.py, ivf/sharded.py).

The scheme is symmetric per-row block scaling: for each row (one corpus
point — the natural "block" here, because rows are the unit every gather,
permute and dot consumes),

    scale = max|row| / QMAX,     code = round(row / scale) ∈ [−QMAX, QMAX]

so the reconstruction ``code · scale`` is exact at the row's extremes and
every element's absolute error is ≤ scale/2 (round-to-nearest), which is
what ``tests/test_quant.py`` property-tests. A zero row gets scale 0 and
all-zero codes — dequantization is exactly zero, no division anywhere
(the inverse scale is computed with a ``where`` guard).

``int4`` packs two codes per int8 lane (low nibble first, two's
complement, QMAX=7 so −8 never appears and negation is involutive); the
packed axis is ``ceil(d/2)`` bytes with an implicit zero pad for odd d.
Unpacking is exact by construction (arithmetic shifts), also
property-tested.

Why scales ride OUTSIDE the quantized payload: a scale folded into the
codes (e.g. a shared exponent stolen from the mantissa bits) would make
the wire format opaque to the lint engine; as a separate f32 vector it is
one more (tiny) array on every permute/all-to-all, and rule R3 can demand
the convert-and-multiply dequant feeding each compress dot while R4
prices the payload at the wire dtype (analysis/rules.py).

Everything here is jit-compatible and shape-static; the quantize side is
normally run ONCE at shard/build time (host-eager or under jit), never
inside the rotation/search programs — re-quantizing per round would both
waste FLOPs and, in the overlap schedule, hang a reduce off the permutes'
backward slice that lint rule R1 would rightly question.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

QUANT_DTYPES = ("int8", "int4")
_QMAX = {"int8": 127, "int4": 7}


def quant_max(dtype: str) -> int:
    """Largest code magnitude of a quantized dtype (symmetric range)."""
    try:
        return _QMAX[dtype]
    except KeyError:
        raise ValueError(
            f"quantized dtype must be one of {QUANT_DTYPES}, got {dtype!r}"
        ) from None


def packed_dim(dim: int, dtype: str) -> int:
    """int8 lanes per row of a ``dim``-wide quantized row: int8 stores one
    code per lane; int4 packs two (odd dims carry one zero nibble)."""
    quant_max(dtype)
    return dim if dtype == "int8" else -(-dim // 2)


def row_wire_bytes(dim: int, dtype: str | None, itemsize: int = 4) -> int:
    """Bytes ONE corpus row's payload occupies at a given transfer/at-rest
    level (codes + its scale for quantized levels; ``itemsize`` is the
    float width for the non-quantized levels). The single pricing rule the
    obs gauges, the R4 wire budgets, and the sharded exchange accounting
    all share — hand-copied byte math would drift."""
    if dtype in QUANT_DTYPES:
        return packed_dim(dim, dtype) + 4  # int8 lanes + one f32 scale
    return dim * itemsize


def pack_int4(codes: jax.Array) -> jax.Array:
    """Pack int8 codes in [−7, 7] two-per-byte along the last axis (low
    nibble = even index). Odd-width rows pad with a zero nibble."""
    d = codes.shape[-1]
    if d % 2:
        codes = jnp.concatenate(
            [codes, jnp.zeros(codes.shape[:-1] + (1,), codes.dtype)], axis=-1
        )
    lo = codes[..., 0::2]
    hi = codes[..., 1::2]
    # two's-complement nibbles: keep the low 4 bits of each signed code
    return ((lo & 0x0F) | (hi << 4)).astype(jnp.int8)


def unpack_int4(packed: jax.Array, dim: int) -> jax.Array:
    """Exact inverse of :func:`pack_int4`: (…, ceil(dim/2)) int8 lanes →
    (…, dim) int8 codes (sign-extended via arithmetic shifts)."""
    packed = packed.astype(jnp.int8)
    lo = (packed << 4) >> 4  # arithmetic shift sign-extends the low nibble
    hi = packed >> 4
    out = jnp.stack([lo, hi], axis=-1).reshape(
        packed.shape[:-1] + (2 * packed.shape[-1],)
    )
    return out[..., :dim]


def quantize_rows(x: jax.Array, dtype: str = "int8"):
    """Symmetric per-row block quantization: (…, d) float → ((…, pd) int8
    codes, (…,) f32 scales) with ``pd = packed_dim(d, dtype)``.

    Max abs reconstruction error is scale/2 per element; zero rows give
    scale 0 and exact-zero dequantization."""
    qmax = quant_max(dtype)
    x = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x), axis=-1)
    scale = amax / qmax
    inv = jnp.where(amax > 0, qmax / jnp.where(amax > 0, amax, 1.0), 0.0)
    codes = jnp.clip(
        jnp.round(x * inv[..., None]), -qmax, qmax
    ).astype(jnp.int8)
    if dtype == "int4":
        codes = pack_int4(codes)
    return codes, scale


def dequantize_rows(
    codes: jax.Array, scales: jax.Array, dtype: str, dim: int
) -> jax.Array:
    """(…, pd) int8 codes + (…,) scales → (…, dim) f32 rows. This is THE
    dequant the lint contract (rule R3) looks for: one convert out of the
    integer domain and one multiply by the scale, feeding the distance
    dots — a dot consuming raw codes without its scale is a finding."""
    quant_max(dtype)
    if dtype == "int4":
        codes = unpack_int4(codes, dim)
    return codes.astype(jnp.float32) * scales[..., None]
