"""Fused distance + top-k Pallas kernel (SURVEY.md §8 step 5).

The XLA path materializes each (query_tile × corpus_tile) distance block in
HBM between the matmul and the top_k. This kernel keeps the block in VMEM:
one MXU matmul computes ``q_sq + c_sq − 2·Q·Cᵀ`` for the tile, masking and a
k-pass iterative min-extraction run on the VPU, and only the (q_tile, k)
survivors leave chip memory — an O(corpus_tile/k) reduction in HBM traffic
for the selection phase.

Per grid cell (qi, ci) the kernel emits that corpus tile's local top-k into
an (n_c, Q, k) output; the cheap cross-tile merge (k·n_c candidates per
query) stays in XLA (ops.topk.smallest_k). Global candidate ids are derived
from ``pl.program_id`` + iota (no id operands — Mosaic block shapes stay
MXU/VPU-aligned). Runs compiled on TPU (Mosaic), interpreted elsewhere, so CI
exercises the same kernel body on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpi_knn_tpu.types import INVALID_ID

_ZERO_RTOL = 1e-6  # matches ops.topk._ZERO_RTOL_DEFAULT (f32 path)
_I32_MAX = jnp.iinfo(jnp.int32).max


def _k_smallest_sweep(d, cand_ids, k, col_offset=None):
    """k-pass min extraction on the VPU: find each row's minimum, record it,
    knock it out, repeat — the in-register replacement for qsort-per-insert.
    ``d`` (q, c) masked distances, ``cand_ids`` (q, c) global candidate ids —
    or None with ``col_offset`` set when ids are affine in the column
    (``id = col_offset + col``, the tile-extraction case): then the winning
    id is ``first_col + col_offset`` directly and the per-round gather-style
    masked-max reduction over the full tile is skipped (~1/3 of the VPU
    passes in the unrolled loop).
    Returns ((q, k) dists, (q, k) ids), ascending; ties broken by the
    leftmost column (the reference's first-encountered-wins scan order).
    """
    q, c = d.shape
    col = jax.lax.broadcasted_iota(jnp.int32, (q, c), 1)
    dists_out, ids_out = [], []
    for _ in range(k):
        row_min = jnp.min(d, axis=1, keepdims=True)  # (q, 1)
        is_min = d == row_min
        first_col = jnp.min(
            jnp.where(is_min, col, _I32_MAX), axis=1, keepdims=True
        )
        hit = col == first_col
        if cand_ids is None:
            ids_j = first_col[:, 0] + col_offset
        else:
            ids_j = jnp.max(jnp.where(hit, cand_ids, INVALID_ID), axis=1)
        dists_out.append(row_min[:, 0])
        # ~isfinite, not isinf: a NaN row (inf inputs upstream) has all-False
        # is_min, so first_col saturates at _I32_MAX — the affine path would
        # wrap it into a garbage id where the masked-max path naturally gave
        # INVALID_ID
        ids_out.append(
            jnp.where(jnp.isfinite(row_min[:, 0]), ids_j, INVALID_ID)
        )
        d = jnp.where(hit, jnp.inf, d)
    return jnp.stack(dists_out, axis=1), jnp.stack(ids_out, axis=1)


def _masked_tile_dists(
    q, c, qi, ci, q_tile, c_tile, m_corpus, exclude_self, exclude_zero,
    all_pairs, zero_eps, precision, compress=False,
):
    """(q_tile, c_tile) masked squared-L2 distances + global candidate ids —
    the kernel-side mirror of ops.distance.pairwise_sq_l2 + ops.topk.mask_tile.

    ``compress=True`` is the mixed-precision policy's pass 1 (ops/rerank.py):
    the dot runs single-pass on explicitly bf16-rounded operands (DEFAULT
    precision, f32 accumulation — the explicit cast makes CPU interpret runs
    measure the same rounding the MXU applies), and the zero-by-value mask
    is SKIPPED — compressed values are preselect keys only; the exact-finish
    rerank re-applies zero-exclusion on exact distances. Padding and self
    masks are id-based (precision-independent) and stay."""
    q_sq = jnp.sum(q * q, axis=-1, keepdims=True)  # (q_tile, 1)
    c_sq = jnp.sum(c * c, axis=-1, keepdims=True).T  # (1, c_tile)
    # MXU: one matmul per tile; f32 accumulation
    xy = jax.lax.dot_general(
        q.astype(jnp.bfloat16) if compress else q,
        c.astype(jnp.bfloat16) if compress else c,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT if compress else precision,
    )
    d = jnp.maximum(q_sq - 2.0 * xy + c_sq, 0.0)

    col = jax.lax.broadcasted_iota(jnp.int32, (q_tile, c_tile), 1)
    col_global = ci * c_tile + col  # candidate global ids
    invalid = col_global >= m_corpus  # divisibility padding rows
    if exclude_zero and not compress:
        # same semantics as ops.topk.mask_tile: explicit absolute eps wins,
        # else relative to the pair magnitude
        thresh = zero_eps if zero_eps > 0.0 else _ZERO_RTOL * (q_sq + c_sq)
        invalid = invalid | (d <= thresh)
    if exclude_self and all_pairs:
        row = jax.lax.broadcasted_iota(jnp.int32, (q_tile, c_tile), 0)
        row_global = qi * q_tile + row  # query global ids (all-pairs mode)
        invalid = invalid | (col_global == row_global)
    return jnp.where(invalid, jnp.inf, d), col_global


def _fused_knn_kernel(
    q_ref,  # (q_tile, d) queries
    c_ref,  # (c_tile, d) corpus tile
    outd_ref,  # (1, q_tile, k) tile-local k smallest distances
    outi_ref,  # (1, q_tile, k) their global corpus ids
    *,
    k: int,
    q_tile: int,
    c_tile: int,
    m_corpus: int,  # real (unpadded) corpus rows; >= id means padding
    exclude_self: bool,
    exclude_zero: bool,
    all_pairs: bool,
    zero_eps: float,  # >0: absolute threshold; 0: relative (rtol · scale)
    precision,
    compress: bool,  # mixed policy pass 1: bf16 DEFAULT dot, zero-mask off
):
    qi = pl.program_id(0)
    ci = pl.program_id(1)
    d, _ = _masked_tile_dists(
        q_ref[:], c_ref[:], qi, ci, q_tile, c_tile, m_corpus,
        exclude_self, exclude_zero, all_pairs, zero_eps, precision,
        compress=compress,
    )
    # ids are affine in the column within a tile -> affine fast path
    outd_ref[0], outi_ref[0] = _k_smallest_sweep(
        d, None, k, col_offset=ci * c_tile
    )


def _fused_knn_sweep_kernel(
    q_ref,  # (q_tile, d) queries
    c_ref,  # (c_tile, d) corpus tile
    outd_ref,  # (q_tile, k) FINAL k smallest distances (written last step)
    outi_ref,  # (q_tile, k)
    cd_ref,  # VMEM scratch: (q_tile, k) running carry distances
    ci_ref,  # VMEM scratch: (q_tile, k) running carry ids
    *,
    k: int,
    q_tile: int,
    c_tile: int,
    m_corpus: int,
    exclude_self: bool,
    exclude_zero: bool,
    all_pairs: bool,
    zero_eps: float,
    precision,
    compress: bool,
):
    """Sweep variant: TPU grid cells execute SEQUENTIALLY, so for a fixed
    query tile the corpus-tile loop (minor grid axis) carries the running
    top-k in VMEM scratch. Only the final (q_tile, k) leaves the kernel —
    no per-tile candidate lists in HBM and no XLA-side cross-tile merge."""
    qi = pl.program_id(0)
    ci = pl.program_id(1)
    n_c = pl.num_programs(1)

    d, _ = _masked_tile_dists(
        q_ref[:], c_ref[:], qi, ci, q_tile, c_tile, m_corpus,
        exclude_self, exclude_zero, all_pairs, zero_eps, precision,
        compress=compress,
    )
    new_d, new_i = _k_smallest_sweep(d, None, k, col_offset=ci * c_tile)

    @pl.when(ci == 0)
    def _first():
        # first tile: the carry IS this tile's top-k (merging against an
        # all-inf init would just burn k extra extraction passes)
        cd_ref[:] = new_d
        ci_ref[:] = new_i

    @pl.when(ci > 0)
    def _merge():
        # merge carry + new: 2k candidates per row, k-pass extract again —
        # always EXACT (cfg.topk_method's approx option applies only to the
        # tiles variant's XLA-side merge). Carry ids come from earlier
        # (lower-id) tiles, disjoint from this tile's, so plain concat is a
        # valid candidate multiset and carry-first preserves the
        # first-encountered-wins tie order.
        all_d = jnp.concatenate([cd_ref[:], new_d], axis=1)
        all_i = jnp.concatenate([ci_ref[:], new_i], axis=1)
        merged_d, merged_i = _k_smallest_sweep(all_d, all_i, k)
        cd_ref[:] = merged_d
        ci_ref[:] = merged_i

    @pl.when(ci == n_c - 1)
    def _emit():
        outd_ref[:] = cd_ref[:]
        outi_ref[:] = ci_ref[:]


def fused_knn_tiles(
    queries: jax.Array,  # (Q, d), Q % q_tile == 0 (padded)
    corpus: jax.Array,  # (C, d), C % c_tile == 0 (padded)
    m_corpus: int,  # real corpus rows (<= C)
    k: int,
    q_tile: int,
    c_tile: int,
    exclude_self: bool = True,
    exclude_zero: bool = True,
    all_pairs: bool = True,
    zero_eps: float = 0.0,
    precision=None,
    compress: bool = False,
    interpret: bool | None = None,
):
    """Per-(query-tile, corpus-tile) local top-k.

    Returns (Q, n_c·k) dists and global ids, ready for one cross-tile merge.
    """
    Q, dim = queries.shape
    C = corpus.shape[0]
    if Q % q_tile or C % c_tile:
        raise ValueError("caller must pad to tile multiples")
    if k > c_tile:
        raise ValueError(f"k={k} exceeds corpus_tile={c_tile}")
    n_q, n_c = Q // q_tile, C // c_tile
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    kernel = functools.partial(
        _fused_knn_kernel,
        k=k,
        q_tile=q_tile,
        c_tile=c_tile,
        m_corpus=m_corpus,
        exclude_self=exclude_self,
        exclude_zero=exclude_zero,
        all_pairs=all_pairs,
        zero_eps=zero_eps,
        # recall-parity anchor, same as ops.distance: full f32 by default
        # (compress mode overrides to the bf16 DEFAULT dot in-kernel)
        precision=(
            jax.lax.Precision.HIGHEST if precision is None else precision
        ),
        compress=compress,
    )
    outd, outi = pl.pallas_call(
        kernel,
        grid=(n_q, n_c),
        in_specs=[
            pl.BlockSpec(
                (q_tile, dim), lambda qi, ci: (qi, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (c_tile, dim), lambda qi, ci: (ci, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=[
            # block's trailing dims (q_tile, k) match the array's -> no
            # lane-alignment constraint on k
            pl.BlockSpec(
                (1, q_tile, k), lambda qi, ci: (ci, qi, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, q_tile, k), lambda qi, ci: (ci, qi, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_c, Q, k), jnp.float32),
            jax.ShapeDtypeStruct((n_c, Q, k), jnp.int32),
        ],
        interpret=interpret,
    )(queries.astype(jnp.float32), corpus.astype(jnp.float32))
    # (n_c, Q, k) -> (Q, n_c·k) candidate lists per query
    outd = jnp.transpose(outd, (1, 0, 2)).reshape(Q, n_c * k)
    outi = jnp.transpose(outi, (1, 0, 2)).reshape(Q, n_c * k)
    return outd, outi


def fused_knn_sweep(
    queries: jax.Array,  # (Q, d), Q % q_tile == 0 (padded)
    corpus: jax.Array,  # (C, d), C % c_tile == 0 (padded)
    m_corpus: int,
    k: int,
    q_tile: int,
    c_tile: int,
    exclude_self: bool = True,
    exclude_zero: bool = True,
    all_pairs: bool = True,
    zero_eps: float = 0.0,
    precision=None,
    compress: bool = False,
    interpret: bool | None = None,
):
    """Full fused all-kNN in one kernel: the corpus-tile sweep runs on the
    minor grid axis with the running top-k in VMEM scratch (TPU grid cells
    are sequential), emitting only the final (Q, k). No cross-tile merge
    work outside the kernel.
    """
    Q, dim = queries.shape
    C = corpus.shape[0]
    if Q % q_tile or C % c_tile:
        raise ValueError("caller must pad to tile multiples")
    if k > c_tile:
        # not a truncation hazard (later tiles would fill the inf-padded
        # slots) but the k-pass unroll runs twice per tile here — keep the
        # contract tight and let the backend route this corner to "tiles"
        raise ValueError(f"k={k} exceeds corpus_tile={c_tile}")
    n_q, n_c = Q // q_tile, C // c_tile
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    kernel = functools.partial(
        _fused_knn_sweep_kernel,
        k=k,
        q_tile=q_tile,
        c_tile=c_tile,
        m_corpus=m_corpus,
        exclude_self=exclude_self,
        exclude_zero=exclude_zero,
        all_pairs=all_pairs,
        zero_eps=zero_eps,
        precision=(
            jax.lax.Precision.HIGHEST if precision is None else precision
        ),
        compress=compress,
    )
    return pl.pallas_call(
        kernel,
        grid=(n_q, n_c),
        in_specs=[
            pl.BlockSpec(
                (q_tile, dim), lambda qi, ci: (qi, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (c_tile, dim), lambda qi, ci: (ci, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=[
            # the same (qi, 0) block is revisited across the ci sweep and
            # written once, at ci == n_c-1
            pl.BlockSpec(
                (q_tile, k), lambda qi, ci: (qi, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (q_tile, k), lambda qi, ci: (qi, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Q, k), jnp.float32),
            jax.ShapeDtypeStruct((Q, k), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((q_tile, k), jnp.float32),
            pltpu.VMEM((q_tile, k), jnp.int32),
        ],
        interpret=interpret,
    )(queries.astype(jnp.float32), corpus.astype(jnp.float32))
