"""Pairwise distance kernels — the framework's replacement for the reference's
hot loop (SURVEY.md C4).

The reference computes ``S = Σ_j pow(Da−Db, 2)`` in a scalar triple loop
(``/root/reference/knn-serial.c:72-93``) and compares ``sqrt(S)``. On TPU the
FLOPs belong on the MXU, so squared L2 is computed in matmul form::

    ‖x − y‖² = ‖x‖² + ‖y‖² − 2·x·yᵀ

and comparisons stay in *squared* space — sqrt is monotone, so the top-k order
is identical up to floating-point rounding (SURVEY.md §5 Q10). A float64 mode
is kept for adjudicating near-tie mismatches against the reference.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def center_for_l2(corpus, queries, all_pairs: bool):
    """Mean-center corpus (and queries consistently) before L2 distances.

    Translation leaves L2 distances unchanged, but cancellation error in the
    ‖x‖²+‖y‖²−2xy matmul form scales with the *centered* norms — centering
    keeps fp noise (and the relative zero-distance threshold, ops.topk) tight
    even when the data sits far from the origin. One shared implementation
    for api.all_knn and both resumable drivers: device-resident inputs are
    centered on device (no host bounce; f64 stays f64 when x64 is on), host
    inputs keep the f64 mean for the debug mode.

    The two paths accumulate the mean at different precisions, so centered
    values for the SAME data differ by fp noise across residencies —
    bit-identical checkpoint resume holds per-residency only, and
    ring_resumable folds the residency into the run fingerprint so a
    cross-residency resume restarts rather than merging mixed carries.
    """
    if isinstance(corpus, jax.Array):
        acc = jnp.float64 if corpus.dtype == jnp.float64 else jnp.float32
        mu = jnp.mean(corpus, axis=0, dtype=acc)
    else:
        mu = np.asarray(corpus, dtype=np.float64).mean(axis=0)
    corpus = corpus - mu
    queries = corpus if all_pairs else queries - mu
    return corpus, queries


def _acc_dtype(x: jax.Array) -> jnp.dtype:
    """Accumulation dtype: f64 inputs accumulate in f64 (debug mode), anything
    else in f32 (bf16 inputs still get full-precision MXU accumulation)."""
    return jnp.float64 if x.dtype == jnp.float64 else jnp.float32


def _dot_precision(x: jax.Array, precision: str | None):
    """Matmul precision for the −2·X·Yᵀ term.

    TPU's MXU default truncates f32 operands to bf16, which was measured to
    cost ~0.3% recall@10 and to move self-distances from ~0 to O(1) on
    MNIST-scale data (see .claude/skills/verify/SKILL.md). Correctness is the
    anchor (recall parity vs the serial reference), so f32 inputs default to
    HIGHEST (multi-pass f32-accurate MXU); bf16 inputs keep DEFAULT — the
    caller already chose throughput over precision.
    """
    if precision is not None:
        return precision
    if x.dtype == jnp.bfloat16:
        return jax.lax.Precision.DEFAULT
    return jax.lax.Precision.HIGHEST


def sq_norms(x: jax.Array) -> jax.Array:
    """Row squared norms, accumulated at full precision. (r, d) -> (r,)."""
    acc = _acc_dtype(x)
    return jnp.sum(x.astype(acc) * x.astype(acc), axis=-1)


def pairwise_sq_l2(
    x: jax.Array,
    y: jax.Array,
    x_sq: jax.Array | None = None,
    y_sq: jax.Array | None = None,
    precision: str | None = None,
) -> jax.Array:
    """Squared L2 distances between all rows of x (q, d) and y (c, d) -> (q, c).

    The −2·X·Yᵀ term is a single MXU matmul (``preferred_element_type`` forces
    f32/f64 accumulation even for bf16 inputs). Precomputed squared norms may
    be passed in so tiled callers hoist them out of the tile loop.
    """
    acc = _acc_dtype(x)
    if x_sq is None:
        x_sq = sq_norms(x)
    if y_sq is None:
        y_sq = sq_norms(y)
    xy = jax.lax.dot_general(
        x,
        y,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=acc,
        precision=_dot_precision(x, precision),
    )
    d = x_sq[:, None] - 2.0 * xy + y_sq[None, :]
    # fp cancellation can produce tiny negatives for near-identical rows
    return jnp.maximum(d, 0.0)


# Norm-squared clamp used by _l2_normalize. Any row with sq_norm <= this is
# NOT normalized to unit length (the clamp wins), so callers relying on the
# unit-row identity (pallas cosine's d² = 2·d_cos) must treat such rows as
# degenerate — guard with `sq_norms(x) <= _NORM_EPS`, not `== 0`.
_NORM_EPS = 1e-30


def _l2_normalize(x: jax.Array, eps: float = _NORM_EPS) -> jax.Array:
    acc = _acc_dtype(x)
    n = jnp.sqrt(jnp.maximum(sq_norms(x), eps)).astype(acc)
    return x.astype(acc) / n[:, None]


def pairwise_cosine(
    x: jax.Array, y: jax.Array, precision: str | None = None
) -> jax.Array:
    """Cosine *distance* (1 − cosine similarity), (q, d) × (c, d) -> (q, c).

    Normalization happens on device; the inner product is one MXU matmul.
    Range [0, 2]; smaller = more similar, so the same top-k machinery applies.
    """
    acc = _acc_dtype(x)
    xn = _l2_normalize(x)
    yn = _l2_normalize(y)
    sim = jax.lax.dot_general(
        xn,
        yn,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=acc,
        precision=_dot_precision(x, precision),
    )
    return jnp.maximum(1.0 - sim, 0.0)


def pairwise_dist(
    x: jax.Array,
    y: jax.Array,
    metric: str = "l2",
    x_sq: jax.Array | None = None,
    y_sq: jax.Array | None = None,
    precision: str | None = None,
) -> jax.Array:
    """Dispatch on metric; returns distances in sortable space (see KNNResult)."""
    if metric == "l2":
        return pairwise_sq_l2(x, y, x_sq=x_sq, y_sq=y_sq, precision=precision)
    if metric == "cosine":
        return pairwise_cosine(x, y, precision=precision)
    raise ValueError(f"unknown metric {metric!r}")
