"""Fused collective-matmul ring rotation (``KNNConfig.ring_fusion="fused"``).

The XLA-level ring (backends/ring.py) issues ``ppermute`` and the distance
compute as separate HLO ops and lets the compiler schedule them
concurrently — lint rule R1 certifies that the schedule *can* overlap, and
obs/attribution measures whether it *did*. This module moves the rotation
inside the Pallas distance kernel, the TPU-KNN/collective-matmul form: the
resident corpus block is on the MXU computing its distance tiles while an
async remote copy (``pltpu.make_async_remote_copy`` with send/recv DMA
semaphores) streams the SAME block over ICI into the next device's landing
buffer — the latency is hidden by construction, not by scheduler goodwill.

Execution forms, chosen by the driver (backends/ring.py):

- **TPU, round mode** — one fused kernel per ring round
  (:func:`fused_round_dma`). Grid is (query_tiles, block_tiles); the first
  grid cell opens a neighbor barrier (``pltpu.get_barrier_semaphore``) and
  starts the remote copies of the whole resident block — codes, scales and
  ids travel exactly as the wire format holds them (int8 codes are NOT
  dequantized before send; the dequant happens in-kernel into each round's
  compress/exact dot) — and the last grid cell waits both DMA semaphore
  sides. The landing buffers are kernel outputs in ``ANY`` (HBM) space:
  they and the resident block are the two slots of the double buffer,
  alternated by the round scan's carry threading.
- **TPU, grid mode** (``ring_fused_rotation="grid"``, behind a flag,
  :func:`fused_rotation_grid`) — the whole P-round rotation as ONE kernel
  launch with rounds on the major grid axis and the block double-buffered
  between two explicit HBM scratch slots, slot reuse gated by a
  receiver→sender capacity handshake; uni/exact, float wire only.
- **CPU interpret** (:func:`fused_block_merge`) — the same kernel body
  computes (interpret mode inlines it into the surrounding XLA program),
  transport stays a driver-level ``ppermute`` moving the identical wire
  bytes. This is the form the tier-1 parity matrix certifies: fused
  results are asserted BIT-IDENTICAL to the XLA-level ring across
  P × schedule × policy × wire dtype (tests/test_ring_fused.py).

Bit-identity is by construction, not luck: the in-kernel tile distances
use the exact expression structure of ops.distance.pairwise_sq_l2 +
ops.topk.mask_tile (same dot shape, precision, accumulation, mask
thresholds), the in-kernel carry merge is ``_k_smallest_sweep`` — bitwise
equal to ``smallest_k``'s ``lax.top_k`` (ascending order, leftmost-column
ties) — and the mixed policy's in-kernel compress pass emits preselect
POSITIONS bitwise equal to ``ops.topk.preselect_smallest`` (the
taken-mask sweep reproduces top_k's index-order hand-out on exhausted
+inf slots), so the shared XLA-side ``rerank_exact_topk`` consumes
identical survivor rows in identical order.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpi_knn_tpu.ops.pallas_knn import _I32_MAX, _ZERO_RTOL, _k_smallest_sweep
from mpi_knn_tpu.ops.quant import dequantize_rows
from mpi_knn_tpu.ops.rerank import (
    mixed_applies,
    overfetch_width,
    rerank_exact_topk,
)
from mpi_knn_tpu.ops.topk import smallest_k


def _k_smallest_positions(d, v):
    """v-pass min extraction emitting COLUMN POSITIONS, bitwise equal to
    ``ops.topk.preselect_smallest`` (= positions of ``lax.top_k(-d, v)``):
    ascending by value, ties to the leftmost column — including the
    exhausted case, where top_k hands out the remaining +inf columns in
    index order. A plain knock-out-with-inf sweep gets that last case
    wrong (it would re-pick column 0 forever), so extraction state is an
    explicit ``taken`` mask instead of overwriting the values."""
    q, c = d.shape
    col = jax.lax.broadcasted_iota(jnp.int32, (q, c), 1)
    taken = jnp.zeros((q, c), dtype=jnp.bool_)
    out = []
    for _ in range(v):
        avail = jnp.where(taken, jnp.inf, d)
        row_min = jnp.min(avail, axis=1, keepdims=True)
        # when row_min is +inf every un-taken column compares equal to it,
        # so first_col degrades exactly to "first un-taken column" — the
        # top_k exhausted-slot order
        is_min = jnp.logical_and(~taken, avail == row_min)
        first_col = jnp.min(
            jnp.where(is_min, col, _I32_MAX), axis=1, keepdims=True
        )
        out.append(first_col[:, 0])
        taken = jnp.logical_or(taken, col == first_col)
    return jnp.stack(out, axis=1)


def _load_wire_tile(blk, scl, wire_dtype: str | None, dim: int):
    """The in-kernel arrival of one resident-block tile: exactly the cast
    the XLA ring applies once per round (backends/ring.py compute()) —
    int8 codes·scale dequant, bf16 upcast, f32 passthrough — so the rows
    every dot consumes are bitwise the XLA path's."""
    if wire_dtype == "int8":
        return dequantize_rows(blk, scl[:, 0], "int8", dim)
    return blk.astype(jnp.float32)


def _masked_ring_tile(
    q, blk, q_ids, blk_ids, *, exclude_self, exclude_zero, zero_eps,
    precision, compress,
):
    """(q_tile, c_tile) masked squared-L2 tile of a ring block — the
    kernel-side mirror of backends.serial.masked_dist_tile (exact) and
    ops.rerank.compress_tile + its id-only mask (compress). Candidate ids
    are OPERANDS (the rotated block's global ids), not grid-affine — a
    ring block's ids are arbitrary after rotation and carry the padding
    sentinel (−1) the masks key on. ``q_ids``/``blk_ids`` arrive as
    (rows, 1) columns (TPU block shapes are 2-D)."""
    q_sq = jnp.sum(q * q, axis=-1, keepdims=True)  # (q_tile, 1)
    c_sq = jnp.sum(blk * blk, axis=-1, keepdims=True).T  # (1, c_tile)
    xy = jax.lax.dot_general(
        q.astype(jnp.bfloat16) if compress else q,
        blk.astype(jnp.bfloat16) if compress else blk,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT if compress else precision,
    )
    raw = q_sq - 2.0 * xy + c_sq
    # compress keys are never clamped (ops.rerank.compress_tile returns
    # the raw expression — a clamp would reorder near-zero preselect ties
    # vs the XLA path); the exact tile clamps like pairwise_sq_l2
    d = raw if compress else jnp.maximum(raw, 0.0)
    ids_row = blk_ids[:, 0][None, :]  # (1, c_tile)
    invalid = ids_row < 0  # divisibility-padding sentinel rows
    if exclude_zero and not compress:
        # same semantics as ops.topk.mask_tile: explicit absolute eps
        # wins, else relative to the pair magnitude q_sq + c_sq
        thresh = zero_eps if zero_eps > 0.0 else _ZERO_RTOL * (q_sq + c_sq)
        invalid = invalid | (d <= thresh)
    if exclude_self:
        invalid = invalid | (ids_row == q_ids[:, 0][:, None])
    return jnp.where(invalid, jnp.inf, d)


def _exact_merge_body(
    q_ref, qid_ref, blk_ref, scl_ref, bid_ref, cind_ref, cini_ref,
    outd_ref, outi_ref, cd_ref, ci_ref,
    *, k, dim, wire_dtype, exclude_self, exclude_zero, zero_eps, precision,
):
    """One ring round's exact-policy block merge: for a fixed query tile
    the block-tile sweep (minor grid axis, sequential on TPU) threads the
    running top-k through VMEM scratch, merging each masked tile with the
    stream semantics — concat(carry ‖ full tile), k-sweep — which is
    bitwise ``smallest_k(concat(carry, d), ..., method="exact")``."""
    ci = pl.program_id(1)
    n_c = pl.num_programs(1)

    @pl.when(ci == 0)
    def _init():
        cd_ref[:] = cind_ref[:]
        ci_ref[:] = cini_ref[:]

    blk = _load_wire_tile(
        blk_ref[:], scl_ref[:] if scl_ref is not None else None,
        wire_dtype, dim,
    )
    d = _masked_ring_tile(
        q_ref[:], blk, qid_ref[:], bid_ref[:],
        exclude_self=exclude_self, exclude_zero=exclude_zero,
        zero_eps=zero_eps, precision=precision, compress=False,
    )
    all_d = jnp.concatenate([cd_ref[:], d], axis=1)
    all_i = jnp.concatenate(
        [ci_ref[:], jnp.broadcast_to(bid_ref[:][:, 0][None, :], d.shape)],
        axis=1,
    )
    md, mi = _k_smallest_sweep(all_d, all_i, k)
    cd_ref[:] = md
    ci_ref[:] = mi

    @pl.when(ci == n_c - 1)
    def _emit():
        outd_ref[:] = cd_ref[:]
        outi_ref[:] = ci_ref[:]


def _make_exact_kernel(quantized, **kw):
    """Positional-signature adapters: pallas passes refs positionally, so
    the quantized form has a scale ref slot and the float form must not."""
    if quantized:
        def kern(q, qid, blk, scl, bid, cind, cini, outd, outi, cd, ci_):
            _exact_merge_body(
                q, qid, blk, scl, bid, cind, cini, outd, outi, cd, ci_,
                **kw,
            )
    else:
        def kern(q, qid, blk, bid, cind, cini, outd, outi, cd, ci_):
            _exact_merge_body(
                q, qid, blk, None, bid, cind, cini, outd, outi, cd, ci_,
                **kw,
            )
    return kern


def _compress_body(
    q_ref, qid_ref, blk_ref, scl_ref, bid_ref, pos_ref,
    *, ov, dim, wire_dtype, exclude_self,
):
    """Mixed policy pass 1, in-kernel: the bf16 DEFAULT compress dot over
    the (dequantized) block tile, id-only masking, and the top-ov
    preselect POSITIONS out — bitwise ``preselect_smallest`` of
    ops.rerank.compress_rerank_tile. The survivors' exact rerank and the
    carry merge stay in the shared XLA code (fused_block_merge below), so
    the carry algebra cannot drift from the XLA ring's."""
    blk = _load_wire_tile(
        blk_ref[:], scl_ref[:] if scl_ref is not None else None,
        wire_dtype, dim,
    )
    d_lo = _masked_ring_tile(
        q_ref[:], blk, qid_ref[:], bid_ref[:],
        exclude_self=exclude_self, exclude_zero=False, zero_eps=0.0,
        precision=None, compress=True,
    )
    pos_ref[0] = _k_smallest_positions(d_lo, ov)


def _make_compress_kernel(quantized, **kw):
    if quantized:
        def kern(q, qid, blk, scl, bid, pos):
            _compress_body(q, qid, blk, scl, bid, pos, **kw)
    else:
        def kern(q, qid, blk, bid, pos):
            _compress_body(q, qid, blk, None, bid, pos, **kw)
    return kern


def _exact_precision(cfg):
    """The exact-policy dot precision, resolved the way ops.distance does
    for f32 inputs (fused requires dtype='float32'): HIGHEST unless
    explicitly overridden."""
    if cfg.matmul_precision is None:
        return jax.lax.Precision.HIGHEST
    return {
        "default": jax.lax.Precision.DEFAULT,
        "high": jax.lax.Precision.HIGH,
        "highest": jax.lax.Precision.HIGHEST,
    }[cfg.matmul_precision]


def _wire_operands(queries, query_ids, block, block_ids, block_scale,
                   quantized):
    q_local = queries.shape[0]
    b = block.shape[0]
    qid2 = query_ids.astype(jnp.int32).reshape(q_local, 1)
    bid2 = block_ids.astype(jnp.int32).reshape(b, 1)
    operands = [queries.astype(jnp.float32), qid2, block]
    if quantized:
        operands.append(block_scale.astype(jnp.float32).reshape(b, 1))
    operands.append(bid2)
    return operands


def _wire_in_specs(q_tile, c_tile, dim, pd, quantized):
    """Input BlockSpecs shared by the round kernels: queries pinned per
    query tile, the block swept on the minor grid axis."""
    specs = [
        pl.BlockSpec((q_tile, dim), lambda qi, ci: (qi, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((q_tile, 1), lambda qi, ci: (qi, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((c_tile, pd), lambda qi, ci: (ci, 0),
                     memory_space=pltpu.VMEM),
    ]
    if quantized:
        specs.append(
            pl.BlockSpec((c_tile, 1), lambda qi, ci: (ci, 0),
                         memory_space=pltpu.VMEM)
        )
    specs.append(
        pl.BlockSpec((c_tile, 1), lambda qi, ci: (ci, 0),
                     memory_space=pltpu.VMEM)
    )
    return specs


def fused_block_merge(
    queries: jax.Array,  # (q_local, d) f32
    query_ids: jax.Array,  # (q_local,)
    block: jax.Array,  # (b, d) at the wire dtype (int8: (b, pd) codes)
    block_ids: jax.Array,  # (b,)
    block_scale: jax.Array | None,  # (b,) f32, int8 wire only
    carry_d: jax.Array,  # (q_local, k) f32
    carry_i: jax.Array,  # (q_local, k) i32
    *,
    cfg,
    q_tile: int,
    c_tile: int,
    interpret: bool | None = None,
):
    """Merge one resident ring block into the carry through the fused
    kernel — the ``ring_fusion="fused"`` replacement for the XLA ring's
    per-round compute() (backends/ring.py). Compute-only: transport is
    the caller's (driver-level ppermute under interpret; on TPU the
    driver uses :func:`fused_round_dma`, whose kernel owns transport and
    shares this body's merge).

    Returns the merged ((q_local, k) dists, ids)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    q_local, dim = queries.shape
    b, pd = block.shape
    if q_local % q_tile or b % c_tile:
        raise ValueError("caller must pad to tile multiples")
    n_q, n_c = q_local // q_tile, b // c_tile
    quantized = cfg.ring_transfer_dtype == "int8"
    wire_dtype = "int8" if quantized else None
    operands = _wire_operands(
        queries, query_ids, block, block_ids, block_scale, quantized
    )
    in_specs = _wire_in_specs(q_tile, c_tile, dim, pd, quantized)
    carry_spec = pl.BlockSpec(
        (q_tile, cfg.k), lambda qi, ci: (qi, 0), memory_space=pltpu.VMEM
    )

    mixed = cfg.precision_policy == "mixed" and mixed_applies(cfg.k, c_tile)
    if not mixed:
        # exact policy — and the mixed DEGENERATE case (overfetch >= tile
        # width: the compress pass could not drop a single candidate, so
        # the XLA pipeline falls back to one exact HIGHEST pass; mirror it)
        kernel = _make_exact_kernel(
            quantized,
            k=cfg.k,
            dim=dim,
            wire_dtype=wire_dtype,
            exclude_self=cfg.exclude_self,
            exclude_zero=cfg.exclude_zero,
            zero_eps=cfg.zero_eps,
            precision=_exact_precision(cfg),
        )
        out_d, out_i = pl.pallas_call(
            kernel,
            grid=(n_q, n_c),
            in_specs=in_specs + [carry_spec, carry_spec],
            out_specs=[carry_spec, carry_spec],
            out_shape=[
                jax.ShapeDtypeStruct((q_local, cfg.k), jnp.float32),
                jax.ShapeDtypeStruct((q_local, cfg.k), jnp.int32),
            ],
            scratch_shapes=[
                pltpu.VMEM((q_tile, cfg.k), jnp.float32),
                pltpu.VMEM((q_tile, cfg.k), jnp.int32),
            ],
            interpret=interpret,
        )(*operands, carry_d.astype(jnp.float32), carry_i)
        return out_d, out_i

    # mixed policy: in-kernel compress preselect, shared-XLA exact finish
    ov = overfetch_width(cfg.k, c_tile)
    kernel = _make_compress_kernel(
        quantized,
        ov=ov,
        dim=dim,
        wire_dtype=wire_dtype,
        exclude_self=cfg.exclude_self,
    )
    pos = pl.pallas_call(
        kernel,
        grid=(n_q, n_c),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, q_tile, ov), lambda qi, ci: (ci, qi, 0),
                         memory_space=pltpu.VMEM)
        ],
        out_shape=[jax.ShapeDtypeStruct((n_c, q_local, ov), jnp.int32)],
        interpret=interpret,
    )(*operands)[0]

    queries = queries.astype(jnp.float32)
    q_sq = jnp.sum(queries * queries, axis=-1)

    def merge_tile(carry, xs):
        cd, ci_ = carry
        t, tile_pos = xs  # (), (q_local, ov) tile-local positions
        pos_g = t * c_tile + tile_pos
        # gather survivors at the WIRE level and dequantize/upcast only
        # them — dequant is row-wise elementwise, so the rows are bitwise
        # the ones the XLA path gathers from its once-per-round
        # dequantized block
        if quantized:
            rows = dequantize_rows(
                jnp.take(block, pos_g, axis=0),
                jnp.take(block_scale, pos_g, axis=0),
                "int8",
                dim,
            )
        else:
            rows = jnp.take(block, pos_g, axis=0).astype(jnp.float32)
        ids_sel = jnp.take(block_ids, pos_g, axis=0)
        ld, li = rerank_exact_topk(
            queries,
            query_ids,
            q_sq,
            rows,
            ids_sel,
            None,
            cfg.k,
            metric=cfg.metric,
            exclude_self=cfg.exclude_self,
            exclude_zero=cfg.exclude_zero,
            zero_eps=cfg.zero_eps,
        )
        md, mi = smallest_k(
            jnp.concatenate([cd, ld.astype(cd.dtype)], axis=1),
            jnp.concatenate([ci_, li], axis=1),
            cfg.k,
            method="exact",
        )
        return (md, mi), None

    (out_d, out_i), _ = jax.lax.scan(
        merge_tile,
        (carry_d.astype(jnp.float32), carry_i),
        (jnp.arange(n_c), pos),
    )
    return out_d, out_i


# ---------------------------------------------------------------------------
# TPU-only transport-owning forms. These issue real remote DMAs and cannot
# run under interpret mode (a copy between devices cannot be emulated
# inside one single-device kernel evaluation) — the CPU tier certifies the
# shared compute body + identical-bytes ppermute transport instead, and
# these forms ride the next TPU bench round.
# ---------------------------------------------------------------------------

# semaphore slots of the per-round DMA kernel: one (send, recv) pair per
# traveling array — block, ids, and (int8 wire) the scale vector
_SEM_BLOCK, _SEM_IDS, _SEM_SCALE = 0, 1, 2


def _dma_round_kernel(
    q_ref, qid_ref, blk_hbm_ref, scl_hbm_ref, bid_hbm_ref,
    blk_ref, scl_ref, bid_ref, cind_ref, cini_ref,
    outd_ref, outi_ref, land_blk_ref, land_scl_ref, land_bid_ref,
    cd_ref, ci_ref, send_sem, recv_sem,
    *,
    k, dim, wire_dtype, exclude_self, exclude_zero, zero_eps, precision,
    axis_name, quantized,
):
    """Round-mode fused kernel WITH transport: grid cell (0, 0) opens a
    neighbor barrier and starts the async remote copies of the whole
    resident block (at the wire format, straight from HBM) to the ring
    successor's landing buffers; every cell runs the same exact merge as
    the interpret path; the LAST cell waits both semaphore sides — the
    ICI stream is hidden under the full (q_tiles × block_tiles) MXU
    sweep, which is the entire point of the fused form."""
    qi, ci = pl.program_id(0), pl.program_id(1)
    n_q, n_c = pl.num_programs(0), pl.num_programs(1)
    num_dev = jax.lax.axis_size(axis_name)
    my_id = jax.lax.axis_index(axis_name)
    right = jax.lax.rem(my_id + 1, num_dev)
    left = jax.lax.rem(my_id + num_dev - 1, num_dev)

    def remote_copies():
        copies = [
            pltpu.make_async_remote_copy(
                blk_hbm_ref, land_blk_ref,
                send_sem.at[_SEM_BLOCK], recv_sem.at[_SEM_BLOCK],
                device_id=(right,),
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            ),
            pltpu.make_async_remote_copy(
                bid_hbm_ref, land_bid_ref,
                send_sem.at[_SEM_IDS], recv_sem.at[_SEM_IDS],
                device_id=(right,),
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            ),
        ]
        if quantized:
            copies.append(
                pltpu.make_async_remote_copy(
                    scl_hbm_ref, land_scl_ref,
                    send_sem.at[_SEM_SCALE], recv_sem.at[_SEM_SCALE],
                    device_id=(right,),
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )
            )
        return copies

    @pl.when(jnp.logical_and(qi == 0, ci == 0))
    def _start():
        # neighbor barrier: the remote write must not land before the
        # receiver has entered the kernel (its landing buffer is a kernel
        # output — live only inside the launch)
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(barrier, inc=1, device_id=left)
        pltpu.semaphore_signal(barrier, inc=1, device_id=right)
        pltpu.semaphore_wait(barrier, 2)
        for copy in remote_copies():
            copy.start()

    _exact_merge_body(
        q_ref, qid_ref, blk_ref, scl_ref if quantized else None, bid_ref,
        cind_ref, cini_ref, outd_ref, outi_ref, cd_ref, ci_ref,
        k=k, dim=dim, wire_dtype=wire_dtype, exclude_self=exclude_self,
        exclude_zero=exclude_zero, zero_eps=zero_eps, precision=precision,
    )

    @pl.when(jnp.logical_and(qi == n_q - 1, ci == n_c - 1))
    def _wait():
        for copy in remote_copies():
            copy.wait()


def fused_round_dma(
    queries, query_ids, block, block_ids, block_scale, carry_d, carry_i,
    *, cfg, q_tile, c_tile, axis_name, collective_id=0,
):
    """TPU round-mode fused rotation step: returns
    ``(landed_block, landed_scale, landed_ids, carry_d, carry_i)`` — the
    landing buffers hold the predecessor's resident block, i.e. exactly
    what the XLA ring's ppermutes would have delivered, but streamed
    during the MXU sweep instead of scheduled beside it. Exact policy
    (the mixed compress round keeps transport at the driver until its
    DMA form is banked on hardware)."""
    q_local, dim = queries.shape
    b, pd = block.shape
    n_q, n_c = q_local // q_tile, b // c_tile
    quantized = cfg.ring_transfer_dtype == "int8"
    wire_dtype = "int8" if quantized else None

    qid2 = query_ids.astype(jnp.int32).reshape(q_local, 1)
    bid2 = block_ids.astype(jnp.int32).reshape(b, 1)
    scl2 = (
        block_scale.astype(jnp.float32).reshape(b, 1)
        if quantized
        else jnp.zeros((b, 1), jnp.float32)
    )
    kernel = functools.partial(
        _dma_round_kernel,
        k=cfg.k,
        dim=dim,
        wire_dtype=wire_dtype,
        exclude_self=cfg.exclude_self,
        exclude_zero=cfg.exclude_zero,
        zero_eps=cfg.zero_eps,
        precision=_exact_precision(cfg),
        axis_name=axis_name,
        quantized=quantized,
    )
    carry_spec = pl.BlockSpec(
        (q_tile, cfg.k), lambda qi, ci: (qi, 0), memory_space=pltpu.VMEM
    )
    any_spec = pl.BlockSpec(memory_space=pl.ANY)
    n_sems = 3 if quantized else 2
    out = pl.pallas_call(
        kernel,
        grid=(n_q, n_c),
        in_specs=[
            pl.BlockSpec((q_tile, dim), lambda qi, ci: (qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((q_tile, 1), lambda qi, ci: (qi, 0),
                         memory_space=pltpu.VMEM),
            any_spec,  # whole-block DMA sources (stay in HBM)
            any_spec,
            any_spec,
            pl.BlockSpec((c_tile, pd), lambda qi, ci: (ci, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((c_tile, 1), lambda qi, ci: (ci, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((c_tile, 1), lambda qi, ci: (ci, 0),
                         memory_space=pltpu.VMEM),
            carry_spec,
            carry_spec,
        ],
        out_specs=[carry_spec, carry_spec, any_spec, any_spec, any_spec],
        out_shape=[
            jax.ShapeDtypeStruct((q_local, cfg.k), jnp.float32),
            jax.ShapeDtypeStruct((q_local, cfg.k), jnp.int32),
            jax.ShapeDtypeStruct(block.shape, block.dtype),
            jax.ShapeDtypeStruct(scl2.shape, scl2.dtype),
            jax.ShapeDtypeStruct(bid2.shape, bid2.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((q_tile, cfg.k), jnp.float32),
            pltpu.VMEM((q_tile, cfg.k), jnp.int32),
            pltpu.SemaphoreType.DMA((n_sems,)),
            pltpu.SemaphoreType.DMA((n_sems,)),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=collective_id
        ),
        interpret=False,
    )(
        queries.astype(jnp.float32), qid2,
        block, scl2, bid2,  # DMA sources
        block, scl2, bid2,  # compute views (same arrays, blocked to VMEM)
        carry_d.astype(jnp.float32), carry_i,
    )
    out_d, out_i, land_blk, land_scl, land_bid = out
    return (
        land_blk,
        land_scl[:, 0] if quantized else None,
        land_bid[:, 0],
        out_d,
        out_i,
    )


def _grid_rotation_kernel(
    q_ref, qid_ref, blk0_ref, bid0_ref, cind_ref, cini_ref,
    outd_ref, outi_ref,
    slot_blk, slot_bid, tile_blk, tile_bid, cd_ref, ci_ref,
    stage_sem, send_sem, recv_sem, free_sem,
    *,
    k, dim, exclude_self, exclude_zero, zero_eps, precision,
    axis_name, q_tile, c_tile,
):
    """Whole-rotation variant: rounds ride the MAJOR grid axis, the block
    double-buffers between two HBM scratch slots (compute reads slot r%2
    while the remote DMA fills the successor's slot (r+1)%2) — one launch
    for the whole ring. Uni schedule, exact policy, float wire — f32 or
    bf16, upcast at the dot; config refuses int8 transfer for this form
    and the driver re-asserts it (raw codes cast without dequantization
    would be silently wrong distances).

    The running top-k carry lives in ONE (q_local, k) VMEM scratch pair
    sliced per query tile (``q_local·k·8`` bytes resident): the grid
    sweeps (r, qi, ci) with ci minor, so every query tile's carry must
    survive the other tiles' cells between its own visits — a (q_tile, k)
    scratch would be clobbered at every qi switch. Init fires per qi at
    round 0, emit per qi at the last round's last ci.

    Cross-device sync is the initial neighbor barrier plus a
    receiver→sender capacity handshake on ``free_sem``: a device's
    round-r stream overwrites its RIGHT neighbor's slot (r+1)%2, which
    that neighbor is still staging compute tiles from (its round r-1)
    until its last cell — so each device releases a slot to its LEFT
    neighbor once every round-r read of it has retired (the final
    staging copy AND its own send DMA, hence after the DMA waits), and
    the sender consumes one release before every stream after the
    first. Without it, device skew
    lets a fast sender corrupt an in-use buffer (the recv-semaphore chain
    alone only orders arrivals, not slot reuse)."""
    r, qi, ci = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    n_r, n_q, n_c = (
        pl.num_programs(0), pl.num_programs(1), pl.num_programs(2)
    )
    num_dev = jax.lax.axis_size(axis_name)
    my_id = jax.lax.axis_index(axis_name)
    right = jax.lax.rem(my_id + 1, num_dev)
    left = jax.lax.rem(my_id + num_dev - 1, num_dev)
    slot = jax.lax.rem(r, 2)
    nxt = jax.lax.rem(r + 1, 2)
    first_cell = jnp.logical_and(qi == 0, ci == 0)
    last_cell = jnp.logical_and(qi == n_q - 1, ci == n_c - 1)
    rows = pl.ds(qi * q_tile, q_tile)  # this query tile's carry slice

    @pl.when(jnp.logical_and(r == 0, first_cell))
    def _boot():
        # stage the resident block into slot 0 (local HBM→HBM copy), then
        # one whole-rotation neighbor barrier
        for src, dst in ((blk0_ref, slot_blk), (bid0_ref, slot_bid)):
            copy = pltpu.make_async_copy(src, dst.at[0], stage_sem)
            copy.start()
            copy.wait()
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(barrier, inc=1, device_id=left)
        pltpu.semaphore_signal(barrier, inc=1, device_id=right)
        pltpu.semaphore_wait(barrier, 2)

    def remote_copies():
        return [
            pltpu.make_async_remote_copy(
                slot_blk.at[slot], slot_blk.at[nxt],
                send_sem.at[_SEM_BLOCK], recv_sem.at[_SEM_BLOCK],
                device_id=(right,),
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            ),
            pltpu.make_async_remote_copy(
                slot_bid.at[slot], slot_bid.at[nxt],
                send_sem.at[_SEM_IDS], recv_sem.at[_SEM_IDS],
                device_id=(right,),
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            ),
        ]

    @pl.when(
        jnp.logical_and(r > 0, jnp.logical_and(r < n_r - 1, first_cell))
    )
    def _backpressure():
        # the slot this round's stream lands in (right neighbor's
        # (r+1)%2) was being staged from during its round r-1 — consume
        # one capacity release before overwriting it. The wait at round r
        # consumes the r-th release, so it proves the neighbor finished
        # ALL reads through its round r-1 (counting order, device skew
        # notwithstanding). Round 0 streams into a never-read slot.
        pltpu.semaphore_wait(free_sem, 1)

    @pl.when(jnp.logical_and(r < n_r - 1, first_cell))
    def _stream():
        for copy in remote_copies():
            copy.start()

    @pl.when(jnp.logical_and(r == 0, ci == 0))
    def _init():
        cd_ref[rows] = cind_ref[:]
        ci_ref[rows] = cini_ref[:]

    # stage this cell's (c_tile) compute tile out of the resident HBM slot
    # (slots live outside BlockSpec's automatic staging)
    for src, dst in (
        (slot_blk.at[slot, pl.ds(ci * c_tile, c_tile)], tile_blk),
        (slot_bid.at[slot, pl.ds(ci * c_tile, c_tile)], tile_bid),
    ):
        copy = pltpu.make_async_copy(src, dst, stage_sem)
        copy.start()
        copy.wait()

    d = _masked_ring_tile(
        q_ref[:], tile_blk[:].astype(jnp.float32), qid_ref[:], tile_bid[:],
        exclude_self=exclude_self, exclude_zero=exclude_zero,
        zero_eps=zero_eps, precision=precision, compress=False,
    )
    all_d = jnp.concatenate([cd_ref[rows], d], axis=1)
    all_i = jnp.concatenate(
        [ci_ref[rows], jnp.broadcast_to(tile_bid[:][:, 0][None, :], d.shape)],
        axis=1,
    )
    md, mi = _k_smallest_sweep(all_d, all_i, k)
    cd_ref[rows] = md
    ci_ref[rows] = mi

    @pl.when(jnp.logical_and(r < n_r - 1, last_cell))
    def _wait():
        for copy in remote_copies():
            copy.wait()

    @pl.when(jnp.logical_and(r < n_r - 2, last_cell))
    def _release():
        # ALL of round r's reads of slot r%2 are now retired — the last
        # staging copy above and (order matters: this sits AFTER _wait's
        # send-semaphore wait) the round's own send DMA out of the slot —
        # so release it to the left neighbor, whose round-(r+1) stream
        # overwrites it. No release for the final two rounds: r = n_r-2
        # feeds the last stream that waits (round n_r-2's wait consumes
        # round n_r-3's release); a later release would leave the
        # semaphore nonzero at kernel exit.
        pltpu.semaphore_signal(
            free_sem, inc=1, device_id=left,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )

    @pl.when(jnp.logical_and(r == n_r - 1, ci == n_c - 1))
    def _emit():
        # per QUERY TILE, not per launch: outd/outi blocks are keyed by
        # qi, and each block's final HBM flush is its last-round visit
        outd_ref[:] = cd_ref[rows]
        outi_ref[:] = ci_ref[rows]


def fused_rotation_grid(
    queries, query_ids, block, block_ids, carry_d, carry_i,
    *, cfg, q_tile, c_tile, axis_name, num_dev, collective_id=0,
):
    """Whole-rotation single-launch form (``ring_fused_rotation="grid"``):
    TPU-only — the between-round remote DMA cannot be emulated inside one
    interpret-mode evaluation, so off-TPU callers must use the per-round
    form (the one the CPU parity matrix certifies). Config already pins
    this variant to uni/exact and a float wire."""
    if not jnp.issubdtype(block.dtype, jnp.floating):
        # config refuses int8 transfer for the grid form; re-assert at
        # the kernel boundary so a relaxed config could never stream raw
        # quantized codes into a plain float cast (silently wrong
        # distances — the scale plumbing belongs to the round form)
        raise ValueError(
            "ring_fused_rotation='grid' supports float wire formats only "
            "(f32/bf16): the grid kernel casts slot bytes straight into "
            f"the distance dot, got block dtype {block.dtype}"
        )
    if jax.default_backend() != "tpu":
        raise ValueError(
            "ring_fused_rotation='grid' runs the whole rotation as one "
            "TPU kernel launch with real inter-device DMAs and cannot be "
            "emulated in interpret mode — use ring_fused_rotation="
            "'round' off-TPU"
        )
    q_local, dim = queries.shape
    b, pd = block.shape
    n_q, n_c = q_local // q_tile, b // c_tile
    qid2 = query_ids.astype(jnp.int32).reshape(q_local, 1)
    bid2 = block_ids.astype(jnp.int32).reshape(b, 1)
    kernel = functools.partial(
        _grid_rotation_kernel,
        k=cfg.k,
        dim=dim,
        exclude_self=cfg.exclude_self,
        exclude_zero=cfg.exclude_zero,
        zero_eps=cfg.zero_eps,
        precision=_exact_precision(cfg),
        axis_name=axis_name,
        q_tile=q_tile,
        c_tile=c_tile,
    )
    carry_spec = pl.BlockSpec(
        (q_tile, cfg.k), lambda r, qi, ci: (qi, 0), memory_space=pltpu.VMEM
    )
    any_spec = pl.BlockSpec(memory_space=pl.ANY)
    out_d, out_i = pl.pallas_call(
        kernel,
        grid=(num_dev, n_q, n_c),
        in_specs=[
            pl.BlockSpec((q_tile, dim), lambda r, qi, ci: (qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((q_tile, 1), lambda r, qi, ci: (qi, 0),
                         memory_space=pltpu.VMEM),
            any_spec,
            any_spec,
            carry_spec,
            carry_spec,
        ],
        out_specs=[carry_spec, carry_spec],
        out_shape=[
            jax.ShapeDtypeStruct((q_local, cfg.k), jnp.float32),
            jax.ShapeDtypeStruct((q_local, cfg.k), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.HBM((2,) + block.shape, block.dtype),  # double buffer
            pltpu.HBM((2,) + bid2.shape, bid2.dtype),
            pltpu.VMEM((c_tile, pd), block.dtype),  # staged compute tile
            pltpu.VMEM((c_tile, 1), bid2.dtype),
            # per-query-tile carries, FULL q_local rows: the (r, qi, ci)
            # sweep leaves each qi's carry parked across every other
            # tile's cells, so the whole (q_local, k) pair stays resident
            pltpu.VMEM((q_local, cfg.k), jnp.float32),
            pltpu.VMEM((q_local, cfg.k), jnp.int32),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR,  # slot-free capacity handshake
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=collective_id
        ),
        interpret=False,
    )(
        queries.astype(jnp.float32), qid2, block, bid2,
        carry_d.astype(jnp.float32), carry_i,
    )
    return out_d, out_i
