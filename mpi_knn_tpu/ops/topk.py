"""Bounded top-k maintenance — the replacement for the reference's
insert-and-qsort neighbor list (SURVEY.md C3).

The reference keeps, per query, NN=30 slots initialized to INFINITY and
re-``qsort``s all 30 on every accepted candidate
(``/root/reference/knn-serial.c:57-63,86-91``) — O(k log k) *per candidate*.
Here a whole (q_tile × c_tile) distance tile is reduced at once with
``lax.top_k`` and cross-tile/cross-round state is merged associatively::

    merge(carry, tile) = top_k(concat(carry, top_k(tile)))

which is exactly the property the distributed ring needs (merge is
commutative/associative over candidate sets — tested in test_topk.py).

All distances flow in "smaller is better" space; +inf marks invalid slots and
``INVALID_ID`` (−1) marks their ids.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from mpi_knn_tpu.types import INVALID_ID

_INF = jnp.inf


def init_topk(num_queries: int, k: int, dtype=jnp.float32):
    """Empty carry: all-inf distances, invalid ids — like the reference's
    INFINITY-filled slots (``knn-serial.c:57-63``) but SoA and batched."""
    d = jnp.full((num_queries, k), _INF, dtype=dtype)
    i = jnp.full((num_queries, k), INVALID_ID, dtype=jnp.int32)
    return d, i


def init_topk_tiles(num_tiles: int, tile_rows: int, k: int, dtype=jnp.float32):
    """``init_topk`` pre-shaped to a (num_tiles, tile_rows, k) query-tile
    stack — the carry layout of the tiled serial core and the serving
    engine's per-batch scratch (one construction, so the backends and the
    executable cache can never disagree about the scratch shape)."""
    d, i = init_topk(num_tiles * tile_rows, k, dtype=dtype)
    return (
        d.reshape(num_tiles, tile_rows, k),
        i.reshape(num_tiles, tile_rows, k),
    )


def _fold_topk(dists: jax.Array, ids: jax.Array, k: int, width: int):
    """Fold (q, c) candidate rows into (q, ceil(c/width)·k) by a per-chunk
    top-k: pad the columns to a multiple of ``width`` with (+inf, -1), sort
    each width-column chunk, keep k survivors each. Every global top-k
    element survives its own chunk's top-k, so folding is exact. The shared
    primitive behind the "block" method and the cascade merge."""
    q, c = dists.shape
    nch = -(-c // width)
    pad = nch * width - c
    if pad:
        dists = jnp.pad(dists, ((0, 0), (0, pad)), constant_values=_INF)
        ids = jnp.pad(ids, ((0, 0), (0, pad)), constant_values=INVALID_ID)
    neg, pos = jax.lax.top_k(-dists.reshape(q, nch, width), k)
    out_ids = jnp.take_along_axis(ids.reshape(q, nch, width), pos, axis=-1)
    return (-neg).reshape(q, nch * k), out_ids.reshape(q, nch * k)


def _pad_lanes(dists: jax.Array, ids: jax.Array, multiple: int = 128):
    """Lane-align the reduction input with (+inf, INVALID_ID) columns:
    ``approx_min_k`` over a width that is not a multiple of 128 (e.g. the
    stream schedule's carry‖tile concat, k+8192 wide) was observed to hang
    the tunneled device transport, while 128-aligned widths run clean
    (BASELINE.md r3). The sentinels can never enter a k-smallest result.
    Load-bearing wedge guard — every ``approx_min_k`` call site must pad
    through this helper."""
    pad = (-dists.shape[-1]) % multiple
    if pad:
        dists = jnp.pad(dists, ((0, 0), (0, pad)), constant_values=_INF)
        ids = jnp.pad(ids, ((0, 0), (0, pad)), constant_values=INVALID_ID)
    return dists, ids


def preselect_smallest(dists: jax.Array, n: int, half_width: bool = False):
    """Column positions of each row's ``n`` smallest entries — the overfetch
    preselect shared by ``smallest_k``'s "bf16" method and the mixed-
    precision compress pass (ops/rerank.py). With ``half_width`` the sort
    keys are rounded to bf16 first (monotone in the f32 values they round
    from — narrower VPU compares); either way the returned positions index
    the ORIGINAL columns, so the caller reranks/gathers exact values."""
    keys = (
        dists.astype(jnp.bfloat16)
        if half_width and dists.dtype == jnp.float32
        else dists
    )
    _, pos = jax.lax.top_k(-keys, n)
    return pos


def smallest_k(
    dists: jax.Array,
    ids: jax.Array,
    k: int,
    method: str = "exact",
    recall_target: float = 0.95,
    block: int = 128,
):
    """Per-row k smallest entries of a (q, c) tile.

    Args:
      dists: (q, c) distances.
      ids: (c,) or (q, c) int32 global candidate ids.
      k: how many to keep. If k > c the result is padded with (+inf, -1).
      method: "exact" = lax.top_k on negated distances; "approx" =
        lax.approx_min_k (TPU-optimized partial reduction, PAPERS.md TPU-KNN);
        "block" = EXACT two-level reduction — per-``block``-column top-k
        (narrow sorts) followed by a top-k over the nb·k survivors. Every
        global top-k element is in its own block's top-k, so the result is
        identical to "exact"; what changes is the sort width (``block``
        instead of ``c``), which is both faster on the VPU and avoids the
        very-wide-sort transport wedge observed at c ≳ 60k (BASELINE.md);
        "bf16" = near-exact half-width-key preselect (4k candidates by
        bf16 sort, exact f32 finish) — no exactness guarantee, recall is
        measured by the caller's gate;
        "approx-rerank" = the TPU-KNN paper's peak-FLOPs recipe
        (PAPERS.md, arxiv 2206.14286): approx_min_k PRESELECTS 4k
        candidates with overfetch (the per-candidate recall_target can be
        far below the caller's gate — a true top-k member is lost only if
        it falls out of the top-4k of the partial reduction), then an
        exact f32 top-k reranks the survivors. Distinct from "approx",
        which asks the partial reduction for the final k directly and
        therefore needs recall_target ≈ 1 (measured slow, BASELINE.md r3).
      recall_target: recall target for "approx" / the preselect of
        "approx-rerank".
      block: column width of the first-level sort for "block".

    Returns:
      (q, k) dists ascending, (q, k) ids.
    """
    q, c = dists.shape
    if ids.ndim == 1:
        ids = jnp.broadcast_to(ids[None, :], (q, c))
    if k > c:
        pad = k - c
        dists = jnp.pad(dists, ((0, 0), (0, pad)), constant_values=_INF)
        ids = jnp.pad(ids, ((0, 0), (0, pad)), constant_values=INVALID_ID)
        c = k
    if method == "block" and k <= block and c > block:
        dists, ids = _fold_topk(dists, ids, k, block)
        c = dists.shape[-1]
    if method == "approx-rerank" and c > 4 * k:
        # overfetched approx preselect (cheap partial reduction), exact
        # rerank below. aggregate_to_topk=False: the paper's recipe — the
        # partial reduction's RAW per-bin winners go straight to the exact
        # rerank; the default True would insert a redundant exact top-4k
        # aggregation between the reduce and the rerank. Recall can only
        # improve: the raw winner set is a superset of its own top-4k.
        dists, ids = _pad_lanes(dists, ids)
        dists, pos = jax.lax.approx_min_k(
            dists, 4 * k, recall_target=recall_target,
            aggregate_to_topk=False,
        )
        ids = jnp.take_along_axis(ids, pos, axis=-1)
        c = dists.shape[-1]
    if method == "bf16" and c > 4 * k and dists.dtype == jnp.float32:
        # preselect 4k candidates by sorting HALF-WIDTH keys (bf16 compare
        # is monotone in the f32 values it rounds from), then finish with
        # an exact f32 top-k over the survivors. Near-exact: a true top-k
        # member can only be lost if >3k candidates round into the same
        # bf16 value at the boundary — the recall gate measures it (the
        # method makes no exactness claim).
        pre = 4 * k
        pos = preselect_smallest(dists, pre, half_width=True)
        dists = jnp.take_along_axis(dists, pos, axis=-1)
        ids = jnp.take_along_axis(ids, pos, axis=-1)
        c = pre
    if method == "approx" and c > k:
        dists, ids = _pad_lanes(dists, ids)
        vals, pos = jax.lax.approx_min_k(dists, k, recall_target=recall_target)
    else:
        neg, pos = jax.lax.top_k(-dists, k)
        vals = -neg
    out_ids = jnp.take_along_axis(ids, pos, axis=-1)
    # slots that hold +inf are by definition invalid
    out_ids = jnp.where(jnp.isinf(vals), INVALID_ID, out_ids)
    return vals, out_ids


def cascade_smallest_k(
    dists: jax.Array,
    ids: jax.Array,
    k: int,
    method: str = "exact",
    recall_target: float = 0.95,
    block: int = 128,
    max_width: int = 8192,
):
    """``smallest_k`` for arbitrarily wide candidate rows: while the row is
    wider than ``max_width``, fold it by a per-chunk top-k (chunks of
    ``max_width`` columns → k survivors each), then finish with one narrow
    ``smallest_k``. Exact when ``method`` is exact/block (each fold keeps
    every possible global top-k element). Used by the two-level merge
    schedule, whose concatenated per-tile survivors can reach
    n_tiles·k ≫ 8k columns at SIFT scale with large k."""
    q, c = dists.shape
    if ids.ndim == 1:
        ids = jnp.broadcast_to(ids[None, :], (q, c))
    # fold width must be >= 2k: chunks narrower than k would break top_k, and
    # chunks of exactly k would make no progress (ceil(c/k)·k >= c)
    fold_w = max(max_width, 2 * k)
    while dists.shape[-1] > fold_w:
        dists, ids = _fold_topk(dists, ids, k, fold_w)
    return smallest_k(
        dists, ids, k, method=method, recall_target=recall_target, block=block
    )


def merge_topk(
    carry_d: jax.Array,
    carry_i: jax.Array,
    new_d: jax.Array,
    new_i: jax.Array,
    method: str = "exact",
    recall_target: float = 0.95,
    block: int = 128,
):
    """Merge two per-query top-k lists into one: top_k over the concatenation.

    O(k log k) per query on device; replaces the reference's per-candidate
    qsort churn. Associative and commutative over candidate multisets, which
    is what lets the ring rotate corpus blocks in any order.
    """
    k = carry_d.shape[-1]
    d = jnp.concatenate([carry_d, new_d], axis=-1)
    i = jnp.concatenate([carry_i, new_i], axis=-1)
    return smallest_k(d, i, k, method=method, recall_target=recall_target,
                      block=block)


# relative tolerance for "numerically zero" squared distances: the matmul form
# ‖x‖²+‖y‖²−2xy leaves an exact-duplicate pair at cancellation-error scale
# (a few ulps of ‖x‖²) rather than exactly 0, so the zero test must be
# relative to the pair's magnitude or it never fires at realistic data scales.
# Measured error at Precision.HIGHEST is ~2e-7·scale (f32); 1e-6 gives ~5x
# margin while staying far below genuine neighbor distances on *centered*
# data (the backends mean-center L2 inputs precisely so this holds).
_ZERO_RTOL = {jnp.dtype(jnp.float64): 1e-12}
_ZERO_RTOL_DEFAULT = 1e-6


def mask_tile(
    dists: jax.Array,
    cand_ids: jax.Array,
    query_ids: jax.Array | None = None,
    exclude_self: bool = True,
    exclude_zero: bool = True,
    zero_eps: float = 0.0,
    scale: jax.Array | None = None,
) -> jax.Array:
    """Apply validity/exclusion masks to a (q, c) distance tile.

    - padding: candidates with id < 0 (sentinel rows from divisibility
      padding, SURVEY.md §8) are forced to +inf;
    - self-exclusion by id: exact leave-one-out (robust under fp, unlike the
      reference's value test);
    - zero-exclusion by value: the reference's actual rule ``sqrt(S) != 0``
      (``/root/reference/knn-serial.c:86``), which also drops exact duplicate
      points — kept for recall parity (SURVEY.md Q3). With the default
      ``zero_eps=0`` the threshold is *relative*: ``rtol · scale`` when a
      per-pair magnitude ``scale`` (q, c) — e.g. ``x_sq + y_sq`` — is given,
      else a strict ``d <= 0`` test.
    """
    q, c = dists.shape
    if cand_ids.ndim == 1:
        cand_ids = jnp.broadcast_to(cand_ids[None, :], (q, c))
    invalid = cand_ids < 0
    if exclude_zero:
        if zero_eps > 0.0:
            thresh = zero_eps
        elif scale is not None:
            rtol = _ZERO_RTOL.get(jnp.dtype(dists.dtype), _ZERO_RTOL_DEFAULT)
            thresh = rtol * scale
        else:
            thresh = 0.0
        invalid = invalid | (dists <= thresh)
    if exclude_self and query_ids is not None:
        invalid = invalid | (cand_ids == query_ids[:, None])
    return jnp.where(invalid, _INF, dists)
