from mpi_knn_tpu.ops.distance import pairwise_sq_l2, pairwise_cosine, pairwise_dist
from mpi_knn_tpu.ops.topk import smallest_k, merge_topk, init_topk
from mpi_knn_tpu.ops.vote import vote, classify_from_labels

__all__ = [
    "pairwise_sq_l2",
    "pairwise_cosine",
    "pairwise_dist",
    "smallest_k",
    "merge_topk",
    "init_topk",
    "vote",
    "classify_from_labels",
]
