"""Mixed-precision compress-and-rerank distance pipeline
(``KNNConfig.precision_policy="mixed"``).

The TPU-KNN paper's peak-FLOPs structure (PAPERS.md, arxiv 2206.14286),
applied to the *distance* side the way ``smallest_k``'s "approx-rerank"
already applies it to the key side:

- **compress** — the (q_tile × c_tile) distance tile is computed with the
  −2·X·Yᵀ dot at ``Precision.DEFAULT`` on bf16-rounded operands (single-pass
  MXU, f32 accumulation), and an overfetched candidate set of ``4k`` columns
  per query survives an exact top-4k over the compressed keys. The operands
  are rounded to bf16 *explicitly* (not just via the precision flag) so the
  CPU tier-1 recall gate measures the same rounding the TPU MXU applies —
  a DEFAULT-precision f32 dot is exact on CPU and would make the gate
  vacuous.
- **rerank** — only the survivors' corpus rows are gathered and their
  distances recomputed exactly (f32 ``HIGHEST``), with ``mask_tile``'s
  padding/self/zero semantics re-applied on the exact values, before the
  final exact top-k.

So the O(q·c·d) FLOPs run at full single-pass MXU rate and only O(q·4k·d)
runs multi-pass. A true top-k member is lost only if bf16 rounding pushes
it out of the top-4k of its tile — the recall gate (≥ 0.999 recall@10 vs
the f64 oracle, tests/test_mixed_precision.py) measures exactly that loss,
on CPU, because the rounding is explicit.

Masking split (deliberate): the compress pass masks *padding and self by
id* (exact under any precision) but NOT zero-by-value — a genuine
near-duplicate neighbor must not be dropped on the evidence of a rounded
distance it would survive exactly. Zero-exclusion happens once, in the
rerank, on exact values; compressed near-zero survivors merely occupy
overfetch slots (≤ a few of the 4k).

The carry stays exact everywhere: each tile's contribution enters the
cross-tile/cross-round merges as (k exact-f32 distances, ids), so ring
checkpoint layouts and the merge algebra are unchanged
(backends/ring_resumable.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from mpi_knn_tpu.ops.distance import (
    _NORM_EPS,
    _l2_normalize,
    pairwise_dist,
    sq_norms,
)
from mpi_knn_tpu.ops.topk import mask_tile, preselect_smallest, smallest_k

# Overfetch factor: the compress pass keeps 4k candidates per query — the
# TPU-KNN paper's operating point, shared with smallest_k's "bf16" /
# "approx-rerank" preselects so all three recipes make the same recall
# trade.
OVERFETCH_FACTOR = 4


def overfetch_width(k: int, c: int) -> int:
    """Candidates the compress pass keeps per query from a c-wide tile."""
    return min(OVERFETCH_FACTOR * k, c)


def mixed_applies(k: int, c: int) -> bool:
    """Whether the two-pass pipeline buys anything on a c-wide tile: with
    4k >= c the compress pass could not drop a single candidate, so the
    policy degenerates to one exact pass (the caller falls back)."""
    return overfetch_width(k, c) < c


def compress_tile(
    q_x: jax.Array,  # (q, d)
    blk: jax.Array,  # (c, d)
    q_sq: jax.Array | None,
    blk_sq: jax.Array | None,
    metric: str = "l2",
) -> jax.Array:
    """Pass-1 (q, c) distances: bf16-rounded operands, single-pass DEFAULT
    dot, f32 accumulation. Order-faithful up to bf16 rounding; never used
    as an output value — only as preselect keys."""
    acc = jnp.float32
    if metric == "l2":
        if q_sq is None:
            q_sq = sq_norms(q_x)
        if blk_sq is None:
            blk_sq = sq_norms(blk)
        xy = jax.lax.dot_general(
            q_x.astype(jnp.bfloat16),
            blk.astype(jnp.bfloat16),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=acc,
            precision=jax.lax.Precision.DEFAULT,
        )
        return q_sq[:, None] - 2.0 * xy + blk_sq[None, :]
    sim = jax.lax.dot_general(
        _l2_normalize(q_x).astype(jnp.bfloat16),
        _l2_normalize(blk).astype(jnp.bfloat16),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=acc,
        precision=jax.lax.Precision.DEFAULT,
    )
    return 1.0 - sim


def rerank_exact_topk(
    q_x: jax.Array,  # (q, d)
    q_ids: jax.Array | None,  # (q,) or None (no self-exclusion)
    q_sq: jax.Array | None,  # (q,) exact squared norms (l2)
    cand_rows: jax.Array,  # (q, v, d) gathered candidate corpus rows
    cand_ids: jax.Array,  # (q, v) global ids (<0 = invalid slot)
    cand_sq: jax.Array | None,  # (q, v) exact squared norms (l2)
    k: int,
    metric: str = "l2",
    exclude_self: bool = True,
    exclude_zero: bool = True,
    zero_eps: float = 0.0,
):
    """Pass-2 exact finish: recompute the survivors' distances at HIGHEST,
    re-apply the full mask_tile semantics on the exact values, exact top-k.

    Returns ((q, k) dists ascending, (q, k) ids) — same contract as
    ``smallest_k`` over an exactly-computed masked tile, which is what
    makes the pipeline drop-in for every backend's tile loop.
    """
    acc = jnp.float32
    if metric == "l2":
        if q_sq is None:
            q_sq = sq_norms(q_x)
        if cand_sq is None:
            cand_sq = jnp.sum(
                cand_rows.astype(acc) * cand_rows.astype(acc), axis=-1
            )
        xy = jax.lax.dot_general(
            q_x,
            cand_rows,
            dimension_numbers=(((1,), (2,)), ((0,), (0,))),
            preferred_element_type=acc,
            precision=jax.lax.Precision.HIGHEST,
        )
        d = jnp.maximum(q_sq[:, None] - 2.0 * xy + cand_sq, 0.0)
        pair_scale = q_sq[:, None] + cand_sq
    elif metric == "cosine":
        qn = _l2_normalize(q_x)
        n = jnp.sqrt(
            jnp.maximum(
                jnp.sum(cand_rows.astype(acc) * cand_rows.astype(acc), -1),
                _NORM_EPS,
            )
        )
        rn = cand_rows.astype(acc) / n[..., None]
        sim = jax.lax.dot_general(
            qn,
            rn,
            dimension_numbers=(((1,), (2,)), ((0,), (0,))),
            preferred_element_type=acc,
            precision=jax.lax.Precision.HIGHEST,
        )
        d = jnp.maximum(1.0 - sim, 0.0)
        pair_scale = jnp.asarray(2.0, dtype=d.dtype)
    else:
        raise ValueError(f"unknown metric {metric!r}")
    d = mask_tile(
        d,
        cand_ids,
        query_ids=q_ids if exclude_self else None,
        exclude_self=exclude_self,
        exclude_zero=exclude_zero,
        zero_eps=zero_eps,
        scale=pair_scale,
    )
    return smallest_k(d, cand_ids, k, method="exact")


def compress_rerank_tile(
    q_x: jax.Array,  # (q, d)
    q_ids: jax.Array,  # (q,)
    q_sq: jax.Array | None,
    blk: jax.Array,  # (c, d)
    blk_ids: jax.Array,  # (c,)
    blk_sq: jax.Array | None,
    cfg,
):
    """The full two-pass tile reduction (q, c) → (q, k): the mixed-policy
    replacement for ``masked_dist_tile`` + ``smallest_k`` in every XLA tile
    loop (serial scan, ring per-round block merge). Falls back to one exact
    pass when the tile is too narrow for the overfetch to drop anything."""
    c = blk.shape[0]
    k = cfg.k
    if not mixed_applies(k, c):
        # narrow tile: compress could not discard a single candidate — run
        # the one exact pass the policy degenerates to (HIGHEST dot, full
        # mask semantics; same shape as the "exact" policy's tile step)
        d = pairwise_dist(
            q_x,
            blk,
            metric=cfg.metric,
            x_sq=q_sq,
            y_sq=blk_sq,
            precision=jax.lax.Precision.HIGHEST,
        )
        if cfg.metric == "l2" and q_sq is not None and blk_sq is not None:
            pair_scale = q_sq[:, None] + blk_sq[None, :]
        else:
            pair_scale = jnp.asarray(2.0, dtype=d.dtype)
        d = mask_tile(
            d,
            blk_ids,
            query_ids=q_ids if cfg.exclude_self else None,
            exclude_self=cfg.exclude_self,
            exclude_zero=cfg.exclude_zero,
            zero_eps=cfg.zero_eps,
            scale=pair_scale,
        )
        return smallest_k(d, blk_ids, k, method="exact")
    d_lo = compress_tile(q_x, blk, q_sq, blk_sq, metric=cfg.metric)
    # padding/self masks are id-based — exact under any precision — but
    # zero-by-value is deliberately NOT applied to compressed keys (see
    # module docstring); the rerank applies it on exact values
    d_lo = mask_tile(
        d_lo,
        blk_ids,
        query_ids=q_ids if cfg.exclude_self else None,
        exclude_self=cfg.exclude_self,
        exclude_zero=False,
    )
    pos = preselect_smallest(d_lo, overfetch_width(k, c))  # (q, 4k)
    rows = jnp.take(blk, pos, axis=0)  # (q, 4k, d)
    ids_sel = jnp.take(blk_ids, pos, axis=0)
    sq_sel = (
        jnp.take(blk_sq, pos, axis=0)
        if blk_sq is not None and cfg.metric == "l2"
        else None
    )
    return rerank_exact_topk(
        q_x,
        q_ids,
        q_sq,
        rows,
        ids_sel,
        sq_sel,
        k,
        metric=cfg.metric,
        exclude_self=cfg.exclude_self,
        exclude_zero=cfg.exclude_zero,
        zero_eps=cfg.zero_eps,
    )
