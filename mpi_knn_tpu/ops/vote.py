"""Majority-vote classification — the replacement for the reference's label
voting (SURVEY.md C10).

The reference histograms the 30 neighbor labels into ``class[10]`` and scans
for the winner with a tie-break that conflates vote *counts* with class
*labels* (``most`` starts as a count, becomes ``j+1``;
``/root/reference/knn-serial.c:113-124``) — and the MPI variants' tie
condition differs from the serial one by an off-by-one
(``/root/reference/mpi-knn-parallel_blocking.c:263-266``), so the two programs
disagree on ties (SURVEY.md §5 Q4).

Here the vote is a one-hot sum + argmax on device, with a *correct*
nearest-neighbor tie-break by default, plus two quirk-compat modes that
bit-replicate each reference loop for parity experiments.

Class labels are 0-based ints in [0, num_classes) throughout the framework;
the data layer maps the reference's 1-based MNIST labels at the boundary.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from mpi_knn_tpu.types import ClassifyResult


def vote_counts(
    neigh_labels: jax.Array, valid: jax.Array, num_classes: int
) -> jax.Array:
    """(q, k) 0-based labels + (q, k) validity -> (q, C) int32 histogram."""
    labels = jnp.where(valid, neigh_labels, 0)
    onehot = jax.nn.one_hot(labels, num_classes, dtype=jnp.int32)
    onehot = onehot * valid[..., None].astype(jnp.int32)
    return jnp.sum(onehot, axis=-2)


def _quirk_vote(counts: jax.Array, cmp_j: jax.Array) -> jax.Array:
    """Bit-replication of the reference's winner scan.

    The C loop is::

        most = 0;
        for (j = 0; j < C; j++)
          if (class[j] > most || (class[j] == most && <tie-cond>)) most = j + 1;

    After the first assignment ``most`` holds a *label*, so later iterations
    compare a count against a label — faithfully reproduced here. ``cmp_j`` is
    the j value that satisfies the tie condition: for the serial program the
    condition ``(j+1) == raw_nearest_label`` means ``cmp_j = nearest_class``
    (0-based); for the MPI programs ``(j+1) == raw_nearest_label − 1`` means
    ``cmp_j = nearest_class − 1``.

    Returns 0-based predictions; −1 if the loop never assigned (all counts 0
    and no tie hit — cannot happen with k ≥ 1 valid neighbors).
    """
    num_classes = counts.shape[-1]

    def body(most, j):
        cj = counts[:, j]
        take = (cj > most) | ((cj == most) & (j == cmp_j))
        return jnp.where(take, j + 1, most), None

    init = jnp.zeros(counts.shape[0], dtype=counts.dtype)
    most, _ = jax.lax.scan(body, init, jnp.arange(num_classes))
    return (most - 1).astype(jnp.int32)


def vote(
    neigh_labels: jax.Array,
    valid: jax.Array,
    num_classes: int,
    tie_break: str = "nearest",
) -> ClassifyResult:
    """Classify each query by majority vote over its neighbors' labels.

    Args:
      neigh_labels: (q, k) 0-based class of each neighbor, ascending distance
        order (column 0 = nearest) — the order KNNResult guarantees.
      valid: (q, k) bool, False for padded/invalid slots.
      num_classes: C.
      tie_break: "nearest" | "lowest" | "quirk-serial" | "quirk-mpi".
    """
    counts = vote_counts(neigh_labels, valid, num_classes)
    nearest = jnp.where(valid[:, 0], neigh_labels[:, 0], 0).astype(jnp.int32)
    any_valid = jnp.any(valid, axis=-1)

    if tie_break == "quirk-serial":
        pred = _quirk_vote(counts, nearest)
    elif tie_break == "quirk-mpi":
        pred = _quirk_vote(counts, nearest - 1)
    else:
        maxc = jnp.max(counts, axis=-1, keepdims=True)
        tied = counts == maxc
        lowest = jnp.argmax(tied, axis=-1).astype(jnp.int32)
        if tie_break == "lowest":
            pred = lowest
        elif tie_break == "nearest":
            nearest_is_tied = jnp.take_along_axis(
                tied, nearest[:, None], axis=-1
            )[:, 0]
            pred = jnp.where(nearest_is_tied, nearest, lowest)
        else:
            raise ValueError(f"unknown tie_break {tie_break!r}")

    # a query whose every neighbor slot is invalid has no evidence at all —
    # emit the sentinel −1 rather than a confident class 0
    pred = jnp.where(any_valid, pred, jnp.int32(-1))
    return ClassifyResult(predictions=pred, counts=counts)


def classify_from_labels(
    ids: jax.Array,
    labels: jax.Array,
    num_classes: int,
    tie_break: str = "nearest",
) -> ClassifyResult:
    """Gather neighbor labels from a global label vector and vote.

    Args:
      ids: (q, k) 0-based global neighbor ids from KNNResult (−1 = invalid).
      labels: (m,) 0-based class per corpus point.
    """
    valid = ids >= 0
    safe = jnp.where(valid, ids, 0)
    neigh_labels = jnp.take(labels.astype(jnp.int32), safe, axis=0)
    return vote(neigh_labels, valid, num_classes, tie_break=tie_break)
