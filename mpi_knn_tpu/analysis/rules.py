"""Static rules over compiled-program structure (the lint engine's R1–R4).

Each rule asserts one property of the HLO a backend configuration actually
lowers/compiles to — the class of bug a timing run cannot surface (the
reference's non-blocking variant "worked" for its whole life while
serializing on MPI_Wait). The parsing core lives in
``mpi_knn_tpu.utils.hlo_graph``; this module interprets the parsed graph.

Shipped rules:

- **R1-overlap** — comm/compute sequencing. The overlap schedule's
  ``collective-permute`` must have no dependence path from the step's
  distance compute (both before and after XLA optimization); the blocking
  schedule's permutes must be sequenced after the compute via the
  ``opt-barrier`` (before-opt only: XLA legitimately expands the barrier
  mid-pipeline once it has constrained the passes it exists to constrain).
- **R2-memory** — footprint bound. No instruction may define a buffer
  larger than the tile budget implied by ``query_tile``/``corpus_tile``
  (with slack for concatenated carries) or the largest input, whichever is
  greater — statically forbidding accidental materialization of the full
  m×m distance matrix.
- **R3-dtype** — dtype integrity. In float64 debug mode no value may be
  silently downcast (f64→f32/bf16/f16 ``convert``); in any mode a ``dot``
  with bf16 operands must accumulate wider (bf16→bf16 dots lose the MXU's
  f32 accumulator). Quantized cells (int8 transfer / int8-int4 at-rest
  stores, ``meta["quantized"]``) add the quant/dequant contract: no dot
  may consume raw int8 codes (scoring codes without their scale is
  numerically meaningless, not merely imprecise), the module must
  contain at least one dequant (an s8→float ``convert`` — zero means the
  quantized payload never reaches compute and every check here is
  vacuous), and in mixed-policy cells each DEFAULT compress dot must be
  fed by EXACTLY ONE dequant convert plus a scale ``multiply`` in its
  backward slice.
- **R4-collective** — collective accounting. Ring backends must contain
  exactly the expected corpus-rotation ``collective-permute``s with
  ring-shaped ``source_target_pairs`` and nothing else (uni: one block+ids
  pair, forward; bidir: two counter-directed pairs, 2 permutes per torus
  direction — wrong-direction or missing permutes are findings);
  single-device backends must contain no collectives at all (a stray
  ``all-gather`` / ``all-reduce`` is a sharding leak); sharded-IVF
  programs contain exactly the candidate exchange's ``all-to-all``s
  (count, full-ring replica groups, payload bytes ≤ the declared
  per-tile exchange budget) and nothing else — an unrouted full-bucket
  broadcast or an over-budget per-shard gather is a finding.
- **R6-ivf-probe** — clustered-index probe discipline. In an IVF cell the
  only way corpus payload may reach a dot is the per-query probe gather:
  every batched candidate dot must carry a ``gather`` in its backward
  slice (and at least one must exist — zero is a vacuous contract), and
  no un-batched dot may be wider than the centroid score. Combined with
  R2's strict probed-bytes budget (``budget_elems``: the gather bound
  nprobe·bucket_cap·d per query row REPLACES the largest-input floor),
  "sublinear per query" is a compiled-program fact, not a Python-side
  counter.
- **R5-donation** — donation/aliasing of the serving batch program. The
  per-batch executable the serving engine compiles (``mpi_knn_tpu.serve``)
  must declare its scratch donation in the module header (``buffer_donor``
  before optimization / ``input_output_alias`` after — the compiled
  program's proof that steady-state serving reuses the carry in place
  rather than allocating per batch), and may not contain a
  ``copy``/``copy-start`` of resident-corpus size in either stage — a
  full-corpus copy inside the batch program would silently re-pay the
  corpus upload the resident index exists to amortize.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from mpi_knn_tpu.utils.hlo_graph import (
    HloModule,
    backward_slice,
    parse_hlo,
    slice_opcodes,
)

# ---------------------------------------------------------------------------
# Findings and rule protocol


@dataclass
class Finding:
    """One rule violation, attributable to an instruction in one stage of
    one lowered configuration."""

    rule: str
    target: str  # "backend/metric/dtype"
    stage: str  # before_opt | after_opt
    message: str
    details: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "target": self.target,
            "stage": self.stage,
            "message": self.message,
            "details": self.details,
        }


class Rule:
    """A static check over one parsed HLO module.

    ``applies`` gates on the configuration (a collective rule has nothing
    to say about code it knows nothing about — it still runs on serial
    programs, where "no collectives" IS the property); ``check`` returns
    findings for one (stage, module) of a configuration that does apply.
    """

    name: str = ""
    description: str = ""

    def applies(self, ctx) -> bool:
        return True

    def check(self, ctx, stage: str, module: HloModule) -> list[Finding]:
        raise NotImplementedError


RULES: list[Rule] = []


def register(cls):
    RULES.append(cls())
    return cls


def rules_by_name(names=None) -> list[Rule]:
    if names is None:
        return list(RULES)
    known = {r.name: r for r in RULES}
    missing = [n for n in names if n not in known]
    if missing:
        raise KeyError(f"unknown rule(s) {missing}; have {sorted(known)}")
    return [known[n] for n in names]


# ---------------------------------------------------------------------------
# R1: overlap/sequencing (the original ring-overlap artifact, generalized)

# Opcodes that witness the ring step's distance/top-k compute. ``dot`` is
# the MXU distance matmul; TopK/sort are the selection; reduce covers the
# sq_norms/row-sum forms XLA sometimes prefers over dot pre-optimization.
# Matched EXACTLY: prefix matching would classify the collective
# ``reduce-scatter`` / data-movement ``reduce-window`` as compute and
# falsely fail the overlap property on dumps with a second collective in
# the permute's slice.
COMPUTE_WITNESS = ("dot", "sort", "custom-call:TopK", "top-k", "topk",
                   "reduce")


def permute_dependence_report(text: str) -> dict:
    """For each collective-permute in the module: which compute-witness
    opcodes and how many opt-barriers its backward slice contains."""
    return permute_report_from_module(parse_hlo(text))


def permute_report_from_module(module: HloModule) -> dict:
    permutes = module.find("collective-permute")
    report = {
        "n_collective_permute": len(permutes),
        "n_opt_barrier_in_module": len(module.find("opt-barrier")),
        "n_dot_in_module": len(module.find("dot")),
        "permutes": [],
    }
    for comp, name in permutes:
        sl = backward_slice(module, comp, name)
        ops = slice_opcodes(module, sl)
        report["permutes"].append(
            {
                "instruction": f"{comp}::{name}",
                "slice_size": len(sl),
                "depends_on_opt_barrier": "opt-barrier" in ops,
                "compute_witnesses_in_slice": sorted(
                    o for o in ops if o in COMPUTE_WITNESS
                ),
                "depends_on_dot": "dot" in ops,
            }
        )
    return report


def overlap_violations(rep: dict) -> list[str]:
    """Why a permute-dependence report fails the OVERLAP schedule property
    (empty = holds). Zero permutes is itself a violation — it would make
    the dependence checks vacuous."""
    out = []
    if rep["n_collective_permute"] < 1:
        out.append("no collective-permute in module (vacuous overlap claim)")
    for p in rep["permutes"]:
        if p["compute_witnesses_in_slice"]:
            out.append(
                f"{p['instruction']} depends on compute "
                f"{p['compute_witnesses_in_slice']} — the transfer cannot "
                "overlap the work it waits on"
            )
        if p["depends_on_opt_barrier"]:
            out.append(
                f"{p['instruction']} is sequenced behind an opt-barrier"
            )
    return out


def blocking_violations(rep: dict) -> list[str]:
    """Why a (before-opt) report fails the BLOCKING schedule property:
    every permute must be sequenced after the compute via the barrier AND
    see the distance dot in its slice."""
    out = []
    if rep["n_collective_permute"] < 1:
        out.append("no collective-permute in module (vacuous blocking claim)")
    for p in rep["permutes"]:
        if not (p["depends_on_opt_barrier"] and p["depends_on_dot"]):
            out.append(
                f"{p['instruction']} is NOT sequenced after the compute "
                "(missing opt-barrier/dot dependence) — 'blocking' would "
                "silently be the overlap schedule"
            )
    return out


def property_holds(variant_reports: dict) -> bool:
    """THE ring-overlap artifact property, single definition shared by
    ``scripts/dump_ring_hlo.py`` (writes it into ``overlap_verdict.json``),
    ``tests/test_hlo_overlap.py`` (asserts it) and the engine's R1 rule —
    hand-maintained copies could drift and let the committed verdict
    disagree with the gate that is supposed to mirror it.

    Input: ``{variant: {stage: permute_dependence_report(...)}}`` with
    variants ``overlap``/``blocking`` and stages ``before_opt``/
    ``after_opt``. Holds iff the overlap reports pass
    :func:`overlap_violations` in BOTH stages and the blocking before-opt
    report passes :func:`blocking_violations` (after optimization the
    barrier is legitimately expanded — cpu: ``cse_barrier_expander`` — so
    after_opt makes no blocking claim).
    """
    ok = not overlap_violations(variant_reports["overlap"]["before_opt"])
    ok = ok and not overlap_violations(variant_reports["overlap"]["after_opt"])
    ok = ok and not blocking_violations(
        variant_reports["blocking"]["before_opt"]
    )
    return bool(ok)


@register
class R1Overlap(Rule):
    name = "R1-overlap"
    description = (
        "ring schedules keep their sequencing contract: overlap permutes "
        "are compute-independent, blocking permutes are barrier-sequenced"
    )

    def applies(self, ctx) -> bool:
        return ctx.target.backend in ("ring", "ring-overlap")

    def check(self, ctx, stage, module) -> list[Finding]:
        rep = permute_report_from_module(module)
        if ctx.target.backend == "ring-overlap":
            why = overlap_violations(rep)
            if ctx.meta.get("fused_dma"):
                # kernel-owned transport (the fused rotation's TPU round
                # form): zero collective-permutes is the CORRECT shape —
                # the rotation is async remote copies issued inside the
                # Pallas kernel, sequenced by its send/recv semaphores,
                # so the vacuous-claim guard does not apply. What takes
                # its place is the side-band contract: the cell must
                # declare the in-kernel wire bytes (R8 prices them) or
                # the overlap claim has no statically checkable residue
                # at all; the runtime dual — the measured
                # overlap_fraction from obs.attribution — is the
                # acceptance instrument for the sequencing itself.
                why = [w for w in why if "vacuous" not in w]
                if not ctx.meta.get("fused_dma_wire_bytes"):
                    why.append(
                        "fused rotation owns its transport in-kernel "
                        "but declares no wire-byte side-band "
                        "(meta['fused_dma_wire_bytes']) — with zero "
                        "permutes in the module the overlap claim "
                        "leaves no statically checkable residue "
                        "(unpriced fused DMA)"
                    )
        elif stage == "before_opt":
            why = blocking_violations(rep)
        else:  # blocking after-opt: barrier already expanded, no claim
            return []
        return [
            Finding(self.name, ctx.target.label, stage, w,
                    {"report": rep["permutes"]})
            for w in why
        ]


# ---------------------------------------------------------------------------
# R2: memory-footprint bound

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# Headroom over the (q_tile × c_tile) distance block for legitimate
# intermediates: the (carry ‖ tile) concatenation before the merge top-k,
# sort temporaries, and the twolevel survivor stack are all small multiples
# of the tile. 4× holds across the whole shipped matrix with margin; a full
# m×m materialization overshoots it by orders of magnitude.
R2_SLACK = 4


def max_buffer_bytes(type_str: str) -> int:
    """Largest single buffer in an HLO result type. Tuples are per-element
    buffers in XLA, so the max element — not the sum — is what an
    instruction materializes at once."""
    best = 0
    for dt, dims in _SHAPE_RE.findall(type_str):
        sz = _DTYPE_BYTES.get(dt)
        if sz is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        best = max(best, n * sz)
    return best


def max_buffer_elems(type_str: str) -> int:
    """Largest single buffer in an HLO result type, in ELEMENTS. The R2
    budget is element-denominated: a bf16 input legitimately widens to the
    f32 accumulation dtype (2× the input bytes), so byte-for-byte against
    the inputs would flag every upcast."""
    best = 0
    for dt, dims in _SHAPE_RE.findall(type_str):
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        best = max(best, n)
    return best


@register
class R2Memory(Rule):
    name = "R2-memory"
    description = (
        "no instruction defines a buffer beyond the query_tile×corpus_tile "
        "budget (or the largest input) — no accidental m×m materialization"
    )

    def applies(self, ctx) -> bool:
        return True

    # strict-budget exemptions: opcodes that forward existing buffers
    # rather than materialize new payload — loop/tuple plumbing (XLA
    # aliases while state in place; tuple/gte are pointer shuffles). The
    # resident corpus legitimately rides through the query-tile loop's
    # state inside them; anything that COMPUTES corpus-sized bytes
    # (gather, dot, broadcast, fusion, …) stays on the hook.
    STRICT_EXEMPT = (
        "parameter", "tuple", "get-tuple-element", "while", "opt-barrier",
        "conditional", "call",
    )
    # sharded (SPMD) programs additionally pass the resident slice through
    # the partitioner's annotation custom-calls (@Sharding and the
    # full↔shard shape casts) — directives, not payload; every other
    # custom-call (TopK, …) stays on the hook
    _SPMD_ANNOTATIONS = (
        'custom_call_target="Sharding"',
        'custom_call_target="SPMDFullToShardShape"',
        'custom_call_target="SPMDShardToFullShape"',
    )

    @classmethod
    def _is_spmd_annotation(cls, instr) -> bool:
        return instr.opcode == "custom-call" and any(
            t in instr.attrs for t in cls._SPMD_ANNOTATIONS
        )

    def check(self, ctx, stage, module) -> list[Finding]:
        entry_params = [
            i
            for c in module.computations.values()
            if c.is_entry
            for i in c.instructions.values()
            if i.opcode == "parameter"
        ]
        max_param = max(
            (max_buffer_elems(i.type_str) for i in entry_params), default=0
        )
        tile_elems = ctx.meta["q_tile"] * ctx.meta["c_tile"]
        acc_bytes = ctx.meta["acc_bytes"]
        # element-denominated, then priced at the accumulation width: an
        # input-sized buffer may widen to the accumulator dtype (bf16
        # corpus → f32 norms path) but must not GROW in element count.
        # "extra_elems" is the lowering's registered legitimate intermediate
        # beyond the tile (today: the mixed policy's (q_tile, 4k, d) rerank
        # gather) — declared per configuration, never a blanket slack bump.
        #
        # "budget_elems" switches R2 to the STRICT mode the clustered (IVF)
        # cells use: the declared bound REPLACES the largest-input floor,
        # so the budget is the probe gather (q_tile·nprobe·bucket_cap·d)
        # and NOT the resident corpus — the lowering must prove it scans
        # only probed partitions, with only non-materializing loop/tuple
        # plumbing exempt.
        strict = ctx.meta.get("budget_elems")
        if strict is not None:
            budget = max(strict, R2_SLACK * tile_elems) * acc_bytes
        else:
            budget = max(
                max_param,
                R2_SLACK * tile_elems,
                ctx.meta.get("extra_elems", 0),
            ) * acc_bytes
        # "strict_exempt_ops": configuration-registered buffer-forwarding
        # opcodes beyond the structural plumbing — today the mutation
        # cells' in-place scatter/dynamic-update-slice forms, which XLA
        # aliases onto the donated store (the aliasing itself is R5's
        # claim; here they would read as store-sized materializations)
        exempt = (
            self.STRICT_EXEMPT + tuple(ctx.meta.get("strict_exempt_ops", ()))
            if strict is not None else ("parameter",)
        )
        # quantized stores additionally bound the GATHERS at the wire
        # width: the probe/exchange gathers must move code lanes (+ the
        # small scale/id/norm tables), never float-widened rows — an
        # f32-sized bucket gather under a quantized config means the
        # store was dequantized BEFORE the gather, silently re-paying the
        # bytes the quantization exists to cut (recall cost with no byte
        # win). The element-denominated budget above cannot see this: the
        # element counts are identical, only the itemsize differs.
        quant_gather = ctx.meta.get("quant_gather_bytes")
        out = []
        for c in module.computations.values():
            for i in c.instructions.values():
                if i.opcode in exempt:
                    continue  # inputs/plumbing: the caller's bytes, not new
                if strict is not None and self._is_spmd_annotation(i):
                    continue  # partitioner directives, not materialization
                b = max_buffer_bytes(i.type_str)
                if (
                    quant_gather is not None
                    and i.opcode == "gather"
                    and b > quant_gather
                ):
                    out.append(
                        Finding(
                            self.name,
                            ctx.target.label,
                            stage,
                            f"{c.name}::{i.name} gathers {b} bytes > the "
                            f"quantized wire budget {quant_gather} — a "
                            "float-sized bucket gather under a quantized "
                            "config moves the bytes the store compressed "
                            "away (dequantize AFTER the gather, not "
                            "before)",
                            {"bytes": b, "budget": quant_gather,
                             "type": i.type_str},
                        )
                    )
                if b > budget:
                    why = (
                        f"(declared probed-bytes bound {strict} elems, "
                        "NOT the resident corpus"
                        if strict is not None
                        else f"(max(largest input {max_param} elems, "
                        f"{R2_SLACK}×{ctx.meta['q_tile']}×"
                        f"{ctx.meta['c_tile']} tile elems)"
                    )
                    out.append(
                        Finding(
                            self.name,
                            ctx.target.label,
                            stage,
                            f"{c.name}::{i.name} ({i.opcode}) materializes "
                            f"{b} bytes > budget {budget} "
                            f"{why} × {acc_bytes} acc bytes)",
                            {
                                "bytes": b,
                                "budget": budget,
                                "type": i.type_str,
                            },
                        )
                    )
        return out


# ---------------------------------------------------------------------------
# R3: dtype integrity


def _result_dtype(type_str: str) -> str | None:
    m = _SHAPE_RE.search(type_str)
    return m.group(1) if m else None


_PRECISION_RE = re.compile(r"operand_precision=\{([^}]*)\}")


def dot_precision_class(instr) -> str:
    """Canonical precision of a ``dot``: the ``operand_precision`` attr
    ("highest"/"high"), or "default" when absent (XLA prints nothing for
    DEFAULT). Mismatched per-operand settings are reported joined — the
    mixed contract treats anything but a uniform default/highest as a
    violation."""
    m = _PRECISION_RE.search(instr.attrs)
    if not m:
        return "default"
    vals = {v.strip() for v in m.group(1).split(",") if v.strip()}
    return vals.pop() if len(vals) == 1 else "/".join(sorted(vals))


@register
class R3Dtype(Rule):
    name = "R3-dtype"
    description = (
        "no silent f64 downcast in float64 debug mode; bf16 dots must "
        "accumulate in f32 or wider; mixed-policy programs declare exactly "
        "one DEFAULT compress dot per tile computation with the rerank dot "
        "at HIGHEST"
    )

    def applies(self, ctx) -> bool:
        return True

    def check(self, ctx, stage, module) -> list[Finding]:
        out = []
        check_f64 = ctx.target.dtype == "float64"
        for c in module.computations.values():
            for i in c.instructions.values():
                res = _result_dtype(i.type_str)
                if (
                    check_f64
                    and i.opcode == "convert"
                    and res in ("f32", "bf16", "f16")
                ):
                    src = c.instructions.get(i.operands[0]) if i.operands else None
                    if src is not None and _result_dtype(src.type_str) == "f64":
                        out.append(
                            Finding(
                                self.name,
                                ctx.target.label,
                                stage,
                                f"{c.name}::{i.name} silently converts f64 "
                                f"-> {res} on the float64 debug path",
                                {"type": i.type_str},
                            )
                        )
                if i.opcode == "dot" and res == "bf16":
                    op_dts = [
                        _result_dtype(c.instructions[o].type_str)
                        for o in i.operands
                        if o in c.instructions
                    ]
                    if "bf16" in op_dts:
                        out.append(
                            Finding(
                                self.name,
                                ctx.target.label,
                                stage,
                                f"{c.name}::{i.name} is a bf16 dot without "
                                "f32 accumulation (result bf16) — the MXU "
                                "accumulator precision is being thrown away",
                                {"type": i.type_str},
                            )
                        )
        if (
            stage == "before_opt"
            and getattr(ctx.cfg, "precision_policy", "exact") == "mixed"
        ):
            out.extend(self._check_mixed_contract(ctx, stage, module))
        if stage == "before_opt" and ctx.meta.get("quantized"):
            out.extend(self._check_quant_contract(ctx, stage, module))
        return out

    @staticmethod
    def _is_dequant_convert(comp, instr) -> bool:
        """A ``convert`` whose source is int8 codes and whose result is a
        float — the first half of the dequant pair."""
        if instr.opcode != "convert":
            return False
        if _result_dtype(instr.type_str) not in ("f32", "bf16", "f16",
                                                 "f64"):
            return False
        for o in instr.operands:
            src = comp.instructions.get(o)
            if src is not None and _result_dtype(src.type_str) == "s8":
                return True
        return False

    def _check_quant_contract(self, ctx, stage, module) -> list[Finding]:
        """The quantized dtype contract (before-opt — fusion may legally
        rewrite the dequant afterwards; the declared dataflow is pinned on
        the module XLA receives): quantized payload reaches compute ONLY
        through the dequant (convert out of int8 + multiply by the scale).
        A dot consuming raw s8 operands is scoring codes without their
        scale; a quantized program with no s8→float convert at all never
        dequantized (the codes are dead or — worse — reinterpreted), which
        would make every other check here vacuous."""
        out = []
        n_dequant = 0
        for c in module.computations.values():
            for i in c.instructions.values():
                if self._is_dequant_convert(c, i):
                    n_dequant += 1
                if i.opcode != "dot":
                    continue
                op_dts = [
                    _result_dtype(c.instructions[o].type_str)
                    for o in i.operands
                    if o in c.instructions
                ]
                if any(dt in ("s8", "s4", "u8", "u4") for dt in op_dts):
                    out.append(
                        Finding(
                            self.name,
                            ctx.target.label,
                            stage,
                            f"{c.name}::{i.name} is a dot consuming raw "
                            f"int8/int4 codes ({op_dts}) — quantized "
                            "payload must be dequantized (convert + scale "
                            "multiply) before any distance dot; scoring "
                            "codes without their block scale is not a "
                            "precision loss, it is a different function",
                            {"operand_dtypes": op_dts,
                             "type": i.type_str},
                        )
                    )
        if n_dequant == 0:
            out.append(
                Finding(
                    self.name,
                    ctx.target.label,
                    stage,
                    "quantized cell lowered NO s8→float dequant convert — "
                    "the quantized payload never reaches compute through "
                    "the dequant path (the quant contract is vacuous)",
                    {},
                )
            )
        if getattr(ctx.cfg, "precision_policy", "exact") != "mixed":
            return out
        # mixed quantized cells: the compress dot is where the quantized
        # rows enter the pipeline — each DEFAULT dot must see exactly one
        # dequant convert and the scale multiply in its backward slice (a
        # second convert would mean two quantized sources merged into one
        # compress pass the budgets do not model; zero means the compress
        # pass is scoring something other than the dequantized store)
        for c in module.computations.values():
            for i in c.instructions.values():
                if i.opcode != "dot" or dot_precision_class(i) != "default":
                    continue
                sl = backward_slice(module, c.name, i.name)
                convs = 0
                has_mul = False
                for sc, sn in sl:
                    si = module.instr(sc, sn)
                    if si.opcode == "multiply":
                        has_mul = True
                    if self._is_dequant_convert(
                        module.computations[sc], si
                    ):
                        convs += 1
                if convs != 1:
                    out.append(
                        Finding(
                            self.name,
                            ctx.target.label,
                            stage,
                            f"{c.name}::{i.name} (DEFAULT compress dot) "
                            f"has {convs} dequant converts in its "
                            "backward slice — the quantized contract is "
                            "exactly one dequant feeding each compress "
                            "dot",
                            {"dequant_converts": convs},
                        )
                    )
                elif not has_mul:
                    out.append(
                        Finding(
                            self.name,
                            ctx.target.label,
                            stage,
                            f"{c.name}::{i.name} (DEFAULT compress dot) "
                            "sees the dequant convert but NO scale "
                            "multiply in its backward slice — the codes "
                            "are being scored unscaled",
                            {},
                        )
                    )
        return out

    def _check_mixed_contract(self, ctx, stage, module) -> list[Finding]:
        """The DECLARED mixed-precision contract, machine-checked on the
        module XLA receives (before-opt: optimization may legally fuse or
        rewrite dots afterwards, but the declared precisions are fixed
        here): every dot is either the compress (DEFAULT — single-pass
        bf16 MXU) or the rerank (HIGHEST — multi-pass exact); each tile
        computation contains at most ONE compress dot; and both passes
        must actually exist — a mixed program with no DEFAULT dot never
        compressed (it silently pays exact FLOPs), one with no HIGHEST
        dot never reranks (it silently ships compressed distances)."""
        out = []
        n_default = n_highest = 0
        for c in module.computations.values():
            defaults_here = []
            for i in c.instructions.values():
                if i.opcode != "dot":
                    continue
                cls = dot_precision_class(i)
                if cls == "default":
                    defaults_here.append(i.name)
                    n_default += 1
                elif cls == "highest":
                    n_highest += 1
                else:
                    out.append(
                        Finding(
                            self.name,
                            ctx.target.label,
                            stage,
                            f"{c.name}::{i.name} is a dot at precision "
                            f"{cls!r} — the mixed contract allows only the "
                            "DEFAULT compress dot and the HIGHEST rerank "
                            "dot",
                            {"precision": cls, "type": i.type_str},
                        )
                    )
            if len(defaults_here) > 1:
                out.append(
                    Finding(
                        self.name,
                        ctx.target.label,
                        stage,
                        f"{c.name} contains {len(defaults_here)} "
                        "DEFAULT-precision dots "
                        f"({', '.join(defaults_here)}) — the compress pass "
                        "is exactly one single-pass dot per tile; a second "
                        "one is a silent downcast of work the contract "
                        "promises at HIGHEST",
                        {"dots": defaults_here},
                    )
                )
        if n_default == 0:
            out.append(
                Finding(
                    self.name,
                    ctx.target.label,
                    stage,
                    "mixed policy lowered NO DEFAULT-precision compress "
                    "dot — the program pays exact multi-pass FLOPs on the "
                    "full tile (the policy silently degenerated to exact)",
                    {},
                )
            )
        if n_highest == 0:
            out.append(
                Finding(
                    self.name,
                    ctx.target.label,
                    stage,
                    "mixed policy lowered NO HIGHEST-precision rerank dot "
                    "— compressed distances would reach the final top-k "
                    "unreranked",
                    {},
                )
            )
        return out


# ---------------------------------------------------------------------------
# R4: collective accounting

RING_COLLECTIVE = "collective-permute"
STRAY_COLLECTIVES = (
    "all-gather",
    "all-reduce",
    "reduce-scatter",
    "all-to-all",
    "collective-broadcast",
)
_PAIR_RE = re.compile(r"\{(\d+),(\d+)\}")


def count_collectives(module: HloModule) -> dict[str, list[tuple[str, str]]]:
    """Collective instructions by canonical opcode. Async ``-start``/
    ``-done`` pairs count once (the ``-start`` carries the semantics)."""
    out: dict[str, list[tuple[str, str]]] = {}
    for op in (RING_COLLECTIVE,) + STRAY_COLLECTIVES:
        hits = [
            (c, n)
            for (c, n) in module.find(op)
            if not module.instr(c, n).opcode.endswith("-done")
        ]
        if hits:
            out[op] = hits
    return out


def _permute_pairs(module: HloModule, comp: str, name: str):
    m = re.search(
        r"source_target_pairs=\{(.*?)\}\}", module.instr(comp, name).attrs
    )
    if not m:
        return None
    return sorted(
        (int(a), int(b)) for a, b in _PAIR_RE.findall(m.group(1) + "}")
    )


def ring_rotation_pairs(ring_n: int) -> tuple[list, list]:
    """The two legal rotation shapes on an n-ring: forward (i → i+1, the
    reference's direction) and backward (i → i−1, the bidir schedule's
    counter-rotation), as sorted source_target_pairs."""
    fwd = sorted((i, (i + 1) % ring_n) for i in range(ring_n))
    bwd = sorted((i, (i - 1) % ring_n) for i in range(ring_n))
    return fwd, bwd


def permute_direction_census(module: HloModule, ring_n: int) -> dict:
    """Classify every collective-permute by rotation direction:
    ``{"fwd": n, "bwd": n, "other": [instruction, ...]}``. The bidir
    schedule must show an equal fwd/bwd split (one block + one ids permute
    per direction) and nothing in ``other`` — a wrong-direction permute
    would merge blocks in an order the round plan does not account for."""
    fwd, bwd = ring_rotation_pairs(ring_n)
    out: dict = {"fwd": 0, "bwd": 0, "other": []}
    for comp, name in module.find(RING_COLLECTIVE):
        if module.instr(comp, name).opcode.endswith("-done"):
            continue
        pairs = _permute_pairs(module, comp, name)
        if pairs == fwd:
            out["fwd"] += 1
        elif pairs == bwd and ring_n > 2:
            # n<=2: fwd and bwd coincide; classify as fwd above
            out["bwd"] += 1
        else:
            out["other"].append(f"{comp}::{name}")
    return out


_REPLICA_GROUPS_RE = re.compile(r"replica_groups=\{(\{[^=]*?\})\}")


def alltoall_census(module: HloModule, ring_n: int) -> dict:
    """Account every ``all-to-all`` (the sharded-IVF candidate exchange's
    collective): instruction count, total payload bytes (result buffer of
    the tiled form — what one scan step moves per shard), and any
    instruction whose replica_groups is NOT the single full-ring group —
    a partial-group exchange would route candidates to a subset of the
    owners the routing table named."""
    full = "{" + ",".join(str(i) for i in range(ring_n)) + "}"
    out: dict = {"count": 0, "bytes": 0, "bad_groups": []}
    for comp, name in module.find("all-to-all"):
        instr = module.instr(comp, name)
        if instr.opcode.endswith("-done"):
            continue
        out["count"] += 1
        out["bytes"] += max_buffer_bytes(instr.type_str)
        m = _REPLICA_GROUPS_RE.search(instr.attrs)
        groups = m.group(1).replace(" ", "") if m else ""
        if groups != full:
            out["bad_groups"].append(f"{comp}::{name} ({groups or 'none'})")
    return out


_WHILE_BODY_RE = re.compile(r"body=%?([\w.\-]+)")
_WHILE_COND_RE = re.compile(r"condition=%?([\w.\-]+)")
_INT_CONST_RE = re.compile(r"^\s*(-?\d+)\s*$")


def _computation_closure(module: HloModule, root: str) -> set[str]:
    """``root`` plus every computation transitively called from it."""
    seen: set[str] = set()
    work = [root]
    while work:
        c = work.pop()
        if c in seen or c not in module.computations:
            continue
        seen.add(c)
        for i in module.computations[c].instructions.values():
            work.extend(i.called)
    return seen


def ring_scan_trip_counts(module: HloModule) -> list[int]:
    """Trip counts of the rotation scan(s): every ``while`` whose body
    (transitively) contains a ``collective-permute``, with the bound read
    from the compare-against-constant in its condition computation. This is
    how the bidir round-count claim (⌊P/2⌋+1 scan steps instead of P) is
    machine-checked from the lowered HLO instead of trusted from the Python
    that emitted it (tests/test_hlo_overlap.py; the dump artifact records
    it in overlap_verdict.json). Inner tile loops (``lax.map`` over query
    tiles, the corpus-tile scan) contain no collectives and are excluded by
    construction."""
    out = []
    for c in module.computations.values():
        for i in c.instructions.values():
            if i.opcode != "while":
                continue
            mb = _WHILE_BODY_RE.search(i.attrs)
            mc = _WHILE_COND_RE.search(i.attrs)
            if not mb or not mc:
                continue
            has_permute = any(
                instr.opcode.startswith(RING_COLLECTIVE)
                for comp in _computation_closure(module, mb.group(1))
                for instr in module.computations[comp].instructions.values()
            )
            if not has_permute:
                continue
            cond = module.computations.get(mc.group(1))
            if cond is None:
                continue
            for ci in cond.instructions.values():
                if ci.opcode != "compare" or "direction=LT" not in ci.attrs:
                    continue
                for op in ci.operands:
                    src = cond.instructions.get(op)
                    if src is None or src.opcode != "constant":
                        continue
                    m = _INT_CONST_RE.match(src.operand_text)
                    if m:
                        out.append(int(m.group(1)))
    return out


# ---------------------------------------------------------------------------
# R5: donation/aliasing of the serving batch program

# module-header alias entry: `{output_index}: (param, {param_index}, kind)`
_ALIAS_ENTRY_RE = re.compile(
    r"\{\s*(\d*)\s*\}\s*:\s*\(\s*(\d+)\s*,\s*\{[^}]*\}\s*,"
    r"\s*(?:may|must)-alias\s*\)"
)
# buffer_donor entry (pre-optimization form on sharded programs, where the
# concrete aliasing is resolved at compile time): `(param, {param_index})`
_DONOR_ENTRY_RE = re.compile(r"\(\s*(\d+)\s*,\s*\{[^}]*\}\s*\)")


def _header_group(header: str, attr: str) -> str | None:
    """The balanced ``{...}`` payload of a module-header attribute."""
    start = header.find(attr + "={")
    if start < 0:
        return None
    i = start + len(attr) + 1
    depth = 0
    for j in range(i, len(header)):
        if header[j] == "{":
            depth += 1
        elif header[j] == "}":
            depth -= 1
            if depth == 0:
                return header[i: j + 1]
    return header[i:]


def output_aliases(module: HloModule) -> dict[int, int]:
    """``{output_index: param_number}`` from the module header's
    ``input_output_alias`` (a single non-tuple output is index 0). Output
    indices — not python argnums — are the stable coordinate: jax elides
    unused arguments from the lowered program, renumbering parameters."""
    grp = _header_group(module.header, "input_output_alias")
    if not grp:
        return {}
    return {
        int(out or 0): int(param)
        for out, param in _ALIAS_ENTRY_RE.findall(grp)
    }


def donor_params(module: HloModule) -> set[int]:
    """Parameter numbers declared in ``buffer_donor`` (the not-yet-resolved
    donation form jax emits for sharded programs before optimization)."""
    grp = _header_group(module.header, "buffer_donor")
    if not grp:
        return set()
    return {int(p) for p in _DONOR_ENTRY_RE.findall(grp)}


def entry_output_count(module: HloModule) -> int:
    """Top-level output arity of the entry computation, read from the
    header's ``entry_computation_layout`` ``->(...)`` group (1 for a
    non-tuple output)."""
    m = re.search(r"->", module.header)
    if not m:
        return 0
    rest = module.header[m.end():].lstrip()
    if not rest.startswith("("):
        return 1
    depth = 0
    count = 1
    for ch in rest:
        if ch in "({[":
            depth += 1
        elif ch in ")}]":
            depth -= 1
            if depth == 0:
                break
        elif ch == "," and depth == 1:
            count += 1
    return count


def oversized_copies(module: HloModule, threshold_bytes: int):
    """``copy``/``copy-start`` instructions materializing a buffer of at
    least ``threshold_bytes`` (async pairs: the ``-start`` carries the
    semantics; ``copy-done`` returns the same buffer and is skipped)."""
    out = []
    for c in module.computations.values():
        for i in c.instructions.values():
            if i.opcode not in ("copy", "copy-start"):
                continue
            b = max_buffer_bytes(i.type_str)
            if b >= threshold_bytes:
                out.append((c.name, i.name, b))
    return out


@register
class R5Donation(Rule):
    name = "R5-donation"
    description = (
        "serving batch programs declare the per-batch scratch donation in "
        "the module header (buffer_donor before opt, input_output_alias "
        "after) and contain no resident-corpus-sized copy — steady-state "
        "serving must reuse memory in place, not re-pay the corpus"
    )

    def applies(self, ctx) -> bool:
        # serve batch programs AND the live-mutation programs (ISSUE 14:
        # the donation contract extends to upsert/delete/compact — an
        # un-donated store update would re-pay the corpus per chunk)
        return bool(
            getattr(ctx.target, "serve", False)
            or getattr(ctx.target, "mutate", "")
        )

    def check(self, ctx, stage, module) -> list[Finding]:
        out = []
        if ctx.meta.get("donated_params"):
            aliases = output_aliases(module)
            n_out = entry_output_count(module)
            unaliased = sorted(set(range(n_out)) - set(aliases))
            if unaliased and stage == "after_opt":
                # the compiled program is the ground truth: every output
                # buffer must alias a donated input or each batch
                # allocates fresh result+scratch memory — the in-place
                # steady state the engine promises did not materialize
                # (declared-but-dropped donation lands here too)
                out.append(
                    Finding(
                        self.name,
                        ctx.target.label,
                        stage,
                        f"output buffer(s) {unaliased} of {n_out} carry "
                        "no input_output_alias in the compiled program — "
                        "the donated scratch is not reused in place; "
                        "every batch allocates fresh result memory",
                        {"aliases": {str(k): v
                                     for k, v in aliases.items()},
                         "outputs": n_out},
                    )
                )
            elif not aliases and not donor_params(module):
                # before optimization the donation may still be the
                # unresolved buffer_donor form (sharded programs); what is
                # NOT acceptable is a serve program with no donation
                # declaration at all
                out.append(
                    Finding(
                        self.name,
                        ctx.target.label,
                        stage,
                        "serve program declares no donation at all (no "
                        "input_output_alias, no buffer_donor) — every "
                        "batch allocates a fresh carry instead of "
                        "reusing the donated one in place",
                        {"outputs": n_out},
                    )
                )
        resident = ctx.meta.get("resident_bytes", 0)
        if resident:
            # Deliberate blind spot, not an oversight: on ring cells the
            # compiled SPMD module is per-shard, and a shard-sized copy is
            # the ROTATION ITSELF (each round copies the traveling block —
            # exactly c_pad/ring_n rows — through the loop state), so a
            # per-shard threshold flags every correct ring program. A
            # redundant local-shard copy is size-indistinguishable from
            # that legitimate traffic; the census therefore keeps the
            # GLOBAL corpus bound everywhere (it still catches full-corpus
            # materializations, and R4's collective accounting covers the
            # regather class on rings).
            for comp, name, b in oversized_copies(module, resident):
                out.append(
                    Finding(
                        self.name,
                        ctx.target.label,
                        stage,
                        f"{comp}::{name} copies {b} bytes >= the resident "
                        f"corpus ({resident} bytes) inside the per-batch "
                        "program — the corpus the index amortized is being "
                        "re-copied every batch",
                        {"bytes": b, "resident_bytes": resident},
                    )
                )
        return out


@register
class R4Collectives(Rule):
    name = "R4-collective"
    description = (
        "ring programs contain exactly the corpus-rotation permutes "
        "(uni: one forward pair; bidir: two counter-directed pairs) with "
        "ring-shaped source_target_pairs; sharded-IVF programs exactly "
        "the candidate-exchange all-to-alls (full-ring groups, payload "
        "inside the declared budget); single-device programs contain no "
        "collectives — anything else is a sharding leak"
    )

    def applies(self, ctx) -> bool:
        # sharded-store MUTATION programs are GSPMD-partitioned scatters:
        # they have no candidate exchange to account (the partitioner
        # owns whatever plumbing it emits), so the sharded-exchange
        # checker has no claim there; single-device mutation cells keep
        # the no-collectives check like every other single-device program
        if (
            getattr(ctx.target, "mutate", "")
            and ctx.target.backend == "ivf-sharded"
        ):
            return False
        return True

    def _check_sharded_exchange(self, ctx, stage, module, found):
        """The sharded-IVF accounting: the candidate exchange is EXACTLY
        ``expected_alltoalls`` all-to-alls per tile (request table + the
        rows/ids/norms returns), each over the single full-ring replica
        group, with total payload bytes inside the declared per-tile
        exchange budget. Anything else — a collective-permute (this
        search has no rotation), an all-gather/broadcast (an unrouted
        full-bucket exchange would re-centralize the corpus the sharding
        exists to distribute), a partial replica group, or an over-budget
        payload — is a finding."""
        t = ctx.target
        out = []
        for op, hits in found.items():
            if op == "all-to-all":
                continue
            out.append(
                Finding(
                    self.name,
                    t.label,
                    stage,
                    f"sharded-clustered program contains a stray {op} "
                    f"({len(hits)}×, e.g. {hits[0][1]}) — the only legal "
                    "collective is the routed candidate exchange's "
                    "all-to-all; an unrouted broadcast/gather would move "
                    "whole bucket stores instead of routed candidates",
                    {"op": op, "count": len(hits)},
                )
            )
        census = alltoall_census(module, ctx.meta.get("shards", 0))
        if stage == "before_opt":
            expected = ctx.meta.get("expected_alltoalls")
            if expected is not None and census["count"] != expected:
                out.append(
                    Finding(
                        self.name,
                        t.label,
                        stage,
                        f"expected exactly {expected} all-to-alls per tile "
                        "(request table + rows/ids/norms candidate "
                        f"returns), found {census['count']}",
                        {"count": census["count"]},
                    )
                )
            for bad in census["bad_groups"]:
                out.append(
                    Finding(
                        self.name,
                        t.label,
                        stage,
                        f"{bad} replica_groups is not the single full-"
                        f"ring group over {ctx.meta.get('shards')} shards "
                        "— a partial-group exchange cannot reach every "
                        "owner the routing table names",
                        {"shards": ctx.meta.get("shards")},
                    )
                )
            budget = ctx.meta.get("exchange_bytes_tile")
            if budget is not None and census["bytes"] > budget:
                out.append(
                    Finding(
                        self.name,
                        t.label,
                        stage,
                        f"candidate exchange moves {census['bytes']} bytes "
                        f"per tile > the declared budget {budget} "
                        "(shards·route_cap·(request + bucket payload)) — "
                        "an over-budget per-shard gather is scanning more "
                        "than it routed",
                        {"bytes": census["bytes"], "budget": budget},
                    )
                )
        elif census["count"] == 0:
            out.append(
                Finding(
                    self.name,
                    t.label,
                    stage,
                    "sharded-clustered program compiled to zero "
                    "all-to-alls — the candidate exchange was optimized "
                    "away (results can only be correct if no query ever "
                    "probes a remote shard, i.e. they are not)",
                    {},
                )
            )
        return out

    def check(self, ctx, stage, module) -> list[Finding]:
        found = count_collectives(module)
        t = ctx.target
        out = []
        if t.backend == "ivf-sharded":
            return self._check_sharded_exchange(ctx, stage, module, found)
        if t.backend not in ("ring", "ring-overlap"):
            for op, hits in found.items():
                out.append(
                    Finding(
                        self.name,
                        t.label,
                        stage,
                        f"single-device backend lowered a collective: "
                        f"{len(hits)}× {op} ({hits[0][1]}, …) — sharding "
                        "leak",
                        {"op": op, "count": len(hits)},
                    )
                )
            return out

        for op in STRAY_COLLECTIVES:
            if op in found:
                hits = found[op]
                out.append(
                    Finding(
                        self.name,
                        t.label,
                        stage,
                        f"ring program contains a stray {op} "
                        f"({len(hits)}×, e.g. {hits[0][1]}) — a sharding "
                        "leak would regather the corpus every round",
                        {"op": op, "count": len(hits)},
                    )
                )
        permutes = found.get(RING_COLLECTIVE, [])
        expected = ctx.meta.get("expected_permutes")
        # wire-dtype pricing (quantized transfer cells): every rotation
        # permute's payload must fit the block's WIRE bytes — int8 codes
        # for the block, s32/f32 for the small id/scale rows. A permute
        # moving 4× the budget is rotating float rows under an int8
        # label: the recall cost of quantization with none of the byte
        # win. Before-opt only (the combiner may later legally fuse the
        # three permutes into one tuple-typed collective).
        pbudget = ctx.meta.get("permute_bytes_budget")
        if stage == "before_opt" and pbudget is not None:
            for comp, name in permutes:
                b = max_buffer_bytes(module.instr(comp, name).type_str)
                if b > pbudget:
                    out.append(
                        Finding(
                            self.name,
                            t.label,
                            stage,
                            f"{comp}::{name} moves {b} bytes > the "
                            f"wire-dtype budget {pbudget} (the int8 code "
                            "block) — the rotation is shipping wider "
                            "payload than the declared transfer dtype",
                            {"bytes": b, "budget": pbudget},
                        )
                    )
        if stage == "before_opt" and expected is not None:
            sched = ctx.meta.get("ring_schedule", "uni")
            if len(permutes) != expected:
                out.append(
                    Finding(
                        self.name,
                        t.label,
                        stage,
                        f"expected exactly {expected} collective-permutes "
                        + (
                            "(corpus block + ids rotation, one pair per "
                            "torus direction)"
                            if sched == "bidir"
                            else "(corpus block + ids rotation)"
                        )
                        + f", found {len(permutes)}",
                        {"count": len(permutes)},
                    )
                )
            ring_n = ctx.meta.get("ring_n")
            if ring_n and sched == "bidir":
                # bidir accounting: 2 permutes per round per DIRECTION
                # (block + ids), counter-directed source_target_pairs.
                # A wrong-direction permute merges blocks in an order the
                # ⌊P/2⌋+1-round plan does not account for (results wrong);
                # a missing one means a traveler stopped moving (a silent
                # fallback to half-duplex) — both are findings.
                census = permute_direction_census(module, ring_n)
                for instr_label in census["other"]:
                    out.append(
                        Finding(
                            self.name,
                            t.label,
                            stage,
                            f"{instr_label} source_target_pairs is neither "
                            f"the forward nor the backward {ring_n}-ring "
                            "rotation — a wrong-direction permute breaks "
                            "the bidir round plan",
                            {"census": {k: census[k] for k in ("fwd", "bwd")}},
                        )
                    )
                if ring_n <= 2:
                    # the two rotations coincide on a <=2-ring (the census
                    # files everything under "fwd"), so only the combined
                    # count is checkable — a per-direction split here would
                    # fail every correct program
                    if census["fwd"] + census["bwd"] != expected:
                        out.append(
                            Finding(
                                self.name,
                                t.label,
                                stage,
                                f"bidir schedule must issue {expected} "
                                f"ring-rotation permutes on the {ring_n}-"
                                "ring (directions coincide there), found "
                                f"{census['fwd'] + census['bwd']}",
                                {"census": {k: census[k]
                                            for k in ("fwd", "bwd")}},
                            )
                        )
                else:
                    want_each = expected // 2
                    for direction in ("fwd", "bwd"):
                        if census[direction] != want_each:
                            out.append(
                                Finding(
                                    self.name,
                                    t.label,
                                    stage,
                                    "bidir schedule must rotate block + "
                                    f"ids in the {direction} direction "
                                    f"({want_each} permutes), found "
                                    f"{census[direction]} — a missing "
                                    "counter-directed permute is a silent "
                                    "fallback to half-duplex",
                                    {"census": {k: census[k]
                                                for k in ("fwd", "bwd")}},
                                )
                            )
            elif ring_n:
                want, _ = ring_rotation_pairs(ring_n)
                for comp, name in permutes:
                    pairs = _permute_pairs(module, comp, name)
                    if pairs is not None and pairs != want:
                        out.append(
                            Finding(
                                self.name,
                                t.label,
                                stage,
                                f"{comp}::{name} source_target_pairs "
                                f"{pairs} is not the {ring_n}-ring rotation",
                                {"pairs": pairs},
                            )
                        )
        elif stage == "after_opt" and not permutes:
            if ctx.meta.get("fused_dma"):
                # the fused rotation's kernel-owned-transport form: zero
                # permutes is the intended lowering (the block moves via
                # async remote copies inside the Pallas kernel). The
                # corpus still rotates — but through a channel this
                # census cannot see, so the accounting hand-off is the
                # declared side-band: absent, the cell gets the same
                # rotation-vanished finding the xla form would (an
                # undeclared fused DMA is indistinguishable from a
                # DCE'd rotation to static analysis).
                if not ctx.meta.get("fused_dma_wire_bytes"):
                    out.append(
                        Finding(
                            self.name,
                            t.label,
                            stage,
                            "fused ring program has zero collective-"
                            "permutes and NO declared in-kernel DMA "
                            "wire bytes (meta['fused_dma_wire_bytes']) "
                            "— an undeclared fused rotation is "
                            "indistinguishable from one that was "
                            "optimized away (unpriced fused DMA)",
                            {},
                        )
                    )
            else:
                out.append(
                    Finding(
                        self.name,
                        t.label,
                        stage,
                        "ring program compiled to zero collective-permutes "
                        "— the rotation was optimized away (results can "
                        "only be correct if the corpus never moved, i.e. "
                        "they are not)",
                        {},
                    )
                )
        return out


# ---------------------------------------------------------------------------
# R6: clustered-index probe discipline

# a dot with a non-empty batch dimension list — the per-query candidate
# form (q, d) × (q, v, d): the only legal way corpus payload reaches a dot
# in an IVF program, because the batched candidate operand can only come
# from the per-query probe gather
_BATCH_DIMS_RE = re.compile(r"(?:lhs|rhs)_batch_dims=\{\s*\d")


@register
class R6IvfProbe(Rule):
    name = "R6-ivf-probe"
    description = (
        "clustered (IVF) programs score corpus payload ONLY through the "
        "probe gather: every batched candidate dot is fed by a gather, at "
        "least one exists, and no un-batched dot is wider than the "
        "centroid score — a full-corpus dot would bypass the partition "
        "pruning the index exists for"
    )

    def applies(self, ctx) -> bool:
        # the sharded form keeps the same probe discipline: the routed
        # exchange only ever moves gathered buckets, so every batched
        # candidate dot still carries a gather in its backward slice.
        # Mutation programs have no candidate dots at all (a scatter and
        # at most the centroid-score assignment) — the ≥1-probe-dot
        # vacuity guard would misfire there, so they are out of scope.
        if getattr(ctx.target, "mutate", ""):
            return False
        return getattr(ctx.target, "backend", None) in ("ivf", "ivf-sharded")

    def check(self, ctx, stage, module) -> list[Finding]:
        if stage != "before_opt":
            # after optimization fusion legitimately rewrites dots and
            # gathers into fusion computations; the declared dataflow is
            # pinned on the module XLA receives (the R3-contract stance)
            return []
        out = []
        n_batched = 0
        # un-batched dots may only be the centroid score: operands are the
        # (q_tile, d) query tile and the (partitions, d) routing table
        allowed = (
            max(ctx.meta.get("q_tile", 0), ctx.meta.get("partitions", 0))
            * ctx.meta.get("dim", 0)
        )
        for c in module.computations.values():
            for i in c.instructions.values():
                if i.opcode != "dot":
                    continue
                if _BATCH_DIMS_RE.search(i.attrs):
                    n_batched += 1
                    sl = backward_slice(module, c.name, i.name)
                    if "gather" not in slice_opcodes(module, sl):
                        out.append(
                            Finding(
                                self.name,
                                ctx.target.label,
                                stage,
                                f"{c.name}::{i.name} is a batched "
                                "candidate dot with NO gather in its "
                                "backward slice — it scores rows the "
                                "probe never selected (the partition "
                                "pruning is bypassed)",
                                {"type": i.type_str},
                            )
                        )
                elif allowed:
                    op_elems = max(
                        (
                            max_buffer_elems(c.instructions[o].type_str)
                            for o in i.operands
                            if o in c.instructions
                        ),
                        default=0,
                    )
                    if op_elems > allowed:
                        out.append(
                            Finding(
                                self.name,
                                ctx.target.label,
                                stage,
                                f"{c.name}::{i.name} is an un-batched dot "
                                f"over {op_elems} elems > the centroid "
                                f"score bound {allowed} (max(q_tile, "
                                "partitions)·d) — a full-corpus dot "
                                "bypasses the partition pruning",
                                {"elems": op_elems, "bound": allowed},
                            )
                        )
        if n_batched == 0:
            out.append(
                Finding(
                    self.name,
                    ctx.target.label,
                    stage,
                    "IVF program lowered NO batched candidate dot — the "
                    "probe-gather contract is vacuous (nothing scores the "
                    "gathered candidates exactly)",
                    {},
                )
            )
        return out


# ---------------------------------------------------------------------------
# R7: peak-HBM certification (ISSUE 15). The analyzer lives in
# analysis/memory.py (liveness model, aliasing, budget derivation, the
# PJRT cross-check, the ledger); this class is the registry adapter —
# the import direction is rules → memory ONLY, so memory.py keeps its
# own shape readers and can be unit-tested without the rule registry.

from mpi_knn_tpu.analysis import memory as _memory  # noqa: E402


@register
class R7PeakMemory(Rule):
    name = "R7-peak-memory"
    description = (
        "aliasing-aware liveness peak of the after-opt program: peak "
        "live bytes (def-use intervals, donated scratch counted once, "
        "while bodies loop-resident, fusions collapsed) must fit the "
        "budget derived from the cell's index facts, and must agree "
        "with PJRT's own memory_analysis() within the declared "
        "tolerance — disagreement is itself a finding"
    )

    def applies(self, ctx) -> bool:
        return True

    def check(self, ctx, stage, module) -> list[Finding]:
        return _memory.r7_check(ctx, stage, module, Finding)


# R8: the static cost certification. Everything substantive lives in
# analysis/cost.py (dot-FLOP counter with loop multiplicities, the
# closed-form exactness contract, the wire-priced collective census,
# the roofline, the cost ledger); this class is the registry adapter —
# the import direction is rules → cost ONLY, mirroring R7.

from mpi_knn_tpu.analysis import cost as _cost  # noqa: E402


@register
class R8Cost(Rule):
    name = "R8-cost"
    description = (
        "static cost model of the after-opt program: MXU FLOPs from dot "
        "shapes × statically-read loop trip counts must EXACTLY equal "
        "the closed-form count from the cell's declared configuration "
        "facts (disagreement in either direction is a finding), every "
        "collective-family opcode must be in the wire-price registry, "
        "and the FLOP/HBM/ICI totals land in the committed cost ledger "
        "with a roofline q/s bound under the declared device profile"
    )

    def applies(self, ctx) -> bool:
        return True

    def check(self, ctx, stage, module) -> list[Finding]:
        return _cost.r8_check(ctx, stage, module, Finding)


# registration order follows source position; the registry is presented in
# rule-number order regardless (R5's helpers sit above R4 in the file so
# they can share the R2 shape readers)
RULES.sort(key=lambda r: r.name)
