"""Rule engine: lower each configuration, parse both stages, run every
applicable rule, aggregate a machine-readable report.

The report is the artifact: ``mpi-knn lint`` writes it to
``artifacts/lint/report.json`` and exits non-zero on any violation, so a
CI step (scripts/check.sh) — or a human before a TPU reservation — gets a
single yes/no with the full evidence attached.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
from dataclasses import field

import jax

from mpi_knn_tpu.analysis import rules as rules_mod
from mpi_knn_tpu.analysis.lowering import (
    LintTarget,
    UnsupportedTarget,
    default_targets,
    lower_target,
)
from mpi_knn_tpu.analysis.rules import Finding, rules_by_name
from mpi_knn_tpu.utils.hlo_graph import parse_hlo

SCHEMA_VERSION = 1


@dataclasses.dataclass
class LintContext:
    """What a rule may know about the program under inspection: the matrix
    cell, the config it was lowered with, and lowering metadata (tile
    sizes, accumulation width, ring topology)."""

    target: LintTarget
    cfg: object
    meta: dict


@dataclasses.dataclass
class TargetResult:
    target: LintTarget
    findings: list[Finding] = field(default_factory=list)
    rules_run: list[str] = field(default_factory=list)
    stages: list[str] = field(default_factory=list)
    skipped: str | None = None  # UnsupportedTarget reason
    # R7's per-cell memory ledger entry (peak live bytes, attribution,
    # budget, PJRT cross-check numbers) — populated whenever the
    # peak-memory rule ran on the after-opt stage (analysis.memory)
    memory: dict | None = None
    # R8's per-cell cost ledger entry (MXU FLOPs + the analytical
    # cross-check, modeled HBM traffic, wire-priced ICI bytes, roofline
    # under the default profile) — populated whenever the cost rule ran
    # on the after-opt stage (analysis.cost)
    cost: dict | None = None

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_json(self) -> dict:
        return {
            "label": self.target.label,
            "backend": self.target.backend,
            "metric": self.target.metric,
            "dtype": self.target.dtype,
            "policy": self.target.policy,
            "schedule": self.target.schedule,
            "fusion": self.target.fusion,
            "quant": self.target.quant,
            "serve": self.target.serve,
            "ladder": self.target.ladder,
            "frontend": self.target.frontend,
            "mutate": self.target.mutate,
            "ok": self.ok,
            "skipped": self.skipped,
            "rules_run": self.rules_run,
            "stages": self.stages,
            "findings": [f.to_json() for f in self.findings],
            "memory": self.memory,
            "cost": self.cost,
        }


@dataclasses.dataclass
class LintReport:
    results: list[TargetResult]

    @property
    def ok(self) -> bool:
        return all(r.ok for r in self.results)

    @property
    def findings(self) -> list[Finding]:
        return [f for r in self.results for f in r.findings]

    def to_json(self) -> dict:
        checked = [r for r in self.results if r.skipped is None]
        return {
            "schema_version": SCHEMA_VERSION,
            "source": "mpi_knn_tpu.analysis",
            "jax_version": jax.__version__,
            "platform": jax.default_backend(),
            "device_count": jax.device_count(),
            "ok": self.ok,
            "summary": {
                "targets_checked": len(checked),
                "targets_skipped": len(self.results) - len(checked),
                "findings": len(self.findings),
            },
            "targets": [r.to_json() for r in self.results],
        }

    def save(self, out_dir) -> pathlib.Path:
        out_dir = pathlib.Path(out_dir)
        out_dir.mkdir(parents=True, exist_ok=True)
        path = out_dir / "report.json"
        path.write_text(json.dumps(self.to_json(), indent=1) + "\n")
        return path


def run_rules(
    texts: dict[str, str],
    ctx: LintContext,
    rules: list | None = None,
) -> tuple[list[Finding], list[str]]:
    """Run rules over already-lowered stage texts. Split out from
    :func:`lint_target` so tests can feed deliberately broken lowerings
    (a de-tiled distance matrix, an injected sharding leak) through the
    exact production rule path."""
    rules = rules_mod.RULES if rules is None else rules
    findings: list[Finding] = []
    ran: list[str] = []
    applicable = [r for r in rules if r.applies(ctx)]
    for rule in applicable:
        ran.append(rule.name)
    for stage, text in texts.items():
        module = parse_hlo(text)
        for rule in applicable:
            findings.extend(rule.check(ctx, stage, module))
    return findings, ran


def lint_target(
    target: LintTarget, rule_names: list[str] | None = None
) -> TargetResult:
    """Lower one matrix cell and run every applicable rule on both stages."""
    rules = rules_by_name(rule_names)
    res = TargetResult(target=target)
    try:
        texts, cfg, meta = lower_target(target)
    except UnsupportedTarget as e:
        res.skipped = str(e)
        return res
    res.stages = list(texts)
    # a per-run copy: lower_target's meta is lru_cached and shared across
    # runs, and R7 stashes its ledger entry into the context's meta
    ctx = LintContext(target=target, cfg=cfg, meta=dict(meta))
    res.findings, res.rules_run = run_rules(texts, ctx, rules)
    res.memory = ctx.meta.get("r7_analysis")
    res.cost = ctx.meta.get("r8_analysis")
    return res


def run_matrix(
    targets: list[LintTarget] | None = None,
    rule_names: list[str] | None = None,
    progress=None,
) -> LintReport:
    """The full backend × metric × dtype sweep (or a filtered subset)."""
    targets = default_targets() if targets is None else targets
    results = []
    for t in targets:
        r = lint_target(t, rule_names)
        if progress is not None:
            progress(r)
        results.append(r)
    return LintReport(results=results)
