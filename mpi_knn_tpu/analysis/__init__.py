"""Static analysis of compiled programs — the framework's "catch it" layer.

A rule engine over XLA HLO: every registered backend × metric × dtype
configuration is lowered on CPU and a suite of static rules runs against
the resulting def-use graph (see ``analysis/README.md`` and the rule
docstrings in :mod:`mpi_knn_tpu.analysis.rules`). Grown from the
single-purpose ring-overlap checker that caught a real sequencing bug in
``backends/ring.py`` (VERDICT r5); the parsing core it was built on stays
in :mod:`mpi_knn_tpu.utils.hlo_graph`.

Entry points: ``mpi-knn lint`` (CLI), :func:`run_matrix` /
:func:`lint_target` (programmatic), ``tests/test_hlo_lint.py`` (tier-1).
"""

from mpi_knn_tpu.analysis.engine import (
    LintContext,
    LintReport,
    TargetResult,
    lint_target,
    run_matrix,
    run_rules,
)
from mpi_knn_tpu.analysis.lowering import (
    LintTarget,
    UnsupportedTarget,
    default_targets,
    lower_target,
)
from mpi_knn_tpu.analysis.rules import RULES, Finding, property_holds

__all__ = [
    "Finding",
    "LintContext",
    "LintReport",
    "LintTarget",
    "RULES",
    "TargetResult",
    "UnsupportedTarget",
    "default_targets",
    "lint_target",
    "lower_target",
    "property_holds",
    "run_matrix",
    "run_rules",
]
