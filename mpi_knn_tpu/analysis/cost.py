"""Static cost certification: per-cell FLOP/byte/roofline model over
after-opt HLO, the committed cost ledger, and lint rule **R8-cost**
(ISSUE 16).

R7 made peak residency a statically certified, CI-gated number; this
module does the same for *work*. For every matrix cell it computes, from
the after-opt module text alone:

- **MXU FLOPs** — every ``dot``/``convolution``, priced as
  ``2 · |result| · |contraction|`` (shapes read from the printed operand
  types and the ``lhs_contracting_dims`` attribute), multiplied by the
  instruction's static execution count: the product of the trip counts
  of every enclosing ``while`` along its call chain, with trip counts
  read from the compare-against-constant in each loop's condition
  computation (the same reader R4 uses for the rotation scan). This is
  the honest count of what the machine executes — including, e.g., the
  bidirectional ring's duplicated middle block.
- **HBM traffic** — the modeled bytes moved: every materializing
  instruction (R7's forwarding model decides what materializes — pointer
  shuffles and in-place update forms are free) writes its result buffer
  once and reads each operand buffer once, scaled by the same execution
  multiplicities; fusion bodies and per-element appliers are collapsed
  (fused intermediates live in registers — only the fusion's result and
  operands touch HBM). A documented traffic *model*, not a hardware
  counter.
- **ICI bytes** — the wire-priced collective census: each collective's
  result buffer bytes × its execution multiplicity, over a closed
  registry of priced collective opcodes. A collective opcode OUTSIDE the
  registry is a finding ("unpriced collective"), not a silent zero —
  bytes-on-wire is a certified budget elsewhere (R4) and must never
  leak.

The FLOP side carries the same honesty contract R7 holds against PJRT:
the HLO-derived count must EXACTLY equal a closed-form analytical count
derived from the cell's own declared configuration facts
(``meta["cost"]``, written by each lowerer) — a dense tile is
``2·q·c·d`` plus its rerank term, a clustered probe is the centroid
score plus ``2·q·nprobe·cap·d``. Disagreement in either direction is a
finding: HLO > analytical means the program does work the model cannot
name; HLO < analytical means the counter lost a loop or a dot.

A declared **device profile** (peak FLOP/s, HBM bandwidth, ICI
bandwidth — shipped as data in ``device_profiles.json``, never code)
turns the three totals into a roofline lower bound on wall-clock per
batch and an upper bound on queries/s. Per-cell results land in the
committed ``artifacts/lint/cost_ledger.json`` with the same drift gate
the memory ledger uses (shared machinery: analysis/ledger.py) — growth
beyond tolerance is a perf regression naming the culprit op, shrinkage
is a stale ledger hiding a banked win. ``mpi_knn_tpu/plan.py`` inverts
these same functions into the capacity planner; it calls THIS module
(shared code path), never a re-derivation.
"""

from __future__ import annotations

import json
import pathlib
import re
from dataclasses import dataclass

from mpi_knn_tpu.analysis import ledger as _ledger
from mpi_knn_tpu.analysis.memory import _is_forwarding, total_buffer_bytes
from mpi_knn_tpu.utils.hlo_graph import HloModule

# ---------------------------------------------------------------------------
# device profiles — data, not code

_PROFILES_PATH = pathlib.Path(__file__).parent / "device_profiles.json"
DEFAULT_PROFILE = "cpu-test"


def load_profiles() -> dict:
    """The shipped device profiles, keyed by name. Each profile declares
    ``peak_flops`` (FLOP/s), ``hbm_bw`` / ``ici_bw`` (bytes/s), and
    ``hbm_bytes`` (per-device capacity, used by the planner)."""
    doc = json.loads(_PROFILES_PATH.read_text())
    return {k: v for k, v in doc.items() if not k.startswith("_")}


def get_profile(name: str) -> dict:
    profiles = load_profiles()
    if name not in profiles:
        raise KeyError(
            f"unknown device profile {name!r} (shipped: "
            f"{', '.join(sorted(profiles))})"
        )
    return profiles[name]


def profile_for_platform(platform: str, device_kind: str = "") -> str | None:
    """Best-effort map from a running JAX platform / device kind to a
    shipped profile name — ``None`` for hardware we ship no numbers for
    (absent, never a guessed profile)."""
    kind = device_kind.lower()
    if platform == "cpu":
        return "cpu-test"
    if platform == "tpu":
        if "v5 lite" in kind or "v5e" in kind or "v5litepod" in kind:
            return "tpu-v5e"
        if "v4" in kind:
            return "tpu-v4"
    return None


def detected_profile() -> dict | None:
    """The declared profile facts for the RUNNING process (lazy jax
    import — this module stays importable jax-free): ``{"name", ...}``
    with the profile's numbers inlined, or ``None`` off the map. This is
    what ``/healthz`` and the serve ``--report`` stamp, so an operator
    reads a deployment's measured throughput next to the declared
    roofline inputs the planner predicted it under."""
    try:
        import jax

        platform = jax.default_backend()
        kind = getattr(jax.devices()[0], "device_kind", "")
    except Exception:
        return None
    name = profile_for_platform(platform, kind)
    if name is None:
        return None
    return {"name": name, **get_profile(name)}


# ---------------------------------------------------------------------------
# execution multiplicities: how many times each computation runs per
# entry execution, statically

_WHILE_BODY_RE = re.compile(r"body=%?([\w.\-]+)")
_WHILE_COND_RE = re.compile(r"condition=%?([\w.\-]+)")
_INT_CONST_RE = re.compile(r"^\s*(-?\d+)\s*$")
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([0-9,]*)\}")

# computations called by these opcodes execute once PER ELEMENT of their
# caller's operand — a static per-program count does not exist for them,
# so a dot inside one is unpriceable (a finding, never a guess)
_PER_ELEMENT_CALLERS = frozenset(
    {"reduce", "reduce-window", "sort", "scatter", "select-and-scatter",
     "map", "reduce-scatter", "all-reduce"}
)


def while_trip_count(module: HloModule, instr) -> int | None:
    """The static trip count of one ``while``: the integer constant its
    condition compares the induction variable against with ``LT`` —
    counted loops lowered from ``lax`` scans/maps/fori all print this
    form. ``None`` when the bound is not statically visible."""
    mc = _WHILE_COND_RE.search(instr.attrs)
    if not mc:
        return None
    cond = module.computations.get(mc.group(1))
    if cond is None:
        return None
    for ci in cond.instructions.values():
        if ci.opcode != "compare" or "direction=LT" not in ci.attrs:
            continue
        for op in ci.operands:
            src = cond.instructions.get(op)
            if src is not None and src.opcode == "constant":
                m = _INT_CONST_RE.match(src.operand_text)
                if m:
                    return int(m.group(1))
    return None


def computation_multiplicities(module: HloModule) -> dict:
    """Static execution count per computation, from the entry down the
    call graph: a ``while`` body runs ``trip`` times per caller
    execution (its condition ``trip + 1``), fusion/call/conditional
    bodies run once per caller execution, and per-element appliers get
    ``None`` (unpriceable — see ``_PER_ELEMENT_CALLERS``). A ``while``
    whose bound is not statically readable also propagates ``None``."""
    entry = next(
        (n for n, c in module.computations.items() if c.is_entry), None
    )
    mult: dict = {entry: 1}
    changed = True
    guard = 0
    while changed and guard < len(module.computations) + 2:
        changed = False
        guard += 1
        for cname, comp in module.computations.items():
            base = mult.get(cname, "absent")
            if base == "absent":
                continue
            for ins in comp.instructions.values():
                if ins.opcode == "while":
                    trip = while_trip_count(module, ins)
                    mb = _WHILE_BODY_RE.search(ins.attrs)
                    mc = _WHILE_COND_RE.search(ins.attrs)
                    updates = []
                    if mb:
                        updates.append(
                            (mb.group(1),
                             None if (base is None or trip is None)
                             else base * trip)
                        )
                    if mc:
                        updates.append(
                            (mc.group(1),
                             None if (base is None or trip is None)
                             else base * (trip + 1))
                        )
                    for callee, val in updates:
                        if mult.get(callee, "absent") != val:
                            mult[callee] = val
                            changed = True
                else:
                    per_element = ins.opcode in _PER_ELEMENT_CALLERS
                    for callee in ins.called:
                        val = None if per_element else base
                        if mult.get(callee, "absent") != val:
                            mult[callee] = val
                            changed = True
    return mult


# ---------------------------------------------------------------------------
# MXU FLOPs from dot shapes × multiplicities

def _shape_dims(type_str: str) -> list[int]:
    m = _SHAPE_RE.search(type_str or "")
    if not m:
        return []
    return ([int(x) for x in m.group(2).split(",")]
            if m.group(2) else [])


@dataclass(frozen=True)
class DotSite:
    computation: str
    instruction: str
    opcode: str
    flops_each: int
    multiplicity: int
    flops: int


def dot_inventory(module: HloModule):
    """Every ``dot``/``convolution`` in the module with its per-execution
    FLOPs and static multiplicity. Returns ``(sites, problems)`` —
    problems are dots whose execution count is not statically priceable
    (inside a per-element applier or an unbounded loop): those can never
    reconcile with a closed form and must surface as findings."""
    mult = computation_multiplicities(module)
    sites, problems = [], []
    for cname, comp in module.computations.items():
        for ins in comp.instructions.values():
            if ins.opcode not in ("dot", "convolution"):
                continue
            out_elems = 1
            for d in _shape_dims(ins.type_str):
                out_elems *= d
            lhs = (comp.instructions.get(ins.operands[0])
                   if ins.operands else None)
            lhs_dims = _shape_dims(lhs.type_str if lhs else "")
            mcd = _CONTRACT_RE.search(ins.attrs)
            cdims = ([int(x) for x in mcd.group(1).split(",")]
                     if mcd and mcd.group(1) else [])
            contract = 1
            for d in cdims:
                contract *= lhs_dims[d] if d < len(lhs_dims) else 0
            m = mult.get(cname, 0)
            if m is None:
                problems.append(
                    f"dot {ins.name!r} in computation {cname!r} has no "
                    "static execution count (per-element applier or "
                    "unbounded loop) — its FLOPs cannot be certified"
                )
                continue
            each = 2 * out_elems * contract
            sites.append(
                DotSite(cname, ins.name, ins.opcode, each, m, each * m)
            )
    return sites, problems


def hlo_mxu_flops(module: HloModule):
    """``(total_flops, largest_site, problems)`` for one module."""
    sites, problems = dot_inventory(module)
    total = sum(s.flops for s in sites)
    largest = max(sites, key=lambda s: s.flops, default=None)
    return total, largest, problems


# ---------------------------------------------------------------------------
# ICI bytes: the wire-priced collective census

# the closed registry of collective opcodes this census knows how to
# price (result buffer bytes × multiplicity); ``-done`` halves of async
# pairs are skipped — their ``-start`` carries the payload
PRICED_COLLECTIVES = frozenset(
    {"collective-permute", "all-to-all", "all-gather", "all-reduce",
     "reduce-scatter", "collective-broadcast"}
)
# "ragged-" catches ragged-all-to-all, whose spelling does not start
# with a priced family prefix — without the marker it would be
# invisible to the census instead of an unpriced-collective finding
_COLLECTIVE_MARKERS = ("all-", "collective-", "reduce-scatter", "ragged-")


def _collective_base(opcode: str) -> str | None:
    """The registry key for a collective-family opcode (``-start``
    variants fold onto their base), ``None`` for ``-done`` halves and
    for non-collective opcodes."""
    if opcode.endswith("-done"):
        return None
    base = opcode[:-6] if opcode.endswith("-start") else opcode
    if any(base.startswith(p) for p in _COLLECTIVE_MARKERS):
        return base
    return None


def collective_census(module: HloModule):
    """``(ici_bytes, problems)``: modeled bytes each device puts on the
    interconnect per execution — every priced collective's result buffer
    bytes × its static multiplicity. A collective-family opcode missing
    from :data:`PRICED_COLLECTIVES` is a problem (an unpriced collective
    would silently zero its wire cost), as is a priced collective with
    no static execution count."""
    mult = computation_multiplicities(module)
    total = 0
    problems = []
    for cname, comp in module.computations.items():
        for ins in comp.instructions.values():
            base = _collective_base(ins.opcode)
            if base is None:
                continue
            if base not in PRICED_COLLECTIVES:
                problems.append(
                    f"unpriced collective {ins.opcode!r} at {ins.name!r}"
                    f" in {cname!r} — not in the wire-price registry, "
                    "its ICI bytes would silently vanish from the census"
                )
                continue
            m = mult.get(cname, 0)
            if m is None:
                problems.append(
                    f"collective {ins.opcode!r} at {ins.name!r} in "
                    f"{cname!r} has no static execution count — its ICI "
                    "bytes cannot be certified"
                )
                continue
            total += total_buffer_bytes(ins.type_str) * m
    return total, problems


# ---------------------------------------------------------------------------
# HBM traffic model

def _collapsed_computations(module: HloModule) -> set:
    """Computations whose instructions do NOT individually touch HBM:
    fusion bodies (fused intermediates live in registers) and
    per-element appliers — their caller instruction accounts for the
    traffic. While/call/conditional bodies DO materialize."""
    out = set()
    for comp in module.computations.values():
        for ins in comp.instructions.values():
            if ins.opcode == "while":
                continue
            if ins.opcode == "fusion" or ins.opcode in _PER_ELEMENT_CALLERS:
                out.update(ins.called)
    return out


def hbm_traffic_bytes(module: HloModule) -> int:
    """Modeled HBM bytes moved per execution: every materializing
    instruction (R7's forwarding model) writes its result once and reads
    each operand buffer once, × its static multiplicity; collapsed
    scopes are skipped. Unpriceable multiplicities contribute zero —
    the FLOP/ICI sides already surface them as findings."""
    mult = computation_multiplicities(module)
    collapsed = _collapsed_computations(module)
    total = 0
    for cname, comp in module.computations.items():
        m = mult.get(cname, 0)
        if cname in collapsed or not m:
            continue
        for ins in comp.instructions.values():
            if ins.opcode == "parameter" or _is_forwarding(module, ins):
                continue
            bytes_moved = total_buffer_bytes(ins.type_str)
            for op in ins.operands:
                src = comp.instructions.get(op)
                if src is not None:
                    bytes_moved += total_buffer_bytes(src.type_str)
            total += bytes_moved * m
    return total


# ---------------------------------------------------------------------------
# the analytical side of the honesty contract: closed-form MXU FLOPs
# from the cell's own declared configuration facts (meta["cost"])

def analytical_mxu_flops(facts: dict) -> int:
    """Closed-form MXU FLOPs from declared configuration facts.

    Schemes (all counts are per program execution, per device for SPMD
    programs — exactly what the per-device after-opt module runs):

    - ``zero``: mutation programs — no dots by design.
    - ``dense``: ``sites·trips·(2·q·c·d + 2·q·rblocks·w·d)`` — the tile
      distance dot over a ``(q, c)`` block plus, on mixed cells, the
      survivor rerank of ``w`` overfetched rows per rerank block. The
      one-shot dense backends are ``sites=trips=1`` with ``c`` the
      (padded) corpus; the ring schedules set ``sites`` (1, or 2 for
      bidir's forward+backward travelers), ``trips`` (``P`` uni,
      ``⌊P/2⌋+1`` bidir — the duplicated middle block is counted
      because the machine honestly executes it), and ``c`` the rotating
      corpus block.
    - ``ivf``: ``2·q·partitions·d`` centroid scoring plus
      ``2·q·v·d`` over the probed width ``v = nprobe·bucket_cap`` plus
      the mixed rerank ``2·q·rblocks·w·d``; the sharded layout runs the
      same program at its per-shard ``q``.
    """
    scheme = facts["scheme"]
    if scheme == "zero":
        return 0
    d = facts["d"]
    q = facts["q"]
    w = facts.get("w", 0)
    rblocks = facts.get("rblocks", 0)
    if scheme == "dense":
        sites = facts.get("sites", 1)
        trips = facts.get("trips", 1)
        return sites * trips * (
            2 * q * facts["c"] * d + 2 * q * rblocks * w * d
        )
    if scheme == "ivf":
        v = facts["nprobe"] * facts["bucket_cap"]
        return (
            2 * q * facts["partitions"] * d
            + 2 * q * v * d
            + 2 * q * rblocks * w * d
        )
    raise ValueError(f"unknown cost scheme {scheme!r}")


# ---------------------------------------------------------------------------
# roofline

def roofline(flops: int, hbm_bytes: int, ici_bytes: int, queries: int,
             profile: dict) -> dict:
    """The roofline lower bound on wall-clock for one execution under a
    declared device profile, and the queries/s upper bound it implies.
    ``bound`` names the binding resource — the planner surfaces it as
    the thing to buy more of."""
    legs = {
        "mxu": flops / profile["peak_flops"],
        "hbm": hbm_bytes / profile["hbm_bw"],
        "ici": (ici_bytes / profile["ici_bw"]) if ici_bytes else 0.0,
    }
    bound = max(legs, key=lambda k: legs[k])
    wall_s = legs[bound]
    return {
        "wall_s": wall_s,
        "qps": (queries / wall_s) if wall_s > 0 else float("inf"),
        "bound": bound,
    }


# ---------------------------------------------------------------------------
# the cost ledger (shared machinery: analysis/ledger.py)

COST_SCHEMA_VERSION = 1
DEFAULT_COST_LEDGER = pathlib.Path("artifacts/lint/cost_ledger.json")
COST_TOL_REL = 0.02
COST_TOL_ABS = 4096


def _dot_culprit(cell: dict) -> str:
    culprit = cell.get("largest_dot") or {}
    return (
        f"largest dot {culprit.get('flops')}FLOP "
        f"{culprit.get('op')!r} at {culprit.get('instruction')!r} "
        f"(×{culprit.get('multiplicity')})"
    )


LEDGER_SPEC = _ledger.LedgerSpec(
    kind="cost",
    schema_version=COST_SCHEMA_VERSION,
    source="mpi_knn_tpu.analysis.cost",
    regen_cmd="mpi-knn lint --cost",
    tol_rel=COST_TOL_REL,
    tol_abs=COST_TOL_ABS,
    metrics=(
        _ledger.MetricSpec(
            key="mxu_flops", noun="MXU work", unit="FLOPs",
            culprit=_dot_culprit,
        ),
        _ledger.MetricSpec(key="hbm_bytes", noun="HBM traffic",
                           unit="bytes"),
        _ledger.MetricSpec(key="ici_bytes", noun="ICI traffic",
                           unit="bytes"),
    ),
)


def load_cost_ledger(path) -> dict | None:
    return _ledger.load_ledger(path, LEDGER_SPEC)


def save_cost_ledger(path, cells: dict, merge_into: dict | None = None):
    return _ledger.save_ledger(path, cells, LEDGER_SPEC,
                               merge_into=merge_into)


def cost_ledger_drift(
    committed: dict, current: dict, *, full_matrix: bool,
    skipped_labels: frozenset | set = frozenset(),
) -> list[str]:
    return _ledger.ledger_drift(
        committed, current, LEDGER_SPEC,
        full_matrix=full_matrix, skipped_labels=skipped_labels,
    )


# ---------------------------------------------------------------------------
# the cell cost entry + R8 as a lint rule (rules.py wraps it — rules →
# cost is the only import direction, mirroring R7)

def cost_entry(module: HloModule, facts: dict,
               profile_name: str = DEFAULT_PROFILE, *,
               fused_dma: bool = False,
               fused_dma_wire_bytes: int | None = None):
    """``(ledger_entry, problems)`` for one after-opt module under its
    declared cost facts. The entry is what the cost ledger commits; the
    problems are R8 findings (exactness breaches, unpriced collectives,
    unpriceable multiplicities).

    ``fused_dma`` cells (the fused collective-matmul rotation's
    kernel-owned-transport form) move their wire bytes with async remote
    copies issued INSIDE the Pallas kernel — no collective-family opcode
    exists for the census to price, so the lowerer must declare the
    per-device rotation bytes as ``fused_dma_wire_bytes`` (the same
    ``ring_wire_bytes_per_batch`` closed form the serving engine stamps
    into its wire gauge). A fused_dma cell WITHOUT the declaration is
    the unpriced-fused-DMA finding: the cell would otherwise certify a
    zero-ICI roofline for a program that saturates the interconnect."""
    flops, largest, problems = hlo_mxu_flops(module)
    ici_bytes, ici_problems = collective_census(module)
    problems = list(problems) + ici_problems
    if fused_dma:
        if not fused_dma_wire_bytes:
            problems.append(
                "fused rotation owns its transport in-kernel (async "
                "remote DMAs) but declares no wire-byte side-band "
                "(meta['fused_dma_wire_bytes']) — the collective census "
                "sees zero collectives, so the cell's ICI bytes would "
                "silently vanish from the roofline (unpriced fused DMA)"
            )
        else:
            ici_bytes += fused_dma_wire_bytes
    hbm_bytes = hbm_traffic_bytes(module)
    analytical = analytical_mxu_flops(facts)
    if flops != analytical:
        direction = (
            "does work the closed form cannot name"
            if flops > analytical
            else "lost a loop or a dot the closed form prices"
        )
        problems.append(
            f"HLO MXU FLOPs {flops} != analytical {analytical} from "
            f"declared facts {facts!r} — the counter {direction} "
            "(exactness is the contract: both sides read the same "
            "configuration)"
        )
    profile = get_profile(profile_name)
    queries = facts.get("queries", facts.get("q", 1))
    entry = {
        "mxu_flops": flops,
        "analytical_flops": analytical,
        "hbm_bytes": hbm_bytes,
        "ici_bytes": ici_bytes,
        "intensity": (
            round(flops / hbm_bytes, 6) if hbm_bytes else 0.0
        ),
        "queries": queries,
        "largest_dot": (
            {
                "flops": largest.flops,
                "op": largest.opcode,
                "instruction": largest.instruction,
                "computation": largest.computation,
                "multiplicity": largest.multiplicity,
            }
            if largest is not None else None
        ),
        "profile": profile_name,
        "roofline": roofline(flops, hbm_bytes, ici_bytes, queries,
                             profile),
    }
    if fused_dma:
        entry["fused_dma_bytes"] = int(fused_dma_wire_bytes or 0)
    return entry, problems


def r8_check(ctx, stage: str, module: HloModule, finding_cls) -> list:
    """The R8-cost check body (rules.py wraps it in the Rule class):
    after-opt only — the cost of the program XLA will RUN; the
    before-opt module still carries fusion-bait the machine never
    executes."""
    if stage != "after_opt":
        return []
    facts = ctx.meta.get("cost")
    if facts is None:
        return [
            finding_cls(
                "R8-cost",
                ctx.target.label,
                stage,
                "cell declares no cost facts (meta['cost']) — the "
                "analytical side of the FLOP exactness contract is "
                "missing, so the cell's work cannot be certified",
                {},
            )
        ]
    entry, problems = cost_entry(
        module, facts,
        fused_dma=bool(ctx.meta.get("fused_dma")),
        fused_dma_wire_bytes=ctx.meta.get("fused_dma_wire_bytes"),
    )
    # stash for the engine's ledger collection (meta is a per-run copy)
    ctx.meta["r8_analysis"] = entry
    return [
        finding_cls(
            "R8-cost", ctx.target.label, stage, msg,
            {"mxu_flops": entry["mxu_flops"],
             "analytical_flops": entry["analytical_flops"],
             "ici_bytes": entry["ici_bytes"]},
        )
        for msg in problems
    ]
