"""Shared ledger machinery for the certification drift gates.

Two rules bank per-cell numbers into committed JSON ledgers — R7's peak-HBM
ledger and R8's static cost ledger — and both need the same lifecycle:
atomic merge-aware writes, a schema gate on load, vanished-cell detection
on full-matrix sweeps, and a tolerance-banded drift check where growth is a
regression naming a culprit and shrinkage is a stale ledger hiding a banked
win. That lifecycle lives HERE, once, so the two gates cannot diverge: a
semantics fix (e.g. the environment-skipped-cell carve-out) lands in both
ledgers by construction. Each client declares a :class:`LedgerSpec` — the
schema version, the regeneration command its messages prescribe, the
tolerance band, and the metric(s) compared — and delegates; R7's public
functions in ``memory.py`` keep their exact signatures and message text
(pinned by tests/test_memory_lint.py) by doing exactly that.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
from typing import Callable


@dataclasses.dataclass(frozen=True)
class MetricSpec:
    """One scalar a ledger certifies per cell.

    ``key`` reads the value from the cell entry dict; ``noun``/``unit``
    render the drift messages ("peak grew … bytes"); ``culprit`` (given
    the CURRENT cell entry) names what to blame on growth — the largest
    temp for R7, the hottest dot for R8 — appended after an em-dash.
    """

    key: str
    noun: str
    unit: str = "bytes"
    culprit: Callable[[dict], str] | None = None


@dataclasses.dataclass(frozen=True)
class LedgerSpec:
    """What distinguishes one ledger from another: everything else is
    shared lifecycle. ``regen_cmd`` is the exact CLI the drift messages
    prescribe (stale ledgers must name their own remedy)."""

    kind: str  # "memory" / "cost" — message prefix on load errors
    schema_version: int
    source: str  # doc provenance field
    regen_cmd: str  # e.g. "mpi-knn lint --memory"
    tol_rel: float
    tol_abs: int
    metrics: tuple[MetricSpec, ...]


def load_ledger(path, spec: LedgerSpec) -> dict | None:
    """The committed ledger doc, ``None`` when absent, ``ValueError``
    when the schema is not the one this build writes (a stale artifact
    must be regenerated, not half-read)."""
    path = pathlib.Path(path)
    if not path.exists():
        return None
    doc = json.loads(path.read_text())
    if doc.get("schema_version") != spec.schema_version:
        raise ValueError(
            f"{spec.kind} ledger {path} has schema "
            f"{doc.get('schema_version')!r}, expected "
            f"{spec.schema_version} (regenerate with "
            f"`{spec.regen_cmd}`)"
        )
    return doc


def save_ledger(path, cells: dict, spec: LedgerSpec,
                merge_into: dict | None = None):
    """Write the ledger (atomically — lint may run concurrently with a
    serve process reading it). ``merge_into``: an existing ledger doc
    whose cells this run did not re-lower are preserved, so a filtered
    sweep refreshes only what it measured."""
    import jax

    from mpi_knn_tpu.utils.atomicio import atomic_write_text

    path = pathlib.Path(path)
    merged = dict(merge_into.get("cells", {})) if merge_into else {}
    merged.update(cells)
    doc = {
        "schema_version": spec.schema_version,
        "source": spec.source,
        "jax_version": jax.__version__,
        "platform": jax.default_backend(),
        "device_count": jax.device_count(),
        "tolerance": {"rel": spec.tol_rel, "abs_bytes": spec.tol_abs},
        "cells": {k: merged[k] for k in sorted(merged)},
    }
    path.parent.mkdir(parents=True, exist_ok=True)
    atomic_write_text(path, json.dumps(doc, indent=1) + "\n")
    return doc


def merge_base_for(
    committed: dict | None, *, full_matrix: bool,
    skipped_labels: frozenset | set = frozenset(),
) -> dict | None:
    """What a ledger WRITE should merge the fresh cells into. A filtered
    sweep refreshes only what it re-lowered, so the committed ledger is
    preserved wholesale. A FULL-matrix regeneration must PURGE vanished
    cells — otherwise the drift gate's prescribed remedy (regenerate
    after deleting a cell on purpose) would re-import the dead entry
    forever — while cells whose lowering was environment-skipped THIS
    run (a too-small mesh, not a dropped certification) keep their
    committed entries."""
    if committed is None:
        return None
    if not full_matrix:
        return committed
    preserved = {
        k: v for k, v in committed.get("cells", {}).items()
        if k in skipped_labels
    }
    return {"cells": preserved} if preserved else None


def ledger_drift(
    committed: dict, current: dict, spec: LedgerSpec, *,
    full_matrix: bool, skipped_labels: frozenset | set = frozenset(),
) -> list[str]:
    """Why the current per-cell numbers fail the committed ledger
    (empty = green). Growth beyond tolerance is a regression; shrinkage
    beyond tolerance is a stale ledger hiding a banked win — both fail.
    A NEW cell (current, not committed) extends the ledger and is not a
    finding; a VANISHED cell (committed, not current) is one — but only
    on full-matrix runs, where absence means the certification was
    dropped rather than filtered out, and never for a cell in
    ``skipped_labels`` (its lowering was environment-skipped this run —
    e.g. ring cells on a one-device mesh — which is a coverage gap, not
    a regression)."""
    out = []
    committed_cells = committed.get("cells", {})
    for label in sorted(set(committed_cells) | set(current)):
        old = committed_cells.get(label)
        new = current.get(label)
        if old is None:
            continue  # new cell: extends the ledger
        if new is None:
            if full_matrix and label not in skipped_labels:
                out.append(
                    f"{label}: cell vanished from the matrix but is "
                    "still in the committed ledger — a dropped "
                    "certification (regenerate the ledger if the cell "
                    "was removed on purpose)"
                )
            continue
        for metric in spec.metrics:
            was, now = old[metric.key], new[metric.key]
            tol = max(spec.tol_abs, was * spec.tol_rel)
            if now > was + tol:
                blame = (
                    f" — {metric.culprit(new)}" if metric.culprit else ""
                )
                out.append(
                    f"{label}: {metric.noun} grew {was} → {now} "
                    f"{metric.unit} (+{now - was}, tolerance "
                    f"{int(tol)}){blame}"
                )
            elif now < was - tol:
                out.append(
                    f"{label}: {metric.noun} shrank {was} → {now} "
                    f"{metric.unit} beyond tolerance — the committed "
                    "ledger is stale; regenerate with "
                    f"`{spec.regen_cmd}` to bank the improvement"
                )
    return out
