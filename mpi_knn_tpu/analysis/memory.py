"""Static peak-HBM certification: an aliasing-aware liveness analyzer
over after-opt HLO, the per-cell memory ledger, and lint rule
**R7-peak-memory** (ISSUE 15).

The serving north star dies on the first OOM, and before this module
nothing bounded what a compiled cell actually holds LIVE: R2 caps the
largest *single* buffer, which cannot see an un-donated scratch doubling
residency (two medium buffers, each under the cap) or a corpus-sized
temp hiding under R2's largest-input floor. This module computes **peak
live bytes** per compiled cell from the after-opt module text — the
program XLA will actually run — and makes it a CI-gated regression axis
exactly like recall and bytes-on-wire already are.

The liveness model (``analyze_module``):

- Every instruction's result occupies a buffer sized from its printed
  result type (tuples sum their elements; a tuple-shaped value adds the
  8-byte-per-element pointer table XLA allocates for it — measured, not
  guessed: PJRT's ``output_size_in_bytes`` includes it).
- **Forwarding ops allocate nothing.** ``tuple``/``get-tuple-element``/
  ``bitcast``/``opt-barrier`` are pointer shuffles; a ``while`` aliases
  its state onto the init operand (XLA's forced while aliasing), so the
  state bytes are counted where the init elements were materialized and
  live as long as anything reads the loop's results; in-place update
  forms (``scatter``/``dynamic-update-slice``, and fusions whose body
  root is one — the mutation cells' donated store updates) write into
  operand 0's buffer. Liveness is tracked on the resolved ALLOCATING
  instruction, so plumbing can neither hide a buffer nor double it.
- **Def-use intervals, event-swept.** An allocating instruction's buffer
  is live from its definition to the last instruction whose operands
  resolve to it (the entry root and output definers live to program
  end). Peak = the maximum over program points of the live-set byte sum.
- **Called computations are loop-resident.** A ``while``/``call``/
  ``conditional`` executes with its callee's own internal peak on top of
  the caller's live set (conditional: the max across branches); fusion
  bodies are collapsed (fused intermediates live in registers — only the
  fusion's result materializes).
- **Aliasing folded in.** Output elements declared in the module
  header's ``input_output_alias`` (R5's reader) write into donated input
  buffers: the donated scratch counts ONCE, not twice — the analyzer
  discounts the aliased bytes from the output's defining instruction.

Honesty check: every cell's analysis is cross-checked against PJRT's own
``compiled.memory_analysis()`` (captured at compile time by
``analysis.lowering``, zero extra compiles). The structural components
(args / outputs / aliased bytes) must match EXACTLY — a mismatch means
the parser or the model is wrong, loudly. The temp peak is a model of a
heap the compiler packs with its own cost function (the analyzer cannot
see XLA's elementwise-reuse trick, so it deliberately over-estimates),
so the TOTAL peak is held to a declared ASYMMETRIC band instead:
measured across the whole matrix analyzer/PJRT ∈ [0.90, 1.72]; the band
is [−15%, +80%], tight on the dangerous direction (an under-estimate is
a buffer the model lost). Disagreement beyond the band is itself a
finding — an analyzer bug or an XLA surprise, either way something a
human must look at.

The ledger (``artifacts/lint/memory_ledger.json``) commits every default
cell's numbers; ``mpi-knn lint --memory --ledger-check`` recomputes and
fails on drift beyond tolerance in EITHER direction (growth is a
regression; shrinkage is a stale ledger hiding a banked win), on a
vanished cell (a silently dropped certification), while a NEW cell
simply extends the ledger.
"""

from __future__ import annotations

import json
import pathlib
import re
from dataclasses import dataclass, field

from mpi_knn_tpu.analysis import ledger as _ledger
from mpi_knn_tpu.utils.hlo_graph import HloModule, parse_hlo

# ---------------------------------------------------------------------------
# shape pricing (kept self-contained: rules.py imports THIS module for R7,
# so this module must not import rules)

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s4": 1, "u4": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}
# XLA materializes an index table of 8-byte pointers for tuple-shaped
# buffers; PJRT's output_size_in_bytes includes it, so the analyzer must
# too or the exact-match cross-check would be off by 8·arity everywhere
_TUPLE_PTR_BYTES = 8

# result buffer IS (part of) an operand buffer — never a new allocation
_FORWARD_OPS = (
    "tuple", "get-tuple-element", "bitcast", "opt-barrier", "copy-done",
    "transpose-bitcast", "while",
)
# in-place update forms: XLA writes the update into operand 0's buffer
# (the donated-store mutation scatters; R2-strict exempts the same set)
_INPLACE_OPS = ("scatter", "dynamic-update-slice")


def total_buffer_bytes(type_str: str) -> int:
    """All bytes of an HLO result type (tuple elements summed, plus the
    tuple pointer table) — what the value occupies, as opposed to R2's
    ``max_buffer_bytes`` (the largest single buffer)."""
    tot = 0
    n_elems = 0
    for dt, dims in _SHAPE_RE.findall(type_str):
        sz = _DTYPE_BYTES.get(dt)
        n_elems += 1
        if sz is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        tot += n * sz
    if type_str.lstrip().startswith("(") and n_elems:
        tot += _TUPLE_PTR_BYTES * n_elems
    return tot


def _elem_sizes(type_str: str) -> list[int]:
    out = []
    for dt, dims in _SHAPE_RE.findall(type_str):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        out.append(n * _DTYPE_BYTES.get(dt, 0))
    return out


# ---------------------------------------------------------------------------
# header readers (self-contained copies of R5's tiny regexes — see the
# import-direction note above)

_ALIAS_ENTRY_RE = re.compile(
    r"\{\s*(\d*)\s*\}\s*:\s*\(\s*(\d+)\s*,\s*\{[^}]*\}\s*,"
    r"\s*(?:may|must)-alias\s*\)"
)


def _header_aliases(header: str) -> dict[int, int]:
    start = header.find("input_output_alias={")
    if start < 0:
        return {}
    seg = header[start:]
    depth = 0
    for j, ch in enumerate(seg):
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                seg = seg[: j + 1]
                break
    return {
        int(out or 0): int(param)
        for out, param in _ALIAS_ENTRY_RE.findall(seg)
    }


# ---------------------------------------------------------------------------
# the liveness analyzer


@dataclass
class MemoryAnalysis:
    """Peak live bytes of one compiled module, with attribution."""

    args_bytes: int
    output_bytes: int
    aliased_bytes: int
    temp_peak_bytes: int
    peak_bytes: int  # args + output − aliased + temp peak
    # the largest single temp buffer anywhere in the module (loop bodies
    # included) — the culprit a regression report names
    largest_temp_bytes: int = 0
    largest_temp_op: str = ""
    largest_temp_name: str = ""
    # where (entry instruction name) the temp peak occurs
    peak_at: str = ""
    # attribution: resident store / donated scratch / temps / collective
    # exchange buffers — context for a human reading the ledger (the
    # categories overlap the totals above, they do not sum to peak)
    categories: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "args_bytes": self.args_bytes,
            "output_bytes": self.output_bytes,
            "aliased_bytes": self.aliased_bytes,
            "temp_peak_bytes": self.temp_peak_bytes,
            "peak_bytes": self.peak_bytes,
            "largest_temp": {
                "bytes": self.largest_temp_bytes,
                "op": self.largest_temp_op,
                "instruction": self.largest_temp_name,
            },
            "peak_at": self.peak_at,
            "categories": self.categories,
        }


def _is_inplace_fusion(module: HloModule, instr) -> bool:
    """A fusion whose body root is (a tuple of only) in-place update ops
    writes into its operand buffers — forwarding, not allocation (the
    mutation cells' donated-store scatter fusions)."""
    if instr.opcode != "fusion" or not instr.called:
        return False
    comp = module.computations.get(instr.called[0])
    if comp is None or comp.root is None:
        return False
    root = comp.instructions.get(comp.root)
    if root is None:
        return False
    if root.opcode in _INPLACE_OPS:
        return True
    if root.opcode == "tuple":
        kids = [comp.instructions.get(o) for o in root.operands]
        return bool(kids) and all(
            k is not None and k.opcode in _INPLACE_OPS for k in kids
        )
    return False


def _is_forwarding(module: HloModule, instr) -> bool:
    return (
        instr.opcode in _FORWARD_OPS
        or instr.opcode in _INPLACE_OPS
        or instr.opcode == "parameter"
        or _is_inplace_fusion(module, instr)
    )


_GTE_IDX_RE = re.compile(r"index=(\d+)")

# resolution paths deeper than this fall back to whole-value (flat)
# resolution — real programs nest state tuples one or two deep; the cap
# only guards against a pathological printer loop
_MAX_PATH = 8


def _resolve_sources(module, comp, cache, name, path=()) -> frozenset:
    """The set of ALLOCATING instructions whose buffers this value (or
    the tuple element named by ``path``, a stack of indices innermost
    first) may occupy, within ``comp``. Parameters resolve to nothing —
    their bytes belong to the caller. Element-precise through
    ``tuple``/``get-tuple-element``/``while`` chains, exactly like
    ``hlo_graph.backward_slice``'s index stack: without this, a gte
    reading the scan carry would keep the whole loop-state tuple's
    sources (the resident traveler blocks included) alive to program
    end and overstate the peak. Any shape the tracker does not
    understand falls back to flat (all operands), which only EXTENDS
    lifetimes — the peak stays an upper-ish bound, never silently
    loses a buffer."""
    key = (name, path)
    if key in cache:
        return cache[key]
    cache[key] = frozenset()  # cycle guard
    i = comp.instructions.get(name)
    if i is None or i.opcode == "parameter":
        out = frozenset()
    elif i.opcode == "get-tuple-element" and i.operands:
        m = _GTE_IDX_RE.search(i.attrs)
        if m and len(path) < _MAX_PATH:
            out = _resolve_sources(
                module, comp, cache, i.operands[0],
                (int(m.group(1)),) + path,
            )
        else:
            out = _resolve_sources(module, comp, cache, i.operands[0])
    elif i.opcode == "tuple":
        if path and path[0] < len(i.operands):
            out = _resolve_sources(
                module, comp, cache, i.operands[path[0]], path[1:]
            )
        else:  # whole-tuple use (or malformed index): all elements
            srcs = set()
            for o in i.operands:
                srcs |= _resolve_sources(module, comp, cache, o)
            out = frozenset(srcs)
    elif _is_forwarding(module, i):
        # while aliases its state onto the init operand; bitcast/
        # opt-barrier/copy-done pass the path through; a bare scatter/
        # dus writes into operand 0 (an in-place FUSION unions all its
        # operands — which one the fused update writes into is not
        # visible from the call site, and a union only extends)
        ops = (
            i.operands[:1] if i.opcode in _INPLACE_OPS else i.operands
        )
        srcs = set()
        for o in ops:
            srcs |= _resolve_sources(module, comp, cache, o, path)
        out = frozenset(srcs)
    else:
        out = frozenset([name])
    cache[key] = out
    return out


def _sweep(module, comp, memo, stack, discount, out_defs):
    """Event-swept liveness peak of one computation. Returns
    ``(peak_bytes, largest (bytes, label, opcode), peak_at)`` where
    ``largest`` merges the callee bodies' largest temps (loop-body
    buffers are where the real culprits live)."""
    instrs = list(comp.instructions.values())
    order = {i.name: t for t, i in enumerate(instrs)}
    cache: dict = {}
    last: dict = {}
    for t, i in enumerate(instrs):
        for o in i.operands + i.controls:
            for s in _resolve_sources(module, comp, cache, o):
                last[s] = max(last.get(s, order[s]), t)
    end = len(instrs)
    if comp.root:
        for s in _resolve_sources(module, comp, cache, comp.root):
            last[s] = end
    for s in out_defs:
        if s in order:
            last[s] = end
    deltas = [0] * (end + 2)
    extras = [0] * (end + 1)
    largest = (0, "", "")
    for t, i in enumerate(instrs):
        if not _is_forwarding(module, i):
            b = max(0, total_buffer_bytes(i.type_str)
                    - discount.get(i.name, 0))
            if b:
                deltas[t] += b
                deltas[last.get(i.name, t) + 1] -= b
                if i.name not in out_defs and b > largest[0]:
                    largest = (b, f"{comp.name}::{i.name}", i.opcode)
        if i.opcode == "fusion":
            continue  # fused intermediates live in registers
        for callee in i.called:
            sub_peak, sub_largest = _computation_peak(
                module, callee, memo, stack
            )
            extras[t] = max(extras[t], sub_peak)
            if sub_largest[0] > largest[0]:
                largest = sub_largest
    run = 0
    peak = 0
    peak_at = ""
    for t in range(end + 1):
        run += deltas[t]
        cand = run + (extras[t] if t < end else 0)
        if cand > peak:
            peak = cand
            peak_at = instrs[t].name if t < end else "<exit>"
    return peak, largest, peak_at


def _computation_peak(module, cname, memo, stack=()):
    """Internal liveness peak of a non-entry computation (memoized;
    cycles — impossible in valid HLO — resolve to 0 rather than hang)."""
    if cname in memo:
        return memo[cname]
    if cname in stack or cname not in module.computations:
        return 0, (0, "", "")
    peak, largest, _ = _sweep(
        module, module.computations[cname], memo, stack + (cname,),
        discount={}, out_defs=frozenset(),
    )
    memo[cname] = (peak, largest)
    return memo[cname]


def _chase_output(comp, name):
    """Resolve a root element to its defining instruction through
    bitcast/copy-done/gte chains (tracking tuple indices through
    matched tuple/gte pairs)."""
    seen = set()
    while name in comp.instructions and name not in seen:
        seen.add(name)
        i = comp.instructions[name]
        if i.opcode in ("bitcast", "copy-done") and i.operands:
            name = i.operands[0]
            continue
        if i.opcode == "get-tuple-element" and i.operands:
            m = re.search(r"index=(\d+)", i.attrs)
            src = comp.instructions.get(i.operands[0])
            if (
                src is not None and src.opcode == "tuple" and m
                and int(m.group(1)) < len(src.operands)
            ):
                name = src.operands[int(m.group(1))]
                continue
            name = i.operands[0]
            continue
        break
    return name


def _entry(module: HloModule):
    for c in module.computations.values():
        if c.is_entry:
            return c
    raise ValueError("module has no ENTRY computation")


def analyze_module(module_or_text) -> MemoryAnalysis:
    """Peak live bytes of one after-opt module (see the module
    docstring for the model). Accepts parsed or raw HLO text."""
    module = (
        module_or_text
        if isinstance(module_or_text, HloModule)
        else parse_hlo(module_or_text)
    )
    entry = _entry(module)
    aliases = _header_aliases(module.header)
    args = sum(
        total_buffer_bytes(i.type_str)
        for i in entry.instructions.values()
        if i.opcode == "parameter"
    )
    root = entry.instructions[entry.root]
    out_elems = _elem_sizes(root.type_str)
    is_tuple = root.type_str.lstrip().startswith("(")
    out_bytes = sum(out_elems) + (
        _TUPLE_PTR_BYTES * len(out_elems) if is_tuple else 0
    )
    # output-defining instructions: their bytes leave the temp sweep
    # entirely — outputs are accounted FLAT via output_bytes (they
    # occupy their allocation for the whole execution, which is how
    # PJRT splits output_size from temp_size too). Aliased output
    # elements additionally subtract from the total: they write into
    # donated input buffers already counted in args (once, not twice).
    if root.opcode == "tuple":
        defs = [_chase_output(entry, o) for o in root.operands]
    else:
        defs = [_chase_output(entry, root.name)]
    aliased = 0
    discount: dict = {}
    out_def_names: set = set()
    cache: dict = {}
    for k, dname in enumerate(defs):
        srcs = _resolve_sources(module, entry, cache, dname)
        out_def_names.update(srcs if srcs else {dname})
        # the discount lands on the ALLOCATING source when it is
        # unambiguous (the chased name may still be a forwarding op);
        # with several candidate sources it stays on the chased name —
        # an over-count, never a lost buffer
        key = next(iter(srcs)) if len(srcs) == 1 else dname
        if k < len(out_elems):
            discount[key] = discount.get(key, 0) + out_elems[k]
            if k in aliases:
                aliased += out_elems[k]
    memo: dict = {}
    temp_peak, largest, peak_at = _sweep(
        module, entry, memo, ("<entry>",), discount,
        frozenset(out_def_names),
    )
    exchange = sum(
        total_buffer_bytes(module.instr(c, n).type_str)
        for op in ("collective-permute", "all-to-all")
        for c, n in module.find(op)
        if not module.instr(c, n).opcode.endswith("-done")
    )
    return MemoryAnalysis(
        args_bytes=args,
        output_bytes=out_bytes,
        aliased_bytes=aliased,
        temp_peak_bytes=temp_peak,
        peak_bytes=args + out_bytes - aliased + temp_peak,
        largest_temp_bytes=largest[0],
        largest_temp_name=largest[1],
        largest_temp_op=largest[2],
        peak_at=peak_at,
        categories={
            "scratch": aliased,
            "temp": temp_peak,
            "exchange": exchange,
        },
    )


# ---------------------------------------------------------------------------
# budget derivation (the R7 gate) + the PJRT cross-check


# Temp-peak slack over the cell's per-buffer working-set base (R2's tile
# budget / strict probed-bytes bound): the peak SUMS several live tile
# buffers (carry ‖ tile concatenations, sort scratch, loop double
# buffers), so the per-buffer base under-counts it by a small factor.
# Measured across the shipped matrix the worst cell needs ≈4.1×; 6×
# holds everywhere with margin while a corpus-sized temp (the bug class)
# overshoots it by an order of magnitude at real shapes.
R7_TEMP_SLACK = 6
# mirrors rules.R2_SLACK without importing rules (see header note)
_R2_SLACK = 4

# PJRT cross-check tolerance on the TOTAL peak — an ASYMMETRIC band.
# The analyzer is deliberately conservative: it cannot see XLA's
# elementwise-reuse trick (a fusion writing into its dying operand's
# buffer), so same-size transform chains each add a modeled buffer the
# real heap shares — overestimates up to ~1.72× on the worst shipped
# cell (cosine-normalized mixed ring bodies). UNDERestimating is the
# dangerous direction (a buffer the model lost), so that side is tight:
# measured across the matrix analyzer/PJRT ∈ [0.90, 1.72]; the band is
# [−15%, +80%]. Leaving it is a finding in either direction.
PJRT_TOL_UNDER = 0.15
PJRT_TOL_OVER = 0.80
PJRT_TOL_ABS = 4096

# ledger drift tolerance: peak numbers are deterministic for a fixed
# (jax, platform) pair, but tiny constant-folding jitter across point
# releases should not page anyone — 2% + 4 KiB is noise, more is a real
# change someone must bank or explain
LEDGER_TOL_REL = 0.02
LEDGER_TOL_ABS = 4096


def temp_budget_bytes(meta: dict) -> int:
    """The cell's temp-peak allowance, derived from the same declared
    facts R2 budgets single buffers with: the strict probed-bytes bound
    when one is declared (clustered cells), else the tile working set —
    NEVER the largest input (that floor is exactly what lets a
    corpus-sized temp hide; see the R2 audit in tests). Registered
    per-cell extras (``extra_elems``: the mixed rerank gather, the bidir
    second traveler; ``peak_extra_elems``: allowances only the liveness
    view needs, e.g. the bf16 store's one-time f32 upcast) ride on top."""
    tile = _R2_SLACK * meta["q_tile"] * meta["c_tile"]
    base = max(
        meta.get("budget_elems") or 0,
        tile,
        meta.get("extra_elems", 0),
    )
    return (
        R7_TEMP_SLACK * base + meta.get("peak_extra_elems", 0)
    ) * meta["acc_bytes"]


def peak_budget_bytes(meta: dict, analysis: MemoryAnalysis) -> int:
    """The cell's peak-HBM budget: the program's own inputs at face
    value (they ARE the index — R2's input floor is fine for what is
    genuinely an input), plus the outputs the donation contract does
    NOT alias away (a donated cell promises every output aliased, so
    un-donated output bytes count against the budget — the un-donated-
    scratch-doubles-residency bug class), plus the derived temp
    allowance."""
    if meta.get("donated_params"):
        # the donation contract says outputs alias donated inputs: any
        # unaliased output bytes are unplanned allocations and must fit
        # inside the temp allowance instead of being budgeted away
        out_allow = 0
    else:
        out_allow = analysis.output_bytes
    return analysis.args_bytes + out_allow + temp_budget_bytes(meta)


def pjrt_memory_stats(compiled) -> dict | None:
    """The PJRT side of the cross-check, from one already-compiled
    executable (zero extra compiles, zero device reads). ``None`` when
    the runtime cannot answer — absent, never fake zeros."""
    try:
        ma = compiled.memory_analysis()
        return {
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "alias_bytes": int(ma.alias_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
            "peak_bytes": int(
                ma.argument_size_in_bytes + ma.output_size_in_bytes
                - ma.alias_size_in_bytes + ma.temp_size_in_bytes
            ),
        }
    except Exception:  # pragma: no cover - runtime-dependent
        return None


def crosscheck_pjrt(analysis: MemoryAnalysis, pjrt: dict) -> list[str]:
    """Why the analyzer and PJRT disagree (empty = they agree). The
    structural components must match EXACTLY — both sides read the same
    declared shapes, so any gap is a parser/model bug. The total peak is
    held to the declared tolerance band."""
    out = []
    for mine, theirs, what in (
        (analysis.args_bytes, pjrt["argument_bytes"], "argument"),
        (analysis.output_bytes, pjrt["output_bytes"], "output"),
        (analysis.aliased_bytes, pjrt["alias_bytes"], "aliased"),
    ):
        if mine != theirs:
            out.append(
                f"{what} bytes disagree: analyzer {mine} vs PJRT "
                f"{theirs} — structural components are declared shapes "
                "and must match exactly (parser or model bug)"
            )
    lo = pjrt["peak_bytes"] * (1 - PJRT_TOL_UNDER) - PJRT_TOL_ABS
    hi = pjrt["peak_bytes"] * (1 + PJRT_TOL_OVER) + PJRT_TOL_ABS
    if not (lo <= analysis.peak_bytes <= hi):
        out.append(
            f"peak bytes disagree beyond tolerance: analyzer "
            f"{analysis.peak_bytes} vs PJRT {pjrt['peak_bytes']} "
            f"(band [{int(lo)}, {int(hi)}] at −{PJRT_TOL_UNDER:.0%}/"
            f"+{PJRT_TOL_OVER:.0%} + {PJRT_TOL_ABS}B) — analyzer bug "
            "or XLA surprise, either way a human must look"
        )
    return out


# ---------------------------------------------------------------------------
# the ledger

LEDGER_SCHEMA_VERSION = 1
DEFAULT_LEDGER = pathlib.Path("artifacts/lint/memory_ledger.json")


def ledger_entry(analysis: MemoryAnalysis, budget: int,
                 pjrt: dict | None) -> dict:
    return {
        **analysis.to_json(),
        "budget_bytes": budget,
        "pjrt": pjrt,
    }


def _peak_culprit(cell: dict) -> str:
    culprit = cell.get("largest_temp", {})
    return (
        f"largest temp {culprit.get('bytes')}B {culprit.get('op')!r} "
        f"at {culprit.get('instruction')!r}"
    )


# The R7 ledger as a LedgerSpec — all lifecycle (schema gate, atomic
# merge-aware save, vanished-cell semantics, tolerance-banded drift) is
# shared with R8's cost ledger via analysis/ledger.py so the two drift
# gates cannot diverge. The public functions below keep their original
# signatures and message text (pinned by tests/test_memory_lint.py).
LEDGER_SPEC = _ledger.LedgerSpec(
    kind="memory",
    schema_version=LEDGER_SCHEMA_VERSION,
    source="mpi_knn_tpu.analysis.memory",
    regen_cmd="mpi-knn lint --memory",
    tol_rel=LEDGER_TOL_REL,
    tol_abs=LEDGER_TOL_ABS,
    metrics=(
        _ledger.MetricSpec(
            key="peak_bytes", noun="peak", unit="bytes",
            culprit=_peak_culprit,
        ),
    ),
)


def load_ledger(path) -> dict | None:
    return _ledger.load_ledger(path, LEDGER_SPEC)


def save_ledger(path, cells: dict, merge_into: dict | None = None):
    """Write the ledger (atomically — lint may run concurrently with a
    serve process reading it). ``merge_into``: an existing ledger doc
    whose cells this run did not re-lower are preserved, so a filtered
    ``--memory`` sweep refreshes only what it measured."""
    return _ledger.save_ledger(path, cells, LEDGER_SPEC,
                               merge_into=merge_into)


def merge_base_for(
    committed: dict | None, *, full_matrix: bool,
    skipped_labels: frozenset | set = frozenset(),
) -> dict | None:
    """What a ``--memory`` WRITE should merge the fresh cells into (see
    :func:`mpi_knn_tpu.analysis.ledger.merge_base_for` — shared with the
    R8 cost ledger)."""
    return _ledger.merge_base_for(
        committed, full_matrix=full_matrix, skipped_labels=skipped_labels
    )


def ledger_drift(
    committed: dict, current: dict, *, full_matrix: bool,
    skipped_labels: frozenset | set = frozenset(),
) -> list[str]:
    """Why the current per-cell peaks fail the committed ledger (empty =
    green; see :func:`mpi_knn_tpu.analysis.ledger.ledger_drift` — shared
    with the R8 cost ledger)."""
    return _ledger.ledger_drift(
        committed, current, LEDGER_SPEC,
        full_matrix=full_matrix, skipped_labels=skipped_labels,
    )


# ---------------------------------------------------------------------------
# R7 as a lint rule — registered into the shared registry. Imported from
# rules.py at the END of its module body (rules → memory is the only
# import direction; memory defines its own shape readers above).


def r7_check(ctx, stage: str, module: HloModule, finding_cls) -> list:
    """The R7-peak-memory check body (rules.py wraps it in the Rule
    class): after-opt only — liveness over the program XLA will RUN;
    the before-opt module's buffers are pre-fusion fiction."""
    if stage != "after_opt":
        return []
    analysis = analyze_module(module)
    budget = peak_budget_bytes(ctx.meta, analysis)
    # stash for the engine's ledger collection (meta is a per-run copy)
    pjrt = ctx.meta.get("pjrt_memory")
    ctx.meta["r7_analysis"] = ledger_entry(analysis, budget, pjrt)
    out = []
    if analysis.peak_bytes > budget:
        out.append(
            finding_cls(
                "R7-peak-memory",
                ctx.target.label,
                stage,
                f"peak live bytes {analysis.peak_bytes} > budget "
                f"{budget} (args {analysis.args_bytes} + unaliased "
                f"outputs + {R7_TEMP_SLACK}× working-set temp "
                f"allowance) — largest temp "
                f"{analysis.largest_temp_bytes}B "
                f"{analysis.largest_temp_op!r} at "
                f"{analysis.largest_temp_name!r}, peak at "
                f"{analysis.peak_at!r}",
                {
                    "peak_bytes": analysis.peak_bytes,
                    "budget_bytes": budget,
                    "largest_temp": {
                        "bytes": analysis.largest_temp_bytes,
                        "op": analysis.largest_temp_op,
                        "instruction": analysis.largest_temp_name,
                    },
                },
            )
        )
    if pjrt is not None:
        for why in crosscheck_pjrt(analysis, pjrt):
            out.append(
                finding_cls(
                    "R7-peak-memory",
                    ctx.target.label,
                    stage,
                    why,
                    {
                        "analyzer": analysis.to_json(),
                        "pjrt": pjrt,
                    },
                )
            )
    return out
