"""Lower every registered backend configuration to HLO text — on CPU, no
TPU needed.

The engine's subjects are the framework's own jitted cores, lowered with
the exact static arguments the production wrappers would pass for a
small-but-structured problem (multiple query tiles per device, multiple
corpus tiles per ring block, a full 8-way ring on the virtual CPU mesh).
Both pipeline stages are captured in-process from one lowering:

- ``before_opt``: ``Lowered.compiler_ir("hlo").as_hlo_text()`` — the
  module XLA receives (where the blocking barrier is still visible);
- ``after_opt``: ``Compiled.as_text()`` — the module XLA will run (where
  fusion/DCE/partitioning have had their say).

No ``--xla_dump_to`` subprocess dance: the old artifact script needed one
process per variant because dump flags are process-wide XLA_FLAGS; the
in-process APIs have no such coupling, so the full matrix runs in one
process and the results are cached per configuration.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from mpi_knn_tpu.config import BACKENDS, METRICS, KNNConfig

STAGES = ("before_opt", "after_opt")
LINT_DTYPES = ("float32", "bfloat16", "float64")
LINT_POLICIES = ("exact", "mixed")
# the quantization axis (ISSUE 9): "" = unquantized; "xfer-int8" = the
# int8 block-scaled RING TRANSFER (mixed-policy ring cells — config.py
# refuses exact); "int8"/"int4" = the clustered store's block-scaled
# AT-REST levels. Quantized cells run the quant/dequant dtype contract
# (R3), wire-priced budgets (R2 gather bytes, R4 permute/all-to-all
# payloads), and the usual donation/probe-discipline rules.
LINT_QUANTS = ("xfer-int8", "int8", "int4")
# the dense (full-scan) backends sweep the whole metric × dtype product;
# the clustered "ivf" / "ivf-sharded" cells are appended explicitly
# (l2/float32 only — the IVF path's own contract) but share the CLI
# filter namespace
DENSE_LINT_BACKENDS = tuple(b for b in BACKENDS if b != "auto")
LINT_BACKENDS = DENSE_LINT_BACKENDS + ("ivf", "ivf-sharded")

# Small but structurally faithful: 8 query tiles, 8 corpus tiles, an 8-way
# ring with one (q_tile × c_tile) block tile per device per round — every
# loop the production shapes have, at compile-in-seconds size.
LINT_M, LINT_NQ, LINT_D, LINT_K = 128, 64, 32, 4
LINT_QUERY_TILE, LINT_CORPUS_TILE = 8, 16
# Mixed-policy cells need tiles WIDER than the 4k overfetch, or the two-pass
# pipeline would degenerate to the exact fallback and R3's compress/rerank
# dot contract would be vacuously unverifiable: a 2× corpus and a 32-wide
# tile keep 4k=16 < c_tile=32 even on the 8-way ring (256/8 = 32 per block).
LINT_M_MIXED, LINT_CORPUS_TILE_MIXED = 256, 32


@dataclasses.dataclass(frozen=True)
class LintTarget:
    """One cell of the backend × metric × dtype × precision-policy ×
    ring-schedule × serve × ladder matrix (``schedule`` only varies for
    ring backends; ``serve`` cells lint the per-batch program the serving
    engine's executable cache compiles instead of the one-shot core;
    ``ladder`` cells lint the program a degradation-ladder rung would
    serve — ``"bucket"`` halves the row bucket, ``"nprobe"`` drops the
    clustered probe count to 1 — so R5's donation contract and R2's
    strict probed-bytes budget are re-certified on exactly what the
    ladder lowers, retry paths introducing no new copies)."""

    backend: str
    metric: str
    dtype: str
    policy: str = "exact"
    schedule: str = "uni"
    serve: bool = False
    ladder: str = ""  # "" | "bucket" | "nprobe" — serve cells only
    quant: str = ""  # "" | "xfer-int8" (ring) | "int8" | "int4" (at-rest)
    # frontend=True (serve cells only): the batch is formed by the
    # serving front end's PRODUCTION coalescer (multi-tenant requests,
    # round-robin drain — mpi_knn_tpu.frontend.coalesce) before lowering
    # through lower_bucket, certifying that coalesced dispatch compiles
    # exactly a cell of the existing bucket grid: the front end adds NO
    # new programs, only fills existing buckets (R1–R5 re-certify on
    # what it fills)
    frontend: bool = False
    # mutate != "" (ISSUE 14): the cell lints a LIVE-MUTATION program —
    # "upsert" / "delete" / "compact" — lowered through the production
    # serve.mutate.lower_mutation (the exact object the mutation
    # executable cache compiles). R5 certifies the donated in-place
    # store update (every output aliased, no corpus-sized copy) and
    # R2-strict budgets the TOUCHED working set (the mutation chunk, or
    # the whole store for a compact — never more), with the in-place
    # scatter/dynamic-update-slice forms exempted as buffer-forwarding
    # plumbing (meta strict_exempt_ops)
    mutate: str = ""
    # fusion="fused" (ring backends only): the per-round compute is the
    # fused collective-matmul Pallas kernel (ops/pallas_ring.py) instead
    # of the XLA tile pipeline. On the CPU lint platform the kernel runs
    # in interpret mode with driver-owned ppermute transport, so R1/R4's
    # permute accounting still sees the rotation; the kernel-owned-DMA
    # form (TPU, uni/exact) is covered by the meta side-band contract
    # (fused_dma / fused_dma_wire_bytes) that R1/R4/R8 branch on
    fusion: str = "xla"

    @property
    def label(self) -> str:
        base = f"{self.backend}/{self.metric}/{self.dtype}"
        if self.policy != "exact":
            base = f"{base}/{self.policy}"
        if self.schedule != "uni":
            base = f"{base}/{self.schedule}"
        if self.fusion != "xla":
            base = f"{base}/{self.fusion}"
        if self.quant:
            base = f"{base}/{self.quant}"
        if self.serve:
            base = f"{base}/serve"
        if self.ladder:
            base = f"{base}/ladder-{self.ladder}"
        if self.frontend:
            base = f"{base}/frontend"
        if self.mutate:
            base = f"{base}/mutate-{self.mutate}"
        return base


RING_BACKENDS = ("ring", "ring-overlap")


def default_targets() -> list[LintTarget]:
    return [
        LintTarget(b, m, d)
        for b in DENSE_LINT_BACKENDS
        for m in METRICS
        for d in LINT_DTYPES
    ] + [
        # the mixed compress-and-rerank policy: float32 only (config.py
        # validation), every backend × metric
        LintTarget(b, m, "float32", "mixed")
        for b in DENSE_LINT_BACKENDS
        for m in METRICS
    ] + [
        # the bidirectional ring schedule: ring backends only, float32, both
        # policies — R4 certifies the counter-directed permute accounting
        # (2 per direction) and R1 re-certifies overlap/blocking sequencing
        # on the two-traveler step body
        LintTarget(b, m, "float32", p, "bidir")
        for b in RING_BACKENDS
        for m in METRICS
        for p in ("exact", "mixed")
    ] + [
        # the serving engine's per-batch programs (mpi_knn_tpu.serve):
        # every backend at l2/float32 plus the mixed serial cell — R5
        # certifies the scratch donation (input_output_alias/buffer_donor)
        # and the no-resident-corpus-copy property; R1–R4 re-run on the
        # serve lowering (same tile/rotation bodies, so the sequencing,
        # memory, dtype and collective contracts must survive the serving
        # wrapper unchanged)
        LintTarget(b, "l2", "float32", serve=True)
        for b in DENSE_LINT_BACKENDS
    ] + [
        LintTarget("serial", "l2", "float32", "mixed", serve=True),
    ] + [
        # the clustered (IVF) cells — one-shot and serve-cache forms, both
        # policies: R6 certifies the probe-gather-feeds-the-only-exact-dot
        # contract and R2 runs in STRICT mode (the probed-bytes bound
        # nprobe·bucket_cap·d per query row replaces the largest-input
        # floor, so a full-corpus materialization is a finding even though
        # the whole corpus is a program input); the serve cells add R5's
        # donation/no-corpus-copy contract on the bucket-cache program
        LintTarget("ivf", "l2", "float32"),
        LintTarget("ivf", "l2", "float32", "mixed"),
        LintTarget("ivf", "l2", "float32", serve=True),
        LintTarget("ivf", "l2", "float32", "mixed", serve=True),
    ] + [
        # the SHARDED clustered cells (ivf/sharded.py): the routed
        # candidate exchange over a 4-shard CPU mesh — R2-strict's
        # probed-bytes budget is enforced PER SHARD (the exchange buffers
        # + rerank working set of one shard's resident tile, never the
        # global corpus), R4 accounts the exchange all-to-alls (count,
        # full-ring replica groups, payload bytes ≤ the declared per-tile
        # budget), R6 re-certifies the probe discipline on the routed
        # gathers, and the serve cells add R5's every-output-aliased
        # donation contract (three outputs, three donated scratches)
        LintTarget("ivf-sharded", "l2", "float32"),
        LintTarget("ivf-sharded", "l2", "float32", "mixed"),
        LintTarget("ivf-sharded", "l2", "float32", serve=True),
        LintTarget("ivf-sharded", "l2", "float32", "mixed", serve=True),
    ] + [
        # the degradation-ladder rung programs (resilience/ladder.py):
        # under sustained deadline breach ServeSession serves smaller-
        # nprobe / mixed / smaller-bucket cells of the SAME executable
        # cache — the mixed rung is already certified by the mixed serve
        # cells above; these add the bucket/2 rung (serial + ivf) and the
        # nprobe→1 rung (ivf, where R2-strict's probed-bytes budget
        # SHRINKS with the rung — the budget is re-derived from the rung
        # cfg, so a rung program materializing more than its own smaller
        # bound is a finding), each under R5's donation/no-corpus-copy
        # contract: degrading must never cost the donation or introduce
        # corpus-sized copies
        LintTarget("serial", "l2", "float32", serve=True, ladder="bucket"),
        LintTarget("ivf", "l2", "float32", serve=True, ladder="bucket"),
        LintTarget("ivf", "l2", "float32", serve=True, ladder="nprobe"),
        # the sharded nprobe rung: the resilience ladder's first shed on
        # a sharded session — at the safe route cap the exchange buffers
        # scale with nprobe, so R2-strict's per-shard budget here is
        # HALF the full rung's (re-derived from the rung cfg; a rung
        # program materializing beyond its own smaller bound is a
        # finding), with R5's donation contract intact on degraded cells
        LintTarget("ivf-sharded", "l2", "float32", serve=True,
                   ladder="nprobe"),
    ] + [
        # the serving FRONT END's hot path (ISSUE 11): a coalesced
        # multi-tenant batch formed by the production Coalescer
        # (mpi_knn_tpu.frontend), lowered through the SAME production
        # lower_bucket as every serve cell. The cell's claim is that
        # coalescing adds no new programs — the coalesced batch compiles
        # exactly the serve grid cell its row count buckets to (asserted
        # in the lowering: formed rows == the bucket the serve cell
        # lints) — with R5's donation and R1–R4 re-certified on what
        # coalesced dispatch actually compiles
        LintTarget("serial", "l2", "float32", serve=True, frontend=True),
    ] + [
        # the LIVE-MUTATION cells (ISSUE 14): the donated in-place
        # upsert/delete/compact programs of the mutable layouts, lowered
        # through the production serve.mutate.lower_mutation. R5's
        # every-output-aliased contract and copy census run on exactly
        # what sustained churn executes (an un-donated store or a
        # corpus-sized copy is a finding — injected counterexamples in
        # tests/test_hlo_lint.py fire through this same rule path), and
        # R2-strict's budget is the TOUCHED working set: the mutation
        # chunk for upsert/delete (a full-store gather — the headroom-
        # overflow shape — is a finding), the store itself only for the
        # compact rebuild
        LintTarget("serial", "l2", "float32", mutate="upsert"),
        LintTarget("serial", "l2", "float32", mutate="delete"),
        LintTarget("ivf", "l2", "float32", mutate="upsert"),
        LintTarget("ivf", "l2", "float32", mutate="delete"),
        LintTarget("ivf", "l2", "float32", mutate="compact"),
        # the sharded store mutates through the SAME donated scatters
        # under GSPMD — the donation/no-copy contract must survive the
        # partitioner (R4's exchange accounting does not apply: mutation
        # has no candidate exchange, and the partitioner owns whatever
        # plumbing it emits)
        LintTarget("ivf-sharded", "l2", "float32", mutate="upsert"),
    ] + [
        # the QUANTIZED cells (ISSUE 9). Ring transfer at int8 — mixed
        # policy only (config.py refuses exact): R3 certifies the
        # quant/dequant contract (exactly one dequant convert + scale
        # multiply feeding each compress dot; no dot touches raw codes),
        # R4 counts THREE permutes per direction (codes + scales + ids)
        # and prices every permute payload at the wire dtype, R1
        # re-certifies overlap/blocking sequencing with the scale row in
        # the rotation (possible only because quantization happens at
        # shard time, OUTSIDE the compiled rotation).
        LintTarget("ring", "l2", "float32", "mixed", quant="xfer-int8"),
        LintTarget("ring-overlap", "l2", "float32", "mixed",
                   quant="xfer-int8"),
        LintTarget("ring-overlap", "l2", "float32", "mixed", "bidir",
                   quant="xfer-int8"),
        LintTarget("ring-overlap", "l2", "float32", "mixed", serve=True,
                   quant="xfer-int8"),
    ] + [
        # the FUSED collective-matmul rotation (ops/pallas_ring.py): the
        # per-round compute is the Pallas merge kernel; on this CPU lint
        # platform it lowers in interpret mode with the driver's
        # ppermutes still moving the wire bytes, so R1's overlap
        # sequencing, R4's permute count/direction/payload accounting,
        # R3's dequant contract (int8 wire dequantizes inside the
        # kernel) and R8's FLOP-exactness contract all re-certify on the
        # fused form with no special-casing; R7 additionally prices the
        # declared double-buffer landing residency (extra_elems). The
        # kernel-owned-DMA TPU form (zero permutes, wire bytes declared
        # via the fused_dma side-band) is certified by the injected-meta
        # tests — it cannot lower off-TPU.
        LintTarget("ring-overlap", "l2", "float32", fusion="fused"),
        LintTarget("ring-overlap", "l2", "float32", "exact", "bidir",
                   fusion="fused"),
        LintTarget("ring-overlap", "l2", "float32", "mixed",
                   fusion="fused"),
        LintTarget("ring-overlap", "l2", "float32", "mixed", "bidir",
                   fusion="fused"),
        LintTarget("ring-overlap", "l2", "float32", "mixed",
                   quant="xfer-int8", fusion="fused"),
    ] + [
        # clustered at-rest int8/int4: R2-strict keeps the element budget
        # AND adds the wire-priced gather bound (the probe gather must
        # move code lanes, 4–8× under the f32 bytes — dequantize AFTER
        # the gather), R6's probe discipline re-certifies on the code
        # gathers, R3 checks the dequant contract, and the serve cell
        # re-certifies R5's donation on a quantized bucket-cache program.
        LintTarget("ivf", "l2", "float32", quant="int8"),
        LintTarget("ivf", "l2", "float32", "mixed", quant="int8"),
        LintTarget("ivf", "l2", "float32", quant="int4"),
        LintTarget("ivf", "l2", "float32", "mixed", serve=True,
                   quant="int8"),
        # sharded at-rest int8: the candidate returns ride the exchange
        # as code lanes + a FIFTH (scales) all-to-all — R4 pins the count
        # and holds the payload to the wire-priced budget; R2-strict's
        # per-shard gather bound covers the owner-side exchange gather.
        LintTarget("ivf-sharded", "l2", "float32", "mixed", quant="int8"),
        LintTarget("ivf-sharded", "l2", "float32", "mixed", serve=True,
                   quant="int8"),
    ]


class UnsupportedTarget(Exception):
    """This configuration is rejected by the backend itself (a registered
    restriction, not a lint failure) or cannot lower in this process."""


def _base_cfg(target: LintTarget) -> KNNConfig:
    mixed = target.policy == "mixed"
    return KNNConfig(
        k=LINT_K,
        metric=target.metric,
        dtype=target.dtype,
        query_tile=LINT_QUERY_TILE,
        corpus_tile=(
            LINT_CORPUS_TILE_MIXED if mixed else LINT_CORPUS_TILE
        ),
        precision_policy=target.policy,
        ring_schedule=target.schedule,
        ring_transfer_dtype=(
            "int8" if target.quant == "xfer-int8" else None
        ),
        ring_fusion=target.fusion,
    )


def _lint_m(target: LintTarget) -> int:
    return LINT_M_MIXED if target.policy == "mixed" else LINT_M


def _mixed_meta(target: LintTarget, q_tile: int, c_tile: int):
    """R2 budget extension for mixed cells: the rerank legitimately gathers
    a (q_tile, 4k, d) block of survivor rows — account for it explicitly
    instead of riding on the input-size floor."""
    if target.policy != "mixed":
        return {}
    from mpi_knn_tpu.ops.rerank import overfetch_width

    return {"extra_elems": q_tile * overfetch_width(LINT_K, c_tile) * LINT_D}


def _dense_cost(target: LintTarget, q: int, c: int, c_tile: int, *,
                queries: int, sites: int = 1, trips: int = 1,
                rblocks: int | None = None) -> dict:
    """R8's declared FLOP facts for a dense cell (analysis/cost.py's
    ``dense`` scheme): the padded per-execution distance-dot extents,
    the schedule's site/trip structure, and — on mixed cells — the
    rerank overfetch width and how many rerank blocks run per site-trip
    (per corpus tile for the serial two-pass, one global block for the
    fused pallas path). ``queries`` is the REAL (unpadded) queries
    answered per execution — the roofline's q/s numerator."""
    from mpi_knn_tpu.ops.rerank import overfetch_width

    facts = {"scheme": "dense", "q": int(q), "c": int(c), "d": LINT_D,
             "sites": sites, "trips": trips, "queries": int(queries)}
    if target.policy == "mixed":
        facts["w"] = overfetch_width(LINT_K, c_tile)
        facts["rblocks"] = (
            rblocks if rblocks is not None else int(c) // int(c_tile)
        )
    return facts


def _ivf_cost(index, cfg: KNNConfig, q: int, *, queries: int) -> dict:
    """R8's declared FLOP facts for a clustered cell (analysis/cost.py's
    ``ivf`` scheme): centroid scoring plus the probed-width gather dot,
    with ``q`` the per-device padded query rows (per-shard for the
    sharded layout — the after-opt module is the per-device program)."""
    from mpi_knn_tpu.ops.rerank import mixed_applies, overfetch_width

    v = cfg.nprobe * index.bucket_cap
    facts = {
        "scheme": "ivf", "q": int(q), "d": index.dim,
        "partitions": index.partitions, "nprobe": cfg.nprobe,
        "bucket_cap": index.bucket_cap, "queries": int(queries),
    }
    if cfg.precision_policy == "mixed" and mixed_applies(cfg.k, v):
        facts["w"] = overfetch_width(cfg.k, v)
        facts["rblocks"] = 1
    return facts


def _acc_bytes(dtype: str) -> int:
    return 8 if dtype == "float64" else 4


def _require_x64(target: LintTarget) -> None:
    if target.dtype == "float64" and not jax.config.jax_enable_x64:
        # flipping the global here would silently change unrelated tracing
        # in the host process; the lint CLI opts in explicitly instead
        raise UnsupportedTarget(
            "float64 targets need jax_enable_x64 (the lint CLI enables it; "
            "in-process callers must opt in)"
        )


def hlo_texts(lowered) -> dict[str, str]:
    """Both pipeline stages from one ``jax.stages.Lowered``."""
    texts, _ = hlo_texts_and_memory(lowered)
    return texts


def hlo_texts_and_memory(lowered):
    """Both pipeline stages PLUS the compiled executable's PJRT memory
    stats (args/outputs/alias/temp bytes) from the SAME compile — the
    honesty anchor R7's liveness analyzer is cross-checked against
    (analysis.memory); capturing it here costs zero extra compiles."""
    from mpi_knn_tpu.analysis.memory import pjrt_memory_stats

    compiled = lowered.compile()
    texts = {
        "before_opt": lowered.compiler_ir(dialect="hlo").as_hlo_text(),
        "after_opt": compiled.as_text(),
    }
    return texts, pjrt_memory_stats(compiled)


def _lower_serial(target: LintTarget):
    from mpi_knn_tpu.backends.serial import (
        effective_tiles,
        knn_chunk_update,
        prepare_tiles,
    )
    from mpi_knn_tpu.ops.topk import init_topk

    _require_x64(target)
    cfg = _base_cfg(target)
    m = _lint_m(target)
    q_tile, c_tile = effective_tiles(cfg, m, LINT_NQ)
    q_tiles, qid_tiles, c_tiles, c_tile_ids, q_pad = prepare_tiles(
        np.zeros((m, LINT_D), np.float32),
        np.zeros((LINT_NQ, LINT_D), np.float32),
        np.full(LINT_NQ, -1, np.int32),
        cfg,
        q_tile,
        c_tile,
    )
    acc = jnp.float64 if target.dtype == "float64" else jnp.float32
    carry_d, carry_i = init_topk(q_pad, cfg.k, dtype=acc)
    qt = q_pad // q_tile
    lowered = knn_chunk_update.lower(
        q_tiles,
        qid_tiles,
        c_tiles,
        c_tile_ids,
        carry_d.reshape(qt, q_tile, cfg.k),
        carry_i.reshape(qt, q_tile, cfg.k),
        cfg,
    )
    m_pad = int(c_tiles.shape[0]) * c_tile
    meta = {"q_tile": q_tile, "c_tile": c_tile,
            "acc_bytes": _acc_bytes(target.dtype),
            "cost": _dense_cost(target, q_pad, m_pad, c_tile,
                                queries=LINT_NQ),
            **_mixed_meta(target, q_tile, c_tile)}
    if target.dtype == "bfloat16":
        # R7 allowance, named and measured (ISSUE 15): the bf16-at-rest
        # corpus and queries upcast ONCE to the f32 accumulation dtype —
        # XLA materializes both converted arrays whole, so the liveness
        # peak legitimately carries (m + nq)·d f32 elements beyond the
        # tile working set. This is exactly the residency cost DESIGN.md
        # §6 already documents for compute over compressed stores; the
        # allowance makes it a declared budget line instead of a
        # largest-input coincidence (the R2-floor audit's point).
        meta["peak_extra_elems"] = (m + LINT_NQ) * LINT_D
    return lowered, cfg, meta


def _lower_ring(target: LintTarget):
    from mpi_knn_tpu.backends.ring import (
        _ring_knn_sharded,
        parse_ring_mesh,
        ring_tiles,
    )
    from mpi_knn_tpu.parallel.mesh import make_ring_mesh

    _require_x64(target)
    if len(jax.devices()) < 2:
        raise UnsupportedTarget(
            "ring targets need a multi-device mesh (force the CPU platform "
            "with virtual devices first, as the lint CLI does)"
        )
    cfg = _base_cfg(target)
    m = _lint_m(target)
    mesh = make_ring_mesh(cfg.num_devices, axis_name=cfg.mesh_axis)
    q_axis, axis, dp, ring_n = parse_ring_mesh(mesh)
    q_tile, c_tile, q_pad, c_pad = ring_tiles(cfg, m, LINT_NQ, dp, ring_n)
    dtype = jnp.dtype(cfg.dtype)
    quantized = target.quant == "xfer-int8"
    corpus_args = (
        # the quantized driver quantizes at shard time: the rotation
        # program's corpus inputs ARE int8 codes + the per-row scales
        dict(corpus=jnp.zeros((c_pad, LINT_D), jnp.int8),
             corpus_scale=jnp.zeros((c_pad,), jnp.float32))
        if quantized
        else dict(corpus=jnp.zeros((c_pad, LINT_D), dtype),
                  corpus_scale=None)
    )
    lowered = _ring_knn_sharded.lower(
        jnp.zeros((q_pad, LINT_D), dtype),
        jnp.zeros((q_pad,), jnp.int32),
        corpus_args["corpus"],
        jnp.zeros((c_pad,), jnp.int32),
        cfg,
        target.backend == "ring-overlap",
        mesh,
        axis,
        q_tile,
        c_tile,
        q_axis=q_axis,
        corpus_scale=corpus_args["corpus_scale"],
    )
    meta = {
        "q_tile": q_tile,
        "c_tile": c_tile,
        "acc_bytes": _acc_bytes(target.dtype),
        "ring_n": ring_n,
        "ring_schedule": target.schedule,
        # the corpus block and its global-id row rotate together (a
        # quantized block adds its scale row — three permutes per
        # direction); the bidir schedule doubles that per torus direction,
        # with counter-directed source_target_pairs (R4 checks both the
        # count and the direction split)
        "expected_permutes": (
            (6 if target.schedule == "bidir" else 3) if quantized
            else (4 if target.schedule == "bidir" else 2)
        ),
        # per-device FLOP facts: queries shard over the ring (1-D mesh)
        # or the dp axis (2-D), the corpus block rotates; bidir runs two
        # dot sites (both travelers) for ⌊P/2⌋+1 scan trips
        "cost": _dense_cost(
            target,
            q_pad // (dp if q_axis is not None else ring_n),
            c_pad // ring_n,
            c_tile,
            queries=LINT_NQ,
            sites=2 if target.schedule == "bidir" else 1,
            trips=(ring_n // 2 + 1 if target.schedule == "bidir"
                   else ring_n),
        ),
        **_mixed_meta(target, q_tile, c_tile),
    }
    if quantized:
        meta["quantized"] = True
        # wire pricing: the largest rotation payload is the int8 code
        # block — (c_pad/ring_n rows × d) at 1 byte (ids/scales are d×
        # smaller); a permute above this is rotating float-width rows
        meta["permute_bytes_budget"] = (c_pad // ring_n) * LINT_D
    if target.schedule == "bidir":
        # R2: the second resident traveler is a REGISTERED intermediate —
        # two (c_pad/ring_n, d) blocks live per device instead of one. The
        # entry-input floor (the whole padded corpus) already dominates at
        # lint shapes, but the budget must name the allowance rather than
        # ride on that coincidence.
        block_elems = (c_pad // ring_n) * LINT_D
        meta["extra_elems"] = max(
            meta.get("extra_elems", 0), 2 * block_elems
        )
    if target.fusion == "fused":
        from mpi_knn_tpu.backends.ring import ring_wire_bytes_per_batch

        block_elems = (c_pad // ring_n) * LINT_D
        # Which side owns the wire this cell? Same predicate as the
        # runtime dispatch in backends/ring.py: only the TPU round form
        # (uni + exact) moves the block with in-kernel async remote DMAs;
        # everywhere else (including this CPU lint platform) the driver's
        # ppermutes carry identical bytes and the permute census above
        # stays in force unchanged.
        fused_dma = (
            target.schedule == "uni"
            and target.policy == "exact"
            and cfg.ring_fused_rotation == "round"
            and jax.default_backend() == "tpu"
        )
        meta["fused_dma"] = fused_dma
        # R7: the fused kernel double-buffers the incoming block — the
        # landing buffer for round r+1 is resident while round r's block
        # is on the MXU, so two wire blocks (+ their id rows, folded into
        # the slack) live per device beyond the xla form's single
        # traveler. Declared, not ridden on the input floor (the bidir
        # allowance's rationale).
        meta["extra_elems"] = max(
            meta.get("extra_elems", 0), 2 * block_elems
        )
        if fused_dma:
            # kernel-owned transport: the lowered program contains ZERO
            # collective-permutes — the rotation is async remote copies
            # issued inside the kernel, invisible to both R4's permute
            # census and R8's collective census. The side-band declares
            # the per-device wire bytes of one full rotation so R8
            # prices the fused cell instead of silently reporting zero
            # ICI; a fused_dma cell WITHOUT this declaration is the
            # unpriced-fused-DMA finding.
            meta["expected_permutes"] = 0
            meta["fused_dma_wire_bytes"] = (
                ring_wire_bytes_per_batch(cfg, c_pad, LINT_D, ring_n)
                // ring_n
            )
    return lowered, cfg, meta


def _lower_pallas(target: LintTarget):
    from mpi_knn_tpu.backends.pallas_backend import _pallas_all_knn
    from mpi_knn_tpu.parallel.partition import pad_to_multiple

    if target.dtype != "float32":
        # mirrors all_knn_pallas's own ValueError — a registered
        # restriction, recorded as skipped rather than silently shrunk
        raise UnsupportedTarget(
            "pallas backend computes in float32 only (its own wrapper "
            "rejects other dtypes)"
        )
    cfg = _base_cfg(target)
    m = _lint_m(target)
    # same tile policy as all_knn_pallas (MXU/VPU alignment + caps); cosine
    # rides the L2 kernels on pre-normalized rows, so the lowered program
    # is the L2 kernel either way and the metric needs no special casing
    q_tile = min(max(8, pad_to_multiple(cfg.query_tile, 8)), 512,
                 pad_to_multiple(LINT_NQ, 8))
    c_tile = min(max(128, pad_to_multiple(cfg.corpus_tile, 128)), 2048,
                 pad_to_multiple(m, 128))
    c_pad = pad_to_multiple(m, c_tile)
    q_pad = pad_to_multiple(LINT_NQ, q_tile)
    lowered = _pallas_all_knn.lower(
        jnp.zeros((q_pad, LINT_D), jnp.float32),
        jnp.zeros((c_pad, LINT_D), jnp.float32),
        cfg,
        q_tile,
        c_tile,
        m,
        False,
        cfg.pallas_variant,
    )
    # fused-path rerank width is the global overfetch: the per-tile
    # survivor lists are preselected back down to 4k on compressed keys
    # before the gather (backends/pallas_backend.py)
    meta = {"q_tile": q_tile, "c_tile": c_tile, "acc_bytes": 4,
            # the fused path reranks ONE global overfetch block, not one
            # per corpus tile (the tile survivors are preselected first)
            "cost": _dense_cost(target, q_pad, c_pad, c_tile,
                                queries=LINT_NQ, rblocks=1),
            **_mixed_meta(target, q_tile, c_tile)}
    if target.policy == "mixed":
        # R7 allowance, named and measured (ISSUE 15): the fused mixed
        # path stacks every tile's survivor keys/ids before preselecting
        # back to the global 4k (backends/pallas_backend.py), holding a
        # q_pad×m-order working set live across the tile loop — a real
        # cost of the tiles-variant restack, declared here instead of
        # hiding under R2's input floor
        meta["peak_extra_elems"] = q_pad * m
    return lowered, cfg, meta


def serve_resident_bytes(index) -> int:
    """R5's copy-census threshold for one resident index. For float
    stores this is the resident payload itself. A QUANTIZED store is
    4–8× smaller than the working set its own probe gather legitimately
    materializes (each query row gathers its own copy of its probed
    buckets — code-lane bytes, inside R2's wire-priced gather budget),
    so the census prices quantized cells at the f32-EQUIVALENT store
    bytes instead: "re-paying the corpus" means corpus-of-values-sized
    copies, and the wire-width gather staying under the f32 store is
    exactly the byte win the quantization bought."""
    n = index.nbytes_resident
    if getattr(index, "bucket_scales", None) is not None:
        n = max(n, index.partitions * index.bucket_cap * index.dim * 4)
    return n


# IVF lint shapes: 256 deterministic rows over 8 partitions probed at 2 —
# balanced buckets hold ~32 rows, so the probed width v = nprobe·cap ≥ 64
# keeps the mixed overfetch 4k=16 strictly narrower than v (the R3/R6
# contracts stay non-vacuous) while the probe bound stays well under the
# corpus (2/8 of it), making R2's strict budget a real claim.
LINT_M_IVF, LINT_PARTITIONS, LINT_NPROBE = 256, 8, 2


def _ivf_cfg(target: LintTarget) -> KNNConfig:
    return KNNConfig(
        k=LINT_K,
        query_tile=LINT_QUERY_TILE,
        precision_policy=target.policy,
        partitions=LINT_PARTITIONS,
        nprobe=LINT_NPROBE,
        kmeans_iters=2,  # lint cares about the search program, not fit
        # the at-rest quantization axis rides cfg.dtype (the bf16-store
        # convention): the lint index is genuinely quantized — codes,
        # scales, dequantized norms — so the cells certify the real store
        dtype=(target.quant if target.quant in ("int8", "int4")
               else "float32"),
    )


@functools.lru_cache(maxsize=None)
def _ivf_lint_index(cfg: KNNConfig):
    """One small trained IVFIndex per config — k-means on deterministic
    rows (seeded rng), shared by the one-shot and serve cells."""
    from mpi_knn_tpu.ivf import build_ivf_index

    rng = np.random.default_rng(0)
    data = (rng.standard_normal((LINT_M_IVF, LINT_D)) * 3).astype(np.float32)
    return build_ivf_index(data, cfg)


def _ivf_meta(index, cfg: KNNConfig, q_tile: int, q_pad: int,
              queries: int) -> dict:
    v = cfg.nprobe * index.bucket_cap
    meta = {
        "q_tile": q_tile,
        "c_tile": v,
        "acc_bytes": 4,
        "partitions": index.partitions,
        "dim": index.dim,
        "cost": _ivf_cost(index, cfg, q_pad, queries=queries),
        # R2 STRICT mode: the probe gather is the declared budget — the
        # program must not materialize beyond nprobe·bucket_cap·d per
        # query row (the sublinear claim, machine-checked)
        "budget_elems": q_tile * v * index.dim,
    }
    if index.bucket_scales is not None:
        meta["quantized"] = True
        # wire-priced gather bound: the probe gather moves CODE lanes
        # ((q_tile, nprobe, cap, packed_dim) int8 — 2× headroom for the
        # mixed path's survivor-row f32 gather, which is 4k/v of the
        # probed width at 4 bytes); an f32-sized bucket gather means the
        # store was dequantized before the gather
        meta["quant_gather_bytes"] = (
            2 * q_tile * v * index.buckets.shape[-1]
        )
    return meta


def _lower_ivf(target: LintTarget):
    from mpi_knn_tpu.ivf.search import _ivf_serve_jit, ivf_query_shapes
    from mpi_knn_tpu.ops.topk import init_topk_tiles

    if target.metric != "l2" or target.dtype != "float32":
        raise UnsupportedTarget(
            "the clustered (IVF) path is l2/float32 by its own contract "
            "(ivf/index.py rejects other combinations)"
        )
    cfg = _ivf_cfg(target)
    index = _ivf_lint_index(cfg)
    cfg = index.compatible_cfg(cfg)
    q_tile, q_pad = ivf_query_shapes(
        cfg, cfg.nprobe, index.bucket_cap, index.dim, LINT_NQ
    )
    qt = q_pad // q_tile
    carry_d, carry_i = init_topk_tiles(qt, q_tile, cfg.k, dtype=jnp.float32)
    lowered = _ivf_serve_jit.lower(
        jnp.zeros((qt, q_tile, index.dim), jnp.float32),
        jnp.full((qt, q_tile), -1, jnp.int32),
        carry_d,
        carry_i,
        index.centroids,
        index.centroid_sqs,
        index.buckets,
        index.bucket_ids,
        index.bucket_sqs,
        index.bucket_scales,
        cfg,
        cfg.nprobe,
    )
    return lowered, cfg, _ivf_meta(index, cfg, q_tile, q_pad, LINT_NQ)


# sharded-IVF lint shapes: the same trained 256-row/8-partition index,
# distributed over a 4-shard CPU mesh at the SAFE route cap (None →
# q_tile·nprobe — the default configuration users get; the exchange
# buffers then scale with nprobe, which is what makes the ladder's
# nprobe rung re-lint against a genuinely SMALLER per-shard budget)
LINT_IVF_SHARDS = 4


def _sharded_cfg(target: LintTarget) -> KNNConfig:
    return _ivf_cfg(target).replace(ivf_shards=LINT_IVF_SHARDS)


@functools.lru_cache(maxsize=None)
def _ivf_sharded_lint_index(cfg: KNNConfig):
    """The lint IVFIndex distributed over the 4-shard mesh — shared by
    the one-shot, serve, and ladder sharded cells."""
    from mpi_knn_tpu.ivf import shard_ivf_index

    plain = _ivf_lint_index(cfg.replace(ivf_shards=None, ivf_route_cap=None))
    return shard_ivf_index(plain, shards=cfg.ivf_shards)


def _ivf_sharded_meta(index, cfg: KNNConfig, q_tile: int,
                      route_cap: int, q_pad: int, queries: int) -> dict:
    from mpi_knn_tpu.ivf.sharded import (
        exchange_bytes_per_tile,
        exchange_elems,
        exchange_wire_args,
        expected_exchange_alltoalls,
    )

    v = cfg.nprobe * index.bucket_cap
    wire_dim, wire_itemsize, wire_scale = exchange_wire_args(index)
    meta = {
        "q_tile": q_tile,
        "c_tile": v,
        "acc_bytes": 4,
        "partitions": index.partitions,
        "dim": index.dim,
        "shards": index.shards,
        "route_cap": route_cap,
        # per-SHARD FLOP facts: q_pad is the global padded batch, every
        # shard runs the same program over its q_pad/shards slice
        "cost": _ivf_cost(index, cfg, q_pad // index.shards,
                          queries=queries),
        # R4: the candidate exchange is exactly these all-to-alls
        # (request table + rows/ids/norms returns; a quantized store adds
        # the scales return), full-ring groups, payload bytes inside this
        # declared per-tile budget — priced at the WIRE width (a
        # quantized store's rows are int8 code lanes)
        "expected_alltoalls": expected_exchange_alltoalls(index),
        "exchange_bytes_tile": exchange_bytes_per_tile(
            index.shards, route_cap, index.bucket_cap, wire_dim,
            wire_itemsize, wire_scale,
        ),
        # R2 STRICT, per shard: one resident tile's rerank working set or
        # its exchange buffers, whichever is larger — NOT the shard's
        # resident slice and never the global corpus
        "budget_elems": max(
            q_tile * v * index.dim,
            exchange_elems(
                index.shards, route_cap, index.bucket_cap, index.dim
            ),
        ),
    }
    if index.bucket_scales is not None:
        meta["quantized"] = True
        # wire-priced gather bound, per shard: the larger of the home
        # probe width and the owner-side exchange gather, in code-lane
        # bytes (2× headroom for the survivor f32 gather of the mixed
        # finish)
        meta["quant_gather_bytes"] = 2 * max(
            q_tile * v,
            index.shards * route_cap * index.bucket_cap,
        ) * index.buckets.shape[-1]
    return meta


def _require_sharded_mesh() -> None:
    if len(jax.devices()) < LINT_IVF_SHARDS:
        raise UnsupportedTarget(
            f"sharded-ivf targets need a {LINT_IVF_SHARDS}-device mesh "
            "(force the CPU platform with virtual devices first, as the "
            "lint CLI does)"
        )


def _lower_ivf_sharded(target: LintTarget):
    from jax.sharding import NamedSharding, PartitionSpec
    from mpi_knn_tpu.ivf.sharded import (
        N_STATS,
        _ivf_sharded_jit,
        sharded_query_shapes,
    )

    if target.metric != "l2" or target.dtype != "float32":
        raise UnsupportedTarget(
            "the clustered (IVF) path is l2/float32 by its own contract "
            "(ivf/index.py rejects other combinations)"
        )
    _require_sharded_mesh()
    cfg = _sharded_cfg(target)
    index = _ivf_sharded_lint_index(cfg)
    cfg = index.compatible_cfg(cfg)
    q_tile, q_pad, route_cap = sharded_query_shapes(
        cfg, cfg.nprobe, index.bucket_cap, index.dim, LINT_NQ, index.shards
    )
    qt = q_pad // q_tile
    qsh = NamedSharding(index.mesh, PartitionSpec(index.axis))
    sds = jax.ShapeDtypeStruct
    lowered = _ivf_sharded_jit.lower(
        sds((qt, q_tile, index.dim), jnp.float32, sharding=qsh),
        sds((qt, q_tile), jnp.int32, sharding=qsh),
        sds((qt, q_tile, cfg.k), jnp.float32, sharding=qsh),
        sds((qt, q_tile, cfg.k), jnp.int32, sharding=qsh),
        sds((N_STATS * index.shards,), jnp.int32, sharding=qsh),
        index.centroids,
        index.centroid_sqs,
        index.buckets,
        index.bucket_ids,
        index.bucket_sqs,
        index.bucket_scales,
        cfg,
        cfg.nprobe,
        index.mesh,
        index.axis,
        index.shards,
        route_cap,
    )
    return lowered, cfg, _ivf_sharded_meta(index, cfg, q_tile, route_cap,
                                           q_pad, LINT_NQ)


def _lower_serve(target: LintTarget):
    """Lower the serving engine's per-batch program for one cell through
    the PRODUCTION path: a real (small) CorpusIndex is built and
    ``serve.engine.lower_bucket`` emits the exact Lowered the executable
    cache would compile — a parallel lint-only reimplementation could
    drift and certify a program nobody serves."""
    from mpi_knn_tpu.serve import build_index
    from mpi_knn_tpu.serve.engine import SCRATCH_PARAMS, lower_bucket

    # degradation-ladder rung programs are ordinary cells of the same
    # cache, lowered at the rung's knob values: the bucket/2 rung halves
    # the row bucket, the nprobe rung probes a single partition (which
    # also SHRINKS R2-strict's probed-bytes budget below — the rung must
    # fit its own smaller bound, not ride on the full rung's)
    bucket = LINT_NQ // 2 if target.ladder == "bucket" else LINT_NQ

    if target.backend == "ivf-sharded":
        # the sharded clustered serve cells lower through the production
        # lower_bucket like every other backend; the nprobe ladder rung
        # drops to 1 probe, and at the safe route cap that HALVES both
        # the exchange budget and the rerank working set — the rung must
        # fit its own smaller per-shard bound
        from mpi_knn_tpu.serve.engine import (
            SHARDED_SCRATCH_PARAMS,
            lower_bucket,
        )
        from mpi_knn_tpu.ivf.sharded import sharded_query_shapes

        if target.metric != "l2" or target.dtype != "float32":
            raise UnsupportedTarget(
                "the clustered (IVF) path is l2/float32 by its own "
                "contract (ivf/index.py rejects other combinations)"
            )
        _require_sharded_mesh()
        cfg = _sharded_cfg(target).replace(query_bucket=bucket, donate=True)
        if target.ladder == "nprobe":
            cfg = cfg.replace(nprobe=1)
        index = _ivf_sharded_lint_index(_sharded_cfg(target))
        cfg = index.compatible_cfg(cfg)
        lowered, q_pad, q_tile = lower_bucket(index, cfg, bucket)
        _, _, route_cap = sharded_query_shapes(
            cfg, cfg.nprobe, index.bucket_cap, index.dim, bucket,
            index.shards,
        )
        meta = {
            **_ivf_sharded_meta(index, cfg, q_tile, route_cap, q_pad,
                                bucket),
            "serve": True,
            "donated_params": SHARDED_SCRATCH_PARAMS if cfg.donate else (),
            "resident_bytes": serve_resident_bytes(index),
        }
        return lowered, cfg, meta

    if target.backend == "ivf":
        # the clustered index serves through the SAME bucket cache; its
        # per-batch program is lowered via the production lower_bucket so
        # R5's donation contract and R2/R6's probe discipline certify the
        # exact executable the cache compiles
        if target.metric != "l2" or target.dtype != "float32":
            raise UnsupportedTarget(
                "the clustered (IVF) path is l2/float32 by its own "
                "contract (ivf/index.py rejects other combinations)"
            )
        cfg = _ivf_cfg(target).replace(query_bucket=bucket, donate=True)
        if target.ladder == "nprobe":
            cfg = cfg.replace(nprobe=1)
        index = _ivf_lint_index(_ivf_cfg(target))
        cfg = index.compatible_cfg(cfg)
        lowered, q_pad, q_tile = lower_bucket(index, cfg, bucket)
        meta = {
            **_ivf_meta(index, cfg, q_tile, q_pad, bucket),
            "serve": True,
            "donated_params": SCRATCH_PARAMS if cfg.donate else (),
            "resident_bytes": serve_resident_bytes(index),
        }
        return lowered, cfg, meta

    if target.backend == "pallas" and target.dtype != "float32":
        raise UnsupportedTarget(
            "pallas backend computes in float32 only (its own wrapper "
            "rejects other dtypes)"
        )
    if target.backend in RING_BACKENDS and len(jax.devices()) < 2:
        raise UnsupportedTarget(
            "ring serve targets need a multi-device mesh (force the CPU "
            "platform with virtual devices first, as the lint CLI does)"
        )
    _require_x64(target)
    # the one-shot lowerers call their backend core directly, but the
    # serving path resolves cfg.backend itself — pin it (the default
    # "auto" would quietly build every cell a ring-overlap index)
    cfg = _base_cfg(target).replace(
        backend=target.backend, query_bucket=bucket, donate=True
    )
    m = _lint_m(target)
    index = build_index(np.zeros((m, LINT_D), np.float32), cfg)
    frontend_meta = {}
    if target.frontend:
        # the front-end cell: the batch is formed by the PRODUCTION
        # coalescer — four tenant streams round-robined into one fill-
        # triggered batch — and the bucket lowered is the one THAT batch
        # selects. The no-new-programs contract is checked right here:
        # the coalesced batch must land on exactly the serve cell's
        # bucket (a mismatch means the front end would compile a program
        # the plain serve matrix never certified — a hard failure, not a
        # skip)
        from mpi_knn_tpu.frontend.coalesce import Coalescer
        from mpi_knn_tpu.serve.engine import bucket_rows

        co = Coalescer(max_batch_rows=bucket, max_wait_s=0.001)
        for i in range(4):
            co.admit(f"tenant-{i}", None, bucket // 4, now=0.0)
        cb = co.pop_ready(now=0.0)
        if cb is None or bucket_rows(cb.rows, cfg.query_bucket) != bucket:
            raise AssertionError(
                "front-end coalescing selected a bucket outside the "
                f"serve grid: coalesced {getattr(cb, 'rows', None)} rows "
                f"vs expected bucket {bucket} — the no-new-programs "
                "contract is broken"
            )
        frontend_meta = {
            "frontend": True,
            "coalesced_rows": cb.rows,
            "coalesced_requests": len(cb.parts),
            "coalesced_tenants": len(cb.tenants),
        }
    lowered, q_pad, q_tile = lower_bucket(index, index.cfg, bucket)
    if target.backend in RING_BACKENDS:
        q_axis, _raxis, dp, ring_n = index.ring_meta
        cost = _dense_cost(
            target,
            q_pad // (dp if q_axis is not None else ring_n),
            index.corpus_sharded.shape[0] // ring_n,
            index.c_tile,
            queries=bucket,
            sites=2 if target.schedule == "bidir" else 1,
            trips=(ring_n // 2 + 1 if target.schedule == "bidir"
                   else ring_n),
        )
    elif target.backend == "pallas":
        cost = _dense_cost(target, q_pad, index.corpus_padded.shape[0],
                           index.c_tile, queries=bucket, rblocks=1)
    else:
        cost = _dense_cost(target, q_pad,
                           int(index.tiles.shape[0]) * index.c_tile,
                           index.c_tile, queries=bucket)
    meta = {
        "q_tile": q_tile,
        "c_tile": index.c_tile,
        "acc_bytes": _acc_bytes(target.dtype),
        "cost": cost,
        "serve": True,
        # R5: the scratch params MUST carry the donation in the header,
        # and nothing in the batch program may copy the resident corpus
        "donated_params": SCRATCH_PARAMS if index.cfg.donate else (),
        "resident_bytes": serve_resident_bytes(index),
        **_mixed_meta(target, q_tile, index.c_tile),
        **frontend_meta,
    }
    if target.backend in RING_BACKENDS:
        ring_n = index.ring_meta[3]
        quantized = target.quant == "xfer-int8"
        meta.update(
            ring_n=ring_n,
            ring_schedule=target.schedule,
            expected_permutes=(
                (6 if target.schedule == "bidir" else 3) if quantized
                else (4 if target.schedule == "bidir" else 2)
            ),
        )
        if quantized:
            meta["quantized"] = True
            meta["permute_bytes_budget"] = (
                index.corpus_sharded.shape[0] // ring_n * LINT_D
            )
    return lowered, index.cfg, meta


# one mutation chunk at lint scale: small, but several scatter rows per
# bucket so the in-place update is structurally faithful
LINT_MUTATE_CHUNK = 32


def _lower_mutate(target: LintTarget):
    """Lower one live-mutation cell through the PRODUCTION
    ``serve.mutate.lower_mutation`` — the exact Lowered the mutation
    executable cache compiles (the lower_bucket stance). Meta wires
    R5's donation contract (donated params per kind + the copy-census
    threshold) and R2-strict's touched-working-set budget, with the
    in-place scatter forms registered as buffer-forwarding plumbing."""
    from mpi_knn_tpu.serve import mutate as serve_mutate
    from mpi_knn_tpu.ivf import mutate as ivf_mutate

    kind = target.mutate
    if target.metric != "l2" or target.dtype != "float32":
        raise UnsupportedTarget(
            "the mutation cells lint the l2/float32 layouts (the quant "
            "and dtype axes ride the same programs)"
        )
    if target.backend == "serial":
        if kind == "compact":
            raise UnsupportedTarget(
                "the serial layout has no compact program (tombstones "
                "reclaim in place)"
            )
        from mpi_knn_tpu.serve import build_index

        cfg = _base_cfg(target).replace(backend="serial")
        index = build_index(np.zeros((LINT_M, LINT_D), np.float32), cfg)
        donated = (serve_mutate.SERIAL_UPSERT_DONATED
                   if kind == "upsert" else ivf_mutate.DELETE_DONATED)
    elif target.backend == "ivf":
        cfg = _ivf_cfg(target)
        index = _ivf_lint_index(cfg)
        cfg = index.compatible_cfg(cfg)
        donated = {
            "upsert": ivf_mutate.UPSERT_DONATED,
            "delete": ivf_mutate.DELETE_DONATED,
            "compact": ivf_mutate.COMPACT_DONATED,
        }[kind]
    elif target.backend == "ivf-sharded":
        _require_sharded_mesh()
        cfg = _sharded_cfg(target)
        index = _ivf_sharded_lint_index(cfg)
        cfg = index.compatible_cfg(cfg)
        donated = {
            "upsert": ivf_mutate.UPSERT_DONATED,
            "delete": ivf_mutate.DELETE_DONATED,
        }[kind]
    else:
        raise UnsupportedTarget(
            f"the {target.backend!r} layout refuses live mutation "
            "(serve.mutate raises — a registered restriction)"
        )
    bucket = (index.bucket_cap if kind == "compact"
              else LINT_MUTATE_CHUNK)
    lowered = serve_mutate.lower_mutation(index, cfg, bucket, kind)
    if kind == "compact":
        store = index.buckets
        budget = int(store.shape[0]) * index.bucket_cap * LINT_D
        q_tile, c_tile = index.bucket_cap, LINT_D
    elif kind == "delete":
        budget = bucket  # two small index vectors — nothing else
        q_tile, c_tile = bucket, 1
    else:
        budget = bucket * LINT_D  # the chunk rows (+ the same-sized
        # at-rest cast / norms intermediates, inside the slack)
        q_tile, c_tile = bucket, LINT_D
    meta = {
        "q_tile": q_tile,
        "c_tile": c_tile,
        "acc_bytes": 4,
        "mutate": kind,
        # mutation programs move rows, they do not score them: no dots
        # by design, and R8 certifies exactly that
        "cost": {"scheme": "zero", "queries": bucket},
        # R5: the donated store params MUST alias every output, and the
        # program must not copy the resident corpus
        "donated_params": donated,
        "resident_bytes": serve_resident_bytes(index),
        # R2 STRICT: the touched working set replaces the largest-input
        # floor — a mutation program materializing store-sized payload
        # (the headroom-overflow full-store gather) is a finding
        "budget_elems": budget,
        # the in-place update forms forward the donated buffer rather
        # than materialize new payload (XLA aliases them in place —
        # exactly what R5 certifies); everything that COMPUTES bytes
        # (gather, dot, broadcast, concatenate, copy) stays on the hook
        "strict_exempt_ops": (
            "scatter", "dynamic-update-slice", "fusion", "bitcast",
            "reshape",
        ),
    }
    return lowered, cfg, meta


_LOWERERS = {
    "serial": _lower_serial,
    "ring": _lower_ring,
    "ring-overlap": _lower_ring,
    "pallas": _lower_pallas,
    "ivf": _lower_ivf,
    "ivf-sharded": _lower_ivf_sharded,
}


@functools.lru_cache(maxsize=None)
def lower_target(target: LintTarget):
    """(texts_by_stage, cfg, meta) for one matrix cell, cached — the test
    matrix and the CLI share lowerings within a process. Meta carries the
    compiled executable's PJRT memory stats (``pjrt_memory``) so R7's
    liveness analysis is cross-checked against the runtime's own
    accounting from the very compile that produced the after-opt text."""
    if target.mutate:
        lowered, cfg, meta = _lower_mutate(target)
    elif target.serve:
        lowered, cfg, meta = _lower_serve(target)
    else:
        try:
            lowerer = _LOWERERS[target.backend]
        except KeyError:
            raise UnsupportedTarget(
                f"no lowering registered for backend {target.backend!r}"
            ) from None
        lowered, cfg, meta = lowerer(target)
    texts, pjrt = hlo_texts_and_memory(lowered)
    if pjrt is not None:
        meta["pjrt_memory"] = pjrt
    return texts, cfg, meta


# ---------------------------------------------------------------------------
# Ring-driver lowerings for the overlap artifact (scripts/dump_ring_hlo.py):
# the resumable single-round jit alongside the headline scan driver, at the
# artifact's historical shapes.


def lower_ring_driver(driver: str, variant: str, schedule: str = "uni"):
    """HLO texts for one (driver, variant, schedule) of the ring-overlap
    artifact.

    ``driver``: ``"scan"`` (the headline ``lax.scan`` ring) or
    ``"one_round"`` (the resumable single-round jit). ``variant``:
    ``"overlap"`` or ``"blocking"``. ``schedule``: ``"uni"`` or ``"bidir"``
    (the full-duplex rotation; the one_round form is lowered at a
    non-degenerate round, ``merge_bwd=True``, where both travelers merge).
    """
    from mpi_knn_tpu.backends.ring import (
        _ring_knn_sharded,
        parse_ring_mesh,
        ring_tiles,
    )
    from mpi_knn_tpu.backends.ring_resumable import (
        _ring_one_round,
        _ring_one_round_bidir,
    )
    from mpi_knn_tpu.ops.topk import init_topk
    from mpi_knn_tpu.parallel.mesh import make_ring_mesh

    mesh = make_ring_mesh(None)
    q_axis, axis, dp, ring_n = parse_ring_mesh(mesh)
    cfg = KNNConfig(k=4, query_tile=8, corpus_tile=16,
                    ring_schedule=schedule)
    m, nq, d = LINT_M, LINT_NQ, LINT_D
    q_tile, c_tile, q_pad, c_pad = ring_tiles(cfg, m, nq, dp, ring_n)
    overlap = variant == "overlap"
    data = (
        jnp.zeros((q_pad, d), jnp.float32),
        jnp.zeros((q_pad,), jnp.int32),
        jnp.zeros((c_pad, d), jnp.float32),
        jnp.zeros((c_pad,), jnp.int32),
    )
    if driver == "one_round" and schedule == "bidir":
        lowered = _ring_one_round_bidir.lower(
            *data[:2],
            data[2],
            data[3],
            data[2],
            data[3],
            *init_topk(q_pad, cfg.k, dtype=jnp.float32),
            cfg,
            overlap,
            mesh,
            axis,
            q_tile,
            c_tile,
            q_axis=q_axis,
            rotate=True,
            merge_bwd=True,
        )
    elif driver == "one_round":
        lowered = _ring_one_round.lower(
            *data,
            *init_topk(q_pad, cfg.k, dtype=jnp.float32),
            cfg,
            overlap,
            mesh,
            axis,
            q_tile,
            c_tile,
            q_axis=q_axis,
            rotate=True,
        )
    else:
        lowered = _ring_knn_sharded.lower(
            *data, cfg, overlap, mesh, axis, q_tile, c_tile, q_axis=q_axis
        )
    return hlo_texts(lowered)
