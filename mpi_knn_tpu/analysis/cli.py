"""``mpi-knn lint`` — run the static rule matrix and write the report.

Exit status is the gate: 0 = every checked configuration passed every
applicable rule, 1 = at least one finding (the JSON report carries the
evidence), 2 = usage error. Runs entirely on CPU (virtual 8-device mesh),
so it works on a laptop, in CI, and while the chip is dead.
"""

from __future__ import annotations

import argparse
import sys

from mpi_knn_tpu.config import METRICS


def build_parser() -> argparse.ArgumentParser:
    from mpi_knn_tpu.analysis.lowering import (
        LINT_BACKENDS,
        LINT_DTYPES,
        LINT_QUANTS,
    )

    p = argparse.ArgumentParser(
        prog="mpi-knn lint",
        description="statically lint every backend's compiled program "
        "(HLO rule engine; CPU-only, no TPU needed)",
    )
    p.add_argument("--backend", action="append", choices=LINT_BACKENDS,
                   help="restrict to backend(s); repeatable")
    p.add_argument("--metric", action="append", choices=METRICS,
                   help="restrict to metric(s); repeatable")
    p.add_argument("--dtype", action="append", choices=LINT_DTYPES,
                   help="restrict to dtype(s); repeatable")
    p.add_argument("--policy", action="append", choices=["exact", "mixed"],
                   help="restrict to precision policy(ies): exact "
                   "(one-pass HIGHEST distances) or mixed (the compress-"
                   "and-rerank pipeline, whose dot-precision contract R3 "
                   "certifies); repeatable")
    p.add_argument("--schedule", action="append", choices=["uni", "bidir"],
                   help="restrict to ring schedule(s): uni (one-directional "
                   "rotation) or bidir (full-duplex counter-rotation, whose "
                   "2-permutes-per-direction accounting R4 certifies); "
                   "repeatable")
    p.add_argument("--serve", action="store_true",
                   help="restrict to the serving-engine cells (the "
                   "per-batch programs the executable cache compiles, "
                   "whose donation/aliasing and no-corpus-copy contract "
                   "R5 certifies)")
    p.add_argument("--frontend", action="store_true",
                   help="restrict to the serving-front-end cells (the "
                   "coalesced-dispatch program: a multi-tenant batch "
                   "formed by the production coalescer, which must "
                   "compile exactly an existing serve-grid bucket — no "
                   "new programs — with R1–R5 re-certified on it)")
    p.add_argument("--mutate", action="append",
                   choices=["upsert", "delete", "compact"],
                   help="restrict to the live-mutation cells (ISSUE 14: "
                   "the donated in-place upsert/delete/compact programs "
                   "from serve.mutate.lower_mutation — R5's every-output-"
                   "aliased + no-corpus-copy contract and R2-strict's "
                   "touched-working-set budget); repeatable")
    p.add_argument("--quant", action="append", choices=list(LINT_QUANTS),
                   help="restrict to quantized cells: xfer-int8 (the "
                   "block-scaled int8 ring transfer — R3's quant/dequant "
                   "contract, R4's wire-priced 3-permutes-per-direction "
                   "accounting) or int8/int4 (the clustered store's "
                   "at-rest levels — R2's wire-priced gather bound); "
                   "repeatable")
    p.add_argument("--fusion", action="append", choices=["xla", "fused"],
                   help="restrict to ring-fusion form(s): xla (the "
                   "per-round XLA body) or fused (the collective-matmul "
                   "Pallas kernel cells — R1's side-band overlap "
                   "contract, R4's kernel-owned-rotation accounting, "
                   "R7's double-buffer residency, R8's fused-DMA wire "
                   "pricing); repeatable")
    p.add_argument("--host", action="store_true",
                   help="run the HOST concurrency lint instead (lock "
                   "discipline / lock ordering / thread confinement / "
                   "atomic publication over the threaded host modules "
                   "— analysis/host; jax-free, writes "
                   "host_report.json). All other flags are the host "
                   "linter's own (--rule/--out/--list-rules/-q)")
    p.add_argument("--memory", action="store_true",
                   help="maintain the per-cell peak-HBM ledger (ISSUE "
                   "15): after the sweep, write every checked cell's "
                   "R7 liveness numbers (peak live bytes, attribution, "
                   "largest-temp culprit, PJRT cross-check) into the "
                   "committed ledger — new cells extend it, re-lowered "
                   "cells refresh it. With --ledger-check, COMPARE "
                   "instead of write")
    p.add_argument("--cost", action="store_true",
                   help="maintain the per-cell cost ledger (ISSUE 16): "
                   "after the sweep, write every checked cell's R8 "
                   "numbers (MXU FLOPs cross-checked against the "
                   "closed form, modeled HBM traffic, wire-priced ICI "
                   "bytes, roofline q/s under the default profile) "
                   "into the committed ledger — new cells extend it, "
                   "re-lowered cells refresh it. With --ledger-check, "
                   "COMPARE instead of write")
    p.add_argument("--ledger-check", action="store_true",
                   help="with --memory and/or --cost: fail (exit 1) "
                   "when any cell's ledgered metric drifts beyond the "
                   "committed ledger's tolerance in either direction "
                   "(growth = regression, shrinkage = stale ledger), "
                   "or when a committed cell vanished from a "
                   "full-matrix sweep; never writes")
    p.add_argument("--ledger", default=None, metavar="PATH",
                   help="memory ledger path (default: <--out>/"
                   "memory_ledger.json)")
    p.add_argument("--cost-ledger", default=None, metavar="PATH",
                   help="cost ledger path (default: <--out>/"
                   "cost_ledger.json)")
    p.add_argument("--rule", action="append", metavar="NAME",
                   help="run only the named rule(s), e.g. R2-memory; "
                   "repeatable")
    p.add_argument("--out", default="artifacts/lint", metavar="DIR",
                   help="report directory (default: artifacts/lint)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule registry and exit")
    p.add_argument("--devices", type=int, default=8,
                   help="virtual CPU device count for the ring mesh "
                   "(default 8)")
    p.add_argument("--cache-dir", default=None, metavar="DIR",
                   help="jax persistent compilation cache for the "
                   "matrix's compile step: lint cells that share a "
                   "program (and repeated lint runs — the check.sh "
                   "gates run overlapping sweeps) reuse compiled "
                   "artifacts instead of re-invoking XLA. This is "
                   "jax's own cache, NOT the serve AOT cache: lint "
                   "needs before/after-opt HLO text, which only a "
                   "real compile step (cached at the XLA layer) "
                   "provides")
    p.add_argument("-q", "--quiet", action="store_true")
    return p


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if "--host" in argv:
        # the host concurrency lint (lock discipline / confinement over
        # the threaded host modules) is a separate, jax-free analyzer:
        # route before the HLO parser so neither namespace pays for the
        # other (and --host never forces a platform or imports jax)
        from mpi_knn_tpu.analysis.host.cli import main as host_main

        return host_main([a for a in argv if a != "--host"])
    args = build_parser().parse_args(argv)

    if args.list_rules:
        from mpi_knn_tpu.analysis.rules import RULES

        for r in RULES:
            print(f"{r.name}: {r.description}")
        return 0

    # platform first: lowering the ring matrix needs the virtual mesh, and
    # the config knob must win before any device access (utils.platform)
    from mpi_knn_tpu.utils.platform import force_platform

    force_platform("cpu", n_devices=args.devices)

    import jax

    # the float64 column is the debug-precision mode; without x64 those
    # lowerings would silently be float32 programs wearing an f64 label
    jax.config.update("jax_enable_x64", True)

    if args.cache_dir:
        # compile-level reuse across cells and runs: thresholds zeroed so
        # even the tiny lint programs cache (the defaults skip sub-second
        # compiles, which is every CPU lint cell)
        jax.config.update("jax_compilation_cache_dir", args.cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

    from mpi_knn_tpu.analysis.engine import run_matrix
    from mpi_knn_tpu.analysis.lowering import default_targets

    targets = [
        t
        for t in default_targets()
        if (not args.backend or t.backend in args.backend)
        and (not args.metric or t.metric in args.metric)
        and (not args.dtype or t.dtype in args.dtype)
        and (not args.policy or t.policy in args.policy)
        and (not args.schedule or t.schedule in args.schedule)
        and (not args.quant or t.quant in args.quant)
        and (not args.fusion or t.fusion in args.fusion)
        and (not args.mutate or t.mutate in args.mutate)
        and (t.serve or not args.serve)
        and (t.frontend or not args.frontend)
    ]
    if not targets:
        print("error: no targets match the given filters", file=sys.stderr)
        return 2

    def progress(res):
        if args.quiet:
            return
        if res.skipped is not None:
            print(f"  SKIP {res.target.label}: {res.skipped}")
        else:
            state = "ok" if res.ok else f"{len(res.findings)} finding(s)"
            print(f"  {res.target.label}: {state} "
                  f"[{', '.join(res.rules_run)}]")

    if args.ledger_check and not (args.memory or args.cost):
        print("error: --ledger-check requires --memory or --cost",
              file=sys.stderr)
        return 2
    # each ledger is its rule's output; a sweep that filters the rule out
    # would silently write/check an EMPTY ledger — refuse loudly
    for flag, flagname, rule in (
        (args.memory, "--memory", "R7-peak-memory"),
        (args.cost, "--cost", "R8-cost"),
    ):
        if flag and args.rule and rule not in args.rule:
            print(f"error: {flagname} needs rule {rule} in the sweep "
                  "(drop --rule or include it)", file=sys.stderr)
            return 2

    try:
        report = run_matrix(targets, rule_names=args.rule, progress=progress)
    except KeyError as e:
        print(f"error: {e.args[0]}", file=sys.stderr)
        return 2
    path = report.save(args.out)

    ledger_rc = 0
    if args.memory or args.cost:
        import pathlib

        from mpi_knn_tpu.analysis import ledger as ledgermod

        # a filtered sweep covers a subset: vanished-cell semantics only
        # apply when every default cell was attempted — and a cell whose
        # lowering was environment-skipped THIS run (e.g. ring cells on
        # a one-device mesh) is a coverage gap, never a "vanished"
        # regression or a purge candidate
        full_matrix = len(targets) == len(default_targets())
        skipped_labels = {
            r.target.label for r in report.results
            if r.skipped is not None
        }

        gates = []
        if args.memory:
            from mpi_knn_tpu.analysis import memory as memmod

            gates.append((
                memmod.LEDGER_SPEC,
                pathlib.Path(args.ledger or
                             pathlib.Path(args.out) / "memory_ledger.json"),
                {r.target.label: r.memory for r in report.results
                 if r.skipped is None and r.memory is not None},
            ))
        if args.cost:
            from mpi_knn_tpu.analysis import cost as costmod

            gates.append((
                costmod.LEDGER_SPEC,
                pathlib.Path(args.cost_ledger or
                             pathlib.Path(args.out) / "cost_ledger.json"),
                {r.target.label: r.cost for r in report.results
                 if r.skipped is None and r.cost is not None},
            ))

        for spec, ledger_path, cells in gates:
            try:
                committed = ledgermod.load_ledger(ledger_path, spec)
            except ValueError as e:
                print(f"error: {e}", file=sys.stderr)
                return 2
            if args.ledger_check:
                if committed is None:
                    print(f"error: no committed {spec.kind} ledger at "
                          f"{ledger_path} (generate one with "
                          f"`{spec.regen_cmd}`)", file=sys.stderr)
                    return 2
                drift = ledgermod.ledger_drift(
                    committed, cells, spec, full_matrix=full_matrix,
                    skipped_labels=skipped_labels,
                )
                for why in drift:
                    print(f"  LEDGER-DRIFT {why}")
                if not args.quiet:
                    print(f"{spec.kind} ledger check: {len(cells)} "
                          f"cell(s) vs {ledger_path}: "
                          + ("GREEN" if not drift
                             else f"{len(drift)} drift finding(s)"))
                ledger_rc = max(ledger_rc, 0 if not drift else 1)
            else:
                ledgermod.save_ledger(
                    ledger_path, cells, spec,
                    merge_into=ledgermod.merge_base_for(
                        committed, full_matrix=full_matrix,
                        skipped_labels=skipped_labels,
                    ),
                )
                if not args.quiet:
                    print(f"{spec.kind} ledger: {len(cells)} cell(s) "
                          f"written to {ledger_path}")

    if not args.quiet:
        s = report.to_json()["summary"]
        print(
            f"lint: {s['targets_checked']} target(s) checked, "
            f"{s['targets_skipped']} skipped, {s['findings']} finding(s); "
            f"report: {path}"
        )
        for f in report.findings:
            print(f"  VIOLATION [{f.rule}] {f.target} {f.stage}: {f.message}")
    return max(0 if report.ok else 1, ledger_rc)


if __name__ == "__main__":
    sys.exit(main())
