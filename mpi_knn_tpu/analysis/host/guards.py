"""Guard maps — the declared concurrency contract of the threaded host
modules, and the target list ``mpi-knn lint --host`` sweeps.

The map is ENFORCED, not advisory (rule H1): a shared mutable attribute
of a thread-crossing class that is not declared here — guarded by a
named lock, confined to a named thread root, or explicitly waived with a
rationale — is a finding when it is touched from two or more thread
roots. Waivers are counted in the report, so intentional unguarded
access cannot accrete silently.

Vocabulary (one :class:`ClassGuard` per class):

- ``guarded={attr: lock}`` — every access site must sit inside
  ``with <lock>:`` (an attr name of the same class, or a full token
  like ``frontend.server.Frontend._lock`` / ``obs.spans:_reclock``).
- ``confined={attr: root}`` — the attr belongs to ONE thread root
  (rule H3: it must be unreachable from every other root's call graph).
- ``serialized_by=<token>`` — an externally-serialized pure class (the
  coalescer/scheduler pattern): the class holds no lock of its own, and
  every call into it from outside its serialization group must hold the
  named lock.
- ``instance_per_thread=<root>`` — handler-style classes whose every
  instance lives on one thread (stdlib ``BaseHTTPRequestHandler``).
- ``waivers={attr: rationale}`` — deliberate unguarded access, with the
  one-line why.

``attr_types``/``name_types``/``callbacks`` are resolution hints for the
call graph: attribute → class typing the scanner cannot infer, and the
callback edges (``on_shed``/``on_recover``) that cross layers as bare
callables.
"""

from __future__ import annotations

import dataclasses
import pathlib
from dataclasses import field

_PKG = pathlib.Path(__file__).resolve().parents[2]  # mpi_knn_tpu/


@dataclasses.dataclass
class ClassGuard:
    guarded: dict[str, str] = field(default_factory=dict)
    confined: dict[str, str] = field(default_factory=dict)
    confined_methods: set[str] = field(default_factory=set)
    waivers: dict[str, str] = field(default_factory=dict)
    aliases: dict[str, str] = field(default_factory=dict)
    serialized_by: str | None = None
    instance_per_thread: str | None = None
    force_thread_crossing: bool = False


@dataclasses.dataclass
class GuardMap:
    classes: dict[str, ClassGuard] = field(default_factory=dict)
    # module -> {global name: lock token} / {global name: rationale}
    module_guards: dict[str, dict[str, str]] = field(default_factory=dict)
    module_waivers: dict[str, dict[str, str]] = field(default_factory=dict)
    # "<class qual>.<attr>" -> class qual (instance typing for chains)
    attr_types: dict[str, str] = field(default_factory=dict)
    # module -> {bare/closure name: class qual}
    name_types: dict[str, dict[str, str]] = field(default_factory=dict)
    # "<class qual>.<attr>" (called as self.attr()) -> function qual
    callbacks: dict[str, str] = field(default_factory=dict)
    # root name -> function quals (declared roots; spawns auto-detect more)
    roots: dict[str, list[str]] = field(default_factory=dict)
    # "<function qual>" -> rationale (H4 write-site waivers)
    h4_waivers: dict[str, str] = field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class HostTarget:
    """One lint target: a named group of (module key, source path)."""

    name: str
    modules: tuple[tuple[str, str], ...]


def default_targets() -> list[HostTarget]:
    """The six threaded-module targets of the production sweep."""

    def p(rel: str) -> str:
        return str(_PKG / rel)

    return [
        HostTarget("frontend", (
            ("frontend.coalesce", p("frontend/coalesce.py")),
            ("frontend.scheduler", p("frontend/scheduler.py")),
            ("frontend.server", p("frontend/server.py")),
            ("frontend.loadgen", p("frontend/loadgen.py")),
            ("frontend.router", p("frontend/router.py")),
            ("frontend.modelreplica", p("frontend/modelreplica.py")),
            ("frontend.cli", p("frontend/cli.py")),
        )),
        HostTarget("serve.engine", (("serve.engine", p("serve/engine.py")),)),
        HostTarget("serve.mutate", (("serve.mutate", p("serve/mutate.py")),)),
        HostTarget(
            "serve.aotcache", (("serve.aotcache", p("serve/aotcache.py")),)
        ),
        HostTarget("obs.metrics", (("obs.metrics", p("obs/metrics.py")),)),
        HostTarget("obs.spans", (("obs.spans", p("obs/spans.py")),)),
        HostTarget(
            "resilience.worker",
            (("resilience.worker", p("resilience/worker.py")),),
        ),
    ]


def default_guards() -> GuardMap:
    """The production guard map — the serving stack's threading contract
    in one place (DESIGN.md "Threading model" is the prose twin)."""
    g = GuardMap()

    # -- frontend ---------------------------------------------------------
    g.classes["frontend.server.Frontend"] = ClassGuard(
        guarded={
            "_tickets": "_lock",
            "_stop": "_lock",
            "_crashed": "_lock",
            # the router's mutation high-water mark (ISSUE 18): written
            # by handler threads at apply, read by /healthz snapshots
            "_applied_seq": "_lock",
        },
        confined={
            # the pump is the only thread that dispatches and scatters;
            # the crash handler that clears it runs in the pump's own
            # except block
            "_dispatched": "dispatch-pump",
        },
        aliases={"_work": "_lock"},  # Condition built on _lock
    )
    g.classes["frontend.server.Ticket"] = ClassGuard(
        force_thread_crossing=True,
        waivers={
            "_dists": "published before _event.set(); readers wait on "
            "the Event (happens-before via Event.set/wait)",
            "_ids": "published before _event.set(); readers wait on the "
            "Event",
            "_error": "published before _event.set(); readers wait on "
            "the Event",
            "done_s": "published before _event.set(); readers wait on "
            "the Event",
        },
    )
    g.classes["frontend.scheduler.FrontendScheduler"] = ClassGuard(
        serialized_by="frontend.server.Frontend._lock",
    )
    g.classes["frontend.coalesce.Coalescer"] = ClassGuard(
        serialized_by="frontend.server.Frontend._lock",
    )
    g.classes["frontend.server._http_handler.Handler"] = ClassGuard(
        instance_per_thread="http-handler",
    )
    g.classes["frontend.server.FrontendHTTPServer"] = ClassGuard()
    g.classes["frontend.server._tuned_server_class.TunedHTTPServer"] = (
        ClassGuard(
            guarded={
                # accept thread adds, handler threads discard at
                # connection end, the stopping thread severs the rest
                "_live_socks": "_live_lock",
            },
        )
    )

    # -- frontend.router (ISSUE 18) ---------------------------------------
    # lock order (H2): _mutlock -> _lock, strict; _plock is a leaf. The
    # pure state machines (Membership, ReplicaState, MutationLog) carry
    # no locks of their own — each is serialized by exactly one of the
    # router's locks, declared here.
    g.classes["frontend.router.Router"] = ClassGuard(
        guarded={
            "_inflight": "_lock",
            "_pools": "_plock",
            # the mutation log is the ordering authority: every touch
            # (sequencing, gap computation, replay planning) holds the
            # mutation lock
            "log": "_mutlock",
            # the log's (seq, min_seq) posture, published under _lock
            # after every append so /healthz and the lag gauges never
            # queue behind _mutlock (held across fan-out/replay I/O)
            "_log_posture": "_lock",
        },
    )
    g.classes["frontend.router.Membership"] = ClassGuard(
        serialized_by="frontend.router.Router._lock",
    )
    g.classes["frontend.router.ReplicaState"] = ClassGuard(
        serialized_by="frontend.router.Router._lock",
    )
    g.classes["frontend.router.MutationLog"] = ClassGuard(
        serialized_by="frontend.router.Router._mutlock",
    )
    g.classes["frontend.router._router_handler.Handler"] = ClassGuard(
        instance_per_thread="http-handler",
    )
    g.classes["frontend.router.RouterHTTPServer"] = ClassGuard()
    g.classes["frontend.router.ReplicaSupervisor"] = ClassGuard(
        guarded={
            "_pids": "_lock",
            "_last": "_lock",
        },
    )
    g.classes["frontend.modelreplica.ModelReplica"] = ClassGuard(
        guarded={
            "_applied_seq": "_lock",
            "_mutations": "_lock",
            "_queries": "_lock",
            "_waiting": "_lock",
            "_failing": "_lock",
            "_drop_mutations": "_lock",
        },
    )
    g.classes["frontend.modelreplica._model_handler.Handler"] = (
        ClassGuard(instance_per_thread="http-handler")
    )

    # -- serve engine -----------------------------------------------------
    g.classes["serve.engine.ServeSession"] = ClassGuard(
        guarded={
            "warm_state": "_warm_lock",
            "latencies": "_stats_lock",
            "queries_served": "_stats_lock",
            "retries_total": "_stats_lock",
            "deadline_breaches": "_stats_lock",
            "tenant_stats": "_stats_lock",
            "exchange": "_stats_lock",
            "degradations": "_stats_lock",
            "restorations": "_stats_lock",
            "_rung": "_stats_lock",
            # live-mutation window accumulators (ISSUE 14): mutations may
            # arrive on HTTP handler threads while the pump retires
            "mutation_stats": "_stats_lock",
            "_compactor": "_stats_lock",
        },
        confined={
            # single-dispatcher contract: the session has exactly one
            # submitting/retiring caller (the pump, or a main-thread
            # driver) — these never cross to handler or warm threads
            "_inflight": "dispatch-pump",
            "_seq": "dispatch-pump",
            "_consecutive_breaches": "dispatch-pump",
        },
        waivers={
            "warm_report": "written once by the warm thread before "
            "_serving_ready.set(); readers wait on that Event",
        },
    )
    g.classes["serve.engine._BucketExec"] = ClassGuard()

    # -- serve.mutate (ISSUE 14) ------------------------------------------
    # the background compaction worker: its history/deferral counters are
    # read by /healthz-adjacent snapshots while the tknn-compact thread
    # appends; the index/store state it mutates is serialized by the
    # per-index mutation lock (engine.mutation_lock — index instances are
    # plain data carriers, not scanned classes; the lock discipline there
    # is enforced by construction: every mutation entry point and the
    # dispatch path take the lock, tested in tests/test_mutation.py)
    g.classes["serve.mutate.Compactor"] = ClassGuard(
        guarded={
            "_history": "_lock",
            "_deferred": "_lock",
        },
    )

    # -- aot cache --------------------------------------------------------
    g.classes["serve.aotcache.AOTCache"] = ClassGuard()
    g.module_guards["serve.aotcache"] = {
        "_active": "serve.aotcache:_lock",
        "_configured": "serve.aotcache:_lock",
    }

    # -- obs --------------------------------------------------------------
    for cls in ("Counter", "Gauge", "Histogram"):
        g.classes[f"obs.metrics.{cls}"] = ClassGuard(
            guarded={
                "_value": "_lock",
                "_counts": "_lock",
                "_sum": "_lock",
                "_count": "_lock",
            },
        )
    g.classes["obs.metrics.MetricsRegistry"] = ClassGuard(
        guarded={"_metrics": "_lock", "_kinds": "_lock"},
    )
    g.module_guards["obs.metrics"] = {
        "_jax_listener_installed": "obs.metrics:_jax_lock",
    }
    g.classes["obs.spans.FlightRecorder"] = ClassGuard(
        guarded={"_f": "_lock", "_gen": "_lock", "_open_t0": "_lock"},
        waivers={
            "_ids": "itertools.count.__next__ is atomic under the GIL "
            "(single bytecode, C-implemented)",
            "_stack": "threading.local: per-thread by construction",
        },
    )
    g.module_guards["obs.spans"] = {
        "_recorder": "obs.spans:_reclock",
        "_env_recorder": "obs.spans:_reclock",
    }

    # -- resolution hints -------------------------------------------------
    g.attr_types.update({
        "frontend.server.Frontend.session": "serve.engine.ServeSession",
        "frontend.server.Frontend.scheduler":
            "frontend.scheduler.FrontendScheduler",
        "frontend.scheduler.FrontendScheduler.coalescer":
            "frontend.coalesce.Coalescer",
        "frontend.scheduler.FrontendScheduler._metrics":
            "obs.metrics.MetricsRegistry",
        "serve.engine.ServeSession._metrics": "obs.metrics.MetricsRegistry",
        "frontend.server.FrontendHTTPServer.frontend":
            "frontend.server.Frontend",
        "frontend.router.Router.membership":
            "frontend.router.Membership",
        "frontend.router.Router.log": "frontend.router.MutationLog",
        "frontend.router.Router.supervisor":
            "frontend.router.ReplicaSupervisor",
        "frontend.router.RouterHTTPServer.router":
            "frontend.router.Router",
    })
    g.name_types["frontend.server"] = {
        # the handler closure's captured front end
        "frontend": "frontend.server.Frontend",
    }
    g.name_types["frontend.router"] = {
        # the handler closure's captured router
        "router": "frontend.router.Router",
    }
    g.name_types["frontend.modelreplica"] = {
        # the handler closure's captured replica
        "replica": "frontend.modelreplica.ModelReplica",
    }
    g.callbacks.update({
        # scheduler → session, wired as bare lambdas in Frontend.__init__
        "frontend.scheduler.FrontendScheduler.on_shed":
            "serve.engine.ServeSession.shed_rung",
        "frontend.scheduler.FrontendScheduler.on_recover":
            "serve.engine.ServeSession.restore_rung",
    })

    # -- thread roots -----------------------------------------------------
    g.roots.update({
        # stdlib ThreadingHTTPServer spawns these per connection — not
        # visible as a threading.Thread(...) in our source, so declared
        "http-handler": [
            "frontend.server._http_handler.Handler.do_POST",
            "frontend.server._http_handler.Handler.do_GET",
            "frontend.router._router_handler.Handler.do_POST",
            "frontend.router._router_handler.Handler.do_GET",
            "frontend.modelreplica._model_handler.Handler.do_POST",
            "frontend.modelreplica._model_handler.Handler.do_GET",
        ],
        "dispatch-pump": ["frontend.server.Frontend._run"],
        # the router's own threads (ISSUE 18)
        "router-prober": ["frontend.router.Router._probe_loop"],
        "replica-supervisor": [
            "frontend.router.ReplicaSupervisor._supervise",
        ],
        "warm-pool": [
            "serve.engine.ServeSession.warm",
            "serve.engine.ServeSession.warm._one",
            "frontend.server.Frontend.start._warm",
        ],
    })
    return g
