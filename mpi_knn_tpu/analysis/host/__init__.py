"""Host-side concurrency lint — lock discipline, lock ordering, thread
confinement, and atomic publication for the THREADED host modules.

The HLO lint engine (``mpi_knn_tpu.analysis``, rules R1–R6) machine-
checks every compiled device program; this package is its host-layer
dual: an AST/call-graph static analyzer over the modules that carry the
serving stack's threads (the frontend dispatch pump and HTTP handlers,
the parallel warm pool, concurrent AOT-cache writers, the process-wide
metrics registry and span recorder, the worker supervisor). A latent
race there silently corrupts the very counters, flight records and
cache entries the whole verification story is built on.

Rules (see ``rules.py``):

- **H1 lock discipline** — every shared mutable attribute of a
  thread-crossing class is declared in a per-class guard map, and every
  access site is statically inside a ``with <its-lock>:`` scope (or a
  declared-confined method). Undeclared attributes touched from two or
  more thread roots are findings — the map is enforced, not advisory.
- **H2 lock ordering** — the static lock-acquisition graph (nested
  ``with`` scopes propagated through the call graph) must be acyclic.
- **H3 thread confinement** — attributes declared confined to one
  thread root must be unreachable from any other root's call graph.
- **H4 atomic publish** — file writes in threaded modules flow through
  the atomic temp+``os.replace`` helper (``utils.atomicio``) or carry
  their own ``os.replace``; a bare ``open(..., "w")`` is a finding.

Entry point: ``mpi-knn lint --host`` → ``artifacts/lint/host_report.json``
(``engine.run_host_lint`` programmatically — tests feed deliberately
broken fixture modules through the same path, the repo's convention
since R1). ``witness.py`` is the runtime side: an instrumented lock
wrapper recording acquisition order and guard violations, armed in
tests only.

Jax-free and import-light by construction: the analyzer reads source
text; it never imports the modules it checks.
"""

from mpi_knn_tpu.analysis.host.engine import HostReport, run_host_lint
from mpi_knn_tpu.analysis.host.guards import (
    ClassGuard,
    GuardMap,
    HostTarget,
    default_guards,
    default_targets,
)
from mpi_knn_tpu.analysis.host.rules import HostFinding

__all__ = [
    "ClassGuard",
    "GuardMap",
    "HostFinding",
    "HostReport",
    "HostTarget",
    "default_guards",
    "default_targets",
    "run_host_lint",
]
