"""The host-lint rules (H1–H4) over scanned modules + the guard map.

All four rules share one :class:`Program`: the merged module scans with
a name-resolution layer (class methods, typed attributes, import
aliases, declared callbacks), the thread-root set (auto-detected spawn
sites plus declared HTTP-handler roots), per-root reachability over the
call graph, and the lock-acquisition graph (nested ``with`` scopes
propagated through calls).

Resolution is deliberately optimistic where syntax runs out: an
unresolvable call contributes no edge, an unresolvable attribute chain
stops at the last typed link. That can only HIDE a finding, never
invent one — and the guard map's hints (``attr_types``, ``name_types``,
``callbacks``) close the gaps the real modules need, while the witness
layer (``witness.py``) covers the dynamic remainder at test time.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from dataclasses import field

from mpi_knn_tpu.analysis.host.astscan import (
    Access,
    Call,
    FunctionInfo,
    ModuleScan,
)
from mpi_knn_tpu.analysis.host.guards import ClassGuard, GuardMap

RULES = {
    "H1-lock-discipline": "every shared mutable attribute of a "
    "thread-crossing class is declared (guard map) and every access "
    "site holds its declared lock",
    "H2-lock-order": "the static lock-acquisition graph (nested with "
    "scopes through the call graph) is acyclic",
    "H3-confinement": "attributes declared confined to one thread root "
    "are unreachable from every other root",
    "H4-atomic-publish": "file writes in threaded modules flow through "
    "the atomic temp+os.replace helper",
}


@dataclasses.dataclass
class HostFinding:
    """One host-lint violation."""

    rule: str
    module: str
    where: str  # function qualname (or class qualname for map-level)
    message: str
    lineno: int = 0
    attr: str | None = None

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "module": self.module,
            "where": self.where,
            "lineno": self.lineno,
            "attr": self.attr,
            "message": self.message,
        }


@dataclasses.dataclass
class LockGraph:
    nodes: list[str] = field(default_factory=list)
    edges: list[tuple[str, str]] = field(default_factory=list)
    cycles: list[list[str]] = field(default_factory=list)

    @property
    def acyclic(self) -> bool:
        return not self.cycles

    def to_json(self) -> dict:
        return {
            "nodes": self.nodes,
            "edges": [list(e) for e in self.edges],
            "cycles": self.cycles,
            "acyclic": self.acyclic,
        }


class Program:
    """Merged scans + guard map with resolution, roots, reachability."""

    def __init__(self, scans: list[ModuleScan], guards: GuardMap) -> None:
        self.scans = scans
        self.guards = guards
        self.functions: dict[str, FunctionInfo] = {}
        self.func_module: dict[str, str] = {}
        self.classes = {}
        self.class_module: dict[str, str] = {}
        self.modules: dict[str, ModuleScan] = {}
        for scan in scans:
            self.modules[scan.module] = scan
            for qual, fn in scan.functions.items():
                self.functions[qual] = fn
                self.func_module[qual] = scan.module
            for qual, ci in scan.classes.items():
                self.classes[qual] = ci
                self.class_module[qual] = scan.module
        self.problems: list[str] = []
        self._edges: dict[str, set[str]] = {}
        self._build_edges()
        # root name -> member functions; multi_roots = roots that are
        # concurrent with THEMSELVES (several member functions — the
        # HTTP handler pair, the warm pool — or one target spawned from
        # several sites): a shared write reachable from one of those is
        # already a race without any second root
        self.multi_roots: set[str] = set()
        self.roots: dict[str, set[str]] = self._find_roots()
        self.roots_of: dict[str, set[str]] = self._reach()

    # -- lock token normalization ----------------------------------------

    def norm_lock(self, token: str) -> str:
        """Collapse Condition aliases (auto-detected and declared) onto
        their underlying lock."""
        if ":" in token or "." not in token:
            return token
        cls, attr = token.rsplit(".", 1)
        ci = self.classes.get(cls)
        if ci is not None and attr in ci.cond_aliases:
            return f"{cls}.{ci.cond_aliases[attr]}"
        cg = self.guards.classes.get(cls)
        if cg is not None and attr in cg.aliases:
            return f"{cls}.{cg.aliases[attr]}"
        return token

    def norm_held(self, held: tuple[str, ...]) -> set[str]:
        return {self.norm_lock(t) for t in held}

    def guard_token(self, cls: str, value: str) -> str:
        """A ``guarded=`` / ``serialized_by=`` value as a full token:
        bare attr names bind to the declaring class."""
        if ":" in value or "." in value:
            return self.norm_lock(value)
        return self.norm_lock(f"{cls}.{value}")

    # -- name resolution --------------------------------------------------

    def _class_of_local(self, fn: FunctionInfo, name: str) -> str | None:
        module = self.func_module[fn.qual]
        ctor = fn.local_ctors.get(name)
        if ctor is not None and ctor != "<ThreadPoolExecutor>":
            qual = ctor if ctor in self.classes else f"{module}.{ctor}"
            return qual if qual in self.classes else None
        alias = fn.local_self_aliases.get(name)
        if alias is not None and fn.cls is not None:
            return self.guards.attr_types.get(f"{fn.cls}.{alias}")
        hinted = self.guards.name_types.get(module, {}).get(name)
        if hinted is not None:
            return hinted
        return None

    def _module_key_of_import(self, module: str, alias: str) -> str | None:
        scan = self.modules.get(module)
        if scan is None:
            return None
        dotted = scan.imports.get(alias)
        if dotted is None:
            return None
        for key in self.modules:
            if dotted == key or dotted.endswith("." + key):
                return key
        return None

    def _resolve_nested(self, caller: str, name: str) -> str | None:
        """``name(...)`` — a lexically visible function: a nested
        sibling (walking out through the caller's nesting), then a
        module-level function, then a module-level class constructor."""
        module = self.func_module[caller]
        local = caller[len(module) + 1:].split(".")
        for i in range(len(local), -1, -1):
            qual = ".".join([module, *local[:i], name])
            if qual in self.functions:
                return qual
            ci = self.classes.get(qual)
            if ci is not None:
                return ci.methods.get("__init__")
        return None

    def resolve_call(self, call: Call) -> str | None:
        fn = self.functions[call.func]
        if call.owner is None:
            return self._resolve_nested(call.func, call.name)
        if call.owner == "self":
            if fn.cls is None:
                return None
            ci = self.classes.get(fn.cls)
            if ci is not None and call.name in ci.methods:
                return ci.methods[call.name]
            return self._callback(fn.cls, call.name)
        if call.owner.startswith("self."):
            cls = self._walk_chain(fn.cls, call.owner[5:].split("."))
            if cls is None:
                return None
            ci = self.classes.get(cls)
            if ci is not None and call.name in ci.methods:
                return ci.methods[call.name]
            return self._callback(cls, call.name)
        # local variable or import alias
        cls = self._class_of_local(fn, call.owner)
        if cls is not None:
            ci = self.classes.get(cls)
            if ci is not None and call.name in ci.methods:
                return ci.methods[call.name]
            return self._callback(cls, call.name)
        modkey = self._module_key_of_import(
            self.func_module[call.func], call.owner
        )
        if modkey is not None:
            qual = f"{modkey}.{call.name}"
            if qual in self.functions:
                return qual
            ci = self.classes.get(qual)
            if ci is not None:
                return ci.methods.get("__init__")
        return None

    def _callback(self, cls: str | None, name: str) -> str | None:
        if cls is None:
            return None
        target = self.guards.callbacks.get(f"{cls}.{name}")
        if target is not None and target not in self.functions:
            self.problems.append(
                f"guard map callback {cls}.{name} -> {target}: no such "
                "function in the scanned modules"
            )
            return None
        return target

    def _walk_chain(
        self, cls: str | None, links: list[str]
    ) -> str | None:
        cur = cls
        for link in links:
            if cur is None:
                return None
            cur = self.guards.attr_types.get(f"{cur}.{link}")
        return cur

    def resolve_access_pairs(
        self, access: Access
    ) -> list[tuple[str, str, str]]:
        """(class, attr, kind) pairs along an access chain — each typed
        link is an access to that class (intermediates read, the final
        link carries the recorded kind)."""
        fn = self.functions[access.func]
        if access.owner == "self":
            cur: str | None = access.cls
        elif access.owner == "":
            return []  # module globals are handled separately
        else:
            cur = self._class_of_local(fn, access.owner)
        if cur is None:
            return []
        links = access.chain.split(".")
        out: list[tuple[str, str, str]] = []
        for i, link in enumerate(links):
            kind = access.kind if i == len(links) - 1 else "read"
            out.append((cur, link, kind))
            nxt = self.guards.attr_types.get(f"{cur}.{link}")
            if nxt is None:
                break
            cur = nxt
        return out

    # -- call graph / roots / reachability --------------------------------

    def _build_edges(self) -> None:
        for qual, fn in self.functions.items():
            targets = self._edges.setdefault(qual, set())
            for call in fn.calls:
                t = self.resolve_call(call)
                if t is not None:
                    targets.add(t)

    def _resolve_spawn_target(
        self, fn: FunctionInfo, target: str
    ) -> str | None:
        if target.startswith("self."):
            links = target[5:].split(".")
            if len(links) == 1 and fn.cls is not None:
                ci = self.classes.get(fn.cls)
                if ci is not None:
                    return ci.methods.get(links[0])
                return None
            cls = self._walk_chain(fn.cls, links[:-1])
            if cls is None:
                return None
            ci = self.classes.get(cls)
            return None if ci is None else ci.methods.get(links[-1])
        if "." not in target:
            return self._resolve_nested(fn.qual, target)
        return None

    def _find_roots(self) -> dict[str, set[str]]:
        roots: dict[str, set[str]] = {}
        declared_names: dict[str, str] = {}
        spawn_sites: dict[str, int] = {}
        for name, quals in self.guards.roots.items():
            for q in quals:
                if q not in self.functions:
                    self.problems.append(
                        f"guard map root {name!r} names {q}, which is not "
                        "a scanned function (stale guard map?)"
                    )
                    continue
                declared_names[q] = name
                roots.setdefault(name, set()).add(q)
        for fn in self.functions.values():
            for spawn in fn.spawns:
                target = self._resolve_spawn_target(fn, spawn.target)
                if target is None:
                    continue
                name = declared_names.get(target, f"thread:{target}")
                roots.setdefault(name, set()).add(target)
                spawn_sites[name] = spawn_sites.get(name, 0) + 1
        for name, funcs in roots.items():
            if len(funcs) >= 2 or spawn_sites.get(name, 0) >= 2:
                self.multi_roots.add(name)
        return roots

    def _reach(self) -> dict[str, set[str]]:
        roots_of: dict[str, set[str]] = {q: set() for q in self.functions}
        for name, funcs in self.roots.items():
            seen: set[str] = set()
            dq = deque(funcs)
            while dq:
                cur = dq.popleft()
                if cur in seen:
                    continue
                seen.add(cur)
                dq.extend(self._edges.get(cur, ()))
            for q in seen:
                roots_of[q].add(name)
        return roots_of

    # -- lock graph -------------------------------------------------------

    def acquired_within(self) -> dict[str, set[str]]:
        """Per function: every lock token acquired by it or anything it
        (transitively) calls — fixpoint over the call graph."""
        acq = {
            q: {self.norm_lock(a.lock) for a in fn.acquires}
            for q, fn in self.functions.items()
        }
        changed = True
        while changed:
            changed = False
            for q in self.functions:
                for t in self._edges.get(q, ()):
                    extra = acq.get(t, set()) - acq[q]
                    if extra:
                        acq[q].update(extra)
                        changed = True
        return acq

    def lock_graph(self) -> tuple[LockGraph, list[HostFinding]]:
        acq = self.acquired_within()
        edges: set[tuple[str, str]] = set()
        findings: list[HostFinding] = []
        seen_self_edge: set[tuple[str, str]] = set()

        def add_edges(
            held: tuple[str, ...], acquired: set[str],
            fn: FunctionInfo, lineno: int,
        ) -> None:
            for h in self.norm_held(held):
                for a in acquired:
                    if h == a:
                        key = (fn.qual, h)
                        if key not in seen_self_edge:
                            seen_self_edge.add(key)
                            findings.append(HostFinding(
                                rule="H2-lock-order",
                                module=self.func_module[fn.qual],
                                where=fn.qual,
                                lineno=lineno,
                                attr=h,
                                message=f"{h} is (re)acquired while "
                                "already held — a non-reentrant "
                                "self-deadlock",
                            ))
                    else:
                        edges.add((h, a))

        for fn in self.functions.values():
            for a in fn.acquires:
                add_edges(a.held, {self.norm_lock(a.lock)}, fn, a.lineno)
            for call in fn.calls:
                if not call.held:
                    continue
                t = self.resolve_call(call)
                if t is not None:
                    add_edges(call.held, acq.get(t, set()), fn, call.lineno)

        nodes = sorted({n for e in edges for n in e} | {
            self.norm_lock(a.lock)
            for fn in self.functions.values()
            for a in fn.acquires
        })
        graph = LockGraph(nodes=nodes, edges=sorted(edges))
        graph.cycles = _find_cycles(nodes, edges)
        for cyc in graph.cycles:
            findings.append(HostFinding(
                rule="H2-lock-order",
                module="*",
                where=" -> ".join(cyc),
                message="lock-acquisition cycle (potential deadlock): "
                + " -> ".join([*cyc, cyc[0]]),
            ))
        return graph, findings


def _find_cycles(
    nodes: list[str], edges: set[tuple[str, str]]
) -> list[list[str]]:
    """Cycles in the lock graph via iterative Tarjan SCC (an SCC with
    more than one node, or a self-loop, is a cycle)."""
    adj: dict[str, list[str]] = {n: [] for n in nodes}
    for a, b in sorted(edges):
        adj[a].append(b)
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    counter = [0]
    sccs: list[list[str]] = []

    def strongconnect(v: str) -> None:
        work: list[tuple[str, int]] = [(v, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            recurse = False
            for i in range(pi, len(adj[node])):
                w = adj[node][i]
                if w not in index:
                    work[-1] = (node, i + 1)
                    work.append((w, 0))
                    recurse = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if recurse:
                continue
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1 or (node, node) in edges:
                    sccs.append(sorted(scc))
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])

    for n in nodes:
        if n not in index:
            strongconnect(n)
    return sccs


# ---------------------------------------------------------------------------
# H1 / H3: attribute discipline


def _class_guard(guards: GuardMap, cls: str) -> ClassGuard:
    return guards.classes.get(cls) or ClassGuard()


def check_attr_discipline(
    prog: Program, target_modules: set[str]
) -> tuple[list[HostFinding], list[dict]]:
    """H1 (lock discipline, enforced guard map, serialized classes,
    module globals) and H3 (confinement) over every scanned class of the
    target modules. Returns (findings, waivers-used)."""
    findings: list[HostFinding] = []
    waivers: list[dict] = []
    guards = prog.guards

    # resolved accesses grouped per owning class: (cls, attr) ->
    # list[(Access, kind)]
    by_class: dict[str, list[tuple[Access, str, str]]] = {}
    for fn in prog.functions.values():
        for access in fn.accesses:
            for cls, attr, kind in prog.resolve_access_pairs(access):
                by_class.setdefault(cls, []).append((access, attr, kind))

    serial_groups: dict[str, set[str]] = {}
    for cls, cg in guards.classes.items():
        if cg.serialized_by is not None:
            serial_groups.setdefault(
                prog.guard_token(cls, cg.serialized_by), set()
            ).add(cls)

    for cls in sorted(prog.classes):
        module = prog.class_module[cls]
        if module not in target_modules:
            continue
        ci = prog.classes[cls]
        cg = _class_guard(guards, cls)
        if cg.instance_per_thread is not None:
            continue  # per-thread instances: nothing shared to check
        accesses = by_class.get(cls, [])
        token = (
            None if cg.serialized_by is None
            else prog.guard_token(cls, cg.serialized_by)
        )
        if token is not None:
            group = serial_groups.get(token, {cls})
            findings.extend(_check_serialized(
                prog, cls, ci, token, group, accesses
            ))
            continue
        findings.extend(_check_guarded(prog, cls, ci, cg, accesses))
        findings.extend(_check_confined(prog, cls, cg, accesses))
        findings.extend(
            _check_undeclared(prog, cls, ci, cg, accesses)
        )
        for attr, why in sorted(cg.waivers.items()):
            waivers.append(
                {"where": f"{cls}.{attr}", "rationale": why}
            )
    # serialized / per-thread classes still surface declared waivers
    for cls in sorted(prog.classes):
        if prog.class_module[cls] not in target_modules:
            continue
        cg = _class_guard(guards, cls)
        if cg.serialized_by is not None or cg.instance_per_thread:
            for attr, why in sorted(cg.waivers.items()):
                waivers.append(
                    {"where": f"{cls}.{attr}", "rationale": why}
                )

    findings.extend(_check_globals(prog, target_modules, waivers))
    return findings, waivers


def _is_state_attr(ci: object, cg: ClassGuard, attr: str) -> bool:
    """Whether ``attr`` is data (not a method, lock, alias or
    thread-local)."""
    methods = getattr(ci, "methods", {})
    locks = getattr(ci, "lock_attrs", set())
    locals_ = getattr(ci, "local_attrs", set())
    return (
        attr not in methods
        and attr not in locks
        and attr not in locals_
        and attr not in cg.aliases
    )


def _check_serialized(
    prog: Program,
    cls: str,
    ci: object,
    token: str,
    group: set[str],
    accesses: list[tuple[Access, str, str]],
) -> list[HostFinding]:
    """Every touch of an externally-serialized class from outside its
    serialization group must hold the serializing lock."""
    findings = []
    cg = _class_guard(prog.guards, cls)
    for access, attr, _kind in accesses:
        if access.cls in group:
            continue  # intra-group: the boundary is the contract
        if not _is_state_attr(ci, cg, attr):
            continue
        if access.func.endswith(".__init__"):
            # construction precedes sharing: an out-of-group constructor
            # seeding a serialized class's state runs before any thread
            # can reach the object (in-group ctors were skipped above)
            continue
        if token not in prog.norm_held(access.held):
            findings.append(HostFinding(
                rule="H1-lock-discipline",
                module=prog.func_module[access.func],
                where=access.func,
                lineno=access.lineno,
                attr=f"{cls}.{attr}",
                message=f"access to externally-serialized {cls}.{attr} "
                f"without holding {token} (held: "
                f"{sorted(prog.norm_held(access.held)) or 'nothing'})",
            ))
    # method CALLS into the group from outside it
    for fn in prog.functions.values():
        if fn.cls in group:
            continue
        for call in fn.calls:
            t = prog.resolve_call(call)
            if t is None:
                continue
            t_cls = prog.functions[t].cls
            if t_cls != cls or t.endswith(".__init__"):
                continue
            if token not in prog.norm_held(call.held):
                findings.append(HostFinding(
                    rule="H1-lock-discipline",
                    module=prog.func_module[call.func],
                    where=call.func,
                    lineno=call.lineno,
                    attr=f"{cls}.{call.name}",
                    message=f"call into externally-serialized "
                    f"{cls}.{call.name} without holding {token}",
                ))
    return findings


def _check_guarded(
    prog: Program,
    cls: str,
    ci: object,
    cg: ClassGuard,
    accesses: list[tuple[Access, str, str]],
) -> list[HostFinding]:
    findings = []
    for access, attr, _kind in accesses:
        lock = cg.guarded.get(attr)
        if lock is None:
            continue
        if access.func.endswith(".__init__") and access.cls == cls:
            continue
        fname = access.func.rsplit(".", 1)[-1]
        if fname in cg.confined_methods:
            continue
        token = prog.guard_token(cls, lock)
        held = prog.norm_held(access.held)
        if token not in held:
            what = (
                f"under the WRONG lock ({sorted(held)})" if held
                else "with no lock held"
            )
            findings.append(HostFinding(
                rule="H1-lock-discipline",
                module=prog.func_module[access.func],
                where=access.func,
                lineno=access.lineno,
                attr=f"{cls}.{attr}",
                message=f"{cls}.{attr} is declared guarded by {token} "
                f"but accessed {what}",
            ))
    return findings


def _check_confined(
    prog: Program,
    cls: str,
    cg: ClassGuard,
    accesses: list[tuple[Access, str, str]],
) -> list[HostFinding]:
    findings = []
    for access, attr, _kind in accesses:
        root = cg.confined.get(attr)
        if root is None:
            continue
        if access.func.endswith(".__init__") and access.cls == cls:
            continue
        foreign = sorted(
            r for r in prog.roots_of.get(access.func, set()) if r != root
        )
        if foreign:
            findings.append(HostFinding(
                rule="H3-confinement",
                module=prog.func_module[access.func],
                where=access.func,
                lineno=access.lineno,
                attr=f"{cls}.{attr}",
                message=f"{cls}.{attr} is declared {root}-confined but "
                f"{access.func} is reachable from thread root(s) "
                f"{foreign}",
            ))
    return findings


def _check_undeclared(
    prog: Program,
    cls: str,
    ci: object,
    cg: ClassGuard,
    accesses: list[tuple[Access, str, str]],
) -> list[HostFinding]:
    """The enforcement teeth: an attribute NOT in the guard map, written
    outside __init__, and touched from >= 2 thread roots."""
    crossing_roots: set[str] = set()
    for m in getattr(ci, "methods", {}).values():
        crossing_roots |= prog.roots_of.get(m, set())
    declared = (
        set(cg.guarded) | set(cg.confined) | set(cg.waivers)
    )
    by_attr: dict[str, list[tuple[Access, str]]] = {}
    for access, attr, kind in accesses:
        if not _is_state_attr(ci, cg, attr) or attr in declared:
            continue
        if access.func.endswith(".__init__") and access.cls == cls:
            continue
        by_attr.setdefault(attr, []).append((access, kind))
    findings = []
    if (
        len(crossing_roots) < 2
        and not (crossing_roots & prog.multi_roots)
        and not cg.force_thread_crossing
    ):
        return findings
    for attr, uses in sorted(by_attr.items()):
        writes = [a for a, k in uses if k == "write"]
        if not writes:
            continue
        roots: set[str] = set()
        for a, _k in uses:
            roots |= prog.roots_of.get(a.func, set())
        if len(roots) >= 2 or roots & prog.multi_roots:
            w = writes[0]
            findings.append(HostFinding(
                rule="H1-lock-discipline",
                module=prog.class_module[cls],
                where=w.func,
                lineno=w.lineno,
                attr=f"{cls}.{attr}",
                message=f"undeclared shared attribute {cls}.{attr}: "
                f"mutated outside __init__ and touched from thread "
                f"roots {sorted(roots)}; declare it in the guard map "
                "(guarded/confined) or waive it with a rationale",
            ))
    return findings


def _check_globals(
    prog: Program, target_modules: set[str], waivers: list[dict]
) -> list[HostFinding]:
    findings = []
    for module in sorted(target_modules):
        scan = prog.modules.get(module)
        if scan is None:
            continue
        declared = prog.guards.module_guards.get(module, {})
        waived = prog.guards.module_waivers.get(module, {})
        for name, why in sorted(waived.items()):
            waivers.append({"where": f"{module}:{name}", "rationale": why})
        names = scan.mutable_globals - scan.module_locks
        for name in sorted(names):
            uses = [
                a
                for fn in scan.functions.values()
                for a in fn.accesses
                if a.owner == "" and a.attr == name
            ]
            lock = declared.get(name)
            if lock is not None:
                token = prog.norm_lock(lock)
                for a in uses:
                    if token not in prog.norm_held(a.held):
                        findings.append(HostFinding(
                            rule="H1-lock-discipline",
                            module=module,
                            where=a.func,
                            lineno=a.lineno,
                            attr=f"{module}:{name}",
                            message=f"module global {name} is declared "
                            f"guarded by {token} but accessed without it",
                        ))
                continue
            if name in waived:
                continue
            roots: set[str] = set()
            writes = [a for a in uses if a.kind == "write"]
            for a in uses:
                roots |= prog.roots_of.get(a.func, set())
            if writes and (len(roots) >= 2 or roots & prog.multi_roots):
                findings.append(HostFinding(
                    rule="H1-lock-discipline",
                    module=module,
                    where=writes[0].func,
                    lineno=writes[0].lineno,
                    attr=f"{module}:{name}",
                    message=f"undeclared shared module global {name}: "
                    f"written and touched from thread roots "
                    f"{sorted(roots)}; guard it (module_guards) or "
                    "waive it with a rationale",
                ))
    return findings


# ---------------------------------------------------------------------------
# H4: atomic publish


def check_atomic_publish(
    prog: Program, target_modules: set[str]
) -> tuple[list[HostFinding], list[dict]]:
    findings: list[HostFinding] = []
    waivers: list[dict] = []
    for module in sorted(target_modules):
        scan = prog.modules.get(module)
        if scan is None:
            continue
        for fn in scan.functions.values():
            for w in fn.writes:
                if fn.calls_os_replace:
                    continue  # the temp+replace idiom, in-function
                why = prog.guards.h4_waivers.get(fn.qual)
                if why is not None:
                    waivers.append(
                        {"where": f"{fn.qual}:{w.lineno}",
                         "rationale": why}
                    )
                    continue
                findings.append(HostFinding(
                    rule="H4-atomic-publish",
                    module=module,
                    where=fn.qual,
                    lineno=w.lineno,
                    attr=w.what,
                    message=f"truncating file write ({w.what}) in a "
                    "threaded module without the atomic temp+os.replace "
                    "idiom — route it through "
                    "mpi_knn_tpu.utils.atomicio or waive it",
                ))
    return findings, waivers
