"""AST scanner for the host concurrency lint.

One pass over a module's source produces a :class:`ModuleScan`: per
function (qualified by its lexical nesting, e.g.
``frontend.server._http_handler.Handler.do_POST``) the attribute
accesses with the lock context they happen under, the calls (with held
locks — the ingredient of the H2 acquisition graph), the thread spawns
(``threading.Thread(target=...)``, ``ThreadPoolExecutor.map/submit`` —
the auto-detected thread roots), and the file-write sites H4 prices.

The scanner is purely syntactic and deliberately conservative: it
resolves only what Python's surface syntax pins down — ``self``
attributes, module-level names, locals bound by ``x = ClassName(...)``
or ``x = self.attr`` (typed via the guard map's ``attr_types``). What it
cannot resolve it records as unresolved rather than guessing; the rules
treat unresolved edges as absent and the guard map carries explicit
hints (``name_types``, ``callbacks``) where the real modules need them.
"""

from __future__ import annotations

import ast
import dataclasses
from dataclasses import field

# method names that mutate their receiver in place: a call
# ``self.X.append(...)`` is a WRITE to ``X`` for lock-discipline
# purposes (the reference itself never rebinds)
MUTATOR_METHODS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "remove", "discard",
    "clear", "update", "setdefault", "pop", "popleft", "popitem", "sort",
})

# modes of ``open`` that truncate/replace the target — the publication
# hazard H4 exists for ("a" appends, "r"/"x" never clobber a reader)
TRUNCATING_MODES = ("w", "wb", "w+", "wb+", "w+b")


@dataclasses.dataclass(frozen=True)
class Access:
    """One attribute (or tracked module-global) touch."""

    owner: str  # syntactic owner: "self", a local/closure name, or "" (global)
    attr: str  # first attribute link ("" for module globals: name in chain)
    chain: str  # full dotted chain after the owner (attr included)
    kind: str  # "read" | "write"
    lineno: int
    func: str  # qualname of the containing function
    cls: str | None  # innermost enclosing class qualname
    held: tuple[str, ...]  # raw lock tokens held at the access site


@dataclasses.dataclass(frozen=True)
class LockAcquire:
    """One ``with <lock>:`` entry."""

    lock: str  # raw token ("<cls>.<attr>" or "<module>:<name>")
    held: tuple[str, ...]  # tokens already held when this one is taken
    lineno: int
    func: str


@dataclasses.dataclass(frozen=True)
class Call:
    """One call site, with the locks held across it."""

    owner: str | None  # None = bare name; "self"; "self.X[.Y]"; local; alias
    name: str  # called function/method name
    held: tuple[str, ...]
    lineno: int
    func: str
    cls: str | None


@dataclasses.dataclass(frozen=True)
class Spawn:
    """One thread-root creation site."""

    target: str  # "self._run", "_warm", "self.warm", ... (syntactic)
    kind: str  # "thread" | "pool"
    lineno: int
    func: str


@dataclasses.dataclass(frozen=True)
class FileWrite:
    """One H4-relevant write site."""

    what: str  # "open-w" | "write_text" | "write_bytes"
    lineno: int
    func: str


@dataclasses.dataclass
class FunctionInfo:
    qual: str
    cls: str | None
    lineno: int
    calls: list[Call] = field(default_factory=list)
    accesses: list[Access] = field(default_factory=list)
    acquires: list[LockAcquire] = field(default_factory=list)
    spawns: list[Spawn] = field(default_factory=list)
    writes: list[FileWrite] = field(default_factory=list)
    calls_os_replace: bool = False
    # local name -> class qualname, from `x = ClassName(...)` and
    # (via guard-map attr_types, applied by the rules) `x = self.attr`
    local_ctors: dict[str, str] = field(default_factory=dict)
    local_self_aliases: dict[str, str] = field(default_factory=dict)


@dataclasses.dataclass
class ClassInfo:
    qual: str
    lineno: int
    methods: dict[str, str] = field(default_factory=dict)  # name -> func qual
    init_attrs: set[str] = field(default_factory=set)  # assigned in __init__
    lock_attrs: set[str] = field(default_factory=set)  # threading.Lock()/RLock()
    cond_aliases: dict[str, str] = field(default_factory=dict)  # Condition(x)
    local_attrs: set[str] = field(default_factory=set)  # threading.local()


@dataclasses.dataclass
class ModuleScan:
    module: str  # dotted key, e.g. "frontend.server"
    path: str
    functions: dict[str, FunctionInfo] = field(default_factory=dict)
    classes: dict[str, ClassInfo] = field(default_factory=dict)
    module_locks: set[str] = field(default_factory=set)  # module-level Lock()s
    mutable_globals: set[str] = field(default_factory=set)  # written via global
    imports: dict[str, str] = field(default_factory=dict)  # alias -> dotted


def _is_threading_call(node: ast.expr, names: tuple[str, ...]) -> bool:
    """Whether ``node`` is a call to ``threading.<name>`` (or a bare
    imported ``<name>``) for any of ``names``."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        return f.value.id == "threading" and f.attr in names
    if isinstance(f, ast.Name):
        return f.id in names
    return False


def _dotted(node: ast.expr) -> str | None:
    """``a.b.c`` as a string, for Name/Attribute chains only."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _Scanner:
    """Recursive walker with explicit scope, class, and held-lock state."""

    def __init__(self, module: str, path: str, tree: ast.Module) -> None:
        self.scan = ModuleScan(module=module, path=path)
        self._scope: list[str] = []  # lexical names (classes + functions)
        self._class_stack: list[ClassInfo] = []
        self._func_stack: list[FunctionInfo] = []
        self._held: list[str] = []
        self._collect_module_level(tree)
        for node in tree.body:
            self._visit(node)

    # -- module-level pre-pass -------------------------------------------

    def _collect_module_level(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Global):
                self.scan.mutable_globals.update(node.names)
        for node in tree.body:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                self._record_import(node)
            if isinstance(node, ast.Assign) and _is_threading_call(
                node.value, ("Lock", "RLock")
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.scan.module_locks.add(t.id)

    def _record_import(self, node: ast.Import | ast.ImportFrom) -> None:
        if isinstance(node, ast.Import):
            for a in node.names:
                self.scan.imports[a.asname or a.name.split(".")[0]] = a.name
        else:
            mod = node.module or ""
            for a in node.names:
                self.scan.imports[a.asname or a.name] = f"{mod}.{a.name}"

    # -- scope helpers ----------------------------------------------------

    def _qual(self, name: str) -> str:
        return ".".join([self.scan.module, *self._scope, name])

    @property
    def _cls(self) -> ClassInfo | None:
        return self._class_stack[-1] if self._class_stack else None

    @property
    def _fn(self) -> FunctionInfo | None:
        return self._func_stack[-1] if self._func_stack else None

    # -- dispatch ---------------------------------------------------------

    def _visit(self, node: ast.AST) -> None:
        meth = getattr(self, f"_visit_{type(node).__name__}", None)
        if meth is not None:
            meth(node)
        else:
            self._generic(node)

    def _generic(self, node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            self._visit(child)

    # -- definitions ------------------------------------------------------

    def _visit_ClassDef(self, node: ast.ClassDef) -> None:
        info = ClassInfo(qual=self._qual(node.name), lineno=node.lineno)
        self.scan.classes[info.qual] = info
        self._scope.append(node.name)
        self._class_stack.append(info)
        held = self._held
        self._held = []  # a class body never runs under a caller's lock
        try:
            for child in node.body:
                self._visit(child)
        finally:
            self._held = held
            self._class_stack.pop()
            self._scope.pop()

    def _visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._def_function(node)

    def _visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._def_function(node)

    def _def_function(
        self, node: ast.FunctionDef | ast.AsyncFunctionDef
    ) -> None:
        qual = self._qual(node.name)
        info = FunctionInfo(
            qual=qual,
            cls=self._cls.qual if self._cls else None,
            lineno=node.lineno,
        )
        self.scan.functions[qual] = info
        if self._cls is not None and len(self._func_stack) == 0:
            self._cls.methods[node.name] = qual
        self._scope.append(node.name)
        self._func_stack.append(info)
        held = self._held
        self._held = []  # lock context is not inherited lexically
        try:
            for child in node.body:
                self._visit(child)
        finally:
            self._held = held
            self._func_stack.pop()
            self._scope.pop()

    # -- locks / with -----------------------------------------------------

    def _lock_token(self, expr: ast.expr) -> str | None:
        """The raw lock token of a with-context expression, or None when
        the expression is not a recognizable lock (a call, a chained
        attribute, …)."""
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and self._cls is not None
        ):
            return f"{self._cls.qual}.{expr.attr}"
        if isinstance(expr, ast.Name) and expr.id in self.scan.module_locks:
            return f"{self.scan.module}:{expr.id}"
        return None

    def _visit_With(self, node: ast.With) -> None:
        tokens: list[str] = []
        for item in node.items:
            tok = self._lock_token(item.context_expr)
            self._visit(item.context_expr)
            if item.optional_vars is not None:
                self._note_with_alias(item)
                self._visit(item.optional_vars)
            if tok is not None:
                if self._fn is not None:
                    self._fn.acquires.append(LockAcquire(
                        lock=tok,
                        held=tuple(self._held),
                        lineno=item.context_expr.lineno,
                        func=self._fn.qual,
                    ))
                self._held.append(tok)
                tokens.append(tok)
        try:
            for child in node.body:
                self._visit(child)
        finally:
            for _ in tokens:
                self._held.pop()

    def _note_with_alias(self, item: ast.withitem) -> None:
        """``with ThreadPoolExecutor(...) as pool:`` — remember the pool
        name so ``pool.map(f, ...)`` registers a spawn."""
        fn = self._fn
        if fn is None or not isinstance(item.optional_vars, ast.Name):
            return
        ctx = item.context_expr
        if isinstance(ctx, ast.Call):
            dotted = _dotted(ctx.func) or ""
            if dotted.endswith("ThreadPoolExecutor"):
                fn.local_ctors[item.optional_vars.id] = "<ThreadPoolExecutor>"

    # -- assignments ------------------------------------------------------

    def _target_chain(self, t: ast.expr) -> tuple[str, str] | None:
        """(owner, chain) of an assignment target rooted at a name."""
        if isinstance(t, ast.Subscript):
            t = t.value
        dotted = _dotted(t)
        if dotted is None or "." not in dotted:
            return None
        owner, chain = dotted.split(".", 1)
        return owner, chain

    def _record_access(
        self, owner: str, chain: str, kind: str, lineno: int
    ) -> None:
        fn = self._fn
        if fn is None:
            return
        fn.accesses.append(Access(
            owner=owner,
            attr=chain.split(".")[0] if chain else "",
            chain=chain,
            kind=kind,
            lineno=lineno,
            func=fn.qual,
            cls=self._cls.qual if self._cls else None,
            held=tuple(self._held),
        ))

    def _visit_Assign(self, node: ast.Assign) -> None:
        self._handle_assign(node.targets, node.value, node.lineno)
        self._visit(node.value)
        for t in node.targets:
            self._visit_store_target(t)

    def _visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._handle_assign([node.target], None, node.lineno)
        self._visit(node.value)
        self._visit_store_target(node.target)

    def _visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        # `self.x: dict = {}` — same write semantics as a bare Assign
        if node.value is not None:
            self._handle_assign([node.target], node.value, node.lineno)
            self._visit(node.value)
        self._visit_store_target(node.target)

    def _visit_store_target(self, t: ast.expr) -> None:
        # subscript indices / nested tuples still contain reads
        if isinstance(t, ast.Subscript):
            self._visit(t.slice)
            self._visit_store_target(t.value)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._visit_store_target(e)
        elif isinstance(t, ast.Attribute):
            # the OWNER side of `self.a.b = x` is a read of `a`; the
            # write itself was recorded by _handle_assign
            pass
        # bare Name stores are locals/globals; globals recorded below

    def _handle_assign(
        self,
        targets: list[ast.expr],
        value: ast.expr | None,
        lineno: int,
    ) -> None:
        fn = self._fn
        for t in targets:
            pair = self._target_chain(t)
            if pair is not None:
                owner, chain = pair
                if owner == "self":
                    self._record_access(owner, chain, "write", lineno)
                    self._note_self_assign(t, value, chain)
                elif fn is not None:
                    self._record_access(owner, chain, "write", lineno)
            elif isinstance(t, ast.Name):
                if (
                    fn is not None
                    and t.id in self.scan.mutable_globals
                ):
                    self._record_access("", t.id, "write", lineno)
                self._note_local_bind(t.id, value)

    def _note_self_assign(
        self, target: ast.expr, value: ast.expr | None, chain: str
    ) -> None:
        """Track __init__ attrs, lock attrs, Condition aliases,
        threading.local attrs on the enclosing class."""
        cls = self._cls
        fn = self._fn
        if cls is None or fn is None or "." in chain:
            return
        attr = chain
        if fn.qual.endswith(".__init__") and fn.cls == cls.qual:
            cls.init_attrs.add(attr)
        if value is None:
            return
        if _is_threading_call(value, ("Lock", "RLock")):
            cls.lock_attrs.add(attr)
        elif _is_threading_call(value, ("Condition",)):
            cls.lock_attrs.add(attr)
            call = value
            assert isinstance(call, ast.Call)
            if call.args:
                inner = _dotted(call.args[0])
                if inner is not None and inner.startswith("self."):
                    cls.cond_aliases[attr] = inner.split(".", 1)[1]
        elif _is_threading_call(value, ("local",)):
            cls.local_attrs.add(attr)

    def _note_local_bind(self, name: str, value: ast.expr | None) -> None:
        """``x = ClassName(...)`` / ``x = self.attr`` local typing."""
        fn = self._fn
        if fn is None or value is None:
            return
        if isinstance(value, ast.Call):
            dotted = _dotted(value.func)
            if dotted is not None and dotted[:1].isupper():
                fn.local_ctors[name] = dotted  # same-module class name
            elif dotted is not None and dotted.endswith(
                "ThreadPoolExecutor"
            ):
                fn.local_ctors[name] = "<ThreadPoolExecutor>"
        else:
            dotted = _dotted(value)
            if dotted is not None and dotted.startswith("self.") \
                    and dotted.count(".") == 1:
                fn.local_self_aliases[name] = dotted.split(".", 1)[1]

    # -- names / attributes ----------------------------------------------

    def _visit_Name(self, node: ast.Name) -> None:
        if (
            isinstance(node.ctx, ast.Load)
            and node.id in self.scan.mutable_globals
            and self._fn is not None
        ):
            self._record_access("", node.id, "read", node.lineno)

    def _visit_Attribute(self, node: ast.Attribute) -> None:
        dotted = _dotted(node)
        if dotted is None:
            self._generic(node)
            return
        owner, _, chain = dotted.partition(".")
        fn = self._fn
        if chain and fn is not None:
            # record for ANY named owner: the rules resolve what the
            # guard map types (locals, closures, name_types hints) and
            # drop the rest — recording narrowly here would blind H1 to
            # hinted owners the scanner cannot type itself
            self._record_access(owner, chain, "read", node.lineno)
        # no recursion: the whole chain is consumed

    # -- calls ------------------------------------------------------------

    def _visit_Call(self, node: ast.Call) -> None:
        fn = self._fn
        f = node.func
        dotted = _dotted(f)
        if fn is not None:
            self._record_call(node, dotted)
            self._detect_spawn(node, dotted)
            self._detect_write(node, dotted)
        # receiver chains are accesses too (``self.X.append`` reads X —
        # recorded as a WRITE below when the method mutates); visit args
        if dotted is None:
            self._visit(f)
        elif fn is not None and "." in dotted:
            owner, _, chain = dotted.partition(".")
            prefix = chain.rsplit(".", 1)[0] if "." in chain else ""
            meth = chain.rsplit(".", 1)[-1]
            if owner == "self":
                if meth in MUTATOR_METHODS and prefix:
                    self._record_access("self", prefix, "write", node.lineno)
                elif prefix:
                    self._record_access("self", prefix, "read", node.lineno)
            elif prefix and (
                owner in fn.local_ctors or owner in fn.local_self_aliases
            ):
                kind = "write" if meth in MUTATOR_METHODS else "read"
                self._record_access(owner, prefix, kind, node.lineno)
        for a in node.args:
            self._visit(a)
        for kw in node.keywords:
            self._visit(kw.value)

    def _record_call(self, node: ast.Call, dotted: str | None) -> None:
        fn = self._fn
        assert fn is not None
        if dotted is None:
            return  # chained call like f(...)(...): unresolvable
        if "." not in dotted:
            fn.calls.append(Call(
                owner=None, name=dotted, held=tuple(self._held),
                lineno=node.lineno, func=fn.qual,
                cls=self._cls.qual if self._cls else None,
            ))
            return
        owner_path, name = dotted.rsplit(".", 1)
        if owner_path == "os" and name == "replace":
            fn.calls_os_replace = True
        fn.calls.append(Call(
            owner=owner_path, name=name, held=tuple(self._held),
            lineno=node.lineno, func=fn.qual,
            cls=self._cls.qual if self._cls else None,
        ))

    def _detect_spawn(self, node: ast.Call, dotted: str | None) -> None:
        fn = self._fn
        assert fn is not None
        if _is_threading_call(node, ("Thread",)):
            for kw in node.keywords:
                if kw.arg == "target":
                    target = _dotted(kw.value)
                    if target is not None:
                        fn.spawns.append(Spawn(
                            target=target, kind="thread",
                            lineno=node.lineno, func=fn.qual,
                        ))
            return
        if dotted is not None and "." in dotted:
            owner_path, name = dotted.rsplit(".", 1)
            if (
                name in ("map", "submit")
                and fn.local_ctors.get(owner_path) == "<ThreadPoolExecutor>"
                and node.args
            ):
                target = _dotted(node.args[0])
                if target is not None:
                    fn.spawns.append(Spawn(
                        target=target, kind="pool",
                        lineno=node.lineno, func=fn.qual,
                    ))

    def _detect_write(self, node: ast.Call, dotted: str | None) -> None:
        fn = self._fn
        assert fn is not None
        if dotted == "open" or (dotted or "").endswith(".open"):
            mode = None
            if len(node.args) >= 2 and isinstance(
                node.args[1], ast.Constant
            ):
                mode = node.args[1].value
            for kw in node.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                    mode = kw.value.value
            if isinstance(mode, str) and any(
                mode.startswith(m) for m in TRUNCATING_MODES
            ):
                fn.writes.append(FileWrite(
                    what="open-w", lineno=node.lineno, func=fn.qual,
                ))
            return
        if dotted is not None and "." in dotted:
            name = dotted.rsplit(".", 1)[1]
            if name in ("write_text", "write_bytes"):
                fn.writes.append(FileWrite(
                    what=name, lineno=node.lineno, func=fn.qual,
                ))


def scan_module(module: str, path: str) -> ModuleScan:
    """Parse and scan one source file into a :class:`ModuleScan`."""
    with open(path, encoding="utf-8") as f:
        src = f.read()
    tree = ast.parse(src, filename=path)
    return _Scanner(module, path, tree).scan
