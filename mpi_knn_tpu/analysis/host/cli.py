"""``mpi-knn lint --host`` — the host concurrency lint.

Exit status mirrors the HLO lint: 0 = clean (waivers allowed, counted),
1 = at least one finding (or a stale guard map / a lock-graph cycle),
2 = usage error. Jax-free and fast: the analyzer reads source text, it
never imports (let alone runs) the modules it checks.
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mpi-knn lint --host",
        description="statically lint the threaded host modules: lock "
        "discipline (H1), lock ordering (H2), thread confinement (H3), "
        "atomic publication (H4)",
    )
    p.add_argument("--rule", action="append", metavar="NAME",
                   help="run only the named rule(s), e.g. H2-lock-order; "
                   "repeatable")
    p.add_argument("--out", default="artifacts/lint", metavar="DIR",
                   help="report directory (default: artifacts/lint; the "
                   "report file is host_report.json)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule registry and exit")
    p.add_argument("-q", "--quiet", action="store_true")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)

    from mpi_knn_tpu.analysis.host.engine import run_host_lint
    from mpi_knn_tpu.analysis.host.rules import RULES

    if args.list_rules:
        for name, desc in RULES.items():
            print(f"{name}: {desc}")
        return 0

    try:
        report = run_host_lint(rule_names=args.rule)
    except KeyError as e:
        print(f"error: {e.args[0]}", file=sys.stderr)
        return 2
    path = report.save(args.out)

    if not args.quiet:
        s = report.to_json()["summary"]
        print(
            f"host lint: {s['targets']} target(s), "
            f"{s['classes_checked']} classes, {s['findings']} finding(s), "
            f"{s['waivers']} waiver(s), lock graph "
            f"{'acyclic' if s['lock_graph_acyclic'] else 'CYCLIC'} "
            f"({s['lock_edges']} edges); report: {path}"
        )
        for prob in report.problems:
            print(f"  CONFIG {prob}")
        for f in report.findings:
            print(
                f"  VIOLATION [{f.rule}] {f.where}:{f.lineno}: {f.message}"
            )
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
