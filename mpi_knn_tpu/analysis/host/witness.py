"""Runtime race witnesses — the dynamic twin of the static host lint,
armed in tests only.

The static rules (H1/H2) prove properties of the SOURCE; this module
observes the same properties at RUNTIME so every rule ships with a
counterexample that actually executes: a lock-order inversion the H2
graph flags statically is reproduced with two real threads and shows up
in :meth:`WitnessLog.inversions`, and an unguarded attribute access the
H1 map flags shows up in :meth:`WitnessLog.guard_violations`.

Mechanics: :class:`InstrumentedLock` wraps a real ``threading.Lock``
and records every acquisition with the witness-lock set the acquiring
thread already holds — the classic lock-order witness. Production
objects keep their own plain locks; tests swap an instance's lock attrs
for instrumented ones (:func:`instrument`) or build fixtures directly.
Nothing in this module is imported by production code paths.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
from collections.abc import Iterator
from types import TracebackType


@dataclasses.dataclass(frozen=True)
class AcquireEvent:
    lock: str
    held_before: tuple[str, ...]
    thread: str


@dataclasses.dataclass(frozen=True)
class AccessEvent:
    name: str
    kind: str  # "read" | "write"
    held: tuple[str, ...]
    thread: str


class WitnessLog:
    """Thread-safe record of lock acquisitions and guarded accesses."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._acquires: list[AcquireEvent] = []
        self._accesses: list[AccessEvent] = []
        self._held = threading.local()

    # -- bookkeeping (called by InstrumentedLock) -------------------------

    def _held_stack(self) -> list[str]:
        stack = getattr(self._held, "v", None)
        if stack is None:
            stack = self._held.v = []
        return stack

    def note_acquire(self, name: str) -> None:
        stack = self._held_stack()
        ev = AcquireEvent(
            lock=name,
            held_before=tuple(stack),
            thread=threading.current_thread().name,
        )
        with self._lock:
            self._acquires.append(ev)
        stack.append(name)

    def note_release(self, name: str) -> None:
        stack = self._held_stack()
        if name in stack:
            stack.reverse()
            stack.remove(name)
            stack.reverse()

    def note_access(self, name: str, kind: str = "read") -> None:
        """Record one access to a witness-guarded attribute with the
        instrumented locks currently held by this thread."""
        ev = AccessEvent(
            name=name,
            kind=kind,
            held=tuple(self._held_stack()),
            thread=threading.current_thread().name,
        )
        with self._lock:
            self._accesses.append(ev)

    # -- verdicts ---------------------------------------------------------

    @property
    def acquires(self) -> list[AcquireEvent]:
        with self._lock:
            return list(self._acquires)

    @property
    def accesses(self) -> list[AccessEvent]:
        with self._lock:
            return list(self._accesses)

    def ordered_pairs(self) -> set[tuple[str, str]]:
        """(outer, inner) pairs actually observed: inner acquired while
        outer was held."""
        pairs: set[tuple[str, str]] = set()
        for ev in self.acquires:
            for outer in ev.held_before:
                if outer != ev.lock:
                    pairs.add((outer, ev.lock))
        return pairs

    def inversions(self) -> set[tuple[str, str]]:
        """Lock pairs observed in BOTH orders — the runtime witness of
        an H2 lock-order cycle (a real interleaving of these two
        threads deadlocks)."""
        pairs = self.ordered_pairs()
        return {(a, b) for (a, b) in pairs if (b, a) in pairs and a < b}

    def guard_violations(self, guard_map: dict[str, str]) -> list[AccessEvent]:
        """Accesses that did not hold their declared lock — the runtime
        witness of an H1 guard breach. ``guard_map`` maps access name →
        required instrumented-lock name."""
        out = []
        for ev in self.accesses:
            lock = guard_map.get(ev.name)
            if lock is not None and lock not in ev.held:
                out.append(ev)
        return out


class InstrumentedLock:
    """A ``threading.Lock`` work-alike that reports every acquisition
    (with the holder's current witness-lock set) to a
    :class:`WitnessLog`. Drop-in for ``with obj._lock:`` call sites —
    supports the context manager protocol plus bare
    ``acquire``/``release``."""

    def __init__(self, name: str, log: WitnessLog,
                 lock: threading.Lock | None = None) -> None:
        self.name = name
        self.log = log
        self._inner = lock if lock is not None else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self.log.note_acquire(self.name)
        return got

    def release(self) -> None:
        self._inner.release()
        self.log.note_release(self.name)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "InstrumentedLock":
        self.acquire()
        return self

    def __exit__(
        self,
        exc_type: type[BaseException] | None,
        exc: BaseException | None,
        tb: TracebackType | None,
    ) -> None:
        self.release()


@contextlib.contextmanager
def instrument(
    obj: object, log: WitnessLog, *attrs: str, prefix: str = ""
) -> Iterator[WitnessLog]:
    """Temporarily replace ``obj``'s named lock attributes with
    instrumented wrappers around the SAME underlying locks, so
    production code paths driven by a test report their acquisition
    order into ``log`` — armed in tests only, restored on exit."""
    saved = {}
    for attr in attrs:
        inner = getattr(obj, attr)
        saved[attr] = inner
        name = f"{prefix}{type(obj).__name__}.{attr}"
        setattr(
            obj, attr,
            InstrumentedLock(name, log, lock=inner),
        )
    try:
        yield log
    finally:
        for attr, inner in saved.items():
            setattr(obj, attr, inner)
