"""Host-lint engine: scan the targets, run H1–H4, write the report.

``mpi-knn lint --host`` calls :func:`run_host_lint` over the production
six-target sweep; tests call the same function over fixture modules with
fixture guard maps, so every injected counterexample fires through the
exact production rule path (the repo's convention since R1). The report
(``artifacts/lint/host_report.json``) carries the findings, the full
lock-acquisition graph with its cycle census, the thread-root map, and
every waiver with its rationale — waivers are counted in the summary so
they cannot accrete silently.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
from dataclasses import field

from mpi_knn_tpu.analysis.host import rules as rules_mod
from mpi_knn_tpu.analysis.host.astscan import ModuleScan, scan_module
from mpi_knn_tpu.analysis.host.guards import (
    GuardMap,
    HostTarget,
    default_guards,
    default_targets,
)
from mpi_knn_tpu.analysis.host.rules import (
    RULES,
    HostFinding,
    LockGraph,
    Program,
)

SCHEMA_VERSION = 1


@dataclasses.dataclass
class HostReport:
    findings: list[HostFinding] = field(default_factory=list)
    waivers: list[dict] = field(default_factory=list)
    lock_graph: LockGraph = field(default_factory=LockGraph)
    targets: list[dict] = field(default_factory=list)
    roots: dict[str, list[str]] = field(default_factory=dict)
    problems: list[str] = field(default_factory=list)
    rules_run: list[str] = field(default_factory=list)
    classes_checked: int = 0

    @property
    def ok(self) -> bool:
        return (
            not self.findings
            and not self.problems
            and self.lock_graph.acyclic
        )

    def to_json(self) -> dict:
        return {
            "schema_version": SCHEMA_VERSION,
            "source": "mpi_knn_tpu.analysis.host",
            "ok": self.ok,
            "rules": {name: RULES[name] for name in self.rules_run},
            "summary": {
                "targets": len(self.targets),
                "classes_checked": self.classes_checked,
                "findings": len(self.findings),
                "waivers": len(self.waivers),
                "roots": len(self.roots),
                "lock_edges": len(self.lock_graph.edges),
                "lock_graph_acyclic": self.lock_graph.acyclic,
                "problems": len(self.problems),
            },
            "targets": self.targets,
            "roots": {k: sorted(v) for k, v in sorted(self.roots.items())},
            "lock_graph": self.lock_graph.to_json(),
            "waivers": self.waivers,
            "problems": self.problems,
            "findings": [f.to_json() for f in self.findings],
        }

    def save(self, out_dir: str | pathlib.Path) -> pathlib.Path:
        out = pathlib.Path(out_dir)
        out.mkdir(parents=True, exist_ok=True)
        path = out / "host_report.json"
        path.write_text(json.dumps(self.to_json(), indent=1) + "\n")
        return path


def run_host_lint(
    targets: list[HostTarget] | None = None,
    guards: GuardMap | None = None,
    rule_names: list[str] | None = None,
) -> HostReport:
    """Scan ``targets`` (default: the six production threaded-module
    targets) and run the host rules under ``guards`` (default: the
    production guard map). ``rule_names`` filters to a subset of
    H1/H2/H3/H4 (H1 and H3 share the attribute-discipline pass)."""
    targets = default_targets() if targets is None else targets
    guards = default_guards() if guards is None else guards
    wanted = set(RULES) if not rule_names else set(rule_names)
    unknown = wanted - set(RULES)
    if unknown:
        raise KeyError(f"unknown host rule(s): {sorted(unknown)}")

    scans: list[ModuleScan] = []
    module_target: dict[str, str] = {}
    for t in targets:
        for module, path in t.modules:
            scans.append(scan_module(module, path))
            module_target[module] = t.name
    prog = Program(scans, guards)
    target_modules = set(module_target)

    report = HostReport(rules_run=sorted(wanted))
    report.roots = {k: sorted(v) for k, v in prog.roots.items()}
    report.classes_checked = sum(
        1 for c, m in prog.class_module.items() if m in target_modules
    )

    findings: list[HostFinding] = []
    waivers: list[dict] = []
    if wanted & {"H1-lock-discipline", "H3-confinement"}:
        f, w = rules_mod.check_attr_discipline(prog, target_modules)
        findings.extend(
            x for x in f if x.rule in wanted
        )
        waivers.extend(w)
    if "H2-lock-order" in wanted:
        graph, f = prog.lock_graph()
        report.lock_graph = graph
        findings.extend(f)
    if "H4-atomic-publish" in wanted:
        f, w = rules_mod.check_atomic_publish(prog, target_modules)
        findings.extend(f)
        waivers.extend(w)

    report.findings = sorted(
        findings, key=lambda f: (f.rule, f.module, f.where, f.lineno)
    )
    report.waivers = sorted(waivers, key=lambda w: str(w["where"]))
    report.problems = list(prog.problems)

    by_target: dict[str, list[HostFinding]] = {t.name: [] for t in targets}
    for f in report.findings:
        by_target.setdefault(
            module_target.get(f.module, f.module), []
        ).append(f)
    report.targets = [
        {
            "name": t.name,
            "modules": [m for m, _ in t.modules],
            "ok": not by_target[t.name],
            "findings": len(by_target[t.name]),
        }
        for t in targets
    ]
    return report
