"""``mpi-knn mutate`` — operator CLI for live index mutation (ISSUE 14).

Two modes, one flag namespace:

- **offline** (``--index sift.ivf.npz``): load a saved clustered index,
  apply upserts / deletes / a compaction, and re-save (atomic-rename, so
  a serving process re-loading the path never sees a torn artifact)::

      mpi-knn mutate --index sift.ivf.npz --delete 17,42,99
      mpi-knn mutate --index sift.ivf.npz --upsert-rows new.npy \\
          --ids 1000000:1000128 --out sift.v2.npz
      mpi-knn mutate --index sift.ivf.npz --compact
      mpi-knn mutate --index sift.ivf.npz --stats        # read-only

- **online** (``--url http://host:port``): POST the same mutations to a
  running ``mpi-knn serve`` front end (tenant-attributed, 429-governed)::

      mpi-knn mutate --url http://127.0.0.1:8100 --tenant alice \\
          --upsert-rows new.npy --ids 1000000:1000128

Ids: ``--ids`` takes ``START:STOP`` (half-open) or a comma list; upsert
row payloads come from a ``.npy`` file (``--upsert-rows``) or
``--synthetic N`` (seeded standard-normal rows — smoke/bench use). Every
run prints one JSON line per action plus a final stats line; exit 0 on
success, 2 on usage errors (the repo's loud-refusal convention), 1 on a
server/overflow failure.
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mpi-knn mutate",
        description="live index mutation: upsert/delete/compact against "
        "a saved index artifact or a running mpi-knn serve front end",
    )
    tgt = p.add_mutually_exclusive_group(required=True)
    tgt.add_argument("--index", metavar="PATH.npz",
                     help="offline mode: a save_ivf_index artifact to "
                     "mutate and re-save")
    tgt.add_argument("--url", metavar="URL",
                     help="online mode: a running `mpi-knn serve` base "
                     "URL (POST /upsert, /delete)")
    p.add_argument("--tenant", default="default",
                   help="tenant attribution for online mutations "
                   "(X-Tenant header)")
    p.add_argument("--ids", default=None, metavar="SPEC",
                   help="ids as START:STOP (half-open) or a comma list — "
                   "the upsert ids, or the delete set with --delete")
    p.add_argument("--upsert-rows", default=None, metavar="FILE.npy",
                   help="(n, dim) f32 rows to upsert under --ids")
    p.add_argument("--synthetic", type=int, default=None, metavar="N",
                   help="upsert N seeded standard-normal rows instead of "
                   "--upsert-rows (smoke/bench)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--delete", default=None, metavar="SPEC",
                   help="ids to tombstone (START:STOP or comma list)")
    p.add_argument("--compact", action="store_true",
                   help="run a re-cluster/compact pass (offline mode)")
    p.add_argument("--no-retrain", action="store_true",
                   help="compact without retraining centroids")
    p.add_argument("--stats", action="store_true",
                   help="print the freelist occupancy stats "
                   "(live/tombstones/fill) and exit")
    p.add_argument("--out", default=None, metavar="PATH.npz",
                   help="offline mode: write the mutated index here "
                   "(default: overwrite --index atomically)")
    p.add_argument("--cache-dir", default=None, metavar="DIR",
                   help="persistent AOT executable cache for the "
                   "mutation programs (serve/aotcache.py)")
    p.add_argument("--platform", choices=["auto", "cpu", "tpu"],
                   default="auto")
    return p


def _parse_ids(spec: str) -> np.ndarray:
    if ":" in spec:
        lo, hi = spec.split(":", 1)
        return np.arange(int(lo), int(hi), dtype=np.int64)
    return np.asarray([int(v) for v in spec.split(",") if v],
                      dtype=np.int64)


def _emit(doc: dict) -> None:
    print(json.dumps(doc), flush=True)


def _usage(msg: str) -> int:
    print(f"error: {msg}", file=sys.stderr)
    return 2


def _post(url: str, path: str, doc: dict, tenant: str) -> tuple[int, dict]:
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        url.rstrip("/") + path,
        data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json", "X-Tenant": tenant},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read().decode())
        except Exception:  # noqa: BLE001 — non-JSON error body
            body = {"error": str(e)}
        return e.code, body


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    upsert_ids = rows = None
    if args.upsert_rows or args.synthetic:
        if args.ids is None:
            return _usage("--upsert-rows/--synthetic need --ids (the "
                          "global ids the rows land under)")
        upsert_ids = _parse_ids(args.ids)
        if args.upsert_rows:
            rows = np.load(args.upsert_rows)
        else:
            rows = None  # dim known only after the index/healthz loads
        if rows is not None and rows.shape[0] != len(upsert_ids):
            return _usage(
                f"{len(upsert_ids)} ids but {rows.shape[0]} rows"
            )
    elif args.ids and not args.delete:
        return _usage("--ids without --upsert-rows/--synthetic/--delete "
                      "names rows but no action")
    delete_ids = _parse_ids(args.delete) if args.delete else None
    if not any((upsert_ids is not None, delete_ids is not None,
                args.compact, args.stats)):
        return _usage("nothing to do: give --upsert-rows/--synthetic, "
                      "--delete, --compact, or --stats")

    if args.url:
        if args.compact or args.stats or args.out:
            return _usage("--compact/--stats/--out are offline-mode "
                          "(--index) actions; the server compacts itself "
                          "(background Compactor) and /healthz carries "
                          "the mutation posture")
        if rows is None and upsert_ids is not None:
            import urllib.request

            with urllib.request.urlopen(
                args.url.rstrip("/") + "/healthz", timeout=30
            ) as r:
                dim = json.loads(r.read().decode())["dim"]
            rng = np.random.default_rng(args.seed)
            rows = rng.standard_normal(
                (len(upsert_ids), dim)
            ).astype(np.float32)
        rc = 0
        if upsert_ids is not None:
            status, body = _post(
                args.url, "/upsert",
                {"ids": upsert_ids.tolist(), "rows": rows.tolist()},
                args.tenant,
            )
            _emit({"action": "upsert", "status": status, **body})
            rc = rc or (0 if status == 200 else 1)
        if delete_ids is not None:
            status, body = _post(
                args.url, "/delete", {"ids": delete_ids.tolist()},
                args.tenant,
            )
            _emit({"action": "delete", "status": status, **body})
            rc = rc or (0 if status == 200 else 1)
        return rc

    # offline mode: jax only loads here (the online path is jax-free)
    if args.platform != "auto":
        from mpi_knn_tpu.utils.platform import force_platform

        force_platform(args.platform)
    if args.cache_dir:
        from mpi_knn_tpu.serve import aotcache

        aotcache.set_cache_dir(args.cache_dir)
    from mpi_knn_tpu.ivf import load_ivf_index, save_ivf_index
    from mpi_knn_tpu.serve import mutate as serve_mutate

    index = load_ivf_index(args.index)
    if args.stats and upsert_ids is None and delete_ids is None \
            and not args.compact:
        _emit({"action": "stats", **serve_mutate.mutation_stats(index)})
        return 0
    if rows is None and upsert_ids is not None:
        rng = np.random.default_rng(args.seed)
        rows = rng.standard_normal(
            (len(upsert_ids), index.dim)
        ).astype(np.float32)
    try:
        if upsert_ids is not None:
            _emit({"action": "upsert",
                   **serve_mutate.upsert_rows(index, upsert_ids, rows)})
        if delete_ids is not None:
            _emit({"action": "delete",
                   **serve_mutate.delete_rows(index, delete_ids)})
        if args.compact:
            _emit({"action": "compact",
                   **serve_mutate.compact_index(
                       index, retrain=not args.no_retrain)})
    except serve_mutate.BucketOverflowError as e:
        _emit({"action": "error", "error": "headroom-exhausted",
               "detail": str(e)})
        return 1
    out = args.out or args.index
    save_ivf_index(index, out)
    _emit({"action": "saved", "path": out,
           **serve_mutate.mutation_stats(index)})
    return 0


if __name__ == "__main__":
    sys.exit(main())
