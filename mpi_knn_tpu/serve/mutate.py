"""Live index mutation through the serving stack: bucketed mutation
executables in the SAME AOT cache as serve, host orchestration
(freelist plan → donated dispatch → commit), and the background
re-cluster/compact worker (ISSUE 14).

The serving discipline, applied to writes:

- **Bucketed executables.** Upsert/delete chunks pad to
  ``mutation_bucket · 2^j`` rows, and each (bucket, config, kind) cell is
  compiled exactly once into the index's executable cache — and
  content-addressed into the persistent on-disk AOT cache
  (``serve.aotcache``, fingerprint extended with the mutation ``kind``),
  so a restarted process against a warm ``--cache-dir`` revives every
  mutation program with ZERO XLA compiles. Sustained churn at ragged
  sizes is compile-free the same way ragged query streams are
  (``jax.monitoring``-counted, ``watch_compiles``-tested).
- **Donation.** The resident store arrays are DONATED to every mutation
  executable and updated in place by scatter: a million-row index
  absorbs an upsert at the cost of the touched bucket rows, never a
  corpus-sized copy. Machine-checked, not promised: lint R5 reads the
  compiled program's ``input_output_alias`` + a copy census, R2-strict
  budgets the touched-chunk working set (``analysis/lowering.py``
  mutation cells).
- **One writer at a time, serialized with dispatch.** A per-index
  mutation lock (``engine.mutation_lock``) serializes every mutation
  (and the compact swap) with the engine's batch dispatch, so a query
  batch always runs against a consistent store: either wholly before or
  wholly after a mutation, never an in-between. The lock is held for
  the O(chunk) scatter dispatch only — mutation latency, not a stop-the-
  world.
- **Compaction in the background, shed first.** ``Compactor`` is a
  supervised daemon thread (heartbeats bracket every phase, spans flight-
  record it — a SIGKILL mid-compact leaves an open ``compact`` span as
  the diagnosis): it watches the freelist triggers
  (``compact_fill_threshold`` / ``compact_tombstone_fraction``) and runs
  the re-cluster rebuild — k-means retrained on a live-row sample OFF
  the lock, then one donated ``compact_scatter`` and an atomic store
  swap between batches. Under overload (the session is off its full
  ladder rung) compaction DEFERS — it is the first load shed, counted in
  ``compact_deferred_total``.

Layout support: the serial ``CorpusIndex`` tile stack (headroom rows,
flat freelist), the clustered ``IVFIndex`` (per-bucket freelists,
centroid-scored placement), and the mesh-sharded ``ShardedIVFIndex``
(the SAME donated scatters over the GSPMD-sharded store — S=1 is
bit-identical to unsharded). The ring and pallas dense layouts refuse
loudly: the ring's resident blocks are wire-representation shards and
the pallas kernel masks by row count, not ids — neither can honor a
tombstone.
"""

from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from mpi_knn_tpu.config import KNNConfig
from mpi_knn_tpu.ivf.mutate import (
    BucketOverflowError,
    assign_jit,
    compact_scatter_jit,
    delete_jit,
    freelist_of,
    make_dst_store,
    plan_compact,
    plan_delete,
    plan_upsert,
    should_compact,
    upsert_jit,
)
from mpi_knn_tpu.obs import metrics as obs_metrics
from mpi_knn_tpu.obs import spans as obs_spans
from mpi_knn_tpu.resilience.heartbeat import maybe_beat

__all__ = [
    "BucketOverflowError",
    "Compactor",
    "compact_index",
    "delete_rows",
    "mutation_stats",
    "supports_mutation",
    "upsert_rows",
]

MUTABLE_BACKENDS = ("serial", "ivf", "ivf-sharded")

# mutation program kinds — cache-key and AOT-fingerprint components
KIND_ASSIGN = "assign"
KIND_UPSERT = "upsert"
KIND_DELETE = "delete"
KIND_COMPACT = "compact"

# row-count buckets for the mutation chunk-size histogram (powers of two
# around the mutation_bucket grid — the frontend fill-histogram shape)
CHUNK_ROW_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048,
                     4096)


def supports_mutation(index) -> bool:
    return getattr(index, "backend", None) in MUTABLE_BACKENDS


def _require_mutable(index) -> None:
    if not supports_mutation(index):
        raise ValueError(
            f"the {getattr(index, 'backend', None)!r} layout cannot honor "
            "live mutation: the ring backends hold wire-representation "
            "corpus shards (a scatter would corrupt quantized blocks) and "
            "the pallas kernel masks by row count, not ids — serve "
            "mutable corpora from the serial, ivf, or ivf-sharded layouts"
        )


# ---------------------------------------------------------------------------
# Serial (dense tile stack) mutation programs — the CorpusIndex half of
# the tentpole; the clustered programs live in ivf/mutate.py


def serial_upsert_chunk(
    rows, new_ids, tpos, spos, clear_t, clear_s,
    tiles, tile_ids, tile_sqs,  # DONATED resident tile stack
    cfg: KNNConfig,
):
    """Donated in-place upsert into the serial tile stack: headroom rows
    (id −1 padding) absorb new rows at (tile, slot) positions the flat
    freelist allocated; updated ids clear their old slot first. The
    at-rest cast and the per-row norms are the build's own math
    (``ivf.mutate.store_rows_and_sqs``)."""
    from mpi_knn_tpu.ivf.mutate import store_rows_and_sqs

    at_rest, _, sqs = store_rows_and_sqs(rows, cfg, rows.shape[-1])
    tile_ids = tile_ids.at[clear_t, clear_s].set(-1, mode="drop")
    tile_ids = tile_ids.at[tpos, spos].set(new_ids, mode="drop")
    tiles = tiles.at[tpos, spos].set(at_rest, mode="drop")
    tile_sqs = tile_sqs.at[tpos, spos].set(
        sqs.astype(tile_sqs.dtype), mode="drop"
    )
    return tiles, tile_ids, tile_sqs


serial_upsert_jit = jax.jit(
    serial_upsert_chunk, static_argnames=("cfg",), donate_argnums=(6, 7, 8)
)
SERIAL_UPSERT_DONATED = (6, 7, 8)
# the serial delete is the clustered delete program over (tile, slot) —
# one tombstone scatter on the id plane, shared verbatim
serial_delete_jit = delete_jit


# ---------------------------------------------------------------------------
# The mutation executable cache (same per-index cache dict + persistent
# AOT cache as serve, keys extended with the mutation kind)


def _store_args(index) -> tuple:
    """The donated store arrays of a mutation program, in call order."""
    if index.backend == "serial":
        return (index.tiles, index.tile_ids, index.tile_sqs)
    return (index.buckets, index.bucket_ids, index.bucket_sqs,
            index.bucket_scales)


def _store_sds(index) -> tuple:
    """The store args as ShapeDtypeStructs (shape/dtype/sharding are
    metadata — readable even while a concurrent mutation donates the
    underlying buffers away), so lowering never races a donation: the
    compact pre-build runs OFF the mutation lock by design."""
    sds = jax.ShapeDtypeStruct
    return tuple(
        None if a is None
        else sds(a.shape, a.dtype, sharding=a.sharding)
        for a in _store_args(index)
    )


def _replicated(index):
    from jax.sharding import NamedSharding, PartitionSpec

    if getattr(index, "mesh", None) is None:
        return None
    return NamedSharding(index.mesh, PartitionSpec())


def _chunk_sds(index, shapes_dtypes):
    """ShapeDtypeStructs for the chunk-side args — replicated on the
    mesh for a sharded index (the store is GSPMD-sharded; the chunk and
    its scatter indices are small and go everywhere)."""
    sds = jax.ShapeDtypeStruct
    rep = _replicated(index)
    if rep is None:
        return [sds(s, d) for s, d in shapes_dtypes]
    return [sds(s, d, sharding=rep) for s, d in shapes_dtypes]


def _mutation_chunk_specs(index, cfg: KNNConfig, bucket: int, kind: str):
    """(shape, dtype) of the chunk-side args per kind — pure shape math,
    shared by the lowering, the dispatch path, and the persistent-cache
    signature check (the ``engine.expected_args`` convention)."""
    i32 = jnp.int32
    if kind == KIND_ASSIGN:
        return [((bucket, index.dim), jnp.float32)]
    if kind == KIND_UPSERT:
        return [
            ((bucket, index.dim), jnp.float32),
            ((bucket,), i32),
            ((bucket,), i32), ((bucket,), i32),
            ((bucket,), i32), ((bucket,), i32),
        ]
    if kind == KIND_DELETE:
        return [((bucket,), i32), ((bucket,), i32)]
    if kind == KIND_COMPACT:
        # "bucket" for a compact cell is the NEW bucket_cap; the chunk
        # args are the per-old-flat-slot destination vectors
        n = index.buckets.shape[0] * index.bucket_cap
        return [((n,), i32), ((n,), i32)]
    raise ValueError(f"unknown mutation kind {kind!r}")


def expected_mutation_args(index, cfg: KNNConfig, bucket: int,
                           kind: str) -> list:
    """Flattened (shape, dtype) input signature of one mutation cell —
    what a persistent-cache hit's ``args_info`` must match."""
    args = [
        (tuple(int(x) for x in s), str(jnp.dtype(d)))
        for s, d in _mutation_chunk_specs(index, cfg, bucket, kind)
    ]
    if kind == KIND_ASSIGN:
        resident = (index.centroids, index.centroid_sqs)
    elif kind == KIND_COMPACT:
        resident = _store_args(index) + _compact_dst_shapes(index, bucket)
    elif kind == KIND_DELETE:
        # the tombstone program touches only the id plane
        resident = (
            index.tile_ids if index.backend == "serial"
            else index.bucket_ids,
        )
    else:
        resident = _store_args(index)
    for a in resident:
        if a is None:
            continue
        if isinstance(a, tuple):
            args.append(a)
        else:
            args.append(
                (tuple(int(s) for s in a.shape), str(a.dtype))
            )
    return args


def _compact_dst_shapes(index, new_cap: int) -> tuple:
    P = index.buckets.shape[0]
    out = [
        ((P, new_cap, int(index.buckets.shape[-1])),
         str(index.buckets.dtype)),
        ((P, new_cap), "int32"),
        ((P, new_cap), str(index.bucket_sqs.dtype)),
    ]
    if index.bucket_scales is not None:
        out.append(((P, new_cap), "float32"))
    return tuple(out)


def lower_mutation(index, cfg: KNNConfig, bucket: int, kind: str):
    """The one (bucket, config, kind) mutation program as a
    ``jax.stages.Lowered`` — the exact object the cache compiles, exposed
    so the lint engine lowers production mutation programs
    (``analysis/lowering.py``), like ``engine.lower_bucket`` for serve."""
    _require_mutable(index)
    chunk = _chunk_sds(index, _mutation_chunk_specs(index, cfg, bucket, kind))
    store = _store_sds(index)
    if kind == KIND_ASSIGN:
        if index.backend == "serial":
            raise ValueError("the serial layout has no centroid assignment")
        sds = jax.ShapeDtypeStruct
        return assign_jit.lower(
            chunk[0],
            sds(index.centroids.shape, index.centroids.dtype,
                sharding=index.centroids.sharding),
            sds(index.centroid_sqs.shape, index.centroid_sqs.dtype,
                sharding=index.centroid_sqs.sharding),
        )
    if kind == KIND_UPSERT:
        if index.backend == "serial":
            return serial_upsert_jit.lower(*chunk, *store, cfg=index.cfg)
        return upsert_jit.lower(*chunk, *store, cfg=index.cfg)
    if kind == KIND_DELETE:
        ids_plane = store[1]  # the id plane (tile_ids / bucket_ids)
        return delete_jit.lower(*chunk, ids_plane)
    if kind == KIND_COMPACT:
        if index.backend == "serial":
            raise ValueError("the serial layout compacts by rebuild only")
        sds = jax.ShapeDtypeStruct
        bsh = _bucket_sharding(index)
        dst = [
            sds(s, jnp.dtype(d)) if bsh is None
            else sds(s, jnp.dtype(d), sharding=bsh)
            for s, d in _compact_dst_shapes(index, bucket)
        ]
        if len(dst) == 3:  # unquantized: dst_scales is the empty pytree
            dst.append(None)
        return compact_scatter_jit.lower(*chunk, *store, *dst)
    raise ValueError(f"unknown mutation kind {kind!r}")


def _bucket_sharding(index):
    from jax.sharding import NamedSharding, PartitionSpec

    if getattr(index, "mesh", None) is None:
        return None
    return NamedSharding(index.mesh, PartitionSpec(index.axis))


def get_mutation_executable(index, cfg: KNNConfig, bucket: int, kind: str):
    """The compiled (bucket, config, kind) mutation cell, built at most
    once per index — revived from the persistent AOT cache when active
    (fingerprint = the serve fingerprint + the mutation kind), compiled
    otherwise. Same per-key locking as the serve cache; the key tuples
    carry the kind so serve and mutation cells share one dict without
    collision."""
    from mpi_knn_tpu.serve import aotcache
    from mpi_knn_tpu.serve.engine import _fingerprint_cfg, _key_lock

    key = (bucket, _fingerprint_cfg(cfg), kind)
    exec_ = index._cache.get(key)
    if exec_ is not None:
        return exec_
    with _key_lock(index, key):
        exec_ = index._cache.get(key)
        if exec_ is not None:
            return exec_
        obs_metrics.install_jax_compile_listener()
        disk = aotcache.active_cache()
        cache_mode = "off"
        reg = obs_metrics.get_registry()
        sid = obs_spans.begin_span(
            "compile", cat="compile", bucket=bucket, backend=index.backend,
            kind=kind,
        )
        try:
            compiled = None
            fp = None
            if disk is not None:
                fp = aotcache.fingerprint(index, cfg, bucket, kind=kind)
                compiled = disk.load(
                    fp,
                    expect_args=expected_mutation_args(
                        index, cfg, bucket, kind
                    ),
                )
                cache_mode = "hit" if compiled is not None else "miss"
            if compiled is None:
                lowered = lower_mutation(index, cfg, bucket, kind)
                compiled = lowered.compile()
                if disk is not None:
                    disk.store(
                        fp, compiled,
                        meta={**aotcache.fingerprint_facts(
                            index, cfg, bucket), "kind": kind},
                    )
        except Exception as e:
            obs_spans.end_span(sid, error=type(e).__name__)
            raise
        obs_spans.end_span(sid, cache=cache_mode)
        reg.counter(
            "mutation_executables_loaded_total"
            if cache_mode == "hit" else "mutation_executables_compiled_total",
            help="mutation (bucket, config, kind) cells revived from the "
            "persistent AOT cache" if cache_mode == "hit"
            else "mutation (bucket, config, kind) cells compiled",
        ).inc()
        index._cache[key] = compiled
    return compiled


def warm_mutation(index, cfg: KNNConfig | None = None,
                  sizes=(None,)) -> dict:
    """Pre-build the mutation cells for the given chunk sizes (None =
    one ``mutation_bucket``) — the serve ``warm()`` discipline for the
    write path, so the first live upsert never compiles into traffic."""
    from mpi_knn_tpu.serve.engine import bucket_rows

    cfg = cfg or index.cfg
    built = 0
    for n in sizes:
        bucket = bucket_rows(
            n if n is not None else cfg.mutation_bucket, cfg.mutation_bucket
        )
        kinds = [KIND_UPSERT, KIND_DELETE]
        if index.backend != "serial":
            kinds.append(KIND_ASSIGN)
        for kind in kinds:
            get_mutation_executable(index, cfg, bucket, kind)
            built += 1
    if index.backend != "serial":
        # the compact path too: the cap-preserving scatter cell (its
        # "bucket" is bucket_cap) plus one tracing call of the
        # assignment pass, so the first trigger-fired compaction
        # compiles nothing while queries wait on the mutation lock
        get_mutation_executable(
            index, cfg, index.bucket_cap, KIND_COMPACT
        )
        from mpi_knn_tpu.ivf.mutate import compact_assign_jit
        from mpi_knn_tpu.serve.engine import mutation_lock

        with mutation_lock(index):  # the eager trace reads the store —
            # never race a concurrent donation
            compact_assign_jit(
                index.buckets, index.bucket_scales, index.centroids,
                index.centroid_sqs, cfg=index.cfg,
            ).block_until_ready()
        built += 2
    return {"cells": built}


# ---------------------------------------------------------------------------
# Orchestration: plan → dispatch (donated) → swap → commit


def _center_rows(index, rows: np.ndarray) -> np.ndarray:
    """The build's centering, applied to an upsert chunk: rows enter the
    store in the index's centered frame (the frozen build-time mean —
    L2 is translation-invariant, so a drifting mean costs conditioning,
    not correctness; compaction keeps the frame for the same reason)."""
    rows = np.asarray(rows)
    if rows.ndim != 2 or rows.shape[1] != index.dim:
        raise ValueError(
            f"upsert rows must be (n, dim={index.dim}), got {rows.shape}"
        )
    if index.mu is not None:
        rows = rows - np.asarray(index.mu)
    return np.ascontiguousarray(rows, dtype=np.float32)


def _dedupe_last(ids: np.ndarray, rows: np.ndarray | None):
    """Last occurrence wins within one chunk (duplicate scatter indices
    apply in unspecified order — refuse to race)."""
    _, last = np.unique(ids[::-1], return_index=True)
    keep = np.sort(len(ids) - 1 - last)
    if len(keep) == len(ids):
        return ids, rows
    return ids[keep], (rows[keep] if rows is not None else None)


def _pad_chunk(arr: np.ndarray, bucket: int, fill) -> np.ndarray:
    n = arr.shape[0]
    if n == bucket:
        return arr
    pad = np.full((bucket - n,) + arr.shape[1:], fill, dtype=arr.dtype)
    return np.concatenate([arr, pad], axis=0)


def _put_chunk(index, *arrays):
    rep = _replicated(index)
    if rep is None:
        return arrays
    return tuple(jax.device_put(a, rep) for a in arrays)


def _swap_store(index, buckets, bucket_ids, bucket_sqs, bucket_scales):
    index.buckets = buckets
    index.bucket_ids = bucket_ids
    index.bucket_sqs = bucket_sqs
    if bucket_scales is not None:
        index.bucket_scales = bucket_scales


def mutation_stats(index) -> dict:
    """The freelist's occupancy snapshot (live/tombstones/fill) — what
    the gauges, ``/healthz`` and the doctor verdict report."""
    _require_mutable(index)
    return freelist_of(index).stats()


def _stamp_gauges(index, reg) -> None:
    fl = freelist_of(index)
    reg.gauge(
        "index_live_rows", help="live (non-tombstoned) rows in the index"
    ).set(fl.live)
    reg.gauge(
        "index_tombstone_fraction",
        help="tombstoned slots as a fraction of live rows (a compaction "
        "trigger)",
    ).set(fl.tombstone_fraction)
    reg.gauge(
        "index_max_bucket_fill",
        help="largest bucket fill fraction (headroom exhaustion — a "
        "compaction trigger)",
    ).set(fl.max_fill)


def upsert_rows(index, ids, rows, config: KNNConfig | None = None) -> dict:
    """Upsert ``rows`` under global ``ids`` into a resident index —
    static shapes end to end: chunk padded to the mutation bucket,
    placement scored on device (clustered layouts), slots from the
    freelist, ONE donated scatter, store swapped in place. Existing ids
    are updated (old slot tombstoned when the row moves partitions).
    Returns a stats dict; raises :class:`BucketOverflowError` when
    headroom is exhausted (the freelist and store are untouched — compact
    and retry)."""
    from mpi_knn_tpu.serve.engine import bucket_rows, mutation_lock

    _require_mutable(index)
    ids = np.asarray(ids, dtype=np.int32).reshape(-1)
    if (ids < 0).any():
        raise ValueError("upsert ids must be >= 0 (id -1 is the padding/"
                         "tombstone sentinel)")
    rows = _center_rows(index, rows)
    if rows.shape[0] != ids.shape[0]:
        raise ValueError(
            f"{ids.shape[0]} ids but {rows.shape[0]} rows"
        )
    ids, rows = _dedupe_last(ids, rows)
    n = int(ids.shape[0])
    cfg = config or index.cfg
    bucket = bucket_rows(n, cfg.mutation_bucket)
    reg = obs_metrics.get_registry()
    t0 = time.perf_counter()
    with obs_spans.span("upsert", cat="mutate", rows=n, bucket=bucket,
                        backend=index.backend):
        with mutation_lock(index):
            fl = freelist_of(index)
            rows_p = _pad_chunk(rows, bucket, 0.0)
            if index.backend == "serial":
                # dense layout: no clustering — the freelist's buckets
                # are the corpus tiles, any free slot will do (lowest
                # tile first, deterministic); ids already live update
                # their own tile IN PLACE and consume no slot, so a
                # zero-headroom index still absorbs pure updates
                parts = _serial_pick_tiles(fl, ids)
            else:
                ex = get_mutation_executable(index, cfg, bucket, KIND_ASSIGN)
                (rows_d,) = _put_chunk(index, rows_p)
                parts = np.asarray(jax.device_get(
                    ex(rows_d, index.centroids, index.centroid_sqs)
                ))[:n]
            part, slot, clear_p, clear_s, commit = plan_upsert(
                fl, ids, parts
            )
            sentinel = fl.total if index.backend != "serial" else (
                index.tiles.shape[0]
            )
            args = _put_chunk(
                index,
                rows_p,
                _pad_chunk(ids, bucket, -1),
                _pad_chunk(part, bucket, sentinel),
                _pad_chunk(slot, bucket, 0),
                _pad_chunk(clear_p, bucket, sentinel),
                _pad_chunk(clear_s, bucket, 0),
            )
            ex = get_mutation_executable(index, cfg, bucket, KIND_UPSERT)
            if index.backend == "serial":
                tiles, tile_ids, tile_sqs = ex(
                    *args, index.tiles, index.tile_ids, index.tile_sqs
                )
                index.tiles, index.tile_ids, index.tile_sqs = (
                    tiles, tile_ids, tile_sqs
                )
            else:
                out = ex(*args, *_store_args(index))
                _swap_store(index, *_normalize_store_out(index, out))
            commit()
        _stamp_gauges(index, reg)
    reg.counter(
        "mutation_upserts_total", help="rows upserted into live indices"
    ).inc(n)
    reg.histogram(
        "mutation_chunk_rows",
        help="rows per mutation chunk (upsert+delete)",
        buckets=CHUNK_ROW_BUCKETS,
    ).observe(n)
    reg.histogram(
        "mutation_latency_seconds",
        help="wall time of one mutation call (plan + donated dispatch + "
        "commit)",
    ).observe(time.perf_counter() - t0)
    return {"upserted": n, "bucket": bucket, **freelist_of(index).stats()}


def _normalize_store_out(index, out):
    """jax drops empty pytree nodes: an unquantized store's 4-tuple
    comes back as (buckets, ids, sqs, None)."""
    if len(out) == 4:
        return out
    return (*out, None)


def _serial_pick_tiles(fl, ids: np.ndarray) -> np.ndarray:
    """Tile choice for dense upserts: an id that is already LIVE keeps
    its own tile (``plan_upsert`` then updates the slot in place,
    consuming nothing — a zero-headroom index absorbs pure updates);
    new ids fill the lowest tile with headroom first (deterministic).
    Raises the shared overflow error when the new rows outnumber the
    free slots — the serial layout has no compactor; rebuild with more
    ``bucket_headroom``."""
    parts = np.empty(len(ids), np.int32)
    new_rows = []
    for i, rid in enumerate(ids):
        old = fl.pos.get(int(rid))
        if old is not None:
            parts[i] = old[0]
        else:
            new_rows.append(i)
    avail = [(p, len(f)) for p, f in enumerate(fl.free)]
    j = 0
    for p, cnt in avail:
        take = min(cnt, len(new_rows) - j)
        for i in new_rows[j:j + take]:
            parts[i] = p
        j += take
        if j == len(new_rows):
            break
    if j < len(new_rows):
        raise BucketOverflowError(
            f"serial tile stack is full ({fl.live} live rows, "
            f"{len(new_rows) - j} new rows do not fit): rebuild the "
            "index with a larger bucket_headroom (the dense layout has "
            "no re-cluster pass)",
        )
    return parts


def delete_rows(index, ids, config: KNNConfig | None = None) -> dict:
    """Tombstone ``ids``: one donated scatter sets their slots' ids to −1
    (``mask_tile`` guarantees they are never again returned), the
    freelist reclaims the slots for future upserts. Unknown ids are
    counted and skipped (idempotent). Returns a stats dict."""
    from mpi_knn_tpu.serve.engine import bucket_rows, mutation_lock

    _require_mutable(index)
    ids = np.asarray(ids, dtype=np.int32).reshape(-1)
    ids, _ = _dedupe_last(ids, None)
    n = int(ids.shape[0])
    cfg = config or index.cfg
    bucket = bucket_rows(max(1, n), cfg.mutation_bucket)
    reg = obs_metrics.get_registry()
    t0 = time.perf_counter()
    with obs_spans.span("delete", cat="mutate", rows=n, bucket=bucket,
                        backend=index.backend):
        with mutation_lock(index):
            fl = freelist_of(index)
            part, slot, commit, missing = plan_delete(fl, ids)
            sentinel = fl.total
            args = _put_chunk(
                index,
                _pad_chunk(part, bucket, sentinel),
                _pad_chunk(slot, bucket, 0),
            )
            ex = get_mutation_executable(index, cfg, bucket, KIND_DELETE)
            if index.backend == "serial":
                index.tile_ids = ex(*args, index.tile_ids)
            else:
                index.bucket_ids = ex(*args, index.bucket_ids)
            commit()
        _stamp_gauges(index, reg)
    deleted = n - missing
    reg.counter(
        "mutation_deletes_total", help="rows tombstoned in live indices"
    ).inc(deleted)
    reg.histogram(
        "mutation_chunk_rows",
        help="rows per mutation chunk (upsert+delete)",
        buckets=CHUNK_ROW_BUCKETS,
    ).observe(n)
    reg.histogram(
        "mutation_latency_seconds",
        help="wall time of one mutation call (plan + donated dispatch + "
        "commit)",
    ).observe(time.perf_counter() - t0)
    return {
        "deleted": deleted, "missing": missing, "bucket": bucket,
        **freelist_of(index).stats(),
    }


# ---------------------------------------------------------------------------
# Compaction


def compact_index(index, config: KNNConfig | None = None,
                  retrain: bool = True, reason: str = "manual",
                  min_cap: int | None = None) -> dict:
    """Re-cluster/compact a clustered index in place: k-means retrained
    on a deterministic live-row sample (OFF the mutation lock — training
    blocks nothing), every slot re-assigned on device, and the store
    rebuilt by ONE donated scatter, then swapped atomically under the
    mutation lock (between batches — the dispatch path holds the same
    lock). ``bucket_cap`` is preserved whenever the live set fits, so
    every compiled serve/mutation cell stays valid; a forced cap growth
    clears the in-memory cell cache (the documented recompile path).
    Returns the compaction stats."""
    from mpi_knn_tpu.serve.engine import mutation_lock

    _require_mutable(index)
    if index.backend == "serial":
        raise ValueError(
            "the serial tile stack has no re-cluster pass (tombstoned "
            "slots are reclaimed in place by upserts); rebuild the index "
            "to re-derive headroom"
        )
    cfg = config or index.cfg
    reg = obs_metrics.get_registry()
    t0 = time.perf_counter()
    with obs_spans.span("compact", cat="mutate", backend=index.backend,
                        reason=reason, retrain=retrain):
        maybe_beat("compact-plan")
        # Phase 1, OFF the mutation lock where possible: the sample
        # gather must hold it (resident arrays are donated away by
        # concurrent mutations — an unlocked read could touch a deleted
        # buffer), but it is one ≤16k-row device gather; the k-means
        # retrain then runs on the host-copied SNAPSHOT with queries
        # flowing freely. Mutations landing between sample and scatter
        # are fine: the assignment below re-reads the store under the
        # lock, and sample-fit centroids are approximate by design.
        if retrain:
            with mutation_lock(index):
                from mpi_knn_tpu.ivf.mutate import gather_live_sample

                sample = gather_live_sample(index)
            from mpi_knn_tpu.ivf.mutate import retrain_centroids

            centroids, centroid_sqs = retrain_centroids(index, cfg, sample)
        else:
            centroids, centroid_sqs = index.centroids, index.centroid_sqs
        # the common (cap-preserving) compact executable is fetched —
        # possibly compiled — BEFORE the lock: a cold compile inside it
        # would stall every query dispatch for the XLA wall time
        get_mutation_executable(
            index, cfg, index.bucket_cap, KIND_COMPACT
        )
        # Phase 2, under the lock: assignment against the FINAL store,
        # layout, one donated scatter, atomic swap — all O(store) device
        # work at memory speed, no training, no compiles on the common
        # path (cap growth compiles in-lock: rare, documented)
        with mutation_lock(index):
            dst_part, dst_slot, new_cap, stats = plan_compact(
                index, cfg, centroids, centroid_sqs, min_cap=min_cap
            )
            stats["retrained"] = bool(retrain)
            maybe_beat("compact-scatter")
            bucket = new_cap
            dst = make_dst_store(
                index, new_cap, sharding=_bucket_sharding(index)
            )
            if new_cap == index.bucket_cap:
                ex = get_mutation_executable(
                    index, cfg, bucket, KIND_COMPACT
                )
                out = ex(
                    *_put_chunk(index, dst_part, dst_slot),
                    *_store_args(index), *dst,
                )
            else:
                # cap growth: a fresh shape — compile-and-go (rare, the
                # documented path; the in-memory cells of the OLD shape
                # are dropped below)
                out = compact_scatter_jit(
                    *_put_chunk(index, dst_part, dst_slot),
                    *_store_args(index), *dst,
                )
            new_store = _normalize_store_out(index, out)
            _swap_store(index, *new_store)
            index.centroids = centroids
            index.centroid_sqs = centroid_sqs
            cap_changed = new_cap != index.bucket_cap
            index.bucket_cap = new_cap
            if cap_changed:
                index._cache.clear()
                index.__dict__.pop("_cache_key_locks", None)
            index.__dict__.pop("_freelist", None)  # re-derive from store
            maybe_beat("compact-swap")
        _stamp_gauges(index, reg)
    wall = time.perf_counter() - t0
    reg.counter(
        "compactions_total", help="background/manual compaction passes run"
    ).inc()
    reg.histogram(
        "compact_wall_seconds", help="wall time of one compaction pass"
    ).observe(wall)
    return {**stats, "reason": reason, "wall_s": round(wall, 4)}


class Compactor:
    """The background re-cluster/compact worker: a supervised daemon
    thread watching the freelist triggers, heartbeat- and flight-
    recorded, shed FIRST under overload (a session off its full ladder
    rung defers compaction — queries keep the device).

    ``session`` is a :class:`~mpi_knn_tpu.serve.engine.ServeSession`
    (the compactor reads its rung and index); ``interval_s`` is the
    trigger poll period. ``stop()`` joins the thread."""

    def __init__(self, session, interval_s: float = 0.25,
                 retrain: bool = True):
        _require_mutable(session.index)
        if session.index.backend == "serial":
            raise ValueError(
                "the serial layout has no compaction pass — the "
                "compactor supervises clustered indices only"
            )
        self.session = session
        self.interval_s = interval_s
        self.retrain = retrain
        self._stop_evt = threading.Event()
        self._lock = threading.Lock()
        self._history: list[dict] = []  # compaction stats, in order
        self._deferred = 0
        self._thread = threading.Thread(
            target=self._run, name="tknn-compact", daemon=True
        )

    def start(self) -> "Compactor":
        self._thread.start()
        return self

    def stop(self, timeout: float = 60.0) -> None:
        self._stop_evt.set()
        self._thread.join(timeout)

    def snapshot(self) -> dict:
        """{compactions, deferred, last} — consistent copy for other
        threads (/healthz, tests)."""
        with self._lock:
            return {
                "compactions": len(self._history),
                "deferred": self._deferred,
                "last": self._history[-1] if self._history else None,
            }

    def tick(self, force_reason: str | None = None) -> dict | None:
        """One trigger check + (maybe) one compaction — the loop body,
        exposed so tests drive it deterministically. Returns the
        compaction stats when one ran, else None."""
        ses = self.session
        reason = force_reason or should_compact(ses.index, ses.cfg)
        if reason is None:
            return None
        from mpi_knn_tpu.resilience.ladder import FULL_RUNG

        if ses.rung != FULL_RUNG:
            # compaction is the FIRST thing shed under overload: a
            # degraded session is already fighting for the device —
            # deferring costs headroom, not correctness
            with self._lock:
                self._deferred += 1
            obs_metrics.get_registry().counter(
                "compact_deferred_total",
                help="compaction ticks deferred because the session was "
                "shedding load (compaction is shed first)",
            ).inc()
            obs_spans.event("compact-deferred", cat="mutate", reason=reason,
                            rung=ses.rung)
            return None
        stats = compact_index(
            ses.index, ses.cfg, retrain=self.retrain, reason=reason
        )
        with self._lock:
            self._history.append(stats)
        return stats

    def _run(self) -> None:
        maybe_beat("compactor-start")
        while not self._stop_evt.wait(self.interval_s):
            maybe_beat("compactor-tick")
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — log, keep supervising
                obs_spans.event(
                    "compact-error", cat="mutate",
                    error=f"{type(e).__name__}: {e}",
                )
                obs_metrics.get_registry().counter(
                    "compact_errors_total",
                    help="compaction passes that raised (the compactor "
                    "keeps running; the store is untouched on failure)",
                ).inc()
