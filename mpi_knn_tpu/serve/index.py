"""Device-resident corpus index — the amortized half of the serving loop.

The one-shot ``all_knn`` API re-uploads the corpus, re-derives its tiling,
re-computes its squared norms and re-traces the backend on every call —
fine for a batch job, fatal for the reference's actual workload ("classify
a stream of query points against a resident training corpus",
``knn-serial.c``). ``CorpusIndex`` does all corpus-side work exactly once:

- tiles + global ids + squared norms live on device, MXU-aligned, never
  bounced through the host again (the ``test_device_resident.py``
  contract, extended from "device inputs are not copied" to "the corpus
  is not even re-inspected");
- for the ring backends the padded corpus and its ids are ``device_put``
  sharded over the ring axis ONCE — every subsequent batch pays only its
  own query H2D;
- the centering mean is computed once and applied to each query batch, so
  serving results are bit-identical to a fresh ``all_knn`` call (which
  derives the same mean from the same corpus);
- bf16 compression is ``dtype="bfloat16"`` at build time: the resident
  tiles are stored (and computed) at half width, halving HBM residency —
  the same measured-recall contract as everywhere else in the framework.

The executable cache for the query side lives in ``serve.engine`` and is
keyed per (row bucket, config); the index carries it so two indices can
never collide on a cache entry.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mpi_knn_tpu.config import KNNConfig
from mpi_knn_tpu.ops.distance import sq_norms
from mpi_knn_tpu.parallel.partition import (
    make_global_ids,
    pad_rows_any,
    pad_to_multiple,
)


@dataclasses.dataclass
class CorpusIndex:
    """Resident corpus state for one (corpus, config[, mesh]) triple.

    ``backend`` is resolved (never "auto"); exactly one of the two storage
    layouts is populated: the tile stack (serial/pallas) or the sharded
    padded corpus (ring/ring-overlap).
    """

    cfg: KNNConfig  # resolved backend; the serving default config
    backend: str
    m: int
    dim: int
    c_tile: int
    mu: object | None  # centering mean (host f64 or device), or None
    # serial/pallas layout
    tiles: jax.Array | None = None  # (T, c_tile, d)
    tile_ids: jax.Array | None = None  # (T, c_tile)
    tile_sqs: jax.Array | None = None  # (T, c_tile)
    corpus_padded: jax.Array | None = None  # (c_pad, d) — pallas layout
    # ring layout
    mesh: Mesh | None = None
    ring_meta: tuple | None = None  # (q_axis, axis, dp, ring_n)
    corpus_sharded: jax.Array | None = None  # (c_pad, d) over P(axis) —
    # int8 CODES when cfg.ring_transfer_dtype == "int8" (the resident
    # corpus IS the wire representation: quantized once at build, so
    # serving batches pay zero re-quantization and resident HBM shrinks
    # with the wire bytes)
    corpus_ids_sharded: jax.Array | None = None
    corpus_scales_sharded: jax.Array | None = None  # (c_pad,) f32, int8 only
    # per-index executable cache: {(bucket, cfg) -> engine._BucketExec}
    _cache: dict = dataclasses.field(default_factory=dict)

    @property
    def nbytes_resident(self) -> int:
        """Bytes of resident corpus payload (tiles or sharded corpus)."""
        arr = self.tiles if self.tiles is not None else (
            self.corpus_padded
            if self.corpus_padded is not None
            else self.corpus_sharded
        )
        return 0 if arr is None else arr.size * arr.dtype.itemsize

    @property
    def live_rows(self) -> int:
        """Rows currently live (non-tombstoned) in a mutable (serial)
        layout — from the mutation freelist; ``m`` stays the build-time
        count (executable-fingerprint material)."""
        from mpi_knn_tpu.ivf.mutate import freelist_of

        if self.tiles is None:
            raise ValueError(
                f"the {self.backend!r} layout does not track liveness "
                "(only the serial tile stack is mutable)"
            )
        return freelist_of(self).live

    def compatible_cfg(self, cfg: KNNConfig) -> KNNConfig:
        """Validate a per-query config against the build-time layout.

        Query-side knobs (k, topk method/block, merge schedule, precision
        policy, bucket/depth/donate, recall target, tie break) may vary per
        call — the executable cache keys on the full config, so each
        variant compiles its own executable. Corpus-side knobs are baked
        into the resident layout and may NOT vary; accepting them silently
        would serve answers from an index built under different math.
        """
        frozen = (
            "backend", "metric", "dtype", "corpus_tile", "query_tile",
            "center", "mesh_axis", "num_devices", "ring_transfer_dtype",
            "ring_schedule", "max_tile_elems", "pallas_variant",
            "exclude_zero", "zero_eps",
        )
        built = self.cfg.replace(backend=self.backend)
        want = cfg if cfg.backend != "auto" else cfg.replace(
            backend=self.backend
        )
        bad = [
            f for f in frozen
            if getattr(want, f) != getattr(built, f)
        ]
        if bad:
            raise ValueError(
                "query config changes corpus-side knobs baked into this "
                f"index: {bad}; build a new index (or override only "
                "query-side knobs: k/topk_method/merge_schedule/"
                "precision_policy/query_bucket/dispatch_depth/donate)"
            )
        if want.precision_policy == "mixed" and self.cfg.dtype != "float32":
            raise ValueError(
                "precision_policy='mixed' cannot serve from a "
                f"{self.cfg.dtype} index: the exact rerank contract is "
                "void on a corpus compressed at rest"
            )
        return want


def build_index(
    corpus,
    config: Optional[KNNConfig] = None,
    mesh: Optional[Mesh] = None,
    **overrides,
) -> CorpusIndex:
    """Build a device-resident :class:`CorpusIndex` for query serving.

    Args:
      corpus: (m, d) host array or device ``jax.Array`` (device inputs are
        tiled/sharded without a host bounce, same contract as ``all_knn``).
      config: build-time :class:`KNNConfig`; kwargs override fields.
      mesh: optional ring mesh for the distributed backends.
    """
    from mpi_knn_tpu.api import resolve_backend
    from mpi_knn_tpu.obs.spans import span as _flight_span

    cfg = (config or KNNConfig()).replace(**overrides)
    if not isinstance(corpus, jax.Array):
        corpus = np.asarray(corpus)
    m, dim = corpus.shape
    backend = resolve_backend(cfg, mesh)
    with _flight_span("index-build", cat="index", backend=backend,
                      m=int(m), dim=int(dim)):
        return _build_index_resident(corpus, cfg, mesh, backend, m, dim)


def _build_index_resident(corpus, cfg, mesh, backend, m, dim) -> CorpusIndex:

    mu = None
    if cfg.center and cfg.metric == "l2":
        # same mean construction as ops.distance.center_for_l2, computed
        # ONCE here: f64 on host, accumulation dtype on device. Queries
        # are centered per batch with this stored mean, so serving math is
        # bit-identical to a fresh all_knn over the same residency.
        if isinstance(corpus, jax.Array):
            acc = jnp.float64 if corpus.dtype == jnp.float64 else jnp.float32
            mu = jnp.mean(corpus, axis=0, dtype=acc)
        else:
            mu = np.asarray(corpus, dtype=np.float64).mean(axis=0)
        corpus = corpus - mu

    if backend in ("ring", "ring-overlap"):
        from mpi_knn_tpu.backends.ring import parse_ring_mesh, ring_tiles
        from mpi_knn_tpu.parallel.mesh import make_ring_mesh

        if mesh is None:
            mesh = make_ring_mesh(cfg.num_devices, axis_name=cfg.mesh_axis)
        q_axis, axis, dp, ring_n = parse_ring_mesh(mesh)
        if backend == "ring" and q_axis is not None:
            from mpi_knn_tpu.backends.ring import (
                blocking_undefined_on_mesh_error,
            )

            raise blocking_undefined_on_mesh_error(mesh.axis_names)
        # corpus-side padding only: the query-side tile/pad is bucket-
        # dependent and computed per executable (engine.ring_query_shapes);
        # ring_tiles with nq=query_bucket fixes c_tile/c_pad for the index
        _, c_tile, _, c_pad = ring_tiles(cfg, m, cfg.query_bucket, dp, ring_n)
        dtype = jnp.dtype(cfg.dtype)
        csh = NamedSharding(mesh, P(axis))
        corpus_p = pad_rows_any(corpus, c_pad, dtype=dtype)
        corpus_scales = None
        if cfg.ring_transfer_dtype == "int8":
            # quantize ONCE at build: the resident shards hold the wire
            # representation (codes + per-row scales), so every batch's
            # rotation starts from the already-compressed block and the
            # serve program only ever dequantizes (backends.ring)
            from mpi_knn_tpu.backends.ring import quantize_ring_block

            corpus_p, corpus_scales = quantize_ring_block(corpus_p)
            corpus_scales = jax.device_put(corpus_scales, csh)
        corpus_p = jax.device_put(corpus_p, csh)
        corpus_ids = jax.device_put(jnp.asarray(make_global_ids(m, c_pad)), csh)
        return CorpusIndex(
            cfg=cfg.replace(backend=backend), backend=backend, m=m, dim=dim,
            c_tile=c_tile, mu=mu, mesh=mesh,
            ring_meta=(q_axis, axis, dp, ring_n),
            corpus_sharded=corpus_p, corpus_ids_sharded=corpus_ids,
            corpus_scales_sharded=corpus_scales,
        )

    if backend == "pallas":
        if cfg.dtype != "float32":
            raise ValueError(
                "pallas backend computes in float32; build the index with "
                f"dtype='float32' (got {cfg.dtype!r})"
            )
        if cfg.metric != "l2":
            raise ValueError(
                "pallas serving supports metric='l2' only: the cosine "
                "path needs a per-batch zero-row degeneracy probe (a "
                "host round-trip) that a streaming engine cannot honor — "
                "use the serial or ring backends for cosine serving"
            )
        c_tile = min(max(128, pad_to_multiple(cfg.corpus_tile, 128)), 2048,
                     pad_to_multiple(m, 128))
        c_pad = pad_to_multiple(m, c_tile)
        corpus_p = pad_rows_any(corpus, c_pad, dtype=jnp.float32)
        return CorpusIndex(
            cfg=cfg.replace(backend=backend), backend=backend, m=m, dim=dim,
            c_tile=c_tile, mu=mu, corpus_padded=corpus_p,
        )

    # serial: the tile stack + ids + NORMS, all resident (norms are the
    # O(m·d) reduction all_knn redoes per call — here they are index state)
    from mpi_knn_tpu.backends.serial import cap_corpus_tile

    dtype = jnp.dtype(cfg.dtype)
    c_tile = cap_corpus_tile(
        cfg.query_tile,
        min(cfg.corpus_tile, pad_to_multiple(m, 128)),
        cfg.max_tile_elems,
    )
    # capacity headroom (ISSUE 14): extra id −1 rows beyond the corpus
    # are the serial layout's upsert capacity — the mutation freelist
    # fills them by donated in-place scatter with no shape change. They
    # cost padded FLOPs per batch (masked, never answers); build with
    # bucket_headroom=0.0 for a frozen corpus.
    c_pad = pad_to_multiple(
        max(m, int(np.ceil(m * (1.0 + cfg.bucket_headroom)))), c_tile
    )
    tiles = pad_rows_any(corpus, c_pad, dtype=dtype).reshape(-1, c_tile, dim)
    tile_ids = jnp.asarray(make_global_ids(m, c_pad).reshape(-1, c_tile))
    # same norm construction as knn_chunk_update (zeros for cosine, where
    # the metric kernel normalizes internally), computed UNDER JIT: the
    # eager-mode reduction produces different bits than the traced one on
    # CPU, and serving must be bit-identical to a fresh all_knn call
    acc = jnp.float64 if dtype == jnp.float64 else jnp.float32
    tile_sqs = (
        jax.jit(jax.vmap(sq_norms))(tiles)
        if cfg.metric == "l2"
        else jnp.zeros(tiles.shape[:2], dtype=acc)
    )
    return CorpusIndex(
        cfg=cfg.replace(backend=backend), backend=backend, m=m, dim=dim,
        c_tile=c_tile, mu=mu, tiles=tiles, tile_ids=tile_ids,
        tile_sqs=tile_sqs,
    )
