"""``mpi-knn query`` — build a resident corpus index, stream query
batches, report per-batch latency and end-to-end throughput.

The serving counterpart of the one-shot run driver: the corpus is loaded
and indexed ONCE (tiles/shards + norms + centering mean on device), then
query batches stream through the bucketed AOT executable cache with
bounded dispatch-ahead (``mpi_knn_tpu.serve``). Steady state issues zero
recompiles; the summary line reports how many executables the whole run
compiled so that claim is visible per invocation.

Flag combinations the engine cannot honor are refused with a loud exit 2
(the ``BENCH_RING_SCHEDULE`` convention: never silently measure a
different configuration than the one requested) — e.g. a pallas index
with a cosine metric or a non-float32 dtype, a mixed-precision query
config over a bf16-compressed index, or a blocking-ring index on a
multi-axis mesh.

Examples::

    mpi-knn query --data synthetic:8192x64c10 --synthetic 4096 --batch 512
    mpi-knn query --data corpus.mat --queries q.npy --backend ring-overlap
    mpi-knn query --data sift:100000 --synthetic 10000 --bucket 1024 \
        --dispatch-depth 4 --report serve.json
    mpi-knn query --data sift:100000 --synthetic 10000 \
        --batch-deadline-ms 50 --retries 2    # resilient serving: deadline,
        # transient-retry, NaN sentinel, degradation ladder (see --help)
    mpi-knn query --data sift:100000 --synthetic 10000 \
        --flight-record flight.jsonl --metrics-out metrics.json \
        --profile-batches 8    # observability (mpi_knn_tpu.obs): span
        # flight record, metrics snapshot, device-time split in --report
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

from mpi_knn_tpu.config import (
    BACKENDS,
    MERGE_SCHEDULES,
    METRICS,
    PRECISION_POLICIES,
    RING_SCHEDULES,
    TOPK_METHODS,
    KNNConfig,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mpi-knn query",
        description="streamed query serving against a device-resident "
        "corpus index (bucketed AOT executable cache, zero steady-state "
        "recompiles)",
    )
    d = p.add_argument_group("data")
    d.add_argument("--data", default="mnist",
                   help="corpus spec (same forms as the run driver: "
                   "'mnist', 'digits', 'synthetic:MxDcC', 'sift:M', "
                   "*.fvecs/bvecs, or a .mat file)")
    d.add_argument("--limit", type=int, default=None,
                   help="use first N corpus rows only")
    d.add_argument("--index-load", default=None, metavar="PATH.npz",
                   help="serve a saved clustered (IVF) index "
                   "(`mpi-knn build-index`) instead of building a dense "
                   "CorpusIndex from --data; --data is then only the "
                   "source of --synthetic query statistics")
    d.add_argument("--nprobe", type=int, default=None,
                   help="with --index-load: partitions probed per query "
                   "(default: the index's tuned value)")
    d.add_argument("--route-cap", type=int, default=None,
                   help="with --index-load --backend ring: static "
                   "per-(home, owner)-shard route capacity of the "
                   "candidate exchange per query tile (default: the safe "
                   "cap q_tile*nprobe — no probe ever drops); smaller "
                   "caps bound exchange memory and DROP overflow probes "
                   "(counted in the metrics/report, never wrong answers)")
    q = p.add_mutually_exclusive_group()
    q.add_argument("--queries", default=None,
                   help=".npy/.mat/.fvecs file of query points, streamed "
                   "in --batch-row chunks")
    q.add_argument("--synthetic", type=int, default=None, metavar="N",
                   help="serve N synthetic query rows (corpus-distributed "
                   "noise, corpus dim) instead of a file")
    d.add_argument("--batch", type=int, default=256,
                   help="rows per streamed batch (the final batch may be "
                   "ragged; it pads to its bucket)")

    k = p.add_argument_group("kNN / serving")
    k.add_argument("--k", type=int, default=30)
    k.add_argument("--metric", choices=METRICS, default="l2")
    k.add_argument("--backend", choices=BACKENDS, default="auto")
    k.add_argument("--devices", type=int, default=None,
                   help="ring size for distributed backends")
    # corpus-side knobs default to None so --index-load can tell an
    # explicitly passed flag (refused loudly if it conflicts with the
    # saved index) from an untouched default; the dense build path
    # resolves None to the documented defaults below
    k.add_argument("--dtype", default=None,
                   choices=["float32", "bfloat16", "float64"],
                   help="resident/compute dtype (default float32); "
                   "bfloat16 stores the index compressed at half width")
    k.add_argument("--query-tile", type=int, default=1024)
    k.add_argument("--corpus-tile", type=int, default=None,
                   help="corpus tile rows (default 2048); baked into a "
                   "loaded index's layout")
    k.add_argument("--precision-policy", choices=list(PRECISION_POLICIES),
                   default="exact")
    k.add_argument("--topk-method", choices=list(TOPK_METHODS),
                   default="exact")
    k.add_argument("--merge-schedule", choices=list(MERGE_SCHEDULES),
                   default="twolevel")
    k.add_argument("--ring-schedule", choices=list(RING_SCHEDULES),
                   default=None,
                   help="ring rotation schedule (default uni); "
                   "meaningless for a loaded clustered index")
    k.add_argument("--ring-transfer-dtype",
                   choices=["bfloat16", "float32", "int8"], default=None,
                   help="dtype of the corpus block on the rotation wire "
                   "(ring backends): bfloat16 halves ICI bytes per hop; "
                   "int8 is the block-scaled quantized level (~4x fewer "
                   "bytes, requires --precision-policy mixed; the "
                   "resident index holds codes + scales, so HBM shrinks "
                   "too — the --report ring_transfer block carries the "
                   "static wire bytes)")
    k.add_argument("--bucket", type=int, default=1024,
                   help="base row bucket: batches pad to bucket*2^j rows "
                   "and each (bucket, config) compiles exactly once")
    k.add_argument("--dispatch-depth", type=int, default=2,
                   help="max batches in flight (2 = double buffering)")
    k.add_argument("--no-donate", action="store_true",
                   help="disable per-batch scratch donation (debugging)")
    k.add_argument("--cache-dir", default=None, metavar="DIR",
                   help="persistent AOT executable cache "
                   "(serve/aotcache.py; also via TKNN_AOT_CACHE): "
                   "executables this process compiles are serialized "
                   "here and revived on the next run — a repeated query "
                   "run against one dir warms with zero XLA backend "
                   "compiles (the summary/report carry the hit/miss "
                   "story). Stale or corrupt entries recompile loudly")

    r = p.add_argument_group(
        "resilience (mpi_knn_tpu.resilience: deadline, retry, sentinel, "
        "degradation ladder)"
    )
    r.add_argument("--batch-deadline-ms", type=float, default=None,
                   metavar="MS",
                   help="per-batch latency deadline (dispatch→sync); on "
                   "--degrade-after consecutive breaches the session "
                   "sheds load one rung down the degradation ladder "
                   "(smaller nprobe → mixed precision → smaller bucket), "
                   "stamping every degraded batch in the records and the "
                   "report")
    r.add_argument("--retries", type=int, default=None, metavar="N",
                   help="bounded exponential-backoff retries of a batch "
                   "dispatch on transient failures (default 2 when a "
                   "resilience policy is active)")
    r.add_argument("--degrade-after", type=int, default=None, metavar="N",
                   help="consecutive deadline breaches before shedding "
                   "one ladder rung (default 2 when a resilience policy "
                   "is active)")
    r.add_argument("--no-nan-sentinel", action="store_true",
                   help="disable the NaN/all-inf sentinel on returned "
                   "top-k (on by default with a resilience policy; trips "
                   "loudly with batch provenance)")

    o = p.add_argument_group("output / observability (mpi_knn_tpu.obs)")
    o.add_argument("--tenant", default=None, metavar="NAME",
                   help="attribute this stream to a tenant id: per-tenant "
                   "counters (serve_tenant_queries_total{tenant=...}) in "
                   "the metrics registry, tenant composition on the batch "
                   "flight spans, and a per-tenant block in --report — "
                   "the single-stream form of the serving front end's "
                   "multi-tenant accounting (mpi-knn serve)")
    o.add_argument("--report", default=None, help="write JSON report here")
    o.add_argument("--flight-record", default=None, metavar="JSONL",
                   help="record structured trace spans (index build, "
                   "per-bucket compiles, per-batch dispatch→retire, "
                   "retry/degradation events) to this append-only JSONL "
                   "ring file, written incrementally so the record "
                   "survives a killed process; inspect/validate/export "
                   "with `mpi-knn metrics --flight`")
    o.add_argument("--metrics-out", default=None, metavar="JSON",
                   help="write the process metrics-registry snapshot "
                   "(batch latency histogram, compile counters, "
                   "resilience counters) at exit; render as Prometheus "
                   "text with `mpi-knn metrics`")
    o.add_argument("--profile-batches", type=int, default=None, metavar="N",
                   help="after the stream, profile N extra steady-state "
                   "batches under jax.profiler and embed the per-category "
                   "device busy split (matmul/sort-topk/collective/copy/"
                   "other + overlap fraction) in the report next to "
                   "p50/p99")
    o.add_argument("--profile-dir", default=None, metavar="DIR",
                   help="with --profile-batches: keep the raw trace here "
                   "(default: a temp dir)")
    o.add_argument("--platform", choices=["auto", "cpu", "tpu"],
                   default="auto")
    o.add_argument("-q", "--quiet", action="store_true")
    return p


def _resilience_policy(args):
    """A ResiliencePolicy when any resilience flag was given, else None
    (the zero-overhead legacy session). A policy-shaping knob WITHOUT a
    policy-activating one is refused, not silently inert — the serve
    CLI's convention for knobs that would not apply."""
    if args.degrade_after is not None and args.batch_deadline_ms is None:
        # degradation is deadline-driven: without a deadline the counter
        # can never trigger, whatever else is active
        raise ValueError(
            "--degrade-after without --batch-deadline-ms: degradation "
            "is triggered by deadline breaches, so the knob would be "
            "silently inert"
        )
    if args.batch_deadline_ms is None and args.retries is None:
        if args.no_nan_sentinel:
            raise ValueError(
                "--no-nan-sentinel without --batch-deadline-ms or "
                "--retries: no resilience policy is active, so the knob "
                "would be silently inert"
            )
        return None
    from mpi_knn_tpu.resilience import ResiliencePolicy

    return ResiliencePolicy(
        batch_deadline_s=(
            args.batch_deadline_ms / 1e3
            if args.batch_deadline_ms is not None else None
        ),
        max_retries=args.retries if args.retries is not None else 2,
        degrade_after=(
            args.degrade_after if args.degrade_after is not None else 2
        ),
        nan_sentinel=not args.no_nan_sentinel,
    )


def _load_query_stream(args, X):
    """(total_rows, iterator of np batches) from --queries / --synthetic."""
    if args.synthetic is not None:
        rng = np.random.default_rng(1)
        dim = X.shape[1]
        total = args.synthetic
        lo, hi = float(np.min(X)), float(np.max(X))

        def gen():
            left = total
            while left > 0:
                n = min(args.batch, left)
                yield rng.uniform(lo, hi, size=(n, dim)).astype(np.float32)
                left -= n

        return total, gen()
    from mpi_knn_tpu.cli import _load_queries

    Q = np.asarray(_load_queries(args.queries))
    if Q.ndim != 2 or Q.shape[1] != X.shape[1]:
        raise SystemExit(
            f"error: queries shape {Q.shape} does not match corpus dim "
            f"{X.shape[1]}"
        )

    def gen():
        for s in range(0, len(Q), args.batch):
            yield Q[s: s + args.batch]

    return len(Q), gen()


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.queries is None and args.synthetic is None:
        print("error: provide a query stream (--queries FILE or "
              "--synthetic N)", file=sys.stderr)
        return 2
    if args.batch < 1:
        print("error: --batch must be >= 1", file=sys.stderr)
        return 2
    if args.synthetic is not None and args.synthetic < 1:
        # a zero/negative stream would "succeed" with 0 queries served —
        # a silent no-op where the convention demands a loud usage error
        print("error: --synthetic must be >= 1", file=sys.stderr)
        return 2
    if args.tenant is not None and (
        not args.tenant
        or any(c in args.tenant for c in ('"', "\\", "\n", "\r"))
    ):
        # the tenant id becomes a metrics label: refuse at the flag, not
        # with a mid-stream traceback at the first batch retire
        print("error: --tenant must be non-empty with no quotes, "
              "backslashes, or newlines (it becomes a metrics label)",
              file=sys.stderr)
        return 2

    try:
        policy = _resilience_policy(args)
    except ValueError as e:
        # invalid resilience knobs (negative deadline, degrade-after 0…):
        # the loud exit-2 usage-error convention
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.profile_dir is not None and args.profile_batches is None:
        # the inert-knob refusal convention: a kept trace dir without a
        # profiling pass would silently record nothing
        print("error: --profile-dir without --profile-batches: no "
              "profiling pass runs, so the knob would be silently inert",
              file=sys.stderr)
        return 2
    if args.profile_batches is not None and args.profile_batches < 1:
        print("error: --profile-batches must be >= 1", file=sys.stderr)
        return 2

    if args.flight_record:
        # install before any index/serve work so the index-build span and
        # the warm-up compiles land in the record; fresh=True — a new run
        # must not append to a previous run's story
        from mpi_knn_tpu.obs.spans import FlightRecorder, set_recorder

        set_recorder(FlightRecorder(args.flight_record, fresh=True))

    if args.cache_dir:
        # before any executable builds, so even the first bucket of the
        # stream can revive from (or land in) the persistent cache
        from mpi_knn_tpu.serve import aotcache

        aotcache.set_cache_dir(args.cache_dir)

    if args.platform != "auto":
        from mpi_knn_tpu.utils.platform import force_platform

        # --platform cpu --devices N: size the virtual host mesh to the
        # request (a ring/sharded serve on a 1-CPU host would otherwise
        # fail with "only 1 visible" despite the explicit ask)
        force_platform(
            args.platform,
            n_devices=(args.devices if args.platform == "cpu" else None),
        )

    from mpi_knn_tpu.cli import load_corpus
    from mpi_knn_tpu.serve import ServeSession, build_index

    X, _, source = load_corpus(args.data, limit=args.limit)

    if args.index_load:
        return _serve_loaded_index(args, X, source, policy)

    if args.nprobe is not None:
        # the serve-CLI refusal convention: a probe count without a
        # clustered index would be silently ignored
        print("error: --nprobe requires --index-load (probing is a "
              "clustered-index knob)", file=sys.stderr)
        return 2
    if args.route_cap is not None:
        print("error: --route-cap requires --index-load --backend ring "
              "(the route cap bounds the sharded clustered candidate "
              "exchange)", file=sys.stderr)
        return 2

    try:
        cfg = KNNConfig(
            k=args.k,
            metric=args.metric,
            backend=args.backend,
            dtype=args.dtype or "float32",
            query_tile=args.query_tile,
            corpus_tile=args.corpus_tile or 2048,
            precision_policy=args.precision_policy,
            topk_method=args.topk_method,
            merge_schedule=args.merge_schedule,
            ring_schedule=args.ring_schedule or "uni",
            ring_transfer_dtype=args.ring_transfer_dtype,
            num_devices=args.devices,
            query_bucket=args.bucket,
            dispatch_depth=args.dispatch_depth,
            donate=not args.no_donate,
        )
    except ValueError as e:
        # invalid knob combination (e.g. mixed policy over a non-f32
        # dtype): loud usage error, never a silently-adjusted run
        print(f"error: {e}", file=sys.stderr)
        return 2

    t_build0 = time.perf_counter()
    try:
        index = build_index(X, cfg)
        session = ServeSession(index, resilience=policy)
    except ValueError as e:
        # the engine cannot honor this combination (pallas+cosine,
        # compressed index + mixed policy, blocking ring on a 2-D mesh…)
        print(f"error: {e}", file=sys.stderr)
        return 2
    build_s = time.perf_counter() - t_build0
    return _stream_and_report(args, session, index, X, source, build_s)


def _serve_loaded_index(args, X, source, policy=None) -> int:
    """``--index-load``: serve a saved clustered (IVF) index through the
    same session/bucket-cache machinery — single-device by default, or
    SHARDED over the ring mesh with ``--backend ring`` (the shard layout
    is derived from ``--devices``; one artifact serves on any shard
    count). Corpus-side knobs come from the saved index; explicitly
    conflicting flags are refused with the standard loud exit 2 (never
    silently serve a different configuration than the one requested)."""
    from mpi_knn_tpu.ivf import load_ivf_index
    from mpi_knn_tpu.serve import ServeSession

    sharded = args.backend == "ring"
    if args.backend not in ("auto", "serial", "ring"):
        print(
            f"error: --index-load × --backend {args.backend} is not "
            "supported: a clustered index serves single-device (serial/"
            "auto) or sharded over the ring mesh (ring — the routed "
            "candidate exchange); the pallas kernels scan the full "
            "corpus by construction, and the exchange has no overlap "
            "schedule (use --backend ring, not ring-overlap)",
            file=sys.stderr,
        )
        return 2
    if args.metric != "l2":
        print(
            f"error: --index-load × --metric {args.metric} is not "
            "supported: the clustered index's k-means partitions and "
            "centroid score are L2 geometry",
            file=sys.stderr,
        )
        return 2
    if args.devices is not None and not sharded:
        print("error: --devices with --index-load requires --backend "
              "ring (the shard count of the distributed clustered "
              "index); the single-device clustered search cannot honor "
              "it", file=sys.stderr)
        return 2
    if args.route_cap is not None and not sharded:
        print("error: --route-cap with --index-load requires --backend "
              "ring: the route cap bounds the sharded candidate "
              "exchange — nothing is routed single-device",
              file=sys.stderr)
        return 2
    if args.corpus_tile is not None:
        print("error: --corpus-tile has no meaning with --index-load "
              "(the bucket layout was baked in at build time)",
              file=sys.stderr)
        return 2
    if args.ring_transfer_dtype is not None:
        print("error: --ring-transfer-dtype has no meaning with "
              "--index-load: the clustered search never rotates a ring, "
              "and the store's AT-REST compression (float32/bfloat16/"
              "int8/int4) was baked in at build time — rebuild with "
              "`mpi-knn build-index --dtype ...` to change it",
              file=sys.stderr)
        return 2
    if args.ring_schedule is not None:
        print("error: --ring-schedule has no meaning with --index-load "
              "(the clustered search never rotates a ring)",
              file=sys.stderr)
        return 2

    t0 = time.perf_counter()
    try:
        index = load_ivf_index(args.index_load)
    except (OSError, KeyError, ValueError) as e:
        print(f"error: cannot load index {args.index_load!r}: {e}",
              file=sys.stderr)
        return 2
    if args.dtype is not None and args.dtype != index.cfg.dtype:
        print(
            f"error: --dtype {args.dtype} conflicts with the loaded "
            f"index's at-rest dtype ({index.cfg.dtype}); the dtype is "
            "baked in at build time",
            file=sys.stderr,
        )
        return 2
    if X.shape[1] != index.dim:
        print(
            f"error: --data {args.data!r} has dim {X.shape[1]} but the "
            f"loaded index was built at dim {index.dim}",
            file=sys.stderr,
        )
        return 2
    try:
        if sharded:
            # derive the shard layout over the mesh — the saved artifact
            # carries no layout, so the SAME .npz serves here at any
            # --devices count (bit-compatibly: every per-query dot shape
            # is shard-count-independent)
            from mpi_knn_tpu.ivf import shard_ivf_index

            index = shard_ivf_index(
                index, shards=args.devices, route_cap=args.route_cap
            )
        cfg = index.compatible_cfg(
            index.cfg.replace(
                k=args.k,
                query_tile=args.query_tile,
                precision_policy=args.precision_policy,
                topk_method=args.topk_method,
                merge_schedule=args.merge_schedule,
                nprobe=args.nprobe,  # None -> the index's tuned default
                query_bucket=args.bucket,
                dispatch_depth=args.dispatch_depth,
                donate=not args.no_donate,
            )
        )
        session = ServeSession(index, cfg, resilience=policy)
    except ValueError as e:
        # unhonorable combination (nprobe > partitions, mixed policy on a
        # bf16-at-rest index, more shards than devices, …)
        print(f"error: {e}", file=sys.stderr)
        return 2
    load_s = time.perf_counter() - t0
    return _stream_and_report(args, session, index, X, source, load_s)


def _stream_and_report(args, session, index, X, source, build_s) -> int:
    """Shared serving tail: stream the query batches, print per-batch
    latency lines, emit the summary/report."""
    from mpi_knn_tpu.serve.engine import index_peak_hbm_bytes

    cfg = session.cfg
    total, stream = _load_query_stream(args, X)

    t0 = time.perf_counter()
    n_batches = 0
    degraded_batches = 0
    for res in session.stream(stream, tenant=args.tenant):
        n_batches += 1
        if res.degraded is not None:
            degraded_batches += 1
        if not args.quiet:
            # the per-batch resilience stamps ride the latency line: a
            # degraded/retried/breached batch must be visible where the
            # operator is already looking (the PR 4 "degraded" marker
            # convention)
            extra = ""
            if res.degraded is not None:
                extra += f" degraded={res.degraded}"
            if res.retries:
                extra += f" retries={res.retries}"
            if res.deadline_breached:
                extra += " DEADLINE-BREACH"
            # res.seq IS the printed batch number: sentinel/degradation
            # provenance (batch seq=N) must point at this exact line
            print(
                f"batch {res.seq}: rows={res.rows} "
                f"bucket={res.bucket} latency={res.latency_s * 1e3:.2f}ms"
                f"{extra}"
            )
    wall = time.perf_counter() - t0

    lats = np.asarray(session.latencies)
    summary = {
        "corpus": source,
        "shape": list(X.shape),
        "backend": index.backend,
        "k": cfg.k,
        "queries": session.queries_served,
        "batches": n_batches,
        "executables_compiled": len(index._cache),
        "index_build_s": round(build_s, 4),
        "wall_s": round(wall, 4),
        "throughput_qps": round(session.queries_served / wall, 2)
        if wall > 0 else None,
        "latency_p50_ms": round(float(np.percentile(lats, 50)) * 1e3, 3)
        if len(lats) else None,
        "latency_p99_ms": round(float(np.percentile(lats, 99)) * 1e3, 3)
        if len(lats) else None,
        # static peak HBM of the largest executable this run built
        # (ISSUE 15): PJRT buffer-assignment figure, zero device reads
        # — the serve_peak_hbm_bytes gauge's number, read next to the
        # throughput it bounds
        "peak_hbm_bytes": index_peak_hbm_bytes(index),
    }
    from mpi_knn_tpu.analysis.cost import detected_profile

    # the declared roofline inputs for this hardware (ISSUE 16): the
    # shipped device profile `mpi-knn plan` predicted q/s under, stamped
    # next to the measured throughput; null off the profile map
    summary["device_profile"] = detected_profile()
    if index.backend in ("ivf", "ivf-sharded"):
        summary["partitions"] = index.partitions
        summary["nprobe"] = cfg.nprobe
        summary["probe_fraction"] = round(
            cfg.nprobe / index.partitions, 4
        )
        # the compression-ladder story (ISSUE 9): the at-rest level and
        # the resident bytes it buys — read next to the recall/latency
        # this run measured, same numbers the ivf_at_rest_bytes gauge
        # stamps at lower time
        summary["at_rest"] = {
            "dtype": cfg.dtype,
            "resident_bytes": index.nbytes_resident,
            "probe_bytes_per_query": index.probe_bytes,
        }
    if index.backend in ("ring", "ring-overlap"):
        from mpi_knn_tpu.backends.ring import ring_wire_bytes_per_batch

        # the transfer level and the static per-batch rotation bytes at
        # the wire dtype (the ring_transfer_wire_bytes gauge's number)
        summary["ring_transfer"] = {
            "dtype": cfg.ring_transfer_dtype or cfg.dtype,
            "wire_bytes_per_batch": ring_wire_bytes_per_batch(
                cfg, index.corpus_sharded.shape[0], index.dim,
                index.ring_meta[3],
            ),
        }
    from mpi_knn_tpu.serve import aotcache as _aotcache

    _disk = _aotcache.active_cache()
    if _disk is not None:
        # the cold-start story next to the throughput it bought: cache
        # size plus this process's hit/miss/error counters (the same
        # numbers the registry exports as aot_cache_*_total)
        from mpi_knn_tpu.obs.metrics import get_registry

        _reg = get_registry()
        summary["aot_cache"] = {
            **_disk.stats(),
            "hits": int(_reg.counter("aot_cache_hits_total").snapshot()
                        ["value"]),
            "misses": int(_reg.counter("aot_cache_misses_total").snapshot()
                          ["value"]),
            "errors": int(_reg.counter("aot_cache_errors_total").snapshot()
                          ["value"]),
        }
    if session.tenant_stats:
        # the per-tenant window accumulators (first-class session state,
        # never reconstructed from deltas of the global blob): rows,
        # batches touched, latency sum/max — per tenant
        summary["tenants"] = {
            t: {
                "queries": st["queries"],
                "batches": st["batches"],
                "latency_sum_ms": round(st["latency_sum_s"] * 1e3, 3),
                "latency_max_ms": round(st["latency_max_s"] * 1e3, 3),
                **(
                    {"routed": round(st["routed"], 1)}
                    if "routed" in st else {}
                ),
            }
            for t, st in sorted(session.tenant_stats.items())
        }
    if session.exchange is not None:
        # the sharded candidate-exchange story, summarized where the
        # round is read: routed probe volume, the (counted, loud) probe-
        # cap overflow drops, static exchange bytes, and the per-shard
        # served-request load — the skew an operator tunes partitions/
        # route caps against
        summary["sharded"] = {
            "shards": session.exchange["shards"],
            "route_cap": cfg.ivf_route_cap,  # None = safe (no drops)
            "routed_total": session.exchange["routed_total"],
            "overflow_dropped_total": session.exchange["dropped_total"],
            "exchange_bytes_total":
                session.exchange["exchange_bytes_total"],
            "served_per_shard": session.exchange["served_per_shard"],
        }
    if args.profile_batches:
        # batches replay the stream's shape (--batch rows,
        # corpus-distributed synthetic noise); session.profile compiles
        # any bucket they still need BEFORE opening the trace (a short
        # --queries file may never have served a full --batch), so the
        # trace measures serving, not compilation.
        rng = np.random.default_rng(2)
        lo, hi = float(np.min(X)), float(np.max(X))
        prof_batches = [
            rng.uniform(lo, hi, size=(args.batch, X.shape[1]))
            .astype(np.float32)
            for _ in range(args.profile_batches)
        ]
        summary["device_time"] = session.profile(
            prof_batches, trace_dir=args.profile_dir
        )
    if session.policy is not None:
        # the degradation story, summarized where the round is read: how
        # often the deadline broke, what the ladder shed, where serving
        # ended up — mirroring the per-batch stamps above
        summary["resilience"] = {
            "batch_deadline_ms": (
                session.policy.batch_deadline_s * 1e3
                if session.policy.batch_deadline_s is not None else None
            ),
            "ladder": [label for label, _ in session.ladder],
            "final_rung": session.rung,
            "degraded_batches": degraded_batches,
            "deadline_breaches": session.deadline_breaches,
            "retries_total": session.retries_total,
            "degradations": session.degradations,
        }
    if not args.quiet:
        print(
            f"[mpi-knn query] {summary['queries']} queries in "
            f"{summary['batches']} batches: {summary['throughput_qps']} q/s "
            f"(p50 {summary['latency_p50_ms']}ms, "
            f"p99 {summary['latency_p99_ms']}ms, "
            f"{summary['executables_compiled']} executable(s) compiled, "
            f"index build {summary['index_build_s']}s)"
        )
    if not args.quiet and "device_time" in summary:
        dt = summary["device_time"]
        if "busy_ms" in dt:
            split = ", ".join(
                f"{k} {v}ms" for k, v in sorted(dt["busy_ms"].items())
            )
            print(
                f"[device-time] plane={dt['plane']} "
                f"busy={dt['busy_total_ms']}ms ({split}) "
                f"overlap-fraction={dt['overlap_fraction']}"
            )
        else:
            print(f"[device-time] {dt.get('error', 'no attribution')}")
    if args.metrics_out:
        from mpi_knn_tpu.obs.metrics import get_registry

        with open(args.metrics_out, "w") as f:
            json.dump(get_registry().snapshot(), f, indent=1)
            f.write("\n")
        if not args.quiet:
            print(f"metrics snapshot written to {args.metrics_out}")
    if args.flight_record and not args.quiet:
        print(f"flight record written to {args.flight_record}")
    if args.report:
        with open(args.report, "w") as f:
            json.dump(summary, f, indent=1)
            f.write("\n")
        if not args.quiet:
            print(f"report written to {args.report}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
