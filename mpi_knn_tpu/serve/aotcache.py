"""Persistent on-disk executable cache — restart-survivable AOT compiles.

Steady-state serving already compiles nothing (the in-memory bucketed
executable cache, PR 4), but the in-memory cache dies with the process:
every server restart, bench child, and doctor probe re-pays the whole
compile matrix before serving its first query. This module makes the
compiled artifact itself durable, so a restarted process compiles nothing
it has ever compiled before.

Mechanism — why serialized executables, not jax's persistent compilation
cache: jax's built-in cache (``jax_compilation_cache_dir``) still walks
the full trace → lower → ``compile_or_get_cached`` path and fires the
``backend_compile_duration`` monitoring event even on a hit, so "zero
XLA backend-compiles on the second start" would be unprovable from the
metrics registry, and tracing/lowering wall time would still be paid per
cell. Here a hit skips ALL of it: the entry stores the pickled PJRT
executable (``jax.experimental.serialize_executable``) plus its arg
pytrees, and loading is one ``deserialize_and_load`` — no trace, no
lower, no XLA invocation, no compile event. The lint CLI, which needs
HLO text rather than a runnable executable, uses jax's own cache instead
(``mpi-knn lint --cache-dir``); the two mechanisms share nothing but the
directory convention.

Keying — the full fingerprint, never the program text: an entry is
addressed by a sha256 over (a) the frozen :class:`KNNConfig` with
host-only pacing knobs canonicalized out (the in-memory cache's own
fingerprint rule), (b) the row bucket, (c) the index facts — backend,
corpus size/dim, every resident array's shape+dtype, tiling/partition/
shard layout, mesh topology, centering — and (d) the platform facts:
backend name, device count and kinds, jax/jaxlib versions, and this
module's format version. Anything that could change the lowered program
or the devices it binds to is in the key, so a mismatched entry is
simply never FOUND. Defense in depth on top: a loaded executable's
``args_info`` avals are checked against the argspec the engine would
have lowered (``serve.engine`` passes ``expect_args``), and a stale or
corrupt entry — bad magic, truncated pickle, checksum mismatch, wrong
jax version, aval mismatch, a deserialization error from a moved device
topology — falls back to a REAL compile loudly: counted in
``aot_cache_errors_total``, warned on stderr, overwritten by the fresh
compile. Never a mismatched program, never a silent miss.

Concurrency: writers serialize to a temp file in the cache directory and
``os.replace`` it into place — readers see either the old entry or the
new one, never a torn write, and concurrent warms (the parallel warm
pool, several bench children sharing one dir) need no locking.

Activation is process-level, not per-config (a cache directory is an
operational fact about the host, and nothing here may perturb executable
fingerprints): ``set_cache_dir(path)`` explicitly, the
``TKNN_AOT_CACHE`` env var ambiently, or ``--cache-dir`` on the serve /
query / doctor CLIs. No jax import at module load.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import pathlib
import pickle
import threading
import warnings

from mpi_knn_tpu.obs import metrics as obs_metrics
from mpi_knn_tpu.utils.atomicio import atomic_write_bytes

# bump when the entry layout (or anything about how executables are
# rebuilt from entries) changes: old entries must MISS, not half-load
FORMAT_VERSION = 1

ENTRY_SUFFIX = ".aotx"

ENV_VAR = "TKNN_AOT_CACHE"


# ---------------------------------------------------------------------------
# Fingerprinting


def index_facts(index) -> dict:
    """Everything about a resident index that reaches its per-batch
    program: backend, corpus/layout scalars, the shape+dtype of every
    resident array, and the mesh topology for the distributed backends.
    Two indices with equal facts lower bit-identical programs for a given
    (bucket, config); any difference — a re-tiled corpus, a different
    shard count, a quantized store — changes the key."""
    facts: dict = {
        "backend": index.backend,
        "m": int(index.m),
        "dim": int(index.dim),
        "has_mu": index.mu is not None,
    }
    for name in (
        "tiles", "tile_ids", "tile_sqs", "corpus_padded",
        "corpus_sharded", "corpus_ids_sharded", "corpus_scales_sharded",
        "centroids", "centroid_sqs", "buckets", "bucket_ids",
        "bucket_sqs", "bucket_scales",
    ):
        arr = getattr(index, name, None)
        if arr is not None:
            facts[name] = [
                [int(s) for s in arr.shape], str(arr.dtype)
            ]
    for name in ("c_tile", "partitions", "bucket_cap", "nprobe",
                 "shards", "per_shard"):
        v = getattr(index, name, None)
        if v is not None:
            facts[name] = int(v)
    mesh = getattr(index, "mesh", None)
    if mesh is not None:
        facts["mesh"] = {
            "axes": [str(a) for a in mesh.axis_names],
            "shape": [int(s) for s in mesh.devices.shape],
        }
    ring_meta = getattr(index, "ring_meta", None)
    if ring_meta is not None:
        facts["ring_meta"] = [
            ring_meta[0], ring_meta[1], int(ring_meta[2]),
            int(ring_meta[3]),
        ]
    return facts


def platform_facts() -> dict:
    """The process-side half of the fingerprint: an executable is a
    device binary bound to a client topology, so the platform, the device
    census, and the exact jax/jaxlib pair are key material — an entry
    compiled under any other combination must miss."""
    import jax
    import jaxlib

    devices = jax.devices()
    return {
        "platform": jax.default_backend(),
        "device_count": len(devices),
        "device_kinds": sorted({d.device_kind for d in devices}),
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "format": FORMAT_VERSION,
    }


def fingerprint_facts(index, cfg, bucket: int, kind: str = "serve") -> dict:
    """The full human-readable fingerprint document (the sha256 preimage,
    also stored in each entry's meta so ``mpi-knn doctor`` and a human
    with ``pickle.load`` can see WHY an entry is what it is). ``kind``
    distinguishes the mutation programs (upsert/delete/assign/compact —
    ``serve.mutate``) from the serve batch program; the default "serve"
    is OMITTED from the document so every pre-mutation entry's address
    is unchanged."""
    from mpi_knn_tpu.serve.engine import _fingerprint_cfg

    doc = {
        "cfg": dataclasses.asdict(_fingerprint_cfg(cfg)),
        "bucket": int(bucket),
        "index": index_facts(index),
        "platform": platform_facts(),
    }
    if kind != "serve":
        doc["kind"] = kind
    return doc


def fingerprint(index, cfg, bucket: int, kind: str = "serve") -> str:
    """Content address of one (index, config, bucket[, kind]) cell."""
    doc = json.dumps(
        fingerprint_facts(index, cfg, bucket, kind=kind), sort_keys=True
    )
    return hashlib.sha256(doc.encode()).hexdigest()


# ---------------------------------------------------------------------------
# The cache


def _counter(name: str, help: str):  # noqa: A002 — registry convention
    return obs_metrics.get_registry().counter(name, help=help)


def _count_hit():
    _counter("aot_cache_hits_total",
             "executables loaded from the persistent AOT cache").inc()


def _count_miss():
    _counter("aot_cache_misses_total",
             "persistent AOT cache lookups that found no entry").inc()


def _count_error():
    _counter(
        "aot_cache_errors_total",
        "stale/corrupt/unloadable AOT cache entries that fell back to a "
        "real compile (loud, never a wrong program)",
    ).inc()


def _count_store():
    _counter("aot_cache_stores_total",
             "executables serialized into the persistent AOT cache").inc()


class AOTCache:
    """One cache directory of content-addressed serialized executables.

    Every entry is a single file ``<key>.aotx``: a pickle of
    ``{"format", "jax", "key", "sha256", "payload", "in_tree",
    "out_tree", "meta"}`` where ``payload`` is the serialized PJRT
    executable, the trees are the pickled arg/result pytree defs, and
    ``sha256`` is the payload digest (truncation/bit-rot detection on
    top of pickle's own framing). All read-side failures degrade to a
    miss — counted and warned, never raised into serving."""

    def __init__(self, path: str | os.PathLike):
        self.dir = pathlib.Path(path)
        self.dir.mkdir(parents=True, exist_ok=True)

    def entry_path(self, key: str) -> pathlib.Path:
        return self.dir / f"{key}{ENTRY_SUFFIX}"

    # -- read side --------------------------------------------------------

    def load(self, key: str, expect_args=None):
        """The compiled executable for ``key``, or None (a miss — absent,
        stale, corrupt, or mismatched entries all land here; only absence
        is silent). ``expect_args`` is an optional sequence of
        ``(shape_tuple, dtype_str)`` the loaded executable's flattened
        ``args_info`` must match — the engine passes the argspec it would
        have lowered, so a fingerprint collision (or a bug in the key)
        can still never serve a mismatched program."""
        path = self.entry_path(key)
        try:
            blob = path.read_bytes()
        except FileNotFoundError:
            _count_miss()
            return None
        except OSError as e:
            _warn_bad(key, f"unreadable entry file: {e}")
            return None
        try:
            doc = pickle.loads(blob)
            if doc.get("format") != FORMAT_VERSION:
                raise ValueError(
                    f"format {doc.get('format')!r} != {FORMAT_VERSION}"
                )
            if doc.get("key") != key:
                raise ValueError("entry key does not match its filename")
            payload = doc["payload"]
            if hashlib.sha256(payload).hexdigest() != doc["sha256"]:
                raise ValueError("payload checksum mismatch (truncated or "
                                 "corrupt entry)")
            import jax
            from jax.experimental import serialize_executable

            if doc.get("jax") != jax.__version__:
                raise ValueError(
                    f"entry compiled under jax {doc.get('jax')} but this "
                    f"process runs {jax.__version__}"
                )
            in_tree = pickle.loads(doc["in_tree"])
            out_tree = pickle.loads(doc["out_tree"])
            compiled = serialize_executable.deserialize_and_load(
                payload, in_tree, out_tree
            )
            if expect_args is not None:
                _check_args(compiled, expect_args)
        except Exception as e:  # noqa: BLE001 — every failure is a miss
            _warn_bad(key, f"{type(e).__name__}: {e}")
            return None
        _count_hit()
        return compiled

    # -- write side -------------------------------------------------------

    def store(self, key: str, compiled, meta: dict | None = None) -> bool:
        """Serialize ``compiled`` under ``key`` via the shared atomic
        temp + ``os.replace`` helper (``utils.atomicio``; concurrent
        writers race benignly: the last full entry wins, readers never
        see a torn file). Returns
        False — counted and warned, never raised — when the executable
        does not support serialization or the write fails: a broken
        cache must not take serving down with it."""
        try:
            from jax.experimental import serialize_executable

            payload, in_tree, out_tree = serialize_executable.serialize(
                compiled
            )
            import jax

            doc = {
                "format": FORMAT_VERSION,
                "jax": jax.__version__,
                "key": key,
                "sha256": hashlib.sha256(payload).hexdigest(),
                "payload": payload,
                "in_tree": pickle.dumps(in_tree),
                "out_tree": pickle.dumps(out_tree),
                "meta": meta or {},
            }
            atomic_write_bytes(self.entry_path(key), pickle.dumps(doc))
        except Exception as e:  # noqa: BLE001 — storing is best-effort
            _count_error()
            warnings.warn(
                f"aot cache: cannot store entry {key[:12]}…: "
                f"{type(e).__name__}: {e}",
                RuntimeWarning,
                stacklevel=2,
            )
            return False
        _count_store()
        return True

    # -- introspection ----------------------------------------------------

    def stats(self) -> dict:
        """{dir, entries, bytes} — the doctor verdict's cache block."""
        entries = 0
        nbytes = 0
        try:
            for p in self.dir.glob(f"*{ENTRY_SUFFIX}"):
                entries += 1
                nbytes += p.stat().st_size
        except OSError:
            pass
        return {"dir": str(self.dir), "entries": entries, "bytes": nbytes}


def _check_args(compiled, expect_args) -> None:
    """Compare the loaded executable's flattened input avals against the
    argspec the engine would have lowered; any difference means the entry
    is NOT this cell's program (fingerprint collision or key bug) and
    must be recompiled."""
    import jax

    got = [
        (tuple(a.shape), str(a.dtype))
        for a in jax.tree_util.tree_leaves(compiled.args_info)
    ]
    want = [(tuple(s), str(d)) for s, d in expect_args]
    if got != want:
        raise ValueError(
            f"loaded executable signature {got} does not match the "
            f"expected argspec {want}"
        )


def _warn_bad(key: str, why: str) -> None:
    _count_error()
    warnings.warn(
        f"aot cache: entry {key[:12]}… is unusable ({why}); falling back "
        "to a real compile and overwriting it",
        RuntimeWarning,
        stacklevel=3,
    )


# ---------------------------------------------------------------------------
# Process-level activation

_lock = threading.Lock()
_active: AOTCache | None = None
_configured = False  # set_cache_dir was called (None = explicit disable)


def set_cache_dir(path: str | os.PathLike | None) -> AOTCache | None:
    """Activate (or, with None, explicitly disable) the process-wide
    cache. Explicit configuration beats the ``TKNN_AOT_CACHE`` env var."""
    global _active, _configured
    with _lock:
        _active = AOTCache(path) if path is not None else None
        _configured = True
        return _active


def active_cache() -> AOTCache | None:
    """The process's cache, if any: the explicitly configured one, else
    one ambient from ``TKNN_AOT_CACHE``, else None (cache off — every
    lookup site must behave exactly as before this module existed).

    An unusable ambient directory (read-only mount, permission wall)
    disables the cache loudly instead of raising: this is called from
    the executable-build path inside live serving, and a broken cache
    must never take serving down with it. Explicit
    :func:`set_cache_dir` still raises — a CLI flag pointing nowhere is
    a startup usage error, not a degradation."""
    global _active, _configured
    with _lock:
        if _configured:
            return _active
        env = os.environ.get(ENV_VAR)
        if env:
            try:
                _active = AOTCache(env)
            except OSError as e:
                _count_error()
                warnings.warn(
                    f"aot cache: {ENV_VAR}={env!r} is unusable "
                    f"({type(e).__name__}: {e}); caching disabled for "
                    "this process",
                    RuntimeWarning,
                    stacklevel=2,
                )
                _active = None
            _configured = True
            return _active
        return None


def reset_for_tests() -> None:
    """Forget process-level activation (tests mutate env/config)."""
    global _active, _configured
    with _lock:
        _active = None
        _configured = False


# ---------------------------------------------------------------------------
# Doctor probe


def probe_roundtrip(cache: AOTCache) -> dict:
    """Store-then-load round trip on a tiny probe program — the doctor's
    hard evidence that THIS directory on THIS platform can persist and
    revive an executable (permissions, disk, serialization support), with
    the revived program's output compared bit-for-bit. The probe key is
    derived from the platform facts alone, so repeated doctor runs
    overwrite one well-known entry instead of growing the cache."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    key = hashlib.sha256(
        json.dumps({"probe": FORMAT_VERSION,
                    "platform": platform_facts()},
                   sort_keys=True).encode()
    ).hexdigest()
    had_entry = cache.entry_path(key).exists()
    lowered = jax.jit(lambda a: (a @ a.T).sum(axis=0)).lower(
        jax.ShapeDtypeStruct((8, 8), jnp.float32)
    )
    compiled = lowered.compile()
    stored = cache.store(key, compiled, meta={"probe": True})
    loaded = cache.load(key) if stored else None
    x = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
    bit_identical = False
    if loaded is not None:
        bit_identical = bool(
            (np.asarray(jax.device_get(compiled(x)))
             == np.asarray(jax.device_get(loaded(x)))).all()
        )
    return {
        "probe_key": key[:16],
        "had_entry": had_entry,
        "store_ok": stored,
        "load_ok": loaded is not None,
        "bit_identical": bit_identical,
    }
