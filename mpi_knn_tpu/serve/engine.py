"""Streamed query serving: bucketed AOT executable cache + double-buffered
batch pipeline over a :class:`~mpi_knn_tpu.serve.index.CorpusIndex`.

Why buckets: ``jax.jit`` compiles per shape, so serving raw batch sizes
means one compile per distinct size — a stream of ragged batches never
stops compiling. Here every batch is padded up to the smallest
``query_bucket · 2^j`` rows and each (bucket, config) pair is
``jit(...).lower(...).compile()``d exactly once; a steady-state stream
touches a handful of buckets and issues ZERO recompiles after warm-up
(machine-checked by the compile-counter tests in ``tests/test_serve.py``
via ``jax.monitoring``). Padded rows carry query id −1 and zero data; the
per-row independence of the tile reduction makes ragged batches
bit-identical to their unpadded selves.

Why donation: the per-batch top-k scratch (``carry_d``/``carry_i``) is
passed to the executable with ``donate_argnums``, so XLA aliases it to the
output buffers (``input_output_alias`` in the module header) and
steady-state serving reuses the same carry memory in place. The padded
QUERY buffer is deliberately NOT donated: there is no query-shaped output
to alias it to (XLA would ignore the donation and warn), so the engine
owns that buffer and drops its reference after dispatch instead. Lint
rule R5 (``analysis/rules.py``) reads the alias map and a copy census
back from the lowered batch program, so "donation happened" and "the
resident corpus is not copied per batch" are compiled-program facts, not
intent.

Why dispatch-ahead: ``dispatch_depth`` bounds how many batches may be in
flight; at depth ≥ 2 batch t+1's H2D transfer and dispatch overlap batch
t's device compute (double buffering). Timing is honest per the
BASELINE.md methodology — a batch is only timed when ``device_sync`` has
forced its result to materialize, never at dispatch.

Why resilience lives here (ISSUE 6): a serving stack is only
production-shaped when hangs, transient faults, and overload degrade
gracefully. ``ServeSession`` optionally takes a
:class:`~mpi_knn_tpu.resilience.ladder.ResiliencePolicy`: per-batch
deadline, bounded-backoff retry of transient dispatch failures, a
NaN/all-inf sentinel on every retired top-k, and an explicit degradation
ladder (smaller ``nprobe`` → ``precision_policy="mixed"`` → smaller
bucket) walked on repeated deadline breach — every rung an ordinary
(bucket, config) cell of this cache, every degradation stamped into the
per-batch records. The fault-injection hooks
(``mpi_knn_tpu/resilience/faults.py``) make all of it testable on CPU.
"""

from __future__ import annotations

import collections
import concurrent.futures
import dataclasses
import functools
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding

from mpi_knn_tpu.config import KNNConfig
from mpi_knn_tpu.obs import metrics as obs_metrics
from mpi_knn_tpu.obs import spans as obs_spans
from mpi_knn_tpu.ops.topk import init_topk, init_topk_tiles, merge_topk
from mpi_knn_tpu.parallel.partition import pad_rows_any, pad_to_multiple
from mpi_knn_tpu.resilience.faults import fault_point, poison_topk
from mpi_knn_tpu.resilience.heartbeat import maybe_beat
from mpi_knn_tpu.resilience.ladder import (
    FULL_RUNG,
    PoisonedResultError,
    ResiliencePolicy,
    build_ladder,
)
from mpi_knn_tpu.resilience.retry import retry_with_backoff
from mpi_knn_tpu.serve.index import CorpusIndex
from mpi_knn_tpu.types import KNNResult
from mpi_knn_tpu.utils.timing import device_sync


def bucket_rows(n: int, base: int) -> int:
    """Smallest ``base · 2^j`` (j ≥ 0) that holds ``n`` rows — power-of-two
    row buckets over a configurable base, so a stream of arbitrary batch
    sizes quantizes to O(log(max/base)) executables instead of one per
    size."""
    if n < 1:
        raise ValueError(f"batch must have >= 1 row, got {n}")
    b = base
    while b < n:
        b *= 2
    return b


# ---------------------------------------------------------------------------
# Per-backend serving functions, jitted ONCE per donation mode at module
# level. All three share the argument convention (queries, query_ids,
# carry_d, carry_i, <resident index arrays...>) so the scratch donation is
# uniformly donate_argnums=(2, 3) and the lint engine can lower the exact
# objects the production cache compiles.


def _pallas_serve_fn(
    queries_p, query_ids, carry_d, carry_i, corpus_p,
    cfg, q_tile, c_tile, m_corpus, variant,
):
    """Pallas batch step: the fused kernel in query mode, its result merged
    into the (all-inf) donated scratch — a bit-exact no-op merge whose sole
    purpose is giving the scratch buffers an output to alias (the serial
    and ring paths thread the scratch through the reduction naturally)."""
    from mpi_knn_tpu.backends.pallas_backend import _pallas_all_knn

    del query_ids  # query mode: queries carry no corpus identity
    d, i = _pallas_all_knn(
        queries_p, corpus_p, cfg, q_tile, c_tile, m_corpus, False, variant
    )
    return merge_topk(carry_d, carry_i, d, i, method="exact")


def _make_jits(fun, static_argnames):
    return {
        donate: jax.jit(
            fun,
            static_argnames=static_argnames,
            donate_argnums=(2, 3) if donate else (),
        )
        for donate in (False, True)
    }


def _serial_jits():
    from mpi_knn_tpu.backends.serial import serve_chunk

    return _make_jits(serve_chunk, ("cfg",))


def _ring_jits():
    from mpi_knn_tpu.backends.ring import ring_serve_sharded

    return _make_jits(
        ring_serve_sharded,
        ("cfg", "overlap", "mesh", "axis", "q_tile", "c_tile", "q_axis"),
    )


def _pallas_jits():
    return _make_jits(
        _pallas_serve_fn,
        ("cfg", "q_tile", "c_tile", "m_corpus", "variant"),
    )


def _ivf_jits():
    from mpi_knn_tpu.ivf.search import ivf_serve_chunk

    return _make_jits(ivf_serve_chunk, ("cfg", "nprobe"))


def _ivf_sharded_jits():
    # the sharded-clustered serve fn carries a THIRD donated scratch (the
    # per-shard exchange-stats vector) so its three outputs all alias
    # donated inputs — donate_argnums=(2, 3, 4), not the uniform (2, 3)
    from mpi_knn_tpu.ivf.sharded import ivf_sharded_serve_chunk

    return {
        donate: jax.jit(
            ivf_sharded_serve_chunk,
            static_argnames=(
                "cfg", "nprobe", "mesh", "axis", "shards", "route_cap"
            ),
            donate_argnums=(2, 3, 4) if donate else (),
        )
        for donate in (False, True)
    }


@functools.lru_cache(maxsize=None)
def _jits(backend: str):
    if backend == "serial":
        return _serial_jits()
    if backend in ("ring", "ring-overlap"):
        return _ring_jits()
    if backend == "pallas":
        return _pallas_jits()
    if backend == "ivf":
        return _ivf_jits()
    if backend == "ivf-sharded":
        return _ivf_sharded_jits()
    raise ValueError(f"no serving path for backend {backend!r}")


# ---------------------------------------------------------------------------
# Executable cache


@dataclasses.dataclass
class _BucketExec:
    """One AOT-compiled (bucket, config) cell plus everything a dispatch
    needs: padded row count, query tiling, and the run adapter state."""

    compiled: object  # jax.stages.Compiled
    bucket: int
    q_pad: int
    q_tile: int
    cfg: KNNConfig
    backend: str
    q_sharding: object | None = None  # ring: NamedSharding for query-side
    # the (q_pad,) all−1 query-id vector is identical for every batch of
    # this executable (serving queries carry no corpus identity) and is
    # NOT donated — built once here instead of re-uploaded per submit
    qids: jax.Array | None = None
    # ring/ivf-sharded only: a once-compiled carry initializer with the
    # query sharding as out_shardings — the scratch IS donated (fresh
    # buffers per batch), but building it on the default device and
    # resharding would pay an allocate-then-copy on every submit
    make_carry: object | None = None
    # ivf-sharded only: the resolved static route cap and the (static)
    # bytes its four all-to-alls move per batch — stamped into the
    # exchange-bytes counter without reading the device
    route_cap: int | None = None
    exchange_bytes: int | None = None
    # how this cell's executable came to exist: "compiled" (a real XLA
    # compile in this process) or "cache-hit" (revived from the
    # persistent AOT cache, zero XLA work) — warm() reports tally it
    source: str = "compiled"
    # static peak HBM of this executable (args + outputs − aliased +
    # temps, from PJRT's own memory_analysis at build time — zero
    # device reads, ISSUE 15): the serve_peak_hbm_bytes gauge, the
    # --report summary and /healthz index facts all read this figure;
    # 0 when the runtime could not answer (absent, never fake)
    peak_hbm_bytes: int = 0


def _acc_dtype(cfg: KNNConfig):
    return jnp.float64 if cfg.dtype == "float64" else jnp.float32


def _resident_args(index) -> tuple:
    """The index-side arguments of one batch program, in call order — the
    ONE place that order lives: the lowered builders, the dispatch path
    (``_run``) and the persistent-cache signature check all consume this,
    so the three can never drift. ``None`` entries (e.g. the scales array
    of an unquantized ring index) are empty pytree nodes that jax drops
    from the flattened argument list."""
    b = index.backend
    if b == "serial":
        return (index.tiles, index.tile_ids, index.tile_sqs)
    if b in ("ring", "ring-overlap"):
        return (index.corpus_sharded, index.corpus_ids_sharded,
                index.corpus_scales_sharded)
    if b == "pallas":
        return (index.corpus_padded,)
    # ivf / ivf-sharded share the clustered store layout
    return (index.centroids, index.centroid_sqs, index.buckets,
            index.bucket_ids, index.bucket_sqs, index.bucket_scales)


def _serial_bucket_shapes(index, cfg: KNNConfig, bucket: int):
    q_tile = min(cfg.query_tile, pad_to_multiple(bucket, 8))
    return pad_to_multiple(bucket, q_tile), q_tile


def _pallas_bucket_shapes(index, cfg: KNNConfig, bucket: int):
    q_tile = min(max(8, pad_to_multiple(cfg.query_tile, 8)), 512,
                 pad_to_multiple(bucket, 8))
    return pad_to_multiple(bucket, q_tile), q_tile


def _ring_bucket_shapes(index, cfg: KNNConfig, bucket: int):
    q_tile, q_pad = ring_query_shapes(index, cfg, bucket)
    return q_pad, q_tile


def _ivf_bucket_shapes(index, cfg: KNNConfig, bucket: int):
    from mpi_knn_tpu.ivf.search import ivf_query_shapes

    q_tile, q_pad = ivf_query_shapes(
        cfg, cfg.nprobe, index.bucket_cap, index.dim, bucket
    )
    return q_pad, q_tile


def _ivf_sharded_bucket_shapes(index, cfg: KNNConfig, bucket: int):
    from mpi_knn_tpu.ivf.sharded import sharded_query_shapes

    q_tile, q_pad, _ = sharded_query_shapes(
        cfg, cfg.nprobe, index.bucket_cap, index.dim, bucket, index.shards
    )
    return q_pad, q_tile


_BUCKET_SHAPES = {
    "serial": _serial_bucket_shapes,
    "ring": _ring_bucket_shapes,
    "ring-overlap": _ring_bucket_shapes,
    "pallas": _pallas_bucket_shapes,
    "ivf": _ivf_bucket_shapes,
    "ivf-sharded": _ivf_sharded_bucket_shapes,
}


def bucket_shapes(index, cfg: KNNConfig, bucket: int):
    """``(q_pad, q_tile)`` of one (bucket, config) cell — pure shape
    math, shared by the lowered builders below and the persistent-cache
    hit path (which must build a dispatchable :class:`_BucketExec`
    WITHOUT tracing or lowering anything)."""
    return _BUCKET_SHAPES[index.backend](index, cfg, bucket)


def expected_args(index, cfg: KNNConfig, bucket: int) -> list:
    """The flattened ``(shape, dtype)`` input signature the cell's
    executable must carry, derived from the same shape helpers and
    resident-arg order the lowering uses. The persistent AOT cache
    checks a loaded executable's ``args_info`` against this, so even a
    fingerprint collision cannot put a mismatched program on the
    dispatch path."""
    q_pad, q_tile = bucket_shapes(index, cfg, bucket)
    acc = str(jnp.dtype(_acc_dtype(cfg)))
    i32 = "int32"
    b = index.backend
    if b in ("serial", "ivf", "ivf-sharded"):
        qt = q_pad // q_tile
        qdt = str(jnp.dtype(cfg.dtype)) if b == "serial" else "float32"
        carry = acc if b == "serial" else "float32"
        args = [
            ((qt, q_tile, index.dim), qdt),
            ((qt, q_tile), i32),
            ((qt, q_tile, cfg.k), carry),
            ((qt, q_tile, cfg.k), i32),
        ]
        if b == "ivf-sharded":
            from mpi_knn_tpu.ivf.sharded import N_STATS

            args.append(((N_STATS * index.shards,), i32))
    else:
        qdt = "float32" if b == "pallas" else str(jnp.dtype(cfg.dtype))
        carry = "float32" if b == "pallas" else acc
        args = [
            ((q_pad, index.dim), qdt),
            ((q_pad,), i32),
            ((q_pad, cfg.k), carry),
            ((q_pad, cfg.k), i32),
        ]
    args.extend(
        (tuple(int(s) for s in a.shape), str(a.dtype))
        for a in _resident_args(index)
        if a is not None
    )
    return args


def _serial_lowered(index: CorpusIndex, cfg: KNNConfig, bucket: int):
    q_pad, q_tile = _serial_bucket_shapes(index, cfg, bucket)
    qt = q_pad // q_tile
    acc = _acc_dtype(cfg)
    dtype = jnp.dtype(cfg.dtype)
    sds = jax.ShapeDtypeStruct
    lowered = _jits("serial")[cfg.donate].lower(
        sds((qt, q_tile, index.dim), dtype),
        sds((qt, q_tile), jnp.int32),
        sds((qt, q_tile, cfg.k), acc),
        sds((qt, q_tile, cfg.k), jnp.int32),
        *_resident_args(index),
        cfg,
    )
    return lowered, q_pad, q_tile


def ring_query_shapes(index: CorpusIndex, cfg: KNNConfig, bucket: int):
    """Per-bucket query tiling against the index's FIXED corpus layout.

    ``ring_tiles`` would re-derive c_tile from the bucket's q_tile, but the
    resident corpus was padded once at build time — so here only the query
    side moves, and the per-step tile cap is honored by shrinking q_tile
    against the frozen c_tile (the cap stays hard either way)."""
    q_axis, axis, dp, ring_n = index.ring_meta
    num_dev = dp * ring_n
    q_tile = min(cfg.query_tile, -(-bucket // num_dev))
    while q_tile > 1 and q_tile * index.c_tile > cfg.max_tile_elems:
        q_tile = max(1, q_tile // 2)
    q_pad = pad_to_multiple(bucket, num_dev * q_tile)
    return q_tile, q_pad


def _ring_lowered(index: CorpusIndex, cfg: KNNConfig, bucket: int):
    from mpi_knn_tpu.backends.ring import _query_spec

    q_axis, axis, dp, ring_n = index.ring_meta
    q_tile, q_pad = ring_query_shapes(index, cfg, bucket)
    qsh = NamedSharding(index.mesh, _query_spec(q_axis, axis))
    acc = _acc_dtype(cfg)
    dtype = jnp.dtype(cfg.dtype)
    sds = jax.ShapeDtypeStruct
    lowered = _jits(index.backend)[cfg.donate].lower(
        sds((q_pad, index.dim), dtype, sharding=qsh),
        sds((q_pad,), jnp.int32, sharding=qsh),
        sds((q_pad, cfg.k), acc, sharding=qsh),
        sds((q_pad, cfg.k), jnp.int32, sharding=qsh),
        *_resident_args(index),
        cfg,
        index.backend == "ring-overlap",
        index.mesh,
        axis,
        q_tile,
        index.c_tile,
        q_axis=q_axis,
    )
    return lowered, q_pad, q_tile


def _pallas_lowered(index: CorpusIndex, cfg: KNNConfig, bucket: int):
    q_pad, q_tile = _pallas_bucket_shapes(index, cfg, bucket)
    variant = cfg.pallas_variant
    if variant == "sweep" and cfg.k > index.c_tile:
        variant = "tiles"  # same corner routing as all_knn_pallas
    sds = jax.ShapeDtypeStruct
    lowered = _jits("pallas")[cfg.donate].lower(
        sds((q_pad, index.dim), jnp.float32),
        sds((q_pad,), jnp.int32),
        sds((q_pad, cfg.k), jnp.float32),
        sds((q_pad, cfg.k), jnp.int32),
        *_resident_args(index),
        cfg,
        q_tile,
        index.c_tile,
        index.m,
        variant,
    )
    return lowered, q_pad, q_tile


def _ivf_lowered(index, cfg: KNNConfig, bucket: int):
    """Per-batch program for a clustered (IVF) index — same tiled layout
    and scratch-donation convention as the serial cell, with the resident
    arrays being the centroid table and the padded bucket store
    (``mpi_knn_tpu.ivf``). ``cfg.nprobe`` is concrete here
    (``IVFIndex.compatible_cfg`` resolves None to the tuned default)."""
    from mpi_knn_tpu.ivf.search import ivf_query_shapes

    nprobe = cfg.nprobe
    q_tile, q_pad = ivf_query_shapes(
        cfg, nprobe, index.bucket_cap, index.dim, bucket
    )
    qt = q_pad // q_tile
    sds = jax.ShapeDtypeStruct
    lowered = _jits("ivf")[cfg.donate].lower(
        sds((qt, q_tile, index.dim), jnp.float32),
        sds((qt, q_tile), jnp.int32),
        sds((qt, q_tile, cfg.k), jnp.float32),
        sds((qt, q_tile, cfg.k), jnp.int32),
        *_resident_args(index),
        cfg,
        nprobe,
    )
    return lowered, q_pad, q_tile


def _ivf_sharded_lowered(index, cfg: KNNConfig, bucket: int):
    """Per-batch program for a sharded clustered index — the routed
    two-stage search under shard_map, with the per-shard exchange stats
    as a third donated scratch (``ivf/sharded.py``)."""
    from mpi_knn_tpu.ivf.sharded import N_STATS, sharded_query_shapes

    nprobe = cfg.nprobe
    q_tile, q_pad, route_cap = sharded_query_shapes(
        cfg, nprobe, index.bucket_cap, index.dim, bucket, index.shards
    )
    qt = q_pad // q_tile
    qsh = NamedSharding(index.mesh, jax.sharding.PartitionSpec(index.axis))
    sds = jax.ShapeDtypeStruct
    lowered = _jits("ivf-sharded")[cfg.donate].lower(
        sds((qt, q_tile, index.dim), jnp.float32, sharding=qsh),
        sds((qt, q_tile), jnp.int32, sharding=qsh),
        sds((qt, q_tile, cfg.k), jnp.float32, sharding=qsh),
        sds((qt, q_tile, cfg.k), jnp.int32, sharding=qsh),
        sds((N_STATS * index.shards,), jnp.int32, sharding=qsh),
        *_resident_args(index),
        cfg,
        nprobe,
        index.mesh,
        index.axis,
        index.shards,
        route_cap,
    )
    return lowered, q_pad, q_tile


_LOWER_BUILDERS = {
    "serial": _serial_lowered,
    "ring": _ring_lowered,
    "ring-overlap": _ring_lowered,
    "pallas": _pallas_lowered,
    "ivf": _ivf_lowered,
    "ivf-sharded": _ivf_sharded_lowered,
}


def lower_bucket(index: CorpusIndex, cfg: KNNConfig, bucket: int):
    """The per-batch program for one (bucket, config) cell as a
    ``jax.stages.Lowered`` — the exact object the executable cache
    compiles, exposed so the lint engine (``analysis.lowering``) inspects
    production lowerings rather than a parallel reimplementation. Returns
    ``(lowered, q_pad, q_tile)``."""
    return _LOWER_BUILDERS[index.backend](index, cfg, bucket)


# donate_argnums of every serving function (the carry scratch); the lint
# engine's R5 reads this to know which parameters MUST carry an alias.
# The sharded-clustered fn adds the exchange-stats scratch as a third
# donated param so all three of its outputs alias donated inputs.
SCRATCH_PARAMS = (2, 3)
SHARDED_SCRATCH_PARAMS = (2, 3, 4)


def _fingerprint_cfg(cfg: KNNConfig) -> KNNConfig:
    """The cache fingerprint: the full config MINUS the host-only knobs
    that never reach ``lower_bucket`` (dispatch_depth paces the session;
    query_bucket only selects the bucket, which is a separate key
    component). Without this, changing the dispatch depth would recompile
    a bit-identical executable for every warm bucket. The live-mutation
    pacing knobs are host-only the same way: ``mutation_bucket`` only
    selects a mutation cell's bucket, the compact thresholds pace the
    background compactor, and ``bucket_headroom`` is a BUILD-time shape
    input whose effect the index facts already carry (``bucket_cap``) —
    none of them reach ``lower_bucket``."""
    return cfg.replace(
        dispatch_depth=1, query_bucket=1, mutation_bucket=1,
        bucket_headroom=0.0, compact_fill_threshold=1.0,
        compact_tombstone_fraction=1.0,
    )


# per-(index, cell) compile locks so a parallel warm pool (and a live
# dispatch racing it) compiles each distinct cell exactly once; the lock
# map lives on the index instance (``__dict__``-attached, like ``_cache``
# a per-index mutable) and the tiny module mutex only guards map access
_KEYLOCK_MUTEX = threading.Lock()


def _key_lock(index, key) -> threading.Lock:
    with _KEYLOCK_MUTEX:
        locks = index.__dict__.setdefault("_cache_key_locks", {})
        lk = locks.get(key)
        if lk is None:
            lk = locks[key] = threading.Lock()
        return lk


def mutation_lock(index) -> threading.Lock:
    """The per-index mutation lock (ISSUE 14): every live mutation
    (upsert/delete scatter, compact swap — ``serve.mutate``) and every
    batch dispatch (``_run``) serialize on it, so a query batch always
    runs against a CONSISTENT store — wholly before or wholly after any
    mutation, never an in-between (the donated in-place scatters would
    otherwise race the dispatch reading ``_resident_args``). Held only
    for the O(chunk) dispatch / O(1) swap, never across device waits.
    The lookup is lock-free after first creation (this sits on EVERY
    batch dispatch — funneling all sessions through the global mutex
    per batch would add a cross-index serialization point); the dict
    read is atomic under the GIL and the mutex only arbitrates the
    one-time creation."""
    lk = index.__dict__.get("_mutation_lock")
    if lk is None:
        with _KEYLOCK_MUTEX:
            lk = index.__dict__.setdefault(
                "_mutation_lock", threading.Lock()
            )
    return lk


def get_executable(
    index: CorpusIndex, cfg: KNNConfig, bucket: int
) -> _BucketExec:
    """The (bucket, config) executable, built at most once per index —
    revived from the persistent AOT cache when one is active
    (``serve.aotcache``; a hit skips trace, lowering AND the XLA compile),
    compiled otherwise. The frozen config is the fingerprint (host-only
    pacing knobs canonicalized out) — two configs differing in any field
    that reaches the lowering (k, topk method, precision policy,
    donation, …) occupy distinct cells and can never serve each other's
    programs; the on-disk key extends the same fingerprint with the index
    facts, platform topology, and jax version (``aotcache.fingerprint``).
    Thread-safe per cell: concurrent callers of the same cell serialize
    on a per-key lock (one compile), distinct cells build in parallel
    (the warm pool's whole point)."""
    key = (bucket, _fingerprint_cfg(cfg))
    exec_ = index._cache.get(key)
    if exec_ is not None:
        return exec_
    with _key_lock(index, key):
        exec_ = index._cache.get(key)
        if exec_ is None:
            exec_ = _build_executable(index, cfg, bucket)
            index._cache[key] = exec_
    return exec_


def _build_executable(
    index: CorpusIndex, cfg: KNNConfig, bucket: int
) -> _BucketExec:
    from mpi_knn_tpu.serve import aotcache

    # the central compile capture must be live BEFORE the compile it
    # is supposed to count (idempotent; jax is already imported here)
    obs_metrics.install_jax_compile_listener()
    disk = aotcache.active_cache()
    cache_mode = "off"
    sid = obs_spans.begin_span(
        "compile", cat="compile", bucket=bucket, backend=index.backend,
        policy=cfg.precision_policy,
    )
    try:
        compiled = None
        fp = None
        if disk is not None:
            # the signature check rebuilds the cell's argspec from pure
            # shape math — a hit never lowers anything
            fp = aotcache.fingerprint(index, cfg, bucket)
            compiled = disk.load(
                fp, expect_args=expected_args(index, cfg, bucket)
            )
            cache_mode = "hit" if compiled is not None else "miss"
        if compiled is not None:
            q_pad, q_tile = bucket_shapes(index, cfg, bucket)
        else:
            lowered, q_pad, q_tile = lower_bucket(index, cfg, bucket)
            compiled = lowered.compile()
            if disk is not None:
                # best-effort (a full disk must not fail serving); meta
                # carries the readable fingerprint for doctor/forensics
                disk.store(
                    fp, compiled,
                    meta=aotcache.fingerprint_facts(index, cfg, bucket),
                )
        exec_ = _finish_executable(
            index, cfg, bucket, compiled, q_pad, q_tile,
            source="cache-hit" if cache_mode == "hit" else "compiled",
        )
    except Exception as e:
        # a raised lowering/compile failure is survivable by the
        # caller — close the span with the error; an OPEN compile
        # span must stay what the contract says: a kill diagnosis
        obs_spans.end_span(sid, error=type(e).__name__)
        raise
    obs_spans.end_span(sid, cache=cache_mode)
    reg = obs_metrics.get_registry()
    if exec_.source == "cache-hit":
        reg.counter(
            "serve_executables_loaded_total",
            help="(bucket, config) cells revived from the persistent AOT "
            "cache (zero XLA compiles)",
        ).inc()
    else:
        reg.counter(
            "serve_executables_compiled_total",
            help="(bucket, config) cells compiled by the serve cache",
        ).inc()
    # compression-ladder gauges, stamped at LOWER time (pure shape
    # math, no device reads — the sharded exchange-bytes precedent):
    # the 2×/4×/8× byte cuts of bf16/int8 transfer and bf16/int8/int4
    # at-rest stores are visible in `mpi-knn metrics` / `--report`
    # next to the recall they paid.
    if index.backend in ("ring", "ring-overlap"):
        from mpi_knn_tpu.backends.ring import ring_wire_bytes_per_batch

        ring_n = index.ring_meta[3]
        reg.gauge(
            "ring_transfer_wire_bytes",
            help="bytes one batch's full corpus rotation moves over "
            "the interconnect, at the wire dtype (static per "
            "executable)",
        ).set(ring_wire_bytes_per_batch(
            cfg, index.corpus_sharded.shape[0], index.dim, ring_n,
        ))
    if index.backend in ("ivf", "ivf-sharded"):
        reg.gauge(
            "ivf_at_rest_bytes",
            help="resident bytes of the clustered bucket store "
            "(codes + scales for quantized stores)",
        ).set(index.nbytes_resident)
    # peak-HBM gauge (ISSUE 15): the max static peak across this
    # index's built cells, from the executables' own buffer assignment
    # — the ledger's figure for the production shapes, stamped at
    # build time with zero device reads (the wire-gauge precedent)
    reg.gauge(
        "serve_peak_hbm_bytes",
        help="static peak live bytes of the largest built serve "
        "executable (args + outputs − aliased + temps, from PJRT "
        "memory_analysis at build time)",
    ).set(max(exec_.peak_hbm_bytes, index_peak_hbm_bytes(index)))
    return exec_


def _finish_executable(
    index, cfg: KNNConfig, bucket: int, compiled, q_pad: int, q_tile: int,
    source: str,
) -> _BucketExec:
    """Wrap a ready executable (freshly compiled OR revived from disk)
    with the dispatch-side state every batch needs — query shardings,
    the constant query-id vector, the carry initializer, the sharded
    exchange accounting. All of it is shape math and small device
    constants, none of it needs the lowering."""
    qsh = None
    route_cap = exchange_bytes = None
    if index.backend in ("ring", "ring-overlap"):
        from mpi_knn_tpu.backends.ring import _query_spec

        q_axis = index.ring_meta[0]
        qsh = NamedSharding(
            index.mesh, _query_spec(q_axis, index.ring_meta[1])
        )
    # the constant query-id vector is built in numpy and device_put (a
    # transfer, never an XLA program): on a persistent-cache hit the
    # whole cell build must count ZERO backend compiles, and an eager
    # jnp.full here would compile a tiny fill executable
    qids = jax.device_put(np.full((q_pad,), -1, np.int32))
    make_carry = None
    if qsh is not None:
        qids = jax.device_put(np.full((q_pad,), -1, np.int32), qsh)
        make_carry = jax.jit(
            functools.partial(
                init_topk, q_pad, cfg.k, dtype=_acc_dtype(cfg)
            ),
            out_shardings=(qsh, qsh),
        )
    if index.backend == "ivf-sharded":
        from jax.sharding import PartitionSpec
        from mpi_knn_tpu.ivf.sharded import (
            exchange_bytes_per_tile,
            exchange_wire_args,
            scratch_maker,
            sharded_query_shapes,
        )

        qsh = NamedSharding(index.mesh, PartitionSpec(index.axis))
        qt = q_pad // q_tile
        _, _, route_cap = sharded_query_shapes(
            cfg, cfg.nprobe, index.bucket_cap, index.dim, bucket,
            index.shards,
        )
        wire_dim, wire_itemsize, wire_scale = exchange_wire_args(
            index
        )
        exchange_bytes = qt * exchange_bytes_per_tile(
            index.shards, route_cap, index.bucket_cap, wire_dim,
            wire_itemsize, wire_scale,
        )
        qids = jax.device_put(
            np.full((qt, q_tile), -1, np.int32), qsh
        )
        make_carry = scratch_maker(
            qt, q_tile, cfg.k, index.shards, index.mesh, index.axis
        )
    # the executable's static peak HBM (ISSUE 15) — PJRT answers from
    # the compiled binary's own buffer assignment, so the figure costs
    # zero device reads and is identical for a fresh compile and an
    # AOT-cache revival of the same program
    from mpi_knn_tpu.analysis.memory import pjrt_memory_stats

    stats = pjrt_memory_stats(compiled)
    return _BucketExec(
        compiled, bucket, q_pad, q_tile, cfg, index.backend,
        q_sharding=qsh, qids=qids, make_carry=make_carry,
        route_cap=route_cap, exchange_bytes=exchange_bytes,
        source=source,
        peak_hbm_bytes=stats["peak_bytes"] if stats else 0,
    )


def index_peak_hbm_bytes(index) -> int:
    """The serving peak-HBM figure of one index: the max static peak
    across its built executables (any of them may run; the binding one
    is the worst). Zero before the first cell builds — absent, never a
    fake measurement. Reads the cell cache lock-free like the dispatch
    path does (values are immutable once inserted)."""
    return max(
        # mutation cells share the dict as raw Compiled objects
        # (serve.mutate) and carry no batch-peak figure — they read 0
        (getattr(e, "peak_hbm_bytes", 0)
         for e in list(index._cache.values())),
        default=0,
    )


# ---------------------------------------------------------------------------
# Batch preparation and dispatch


def _prep_queries(index: CorpusIndex, cfg: KNNConfig, exec_: _BucketExec, q):
    """Center + pad one batch to the executable's padded row count and move
    it on device, engine-owned. Host batches are centered/padded in numpy
    (one H2D of a bucket-stable shape — no per-raw-size device programs);
    device batches stay on device (ops cached per raw shape after first
    sight). Returns (q2d, qids, rows)."""
    rows = q.shape[0]
    if rows > exec_.q_pad:
        raise ValueError(
            f"batch of {rows} rows exceeds the executable's bucket "
            f"({exec_.q_pad} padded rows)"
        )
    # an IVF index's dtype is the bucket store's AT-REST width; its search
    # computes (and takes queries) in f32 — bf16-rounding the queries here
    # would silently change the math vs the one-shot search_ivf path
    dtype = (
        jnp.float32 if exec_.backend in ("ivf", "ivf-sharded")
        else jnp.dtype(cfg.dtype)
    )
    on_device = isinstance(q, jax.Array)
    if cfg.center and cfg.metric == "l2" and index.mu is not None:
        # same op order as all_knn's center_for_l2 on each residency, so
        # serving stays bit-identical to the one-shot API
        q = q - index.mu if (on_device or isinstance(index.mu, jax.Array)) \
            else np.asarray(q) - index.mu
        on_device = isinstance(q, jax.Array)
    if exec_.backend == "ivf-sharded":
        # tiles shaped on host when possible (one H2D straight onto the
        # query sharding, zero per-shape reshape programs); a device
        # batch pays a shard-local reshape op, cached per bucket shape
        qt = exec_.q_pad // exec_.q_tile
        if on_device:
            q3 = pad_rows_any(q, exec_.q_pad, dtype=dtype).reshape(
                qt, exec_.q_tile, index.dim
            )
        else:
            qh = np.asarray(q, dtype=dtype)
            q3 = np.pad(qh, ((0, exec_.q_pad - rows), (0, 0))).reshape(
                qt, exec_.q_tile, index.dim
            )
        return jax.device_put(q3, exec_.q_sharding), exec_.qids, rows
    if on_device:
        q2d = pad_rows_any(q, exec_.q_pad, dtype=dtype)
        if exec_.q_sharding is not None:
            q2d = jax.device_put(q2d, exec_.q_sharding)
    else:
        qh = np.asarray(q)
        pad = exec_.q_pad - rows
        if pad:
            qh = np.pad(qh, ((0, pad), (0, 0)))
        if exec_.q_sharding is not None:
            # one transfer, straight onto the ring sharding: casting on
            # host first avoids the default-device upload that a
            # jnp.asarray → device_put resharding pair would pay twice
            q2d = jax.device_put(qh.astype(dtype), exec_.q_sharding)
        else:
            q2d = jnp.asarray(qh, dtype=dtype)
    return q2d, exec_.qids, rows


def _run(index: CorpusIndex, cfg: KNNConfig, exec_: _BucketExec, q2d, qids):
    """Issue one padded batch on the compiled executable; returns padded
    ((q_pad, k) dists, ids, exchange_stats-or-None) device results
    (async — not synchronized here). The stats slot is populated only by
    the sharded-clustered backend (its per-shard (N_STATS·S,) vector).
    Dispatch serializes with live mutation on the per-index mutation
    lock — the resident args are read and the batch enqueued as one
    atomic step w.r.t. any in-place store update."""
    with mutation_lock(index):
        return _run_locked(index, cfg, exec_, q2d, qids)


def _run_locked(index, cfg: KNNConfig, exec_: _BucketExec, q2d, qids):
    acc = _acc_dtype(cfg)
    if exec_.backend == "serial":
        qt = exec_.q_pad // exec_.q_tile
        carry_d, carry_i = init_topk_tiles(qt, exec_.q_tile, cfg.k, dtype=acc)
        d, i = exec_.compiled(
            q2d.reshape(qt, exec_.q_tile, index.dim),
            qids.reshape(qt, exec_.q_tile),
            carry_d,
            carry_i,
            *_resident_args(index),
        )
        return (
            d.reshape(exec_.q_pad, cfg.k),
            i.reshape(exec_.q_pad, cfg.k),
            None,
        )
    if exec_.backend == "ivf":
        qt = exec_.q_pad // exec_.q_tile
        carry_d, carry_i = init_topk_tiles(
            qt, exec_.q_tile, cfg.k, dtype=jnp.float32
        )
        d, i = exec_.compiled(
            q2d.reshape(qt, exec_.q_tile, index.dim),
            qids.reshape(qt, exec_.q_tile),
            carry_d,
            carry_i,
            *_resident_args(index),
        )
        return (
            d.reshape(exec_.q_pad, cfg.k),
            i.reshape(exec_.q_pad, cfg.k),
            None,
        )
    if exec_.backend == "ivf-sharded":
        # q2d arrives pre-tiled (QT, q_tile, d) on the query sharding
        carry_d, carry_i, stats0 = exec_.make_carry()
        d, i, stats = exec_.compiled(
            q2d, qids, carry_d, carry_i, stats0, *_resident_args(index),
        )
        return (
            d.reshape(exec_.q_pad, cfg.k),
            i.reshape(exec_.q_pad, cfg.k),
            stats,
        )
    if exec_.backend in ("ring", "ring-overlap"):
        # scratch born directly under the query sharding (no allocate-
        # then-reshard per batch); fresh buffers every call because the
        # executable consumes them (donation)
        carry_d, carry_i = exec_.make_carry()
        d, i = exec_.compiled(
            q2d, qids, carry_d, carry_i, *_resident_args(index),
        )
        return d, i, None
    carry_d, carry_i = init_topk(exec_.q_pad, cfg.k, dtype=acc)
    d, i = exec_.compiled(
        q2d, qids, carry_d, carry_i, *_resident_args(index)
    )
    return d, i, None


@dataclasses.dataclass
class BatchResult:
    """One served batch: padded device results plus the real row count.
    ``dists``/``ids`` strip the padding on host (no per-raw-size device
    program in the steady-state path), fetching the device buffer once —
    repeated attribute access must not re-pay the padded D2H transfer.

    The resilience fields are the per-batch record the degradation
    machinery stamps (``None``/zero when the session has no policy):
    ``degraded`` names the ladder rung the batch was DISPATCHED under
    (``None`` = the configured full rung — the PR 4 ``"degraded"`` marker
    convention), ``retries``/``backoffs`` the transient-failure retry
    story, and ``deadline_breached`` whether this batch's measured
    latency overran the policy's per-batch deadline."""

    dists_padded: jax.Array
    ids_padded: jax.Array
    rows: int
    bucket: int
    latency_s: float | None = None  # filled by the session at sync time
    seq: int = 0  # 0-indexed session-order batch number (provenance —
    # the same number the serve CLI prints on the batch's latency line)
    degraded: str | None = None  # ladder rung label, None = full
    retries: int = 0
    backoffs: tuple = ()
    deadline_breached: bool = False
    # multi-tenant composition of a coalesced batch (the serving front
    # end, mpi_knn_tpu.frontend): ((tenant, rows), ...) in row order,
    # summing to ``rows``; None = an unattributed legacy batch. The
    # session's per-tenant accumulators and the per-tenant registry
    # counters are fed from this at retire.
    tenants: tuple | None = None
    # sharded-clustered batches only: the device (N_STATS·S,) exchange
    # stats vector (routed/dropped/served per shard) + the executable's
    # static per-batch exchange bytes
    stats_padded: jax.Array | None = None
    exchange_bytes: int | None = None

    @functools.cached_property
    def dists(self) -> np.ndarray:
        return np.asarray(jax.device_get(self.dists_padded))[: self.rows]

    @functools.cached_property
    def ids(self) -> np.ndarray:
        return np.asarray(jax.device_get(self.ids_padded))[: self.rows]

    @functools.cached_property
    def exchange(self) -> np.ndarray | None:
        """Per-shard (S, N_STATS) [routed, dropped, served] exchange
        stats of a sharded-clustered batch (None elsewhere). Counts are
        over the PADDED batch — bucket padding rows route like real
        rows, deterministically."""
        if self.stats_padded is None:
            return None
        from mpi_knn_tpu.ivf.sharded import N_STATS

        return np.asarray(
            jax.device_get(self.stats_padded)
        ).reshape(-1, N_STATS)


def query_knn(
    queries,
    index: CorpusIndex,
    config: KNNConfig | None = None,
    **overrides,
) -> KNNResult:
    """One-shot query batch against a resident index (the serving analogue
    of ``all_knn(corpus, queries=...)``): bucket, fetch-or-compile the
    executable, dispatch, and return (q, k) results with padding stripped.

    Results are fetched to HOST: stripping a ragged batch's padding on
    device (``d[:rows]``) would trace a fresh slice program per distinct
    raw batch size — exactly the per-shape compile churn the bucket cache
    exists to eliminate — so the strip happens in numpy, like
    ``BatchResult``. Steady-state calls at a warm bucket therefore
    compile nothing for ANY batch size; callers that want padded
    device-resident results use :class:`ServeSession`.
    """
    cfg = index.compatible_cfg(
        (config or index.cfg).replace(**overrides)
    )
    nq = queries.shape[0]
    bucket = bucket_rows(nq, cfg.query_bucket)
    exec_ = get_executable(index, cfg, bucket)
    q2d, qids, rows = _prep_queries(index, cfg, exec_, queries)
    d, i, stats = _run(index, cfg, exec_, q2d, qids)
    if stats is not None:
        _count_exchange(stats, exec_.exchange_bytes)
    return KNNResult(
        dists=np.asarray(jax.device_get(d))[:rows],
        ids=np.asarray(jax.device_get(i))[:rows],
    )


def _count_exchange(stats, exchange_bytes: int | None,
                    registry=None) -> np.ndarray:
    """Stamp one sharded batch's candidate-exchange story into the
    metrics registry: routed candidate rows (histogram + counter),
    probe-cap overflow drops (counter — a nonzero here is recall being
    spent on routing skew), and the static exchange bytes. Returns the
    per-shard (S, N_STATS) array for callers that also want it."""
    from mpi_knn_tpu.ivf.sharded import N_STATS

    reg = registry or obs_metrics.get_registry()
    per_shard = np.asarray(jax.device_get(stats)).reshape(-1, N_STATS)
    routed = int(per_shard[:, 0].sum())
    dropped = int(per_shard[:, 1].sum())
    reg.counter(
        "serve_exchange_routed_total",
        help="probe routes exchanged between shards (padded batches)",
    ).inc(routed)
    reg.histogram(
        "serve_exchange_routed_per_batch",
        help="probe routes exchanged per sharded batch",
    ).observe(routed)
    reg.counter(
        "serve_exchange_overflow_dropped_total",
        help="probes dropped at the static per-shard route cap "
        "(counted recall loss, never wrong answers)",
    ).inc(dropped)
    if exchange_bytes:
        reg.counter(
            "serve_exchange_bytes_total",
            help="bytes moved by the candidate-exchange all-to-alls "
            "(static per executable)",
        ).inc(exchange_bytes)
    return per_shard


class ServeSession:
    """Bounded dispatch-ahead serving over one index.

    ``submit`` dispatches a batch and returns any batches whose results it
    had to retire to respect ``dispatch_depth``; ``drain`` retires the
    rest. With depth ≥ 2 the next batch's preparation/H2D overlaps the
    previous batch's device compute (double buffering). Latency per batch
    is dispatch→``device_sync`` — the honest number under async dispatch.

    Sessions are REUSABLE across streams: ``stream``/``submit``+``drain``
    may be called any number of times over one session, the executable
    cache stays warm across streams (zero recompiles on the second
    stream), and ``seq`` keeps counting monotonically so batch provenance
    never aliases between streams. ``latencies``/``queries_served``/
    ``tenant_stats``/``exchange`` accumulate until ``reset_stats()``: a
    long-lived server should reset per reporting window (one float per
    batch adds up over millions of batches). See ``reset_stats`` for the
    exact window semantics (in-flight batches land in the NEW window).

    Multi-tenant attribution (the serving front end's contract): a
    coalesced batch submitted with ``tenants=((tenant, rows), ...)``
    stamps its composition on the batch span and, at retire, feeds
    ``tenant_stats`` — per tenant: served query rows, batches touched,
    latency sum/max, and (sharded-clustered sessions) a rows-proportional
    share of the routed candidate exchange — plus the labeled
    ``serve_tenant_queries_total{tenant=...}`` registry counters, so
    per-tenant reporting is first-class state, never reconstructed from
    deltas of the global accumulators.

    With a :class:`~mpi_knn_tpu.resilience.ladder.ResiliencePolicy` the
    session additionally enforces a per-batch deadline (measured at
    retire — the same dispatch→sync latency it already reports), retries
    transiently-failing dispatches with bounded exponential backoff,
    trips a NaN/all-inf sentinel on every retired batch's top-k (loudly:
    :class:`PoisonedResultError` with full batch provenance), and on
    ``degrade_after`` CONSECUTIVE deadline breaches sheds load one rung
    down the explicit degradation ladder (smaller ``nprobe`` →
    ``precision_policy="mixed"`` → smaller bucket — see
    ``resilience/ladder.py`` for why each rung is recall-safe). Every
    rung is an ordinary (bucket, config) cell of the executable cache;
    every degradation is stamped into the batch records
    (``BatchResult.degraded``) and the ``degradations`` event list.
    ``resilience=None`` (default) is the zero-overhead legacy behavior.
    """

    def __init__(
        self,
        index: CorpusIndex,
        config: KNNConfig | None = None,
        resilience: ResiliencePolicy | None = None,
        **overrides,
    ):
        self.index = index
        self.cfg = index.compatible_cfg(
            (config or index.cfg).replace(**overrides)
        )
        # observability: every session feeds the shared registry (the
        # compile capture must be live before warm()'s first compile)
        obs_metrics.install_jax_compile_listener()
        self._metrics = obs_metrics.get_registry()
        self.policy = resilience
        if resilience is not None:
            self.ladder = build_ladder(index, self.cfg, resilience)
        else:
            self.ladder = [(FULL_RUNG, self.cfg)]
        self._rung = 0
        self._consecutive_breaches = 0
        self._seq = 0
        # cold-start readiness (ISSUE 12): warm() publishes per-cell
        # progress here — /healthz's warming block and the front end's
        # per-bucket admission read it (possibly from other threads)
        self._warm_lock = threading.Lock()
        self.warm_state: dict = {"total": 0, "ready": 0, "done": True}
        self.warm_report: dict | None = None
        # window accumulators + current rung are read cross-thread (the
        # front end's /healthz handler threads via stats_snapshot, the
        # scheduler's shed/restore callbacks) while the dispatch pump
        # mutates them at retire — one lock guards them all (host-lint
        # H1 guard map: serve.engine.ServeSession). The in-flight deque
        # and seq counter stay pump-confined: the session has exactly
        # one dispatching caller by contract.
        self._stats_lock = threading.Lock()
        self._inflight: collections.deque = collections.deque()
        self.latencies: list[float] = []
        self.queries_served = 0
        self.degradations: list[dict] = []  # rung-shed events, in order
        self.restorations: list[dict] = []  # rung-restore events, in order
        self.retries_total = 0
        self.deadline_breaches = 0
        # per-tenant window accumulators (fed by batches submitted with a
        # ``tenants`` composition): tenant -> {queries, batches,
        # latency_sum_s, latency_max_s[, routed]}
        self.tenant_stats: dict[str, dict] = {}
        # live-mutation window accumulators (ISSUE 14): rows upserted/
        # tombstoned through this session + compaction passes — guarded
        # by _stats_lock like every other window stat; the index-level
        # occupancy truth lives on the freelist (serve.mutate)
        self.mutation_stats: dict[str, int] = {
            "upserts": 0, "deletes": 0, "calls": 0, "compactions": 0,
        }
        self._compactor = None
        # sharded-clustered sessions accumulate the candidate-exchange
        # story (routed/dropped totals, static exchange bytes, per-shard
        # served-request load) for the CLI report; None elsewhere
        self.exchange: dict | None = None
        if getattr(index, "backend", None) == "ivf-sharded":
            self.exchange = {
                "shards": index.shards,
                "routed_total": 0,
                "dropped_total": 0,
                "exchange_bytes_total": 0,
                "served_per_shard": [0] * index.shards,
            }

    @property
    def rung(self) -> str:
        """The ladder rung new submissions dispatch under."""
        with self._stats_lock:
            return self.ladder[self._rung][0]

    def warm_snapshot(self) -> dict:
        """A consistent copy of ``warm_state`` for cross-thread readers
        (the /healthz handler, the front end's warming admission) —
        ``dict(session.warm_state)`` outside the warm lock raced the
        pool threads' per-cell updates (a dict being replaced AND
        mutated while iterated)."""
        with self._warm_lock:
            return dict(self.warm_state)

    def stats_snapshot(self) -> dict:
        """The serving-posture counters read from other threads (the
        front end's /healthz), in ONE critical section — reading the
        raw attributes while the dispatch pump retires a batch tears
        (e.g. ``sorted(tenant_stats)`` raises mid-rehash, queries_served
        disagrees with batches_retired)."""
        with self._stats_lock:
            return {
                "batches_retired": len(self.latencies),
                "queries_served": self.queries_served,
                "retries_total": self.retries_total,
                "deadline_breaches": self.deadline_breaches,
                "rung": self.ladder[self._rung][0],
                "tenants": sorted(self.tenant_stats),
                "mutation": dict(self.mutation_stats),
                # static peak HBM of the largest built cell (ISSUE 15;
                # a lock-free cache read — no new lock edge from here)
                "peak_hbm_bytes": index_peak_hbm_bytes(self.index),
            }

    def warm(self, sizes, parallel: int | None = None,
             progress=None) -> dict:
        """Pre-build the executables for the given batch sizes — at
        EVERY ladder rung, not just the configured one: the first batch
        after a degradation lands at the moment of overload, and a cold
        compile there would itself breach the deadline and cascade the
        session further down the ladder on compile latency, not load.

        Cold-start machinery (ISSUE 12):

        - cells are DEDUPED by executable fingerprint before anything
          lowers — rungs whose frozen config resolves to an identical
          program at the same bucket (e.g. the ``bucket/2`` rung when a
          size pads to the same row count) occupy one cell, so the
          dedupe saves compiles even with the persistent cache disabled;
        - distinct cells build across a thread pool (XLA releases the
          GIL during compilation; ``parallel=None`` sizes the pool to
          min(cells, cpu count), ``parallel=1`` forces the old
          sequential walk). Per-cell "compile" spans carry a ``cache``
          attr (hit/miss/off) and the aot hit/miss counters land in the
          registry, so a warm's cache story is machine-readable;
        - per-cell progress feeds ``warm_state`` (ready / total — the
          ``/healthz`` warming block) and the optional
          ``progress(ready, total, bucket)`` callback, called from pool
          threads as each executable lands.

        Returns a report: ``{cells, raw_cells, deduped, compiled,
        loaded, reused, wall_s}`` where ``loaded`` counts cells revived
        from the persistent AOT cache and ``reused`` cells that were
        already in memory before this warm."""
        t0 = time.perf_counter()
        raw: list = []
        for n in sizes:
            for _, cfg in self.ladder:
                raw.append((bucket_rows(n, cfg.query_bucket), cfg))
        distinct: dict = {}
        for bucket, cfg in raw:
            distinct.setdefault((bucket, _fingerprint_cfg(cfg)),
                                (bucket, cfg))
        cells = list(distinct.values())
        total = len(cells)
        with self._warm_lock:
            self.warm_state = {"total": total, "ready": 0, "done": False}
        workers = (
            max(1, min(total, os.cpu_count() or 1))
            if parallel is None else max(1, parallel)
        )

        def _one(cell):
            bucket, cfg = cell
            existed = (bucket, _fingerprint_cfg(cfg)) in self.index._cache
            exec_ = get_executable(self.index, cfg, bucket)
            with self._warm_lock:
                self.warm_state["ready"] += 1
                ready = self.warm_state["ready"]
            if progress is not None:
                progress(ready, total, bucket)
            return existed, exec_

        with obs_spans.span("warm", cat="serve", sizes=list(sizes),
                            rungs=len(self.ladder), cells=total,
                            deduped=len(raw) - total, workers=workers):
            if workers <= 1 or total <= 1:
                built = [_one(c) for c in cells]
            else:
                with concurrent.futures.ThreadPoolExecutor(
                    max_workers=workers,
                    thread_name_prefix="tknn-warm",
                ) as pool:
                    built = list(pool.map(_one, cells))
        with self._warm_lock:
            self.warm_state["done"] = True
        report = {
            "cells": total,
            "raw_cells": len(raw),
            "deduped": len(raw) - total,
            "reused": sum(1 for existed, _ in built if existed),
            "loaded": sum(
                1 for existed, e in built
                if not existed and e.source == "cache-hit"
            ),
            "compiled": sum(
                1 for existed, e in built
                if not existed and e.source == "compiled"
            ),
            "wall_s": round(time.perf_counter() - t0, 4),
        }
        self.warm_report = report
        return report

    def warm_async(self, sizes, parallel: int | None = None,
                   progress=None) -> threading.Thread:
        """Run :meth:`warm` on a background thread (the serve CLI's
        bind-the-port-first startup): returns the started daemon thread;
        ``warm_state``/``bucket_ready`` expose progress to ``/healthz``
        and the front end's per-bucket admission while it runs."""
        t = threading.Thread(
            target=self.warm, args=(sizes, parallel, progress),
            name="tknn-warm-async", daemon=True,
        )
        t.start()
        return t

    def bucket_ready(self, rows: int) -> bool:
        """Whether a batch of exactly ``rows`` rows would dispatch on an
        already-built executable at the CURRENT ladder rung."""
        _, cfg = self._current_rung()
        key = (bucket_rows(max(1, rows), cfg.query_bucket),
               _fingerprint_cfg(cfg))
        return key in self.index._cache

    def coalesced_ready(self, rows: int, max_rows: int) -> bool:
        """The front end's per-bucket admission signal while warming: a
        request of ``rows`` rows admitted into a coalescer that fills up
        to ``max_rows`` can land in ANY power-of-two bucket between its
        own and the fill target's — gating on the request's own bucket
        alone would let admitted requests coalesce into a larger, still-
        cold bucket and compile inline on the dispatch pump (exactly the
        stall the 503 "warming" refusal exists to prevent). True iff
        every bucket in that span is built at the current rung."""
        _, cfg = self._current_rung()
        fp = _fingerprint_cfg(cfg)
        b = bucket_rows(max(1, rows), cfg.query_bucket)
        top = bucket_rows(max(1, max(rows, max_rows)), cfg.query_bucket)
        while True:
            if (b, fp) not in self.index._cache:
                return False
            if b >= top:
                return True
            b *= 2

    def reset_stats(self) -> None:
        """Start a fresh measurement window. The exact contract (tested
        in ``tests/test_serve.py`` — the front end's per-tenant reporting
        leans on it):

        - resets the WINDOW accumulators: ``latencies``,
          ``queries_served``, ``retries_total``, ``deadline_breaches``,
          ``tenant_stats``, and the sharded ``exchange`` totals;
        - does NOT reset serving identity or position: ``seq`` keeps
          counting (batch provenance stays unique across windows), the
          executable cache stays warm (a reset never costs a recompile),
          and the ladder keeps its current rung with its
          ``degradations``/``restorations`` history (shedding is a
          serving condition, not a statistic of one window);
        - in-flight batches keep their dispatch timestamps and land in
          the NEW window at retire — a window boundary never drops or
          double-counts a batch, it only decides which window's
          percentile the batch feeds.
        """
        with self._stats_lock:
            self.latencies = []
            self.queries_served = 0
            self.retries_total = 0
            self.deadline_breaches = 0
            self.tenant_stats = {}
            self.mutation_stats = {
                "upserts": 0, "deletes": 0, "calls": 0, "compactions": 0,
            }
            if self.exchange is not None:
                # the candidate-exchange story is part of the window:
                # totals spanning a warm-up batch would overstate routed
                # volume
                self.exchange.update(
                    routed_total=0,
                    dropped_total=0,
                    exchange_bytes_total=0,
                    served_per_shard=[0] * self.exchange["shards"],
                )

    def _current_rung(self) -> tuple:
        """(label, cfg) of the rung new work dispatches under — one
        locked read of ``_rung`` (mutated by shed/restore, possibly from
        the scheduler's overload callback while a handler thread asks
        ``bucket_ready``)."""
        with self._stats_lock:
            return self.ladder[self._rung]

    def _check_sentinel(self, res: BatchResult) -> None:
        """NaN/all-inf sentinel on a retired batch's REAL rows. NaN in a
        top-k distance has exactly one source — a poisoned distance tile
        (fp distances are sums of squares; the masks use +inf) — and an
        all-inf row means every candidate was masked away. Neither may be
        returned as an answer or dropped silently: trip loudly, with the
        provenance an operator needs to find the batch."""
        d = res.dists  # strips padding; cached, so retire pays D2H once
        bad_nan = bool(np.isnan(d).any())
        bad_inf = bool(d.size) and bool(np.isinf(d).all(axis=1).any())
        if bad_inf and not bad_nan and res.exchange is not None \
                and res.exchange[:, 1].sum() > 0:
            # sharded batch under probe-cap overflow: a query whose every
            # probe was dropped legitimately retires all-inf — that is
            # the DOCUMENTED graceful recall loss (counted per shard in
            # the exchange stats and the overflow-drop counter), not a
            # poisoned tile. NaN still trips unconditionally.
            bad_inf = False
        if bad_nan or bad_inf:
            kind = "NaN" if bad_nan else "all-inf row"
            obs_spans.event(
                "poisoned-result", cat="serve", seq=res.seq,
                kind=kind, bucket=res.bucket,
            )
            self._metrics.counter(
                "serve_poisoned_results_total",
                help="batches whose top-k tripped the NaN/all-inf sentinel",
            ).inc()
            raise PoisonedResultError(
                f"poisoned top-k ({kind}) in served batch seq={res.seq} "
                f"bucket={res.bucket} rows={res.rows} "
                f"rung={res.degraded or FULL_RUNG}",
                batch_seq=res.seq,
                bucket=res.bucket,
                rung=res.degraded or FULL_RUNG,
                rows=res.rows,
            )

    def _note_latency(self, res: BatchResult) -> None:
        """Deadline accounting at retire time: count CONSECUTIVE breaches
        and shed one ladder rung when the policy's patience runs out. A
        single slow batch (compile, GC pause) never degrades; a breach
        streak does, and the event is recorded. Retry backoff sleeps are
        EXCLUDED from the comparison (``latency_s`` itself stays the
        honest dispatch→sync total): backoff is self-inflicted waiting on
        a transient fault, not load — counting it would let two transport
        blips walk the one-way ladder and spend recall on a problem the
        ladder's smaller programs cannot fix."""
        pol = self.policy
        if pol is None or pol.batch_deadline_s is None:
            return
        if res.latency_s - sum(res.backoffs) <= pol.batch_deadline_s:
            self._consecutive_breaches = 0
            return
        res.deadline_breached = True
        with self._stats_lock:
            self.deadline_breaches += 1
        self._consecutive_breaches += 1
        self._metrics.counter(
            "serve_deadline_breaches_total",
            help="batches whose dispatch→sync latency overran the deadline",
        ).inc()
        if self._consecutive_breaches >= pol.degrade_after:
            self.shed_rung(reason="deadline-breach", after_batch=res.seq)

    def shed_rung(self, *, reason: str = "deadline-breach",
                  after_batch: int | None = None) -> str | None:
        """Walk ONE rung down the degradation ladder, explicitly.

        Two callers: the session's own deadline machinery
        (``_note_latency``, on a breach streak) and the serving front
        end's SLO scheduler (``mpi_knn_tpu.frontend.scheduler``, on
        sustained queue growth — overload is visible upstream of the
        per-batch latency there). Either way the event is recorded the
        same: a ``degradations`` entry with the triggering ``reason``, a
        ``degrade`` flight event, and the registry counter + rung gauge —
        a rung walk is never invisible. Returns the new rung's label, or
        None when already at the ladder floor (nothing shed)."""
        with self._stats_lock:
            if self._rung >= len(self.ladder) - 1:
                return None
            self._rung += 1
            self._consecutive_breaches = 0
            label = self.ladder[self._rung][0]
            rung_idx = self._rung
            breaches = self.deadline_breaches
            ev = {
                "after_batch": after_batch if after_batch is not None
                else max(0, self._seq - 1),
                "rung": label,
                "breaches": breaches,
                "reason": reason,
            }
            self.degradations.append(ev)
        obs_spans.event(
            "degrade", cat="serve", after_batch=ev["after_batch"],
            rung=label, breaches=breaches, reason=reason,
        )
        self._metrics.counter(
            "serve_degradations_total",
            help="ladder rungs shed (deadline breach or queue overload)",
        ).inc()
        self._metrics.gauge(
            "serve_ladder_rung",
            help="current degradation-ladder rung index (0 = full)",
        ).set(rung_idx)
        return label

    def restore_rung(self, *, reason: str = "recovered") -> str | None:
        """Walk ONE rung back UP the ladder after the overload that shed
        it has passed (the front end's recovery path; the deadline
        machinery never restores — a breach-driven shed has no
        symmetrical 'deadlines are comfortably met' signal, queue depth
        does). Every rung on the way up is already compiled (``warm``
        pre-compiles the whole ladder), so a restore can never cold-
        compile into recovering traffic. Returns the restored rung's
        label, or None when already serving the full rung."""
        with self._stats_lock:
            if self._rung == 0:
                return None
            self._rung -= 1
            self._consecutive_breaches = 0
            label = self.ladder[self._rung][0]
            rung_idx = self._rung
            self.restorations.append({"rung": label, "reason": reason})
        obs_spans.event("restore", cat="serve", rung=label, reason=reason)
        self._metrics.counter(
            "serve_restorations_total",
            help="ladder rungs restored after overload recovery",
        ).inc()
        self._metrics.gauge(
            "serve_ladder_rung",
            help="current degradation-ladder rung index (0 = full)",
        ).set(rung_idx)
        return label

    # -- live mutation (ISSUE 14) -----------------------------------------
    # Thin session-facing wrappers over serve.mutate: the index mutates
    # under the per-index mutation lock (serialized with this session's
    # dispatch), the session's window accumulators take the tenant-
    # attributed story under _stats_lock. Mutations interleave freely
    # with submit()/stream() from other threads — that is the point.

    def upsert(self, ids, rows, tenant: str | None = None) -> dict:
        """Upsert rows into the live index (static shapes, donated
        in-place scatter — zero compiles at a warm mutation bucket).
        Returns the mutation stats. A clustered index that overflows its
        headroom compacts synchronously ONCE and retries (the background
        compactor normally fires on the fill threshold first, so this is
        the backstop for a burst that outruns it); the serial layout has
        no re-cluster pass, so its overflow propagates. Raises
        :class:`~mpi_knn_tpu.ivf.mutate.BucketOverflowError` when even a
        compacted store cannot absorb the rows."""
        from mpi_knn_tpu.ivf.mutate import BucketOverflowError
        from mpi_knn_tpu.serve import mutate as serve_mutate

        try:
            stats = serve_mutate.upsert_rows(self.index, ids, rows, self.cfg)
        except BucketOverflowError:
            if self.index.backend == "serial":
                raise
            self.compact(reason="overflow")
            try:
                stats = serve_mutate.upsert_rows(
                    self.index, ids, rows, self.cfg
                )
            except BucketOverflowError:
                # a burst aimed at one cluster can outsize any balanced
                # cap — grow it so the chunk is GUARANTEED to fit (the
                # documented recompile path), rather than failing an
                # admitted write
                serve_mutate.compact_index(
                    self.index, self.cfg, reason="overflow-grow",
                    min_cap=self.index.bucket_cap + int(
                        np.shape(rows)[0]
                    ),
                )
                stats = serve_mutate.upsert_rows(
                    self.index, ids, rows, self.cfg
                )
        self._note_mutation("upserts", stats.get("upserted", 0), tenant)
        return stats

    def delete(self, ids, tenant: str | None = None) -> dict:
        """Tombstone ids in the live index (they are never returned
        again; slots reclaim via the freelist). Idempotent for unknown
        ids. Returns the mutation stats."""
        from mpi_knn_tpu.serve import mutate as serve_mutate

        stats = serve_mutate.delete_rows(self.index, ids, self.cfg)
        self._note_mutation("deletes", stats.get("deleted", 0), tenant)
        return stats

    def compact(self, reason: str = "manual", retrain: bool = True) -> dict:
        """Re-cluster/compact the live index now (the background
        ``Compactor`` calls this on trigger): store rebuilt by one
        donated scatter and swapped between batches under the mutation
        lock."""
        from mpi_knn_tpu.serve import mutate as serve_mutate

        stats = serve_mutate.compact_index(
            self.index, self.cfg, retrain=retrain, reason=reason
        )
        with self._stats_lock:
            self.mutation_stats["compactions"] += 1
        return stats

    def start_compactor(self, interval_s: float = 0.25,
                        retrain: bool = True):
        """Start (and return) the background compaction worker for this
        session — trigger-driven, heartbeat/flight-recorded, deferred
        while the session is shedding load."""
        from mpi_knn_tpu.serve.mutate import Compactor

        compactor = Compactor(self, interval_s=interval_s, retrain=retrain)
        with self._stats_lock:
            self._compactor = compactor
        return compactor.start()

    def _note_mutation(self, kind: str, n: int,
                       tenant: str | None) -> None:
        with self._stats_lock:
            self.mutation_stats[kind] += n
            self.mutation_stats["calls"] += 1
        if tenant is not None:
            self._metrics.counter(
                f"serve_tenant_{kind}_total",
                help=f"rows {kind[:-1]}ed per tenant",
                labels={"tenant": str(tenant)},
            ).inc(n)

    def _retire(self) -> BatchResult:
        res, t0, sid = self._inflight.popleft()
        device_sync(res.dists_padded, res.ids_padded)
        res.latency_s = time.perf_counter() - t0
        with self._stats_lock:
            self.latencies.append(res.latency_s)
            self.queries_served += res.rows
        self._note_latency(res)
        if self.policy is not None and self.policy.nan_sentinel:
            try:
                self._check_sentinel(res)
            except PoisonedResultError:
                # the process survives a caught sentinel trip — close the
                # span with the error so an OPEN span stays what the
                # contract says it is: a kill diagnosis, never a raise
                obs_spans.end_span(
                    sid, latency_s=res.latency_s, retries=res.retries,
                    error="poisoned-result",
                )
                raise
        tenant_rows: dict[str, int] = {}
        if res.tenants:
            # aggregate the per-PART composition first: one tenant with
            # several coalesced requests in this batch is still ONE batch
            # (and one latency observation) for that tenant — iterating
            # raw parts would inflate batches and latency_sum per request
            for t, n in res.tenants:
                tenant_rows[t] = tenant_rows.get(t, 0) + n
            with self._stats_lock:
                for t, n in tenant_rows.items():
                    st = self.tenant_stats.setdefault(t, {
                        "queries": 0, "batches": 0,
                        "latency_sum_s": 0.0, "latency_max_s": 0.0,
                    })
                    st["queries"] += n
                    st["batches"] += 1
                    st["latency_sum_s"] += res.latency_s
                    st["latency_max_s"] = max(
                        st["latency_max_s"], res.latency_s
                    )
            for t, n in tenant_rows.items():
                self._metrics.counter(
                    "serve_tenant_queries_total",
                    help="query rows served per tenant (padding excluded)",
                    labels={"tenant": t},
                ).inc(n)
                self._metrics.counter(
                    "serve_tenant_batches_total",
                    help="batches carrying at least one row of this tenant",
                    labels={"tenant": t},
                ).inc()
        extra = {}
        if res.stats_padded is not None:
            # the candidate-exchange story, stamped at retire (the batch
            # is already synchronized — reading the tiny stats vector
            # costs one small D2H, never a mid-pipeline sync)
            per_shard = _count_exchange(
                res.stats_padded, res.exchange_bytes,
                registry=self._metrics,
            )
            routed = int(per_shard[:, 0].sum())
            dropped = int(per_shard[:, 1].sum())
            with self._stats_lock:
                if self.exchange is not None:
                    self.exchange["routed_total"] += routed
                    self.exchange["dropped_total"] += dropped
                    self.exchange["exchange_bytes_total"] += (
                        res.exchange_bytes or 0
                    )
                    for s, n in enumerate(per_shard[:, 2].tolist()):
                        self.exchange["served_per_shard"][s] += int(n)
                if tenant_rows and res.rows:
                    # tenant-attributable exchange: the routed volume is
                    # a batch-level fact (routes are per query TILE,
                    # tiles mix tenants), so the per-tenant share is
                    # rows-proportional — documented as an attribution,
                    # not a count
                    for t, n in tenant_rows.items():
                        self.tenant_stats[t]["routed"] = (
                            self.tenant_stats[t].get("routed", 0.0)
                            + routed * n / res.rows
                        )
            extra = {"routed": routed, "dropped": dropped}
            # the per-shard load event is the hang-attribution record: a
            # flight reader pairing an OPEN batch span with the LAST
            # exchange event before it sees which shard was carrying the
            # requests when serving stopped
            obs_spans.event(
                "exchange", cat="serve", seq=res.seq,
                served_per_shard=per_shard[:, 2].tolist(),
                routed=routed, dropped=dropped,
            )
        # the dispatch→retire span closes with the same honest latency
        # the session reports; a beat per retire lets a supervisor see
        # serving progress (a wedged dispatch stops both immediately)
        obs_spans.end_span(
            sid, latency_s=res.latency_s, retries=res.retries,
            deadline_breached=res.deadline_breached, **extra,
        )
        maybe_beat(f"serve-batch-{res.seq}")
        self._metrics.counter(
            "serve_batches_total", help="batches retired"
        ).inc()
        self._metrics.counter(
            "serve_queries_total", help="query rows served (padding excluded)"
        ).inc(res.rows)
        self._metrics.histogram(
            "serve_batch_latency_seconds",
            help="per-batch dispatch→device_sync latency",
        ).observe(res.latency_s)
        return res

    def _dispatch(self, queries, cfg: KNNConfig):
        """One dispatch attempt under ``cfg`` (a ladder rung's config).
        The fault site models a transient transport failure; the poison
        hook injects a NaN into the returned tile for sentinel tests."""
        fault_point("serve-batch")
        bucket = bucket_rows(queries.shape[0], cfg.query_bucket)
        exec_ = get_executable(self.index, cfg, bucket)
        q2d, qids, rows = _prep_queries(self.index, cfg, exec_, queries)
        d, i, stats = _run(self.index, cfg, exec_, q2d, qids)
        return bucket, rows, poison_topk(d), i, stats, exec_.exchange_bytes

    def submit(self, queries, tenants=None) -> list[BatchResult]:
        """Dispatch one batch; ``tenants`` is an optional
        ``((tenant, rows), ...)`` composition in row order (a coalesced
        multi-tenant batch from the serving front end) — it must sum to
        the batch's row count, or the per-tenant accounting would
        silently mis-attribute."""
        t0 = time.perf_counter()
        if tenants is not None:
            tenants = tuple((str(t), int(n)) for t, n in tenants)
            for t, _ in tenants:
                if not t or any(c in t for c in ('"', "\\", "\n", "\r")):
                    # tenant ids become metrics LABELS at retire; a value
                    # the exposition cannot carry must fail HERE at
                    # submit (loud, at the caller) — not at retire inside
                    # a dispatch pump that serves every other tenant
                    raise ValueError(
                        f"tenant id {t!r} must be non-empty with no "
                        "quotes, backslashes, or newlines (it becomes a "
                        "metrics label)"
                    )
            total = sum(n for _, n in tenants)
            if total != int(queries.shape[0]):
                raise ValueError(
                    f"tenant composition sums to {total} rows but the "
                    f"batch has {int(queries.shape[0])}: refusing to "
                    "mis-attribute per-tenant stats"
                )
        label, cfg = self._current_rung()
        # the batch span opens BEFORE the dispatch attempt: a hang inside
        # the dispatch leaves an OPEN "batch" record in the flight file —
        # the kill diagnosis a supervisor banks (ISSUE 7). Sharded-
        # clustered sessions stamp the shard topology on the span: an
        # open span plus the last retired batch's per-shard exchange
        # event is how a flight reader attributes a hang to a shard.
        span_attrs = {}
        if self.index.backend == "ivf-sharded":
            span_attrs["shards"] = self.index.shards
        if tenants is not None:
            # the batch span carries the tenant composition: a hang's
            # open-span diagnosis (or a slow batch in the flight record)
            # names WHOSE rows were on board, not just how many
            comp: dict[str, int] = {}
            for t, n in tenants:
                comp[t] = comp.get(t, 0) + n
            span_attrs["tenants"] = comp
        sid = obs_spans.begin_span(
            "batch", cat="serve", seq=self._seq,
            rows=int(queries.shape[0]), rung=label, **span_attrs,
        )
        pol = self.policy
        try:
            if pol is not None and pol.max_retries > 0:
                out = retry_with_backoff(
                    lambda: self._dispatch(queries, cfg),
                    retries=pol.max_retries,
                    base_s=pol.backoff_base_s,
                    max_s=pol.backoff_max_s,
                    retryable=pol.retryable,
                )
                bucket, rows, d, i, stats, xbytes = out.value
                retries, backoffs = out.attempts - 1, out.backoffs
                with self._stats_lock:
                    self.retries_total += retries
                if retries:
                    obs_spans.event(
                        "retry", cat="retry", seq=self._seq,
                        retries=retries, backoffs=list(backoffs),
                    )
                    self._metrics.counter(
                        "serve_retries_total",
                        help="transient dispatch failures retried",
                    ).inc(retries)
            else:
                bucket, rows, d, i, stats, xbytes = self._dispatch(
                    queries, cfg
                )
                retries, backoffs = 0, ()
        except Exception as e:
            # a RAISED dispatch failure (retries exhausted, non-retryable
            # fault) is survivable by the caller — close the span with
            # the error; only a hang/kill leaves it open
            obs_spans.end_span(sid, error=type(e).__name__)
            raise
        res = BatchResult(
            d, i, rows, bucket,
            seq=self._seq,  # 0-indexed, matching the CLI's printed lines
            degraded=None if label == FULL_RUNG else label,
            retries=retries,
            backoffs=backoffs,
            tenants=tenants,
            stats_padded=stats,
            exchange_bytes=xbytes,
        )
        self._seq += 1
        self._inflight.append((res, t0, sid))
        done = []
        # bound the dispatch-ahead window: at depth d, batch t+d-1 may be
        # prepared/dispatched while batch t is still in flight; depth 1
        # retires (syncs) every batch before submit returns
        while len(self._inflight) >= max(1, self.cfg.dispatch_depth):
            done.append(self._retire())
        return done

    def drain(self) -> list[BatchResult]:
        out = []
        while self._inflight:
            out.append(self._retire())
        return out

    def stream(self, batches, tenant: str | None = None):
        """Serve an iterable of batches, yielding results in order.
        ``tenant`` tags every batch as one tenant's stream (single-tenant
        attribution — the ``mpi-knn query --tenant`` path); coalesced
        multi-tenant batches use ``submit(..., tenants=...)`` directly."""
        for q in batches:
            yield from self.submit(
                q,
                tenants=(
                    None if tenant is None
                    else ((tenant, int(q.shape[0])),)
                ),
            )
        yield from self.drain()

    def profile(self, batches, trace_dir: str | None = None) -> dict:
        """Opt-in device-time attribution: serve ``batches`` under
        ``jax.profiler.trace`` and return the per-category device busy
        split (``mpi_knn_tpu.obs.attribution``) — matmul / sort-topk /
        collective / copy / other plus the collective-under-compute
        overlap fraction. Steady state is enforced here: every bucket
        the profile batches need is compiled BEFORE the trace opens —
        a batch size the stream never served would otherwise cold-compile
        inside the trace, the compile events would categorize as "other",
        and the split would measure compilation while claiming serving."""
        import tempfile

        from mpi_knn_tpu.obs.attribution import attribute_trace

        batches = list(batches)
        _, cfg = self._current_rung()
        for rows in sorted({int(q.shape[0]) for q in batches}):
            get_executable(
                self.index, cfg, bucket_rows(rows, cfg.query_bucket)
            )
        tdir = trace_dir or tempfile.mkdtemp(prefix="tknn-profile-")
        n = 0
        with obs_spans.span("profile", cat="profile", trace_dir=tdir):
            with jax.profiler.trace(tdir):
                for q in batches:
                    self.submit(q)
                    n += 1
                self.drain()
        out = attribute_trace(tdir)
        out["batches_profiled"] = n
        out["trace_dir"] = tdir
        return out
