"""Query-serving engine: device-resident corpus index, bucketed AOT
executable cache, donated per-batch scratch, double-buffered dispatch.

Public surface::

    from mpi_knn_tpu.serve import build_index, query_knn, ServeSession

    index = build_index(corpus, KNNConfig(k=10, backend="serial"))
    res = query_knn(Q, index)              # one-shot, recompile-free when warm

    session = ServeSession(index)          # streaming, dispatch-ahead
    for batch_result in session.stream(batches):
        use(batch_result.ids)

Design rationale and the machine-checked donation/copy contract (lint
rule R5): ``serve/engine.py`` docstring and DESIGN.md "Serving pipeline".
Cold start — the persistent on-disk executable cache
(``serve/aotcache.py``: ``aotcache.set_cache_dir`` / ``TKNN_AOT_CACHE``,
CLI ``--cache-dir``), fingerprint-deduped parallel ``warm()``, and the
zero-copy index load — is DESIGN.md "Cold start".
"""

from mpi_knn_tpu.serve import aotcache
from mpi_knn_tpu.serve.engine import (
    BatchResult,
    ServeSession,
    bucket_rows,
    get_executable,
    query_knn,
)
from mpi_knn_tpu.serve.index import CorpusIndex, build_index

__all__ = [
    "BatchResult",
    "CorpusIndex",
    "ServeSession",
    "aotcache",
    "bucket_rows",
    "build_index",
    "get_executable",
    "query_knn",
]
