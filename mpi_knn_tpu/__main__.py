from mpi_knn_tpu.cli import main

raise SystemExit(main())
