"""Result containers.

Replaces the reference's ``neighbour{distance, idx[, label]}`` array-of-structs
(``/root/reference/knn-serial.c:14-18``) with structure-of-arrays device
arrays: distances and global indices live in separate, MXU/VPU-friendly
tensors; labels are gathered on demand from a label vector.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

# Sentinel index used for padded / masked-out candidate rows.
INVALID_ID = -1


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class KNNResult:
    """Top-k nearest neighbors for a batch of queries.

    Attributes:
      dists: (q, k) float array. Distances in *sortable* space — squared L2 for
        the ``l2`` metric (monotone in true L2, per SURVEY.md §5 Q10), or
        ``1 − cosine`` for the ``cosine`` metric. Ascending along k.
      ids: (q, k) int32 array of 0-based global corpus ids (the reference uses
        1-based ids, ``/root/reference/knn-serial.c:89``; use ``one_based()``
        for parity output). ``INVALID_ID`` marks unfilled slots (k > valid
        candidates).
    """

    dists: jax.Array
    ids: jax.Array

    @property
    def k(self) -> int:
        return self.ids.shape[-1]

    def l2_dists(self) -> jax.Array:
        """True (non-squared) L2 distances, like the reference compares in."""
        return jnp.sqrt(jnp.maximum(self.dists, 0.0))

    def one_based(self) -> jax.Array:
        """1-based ids for bit-parity with the reference (invalid stays -1)."""
        return jnp.where(self.ids >= 0, self.ids + 1, self.ids)

    def valid(self) -> jax.Array:
        return self.ids >= 0


@dataclasses.dataclass(frozen=True)
class ClassifyResult:
    """Output of kNN majority-vote classification (SURVEY.md C10)."""

    predictions: jax.Array  # (q,) int32, 0-based class ids
    counts: jax.Array  # (q, num_classes) int32 vote histogram

    def matches(self, true_labels: Any) -> jax.Array:
        """The reference's end-to-end oracle: number of correct predictions
        (``/root/reference/knn-serial.c:127-130``)."""
        return jnp.sum(self.predictions == jnp.asarray(true_labels))
