"""Serving front end — the async layer above ``ServeSession`` (ISSUE 11):
request coalescing, SLO-aware admission, queue-driven degradation, and a
thin multi-tenant HTTP shell with an open-loop load generator.

Layout::

    coalesce.py    pure deterministic batcher (per-tenant FIFO, fill-or-
                   deadline formation, deadline-first round-robin drain)
    scheduler.py   SLO admission (queue-depth/rate 429s), overload
                   shed/recover wired to the resilience ladder
    server.py      the only impure parts: dispatch pump thread + stdlib
                   HTTP server (POST /query, GET /metrics, GET /healthz)
    loadgen.py     open-loop multi-tenant load generation (in-process
                   and HTTP transports, keep-alive connection pools,
                   multi-target), throughput-vs-p99 rows
    router.py      the replicated tier (ISSUE 18): health-gated
                   membership, tenant-affine (rendezvous) spread with
                   least-queued spill, sequenced mutation fan-out with
                   bounded replay, supervised replica spawning
    modelreplica.py jax-free deterministic-service stand-in replica
                   (the router's scaling proof on 1-core CI hosts)
    cli.py         `mpi-knn serve` / `mpi-knn loadgen` / `mpi-knn router`

Public surface::

    from mpi_knn_tpu.frontend import (
        Coalescer, SLOPolicy, FrontendScheduler, Rejection,
        Frontend, FrontendHTTPServer,
        Router, RouterPolicy, RouterHTTPServer, ReplicaSupervisor,
        Membership, MutationLog, ModelReplica,
    )

Like ``resilience`` and ``obs``, the package is import-lazy (PEP 562)
and jax-free at module load: the pure machinery (coalescer, scheduler,
loadgen) runs in processes that never touch a device; only a bound
``ServeSession`` brings jax with it.
"""

from __future__ import annotations

_EXPORTS = {
    "Coalescer": ("mpi_knn_tpu.frontend.coalesce", "Coalescer"),
    "CoalescedBatch": ("mpi_knn_tpu.frontend.coalesce", "CoalescedBatch"),
    "FrontendRequest": ("mpi_knn_tpu.frontend.coalesce", "FrontendRequest"),
    "SLOPolicy": ("mpi_knn_tpu.frontend.scheduler", "SLOPolicy"),
    "Rejection": ("mpi_knn_tpu.frontend.scheduler", "Rejection"),
    "FrontendScheduler": (
        "mpi_knn_tpu.frontend.scheduler", "FrontendScheduler"
    ),
    "Frontend": ("mpi_knn_tpu.frontend.server", "Frontend"),
    "FrontendHTTPServer": (
        "mpi_knn_tpu.frontend.server", "FrontendHTTPServer"
    ),
    "Ticket": ("mpi_knn_tpu.frontend.server", "Ticket"),
    "Router": ("mpi_knn_tpu.frontend.router", "Router"),
    "RouterPolicy": ("mpi_knn_tpu.frontend.router", "RouterPolicy"),
    "RouterHTTPServer": (
        "mpi_knn_tpu.frontend.router", "RouterHTTPServer"
    ),
    "ReplicaSupervisor": (
        "mpi_knn_tpu.frontend.router", "ReplicaSupervisor"
    ),
    "Membership": ("mpi_knn_tpu.frontend.router", "Membership"),
    "MutationLog": ("mpi_knn_tpu.frontend.router", "MutationLog"),
    "ModelReplica": (
        "mpi_knn_tpu.frontend.modelreplica", "ModelReplica"
    ),
    "loadgen": ("mpi_knn_tpu.frontend", "loadgen"),
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    try:
        mod_name, attr = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    import importlib

    if name == "loadgen":
        return importlib.import_module("mpi_knn_tpu.frontend.loadgen")
    return getattr(importlib.import_module(mod_name), attr)


def __dir__():
    return __all__
