"""The replicated serving tier (ISSUE 18): a jax-free router fronting N
``mpi-knn serve`` replicas of ONE saved index artifact.

Layering follows the front end's testability contract: everything with
behavior worth asserting is a pure state machine here —

- :class:`Membership` — health-gated rotation: a replica joins only when
  its ``/healthz`` says ready, leaves after ``evict_after`` consecutive
  probe failures, and re-enters through ``joining`` (probation) after
  ``rejoin_after`` consecutive ready probes. Every transition is
  returned as an event dict for the impure shell to count and stamp.
- :class:`MutationLog` — the per-index mutation history: a monotone
  sequence number per ``POST /upsert``/``/delete``, a BOUNDED replay
  buffer, and the gap computation that decides whether an out-of-date
  replica can be replayed forward or has diverged past the buffer
  (overflow ⇒ quarantine until cold-reloaded to a coverable baseline).
- :func:`rendezvous_order` / :func:`choose_replica` — tenant-affine
  spread: rendezvous (HRW) hashing, so membership churn remaps ONLY the
  affected tenants' keys and each replica keeps its tenants' coalescing
  locality; least-queued spill when the affine replica is out of
  rotation or over the depth bound read from ``/healthz``.

— and the impure shell is as thin as it can be made:

- :class:`Router` — threads and sockets: a prober thread polls each
  replica's ``/healthz`` on the router's OWN clock (replica clocks are
  never trusted, and a wedged replica must not stall the rotation
  decision), fans mutations out to every in-rotation replica stamped
  with ``X-Mutation-Seq``, and replays buffered gaps to joining or
  lagging replicas in order. Lock order is ``_mutlock`` → ``_lock``
  (strict): the mutation lock is held across fan-out/replay I/O — that
  is the ordering authority — while the membership lock only covers
  routing decisions and state, so queries and ``/healthz`` (which reads
  the log's posture from a snapshot published under ``_lock``) never
  wait on mutation I/O.
- :class:`RouterHTTPServer` — the stdlib ``ThreadingHTTPServer`` shell:
  ``POST /query`` proxies to the chosen replica (structured 503 when
  the rotation is empty, one retry on a different replica when the
  transport fails mid-flight — queries are idempotent), ``POST
  /upsert``/``/delete`` sequence-and-fan-out, ``GET /healthz`` the
  router posture, ``GET /metrics`` the obs exposition.
- :class:`ReplicaSupervisor` — ``mpi-knn router --spawn N``: each
  replica slot is one thread looping ``resilience.worker.
  run_supervised`` over a ``mpi-knn serve`` child with a SHARED
  ``--cache-dir`` (replica cold start rides the AOT cache — second and
  later replicas compile zero programs) and a per-slot ``--ready-file``
  that doubles as discovery: children bind ``--port 0`` and publish
  their URL atomically; a restarted child publishes a NEW port and the
  prober picks it up on its next cycle.

Replica-side contract (``frontend/server.py``): mutations carrying
``X-Mutation-Seq`` advance an ``applied_seq`` high-water mark exposed in
``/healthz``; a seq at or below the mark is a replayed duplicate —
acknowledged, never re-applied — so replay may overlap live fan-out.
The mark is GAPLESS: a replica refuses a seq beyond ``applied_seq + 1``
with 409 (outside the deterministic set, so the router never acks it),
because applying over a hole would silently lose the missed mutation —
the router's in-order replay is the only path that advances a lagging
replica. Deterministic refusals (400/507) consume their seq exactly as
an apply would (a replay could only repeat them; a position that did
not advance would wedge the stream on 409 forever).

No jax import anywhere in this module: the router is exactly the layer
that must run on a box with no accelerator.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import http.client
import json
import os
import threading
import time
import urllib.parse

from mpi_knn_tpu.obs import metrics as obs_metrics
from mpi_knn_tpu.obs import spans as obs_spans

SEQ_HEADER = "X-Mutation-Seq"
TENANT_HEADER = "X-Tenant"
DEFAULT_TENANT = "default"

# membership states
JOINING = "joining"  # known, probation: not yet (or not yet re-) promoted
IN = "in"  # in rotation
OUT = "out"  # evicted on probe failures, awaiting recovery
STALE = "stale"  # mutation gap fell off the replay buffer: quarantined


@dataclasses.dataclass(frozen=True)
class RouterPolicy:
    """The router's knobs — all times on the router's clock."""

    probe_interval_s: float = 0.5
    probe_timeout_s: float = 5.0
    # consecutive probe FAILURES before an in-rotation replica is evicted
    # (hysteresis: one dropped poll must not flap the rotation)
    evict_after: int = 3
    # consecutive READY probes before a joining replica is promoted
    rejoin_after: int = 2
    # spill when the affine replica's /healthz queue_rows exceeds this
    spill_queue_rows: int = 4096
    # bounded mutation replay buffer (entries, not bytes): the outage
    # window a replica may sleep through and still be replayed forward
    replay_buffer: int = 4096
    request_timeout_s: float = 30.0
    # per fan-out/replay leg: deliberately much shorter than the query
    # timeout — a failed leg is replayed by the probe loop anyway, and
    # the leg runs under the mutation lock, so one wedged replica must
    # bound how long it can stall every other mutation
    fanout_timeout_s: float = 5.0

    def __post_init__(self):
        if self.evict_after < 1 or self.rejoin_after < 1:
            raise ValueError("evict_after and rejoin_after must be >= 1")
        if self.replay_buffer < 1:
            raise ValueError("replay_buffer must be >= 1")


def rendezvous_order(tenant: str, names) -> list[str]:
    """Replica names by descending HRW score for ``tenant``: the first
    IS the tenant's affine replica; churn anywhere else in the list
    never changes it (the minimal-remap property a modulo hash lacks)."""
    def score(name: str) -> int:
        h = hashlib.sha256(f"{tenant}|{name}".encode()).digest()
        return int.from_bytes(h[:8], "big")

    return sorted(names, key=lambda n: (-score(n), n))


def choose_replica(tenant: str, known, rotation: dict,
                   *, spill_queue_rows: int) -> tuple:
    """(name, spilled) — the affine replica when it is in rotation and
    under the depth bound, else the least-queued in-rotation replica
    (spill). ``rotation`` maps name → (queue_rows, inflight); ``known``
    is EVERY known replica, in or out — affinity is computed over the
    full set so an eviction only remaps the evicted replica's tenants,
    and they snap back on rejoin. (None, False) on empty rotation."""
    if not rotation:
        return None, False
    affine = rendezvous_order(tenant, known)[0]
    depth = rotation.get(affine)
    if depth is not None and depth[0] <= spill_queue_rows:
        return affine, False
    pick = min(sorted(rotation), key=lambda n: (*rotation[n], n))
    return pick, True


@dataclasses.dataclass
class ReplicaState:
    """One replica as the router last saw it (mutated only under the
    router's membership lock — :class:`Membership` is serialized)."""

    name: str
    url: str | None = None
    state: str = JOINING
    ok_streak: int = 0
    fail_streak: int = 0
    ready: bool = False
    # the replica's own high-water mark — from the last probe, advanced
    # between probes by each 200 fan-out/replay leg's response (a
    # restart in the probe gap must not be compared against a mark
    # staler than the legs the router already saw land)
    applied_seq: int = 0
    # the replica's last reported /healthz uptime_s: the LIFE marker.
    # Within one life both uptime and applied_seq are monotone; an
    # uptime that drops is a restart even when the new life's baseline
    # happens to equal the last mark
    uptime_s: float | None = None
    # the router-side acknowledgment horizon: the highest seq this
    # replica gave a DETERMINISTIC response for (2xx, or a 4xx/507 that
    # a replay could only repeat) — transient failures don't advance it
    acked_seq: int = 0
    queue_rows: int = 0
    last_probe_s: float | None = None
    doc: dict | None = None


class Membership:
    """The health-gated rotation state machine — pure: probes come in as
    (name, healthz-doc-or-None, now) observations, transitions come out
    as event dicts. Serialized by the router's membership lock."""

    def __init__(self, policy: RouterPolicy):
        self.policy = policy
        self.replicas: dict[str, ReplicaState] = {}

    def add(self, name: str, url: str | None = None) -> None:
        if name in self.replicas:
            raise ValueError(f"duplicate replica {name!r}")
        self.replicas[name] = ReplicaState(name=name, url=url)

    def set_url(self, name: str, url: str | None) -> None:
        self.replicas[name].url = url

    def in_rotation(self) -> list[str]:
        return sorted(
            n for n, r in self.replicas.items() if r.state == IN
        )

    def _event(self, event: str, r: ReplicaState, now: float,
               **extra) -> dict:
        return {"event": event, "replica": r.name, "state": r.state,
                "now": now, **extra}

    def note_probe(self, name: str, doc: dict | None,
                   now: float) -> list[dict]:
        """Fold one probe observation in. ``doc`` is the parsed
        ``/healthz`` body, or None for any transport/HTTP failure —
        the two are deliberately indistinct: a replica that cannot
        answer its health check is out, whatever the reason."""
        r = self.replicas[name]
        r.last_probe_s = now
        events: list[dict] = []
        if doc is None or not doc.get("ok", False):
            r.ok_streak = 0
            r.fail_streak += 1
            r.ready = False
            if r.state == IN and r.fail_streak >= self.policy.evict_after:
                r.state = OUT
                events.append(self._event(
                    "evict", r, now, fails=r.fail_streak
                ))
            return events
        applied = int(doc.get("applied_seq", 0))
        up = doc.get("uptime_s")
        up = float(up) if up is not None else None
        # restart detection: the uptime LIFE marker is authoritative
        # when both sides report it — a probed doc that raced a fan-out
        # leg can carry an applied_seq below the leg-updated mark with
        # no restart, and a restart restored to the last mark shows no
        # seq regression at all. Without uptime data (a minimal
        # /healthz), a dropping applied_seq is the only signal.
        if up is not None and r.uptime_s is not None:
            restarted = up < r.uptime_s
        else:
            restarted = applied < r.applied_seq
        if restarted:
            # every router-side acknowledgment was for a life that no
            # longer exists: resynchronize both marks to what the new
            # life reports, so the replay planner sees the real gap
            r.acked_seq = applied
            r.applied_seq = applied
            events.append(self._event(
                "restart-detected", r, now, applied_seq=applied
            ))
        else:
            # same life: the mark never regresses (the probed doc may
            # trail mutation legs acknowledged since it was rendered)
            r.applied_seq = max(r.applied_seq, applied)
        r.uptime_s = up
        r.fail_streak = 0
        r.queue_rows = int(doc.get("queue_rows", 0))
        r.ready = bool(doc.get("ready", False))
        r.doc = doc
        r.ok_streak = r.ok_streak + 1 if r.ready else 0
        if r.state == OUT and r.ready:
            r.state = JOINING
            events.append(self._event("recover", r, now))
        return events

    def promotable(self) -> list[str]:
        """Joining replicas past probation — the shell promotes each one
        only after its mutation gap has been replayed."""
        return sorted(
            n for n, r in self.replicas.items()
            if r.state == JOINING
            and r.ok_streak >= self.policy.rejoin_after
        )

    def promote(self, name: str, now: float) -> dict:
        r = self.replicas[name]
        r.state = IN
        return self._event("join", r, now, applied_seq=r.applied_seq)

    def quarantine(self, name: str, now: float, *,
                   min_seq: int) -> dict:
        """The replica's gap fell off the replay buffer: it cannot be
        replayed forward and must cold-reload to a baseline at or past
        ``min_seq - 1`` before it is considered again."""
        r = self.replicas[name]
        r.state = STALE
        return self._event(
            "quarantine", r, now,
            applied_seq=r.applied_seq, min_buffered_seq=min_seq,
        )

    def reloadable(self, name: str, min_seq: int) -> bool:
        """A stale replica whose reported baseline became coverable
        again (cold-reloaded from a refreshed artifact)."""
        r = self.replicas[name]
        return (
            r.state == STALE and r.ready
            and max(r.applied_seq, r.acked_seq) >= min_seq - 1
        )

    def note_reload(self, name: str, now: float) -> dict:
        r = self.replicas[name]
        r.state = JOINING
        r.ok_streak = 0  # fresh probation after the reload
        return self._event("reload", r, now, applied_seq=r.applied_seq)

    def posture(self) -> dict:
        """The /healthz replica table (plain data, no I/O)."""
        return {
            name: {
                "url": r.url,
                "state": r.state,
                "ready": r.ready,
                "applied_seq": r.applied_seq,
                "acked_seq": r.acked_seq,
                "queue_rows": r.queue_rows,
                "ok_streak": r.ok_streak,
                "fail_streak": r.fail_streak,
            }
            for name, r in sorted(self.replicas.items())
        }


class MutationLog:
    """Sequenced, bounded mutation history. The router is the ordering
    authority: every mutation gets the next seq here, and replicas apply
    strictly by seq (duplicates suppressed replica-side). Bounded: the
    buffer covers a bounded outage window, not unbounded divergence —
    ``gap_after`` returns None when a baseline fell off the left edge.
    Serialized by the router's mutation lock."""

    def __init__(self, cap: int):
        self.cap = cap
        self.seq = 0  # last assigned
        self._buf: collections.deque = collections.deque()

    @property
    def min_seq(self) -> int:
        """Lowest buffered seq (``seq + 1`` when empty — an empty log
        covers exactly the baselines that need nothing replayed)."""
        return self._buf[0][0] if self._buf else self.seq + 1

    def append(self, path: str, tenant: str, body: bytes) -> int:
        self.seq += 1
        self._buf.append((self.seq, path, tenant, body))
        while len(self._buf) > self.cap:
            self._buf.popleft()
        return self.seq

    def gap_after(self, applied_seq: int) -> list | None:
        """The (seq, path, tenant, body) entries a replica at
        ``applied_seq`` is missing, in order — or None when the gap is
        no longer fully buffered (overflow)."""
        if applied_seq >= self.seq:
            return []
        if applied_seq + 1 < self.min_seq:
            return None
        return [m for m in self._buf if m[0] > applied_seq]


# ---------------------------------------------------------------------------
# impure shell

# replica responses a replay could only repeat: advancing the ack
# horizon past them keeps the protocol live (a malformed or
# headroom-overflowing mutation must not wedge replay forever); 429 and
# 5xx are transient — the next replay cycle retries them — and 409 is
# the replica's seq-gap refusal (it has not seen seq - 1 yet): the leg
# stays unacked so the probe loop replays the hole forward in order
_DETERMINISTIC = frozenset({200, 400, 404, 507})


class Router:
    """Bind a :class:`Membership` + :class:`MutationLog` to real probes,
    proxying, and fan-out. ``replicas`` maps name → base URL for a
    static fleet; pass ``supervisor`` instead (or as well) for spawned
    replicas whose URLs come from ready files and change on restart."""

    def __init__(self, replicas: dict | None = None, *,
                 policy: RouterPolicy | None = None, supervisor=None,
                 clock=time.monotonic):
        self.policy = policy or RouterPolicy()
        self._clock = clock
        self.supervisor = supervisor
        # lock order (H2): _mutlock -> _lock, never the reverse. _plock
        # is a leaf (held only around pool list ops, no calls out).
        self._lock = threading.Lock()
        self._mutlock = threading.Lock()
        self._plock = threading.Lock()
        self.membership = Membership(self.policy)
        self.log = MutationLog(self.policy.replay_buffer)
        # (seq, min_seq) published under _lock after every append, so
        # /healthz and the lag gauges read the log's posture WITHOUT
        # _mutlock — the mutation lock is held across fan-out/replay
        # I/O, and one wedged replica must not stall the health surface
        self._log_posture = (self.log.seq, self.log.min_seq)
        self._inflight: dict[str, int] = {}
        self._pools: dict[tuple, list] = {}
        self.started_s = time.monotonic()
        self._stop = threading.Event()
        self._prober = threading.Thread(
            target=self._probe_loop, name="router-prober", daemon=True
        )
        with self._lock:  # single-threaded here; the lint's discipline
            # is cheap to honor and keeps Membership's contract uniform
            for name, url in sorted((replicas or {}).items()):
                self.membership.add(name, url)
            if supervisor is not None:
                for name in supervisor.names():
                    self.membership.add(name, supervisor.url(name))

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "Router":
        self._prober.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._prober.ident is not None:  # join only a started thread
            self._prober.join(
                self.policy.probe_interval_s
                + self.policy.probe_timeout_s + 5
            )
        # close every pooled keep-alive socket: a daemon-threaded shell
        # dies with the process, but an embedding test or CLI stops many
        # routers in one life — their pools must not strand sockets
        with self._plock:
            conns = [c for pool in self._pools.values() for c in pool]
            self._pools.clear()
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass

    def wait_rotation(self, n: int, timeout_s: float = 60.0) -> bool:
        """Block until ≥ n replicas are in rotation (startup rendezvous
        for CLIs and tests)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if len(self.membership.in_rotation()) >= n:
                    return True
            if self._stop.wait(0.05):
                return False
        return False

    # -- probe / membership ----------------------------------------------

    def _probe_loop(self) -> None:
        # first cycle immediately: a fresh fleet should not wait a full
        # interval to start joining
        while True:
            try:
                self._probe_once()
            except Exception:  # noqa: BLE001 — the rotation must outlive
                # one bad cycle (a half-dead replica yielding garbage
                # must not kill probing for the healthy ones)
                pass
            if self._stop.wait(self.policy.probe_interval_s):
                return

    def _fetch_healthz(self, url: str) -> dict | None:
        """One health poll — None on ANY failure. A stale pooled
        connection is retried once fresh so an idle-closed socket never
        masquerades as a sick replica."""
        for _attempt in range(2):
            try:
                conn, pooled = self._conn_get("probe", url)
            except OSError:
                return None
            try:
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                data = resp.read()
                doc = json.loads(data) if resp.status == 200 else None
            except (OSError, http.client.HTTPException, ValueError,
                    TimeoutError):
                try:
                    conn.close()
                except OSError:
                    pass
                if pooled:
                    continue
                return None
            self._conn_put("probe", url, conn)
            return doc if isinstance(doc, dict) else None
        return None

    def _probe_once(self) -> None:
        with self._lock:
            names = sorted(self.membership.replicas)
        # I/O with no lock held: a wedged replica costs probe_timeout_s
        # of this thread, never a lock anyone else wants
        observed = {}
        urls: dict[str, str | None] = {}
        for name in names:
            url = (
                self.supervisor.url(name)
                if self.supervisor is not None
                else None
            )
            with self._lock:
                if url is None:
                    url = self.membership.replicas[name].url
                elif url != self.membership.replicas[name].url:
                    self.membership.set_url(name, url)
            urls[name] = url
            doc = self._fetch_healthz(url) if url else None
            observed[name] = doc
        self._prune_pools(urls)
        events: list[dict] = []
        with self._mutlock:
            plans = []
            with self._lock:
                now = self._clock()
                for name, doc in observed.items():
                    events += self.membership.note_probe(name, doc, now)
                # quarantine exit: a stale replica whose baseline became
                # coverable again (cold reload)
                for name in names:
                    if self.membership.reloadable(name, self.log.min_seq):
                        events.append(
                            self.membership.note_reload(name, now)
                        )
                # replay planning: joining replicas past probation, and
                # in-rotation replicas a failed fan-out left lagging
                for name in names:
                    r = self.membership.replicas[name]
                    base = max(r.applied_seq, r.acked_seq)
                    promoting = (
                        r.state == JOINING
                        and r.ok_streak >= self.policy.rejoin_after
                    )
                    lagging = r.state == IN and base < self.log.seq
                    if not (promoting or lagging):
                        continue
                    gap = self.log.gap_after(base)
                    if gap is None:
                        events.append(self.membership.quarantine(
                            name, now, min_seq=self.log.min_seq
                        ))
                        self._registry().counter(
                            "router_replay_overflow_total",
                            help="replicas quarantined because their "
                            "mutation gap fell off the replay buffer",
                        ).inc()
                        continue
                    plans.append((name, r.url, gap, promoting))
            # replay I/O under _mutlock only: live mutations queue
            # behind the replay, preserving the global order
            for name, url, gap, promoting in plans:
                done = self._send_gap(name, url, gap)
                if promoting and done:
                    with self._lock:
                        r = self.membership.replicas[name]
                        if r.state == JOINING:
                            events.append(
                                self.membership.promote(name, self._clock())
                            )
        self._note_events(events)
        with self._lock:  # the published posture, never _mutlock: the
            # gauges must not queue behind replay I/O
            seq_now = self._log_posture[0]
            rotation = len(self.membership.in_rotation())
            lags = {
                name: max(0, seq_now
                          - max(r.applied_seq, r.acked_seq))
                for name, r in self.membership.replicas.items()
            }
        reg = self._registry()
        reg.gauge(
            "router_rotation_size", help="replicas in rotation"
        ).set(rotation)
        for name, lag in sorted(lags.items()):
            reg.gauge(
                "router_replica_lag", help="mutation seqs behind the log",
                labels={"replica": name},
            ).set(lag)

    def _send_gap(self, name: str, url: str | None, gap) -> bool:
        """Replay ``gap`` to one replica in seq order; stop at the first
        non-deterministic failure (order must never have holes). True
        when the replica acknowledged the whole gap."""
        if url is None:
            return False
        for seq, path, tenant, body in gap:
            status, rdoc = self._post_to(
                name, url, path, body, tenant, seq,
                timeout_s=self.policy.fanout_timeout_s,
            )
            if status not in _DETERMINISTIC:
                return False
            self._note_leg(name, seq, rdoc)
            self._registry().counter(
                "router_replayed_mutations_total",
                help="buffered mutations replayed to replicas",
                labels={"replica": name},
            ).inc()
        return True

    def _note_events(self, events) -> None:
        reg = self._registry()
        for ev in events:
            reg.counter(
                "router_membership_transitions_total",
                help="membership state transitions",
                labels={"event": ev["event"]},
            ).inc()
            obs_spans.event(
                "membership", cat="router", event=ev["event"],
                replica=ev["replica"], state=ev["state"],
            )

    def _note_leg(self, name: str, seq: int, rdoc) -> None:
        """Fold one DETERMINISTIC fan-out/replay leg into the replica's
        marks: the ack horizon reaches ``seq``, and the response's own
        ``applied_seq`` (both serve and modeled replicas stamp it)
        advances the probed mark BETWEEN probe cycles — restart
        detection and replay planning must never work from a mark
        staler than the legs the router already saw land."""
        rep = rdoc.get("applied_seq") if isinstance(rdoc, dict) else None
        with self._lock:
            r = self.membership.replicas[name]
            if seq > r.acked_seq:
                r.acked_seq = seq
            if rep is not None and int(rep) > r.applied_seq:
                r.applied_seq = int(rep)

    # -- connection pooling ----------------------------------------------

    def _conn_get(self, name: str, url: str):
        """(conn, pooled): a keep-alive connection — pooled=True means
        it may have gone stale (server closed it between requests) and
        a transport failure on it warrants one fresh retry."""
        key = (name, url)
        with self._plock:
            pool = self._pools.get(key)
            if pool:
                return pool.pop(), True
        import socket

        u = urllib.parse.urlsplit(url)
        conn = http.client.HTTPConnection(
            u.hostname, u.port or 80, timeout=self.policy.probe_timeout_s
        )
        conn.connect()
        # Nagle + delayed-ACK would stall the headers/body send pair
        # ~40ms per proxied request — the router must add microseconds,
        # not a TCP timer
        conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return conn, False

    def _conn_put(self, name: str, url: str, conn) -> None:
        with self._plock:
            self._pools.setdefault((name, url), []).append(conn)

    def _prune_pools(self, urls: dict) -> None:
        """Drop (and close) pooled connections whose url is no longer
        any replica's CURRENT url: a supervised restart publishes a new
        port, and the old port's sockets would otherwise strand open
        under the dead key for the process lifetime. ``urls`` maps
        replica name → current base url (None while unpublished)."""
        live = {u for u in urls.values() if u}
        stale = []
        with self._plock:
            for key in list(self._pools):
                name, url = key
                current = (
                    url in live if name == "probe"
                    else urls.get(name) == url
                )
                if not current:
                    stale.extend(self._pools.pop(key))
        for conn in stale:  # close OUTSIDE _plock (leaf lock, no calls)
            try:
                conn.close()
            except OSError:
                pass

    # -- query path -------------------------------------------------------

    def route_query(self, tenant: str, exclude=()) -> tuple | None:
        """(name, url, spilled) for one query, or None when the rotation
        (minus ``exclude``) is empty. Bumps the in-flight count — pair
        with :meth:`finish_query`."""
        with self._lock:
            known = sorted(self.membership.replicas)
            rotation = {
                n: (r.queue_rows, self._inflight.get(n, 0))
                for n, r in self.membership.replicas.items()
                if r.state == IN and n not in exclude
                and r.url is not None
            }
            name, spilled = choose_replica(
                tenant, known, rotation,
                spill_queue_rows=self.policy.spill_queue_rows,
            )
            if name is None:
                return None
            self._inflight[name] = self._inflight.get(name, 0) + 1
            url = self.membership.replicas[name].url
        if spilled:
            self._registry().counter(
                "router_spills_total",
                help="queries routed off their affine replica",
            ).inc()
        return name, url, spilled

    def finish_query(self, name: str) -> None:
        with self._lock:
            self._inflight[name] = max(0, self._inflight.get(name, 0) - 1)

    def forward_query(self, tenant: str, body: bytes,
                      ctype: str) -> tuple:
        """(status, headers, body) — proxy one query to the chosen
        replica; on a TRANSPORT failure (never an HTTP status) retry
        once on a different replica: queries are idempotent, and the
        in-flight requests of a killed replica are exactly what the
        rolling-restart drill must not surface as 5xx."""
        reg = self._registry()
        exclude: set[str] = set()
        for _attempt in range(2):
            pick = self.route_query(tenant, exclude=exclude)
            if pick is None:
                reg.counter(
                    "router_no_replica_total",
                    help="requests refused with an empty rotation",
                ).inc()
                return 503, {"Retry-After": "1"}, _json_body({
                    "error": "no-replicas",
                    "detail": "no replica in rotation",
                    "tenant": tenant,
                })
            name, url, _sp = pick
            try:
                status, headers, data = self._proxy(
                    name, url, "/query", body,
                    {"Content-Type": ctype, TENANT_HEADER: tenant},
                    timeout_s=self.policy.request_timeout_s,
                )
            except (OSError, http.client.HTTPException, ValueError,
                    TimeoutError):
                reg.counter(
                    "router_proxy_failures_total",
                    help="transport failures talking to a replica",
                    labels={"replica": name},
                ).inc()
                exclude.add(name)
                continue
            finally:
                self.finish_query(name)
            reg.counter(
                "router_requests_total",
                help="queries proxied, by serving replica",
                labels={"replica": name},
            ).inc()
            headers["X-Routed-To"] = name
            return status, headers, data
        return 502, {}, _json_body({
            "error": "replica-unreachable",
            "detail": "transport failed on two replicas",
            "tenant": tenant,
        })

    def _proxy(self, name: str, url: str, path: str, body: bytes,
               headers: dict, *, timeout_s: float) -> tuple:
        """One proxied round trip over a pooled keep-alive connection;
        a stale pooled connection is retried once on a fresh one, a
        fresh-connection failure propagates to the caller."""
        while True:
            conn, pooled = self._conn_get(name, url)
            conn.timeout = timeout_s
            if conn.sock is not None:
                conn.sock.settimeout(timeout_s)
            try:
                conn.request("POST", path, body=body, headers=headers)
                resp = conn.getresponse()
                data = resp.read()
            except (OSError, http.client.HTTPException, ValueError,
                    TimeoutError):
                try:
                    conn.close()
                except OSError:
                    pass
                if not pooled:
                    raise
                continue
            out_headers = {}
            for h in ("Content-Type", "Retry-After"):
                v = resp.getheader(h)
                if v is not None:
                    out_headers[h] = v
            self._conn_put(name, url, conn)
            return resp.status, out_headers, data

    # -- mutation path ----------------------------------------------------

    def mutate(self, path: str, tenant: str, body: bytes) -> tuple:
        """(status, doc): sequence one mutation and fan it out to every
        in-rotation replica under the mutation lock — the lock IS the
        ordering authority (two concurrent mutations serialize here, so
        every replica sees the same order the log records). A replica
        that fails transiently is left lagging; the probe loop replays
        it forward (duplicates suppressed replica-side)."""
        try:
            doc = json.loads(body)
            if not isinstance(doc, dict) or "ids" not in doc:
                raise ValueError("mutation body must carry ids")
        except (ValueError, TypeError) as e:
            return 400, {"error": f"malformed mutation: {e}"}
        reg = self._registry()
        with self._mutlock:
            with self._lock:
                targets = [
                    (n, self.membership.replicas[n].url)
                    for n in self.membership.in_rotation()
                    if self.membership.replicas[n].url is not None
                ]
            if not targets:
                reg.counter(
                    "router_no_replica_total",
                    help="requests refused with an empty rotation",
                ).inc()
                return 503, {
                    "error": "no-replicas",
                    "detail": "no replica in rotation",
                    "tenant": tenant,
                }
            seq = self.log.append(path, tenant, body)
            with self._lock:  # lock order: _mutlock -> _lock
                self._log_posture = (self.log.seq, self.log.min_seq)
            reg.counter(
                "router_mutations_total",
                help="mutations sequenced, by route",
                labels={"path": path.lstrip("/")},
            ).inc()
            results: dict[str, tuple] = {}
            for name, url in targets:
                status, rdoc = self._post_to(
                    name, url, path, body, tenant, seq,
                    timeout_s=self.policy.fanout_timeout_s,
                )
                results[name] = (status, rdoc)
                if status in _DETERMINISTIC:
                    self._note_leg(name, seq, rdoc)
                else:
                    reg.counter(
                        "router_fanout_failures_total",
                        help="mutation fan-out legs that failed "
                        "(replayed later)",
                        labels={"replica": name},
                    ).inc()
        applied = sorted(n for n, (s, _) in results.items() if s == 200)
        failed = sorted(n for n in results if n not in applied)
        first_doc = next(
            (d for _n, (s, d) in sorted(results.items())
             if s == 200 and isinstance(d, dict)),
            None,
        )
        if not applied:
            # every leg failed: surface the first replica's verdict when
            # it was deterministic (a 400 IS a 400), else a structured 502
            status0, doc0 = results[sorted(results)[0]]
            if status0 in _DETERMINISTIC and isinstance(doc0, dict):
                return status0, {**doc0, "seq": seq, "failed": failed}
            return 502, {
                "error": "fanout-failed", "seq": seq, "failed": failed,
            }
        return 200, {
            "seq": seq, "applied": applied, "failed": failed,
            "result": first_doc,
        }

    def _post_to(self, name: str, url: str, path: str, body: bytes,
                 tenant: str, seq: int, *, timeout_s: float) -> tuple:
        """(status, doc-or-None) for one mutation leg; transport
        failures come back as status 0, never an exception."""
        try:
            status, _h, data = self._proxy(
                name, url, path, body,
                {
                    "Content-Type": "application/json",
                    TENANT_HEADER: tenant,
                    SEQ_HEADER: str(seq),
                },
                timeout_s=timeout_s,
            )
        except (OSError, http.client.HTTPException, ValueError,
                TimeoutError):
            return 0, None
        try:
            return status, json.loads(data)
        except ValueError:
            return status, None

    # -- posture ----------------------------------------------------------

    def stats(self) -> dict:
        """The router's own ``GET /healthz`` document. Reads the log's
        PUBLISHED posture, never ``_mutlock``: the mutation lock is held
        across fan-out/replay I/O, and the health endpoint must answer
        while a wedged replica is timing a leg out."""
        with self._lock:
            seq, min_seq = self._log_posture
            replicas = self.membership.posture()
            rotation = self.membership.in_rotation()
            inflight = dict(sorted(self._inflight.items()))
            # mirror the index facts (dim/k/backend/...) from any probed
            # replica, so a load generator can point at the router and
            # shape requests exactly as it would against one replica
            facts = {}
            for _n, r in sorted(self.membership.replicas.items()):
                if r.doc is not None:
                    facts = {
                        key: r.doc.get(key)
                        for key in ("dim", "k", "backend",
                                    "max_batch_rows")
                        if key in r.doc
                    }
                    break
        doc = {
            "ok": True,
            "role": "router",
            **facts,
            "uptime_s": round(time.monotonic() - self.started_s, 3),
            "seq": seq,
            "min_buffered_seq": min_seq,
            "rotation": rotation,
            "replicas": replicas,
            "inflight": inflight,
            "policy": {
                "probe_interval_s": self.policy.probe_interval_s,
                "evict_after": self.policy.evict_after,
                "rejoin_after": self.policy.rejoin_after,
                "spill_queue_rows": self.policy.spill_queue_rows,
                "replay_buffer": self.policy.replay_buffer,
            },
        }
        if self.supervisor is not None:
            doc["children"] = self.supervisor.posture()
        return doc

    def _registry(self):
        return obs_metrics.get_registry()


def _json_body(doc: dict) -> bytes:
    return (json.dumps(doc) + "\n").encode()


# ---------------------------------------------------------------------------
# HTTP shell


def _router_handler(router: Router, quiet: bool = True):
    """The handler class bound to one router (closure construction, the
    front end's convention — stdlib handlers have no constructor
    channel)."""
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # noqa: A003
            if not quiet:
                BaseHTTPRequestHandler.log_message(self, fmt, *args)

        def _send(self, status: int, headers: dict, body: bytes) -> None:
            self.send_response(status)
            for k, v in headers.items():
                self.send_header(k, v)
            if "Content-Type" not in headers:
                self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _body(self) -> bytes:
            n = int(self.headers.get("Content-Length") or 0)
            return self.rfile.read(n) if n > 0 else b""

        def do_POST(self):  # noqa: N802 — stdlib handler convention
            tenant = self.headers.get(TENANT_HEADER, DEFAULT_TENANT)
            body = self._body()
            if self.path == "/query":
                ctype = (
                    self.headers.get("Content-Type")
                    or "application/octet-stream"
                )
                status, headers, data = router.forward_query(
                    tenant, body, ctype
                )
                self._send(status, headers, data)
            elif self.path in ("/upsert", "/delete"):
                status, doc = router.mutate(self.path, tenant, body)
                self._send(status, {}, _json_body(doc))
            else:
                self._send(404, {}, _json_body(
                    {"error": f"no such route {self.path}"}
                ))

        def do_GET(self):  # noqa: N802
            if self.path == "/healthz":
                self._send(200, {}, _json_body(router.stats()))
            elif self.path == "/metrics":
                text = obs_metrics.get_registry().to_prometheus()
                self._send(
                    200,
                    {"Content-Type": "text/plain; version=0.0.4"},
                    text.encode(),
                )
            else:
                self._send(404, {}, _json_body(
                    {"error": f"no such route {self.path}"}
                ))

    return Handler


class RouterHTTPServer:
    """``ThreadingHTTPServer`` wrapper for the router — the front end
    server's bind/serve/stop shape, ``--port 0`` picks an ephemeral
    port."""

    def __init__(self, router: Router, host: str = "127.0.0.1",
                 port: int = 0, quiet: bool = True):
        from mpi_knn_tpu.frontend.server import _tuned_server_class

        self.router = router
        self._httpd = _tuned_server_class()(
            (host, port), _router_handler(router, quiet)
        )
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="router-http",
            daemon=True,
        )

    @property
    def address(self) -> tuple:
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def start(self) -> "RouterHTTPServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(10.0)


# ---------------------------------------------------------------------------
# replica supervisor


class ReplicaSupervisor:
    """N supervised ``mpi-knn serve`` children — one thread per slot
    looping :func:`~mpi_knn_tpu.resilience.worker.run_supervised`, so a
    crashed replica is restarted (and then health-gated back into
    rotation by the router; the supervisor only keeps processes alive,
    it never touches membership). Children bind ``--port 0`` and publish
    their URL to a per-slot ready file (atomic rename), which doubles as
    discovery: the prober re-reads it every cycle, so a restarted child
    on a new port is found without any registration channel."""

    def __init__(self, count: int, serve_args, *, workdir: str,
                 restart_backoff_s: float = 0.5):
        if count < 1:
            raise ValueError("need at least one replica")
        self.count = count
        self.serve_args = list(serve_args)
        self.workdir = workdir
        self.restart_backoff_s = restart_backoff_s
        os.makedirs(workdir, exist_ok=True)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._pids: dict[str, int] = {}
        self._last: dict[str, dict] = {}
        self._threads = [
            threading.Thread(
                target=self._supervise, args=(i,),
                name=f"replica-supervisor-{i}", daemon=True,
            )
            for i in range(count)
        ]

    def names(self) -> list[str]:
        return [f"r{i}" for i in range(self.count)]

    def _ready_file(self, name: str) -> str:
        return os.path.join(self.workdir, f"{name}.url")

    def start(self) -> "ReplicaSupervisor":
        for t in self._threads:
            t.start()
        return self

    def stop(self, timeout_s: float = 30.0) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout_s)

    def _supervise(self, i: int) -> None:
        from mpi_knn_tpu.resilience.worker import (
            python_worker_argv,
            run_supervised,
        )

        name = f"r{i}"
        ready = self._ready_file(name)
        while not self._stop.is_set():
            try:
                os.unlink(ready)  # a dead child's URL must not linger
            except OSError:
                pass
            argv = python_worker_argv(
                "-m", "mpi_knn_tpu", "serve", *self.serve_args,
                "--port", "0", "--ready-file", ready, "-q",
            )

            def note_pid(pid: int, name=name) -> None:
                with self._lock:
                    self._pids[name] = pid

            res = run_supervised(
                argv, beat_timeout_s=None, wall_timeout_s=None,
                stop_event=self._stop, on_spawn=note_pid,
            )
            with self._lock:
                self._pids.pop(name, None)
                self._last[name] = {
                    "status": res.status,
                    "returncode": res.returncode,
                    "reason": res.reason,
                    "stderr_tail": res.stderr_tail[-512:],
                }
            if self._stop.is_set():
                break
            obs_metrics.get_registry().counter(
                "router_replica_restarts_total",
                help="supervised replica children restarted",
                labels={"replica": name},
            ).inc()
            obs_spans.event(
                "replica-exit", cat="router", replica=name,
                status=res.status,
                returncode=res.returncode if res.returncode is not None
                else -1,
            )
            self._stop.wait(self.restart_backoff_s)

    def url(self, name: str) -> str | None:
        """The replica's published base URL — None while it is (re)
        booting. Read from the ready file every time: the file IS the
        discovery channel and a restart rewrites it."""
        try:
            with open(self._ready_file(name)) as f:
                url = f.read().strip()
            return url or None
        except OSError:
            return None

    def pid(self, name: str) -> int | None:
        with self._lock:
            return self._pids.get(name)

    def posture(self) -> dict:
        with self._lock:
            pids = dict(self._pids)
            last = {n: dict(d) for n, d in self._last.items()}
        return {
            name: {
                "pid": pids.get(name),
                "url": self.url(name),
                "last_exit": last.get(name),
            }
            for name in self.names()
        }
