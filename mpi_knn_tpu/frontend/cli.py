"""``mpi-knn serve`` and ``mpi-knn loadgen`` — the network front end and
its load generator.

``serve`` builds a device-resident index over ``--data`` (the run
driver's corpus specs), wraps a :class:`~mpi_knn_tpu.serve.ServeSession`
in the coalescing front end, and listens on a loopback (or given) HTTP
port: ``POST /query`` (JSON or raw f32 rows, ``X-Tenant`` header),
``GET /metrics`` (Prometheus exposition), ``GET /healthz``. ``--port 0``
binds an ephemeral port; ``--ready-file`` writes the final URL once the
server is listening (the CI gate's rendezvous — parsing a log for a port
number is a race, a file appearing is not).

``loadgen`` drives a running server with open-loop multi-tenant load and
prints/writes the throughput-vs-p50/p99 rows (``frontend/loadgen.py``;
``--sweep`` runs several offered-QPS levels).

Usage error convention as everywhere: combinations the stack cannot
honor exit 2 loudly.

Examples::

    mpi-knn serve --data sift:100000 --k 10 --bucket 512 --port 8080
    mpi-knn serve --data synthetic:8192x64c10 --port 0 \
        --ready-file /tmp/knn.url --flight-record flight.jsonl
    mpi-knn loadgen --url http://127.0.0.1:8080 --tenants 8 \
        --qps 50 --requests 40 --rows 16 --report curve.json
    mpi-knn loadgen --url http://127.0.0.1:8080 --sweep 10,50,200
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading
import time

from mpi_knn_tpu.config import (
    BACKENDS,
    PRECISION_POLICIES,
    KNNConfig,
)


def build_serve_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mpi-knn serve",
        description="multi-tenant HTTP serving front end: async request "
        "coalescing into the bucketed AOT executable cache, SLO-aware "
        "admission, queue-driven degradation ladder",
    )
    d = p.add_argument_group("data / index")
    d.add_argument("--data", default="mnist",
                   help="corpus spec (run-driver forms: 'mnist', 'digits', "
                   "'synthetic:MxDcC', 'sift:M', *.fvecs/bvecs, .mat)")
    d.add_argument("--limit", type=int, default=None)
    d.add_argument("--k", type=int, default=30)
    d.add_argument("--backend", choices=BACKENDS, default="auto")
    d.add_argument("--devices", type=int, default=None,
                   help="ring size for distributed backends")
    d.add_argument("--dtype", default="float32",
                   choices=["float32", "bfloat16", "float64"])
    d.add_argument("--query-tile", type=int, default=1024)
    d.add_argument("--corpus-tile", type=int, default=2048)
    d.add_argument("--precision-policy", choices=list(PRECISION_POLICIES),
                   default="exact")
    d.add_argument("--bucket", type=int, default=1024,
                   help="base row bucket of the executable cache; batches "
                   "pad to bucket*2^j rows")
    d.add_argument("--dispatch-depth", type=int, default=2)
    d.add_argument("--partitions", type=int, default=None,
                   help="serve a CLUSTERED (IVF) index: train this many "
                   "k-means partitions at startup (sublinear probing; "
                   "enables the background compactor for live mutation)")
    d.add_argument("--nprobe", type=int, default=None,
                   help="partitions probed per query (None with "
                   "--partitions = recall-targeted auto-tune)")
    d.add_argument("--bucket-headroom", type=float, default=0.0,
                   help="fractional spare capacity per bucket/tile for "
                   "LIVE mutation (POST /upsert, /delete — ISSUE 14): "
                   "pre-allocated free slots the donated in-place "
                   "scatters fill without a recompile. 0.0 (default) = "
                   "zero-rent frozen corpus; 0.25-0.5 for mutable ones "
                   "(headroom rows ride the fixed-shape FLOPs)")
    d.add_argument("--mutation-bucket", type=int, default=256,
                   help="base row bucket of the mutation executables "
                   "(chunks pad to mutation_bucket*2^j)")
    d.add_argument("--compactor-interval-s", type=float, default=0.25,
                   help="background compactor trigger-poll period for "
                   "clustered indices; 0 disables the compactor")
    d.add_argument("--compact-fill-threshold", type=float, default=0.9)
    d.add_argument("--compact-tombstone-fraction", type=float,
                   default=0.3)
    d.add_argument("--cache-dir", default=None, metavar="DIR",
                   help="persistent AOT executable cache "
                   "(serve/aotcache.py; also via TKNN_AOT_CACHE): a "
                   "restarted server revives every executable it has "
                   "ever compiled from disk instead of re-paying XLA — "
                   "the second start against one dir warms with zero "
                   "backend compiles. Stale/corrupt entries fall back "
                   "to a real compile loudly; the dir is safe to share "
                   "between concurrent processes (atomic-rename writes)")
    d.add_argument("--warm-threads", type=int, default=None,
                   help="thread-pool width of the start-up warm "
                   "(default: auto = min(cells, cpu count); 1 forces "
                   "the sequential walk)")

    f = p.add_argument_group("front end (coalescing / SLO)")
    f.add_argument("--max-wait-ms", type=float, default=2.0,
                   help="coalescing deadline: no request waits longer "
                   "than this for co-travelers before its batch "
                   "dispatches ragged")
    f.add_argument("--max-batch-rows", type=int, default=None,
                   help="coalesced batch row target (default: --bucket, "
                   "so steady-state fill batches land in one executable)")
    f.add_argument("--max-queue-rows", type=int, default=8192,
                   help="per-tenant queued-row ceiling; beyond it "
                   "requests are refused with a structured 429")
    f.add_argument("--tenant-qps", type=float, default=None,
                   help="per-tenant admission rate limit (token bucket "
                   "of --burst); default unlimited")
    f.add_argument("--burst", type=int, default=32)
    f.add_argument("--shed-queue-rows", type=int, default=None,
                   help="total queued rows that, sustained for "
                   "--shed-hold-ms, walk the serving degradation ladder "
                   "one rung down (recovery restores it); default: never "
                   "shed")
    f.add_argument("--shed-hold-ms", type=float, default=50.0)
    f.add_argument("--recover-hold-ms", type=float, default=250.0)

    n = p.add_argument_group("network / output")
    n.add_argument("--host", default="127.0.0.1")
    n.add_argument("--port", type=int, default=8080,
                   help="0 = ephemeral (printed, and written to "
                   "--ready-file)")
    n.add_argument("--request-timeout-s", type=float, default=30.0)
    n.add_argument("--ready-file", default=None, metavar="PATH",
                   help="write the listening URL here once ready (script "
                   "rendezvous)")
    n.add_argument("--flight-record", default=None, metavar="JSONL",
                   help="span flight record (coalesce events, batch "
                   "spans with tenant composition, shed/restore walks)")
    n.add_argument("--metrics-out", default=None, metavar="JSON",
                   help="write the metrics-registry snapshot at shutdown")
    n.add_argument("--platform", choices=["auto", "cpu", "tpu"],
                   default="auto")
    n.add_argument("-q", "--quiet", action="store_true")
    return p


def serve_main(argv=None) -> int:
    args = build_serve_parser().parse_args(argv)
    if args.max_wait_ms < 0:
        print("error: --max-wait-ms must be >= 0", file=sys.stderr)
        return 2
    if args.port < 0:
        print("error: --port must be >= 0", file=sys.stderr)
        return 2
    if args.recover_hold_ms < 0 or args.shed_hold_ms < 0:
        print("error: hold times must be >= 0", file=sys.stderr)
        return 2
    if args.shed_queue_rows is None and (
        args.shed_hold_ms != 50.0 or args.recover_hold_ms != 250.0
    ):
        # the serve-CLI inert-knob convention: hold times only matter
        # once a shed threshold exists
        print("error: --shed-hold-ms/--recover-hold-ms without "
              "--shed-queue-rows: no shed threshold is set, so the "
              "knobs would be silently inert", file=sys.stderr)
        return 2

    if args.warm_threads is not None and args.warm_threads < 1:
        print("error: --warm-threads must be >= 1", file=sys.stderr)
        return 2

    if args.flight_record:
        from mpi_knn_tpu.obs.spans import FlightRecorder, set_recorder

        set_recorder(FlightRecorder(args.flight_record, fresh=True))

    if args.cache_dir:
        from mpi_knn_tpu.serve import aotcache

        aotcache.set_cache_dir(args.cache_dir)

    if args.platform != "auto":
        from mpi_knn_tpu.utils.platform import force_platform

        force_platform(
            args.platform,
            n_devices=(args.devices if args.platform == "cpu" else None),
        )

    from mpi_knn_tpu.cli import load_corpus
    from mpi_knn_tpu.frontend.scheduler import SLOPolicy
    from mpi_knn_tpu.frontend.server import Frontend, FrontendHTTPServer
    from mpi_knn_tpu.resilience import ResiliencePolicy
    from mpi_knn_tpu.serve import ServeSession, build_index

    X, _, source = load_corpus(args.data, limit=args.limit)
    try:
        cfg = KNNConfig(
            k=args.k,
            backend=args.backend,
            dtype=args.dtype,
            query_tile=args.query_tile,
            corpus_tile=args.corpus_tile,
            precision_policy=args.precision_policy,
            num_devices=args.devices,
            query_bucket=args.bucket,
            dispatch_depth=args.dispatch_depth,
            partitions=args.partitions,
            nprobe=args.nprobe,
            bucket_headroom=args.bucket_headroom,
            mutation_bucket=args.mutation_bucket,
            compact_fill_threshold=args.compact_fill_threshold,
            compact_tombstone_fraction=args.compact_tombstone_fraction,
        )
        policy = SLOPolicy(
            max_batch_rows=args.max_batch_rows or args.bucket,
            max_wait_s=args.max_wait_ms / 1e3,
            max_queue_rows=max(
                args.max_queue_rows, args.max_batch_rows or args.bucket
            ),
            max_tenant_qps=args.tenant_qps,
            burst=args.burst,
            shed_queue_rows=args.shed_queue_rows,
            shed_hold_s=args.shed_hold_ms / 1e3,
            recover_hold_s=args.recover_hold_ms / 1e3,
        )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    t0 = time.perf_counter()
    try:
        if args.partitions is not None:
            # the clustered index serves through the same engine/front
            # end (it duck-types CorpusIndex) — and is the layout the
            # background compactor supervises
            from mpi_knn_tpu.ivf import build_ivf_index

            index = build_ivf_index(X, cfg)
        else:
            index = build_index(X, cfg)
        # a ResiliencePolicy (even the default) builds the degradation
        # ladder the queue-driven shed walks; without one the session
        # would have only its full rung
        session = ServeSession(index, resilience=ResiliencePolicy())
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    # cold-start order (ISSUE 12): bind the port and write the ready
    # file BEFORE warming — the warm-up runs on a background thread and
    # /healthz reports its buckets-ready/total progress, so time-to-
    # listening is index load, not the compile matrix; traffic is
    # admitted per bucket as executables land (a not-yet-ready bucket
    # gets a structured 503 "warming", never a hung socket)
    frontend = Frontend(session, policy)
    frontend.start(
        background=True, warm_parallel=args.warm_threads,
    )
    server = FrontendHTTPServer(
        frontend, host=args.host, port=args.port,
        request_timeout_s=args.request_timeout_s, quiet=args.quiet,
    ).start()
    build_s = time.perf_counter() - t0
    if not args.quiet:
        print(
            f"[mpi-knn serve] {source} shape={list(X.shape)} "
            f"backend={index.backend} k={cfg.k} bucket={cfg.query_bucket} "
            f"max_wait={args.max_wait_ms}ms (index+bind {build_s:.2f}s, "
            "warming in background)"
        )
        print(f"[mpi-knn serve] listening on {server.url}", flush=True)
    if args.ready_file:
        # atomic publish (utils.atomicio, host-lint rule H4): the CI
        # gate polls this file from another process while it is being
        # written — it must read nothing or the full URL, never a
        # truncated prefix
        from mpi_knn_tpu.utils.atomicio import atomic_write_text

        atomic_write_text(args.ready_file, server.url + "\n")

    def _report_warm():
        frontend._serving_ready.wait()
        rep = session.warm_report or {}
        if not args.quiet and rep:
            print(
                f"[mpi-knn serve] warm done in {rep.get('wall_s')}s: "
                f"{rep.get('cells')} cells ({rep.get('compiled')} "
                f"compiled, {rep.get('loaded')} from cache, "
                f"{rep.get('deduped')} deduped)"
                + (f" cache={args.cache_dir}" if args.cache_dir else ""),
                flush=True,
            )

    threading.Thread(target=_report_warm, daemon=True,
                     name="warm-report").start()

    # background compaction (ISSUE 14): clustered indices get the
    # trigger-driven re-cluster/compact worker (heartbeat/flight-
    # recorded, deferred while the session sheds load); the dense
    # layouts reclaim tombstones in place and need none
    compactor = None
    if args.compactor_interval_s > 0 and index.backend in (
        "ivf", "ivf-sharded"
    ):
        compactor = session.start_compactor(
            interval_s=args.compactor_interval_s
        )

    stop = threading.Event()

    def _sig(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    try:
        while not stop.wait(0.5):
            pass
    finally:
        if compactor is not None:
            compactor.stop()
        server.stop()
        frontend.stop()
        if args.metrics_out:
            from mpi_knn_tpu.obs.metrics import get_registry
            from mpi_knn_tpu.utils.atomicio import atomic_write_text

            atomic_write_text(
                args.metrics_out,
                json.dumps(get_registry().snapshot(), indent=1) + "\n",
            )
        if not args.quiet:
            st = frontend.stats()
            print(
                f"[mpi-knn serve] shutdown: {st['queries_served']} query "
                f"rows in {st['batches_retired']} batches, "
                f"{st['rejected']} rejected, rung={st['rung']}"
            )
    return 0


# ---------------------------------------------------------------------------


def build_loadgen_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mpi-knn loadgen",
        description="open-loop multi-tenant load generator for a running "
        "`mpi-knn serve` (throughput-vs-p50/p99 rows; open loop so an "
        "overloaded server shows growing latency, not a slowing client)",
    )
    p.add_argument("--url", default=None,
                   help="server base URL (e.g. http://127.0.0.1:8080)")
    p.add_argument("--targets", default=None, metavar="URL1,URL2,...",
                   help="drive several endpoints at once (tenant i pins "
                   "to target i mod N — the router drill's multi-replica "
                   "direct baseline); replaces --url")
    p.add_argument("--connect", choices=["reuse", "per-request"],
                   default="reuse",
                   help="HTTP transport: 'reuse' = fixed worker pool "
                   "with persistent keep-alive connections (default); "
                   "'per-request' = legacy fresh connect + thread per "
                   "request")
    p.add_argument("--connections", type=int, default=4,
                   help="keep-alive connections per tenant stream "
                   "(reuse mode)")
    p.add_argument("--tenants", type=int, default=4,
                   help="concurrent tenant streams")
    p.add_argument("--qps", type=float, default=20.0,
                   help="offered request rate PER TENANT stream")
    p.add_argument("--sweep", default=None, metavar="Q1,Q2,...",
                   help="sweep these offered per-tenant QPS levels "
                   "instead of the single --qps")
    p.add_argument("--requests", type=int, default=20,
                   help="requests per tenant per level")
    p.add_argument("--rows", type=int, default=16,
                   help="query rows per request")
    p.add_argument("--timeout-s", type=float, default=30.0)
    p.add_argument("--report", default=None, help="write JSON rows here")
    p.add_argument("-q", "--quiet", action="store_true")
    return p


def loadgen_main(argv=None) -> int:
    args = build_loadgen_parser().parse_args(argv)
    if args.tenants < 1 or args.requests < 1 or args.rows < 1:
        print("error: --tenants/--requests/--rows must be >= 1",
              file=sys.stderr)
        return 2
    if args.qps <= 0:
        print("error: --qps must be > 0", file=sys.stderr)
        return 2
    if args.connections < 1:
        print("error: --connections must be >= 1", file=sys.stderr)
        return 2
    targets = None
    if args.targets:
        targets = [u.strip() for u in args.targets.split(",") if u.strip()]
        if not targets:
            print(f"error: bad --targets {args.targets!r}",
                  file=sys.stderr)
            return 2
    if targets is None and not args.url:
        print("error: one of --url / --targets is required",
              file=sys.stderr)
        return 2
    levels = [args.qps]
    if args.sweep:
        try:
            levels = [float(v) for v in args.sweep.split(",") if v.strip()]
        except ValueError:
            levels = []
        if not levels or any(v <= 0 for v in levels):
            print(f"error: bad --sweep {args.sweep!r}: want a "
                  "comma-separated list of positive QPS levels",
                  file=sys.stderr)
            return 2

    from mpi_knn_tpu.frontend import loadgen

    probe_url = targets[0] if targets else args.url
    try:
        health = loadgen.probe_server(probe_url, timeout_s=args.timeout_s)
    except OSError as e:
        print(f"error: cannot reach {probe_url}: {e}", file=sys.stderr)
        return 2
    if not args.quiet:
        print(
            f"[mpi-knn loadgen] {probe_url}"
            + (f" (+{len(targets) - 1} more)"
               if targets and len(targets) > 1 else "")
            + f": backend={health['backend']} "
            f"dim={health['dim']} k={health['k']} "
            f"max_batch_rows={health['max_batch_rows']} "
            f"connect={args.connect}"
        )
    rows_out = []
    for qps in sorted(levels):
        rep = loadgen.run_http(
            args.url, targets=targets, tenants=args.tenants, qps=qps,
            n_requests=args.requests, rows=args.rows,
            timeout_s=args.timeout_s, connect=args.connect,
            connections=args.connections,
        )
        rows_out.append(rep)
        if not args.quiet:
            print(
                f"  offered {rep['offered_qps_total']:g} req/s "
                f"({args.tenants} tenants): achieved "
                f"{rep['achieved_rps']} req/s "
                f"({rep['achieved_qps_rows']} rows/s), "
                f"p50 {rep['p50_ms']}ms p99 {rep['p99_ms']}ms, "
                f"rejected {rep['rejected']}, errors {rep['errors']}"
            )
    if any(r["errors"] for r in rows_out):
        print("error: load run saw serving errors (not 200/429)",
              file=sys.stderr)
        return 1
    if args.report:
        from mpi_knn_tpu.utils.atomicio import atomic_write_text

        atomic_write_text(args.report, json.dumps({
            "schema": "mpi_knn_tpu.frontend.loadgen/1",
            "url": probe_url,
            "targets": targets,
            "connect": args.connect,
            "health": health,
            "rows": rows_out,
        }, indent=1) + "\n")
        if not args.quiet:
            print(f"report written to {args.report}")
    return 0


# ---------------------------------------------------------------------------


def build_router_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mpi-knn router",
        description="replicated serving tier (ISSUE 18): a jax-free "
        "router fronting N `mpi-knn serve` replicas of one artifact — "
        "health-gated membership, tenant-affine (rendezvous-hash) "
        "spread with least-queued spill, sequenced mutation fan-out "
        "with bounded replay, optional supervised replica spawning",
        epilog="with --spawn, arguments after `--` are passed through "
        "to every `mpi-knn serve` child (e.g. `mpi-knn router --spawn 3 "
        "--cache-dir /tmp/aot -- --data synthetic:4096x32c4 --k 10`)",
    )
    m = p.add_argument_group("fleet")
    m.add_argument("--replicas", default=None, metavar="URL1,URL2,...",
                   help="static fleet: base URLs of running replicas "
                   "(named r0, r1, ... in probe order)")
    m.add_argument("--spawn", type=int, default=None, metavar="N",
                   help="launch and supervise N `mpi-knn serve` children "
                   "(resilience/worker.py: crashed replicas restart and "
                   "are health-gated back in); serve flags follow `--`")
    m.add_argument("--cache-dir", default=None, metavar="DIR",
                   help="shared AOT executable cache for spawned "
                   "replicas: replica cold start rides the cache, so "
                   "second-and-later replicas compile zero programs")
    m.add_argument("--workdir", default=None, metavar="DIR",
                   help="spawn mode: ready-file directory (default: a "
                   "fresh temp dir)")

    r = p.add_argument_group("membership / routing")
    r.add_argument("--probe-interval-ms", type=float, default=500.0,
                   help="health-poll period (the router's own clock)")
    r.add_argument("--evict-after", type=int, default=3,
                   help="consecutive probe failures before eviction")
    r.add_argument("--rejoin-after", type=int, default=2,
                   help="consecutive ready probes before (re)join")
    r.add_argument("--spill-queue-rows", type=int, default=4096,
                   help="/healthz queue depth beyond which the affine "
                   "replica spills to the least-queued one")
    r.add_argument("--replay-buffer", type=int, default=4096,
                   help="bounded mutation replay buffer (entries); a "
                   "replica whose gap falls off it is quarantined until "
                   "cold-reloaded")

    n = p.add_argument_group("network / output")
    n.add_argument("--host", default="127.0.0.1")
    n.add_argument("--port", type=int, default=8090,
                   help="0 = ephemeral (printed, and written to "
                   "--ready-file)")
    n.add_argument("--request-timeout-s", type=float, default=30.0)
    n.add_argument("--ready-file", default=None, metavar="PATH",
                   help="write the router URL here once listening")
    n.add_argument("--flight-record", default=None, metavar="JSONL",
                   help="span flight record (membership transitions, "
                   "replica exits)")
    n.add_argument("--metrics-out", default=None, metavar="JSON",
                   help="write the metrics-registry snapshot at shutdown")
    n.add_argument("-q", "--quiet", action="store_true")
    p.add_argument("serve_args", nargs=argparse.REMAINDER,
                   help="after `--`: flags for every spawned `mpi-knn "
                   "serve` child")
    return p


def router_main(argv=None) -> int:
    args = build_router_parser().parse_args(argv)
    if (args.replicas is None) == (args.spawn is None):
        print("error: exactly one of --replicas / --spawn is required",
              file=sys.stderr)
        return 2
    if args.spawn is not None and args.spawn < 1:
        print("error: --spawn must be >= 1", file=sys.stderr)
        return 2
    if args.replicas is not None and (args.cache_dir or args.workdir):
        print("error: --cache-dir/--workdir only apply to --spawn "
              "(a static fleet owns its own caches)", file=sys.stderr)
        return 2
    serve_args = list(args.serve_args)
    if serve_args and serve_args[0] == "--":
        serve_args = serve_args[1:]
    if serve_args and args.spawn is None:
        print("error: serve pass-through args require --spawn",
              file=sys.stderr)
        return 2

    if args.flight_record:
        from mpi_knn_tpu.obs.spans import FlightRecorder, set_recorder

        set_recorder(FlightRecorder(args.flight_record, fresh=True))

    from mpi_knn_tpu.frontend.router import (
        ReplicaSupervisor,
        Router,
        RouterHTTPServer,
        RouterPolicy,
    )

    try:
        policy = RouterPolicy(
            probe_interval_s=args.probe_interval_ms / 1e3,
            evict_after=args.evict_after,
            rejoin_after=args.rejoin_after,
            spill_queue_rows=args.spill_queue_rows,
            replay_buffer=args.replay_buffer,
            request_timeout_s=args.request_timeout_s,
        )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    supervisor = None
    replicas = None
    if args.spawn is not None:
        if args.cache_dir:
            serve_args += ["--cache-dir", args.cache_dir]
        workdir = args.workdir
        if workdir is None:
            import tempfile

            workdir = tempfile.mkdtemp(prefix="tknn-router-")
        supervisor = ReplicaSupervisor(
            args.spawn, serve_args, workdir=workdir
        ).start()
    else:
        urls = [u.strip() for u in args.replicas.split(",") if u.strip()]
        if not urls:
            print(f"error: bad --replicas {args.replicas!r}",
                  file=sys.stderr)
            return 2
        replicas = {f"r{i}": u for i, u in enumerate(urls)}

    router = Router(
        replicas, policy=policy, supervisor=supervisor
    ).start()
    server = RouterHTTPServer(
        router, host=args.host, port=args.port, quiet=args.quiet
    ).start()
    if not args.quiet:
        fleet = (
            f"{args.spawn} spawned replicas" if supervisor is not None
            else f"{len(replicas)} static replicas"
        )
        print(f"[mpi-knn router] fronting {fleet}; "
              f"listening on {server.url}", flush=True)
    if args.ready_file:
        from mpi_knn_tpu.utils.atomicio import atomic_write_text

        atomic_write_text(args.ready_file, server.url + "\n")

    stop = threading.Event()

    def _sig(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    try:
        while not stop.wait(0.5):
            pass
    finally:
        server.stop()
        router.stop()
        if supervisor is not None:
            supervisor.stop()
        if args.metrics_out:
            from mpi_knn_tpu.obs.metrics import get_registry
            from mpi_knn_tpu.utils.atomicio import atomic_write_text

            atomic_write_text(
                args.metrics_out,
                json.dumps(get_registry().snapshot(), indent=1) + "\n",
            )
        if not args.quiet:
            st = router.stats()
            print(
                f"[mpi-knn router] shutdown: seq={st['seq']} "
                f"rotation={st['rotation']}"
            )
    return 0


if __name__ == "__main__":
    sys.exit(serve_main())
