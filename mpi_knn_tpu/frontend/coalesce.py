"""Deterministic request coalescing — many small concurrent streams in,
bucket-sized batches out.

Why coalescing: TPU-KNN reaches peak FLOP/s only on large uniform
batches, and the serve engine's bucketed AOT cache (``serve/engine.py``)
was built for exactly that — but real concurrent traffic arrives as many
small per-client requests. Serving each alone would pad every 16-row
request up to the base bucket and burn the pad rows as wasted compute
(or, without buckets, compile per shape). The coalescer merges requests
from many tenants into one batch near the bucket size, so steady-state
traffic fills the executables the cache already has: the front end adds
NO new programs, only fills existing buckets (machine-checked by the
``frontend`` lint cell, which lowers a coalesced batch through the
production ``serve.engine.lower_bucket``).

The batching math (DESIGN.md "Serving front end"):

- **admit until the bucket fills or the oldest request's wait budget
  expires.** A batch forms when pending rows reach ``max_batch_rows``
  (reason ``"fill"`` — offered load is high enough to fill buckets, the
  peak-throughput regime) or when ``now − oldest.arrival ≥ max_wait_s``
  (reason ``"deadline"`` — the latency floor under light load: no
  request ever waits more than ``max_wait_s`` for co-travelers).
- **round-robin draining with deadline-first rotation.** Requests stay
  in per-tenant FIFO queues; a forming batch takes ONE whole request per
  tenant per rotation pass, starting at the tenant owning the globally
  oldest request (so a deadline-triggered batch always contains the
  request whose deadline triggered it), cycling in first-seen tenant
  order until the next head does not fit or nothing is pending. One
  request per tenant per pass is the no-starvation guarantee: a
  flooding tenant contributes at most one more request per pass than the
  slowest active tenant, so per-batch service is fair to within one
  request (the fairness bound ``tests/test_frontend.py`` asserts).
- **requests are indivisible.** Splitting a request across batches would
  split its result across retires; whole-request admission keeps the
  scatter trivial and the coalesced results bit-identical to serving the
  request alone (per-row independence of the tile reduction — the same
  property that makes bucket padding sound).

Determinism: this module is a PURE state machine. Every decision is a
function of (state, ``now``) with ``now`` passed in explicitly — no
wall-clock reads, no threads, no sockets — so tier-1 asserts coalescing
behavior exactly, replaying arrival orders under a fake clock. The
threaded binding that pumps it with real time lives in ``server.py``.

No jax (and no numpy) at module load: payloads are opaque here.
"""

from __future__ import annotations

import dataclasses
import itertools
from collections import deque

REASONS = ("fill", "deadline", "flush")


@dataclasses.dataclass(frozen=True)
class FrontendRequest:
    """One admitted client request: an opaque (rows, d) payload plus the
    bookkeeping the batcher needs. ``seq`` is the global admission order
    — the deterministic tie-break and the "oldest" ordering (arrival
    timestamps may collide under a coarse injected clock)."""

    tenant: str
    queries: object  # opaque payload; (rows, d) array for the server
    rows: int
    arrival_s: float
    seq: int

    def wait_s(self, now: float) -> float:
        return now - self.arrival_s


@dataclasses.dataclass(frozen=True)
class CoalescedBatch:
    """One formed batch: whole requests in admission slices, plus the
    formation story (why now, how full, who waited longest)."""

    parts: tuple  # (FrontendRequest, ...) in batch row order
    rows: int
    reason: str  # "fill" | "deadline" | "flush"
    formed_s: float
    oldest_wait_s: float

    @property
    def tenants(self) -> dict:
        """tenant -> rows composition (the ``ServeSession.submit``
        span/stats form, aggregated over parts)."""
        comp: dict[str, int] = {}
        for r in self.parts:
            comp[r.tenant] = comp.get(r.tenant, 0) + r.rows
        return comp

    def composition(self) -> tuple:
        """((tenant, rows), ...) per PART in row order — the exact
        ``tenants=`` argument for ``ServeSession.submit``."""
        return tuple((r.tenant, r.rows) for r in self.parts)

    def slices(self):
        """Yield (request, start, stop) row slices into the stacked
        batch — the scatter map back to per-request results."""
        off = 0
        for r in self.parts:
            yield r, off, off + r.rows
            off += r.rows


class Coalescer:
    """The pure batcher: per-tenant FIFO queues, fill-or-deadline batch
    formation, deadline-first round-robin draining. Thread-unsafe by
    design (the threaded wrapper holds its own lock); every method takes
    time as an argument."""

    def __init__(self, *, max_batch_rows: int, max_wait_s: float):
        if max_batch_rows < 1:
            raise ValueError(
                f"max_batch_rows must be >= 1, got {max_batch_rows}"
            )
        if not max_wait_s >= 0.0:
            raise ValueError(f"max_wait_s must be >= 0, got {max_wait_s}")
        self.max_batch_rows = max_batch_rows
        self.max_wait_s = max_wait_s
        # insertion-ordered tenant -> FIFO deque; empty deques are KEPT so
        # the first-seen rotation order is stable across a tenant's idle
        # gaps (fairness must not depend on who happened to drain to zero)
        self._queues: dict[str, deque] = {}
        self._seq = itertools.count()
        self._pending_rows = 0

    # -- admission --------------------------------------------------------

    def admit(self, tenant: str, queries, rows: int,
              now: float) -> FrontendRequest:
        """Enqueue one request (admission control — depth/rate — is the
        scheduler's job and has already happened). Oversized and empty
        requests are caller bugs here and raise."""
        rows = int(rows)
        if rows < 1:
            raise ValueError(f"request must have >= 1 row, got {rows}")
        if rows > self.max_batch_rows:
            raise ValueError(
                f"request of {rows} rows exceeds max_batch_rows="
                f"{self.max_batch_rows} (the scheduler rejects these "
                "before admission)"
            )
        req = FrontendRequest(
            tenant=str(tenant), queries=queries, rows=rows,
            arrival_s=now, seq=next(self._seq),
        )
        self._queues.setdefault(req.tenant, deque()).append(req)
        self._pending_rows += rows
        return req

    # -- state ------------------------------------------------------------

    @property
    def pending_rows(self) -> int:
        return self._pending_rows

    @property
    def pending_requests(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def pending_rows_for(self, tenant: str) -> int:
        q = self._queues.get(str(tenant))
        return sum(r.rows for r in q) if q else 0

    def _oldest(self) -> FrontendRequest | None:
        heads = [q[0] for q in self._queues.values() if q]
        return min(heads, key=lambda r: r.seq) if heads else None

    def next_deadline_s(self) -> float | None:
        """When the oldest pending request's wait budget expires (the
        wake-up time a pump should sleep until); None when idle."""
        oldest = self._oldest()
        return None if oldest is None else oldest.arrival_s + self.max_wait_s

    # -- batch formation --------------------------------------------------

    def pop_ready(self, now: float, flush: bool = False):
        """The next formed batch, or None when no formation condition
        holds. Callers loop (``while (b := pop_ready(now)):``) — a burst
        may fill several buckets at one instant. ``flush=True`` forms a
        batch from whatever is pending regardless of fill/deadline
        (shutdown: enqueued requests must not be stranded)."""
        oldest = self._oldest()
        if oldest is None:
            return None
        fill = self._pending_rows >= self.max_batch_rows
        expired = now - oldest.arrival_s >= self.max_wait_s
        if not (fill or expired or flush):
            return None
        reason = "fill" if fill else ("deadline" if expired else "flush")

        # rotation order: first-seen tenant order, started at the oldest
        # request's tenant — the deadline-ordered guarantee (the request
        # that triggered formation is the batch's first take)
        order = list(self._queues)
        start = order.index(oldest.tenant)
        order = order[start:] + order[:start]

        parts: list[FrontendRequest] = []
        rows = 0
        closed = False
        while not closed:
            progress = False
            for t in order:
                q = self._queues[t]
                if not q:
                    continue
                head = q[0]
                if rows + head.rows > self.max_batch_rows:
                    # first misfit closes the batch: skipping ahead to
                    # smaller requests would reorder service within the
                    # rotation and make formation depend on payload sizes
                    # in a way no fairness bound survives
                    closed = True
                    break
                q.popleft()
                parts.append(head)
                rows += head.rows
                progress = True
            if not progress:
                break
        self._pending_rows -= rows
        return CoalescedBatch(
            parts=tuple(parts), rows=rows, reason=reason, formed_s=now,
            oldest_wait_s=now - oldest.arrival_s,
        )
