"""A jax-free stand-in replica with a DETERMINISTIC service model —
the router's proof harness on hosts whose core count cannot host real
replica parallelism.

The repo's CI box has one core: three real ``mpi-knn serve`` processes
time-slice it, so aggregate throughput behind the router could never
legitimately exceed one replica's — the 1-CPU dual of the virtual-CPU-
mesh convention the device tests already use. A :class:`ModelReplica`
replaces the jax engine with ``lanes`` service lanes of a fixed
``service_s`` each (capacity = lanes / service_s requests/s, spent
SLEEPING — which a single core can run three of concurrently), while
speaking the real serve front end's HTTP surface verbatim: ``POST
/query`` (raw f32 or JSON), ``POST /upsert``/``/delete`` with the
``X-Mutation-Seq`` contract (duplicate suppression AND the gapless-mark
409 refusal), ``GET /healthz`` with ``ready``/``applied_seq``/
``queue_rows``, keep-alive throughout.
So the router, loadgen, and the scaling/affinity/convergence tests
exercise the full wire protocol; only the distance math is modeled.

Failure injection for membership tests: :meth:`fail` turns /healthz
into ``ok: false`` (probe failures → eviction) without dropping the
socket; :meth:`drop_mutations` fails only the mutation route (503)
while health stays green — the transient fan-out-leg failure that must
leave a replica lagging, never gapped; :meth:`kill` is the SIGKILL analogue — it stops the listener
AND severs every open keep-alive connection, so in-flight requests
die with transport errors exactly as a killed process's would;
:meth:`stop` is the graceful shutdown; :meth:`cold_reload` resets the
mutation state to a given baseline — the quarantine-exit path.

No jax import (that is the point).
"""

from __future__ import annotations

import json
import threading
import time

import numpy as np

from mpi_knn_tpu.frontend.server import (
    DEFAULT_TENANT,
    SEQ_HEADER,
    TENANT_HEADER,
)


class ModelReplica:
    """One modeled replica: an HTTP server whose query handler sleeps
    ``service_s`` on one of ``lanes`` serialized service lanes
    (``lanes=0`` = unlimited — a pure-transport server for connection-
    reuse benchmarks)."""

    def __init__(self, *, dim: int = 16, k: int = 4,
                 service_s: float = 0.0, lanes: int = 1,
                 warm_delay_s: float = 0.0, host: str = "127.0.0.1",
                 port: int = 0):
        self.dim = dim
        self.k = k
        self.service_s = service_s
        self._lanes = (
            threading.Semaphore(lanes) if lanes > 0 else None
        )
        self._lock = threading.Lock()
        self._applied_seq = 0
        self._mutations: list[tuple] = []  # (seq, path, tenant, ids)
        self._queries = 0
        self._waiting = 0
        self._failing = False
        self._drop_mutations = False
        self.started_s = time.monotonic()
        self.warm_delay_s = warm_delay_s
        from mpi_knn_tpu.frontend.server import _tuned_server_class

        self._httpd = _tuned_server_class()(
            (host, port), _model_handler(self)
        )
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="model-replica",
            daemon=True,
        )

    # -- lifecycle / injection --------------------------------------------

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "ModelReplica":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(10.0)

    def kill(self) -> None:
        """SIGKILL analogue: stop accepting and sever every open
        connection mid-flight — peers see transport failures, never an
        orderly close. (The tuned server severs live connections in
        ``server_close``, so a kill under load IS a stop under load —
        the alias keeps the drill's intent readable.)"""
        self.stop()

    def fail(self, failing: bool = True) -> None:
        """Make /healthz report ``ok: false`` (and queries 503) — the
        soft-death a router must evict on without a socket error."""
        with self._lock:
            self._failing = failing

    def drop_mutations(self, dropping: bool = True) -> None:
        """Make mutations fail 503 while /healthz stays ok — the
        TRANSIENT single-leg fan-out failure (a wedged apply, a dropped
        packet) that must leave this replica lagging-but-in-rotation,
        never applying later seqs over the hole."""
        with self._lock:
            self._drop_mutations = dropping

    def cold_reload(self, applied_seq: int = 0) -> None:
        """Reset the mutation state to ``applied_seq`` — a reload from
        an artifact current as of that seq (0 = the original)."""
        with self._lock:
            self._applied_seq = applied_seq
            self._mutations = []

    # -- state the tests assert -------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "applied_seq": self._applied_seq,
                "mutations": list(self._mutations),
                "queries": self._queries,
            }

    # -- handler backend ---------------------------------------------------

    def stats(self) -> dict:
        ready = (
            time.monotonic() - self.started_s >= self.warm_delay_s
        )
        with self._lock:
            return {
                "ok": not self._failing,
                "ready": ready and not self._failing,
                "warming": {"ready": 1 if ready else 0, "total": 1,
                            "done": ready},
                "uptime_s": round(
                    time.monotonic() - self.started_s, 3
                ),
                "queue_rows": self._waiting,
                "applied_seq": self._applied_seq,
                "queries_served": self._queries,
                "dim": self.dim,
                "k": self.k,
                "backend": "model",
                "max_batch_rows": 1024,
            }

    def serve_query(self, rows: int) -> dict:
        """Burn one service slot: queue on a lane, sleep the modeled
        batch time, return a shaped (all-zero) result."""
        with self._lock:
            if self._failing:
                return {"error": "failing"}
            self._waiting += 1
        try:
            if self._lanes is not None:
                with self._lanes:
                    if self.service_s > 0:
                        time.sleep(self.service_s)
            elif self.service_s > 0:
                time.sleep(self.service_s)
        finally:
            with self._lock:
                self._waiting -= 1
                self._queries += 1
        return {
            "rows": rows,
            "dists": [[0.0] * self.k] * rows,
            "ids": [list(range(self.k))] * rows,
        }

    def apply_mutation(self, path: str, tenant: str, ids,
                       seq: int | None) -> dict:
        with self._lock:
            if self._failing or self._drop_mutations:
                return {"error": "failing"}
            if seq is not None and seq <= self._applied_seq:
                return {"duplicate": True,
                        "applied_seq": self._applied_seq}
            if seq is not None and seq > self._applied_seq + 1:
                # the gapless-mark rule (the serve front end's 409):
                # applying over a hole would lose the missed seq —
                # refuse, stay lagging, let the router replay in order
                return {"error": "seq-gap", "status": 409,
                        "applied_seq": self._applied_seq}
            self._mutations.append((seq, path, tenant, list(ids)))
            if seq is not None and seq > self._applied_seq:
                self._applied_seq = seq
            out = {
                "upserts" if path == "/upsert" else "deletes": len(ids),
            }
            if seq is not None:
                out["applied_seq"] = self._applied_seq
            return out


def _model_handler(replica: ModelReplica):
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # noqa: A003
            pass

        def _json(self, status: int, doc: dict) -> None:
            body = (json.dumps(doc) + "\n").encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _rows(self, raw: bytes) -> int:
            ctype = (
                self.headers.get("Content-Type") or ""
            ).split(";")[0]
            if ctype == "application/octet-stream":
                if len(raw) % (4 * replica.dim):
                    raise ValueError("ragged raw body")
                return len(raw) // (4 * replica.dim)
            q = np.asarray(json.loads(raw)["queries"], np.float32)
            if q.ndim != 2 or q.shape[1] != replica.dim:
                raise ValueError(f"bad queries shape {q.shape}")
            return int(q.shape[0])

        def do_POST(self):  # noqa: N802 — stdlib handler convention
            tenant = self.headers.get(TENANT_HEADER, DEFAULT_TENANT)
            n = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(n) if n > 0 else b""
            if self.path == "/query":
                try:
                    rows = self._rows(raw)
                except (ValueError, KeyError, TypeError) as e:
                    self._json(400, {"error": str(e)})
                    return
                out = replica.serve_query(rows)
                self._json(503 if "error" in out else 200, out)
            elif self.path in ("/upsert", "/delete"):
                try:
                    doc = json.loads(raw)
                    ids = doc["ids"]
                    seq_h = self.headers.get(SEQ_HEADER)
                    seq = None if seq_h is None else int(seq_h)
                except (ValueError, KeyError, TypeError) as e:
                    self._json(400, {"error": str(e)})
                    return
                out = replica.apply_mutation(self.path, tenant, ids, seq)
                status = out.pop("status", 503) if "error" in out else 200
                self._json(status, out)
            else:
                self._json(404, {"error": f"no such route {self.path}"})

        def do_GET(self):  # noqa: N802
            if self.path == "/healthz":
                st = replica.stats()
                self._json(200 if st["ok"] else 503, st)
            elif self.path == "/metrics":
                body = (
                    "# modeled replica: no registry\n".encode()
                )
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._json(404, {"error": f"no such route {self.path}"})

    return Handler
