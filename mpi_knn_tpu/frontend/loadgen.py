"""Open-loop multi-tenant load generation — throughput-vs-latency curves
for the serving front end.

Open loop is the honest protocol for a throughput-vs-p99 curve: each
tenant stream issues requests on a FIXED arrival schedule (request i of a
``qps``-rate stream is due at ``i / qps``), never waiting for responses —
so when the server falls behind, latency GROWS instead of the generator
politely slowing down to match (the closed-loop coordination artifact
that makes overloaded servers look fine). Per-request latency is measured
from the request's SCHEDULED arrival to completion, so queueing delay —
including the generator itself getting behind schedule — is inside the
number, not hidden beside it.

Two transports, one report shape:

- :func:`run_inprocess` drives a :class:`~mpi_knn_tpu.frontend.server.
  Frontend` directly (no sockets): ``submit`` is a non-blocking enqueue,
  so ONE thread per tenant sustains true open-loop arrivals, and the
  pump's ticket fulfillment stamps completion times. This is what
  ``scripts/bench_ops.py`` and the acceptance tests use.
- :func:`run_http` drives a running server over HTTP (stdlib urllib,
  one worker thread per in-flight request) — the ``mpi-knn loadgen``
  CLI, exercising the full network path in the CI gate.

:func:`run_sequential_baseline` is the comparison anchor: the same
requests served one at a time at dispatch depth 1 (each lone request
padding to its own bucket) — the "no front end" number the coalesced
curve must beat (ISSUE 11 acceptance: ≥ 2× at an equal p99 bound).

Report row shape (both transports)::

    {tenants, offered_qps_per_tenant, offered_qps_total, requests,
     rows_per_request, wall_s, achieved_qps_rows, achieved_rps,
     p50_ms, p99_ms, rejected, errors, per_tenant: {t: served}}

No jax import at module load.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np

from mpi_knn_tpu.frontend.scheduler import Rejection


def synth_queries(dim: int, rows: int, *, lo: float = 0.0, hi: float = 1.0,
                  seed: int = 0):
    """One synthetic request payload (uniform in the corpus range — the
    serve CLI's synthetic-stream convention)."""
    rng = np.random.default_rng(seed)
    return rng.uniform(lo, hi, size=(rows, dim)).astype(np.float32)


def _percentiles_ms(lat_s: list) -> tuple:
    if not lat_s:
        return None, None
    a = np.asarray(lat_s)
    return (
        round(float(np.percentile(a, 50)) * 1e3, 3),
        round(float(np.percentile(a, 99)) * 1e3, 3),
    )


def _report(*, tenants, qps, rows, n_requests, wall_s, lat_s, rejected,
            errors, served_rows, per_tenant) -> dict:
    p50, p99 = _percentiles_ms(lat_s)
    return {
        "tenants": tenants,
        "offered_qps_per_tenant": qps,
        "offered_qps_total": round(qps * tenants, 3),
        "requests": n_requests,
        "rows_per_request": rows,
        "wall_s": round(wall_s, 4),
        "achieved_rps": round(len(lat_s) / wall_s, 2) if wall_s > 0 else None,
        "achieved_qps_rows": round(served_rows / wall_s, 1)
        if wall_s > 0 else None,
        "p50_ms": p50,
        "p99_ms": p99,
        "rejected": rejected,
        "errors": errors,
        "per_tenant": dict(sorted(per_tenant.items())),
    }


# ---------------------------------------------------------------------------
# in-process transport


def run_inprocess(frontend, *, tenants: int, qps: float, n_requests: int,
                  rows: int, lo: float = 0.0, hi: float = 1.0,
                  seed: int = 0, timeout_s: float = 60.0) -> dict:
    """Open-loop load against an in-process ``Frontend``: ``tenants``
    streams × ``n_requests`` requests each at ``qps`` per stream.
    Payloads are seeded per (tenant, request) so reruns offer identical
    queries."""
    dim = frontend.session.index.dim
    t0 = time.monotonic()
    tickets = []  # (tenant, scheduled_s, ticket-or-None(rejected))
    lock = threading.Lock()

    def stream(ti: int):
        tenant = f"tenant-{ti}"
        for i in range(n_requests):
            due = t0 + i / qps
            delay = due - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            q = synth_queries(
                dim, rows, lo=lo, hi=hi, seed=seed + ti * 100003 + i
            )
            out = frontend.submit(tenant, q)
            with lock:
                tickets.append(
                    (tenant, due, None if isinstance(out, Rejection) else out)
                )

    threads = [
        threading.Thread(target=stream, args=(ti,), daemon=True)
        for ti in range(tenants)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join()

    lat_s, rejected, errors, served_rows = [], 0, 0, 0
    per_tenant: dict[str, int] = {}
    deadline = time.monotonic() + timeout_s
    for tenant, due, ticket in tickets:
        if ticket is None:
            rejected += 1
            continue
        try:
            _, ids = ticket.result(timeout=max(0.0, deadline - time.monotonic()))
        except Exception:
            errors += 1
            continue
        lat_s.append(ticket.done_s - due)
        served_rows += int(ids.shape[0])
        per_tenant[tenant] = per_tenant.get(tenant, 0) + 1
    wall = (
        max(t.done_s for _, _, t in tickets if t is not None and t.done_s)
        - t0
        if any(t is not None and t.done_s for _, _, t in tickets)
        else time.monotonic() - t0
    )
    return _report(
        tenants=tenants, qps=qps, rows=rows, n_requests=n_requests,
        wall_s=wall, lat_s=lat_s, rejected=rejected, errors=errors,
        served_rows=served_rows, per_tenant=per_tenant,
    )


def run_sequential_baseline(session, *, tenants: int, n_requests: int,
                            rows: int, lo: float = 0.0, hi: float = 1.0,
                            seed: int = 0) -> dict:
    """The no-front-end anchor: the SAME request population served one
    request at a time, dispatch depth 1 (submit → retire before the next
    request — per-stream sequential dispatch). Each lone request pads to
    its own bucket, so the padded rows burned per request are exactly
    what coalescing exists to reclaim. The caller passes a depth-1
    session over the same index (``dispatch_depth=1``) so the comparison
    isolates coalescing, not pipelining."""
    dim = session.index.dim
    lat_s, served_rows = [], 0
    per_tenant: dict[str, int] = {}
    t0 = time.monotonic()
    for ti in range(tenants):
        tenant = f"tenant-{ti}"
        for i in range(n_requests):
            q = synth_queries(
                dim, rows, lo=lo, hi=hi, seed=seed + ti * 100003 + i
            )
            t1 = time.monotonic()
            done = session.submit(q, tenants=((tenant, rows),))
            done += session.drain()
            lat_s.append(time.monotonic() - t1)
            served_rows += sum(r.rows for r in done)
            per_tenant[tenant] = per_tenant.get(tenant, 0) + 1
    wall = time.monotonic() - t0
    return _report(
        tenants=tenants, qps=float("inf"), rows=rows,
        n_requests=n_requests, wall_s=wall, lat_s=lat_s, rejected=0,
        errors=0, served_rows=served_rows, per_tenant=per_tenant,
    )


# ---------------------------------------------------------------------------
# HTTP transport


def probe_server(url: str, timeout_s: float = 10.0) -> dict:
    """GET /healthz — the index facts (dim, k) a generator needs."""
    with urllib.request.urlopen(
        url.rstrip("/") + "/healthz", timeout=timeout_s
    ) as resp:
        return json.loads(resp.read())


def fetch_metrics(url: str, timeout_s: float = 10.0) -> str:
    """GET /metrics — the raw Prometheus exposition text."""
    with urllib.request.urlopen(
        url.rstrip("/") + "/metrics", timeout=timeout_s
    ) as resp:
        return resp.read().decode()


def _post_query(url: str, tenant: str, q: np.ndarray,
                timeout_s: float) -> tuple:
    """(status, rows_served): one POST /query round trip (raw f32 body —
    no JSON float inflation on the wire)."""
    req = urllib.request.Request(
        url.rstrip("/") + "/query",
        data=np.ascontiguousarray(q, dtype="<f4").tobytes(),
        headers={
            "Content-Type": "application/octet-stream",
            "X-Tenant": tenant,
        },
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            doc = json.loads(resp.read())
            return resp.status, int(doc.get("rows", 0))
    except urllib.error.HTTPError as e:
        e.read()
        return e.code, 0
    except (urllib.error.URLError, OSError, TimeoutError, ValueError):
        # connection refused/reset, socket timeout, truncated body: the
        # exact failures an OVERLOADED server produces — they must land
        # in the report's error count, not kill the worker thread and
        # vanish from achieved/p99 (a load tool that loses its failures
        # under load flatters exactly what it exists to expose)
        return 0, 0


def run_http(url: str, *, tenants: int, qps: float, n_requests: int,
             rows: int, lo: float = 0.0, hi: float = 1.0, seed: int = 0,
             timeout_s: float = 30.0) -> dict:
    """Open-loop load over HTTP: per tenant, an issuer thread fires one
    worker thread per request at its scheduled arrival (workers carry the
    blocking round trip so the schedule never waits on a response)."""
    dim = int(probe_server(url)["dim"])
    t0 = time.monotonic()
    lock = threading.Lock()
    lat_s: list[float] = []
    stats = {"rejected": 0, "errors": 0, "served_rows": 0}
    per_tenant: dict[str, int] = {}
    workers: list[threading.Thread] = []

    def fire(tenant: str, due: float, q) -> None:
        status, served = _post_query(url, tenant, q, timeout_s)
        done = time.monotonic()
        with lock:
            if status == 200:
                lat_s.append(done - due)
                stats["served_rows"] += served
                per_tenant[tenant] = per_tenant.get(tenant, 0) + 1
            elif status == 429:
                stats["rejected"] += 1
            else:
                stats["errors"] += 1

    def stream(ti: int):
        tenant = f"tenant-{ti}"
        for i in range(n_requests):
            due = t0 + i / qps
            delay = due - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            q = synth_queries(
                dim, rows, lo=lo, hi=hi, seed=seed + ti * 100003 + i
            )
            w = threading.Thread(
                target=fire, args=(tenant, due, q), daemon=True
            )
            with lock:
                workers.append(w)
            w.start()

    issuers = [
        threading.Thread(target=stream, args=(ti,), daemon=True)
        for ti in range(tenants)
    ]
    for th in issuers:
        th.start()
    for th in issuers:
        th.join()
    for w in list(workers):
        w.join(timeout_s)
    wall = time.monotonic() - t0
    return _report(
        tenants=tenants, qps=qps, rows=rows, n_requests=n_requests,
        wall_s=wall, lat_s=lat_s, rejected=stats["rejected"],
        errors=stats["errors"], served_rows=stats["served_rows"],
        per_tenant=per_tenant,
    )


def sweep(run_one, qps_levels) -> list:
    """Offered-QPS sweep: ``run_one(qps) -> report`` at each level —
    the throughput-vs-p50/p99 curve, lowest load first."""
    return [run_one(q) for q in sorted(qps_levels)]
