"""Open-loop multi-tenant load generation — throughput-vs-latency curves
for the serving front end.

Open loop is the honest protocol for a throughput-vs-p99 curve: each
tenant stream issues requests on a FIXED arrival schedule (request i of a
``qps``-rate stream is due at ``i / qps``), never waiting for responses —
so when the server falls behind, latency GROWS instead of the generator
politely slowing down to match (the closed-loop coordination artifact
that makes overloaded servers look fine). Per-request latency is measured
from the request's SCHEDULED arrival to completion, so queueing delay —
including the generator itself getting behind schedule — is inside the
number, not hidden beside it.

Two transports, one report shape:

- :func:`run_inprocess` drives a :class:`~mpi_knn_tpu.frontend.server.
  Frontend` directly (no sockets): ``submit`` is a non-blocking enqueue,
  so ONE thread per tenant sustains true open-loop arrivals, and the
  pump's ticket fulfillment stamps completion times. This is what
  ``scripts/bench_ops.py`` and the acceptance tests use.
- :func:`run_http` drives one or more running servers over HTTP — the
  ``mpi-knn loadgen`` CLI, exercising the full network path in the CI
  gate. The default transport (``connect="reuse"``, ISSUE 18) is a
  fixed pool of worker threads per tenant, each holding ONE persistent
  keep-alive connection and draining a shared open-loop queue — the
  schedule never waits on a response, and queue wait is inside the
  latency because it is measured from the scheduled arrival. The
  legacy ``connect="per-request"`` mode (a fresh TCP connect + thread
  per request) is kept as the comparison anchor: it understates q/s
  and inflates p50 at high offered load, which the regression test
  pins (reuse ≥ per-connect on the same server). ``targets=[url,...]``
  spreads tenants round-robin over endpoints — the router drill's
  multi-replica direct baseline.

:func:`run_sequential_baseline` is the comparison anchor: the same
requests served one at a time at dispatch depth 1 (each lone request
padding to its own bucket) — the "no front end" number the coalesced
curve must beat (ISSUE 11 acceptance: ≥ 2× at an equal p99 bound).

Report row shape (both transports)::

    {tenants, offered_qps_per_tenant, offered_qps_total, requests,
     rows_per_request, wall_s, achieved_qps_rows, achieved_rps,
     p50_ms, p99_ms, rejected, errors, per_tenant: {t: served}}

No jax import at module load.
"""

from __future__ import annotations

import http.client
import json
import queue
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np

from mpi_knn_tpu.frontend.scheduler import Rejection


def synth_queries(dim: int, rows: int, *, lo: float = 0.0, hi: float = 1.0,
                  seed: int = 0):
    """One synthetic request payload (uniform in the corpus range — the
    serve CLI's synthetic-stream convention)."""
    rng = np.random.default_rng(seed)
    return rng.uniform(lo, hi, size=(rows, dim)).astype(np.float32)


def _percentiles_ms(lat_s: list) -> tuple:
    if not lat_s:
        return None, None
    a = np.asarray(lat_s)
    return (
        round(float(np.percentile(a, 50)) * 1e3, 3),
        round(float(np.percentile(a, 99)) * 1e3, 3),
    )


def _report(*, tenants, qps, rows, n_requests, wall_s, lat_s, rejected,
            errors, served_rows, per_tenant, connect=None, targets=None,
            by_status=None) -> dict:
    p50, p99 = _percentiles_ms(lat_s)
    out = {
        "tenants": tenants,
        "offered_qps_per_tenant": qps,
        "offered_qps_total": round(qps * tenants, 3),
        "requests": n_requests,
        "rows_per_request": rows,
        "wall_s": round(wall_s, 4),
        "achieved_rps": round(len(lat_s) / wall_s, 2) if wall_s > 0 else None,
        "achieved_qps_rows": round(served_rows / wall_s, 1)
        if wall_s > 0 else None,
        "p50_ms": p50,
        "p99_ms": p99,
        "rejected": rejected,
        "errors": errors,
        "per_tenant": dict(sorted(per_tenant.items())),
    }
    if connect is not None:
        out["connect"] = connect
    if targets is not None:
        out["targets"] = len(targets)
    if by_status is not None:
        # status -> count over every response, 200s included (status 0 =
        # transport failure): the drill's "zero 5xx beyond structured
        # 503s" assertion reads this, not the lumped error count
        out["by_status"] = {
            str(k): v for k, v in sorted(by_status.items())
        }
    return out


# ---------------------------------------------------------------------------
# in-process transport


def run_inprocess(frontend, *, tenants: int, qps: float, n_requests: int,
                  rows: int, lo: float = 0.0, hi: float = 1.0,
                  seed: int = 0, timeout_s: float = 60.0) -> dict:
    """Open-loop load against an in-process ``Frontend``: ``tenants``
    streams × ``n_requests`` requests each at ``qps`` per stream.
    Payloads are seeded per (tenant, request) so reruns offer identical
    queries."""
    dim = frontend.session.index.dim
    t0 = time.monotonic()
    tickets = []  # (tenant, scheduled_s, ticket-or-None(rejected))
    lock = threading.Lock()

    def stream(ti: int):
        tenant = f"tenant-{ti}"
        for i in range(n_requests):
            due = t0 + i / qps
            delay = due - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            q = synth_queries(
                dim, rows, lo=lo, hi=hi, seed=seed + ti * 100003 + i
            )
            out = frontend.submit(tenant, q)
            with lock:
                tickets.append(
                    (tenant, due, None if isinstance(out, Rejection) else out)
                )

    threads = [
        threading.Thread(target=stream, args=(ti,), daemon=True)
        for ti in range(tenants)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join()

    lat_s, rejected, errors, served_rows = [], 0, 0, 0
    per_tenant: dict[str, int] = {}
    deadline = time.monotonic() + timeout_s
    for tenant, due, ticket in tickets:
        if ticket is None:
            rejected += 1
            continue
        try:
            _, ids = ticket.result(timeout=max(0.0, deadline - time.monotonic()))
        except Exception:
            errors += 1
            continue
        lat_s.append(ticket.done_s - due)
        served_rows += int(ids.shape[0])
        per_tenant[tenant] = per_tenant.get(tenant, 0) + 1
    wall = (
        max(t.done_s for _, _, t in tickets if t is not None and t.done_s)
        - t0
        if any(t is not None and t.done_s for _, _, t in tickets)
        else time.monotonic() - t0
    )
    return _report(
        tenants=tenants, qps=qps, rows=rows, n_requests=n_requests,
        wall_s=wall, lat_s=lat_s, rejected=rejected, errors=errors,
        served_rows=served_rows, per_tenant=per_tenant,
    )


def run_sequential_baseline(session, *, tenants: int, n_requests: int,
                            rows: int, lo: float = 0.0, hi: float = 1.0,
                            seed: int = 0) -> dict:
    """The no-front-end anchor: the SAME request population served one
    request at a time, dispatch depth 1 (submit → retire before the next
    request — per-stream sequential dispatch). Each lone request pads to
    its own bucket, so the padded rows burned per request are exactly
    what coalescing exists to reclaim. The caller passes a depth-1
    session over the same index (``dispatch_depth=1``) so the comparison
    isolates coalescing, not pipelining."""
    dim = session.index.dim
    lat_s, served_rows = [], 0
    per_tenant: dict[str, int] = {}
    t0 = time.monotonic()
    for ti in range(tenants):
        tenant = f"tenant-{ti}"
        for i in range(n_requests):
            q = synth_queries(
                dim, rows, lo=lo, hi=hi, seed=seed + ti * 100003 + i
            )
            t1 = time.monotonic()
            done = session.submit(q, tenants=((tenant, rows),))
            done += session.drain()
            lat_s.append(time.monotonic() - t1)
            served_rows += sum(r.rows for r in done)
            per_tenant[tenant] = per_tenant.get(tenant, 0) + 1
    wall = time.monotonic() - t0
    return _report(
        tenants=tenants, qps=float("inf"), rows=rows,
        n_requests=n_requests, wall_s=wall, lat_s=lat_s, rejected=0,
        errors=0, served_rows=served_rows, per_tenant=per_tenant,
    )


# ---------------------------------------------------------------------------
# HTTP transport


def probe_server(url: str, timeout_s: float = 10.0) -> dict:
    """GET /healthz — the index facts (dim, k) a generator needs."""
    with urllib.request.urlopen(
        url.rstrip("/") + "/healthz", timeout=timeout_s
    ) as resp:
        return json.loads(resp.read())


def fetch_metrics(url: str, timeout_s: float = 10.0) -> str:
    """GET /metrics — the raw Prometheus exposition text."""
    with urllib.request.urlopen(
        url.rstrip("/") + "/metrics", timeout=timeout_s
    ) as resp:
        return resp.read().decode()


def _post_query(url: str, tenant: str, q: np.ndarray,
                timeout_s: float) -> tuple:
    """(status, rows_served): one POST /query round trip (raw f32 body —
    no JSON float inflation on the wire)."""
    req = urllib.request.Request(
        url.rstrip("/") + "/query",
        data=np.ascontiguousarray(q, dtype="<f4").tobytes(),
        headers={
            "Content-Type": "application/octet-stream",
            "X-Tenant": tenant,
        },
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            doc = json.loads(resp.read())
            return resp.status, int(doc.get("rows", 0))
    except urllib.error.HTTPError as e:
        e.read()
        return e.code, 0
    except (urllib.error.URLError, OSError, TimeoutError, ValueError):
        # connection refused/reset, socket timeout, truncated body: the
        # exact failures an OVERLOADED server produces — they must land
        # in the report's error count, not kill the worker thread and
        # vanish from achieved/p99 (a load tool that loses its failures
        # under load flatters exactly what it exists to expose)
        return 0, 0


def _conn_open(target: str, timeout_s: float):
    """A connected keep-alive HTTPConnection with Nagle disabled: the
    request headers and the raw-f32 body go out as separate sends, and
    Nagle + delayed-ACK would stall every second send ~40ms — a
    per-request tax that would swamp the very reuse win this transport
    exists to measure."""
    import socket

    u = urllib.parse.urlsplit(target)
    conn = http.client.HTTPConnection(
        u.hostname, u.port or 80, timeout=timeout_s
    )
    conn.connect()
    conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return conn


def _post_query_conn(conn, tenant: str, q: np.ndarray) -> tuple:
    """(status, rows_served) over a persistent connection — raises the
    transport errors (the caller owns stale-connection retry); non-200
    statuses come back as values, http.client never raises on them."""
    conn.request(
        "POST", "/query",
        body=np.ascontiguousarray(q, dtype="<f4").tobytes(),
        headers={
            "Content-Type": "application/octet-stream",
            "X-Tenant": tenant,
        },
    )
    resp = conn.getresponse()
    data = resp.read()  # always drain: keep-alive needs the body consumed
    if resp.status == 200:
        return resp.status, int(json.loads(data).get("rows", 0))
    return resp.status, 0


def run_http(url: str | None = None, *, targets=None, tenants: int,
             qps: float, n_requests: int, rows: int, lo: float = 0.0,
             hi: float = 1.0, seed: int = 0, timeout_s: float = 30.0,
             connect: str = "reuse", connections: int = 4) -> dict:
    """Open-loop load over HTTP against ``url`` or ``targets`` (tenant
    ``i`` drives ``targets[i % len(targets)]`` — round-robin tenant
    pinning, so a multi-replica direct baseline keeps each tenant's
    coalescing locality just like the router's affinity does).

    ``connect="reuse"`` (default): per tenant, an issuer thread enqueues
    requests at their scheduled arrivals and ``connections`` worker
    threads — each holding one persistent keep-alive connection — drain
    the queue. A request that finds every connection busy waits in the
    queue, and that wait is inside its latency (measured from the
    scheduled arrival): the open-loop contract survives the fixed pool.
    A stale keep-alive connection (server closed between requests) is
    reopened and the request retried once; a failure on a FRESH
    connection is counted, never retried.

    ``connect="per-request"``: the legacy transport — a fresh TCP
    connect and a worker thread per request (unbounded concurrency,
    per-connect overhead on every request)."""
    if targets is None:
        if url is None:
            raise ValueError("run_http needs url or targets")
        targets = [url]
    targets = [t.rstrip("/") for t in targets]
    if connect not in ("reuse", "per-request"):
        raise ValueError(f"unknown connect mode {connect!r}")
    dim = int(probe_server(targets[0])["dim"])
    t0 = time.monotonic()
    lock = threading.Lock()
    lat_s: list[float] = []
    stats = {"rejected": 0, "errors": 0, "served_rows": 0}
    by_status: dict[int, int] = {}
    per_tenant: dict[str, int] = {}

    def record(tenant: str, due: float, status: int, served: int) -> None:
        done = time.monotonic()
        with lock:
            by_status[status] = by_status.get(status, 0) + 1
            if status == 200:
                lat_s.append(done - due)
                stats["served_rows"] += served
                per_tenant[tenant] = per_tenant.get(tenant, 0) + 1
            elif status == 429:
                stats["rejected"] += 1
            else:
                stats["errors"] += 1

    def conn_worker(target: str, tenant: str, jobs) -> None:
        conn, fresh = None, True
        while True:
            item = jobs.get()
            if item is None:
                break
            due, q = item
            status, served = 0, 0
            for _attempt in range(2):
                try:
                    if conn is None:
                        conn, fresh = _conn_open(target, timeout_s), True
                    status, served = _post_query_conn(conn, tenant, q)
                    fresh = False
                    break
                except (OSError, http.client.HTTPException, ValueError,
                        TimeoutError):
                    if conn is not None:
                        try:
                            conn.close()
                        except OSError:
                            pass
                    conn = None
                    if fresh:
                        # a fresh connection failed: that is the server
                        # (refused/reset/timeout under overload) — count
                        # it, don't retry into the same failure
                        break
                    # stale keep-alive (server closed between requests):
                    # reconnect and retry this one request — queries are
                    # idempotent, and without the retry every server-side
                    # idle close would masquerade as a load failure
            record(tenant, due, status, served)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    workers: list[threading.Thread] = []
    tenant_jobs: dict[int, queue.Queue] = {}
    if connect == "reuse":
        for ti in range(tenants):
            jobs: queue.Queue = queue.Queue()
            tenant_jobs[ti] = jobs
            target = targets[ti % len(targets)]
            for c in range(connections):
                w = threading.Thread(
                    target=conn_worker,
                    args=(target, f"tenant-{ti}", jobs),
                    name=f"loadgen-conn-{ti}-{c}", daemon=True,
                )
                workers.append(w)
                w.start()

    def fire(target: str, tenant: str, due: float, q) -> None:
        status, served = _post_query(target, tenant, q, timeout_s)
        record(tenant, due, status, served)

    def stream(ti: int):
        tenant = f"tenant-{ti}"
        target = targets[ti % len(targets)]
        for i in range(n_requests):
            due = t0 + i / qps
            delay = due - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            q = synth_queries(
                dim, rows, lo=lo, hi=hi, seed=seed + ti * 100003 + i
            )
            if connect == "reuse":
                tenant_jobs[ti].put((due, q))
            else:
                w = threading.Thread(
                    target=fire, args=(target, tenant, due, q),
                    daemon=True,
                )
                with lock:
                    workers.append(w)
                w.start()

    issuers = [
        threading.Thread(target=stream, args=(ti,), daemon=True)
        for ti in range(tenants)
    ]
    for th in issuers:
        th.start()
    for th in issuers:
        th.join()
    for jobs in tenant_jobs.values():
        for _ in range(connections):
            jobs.put(None)
    for w in list(workers):
        w.join(timeout_s)
    wall = time.monotonic() - t0
    return _report(
        tenants=tenants, qps=qps, rows=rows, n_requests=n_requests,
        wall_s=wall, lat_s=lat_s, rejected=stats["rejected"],
        errors=stats["errors"], served_rows=stats["served_rows"],
        per_tenant=per_tenant, connect=connect, targets=targets,
        by_status=by_status,
    )


def sweep(run_one, qps_levels) -> list:
    """Offered-QPS sweep: ``run_one(qps) -> report`` at each level —
    the throughput-vs-p50/p99 curve, lowest load first."""
    return [run_one(q) for q in sorted(qps_levels)]
