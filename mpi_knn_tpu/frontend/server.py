"""The thin shells around the pure front end: a threaded dispatch pump
binding the scheduler to one ``ServeSession``, and a stdlib HTTP server.

Layering (the testability contract): ``coalesce.py`` and ``scheduler.py``
are pure state machines with injected clocks — everything with behavior
worth asserting lives there and in the serve engine. THIS module only
adds the two unavoidable impurities, each as thin as it can be made:

- :class:`Frontend` — threads and real time: client threads enqueue
  through ``submit`` (admission under one lock, O(µs)); ONE pump thread
  polls the scheduler, stacks each coalesced batch, drives the session
  (the session is single-threaded by design — the pump is its only
  caller), and scatters retired results back to per-request tickets.
- :class:`FrontendHTTPServer` — sockets: ``POST /query`` (JSON or raw
  little-endian f32 rows, tenant id in ``X-Tenant``), ``GET /metrics``
  (the obs Prometheus exposition, the exact text ``parse_prometheus``
  re-parses in CI), ``GET /healthz`` (liveness + serving posture: rung,
  queue, uptime — and the index facts a load generator needs to shape
  requests). Handlers translate: 429 from a :class:`Rejection`, 400 from
  malformed payloads, 200 with per-row results otherwise.

Why one pump thread: the serve engine's dispatch-ahead pipeline
(``dispatch_depth``) already provides the useful concurrency on the
device side; a second submitting thread would only interleave
``submit``/``drain`` nondeterministically. The pump wakes on new work
(condition variable) or the oldest request's coalescing deadline —
idle-spinning would burn a core, sleeping a fixed quantum would add it
to every light-load latency.

No jax import at module load (the session object carries everything).
"""

from __future__ import annotations

import json
import threading
import time

import numpy as np

from mpi_knn_tpu.frontend.scheduler import (
    FrontendScheduler,
    Rejection,
    SLOPolicy,
)
from mpi_knn_tpu.obs import metrics as obs_metrics
from mpi_knn_tpu.obs import spans as obs_spans




class FrontendError(RuntimeError):
    """The pump died (or the session raised) with requests outstanding;
    carried to every waiting ticket so no client blocks forever."""


class Ticket:
    """One admitted request's rendezvous: the submitting thread waits on
    ``result``; the pump fulfills (or fails) it at retire."""

    __slots__ = ("request", "_event", "_dists", "_ids", "_error", "done_s")

    def __init__(self, request):
        self.request = request
        self._event = threading.Event()
        self._dists = None
        self._ids = None
        self._error = None
        self.done_s = None  # time.monotonic() at fulfill (loadgen's clock)

    def _fulfill(self, dists, ids) -> None:
        self._dists, self._ids = dists, ids
        self.done_s = time.monotonic()
        self._event.set()

    def _fail(self, error: BaseException) -> None:
        self._error = error
        self.done_s = time.monotonic()
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None):
        """(dists, ids) for this request's rows — blocks until the
        coalesced batch carrying it retires. Raises the serving error on
        failure, TimeoutError on timeout."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request seq={self.request.seq} not served within "
                f"{timeout}s (tenant={self.request.tenant!r})"
            )
        if self._error is not None:
            raise self._error
        return self._dists, self._ids


class Frontend:
    """Bind a :class:`FrontendScheduler` to one ``ServeSession`` with a
    dispatch pump thread. ``session`` should be constructed with a
    ``ResiliencePolicy`` (even the default one) when shedding is wanted:
    the degradation ladder is built at session construction, and a
    policy-less session has only its full rung to serve."""

    def __init__(self, session, policy: SLOPolicy,
                 clock=time.monotonic):
        self.session = session
        self.policy = policy
        self._clock = clock
        self.scheduler = FrontendScheduler(
            policy,
            on_shed=lambda: session.shed_rung(reason="queue-overload"),
            on_recover=lambda: session.restore_rung(
                reason="queue-recovered"
            ),
        )
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._tickets: dict[int, Ticket] = {}  # request seq -> ticket
        self._dispatched = []  # CoalescedBatch FIFO awaiting retire
        self._stop = False
        self._crashed: BaseException | None = None
        # highest router-stamped mutation sequence number applied here
        # (ISSUE 18): the router fans mutations out with X-Mutation-Seq
        # and reads this back from /healthz to track per-replica lag;
        # seq <= applied is a replayed duplicate and must not re-apply,
        # and seq > applied + 1 is a GAP and must not apply either (409)
        # — applying over a hole would advance the mark past a mutation
        # this replica never saw, losing it silently: the router's
        # in-order replay is the only path that moves a lagging replica
        self._applied_seq = 0
        self.started_s = time.monotonic()
        # declared device profile (ISSUE 16), resolved once here —
        # jax is already loaded by the session, and a construction-time
        # write keeps the attribute immutable across threads (H1); None
        # is a legitimate /healthz value (no shipped profile for this
        # hardware — never a guessed device)
        from mpi_knn_tpu.analysis.cost import detected_profile

        self._profile_facts: dict | None = detected_profile()
        # cold-start readiness (ISSUE 12): set once start-up warming —
        # executable builds at every rung plus the one-time dispatch-path
        # plumbing — has finished. While unset, admission is PER BUCKET:
        # a request whose row bucket's executable has landed serves, the
        # rest get a structured 503 "warming" with the progress counters.
        self._serving_ready = threading.Event()
        self._warm_thread: threading.Thread | None = None
        self._pump = threading.Thread(
            target=self._run, name="frontend-pump", daemon=True
        )

    # -- lifecycle --------------------------------------------------------

    def start(self, warm_sizes=None, background: bool = False,
              warm_parallel: int | None = None) -> "Frontend":
        """Start the pump; ``warm_sizes`` (row counts) pre-builds those
        buckets at EVERY ladder rung first — via the persistent AOT
        cache when one is active, across ``warm_parallel`` threads
        (None = auto) — so neither the first batch nor a shed rung ever
        cold-compiles into live traffic (default: the policy's full
        batch target). One real zero-batch dispatch then runs per size
        via the one-shot ``query_knn`` path: the first dispatch pays
        jax's one-time dispatch-path setup (~hundreds of ms) on top of
        the AOT cache, and that cost belongs in startup, not in the
        first client's latency. ``query_knn`` shares the executables and
        dispatch machinery but feeds NO session window stats and NO
        serving counters/histograms — the warm-up is plumbing and must
        be invisible to /metrics, not merely wiped from the session
        window.

        ``background=True`` is the bind-the-port-first cold-start shape
        (ISSUE 12): the pump starts IMMEDIATELY and the warm-up runs on
        a daemon thread, so the HTTP server can listen while executables
        are still landing. Until the warm-up finishes, ``submit`` admits
        per bucket (``session.coalesced_ready``): traffic whose whole
        coalescable bucket span has landed serves at once, the rest get
        a structured 503 "warming" rejection carrying the buckets-
        ready/total progress that ``/healthz`` also reports.

        The default warm set is the full bucket LADDER from the config's
        base bucket up to the fill target — not just the fill target:
        a coalesced batch can land in any power-of-two bucket in that
        span (a ragged deadline dispatch, a lull), and per-bucket
        admission during warming is only safe when the span a request
        could reach is entirely built."""
        if warm_sizes is None:
            base = self.session.cfg.query_bucket
            top = self.policy.max_batch_rows
            sizes, b = [], base
            while b < top:
                sizes.append(b)
                b *= 2
            sizes.append(top)
        else:
            sizes = list(warm_sizes)

        def _warm():
            try:
                if sizes:
                    from mpi_knn_tpu.serve.engine import query_knn

                    self.session.warm(sizes, parallel=warm_parallel)
                    dim = self.session.index.dim
                    for n in sizes:
                        query_knn(
                            np.zeros((n, dim), np.float32),
                            self.session.index, self.session.cfg,
                        )
                # the mutation cells too (ISSUE 14): a cold upsert would
                # otherwise compile while HOLDING the mutation lock —
                # stalling batch dispatch exactly once, at the worst time
                from mpi_knn_tpu.serve.mutate import (
                    supports_mutation,
                    warm_mutation,
                )

                if supports_mutation(self.session.index):
                    warm_mutation(self.session.index, self.session.cfg)
            finally:
                # a failed warm releases the gate anyway: the same
                # failure will re-raise loudly on the dispatch path
                # (where the pump's error machinery fails tickets),
                # whereas a stuck gate would 503 every client forever
                self._serving_ready.set()

        if background:
            self._pump.start()
            self._warm_thread = threading.Thread(
                target=_warm, name="frontend-warm", daemon=True
            )
            self._warm_thread.start()
        else:
            _warm()
            self._pump.start()
        return self

    def stop(self, timeout: float = 30.0) -> None:
        """Flush: every admitted request is served before the pump exits
        (admission stops immediately)."""
        with self._lock:
            self._stop = True
            self._work.notify()
        self._pump.join(timeout)

    # -- client side ------------------------------------------------------

    def submit(self, tenant: str, queries):
        """Admit one request (non-blocking): a :class:`Ticket` to wait
        on, or the scheduler's structured :class:`Rejection`."""
        queries = np.ascontiguousarray(queries, dtype=np.float32)
        if queries.ndim != 2:
            raise ValueError(
                f"queries must be (rows, dim), got shape {queries.shape}"
            )
        if not self._serving_ready.is_set() and not \
                self.session.coalesced_ready(
                    queries.shape[0], self.policy.max_batch_rows
                ):
            # per-bucket admission while warming (ISSUE 12): traffic
            # whose executable has landed serves immediately; the rest
            # are refused with the warming progress, not queued behind a
            # compile that would blow their deadline anyway. The
            # progress copy goes through warm_snapshot(): a bare
            # dict(warm_state) here raced the warm pool's per-cell
            # updates under the session's OWN lock (host-lint H1)
            ws = self.session.warm_snapshot()
            return Rejection(
                tenant=str(tenant), reason="warming",
                detail=(
                    f"bucket for {queries.shape[0]} rows not compiled "
                    f"yet ({ws['ready']}/{ws['total']} executables "
                    "ready)"
                ),
                retry_after_s=0.5,
                status=503,
            )
        with self._lock:
            if self._stop or self._crashed is not None:
                return Rejection(
                    tenant=str(tenant), reason="shutting-down",
                    detail="front end is stopping", retry_after_s=0.0,
                    status=503,
                )
            out = self.scheduler.submit(
                tenant, queries, queries.shape[0], self._clock()
            )
            if isinstance(out, Rejection):
                return out
            ticket = Ticket(out)
            self._tickets[out.seq] = ticket
            self._work.notify()
            return ticket

    def upsert(self, tenant: str, ids, rows, seq: int | None = None):
        """Admit + execute one tenant's upsert (ISSUE 14): 429-governed
        through the scheduler's shared per-tenant budget, then
        dispatched synchronously on this (handler) thread — the index's
        mutation lock serializes it with the pump's batch dispatch, so
        no ticket machinery is needed. Returns the mutation stats dict,
        or a structured :class:`Rejection`.

        ``seq`` is the router's per-index mutation sequence number
        (ISSUE 18): a seq at or below the high-water mark is a replayed
        duplicate — acknowledged without re-applying (and without
        charging the tenant's mutation budget), so the router's
        rejoin-replay can safely overlap live fan-out — and a seq past
        ``applied + 1`` is a gap, refused with a 409-status rejection
        (the router replays the hole forward in order)."""
        rows = np.ascontiguousarray(rows, dtype=np.float32)
        with self._lock:
            if self._stop or self._crashed is not None:
                return Rejection(
                    tenant=str(tenant), reason="shutting-down",
                    detail="front end is stopping", retry_after_s=0.0,
                    status=503,
                )
            gate = self._seq_gate(tenant, seq, self._applied_seq)
            if gate is not None:
                return gate
            rej = self.scheduler.admit_mutation(
                tenant, rows.shape[0], self._clock()
            )
        if rej is not None:
            return rej
        out = self.session.upsert(ids, rows, tenant=str(tenant))
        return self._note_applied(out, seq)

    def delete(self, tenant: str, ids, seq: int | None = None):
        """Admit + execute one tenant's delete — the upsert path's
        429 governance (and seq duplicate/gap gating) over the
        tombstone scatter."""
        ids = np.asarray(ids).reshape(-1)
        with self._lock:
            if self._stop or self._crashed is not None:
                return Rejection(
                    tenant=str(tenant), reason="shutting-down",
                    detail="front end is stopping", retry_after_s=0.0,
                    status=503,
                )
            gate = self._seq_gate(tenant, seq, self._applied_seq)
            if gate is not None:
                return gate
            rej = self.scheduler.admit_mutation(
                tenant, max(1, ids.shape[0]), self._clock()
            )
        if rej is not None:
            return rej
        out = self.session.delete(ids, tenant=str(tenant))
        return self._note_applied(out, seq)

    @staticmethod
    def _seq_gate(tenant: str, seq: int | None, applied: int):
        """The stream-order gate — pure in ``applied`` (callers read the
        mark under ``_lock`` and pass it in): None means the seq is
        consumable (exactly ``applied + 1``, or unsequenced); a dict is
        the duplicate acknowledgment; a 409 :class:`Rejection` means the
        seq would leave a GAP — 409 is outside the router's
        deterministic set, so the leg stays unacknowledged and the probe
        loop replays the hole forward in order."""
        if seq is None:
            return None
        if seq <= applied:
            return {"duplicate": True, "applied_seq": applied}
        if seq > applied + 1:
            return Rejection(
                tenant=str(tenant), reason="seq-gap",
                detail=(
                    f"seq {seq} skips ahead of applied_seq "
                    f"{applied}; refusing to apply out of order"
                ),
                retry_after_s=0.5, status=409,
            )
        return None

    def _note_applied(self, out: dict, seq: int | None) -> dict:
        """Advance the mutation high-water mark AFTER the session applied
        the mutation (never on admission — a crash between admit and
        apply must leave the seq unacknowledged so replay re-sends it)."""
        if seq is not None:
            with self._lock:
                if seq > self._applied_seq:
                    self._applied_seq = seq
                out["applied_seq"] = self._applied_seq
        return out

    def _note_refused(self, seq: int | None) -> dict | None:
        """A DETERMINISTIC refusal (400/507) consumed its seq: the
        stream position advances exactly as an apply would, because a
        replay could only repeat the refusal — a position that did not
        advance would make this replica 409 every later seq forever
        (the stream has no skip marker). Returns the position facts for
        the refusal body, ``{"gap": True, ...}`` when the seq cannot be
        consumed in order (the handler must answer 409 seq-gap instead
        of its refusal), or None for an unsequenced mutation."""
        if seq is None:
            return None
        with self._lock:
            if seq > self._applied_seq + 1:
                return {"gap": True, "applied_seq": self._applied_seq}
            if seq > self._applied_seq:
                self._applied_seq = seq
            return {"applied_seq": self._applied_seq}

    def stats(self) -> dict:
        """The health/posture snapshot ``GET /healthz`` serves.

        Session state comes through the session's OWN locked snapshots
        (``warm_snapshot``/``stats_snapshot``), taken BEFORE the
        frontend lock: handler threads previously read ``ses.latencies``
        / ``ses.tenant_stats`` raw while the pump mutated them at
        retire — the exact guard-map breach host-lint H1 flags — and
        keeping the two critical sections disjoint also keeps the lock
        graph free of a Frontend→Session edge from this path."""
        ses = self.session
        warm = ses.warm_snapshot()
        posture = ses.stats_snapshot()
        with self._lock:
            return {
                # a stopping frontend FAILS its health check on purpose:
                # a router must pull a draining replica out of rotation
                # before its socket goes away (ISSUE 18)
                "ok": self._crashed is None and not self._stop,
                # cold-start posture (ISSUE 12): executables ready/total
                # while warming, and whether start-up warming is done —
                # the CI gate's time-to-ready rendezvous reads this
                "ready": self._serving_ready.is_set() and not self._stop,
                "warming": {
                    "ready": warm["ready"],
                    "total": warm["total"],
                    "done": self._serving_ready.is_set(),
                },
                "uptime_s": round(time.monotonic() - self.started_s, 3),
                "queue_rows": self.scheduler.coalescer.pending_rows,
                "queue_requests": self.scheduler.coalescer.pending_requests,
                "admitted": self.scheduler.admitted,
                "rejected": self.scheduler.rejected,
                "rung": posture["rung"],
                "ladder": [label for label, _ in ses.ladder],
                "sheds": len(self.scheduler.sheds),
                "recoveries": len(self.scheduler.recoveries),
                "batches_retired": posture["batches_retired"],
                "queries_served": posture["queries_served"],
                "tenants": posture["tenants"],
                # live-mutation posture (ISSUE 14): the session window's
                # upsert/delete/compaction counts
                "mutation": posture.get("mutation", {}),
                # router mutation high-water mark (ISSUE 18): the probe
                # loop reads per-replica lag from here
                "applied_seq": self._applied_seq,
                # what a load generator needs to shape requests
                "dim": ses.index.dim,
                "k": ses.cfg.k,
                "backend": ses.index.backend,
                "max_batch_rows": self.policy.max_batch_rows,
                # static peak HBM of the largest built executable
                # (ISSUE 15): the memory-ledger figure for THIS
                # deployment's shapes, zero device reads — an operator
                # sizing a box reads it here next to dim/k/backend
                "peak_hbm_bytes": posture.get("peak_hbm_bytes", 0),
                # the declared roofline inputs for this hardware
                # (ISSUE 16): the shipped device profile the planner
                # predicted q/s under, so measured throughput and its
                # predicted bar read from the same endpoint; null off
                # the profile map — never a guessed device
                "device_profile": self._device_profile(),
            }

    def _device_profile(self) -> dict | None:
        return self._profile_facts

    # -- pump -------------------------------------------------------------

    def _run(self) -> None:
        try:
            while True:
                with self._lock:
                    stopping = self._stop
                    batches = self.scheduler.poll(
                        self._clock(), flush=stopping
                    )
                for b in batches:
                    self._dispatch(b)
                if not batches:
                    # nothing formed: retire in-flight work so results
                    # are not held hostage to the NEXT batch arriving
                    # (dispatch-ahead depth > 1 would otherwise strand
                    # the last batch of a lull in the pipeline)
                    if self._dispatched:
                        for res in self.session.drain():
                            self._scatter(res)
                    with self._lock:
                        if self._stop and not (
                            self._dispatched
                            or self.scheduler.coalescer.pending_rows
                        ):
                            return
                        wake = self.scheduler.next_wake_s()
                        timeout = (
                            0.05 if wake is None
                            else max(0.0, wake - self._clock())
                        )
                        if not self._stop:
                            self._work.wait(timeout=min(timeout, 0.05))
        except BaseException as e:  # noqa: BLE001 — fail tickets, re-raise
            with self._lock:
                self._crashed = e
                err = FrontendError(
                    f"frontend pump died: {type(e).__name__}: {e}"
                )
                for t in self._tickets.values():
                    if not t.done():
                        t._fail(err)
                self._tickets.clear()
                self._dispatched.clear()
            raise

    def _dispatch(self, batch) -> None:
        q = np.concatenate([r.queries for r in batch.parts], axis=0)
        self._metrics().histogram(
            "frontend_batch_fill_rows",
            help="coalesced rows per dispatched batch",
            buckets=_FILL_BUCKETS,
        ).observe(batch.rows)
        self._metrics().counter(
            "frontend_batches_total",
            help="coalesced batches dispatched",
            labels={"reason": batch.reason},
        ).inc()
        obs_spans.event(
            "coalesce", cat="frontend", rows=batch.rows,
            requests=len(batch.parts), reason=batch.reason,
            oldest_wait_ms=round(batch.oldest_wait_s * 1e3, 3),
        )
        self._dispatched.append(batch)
        for res in self.session.submit(q, tenants=batch.composition()):
            self._scatter(res)

    def _scatter(self, res) -> None:
        batch = self._dispatched.pop(0)
        dists, ids = res.dists, res.ids  # one D2H, padding stripped
        with self._lock:
            for req, start, stop in batch.slices():
                t = self._tickets.pop(req.seq, None)
                if t is not None:
                    t._fulfill(dists[start:stop], ids[start:stop])

    def _metrics(self):
        return obs_metrics.get_registry()


# ---------------------------------------------------------------------------
# HTTP layer

# fill histogram: powers of two around common bucket grids
_FILL_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096)


def _tuned_server_class():
    """``ThreadingHTTPServer`` tuned for a load-bearing loopback tier:

    - the stdlib's accept backlog of 5 DROPS connection bursts (an
      open-loop generator or a router opening its pool refused at the
      kernel) — raised to 128;
    - Nagle + delayed-ACK stalls the headers/body response pair ~40ms
      per request on KEEP-ALIVE connections (fresh connections hide it
      behind Linux quickack) — NODELAY is set on the accepted socket
      here, because ``disable_nagle_algorithm`` is a *handler* knob and
      the handler classes are per-caller closures;
    - ``server_close`` SEVERS live keep-alive connections: a threaded
      stdlib server otherwise leaves handler threads serving pooled
      connections after shutdown, so a "stopped" server keeps answering
      its old peers — a zombie a router would keep probing forever
      while its replacement listens unvisited on the same port. A real
      process's sockets die with it; an in-process stop must match.
    """
    import socket

    from http.server import ThreadingHTTPServer

    class TunedHTTPServer(ThreadingHTTPServer):
        request_queue_size = 128
        daemon_threads = True

        def __init__(self, *args, **kwargs):
            self._live_socks: set = set()
            self._live_lock = threading.Lock()
            ThreadingHTTPServer.__init__(self, *args, **kwargs)

        def get_request(self):
            sock, addr = ThreadingHTTPServer.get_request(self)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._live_lock:
                self._live_socks.add(sock)
            return sock, addr

        def shutdown_request(self, request):
            with self._live_lock:
                self._live_socks.discard(request)
            ThreadingHTTPServer.shutdown_request(self, request)

        def server_close(self):
            ThreadingHTTPServer.server_close(self)
            with self._live_lock:
                socks = list(self._live_socks)
                self._live_socks.clear()
            for s in socks:
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass

        def handle_error(self, request, client_address):
            import sys

            # a peer that went away mid-request (severed connection,
            # killed client) is routine for a load-bearing tier, not a
            # traceback; anything else still gets the stdlib report
            if isinstance(sys.exc_info()[1],
                          (ConnectionError, TimeoutError, OSError)):
                return
            ThreadingHTTPServer.handle_error(
                self, request, client_address
            )

    return TunedHTTPServer

TENANT_HEADER = "X-Tenant"
DEFAULT_TENANT = "default"
# the router's per-index mutation sequence number (ISSUE 18)
SEQ_HEADER = "X-Mutation-Seq"


def _http_handler(frontend: Frontend, request_timeout_s: float,
                  quiet: bool = True):
    """The BaseHTTPRequestHandler subclass bound to one frontend —
    built by closure (stdlib handlers have no constructor channel)."""
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def _json(self, status: int, doc: dict) -> None:
            body = (json.dumps(doc) + "\n").encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _text(self, status: int, text: str, ctype: str) -> None:
            body = text.encode()
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):  # noqa: A003
            if not quiet:
                BaseHTTPRequestHandler.log_message(self, fmt, *args)

        def _read_queries(self):
            """(rows, dim) f32 from the request body: JSON
            ``{"queries": [[...], ...]}`` or raw little-endian f32 rows
            at the index dim (``application/octet-stream``)."""
            n = int(self.headers.get("Content-Length") or 0)
            if n <= 0:
                raise ValueError("empty request body")
            raw = self.rfile.read(n)
            ctype = (self.headers.get("Content-Type") or "").split(";")[0]
            dim = frontend.session.index.dim
            if ctype == "application/octet-stream":
                if len(raw) % (4 * dim):
                    raise ValueError(
                        f"raw f32 body of {len(raw)} bytes is not a "
                        f"whole number of dim={dim} rows"
                    )
                return np.frombuffer(raw, dtype="<f4").reshape(-1, dim)
            doc = json.loads(raw)
            q = np.asarray(doc["queries"], dtype=np.float32)
            if q.ndim != 2 or q.shape[1] != dim:
                raise ValueError(
                    f"queries shape {q.shape} does not match index "
                    f"dim {dim}"
                )
            return q

        def _reject(self, out: Rejection) -> None:
            self.send_response(out.status)
            body = (json.dumps({
                "error": out.reason,
                "detail": out.detail,
                "tenant": out.tenant,
                "retry_after_s": out.retry_after_s,
            }) + "\n").encode()
            self.send_header("Content-Type", "application/json")
            self.send_header("Retry-After",
                             str(max(0.0, out.retry_after_s)))
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _read_json(self) -> dict:
            n = int(self.headers.get("Content-Length") or 0)
            if n <= 0:
                raise ValueError("empty request body")
            return json.loads(self.rfile.read(n))

        def _refuse_mutation(self, status: int, doc: dict, seq) -> None:
            """Send a DETERMINISTIC refusal (400/507): the seq is
            consumed (the router acks these — a replay could only
            repeat them, so the stream position must move past), unless
            it would leave a gap, which downgrades the answer to a 409
            the router never acks."""
            note = frontend._note_refused(seq)
            if note is not None and note.pop("gap", False):
                self._json(409, {"error": "seq-gap", **note})
                return
            if note is not None:
                doc = {**doc, **note}
            self._json(status, doc)

        def _do_mutation(self, tenant: str) -> None:
            """POST /upsert {"ids": [...], "rows": [[...]]} and
            POST /delete {"ids": [...]} — tenant-attributed (X-Tenant),
            429-governed through the scheduler's shared budget,
            dispatched synchronously (the mutation lock serializes with
            batch dispatch). Headroom overflow on the serial layout
            surfaces as 507 (no re-cluster pass to absorb it); clustered
            layouts compact-and-retry inside the session."""
            from mpi_knn_tpu.ivf.mutate import BucketOverflowError

            seq = None
            try:
                seq_h = self.headers.get(SEQ_HEADER)
                seq = None if seq_h is None else int(seq_h)
                doc = self._read_json()
                ids = doc["ids"]
                if self.path == "/upsert":
                    dim = frontend.session.index.dim
                    rows = np.asarray(doc["rows"], dtype=np.float32)
                    if rows.ndim != 2 or rows.shape[1] != dim:
                        raise ValueError(
                            f"rows shape {rows.shape} does not match "
                            f"index dim {dim}"
                        )
                    if len(ids) != rows.shape[0]:
                        raise ValueError(
                            f"{len(ids)} ids but {rows.shape[0]} rows"
                        )
            except (ValueError, KeyError, TypeError) as e:
                self._refuse_mutation(400, {"error": str(e)}, seq)
                return
            try:
                if self.path == "/upsert":
                    out = frontend.upsert(tenant, ids, rows, seq=seq)
                else:
                    out = frontend.delete(tenant, ids, seq=seq)
            except BucketOverflowError as e:
                self._refuse_mutation(
                    507, {"error": "headroom-exhausted",
                          "detail": str(e)}, seq,
                )
                return
            except ValueError as e:
                self._refuse_mutation(400, {"error": str(e)}, seq)
                return
            except Exception as e:  # noqa: BLE001 — serving error
                self._json(500, {"error": f"{type(e).__name__}: {e}"})
                return
            if isinstance(out, Rejection):
                self._reject(out)
                return
            self._json(200, out)

        def do_POST(self):  # noqa: N802 — stdlib handler convention
            tenant = self.headers.get(TENANT_HEADER, DEFAULT_TENANT)
            if self.path in ("/upsert", "/delete"):
                self._do_mutation(tenant)
                return
            if self.path != "/query":
                self._json(404, {"error": f"no such route {self.path}"})
                return
            try:
                q = self._read_queries()
            except (ValueError, KeyError, TypeError) as e:
                self._json(400, {"error": str(e)})
                return
            out = frontend.submit(tenant, q)
            if isinstance(out, Rejection):
                self._reject(out)
                return
            try:
                dists, ids = out.result(timeout=request_timeout_s)
            except TimeoutError as e:
                self._json(504, {"error": str(e)})
                return
            except Exception as e:  # serving error (sentinel, …)
                self._json(500, {"error": f"{type(e).__name__}: {e}"})
                return
            self._json(200, {
                "rows": int(ids.shape[0]),
                "dists": [[float(v) for v in row] for row in dists],
                "ids": ids.tolist(),
            })

        def do_GET(self):  # noqa: N802
            if self.path == "/metrics":
                self._text(
                    200, obs_metrics.get_registry().to_prometheus(),
                    "text/plain; version=0.0.4",
                )
            elif self.path == "/healthz":
                st = frontend.stats()
                self._json(200 if st["ok"] else 503, st)
            else:
                self._json(404, {"error": f"no such route {self.path}"})

    return Handler


class FrontendHTTPServer:
    """``ThreadingHTTPServer`` wrapper: bind, serve in a thread, expose
    the bound address (``--port 0`` picks an ephemeral port)."""

    def __init__(self, frontend: Frontend, host: str = "127.0.0.1",
                 port: int = 0, request_timeout_s: float = 30.0,
                 quiet: bool = True):
        self.frontend = frontend
        self._httpd = _tuned_server_class()(
            (host, port), _http_handler(frontend, request_timeout_s, quiet)
        )
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="frontend-http",
            daemon=True,
        )

    @property
    def address(self) -> tuple:
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def start(self) -> "FrontendHTTPServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(10.0)
